"""SQL-level integration tests over the mock cluster — the workhorse tier.

Ref model: util/testkit.TestKit MustExec/MustQuery (testkit.go:31-60) driving
executor_test.go / session_test.go cases against mocktikv.
"""

import decimal

import pytest

from tidb_tpu.session import ResultSet, Session, SQLError
from tidb_tpu.store import new_mock_storage


@pytest.fixture
def tk():
    storage = new_mock_storage()
    storage.async_commit_secondaries = False
    s = Session(storage)
    s.execute("CREATE DATABASE test; USE test")
    yield s
    s.close()
    storage.close()


def q(tk, sql):
    return tk.query(sql).rows


class TestBasics:
    def test_create_insert_select(self, tk):
        tk.execute("CREATE TABLE t (id BIGINT PRIMARY KEY, v INT, s VARCHAR(10))")
        tk.execute("INSERT INTO t VALUES (1, 10, 'a'), (2, 20, 'b'), (3, NULL, NULL)")
        assert q(tk, "SELECT * FROM t") == [(1, 10, "a"), (2, 20, "b"),
                                            (3, None, None)]
        assert q(tk, "SELECT v FROM t WHERE id = 2") == [(20,)]
        assert q(tk, "SELECT id FROM t WHERE v IS NULL") == [(3,)]

    def test_expressions_in_select(self, tk):
        tk.execute("CREATE TABLE t (a INT, b INT)")
        tk.execute("INSERT INTO t VALUES (1, 2), (3, 4)")
        assert q(tk, "SELECT a + b, a * 10 FROM t") == [(3, 10), (7, 30)]
        assert q(tk, "SELECT a FROM t WHERE a + b > 4") == [(3,)]

    def test_select_no_from(self, tk):
        assert q(tk, "SELECT 1 + 1, 'x'") == [(2, "x")]

    def test_order_limit(self, tk):
        tk.execute("CREATE TABLE t (a INT, b INT)")
        tk.execute("INSERT INTO t VALUES (3,1),(1,2),(2,3),(5,4),(4,5)")
        assert q(tk, "SELECT a FROM t ORDER BY a") == \
            [(1,), (2,), (3,), (4,), (5,)]
        assert q(tk, "SELECT a FROM t ORDER BY a DESC LIMIT 2") == \
            [(5,), (4,)]
        assert q(tk, "SELECT a FROM t ORDER BY b LIMIT 2 OFFSET 1") == \
            [(1,), (2,)]

    def test_decimal_datetime(self, tk):
        tk.execute("CREATE TABLE p (price DECIMAL(10,2), d DATETIME)")
        tk.execute("INSERT INTO p VALUES (12.50, '2024-03-15 10:30:00'), "
                   "(0.99, '2023-01-01 00:00:00')")
        rows = q(tk, "SELECT price, d FROM p ORDER BY price")
        assert rows[0] == (decimal.Decimal("0.99"), "2023-01-01 00:00:00")
        assert rows[1] == (decimal.Decimal("12.50"), "2024-03-15 10:30:00")
        assert q(tk, "SELECT price * 2 FROM p WHERE d > '2024-01-01'") == \
            [(decimal.Decimal("25.00"),)]

    def test_update_delete(self, tk):
        tk.execute("CREATE TABLE t (id BIGINT PRIMARY KEY, v INT)")
        tk.execute("INSERT INTO t VALUES (1, 10), (2, 20), (3, 30)")
        assert tk.execute("UPDATE t SET v = v + 1 WHERE id < 3")[0] == 2
        assert q(tk, "SELECT v FROM t ORDER BY id") == [(11,), (21,), (30,)]
        assert tk.execute("DELETE FROM t WHERE v = 21")[0] == 1
        assert q(tk, "SELECT id FROM t ORDER BY id") == [(1,), (3,)]

    def test_auto_increment(self, tk):
        tk.execute("CREATE TABLE t (id BIGINT PRIMARY KEY AUTO_INCREMENT, "
                   "v INT)")
        tk.execute("INSERT INTO t (v) VALUES (10), (20)")
        rows = q(tk, "SELECT id, v FROM t ORDER BY id")
        assert rows[0][1] == 10 and rows[1][1] == 20
        assert rows[0][0] < rows[1][0]

    def test_dup_key(self, tk):
        tk.execute("CREATE TABLE t (id BIGINT PRIMARY KEY, v INT)")
        tk.execute("INSERT INTO t VALUES (1, 1)")
        with pytest.raises(Exception, match="[Dd]uplicate"):
            tk.execute("INSERT INTO t VALUES (1, 2)")
        tk.execute("INSERT IGNORE INTO t VALUES (1, 3), (2, 4)")
        assert q(tk, "SELECT * FROM t ORDER BY id") == [(1, 1), (2, 4)]
        tk.execute("INSERT INTO t VALUES (1, 9) ON DUPLICATE KEY UPDATE "
                   "v = v + 100")
        assert q(tk, "SELECT v FROM t WHERE id = 1") == [(101,)]
        tk.execute("REPLACE INTO t VALUES (2, 99)")
        assert q(tk, "SELECT v FROM t WHERE id = 2") == [(99,)]


class TestAggregation:
    def test_group_by(self, tk):
        tk.execute("CREATE TABLE t (k VARCHAR(5), v INT)")
        tk.execute("INSERT INTO t VALUES ('a',1),('b',2),('a',3),('b',4),"
                   "('c',NULL)")
        rows = q(tk, "SELECT k, COUNT(*), SUM(v), AVG(v), MIN(v), MAX(v) "
                     "FROM t GROUP BY k ORDER BY k")
        assert rows == [("a", 2, 4, 2.0, 1, 3),
                        ("b", 2, 6, 3.0, 2, 4),
                        ("c", 1, None, None, None, None)]

    def test_scalar_agg(self, tk):
        tk.execute("CREATE TABLE t (v INT)")
        tk.execute("INSERT INTO t VALUES (1),(2),(3),(NULL)")
        assert q(tk, "SELECT COUNT(*), COUNT(v), SUM(v) FROM t") == \
            [(4, 3, 6)]
        assert q(tk, "SELECT COUNT(*) FROM t WHERE v > 10") == [(0,)]
        assert q(tk, "SELECT SUM(v) FROM t WHERE v > 10") == [(None,)]

    def test_having_and_agg_expr(self, tk):
        tk.execute("CREATE TABLE t (k INT, v INT)")
        tk.execute("INSERT INTO t VALUES (1,10),(1,20),(2,5),(2,6),(3,100)")
        rows = q(tk, "SELECT k, SUM(v) s FROM t GROUP BY k "
                     "HAVING SUM(v) > 20 ORDER BY s DESC")
        assert rows == [(3, 100), (1, 30)]
        # agg inside expressions
        assert q(tk, "SELECT SUM(v) * 2 + 1 FROM t") == [(283,)]

    def test_group_by_expr(self, tk):
        tk.execute("CREATE TABLE t (a INT)")
        tk.execute("INSERT INTO t VALUES (1),(2),(3),(4),(5),(6)")
        rows = q(tk, "SELECT a % 3, COUNT(*) FROM t GROUP BY a % 3 "
                     "ORDER BY a % 3")
        assert rows == [(0, 2), (1, 2), (2, 2)]

    def test_distinct(self, tk):
        tk.execute("CREATE TABLE t (a INT, b INT)")
        tk.execute("INSERT INTO t VALUES (1,1),(1,1),(2,1),(2,2)")
        assert q(tk, "SELECT DISTINCT a FROM t ORDER BY a") == [(1,), (2,)]
        assert q(tk, "SELECT COUNT(DISTINCT a) FROM t") == [(2,)]

    def test_implicit_first_row(self, tk):
        tk.execute("CREATE TABLE t (k INT, v INT)")
        tk.execute("INSERT INTO t VALUES (1, 7), (1, 8)")
        rows = q(tk, "SELECT k, v FROM t GROUP BY k")
        assert rows == [(1, 7)]


class TestJoins:
    def setup_join(self, tk):
        tk.execute("CREATE TABLE u (id BIGINT PRIMARY KEY, name VARCHAR(10))")
        tk.execute("CREATE TABLE o (oid BIGINT PRIMARY KEY, uid INT, amt INT)")
        tk.execute("INSERT INTO u VALUES (1,'ann'),(2,'bob'),(3,'cat')")
        tk.execute("INSERT INTO o VALUES (10,1,100),(11,1,150),(12,2,200)")

    def test_inner_join(self, tk):
        self.setup_join(tk)
        rows = q(tk, "SELECT u.name, o.amt FROM u JOIN o ON u.id = o.uid "
                     "ORDER BY o.amt")
        assert rows == [("ann", 100), ("ann", 150), ("bob", 200)]

    def test_comma_join_where(self, tk):
        self.setup_join(tk)
        rows = q(tk, "SELECT u.name, o.amt FROM u, o WHERE u.id = o.uid "
                     "AND o.amt > 120 ORDER BY amt")
        assert rows == [("ann", 150), ("bob", 200)]

    def test_left_join(self, tk):
        self.setup_join(tk)
        rows = q(tk, "SELECT u.name, o.amt FROM u LEFT JOIN o "
                     "ON u.id = o.uid ORDER BY u.name, o.amt")
        assert rows == [("ann", 100), ("ann", 150), ("bob", 200),
                        ("cat", None)]

    def test_join_group(self, tk):
        self.setup_join(tk)
        rows = q(tk, "SELECT u.name, SUM(o.amt) FROM u JOIN o "
                     "ON u.id = o.uid GROUP BY u.name ORDER BY u.name")
        assert rows == [("ann", 250), ("bob", 200)]

    def test_subquery_table(self, tk):
        self.setup_join(tk)
        rows = q(tk, "SELECT name FROM (SELECT name, id FROM u WHERE id > 1)"
                     " s ORDER BY name")
        assert rows == [("bob",), ("cat",)]


class TestTxn:
    def test_explicit_txn_visibility(self, tk):
        tk.execute("CREATE TABLE t (id BIGINT PRIMARY KEY, v INT)")
        tk.execute("INSERT INTO t VALUES (1, 1)")
        tk.execute("BEGIN")
        tk.execute("INSERT INTO t VALUES (2, 2)")
        tk.execute("UPDATE t SET v = 100 WHERE id = 1")
        # own writes visible inside the txn
        assert q(tk, "SELECT v FROM t ORDER BY id") == [(100,), (2,)]
        tk.execute("ROLLBACK")
        assert q(tk, "SELECT v FROM t ORDER BY id") == [(1,)]

    def test_commit_persists(self, tk):
        tk.execute("CREATE TABLE t (id BIGINT PRIMARY KEY, v INT)")
        tk.execute("BEGIN; INSERT INTO t VALUES (1, 5); COMMIT")
        assert q(tk, "SELECT v FROM t") == [(5,)]

    def test_two_sessions_isolation(self, tk):
        tk.execute("CREATE TABLE t (id BIGINT PRIMARY KEY, v INT)")
        tk.execute("INSERT INTO t VALUES (1, 1)")
        s2 = Session(tk.storage, db="test")
        s2.execute("BEGIN")
        assert s2.query("SELECT v FROM t").rows == [(1,)]
        tk.execute("UPDATE t SET v = 2 WHERE id = 1")
        # s2 still sees its snapshot
        assert s2.query("SELECT v FROM t").rows == [(1,)]
        s2.execute("COMMIT")
        assert s2.query("SELECT v FROM t").rows == [(2,)]
        s2.close()

    def test_conflict_retry(self, tk):
        tk.execute("CREATE TABLE t (id BIGINT PRIMARY KEY, v INT)")
        tk.execute("INSERT INTO t VALUES (1, 0)")
        s2 = Session(tk.storage, db="test")
        tk.execute("BEGIN")
        tk.execute("UPDATE t SET v = v + 1 WHERE id = 1")
        # s2 commits a conflicting write first
        s2.execute("UPDATE t SET v = v + 10 WHERE id = 1")
        # tk's commit retries by replaying history
        tk.execute("COMMIT")
        assert q(tk, "SELECT v FROM t") == [(11,)]
        s2.close()


class TestDDL:
    def test_show_and_describe(self, tk):
        tk.execute("CREATE TABLE t (id BIGINT PRIMARY KEY, v INT)")
        assert ("test",) in tk.query("SHOW DATABASES").rows
        assert q(tk, "SHOW TABLES") == [("t",)]
        cols = tk.query("SHOW COLUMNS FROM t").rows
        assert cols[0][0] == "id" and cols[0][3] == "PRI"

    def test_alter_add_drop_column(self, tk):
        tk.execute("CREATE TABLE t (id BIGINT PRIMARY KEY)")
        tk.execute("INSERT INTO t VALUES (1)")
        tk.execute("ALTER TABLE t ADD COLUMN v INT DEFAULT 7")
        assert q(tk, "SELECT id, v FROM t") == [(1, 7)]
        tk.execute("INSERT INTO t VALUES (2, 9)")
        assert q(tk, "SELECT v FROM t ORDER BY id") == [(7,), (9,)]
        tk.execute("ALTER TABLE t DROP COLUMN v")
        assert q(tk, "SELECT * FROM t") == [(1,), (2,)]

    def test_create_index_backfill_and_drop(self, tk):
        tk.execute("CREATE TABLE t (id BIGINT PRIMARY KEY, v INT)")
        tk.execute("INSERT INTO t VALUES (1, 10), (2, 20)")
        tk.execute("CREATE INDEX iv ON t (v)")
        tk.execute("INSERT INTO t VALUES (3, 30)")
        assert q(tk, "SELECT id FROM t WHERE v = 20") == [(2,)]
        tk.execute("DROP INDEX iv ON t")

    def test_unique_index_enforced(self, tk):
        tk.execute("CREATE TABLE t (id BIGINT PRIMARY KEY, v INT, "
                   "UNIQUE KEY uv (v))")
        tk.execute("INSERT INTO t VALUES (1, 10)")
        with pytest.raises(Exception, match="[Dd]uplicate"):
            tk.execute("INSERT INTO t VALUES (2, 10)")

    def test_truncate_drop(self, tk):
        tk.execute("CREATE TABLE t (id BIGINT PRIMARY KEY)")
        tk.execute("INSERT INTO t VALUES (1)")
        tk.execute("TRUNCATE TABLE t")
        assert q(tk, "SELECT COUNT(*) FROM t") == [(0,)]
        tk.execute("DROP TABLE t")
        with pytest.raises(SQLError):
            tk.query("SELECT * FROM t")

    def test_explain(self, tk):
        tk.execute("CREATE TABLE t (a INT, b INT)")
        lines = [r[0] for r in
                 tk.query("EXPLAIN SELECT SUM(b) FROM t WHERE a > 1 "
                          "GROUP BY a").rows]
        assert any("FinalAgg" in l for l in lines)
        assert any("partial_agg" in l for l in lines)


class TestMultiRegion:
    def test_split_and_query(self, tk):
        tk.execute("CREATE TABLE t (id BIGINT PRIMARY KEY, v INT)")
        tk.execute("INSERT INTO t VALUES " +
                   ",".join(f"({i},{i*10})" for i in range(1, 101)))
        # split the table into 4 regions mid-life
        ischema = tk.domain.info_schema()
        info = ischema.table("test", "t")
        tk.storage.cluster.split_table(info.id, 4, max_handle=100)
        assert len(tk.storage.cluster.all_regions()) >= 4
        assert q(tk, "SELECT COUNT(*), SUM(v) FROM t") == [(100, 50500)]
        assert q(tk, "SELECT v FROM t WHERE id = 77") == [(770,)]
        assert tk.execute("UPDATE t SET v = 0 WHERE id > 90")[0] == 10
        assert q(tk, "SELECT SUM(v) FROM t") == [(50500 - sum(
            i * 10 for i in range(91, 101)),)]


class TestDecimalPrecisionGuards:
    """p<=18 decimals are scaled int64 (device lane); wider columns up
    to MySQL's 65 use the exact wide lane (tests/test_wide_decimal.py);
    beyond 65 fails at DDL, out-of-range values fail at write — never
    silent truncation or wraparound."""

    def test_precision_limits_at_ddl(self, tk):
        from tidb_tpu.session import SQLError
        tk.execute("CREATE TABLE wd38 (id BIGINT PRIMARY KEY, "
                   "amt DECIMAL(38, 10))")        # wide lane
        with pytest.raises(SQLError, match="exceeds the supported"):
            tk.execute("CREATE TABLE wd66 (id BIGINT PRIMARY KEY, "
                       "amt DECIMAL(66, 10))")
        with pytest.raises(SQLError, match="scale"):
            tk.execute("CREATE TABLE wd (id BIGINT PRIMARY KEY, "
                       "amt DECIMAL(6, 8))")

    def test_out_of_range_value_rejected(self, tk):
        tk.execute("CREATE TABLE dg (id BIGINT PRIMARY KEY, "
                   "amt DECIMAL(8, 2))")
        with pytest.raises(Exception, match="Out of range"):
            tk.execute("INSERT INTO dg VALUES (1, 12345678901.25)")
        tk.execute("INSERT INTO dg VALUES (1, 123456.78)")
        assert str(tk.query("SELECT amt FROM dg").rows[0][0]) == \
            "123456.78"


class TestDMLOrderLimit:
    """UPDATE/DELETE ... ORDER BY ... LIMIT n restrict the write scope
    (silently ignoring them deleted every match — the original bug)."""

    def test_delete_order_limit(self, tk):
        tk.execute("CREATE TABLE dl (id BIGINT PRIMARY KEY, v BIGINT)")
        tk.execute("INSERT INTO dl VALUES (1,1),(2,2),(3,3),(4,4)")
        [n] = tk.execute("DELETE FROM dl ORDER BY id DESC LIMIT 1")
        assert n == 1
        assert tk.query("SELECT id FROM dl ORDER BY id").rows == \
            [(1,), (2,), (3,)]

    def test_update_order_limit(self, tk):
        tk.execute("CREATE TABLE ul (id BIGINT PRIMARY KEY, v BIGINT)")
        tk.execute("INSERT INTO ul VALUES (1,1),(2,2),(3,3)")
        [n] = tk.execute("UPDATE ul SET v = 0 ORDER BY id LIMIT 2")
        assert n == 2
        assert tk.query("SELECT v FROM ul ORDER BY id").rows == \
            [(0,), (0,), (3,)]

    def test_plain_limit_without_order(self, tk):
        tk.execute("CREATE TABLE pl (id BIGINT PRIMARY KEY)")
        tk.execute("INSERT INTO pl VALUES (1),(2),(3)")
        [n] = tk.execute("DELETE FROM pl LIMIT 2")
        assert n == 2
        assert tk.query("SELECT COUNT(*) FROM pl").rows == [(1,)]


class TestHavingAlias:
    def test_having_references_select_aliases(self, tk):
        tk.execute("CREATE TABLE ha (id BIGINT PRIMARY KEY, v BIGINT, "
                   "g BIGINT)")
        tk.execute("INSERT INTO ha VALUES (1,10,1),(2,20,1),"
                   "(3,30,2),(4,40,2)")
        assert tk.query("SELECT g, SUM(v) s FROM ha GROUP BY g "
                        "HAVING s > 40 ORDER BY g").rows == [(2, 70)]
        assert tk.query("SELECT g, SUM(v) s FROM ha GROUP BY g "
                        "HAVING s > 20 AND g < 2").rows == [(1, 30)]

    def test_real_column_shadows_alias(self, tk):
        """MySQL resolves HAVING names FROM-clause-first: an alias only
        fires when no real column of that name exists."""
        tk.execute("CREATE TABLE hs (a BIGINT PRIMARY KEY, b BIGINT)")
        tk.execute("INSERT INTO hs VALUES (1,2),(2,3),(5,3)")
        # 'a' below is the real column (grouped), NOT the alias of b
        assert tk.query("SELECT b AS a, SUM(b) FROM hs GROUP BY a "
                        "HAVING a > 1 ORDER BY a").rows == \
            [(3, 3), (3, 3)]

    def test_alias_inside_aggregate_rejected(self, tk):
        """HAVING SUM(s) where s aliases an aggregate would nest group
        functions — MySQL raises ER_INVALID_GROUP_FUNC_USE."""
        from tidb_tpu.session import SQLError
        import pytest
        tk.execute("CREATE TABLE hn (a BIGINT PRIMARY KEY, b BIGINT)")
        tk.execute("INSERT INTO hn VALUES (1,2),(2,3)")
        with pytest.raises(SQLError, match="group function"):
            tk.query("SELECT SUM(b) s FROM hn GROUP BY a "
                     "HAVING SUM(s) > 0")
