"""Race harness over the threaded store paths (util/racecheck.py; the
reference's `make race` role, SURVEY §5.2). Each test multiplies thread
interleavings via a floor switch-interval and asserts semantic
invariants that break under lost updates or torn state."""

import threading

import pytest

from tidb_tpu import kv
from tidb_tpu.store.storage import new_mock_storage
from tidb_tpu.util.racecheck import stress


@pytest.fixture
def storage():
    return new_mock_storage()


def _run_threads(n, fn):
    errs = []

    def wrap(i):
        try:
            fn(i)
        except Exception as e:   # noqa: BLE001 — collected for assert
            errs.append(e)

    ts = [threading.Thread(target=wrap, args=(i,)) for i in range(n)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    return errs


class TestInvariants:
    def test_tso_strictly_monotonic_across_threads(self, storage):
        out = [[] for _ in range(8)]

        def worker(i):
            for _ in range(500):
                out[i].append(storage.cluster.tso())

        with stress():
            assert _run_threads(8, worker) == []
        allts = sorted(t for lst in out for t in lst)
        assert len(set(allts)) == len(allts), "duplicate TSO issued"
        for lst in out:
            assert lst == sorted(lst), "per-thread TSO went backwards"

    def test_concurrent_increments_no_lost_updates(self, storage):
        """Counter bumped via conflicting txns: optimistic conflicts are
        allowed (retried by sessions); silent lost updates are not."""
        key = b"ctr"
        txn0 = storage.begin()
        txn0.set(key, b"0")
        txn0.commit()
        applied = [0]
        mu = threading.Lock()

        def worker(_i):
            for _ in range(60):
                txn = storage.begin()
                try:
                    cur = int(txn.get(key) or b"0")
                    txn.set(key, str(cur + 1).encode())
                    txn.commit()
                    with mu:
                        applied[0] += 1
                except (kv.RetryableError, kv.KVError):
                    try:
                        txn.rollback()
                    except Exception:   # noqa: BLE001
                        pass

        with stress():
            assert _run_threads(6, worker) == []
        txn = storage.begin()
        final = int(txn.get(key))
        txn.rollback()
        assert final == applied[0], \
            f"lost updates: committed {applied[0]}, visible {final}"

    def test_concurrent_unique_insert_exactly_one_winner(self, storage):
        """PresumeKeyNotExists race: exactly one of N concurrent writers
        of the same key may commit a first-write."""
        wins = []
        mu = threading.Lock()

        def worker(i):
            txn = storage.begin()
            try:
                if txn.get(b"uniq") is not None:
                    txn.rollback()
                    return
                txn.set(b"uniq", b"w%d" % i)
                txn.commit()
                with mu:
                    wins.append(i)
            except (kv.RetryableError, kv.KVError):
                try:
                    txn.rollback()
                except Exception:   # noqa: BLE001
                    pass

        with stress():
            assert _run_threads(8, worker) == []
        assert len(wins) == 1, f"winners: {wins}"

    def test_region_split_during_scans(self, storage):
        from tidb_tpu.store.region_cache import RegionCache
        txn = storage.begin()
        for i in range(2000):
            txn.set(b"rk%06d" % i, b"v")
        txn.commit()
        cache = RegionCache(storage.cluster)
        stop = threading.Event()
        errs = []

        def splitter(_i):
            for i in range(0, 2000, 50):
                storage.cluster.split(b"rk%06d" % i)

        def scanner(_i):
            import random
            rnd = random.Random(_i)
            while not stop.is_set():
                k = b"rk%06d" % rnd.randrange(2000)
                loc = cache.locate(k)
                if not loc.region.contains(k):
                    errs.append((k, loc.region))

        with stress():
            scan_threads = [threading.Thread(target=scanner, args=(i,))
                            for i in range(4)]
            for t in scan_threads:
                t.start()
            assert _run_threads(1, splitter) == []
            stop.set()
            for t in scan_threads:
                t.join()
        assert errs == []

    def test_session_concurrent_ddl_and_dml(self, storage):
        """Schema churn while another session writes: every outcome must
        be a clean success or a typed error, never corruption."""
        from tidb_tpu.session import Session, SQLError
        s0 = Session(storage)
        s0.execute("CREATE DATABASE rc; USE rc")
        s0.execute("CREATE TABLE t (id BIGINT PRIMARY KEY, v BIGINT)")
        errs = []

        def ddl(_i):
            s = Session(storage)
            s.execute("USE rc")
            for k in range(6):
                try:
                    s.execute(f"CREATE INDEX i{k} ON t (v)")
                    s.execute(f"DROP INDEX i{k} ON t")
                except SQLError:
                    pass
            s.close()

        def dml(i):
            s = Session(storage)
            s.execute("USE rc")
            for k in range(40):
                try:
                    s.execute(f"INSERT INTO t VALUES ({i * 1000 + k}, "
                              f"{k})")
                except SQLError:
                    pass
            s.close()

        with stress():
            assert _run_threads(1, ddl) == []
            assert _run_threads(3, dml) == []
        # table is consistent: every row readable, index (if any) sane
        rows = s0.query("SELECT COUNT(*) FROM t").rows[0][0]
        assert rows > 0
        s0.execute("ADMIN CHECK TABLE t")
        s0.close()
