"""Race harness over the threaded store paths (util/racecheck.py; the
reference's `make race` role, SURVEY §5.2). Each test multiplies thread
interleavings via a floor switch-interval and asserts semantic
invariants that break under lost updates or torn state.

The whole module runs under the runtime lock-order sanitizer
(util/lockorder.py): every registered lock constructed while these
workloads run is order-checked against the statically-derived DAG of
the `lock-order` lint rule — the dynamic harness validates the static
model, and the static DAG gives the dynamic run its oracle. A
violation fails the module at teardown (and TestSanitizer below pins
the checker itself: inversions caught, hierarchies allowed,
self-deadlocks raised instead of hung)."""

import threading

import pytest

from tidb_tpu import kv
from tidb_tpu.store.storage import new_mock_storage
from tidb_tpu.util import lockorder
from tidb_tpu.util.racecheck import stress


@pytest.fixture(scope="module", autouse=True)
def lock_sanitizer():
    """One sanitizer for the whole module (the static DAG costs one
    forest parse + flow analysis — build it once). Raises
    LockOrderError at teardown if any workload ordering contradicted
    the DAG."""
    with lockorder.sanitize() as san:
        yield san


@pytest.fixture
def storage():
    return new_mock_storage()


def _run_threads(n, fn):
    errs = []

    def wrap(i):
        try:
            fn(i)
        except Exception as e:   # noqa: BLE001 — collected for assert
            errs.append(e)

    ts = [threading.Thread(target=wrap, args=(i,)) for i in range(n)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    return errs


class TestInvariants:
    def test_tso_strictly_monotonic_across_threads(self, storage):
        out = [[] for _ in range(8)]

        def worker(i):
            for _ in range(500):
                out[i].append(storage.cluster.tso())

        with stress():
            assert _run_threads(8, worker) == []
        allts = sorted(t for lst in out for t in lst)
        assert len(set(allts)) == len(allts), "duplicate TSO issued"
        for lst in out:
            assert lst == sorted(lst), "per-thread TSO went backwards"

    def test_concurrent_increments_no_lost_updates(self, storage):
        """Counter bumped via conflicting txns: optimistic conflicts are
        allowed (retried by sessions); silent lost updates are not."""
        key = b"ctr"
        txn0 = storage.begin()
        txn0.set(key, b"0")
        txn0.commit()
        applied = [0]
        mu = threading.Lock()

        def worker(_i):
            for _ in range(60):
                txn = storage.begin()
                try:
                    cur = int(txn.get(key) or b"0")
                    txn.set(key, str(cur + 1).encode())
                    txn.commit()
                    with mu:
                        applied[0] += 1
                except (kv.RetryableError, kv.KVError):
                    try:
                        txn.rollback()
                    except Exception:   # noqa: BLE001
                        pass

        with stress():
            assert _run_threads(6, worker) == []
        txn = storage.begin()
        final = int(txn.get(key))
        txn.rollback()
        assert final == applied[0], \
            f"lost updates: committed {applied[0]}, visible {final}"

    def test_concurrent_unique_insert_exactly_one_winner(self, storage):
        """PresumeKeyNotExists race: exactly one of N concurrent writers
        of the same key may commit a first-write."""
        wins = []
        mu = threading.Lock()

        def worker(i):
            txn = storage.begin()
            try:
                if txn.get(b"uniq") is not None:
                    txn.rollback()
                    return
                txn.set(b"uniq", b"w%d" % i)
                txn.commit()
                with mu:
                    wins.append(i)
            except (kv.RetryableError, kv.KVError):
                try:
                    txn.rollback()
                except Exception:   # noqa: BLE001
                    pass

        with stress():
            assert _run_threads(8, worker) == []
        assert len(wins) == 1, f"winners: {wins}"

    def test_region_split_during_scans(self, storage):
        from tidb_tpu.store.region_cache import RegionCache
        txn = storage.begin()
        for i in range(2000):
            txn.set(b"rk%06d" % i, b"v")
        txn.commit()
        cache = RegionCache(storage.cluster)
        stop = threading.Event()
        errs = []

        def splitter(_i):
            for i in range(0, 2000, 50):
                storage.cluster.split(b"rk%06d" % i)

        def scanner(_i):
            import random
            rnd = random.Random(_i)
            while not stop.is_set():
                k = b"rk%06d" % rnd.randrange(2000)
                loc = cache.locate(k)
                if not loc.region.contains(k):
                    errs.append((k, loc.region))

        with stress():
            scan_threads = [threading.Thread(target=scanner, args=(i,))
                            for i in range(4)]
            for t in scan_threads:
                t.start()
            assert _run_threads(1, splitter) == []
            stop.set()
            for t in scan_threads:
                t.join()
        assert errs == []

    def test_session_concurrent_ddl_and_dml(self, storage):
        """Schema churn while another session writes: every outcome must
        be a clean success or a typed error, never corruption."""
        from tidb_tpu.session import Session, SQLError
        s0 = Session(storage)
        s0.execute("CREATE DATABASE rc; USE rc")
        s0.execute("CREATE TABLE t (id BIGINT PRIMARY KEY, v BIGINT)")
        errs = []

        def ddl(_i):
            s = Session(storage)
            s.execute("USE rc")
            for k in range(6):
                try:
                    s.execute(f"CREATE INDEX i{k} ON t (v)")
                    s.execute(f"DROP INDEX i{k} ON t")
                except SQLError:
                    pass
            s.close()

        def dml(i):
            s = Session(storage)
            s.execute("USE rc")
            for k in range(40):
                try:
                    s.execute(f"INSERT INTO t VALUES ({i * 1000 + k}, "
                              f"{k})")
                except SQLError:
                    pass
            s.close()

        with stress():
            assert _run_threads(1, ddl) == []
            assert _run_threads(3, dml) == []
        # table is consistent: every row readable, index (if any) sane
        rows = s0.query("SELECT COUNT(*) FROM t").rows[0][0]
        assert rows > 0
        s0.execute("ADMIN CHECK TABLE t")
        s0.close()

    def test_delta_store_writes_vs_analytic_scans(self, storage):
        """The delta store under contention: writers mutate rows
        through sessions while readers run cached analytic scans, all
        under the lock sanitizer (the MVCCStore._mu -> DeltaStore._mu
        capture edge and the in-place HBM patch run here). Every read
        must be a consistent snapshot: COUNT(*) is always the loaded
        row count (updates never add or lose rows) and SUM(v) only
        grows (writers only increment)."""
        import numpy as np
        from tidb_tpu.session import Session
        from tidb_tpu.table import Table, bulkload
        s0 = Session(storage)
        s0.execute("CREATE DATABASE dr; USE dr")
        s0.execute("CREATE TABLE t (id BIGINT PRIMARY KEY, v BIGINT)")
        n = 3000
        bulkload.bulk_load(storage, Table(
            s0.domain.info_schema().table("dr", "t"), storage), {
            "id": np.arange(n), "v": np.zeros(n, dtype=np.int64)})
        s0.query("SELECT SUM(v) FROM t")     # warm the caches
        bad: list = []

        def writer(i):
            s = Session(storage)
            s.execute("USE dr")
            from tidb_tpu.session import SQLError
            for k in range(25):
                try:
                    s.execute(f"UPDATE t SET v = v + 1 WHERE id = "
                              f"{(i * 97 + k * 13) % n}")
                except SQLError:
                    pass            # write-write conflict: retried IRL
            s.close()

        def reader(i):
            s = Session(storage)
            s.execute("USE dr")
            prev = -1
            for _ in range(12):
                cnt, sv = s.query(
                    "SELECT COUNT(*), SUM(v) FROM t").rows[0]
                if cnt != n or sv < prev:
                    bad.append((cnt, sv, prev))
                prev = sv
            s.close()

        with stress():
            stop = threading.Event()
            rts = [threading.Thread(target=reader, args=(i,))
                   for i in range(3)]
            for t in rts:
                t.start()
            assert _run_threads(2, writer) == []
            stop.set()
            for t in rts:
                t.join()
        assert bad == [], f"inconsistent snapshots: {bad[:3]}"
        # final state visible through the delta-served cache
        final = s0.query("SELECT SUM(v) FROM t").rows[0][0]
        storage.delta_store.merge(trigger="rows")
        assert s0.query("SELECT SUM(v) FROM t").rows[0][0] == final
        s0.close()

    def test_sanitizer_saw_the_workloads(self, lock_sanitizer):
        """Vacuity guard for the dynamic half: the store workloads
        above really went through tracked locks (registered sites are
        wrapped while the sanitizer is enabled), and none of their
        orderings contradicted the static DAG so far."""
        assert lock_sanitizer.acquires > 100, \
            "sanitizer wrapped (almost) nothing — factory patching or " \
            "the registry site map has regressed"
        assert lock_sanitizer.violations == []


class TestSanitizer:
    """The checker itself, against a synthetic DAG (no patching —
    wrap() installs the proxies directly)."""

    DAG = {"edges": {("A", "B")},
           "kinds": {"A": "Lock", "B": "Lock", "C": "RLock"},
           "sites": {}}

    def _san(self):
        return lockorder.LockOrderSanitizer(self.DAG)

    def test_consistent_order_is_clean(self):
        san = self._san()
        a = san.wrap(threading.Lock(), "A")
        b = san.wrap(threading.Lock(), "B")
        with a:
            with b:
                pass
        assert san.violations == []
        assert ("A", "B") in san.observed

    def test_inversion_against_static_dag_is_caught(self):
        san = self._san()
        a = san.wrap(threading.Lock(), "A")
        b = san.wrap(threading.Lock(), "B")
        with b:         # B then A contradicts the static A -> B
            with a:
                pass
        assert [v.kind for v in san.violations] == ["cycle"]
        assert san.violations[0].edge == ("B", "A")

    def test_dynamic_dynamic_inversion_is_caught(self):
        """Two orders only ever seen at runtime still conflict: the
        observed half of the graph participates in the cycle check."""
        san = self._san()
        x = san.wrap(threading.Lock(), "X")
        y = san.wrap(threading.Lock(), "Y")
        with x:
            with y:
                pass
        with y:
            with x:
                pass
        assert [v.kind for v in san.violations] == ["cycle"]

    def test_same_name_hierarchy_is_allowed(self):
        """Distinct instances under one static name (the memtracker
        parent/child walk) are hierarchical locking the static names
        cannot order — not an inversion."""
        san = self._san()
        parent = san.wrap(threading.Lock(), "N")
        child = san.wrap(threading.Lock(), "N")
        with parent:
            with child:
                pass
        assert san.violations == []

    def test_rlock_reentry_is_allowed(self):
        san = self._san()
        c = san.wrap(threading.RLock(), "C", kind="RLock")
        with c:
            with c:
                pass
        assert san.violations == []

    def test_self_deadlock_raises_instead_of_hanging(self):
        san = self._san()
        a = san.wrap(threading.Lock(), "A")
        a.acquire()
        with pytest.raises(lockorder.LockOrderError):
            a.acquire()
        a.release()
        assert [v.kind for v in san.violations] == ["self-deadlock"]

    def test_transitive_cycle_through_static_edges(self):
        """B -> C observed, then C -> A: with static A -> B the chain
        closes a three-lock cycle even though no single pair inverts."""
        san = self._san()
        a = san.wrap(threading.Lock(), "A")
        b = san.wrap(threading.Lock(), "B")
        c = san.wrap(threading.Lock(), "C2")
        with b:
            with c:
                pass
        with c:
            with a:
                pass
        assert [v.kind for v in san.violations] == ["cycle"]
        assert san.violations[0].edge == ("C2", "A")

    def test_timed_acquire_miss_records_nothing(self):
        """Trylock backoff is deadlock AVOIDANCE: a miss must neither
        count as held nor enter the observed edge set — even when the
        attempt was made while holding another lock (recording B->A
        here would fabricate a cycle against everyone's real A-then-B
        order)."""
        san = self._san()
        a = san.wrap(threading.Lock(), "A")
        b = san.wrap(threading.Lock(), "B")
        grabbed = threading.Event()
        done = threading.Event()

        def holder():
            with a:
                grabbed.set()
                done.wait(5)

        t = threading.Thread(target=holder)
        t.start()
        grabbed.wait(5)
        with b:     # holding B while the timed grab of A misses
            assert a.acquire(timeout=0.01) is False
        done.set()
        t.join()
        assert ("B", "A") not in san.observed   # the miss left no edge
        with a:     # the avoided ordering's inverse stays legal
            with b:
                pass
        assert san.violations == []
        assert ("A", "B") in san.observed

    def test_timed_acquire_success_is_tracked(self):
        san = self._san()
        a = san.wrap(threading.Lock(), "A")
        b = san.wrap(threading.Lock(), "B")
        with a:
            assert b.acquire(timeout=1) is True
            b.release()
        assert ("A", "B") in san.observed
        assert san.violations == []

    def test_nested_sanitize_joins_active_and_leaves_it_enabled(self):
        """An inner sanitize() under an active sanitizer (env gate or
        an outer scope) joins it: same instance back, factories still
        patched on exit, and only scope-local violations would raise."""
        outer = lockorder.active()
        assert outer is not None    # module fixture
        with lockorder.sanitize() as inner:
            assert inner is outer
        assert lockorder.active() is outer

    def test_factory_patching_wraps_registered_sites_only(self):
        """While enabled, a lock constructed at a registry site comes
        back wrapped; stdlib/test-local construction passes through."""
        active = lockorder.active()
        assert active is not None   # module fixture
        raw = threading.Lock()      # this line is no registry site
        assert not isinstance(raw, lockorder._TrackedLock)
        from tidb_tpu.memtrack import MemTracker
        t = MemTracker("sanity")
        assert isinstance(t._mu, lockorder._TrackedLock)
        assert t._mu._lo_name.endswith("MemTracker._mu")
