"""Superchunk batching + the async device pipeline (ops/runtime.py):
assembly edge cases (0-row chunks, exact power-of-two sizes, oversize
slicing, varlen dict columns spanning a coalesce boundary), masked-tail
correctness against the host executor, the dispatch-ahead pipeline_map
contract, the dev-cache true-LRU fix, and end-to-end device-vs-host
agreement with pipelining on."""

import random

import numpy as np
import pytest

from tidb_tpu import config, sqltypes as st
from tidb_tpu.chunk import Chunk, Column
from tidb_tpu.expression import AggDesc, AggFunc, col
from tidb_tpu.ops import runtime
from tidb_tpu.ops.hashagg import HashAggKernel, HashAggregator
from tidb_tpu.ops.hostagg import host_hash_agg

INT = st.new_int_field()
DBL = st.new_double_field()
STR = st.new_string_field()


def _int_chunk(values):
    return Chunk.from_rows([INT], [(v,) for v in values])


# ---------------------------------------------------------------------------
# bucket_size / pad_column edges


def test_bucket_size_edges():
    assert runtime.bucket_size(0) == runtime.MIN_BUCKET
    assert runtime.bucket_size(1) == runtime.MIN_BUCKET
    assert runtime.bucket_size(runtime.MIN_BUCKET) == runtime.MIN_BUCKET
    assert runtime.bucket_size(runtime.MIN_BUCKET + 1) == \
        2 * runtime.MIN_BUCKET
    assert runtime.bucket_size(1 << 18) == 1 << 18       # exact pow2
    assert runtime.bucket_size((1 << 18) + 1) == 1 << 19


def test_pad_column_exact_size_is_identity():
    data = np.arange(16, dtype=np.int64)
    valid = np.ones(16, dtype=bool)
    pd, pv = runtime.pad_column(data, valid, 16)
    assert pd is data and pv is valid


def test_pad_column_zero_rows():
    pd, pv = runtime.pad_column(np.empty(0, dtype=np.int64),
                                np.empty(0, dtype=bool), 8)
    assert len(pd) == 8 and not pv.any()


def test_pad_column_tail_is_invalid():
    pd, pv = runtime.pad_column(np.arange(5, dtype=np.int64),
                                np.array([True] * 5), 8)
    assert pv[:5].all() and not pv[5:].any()
    assert (pd[5:] == 0).all()


# ---------------------------------------------------------------------------
# superchunk assembly


def test_superchunks_skip_zero_row_chunks():
    chunks = [_int_chunk([]), _int_chunk([1, 2]), _int_chunk([]),
              _int_chunk([3])]
    out = list(runtime.superchunk_batches(iter(chunks), 1024))
    assert len(out) == 1
    assert out[0].num_rows == 3 and out[0].sources == 2
    assert out[0].chunk.columns[0].data.tolist() == [1, 2, 3]


def test_superchunks_exact_power_of_two_fill():
    # 4 chunks of 256 rows coalesce into exactly one 1024-row bucket
    chunks = [_int_chunk(range(i * 256, (i + 1) * 256)) for i in range(4)]
    out = list(runtime.superchunk_batches(iter(chunks), 1024))
    assert [sc.num_rows for sc in out] == [1024]
    assert out[0].sources == 4
    assert out[0].bucket == 1024 and out[0].fill == 1.0
    assert out[0].chunk.columns[0].data.tolist() == list(range(1024))


def test_superchunks_slice_oversize_chunk():
    out = list(runtime.superchunk_batches(iter([_int_chunk(range(2500))]),
                                          1024))
    assert [sc.num_rows for sc in out] == [1024, 1024, 452]
    got = [v for sc in out for v in sc.chunk.columns[0].data.tolist()]
    assert got == list(range(2500))
    # the tail superchunk pads to the next power of two with dead rows
    assert out[2].bucket == 1024 and 0 < out[2].fill < 1


def test_superchunks_source_counts_across_boundary():
    # 600+600: second chunk spans the 1024 boundary, so it contributes
    # to (and counts in) both superchunks
    chunks = [_int_chunk(range(600)), _int_chunk(range(600, 1200))]
    out = list(runtime.superchunk_batches(iter(chunks), 1024))
    assert [sc.num_rows for sc in out] == [1024, 176]
    assert out[0].sources == 2 and out[1].sources == 1


def test_varlen_dict_column_spans_coalesce_boundary():
    """String group keys whose values straddle two source chunks must
    dict-encode consistently after coalescing: group-by over the
    superchunk equals group-by over the concatenated host rows."""
    rng = random.Random(7)
    words = ["ash", "birch", "cedar", "oak"]
    rows1 = [(words[rng.randrange(4)], rng.randrange(50))
             for _ in range(700)]
    rows2 = [(words[rng.randrange(4)], rng.randrange(50))
             for _ in range(700)]
    c1 = Chunk.from_rows([STR, INT], rows1)
    c2 = Chunk.from_rows([STR, INT], rows2)
    scs = list(runtime.superchunk_batches(iter([c1, c2]), 1024))
    assert len(scs) == 2 and scs[0].sources == 2
    aggs = [AggDesc(AggFunc.SUM, col(1, INT)), AggDesc(AggFunc.COUNT, None)]
    kernel = HashAggKernel(None, [col(0, STR)], aggs)
    dev = HashAggregator(aggs)
    for sc in scs:
        dev.update(kernel(sc.chunk))
    host = HashAggregator(aggs)
    for c in (c1, c2):
        host.update(host_hash_agg(c, None, [col(0, STR)], aggs))
    got = {k[0]: (int(v[0]), int(v[1])) for k, v in dev.results()}
    want = {k[0]: (int(v[0]), int(v[1])) for k, v in host.results()}
    assert got == want


def test_masked_tail_matches_host():
    """A partially-filled bucket's padding rows (valid=False tail) must
    contribute nothing: kernel over the padded superchunk == host agg
    over the raw rows."""
    rows = [(i % 7, float(i % 11)) for i in range(1500)]   # pads to 2048
    ch = Chunk.from_rows([INT, DBL], rows)
    sc = next(runtime.superchunk_batches(iter([ch]), 1 << 18))
    assert sc.bucket == 2048 and sc.num_rows == 1500
    aggs = [AggDesc(AggFunc.SUM, col(1, DBL)), AggDesc(AggFunc.COUNT, None),
            AggDesc(AggFunc.MIN, col(1, DBL))]
    kernel = HashAggKernel(None, [col(0, INT)], aggs)
    dev = HashAggregator(aggs)
    dev.update(kernel(sc.chunk))
    host = HashAggregator(aggs)
    host.update(host_hash_agg(ch, None, [col(0, INT)], aggs))
    for (gk, gv), (hk, hv) in zip(dev.results(), host.results()):
        assert gk == hk
        assert float(gv[0]) == pytest.approx(float(hv[0]))
        assert int(gv[1]) == int(hv[1])
        assert float(gv[2]) == pytest.approx(float(hv[2]))


def test_super_batches_wrapper_yields_chunks():
    chunks = [_int_chunk(range(10)), _int_chunk(range(10, 20))]
    out = list(runtime.super_batches([chunks[0]], iter(chunks[1:]), 1024))
    assert len(out) == 1 and out[0].num_rows == 20


# ---------------------------------------------------------------------------
# dispatch-ahead pipeline


def test_pipeline_map_order_and_depth():
    events = []
    in_flight = [0]
    peak = [0]

    def dispatch(i):
        in_flight[0] += 1
        peak[0] = max(peak[0], in_flight[0])
        events.append(("d", i))
        return i * 10

    def finalize(i, tok):
        in_flight[0] -= 1
        events.append(("f", i))
        return tok + 1

    out = list(runtime.pipeline_map(range(5), dispatch, finalize, 2))
    assert out == [1, 11, 21, 31, 41]          # item order preserved
    assert peak[0] == 2                        # never more than depth
    # double buffering: item 1 dispatches BEFORE item 0 finalizes
    assert events.index(("d", 1)) < events.index(("f", 0))


def test_pipeline_map_depth_one_is_serial():
    events = []
    out = list(runtime.pipeline_map(
        range(3), lambda i: events.append(("d", i)) or i,
        lambda i, t: events.append(("f", i)) or t, 1))
    assert out == [0, 1, 2]
    assert events == [("d", 0), ("f", 0), ("d", 1), ("f", 1),
                      ("d", 2), ("f", 2)]


def test_fingerprint_cache_lru_refresh():
    cache = runtime.FingerprintCache(capacity=2)
    a = cache.get_or_create("a", lambda: object())
    cache.get_or_create("b", lambda: object())
    assert cache.get_or_create("a", lambda: object()) is a  # refresh "a"
    cache.get_or_create("c", lambda: object())              # evicts "b"
    assert cache.get_or_create("a", lambda: object()) is a  # still cached
    made = []
    cache.get_or_create("b", lambda: made.append(1) or object())
    assert made == [1]                                      # "b" was evicted


# ---------------------------------------------------------------------------
# dev-cache true LRU


def test_dev_cache_hit_refreshes_lru_position():
    ch = _int_chunk(range(4))
    runtime.dev_cache_put(ch, "a", 1)
    runtime.dev_cache_put(ch, "b", 2)
    assert runtime.dev_cache_get(ch, "a") == 1     # refresh "a"
    runtime.dev_cache_put(ch, "c", 3)              # evicts LRU == "b"
    assert runtime.dev_cache_get(ch, "a") == 1
    assert runtime.dev_cache_get(ch, "b") is None
    assert runtime.dev_cache_get(ch, "c") == 3


# ---------------------------------------------------------------------------
# fingerprint-keyed kernel cache


def test_kernel_cache_shares_across_equal_plans():
    from tidb_tpu.ops.hashagg import kernel_for
    aggs1 = [AggDesc(AggFunc.SUM, col(1, INT))]
    aggs2 = [AggDesc(AggFunc.SUM, col(1, INT))]
    k1 = kernel_for(None, [col(0, INT)], aggs1)
    k2 = kernel_for(None, [col(0, INT)], aggs2)
    assert k1 is k2
    # different capacity / different column index -> different kernels
    assert kernel_for(None, [col(0, INT)], aggs1, capacity=8192) is not k1
    assert kernel_for(None, [col(2, INT)], aggs1) is not k1


def test_kernel_cache_distinguishes_scalar_func_extra():
    """IN value lists ride ScalarFunc.extra — two filters differing only
    there must NOT share a kernel (same op tree, different semantics)."""
    from tidb_tpu.expression import Op
    from tidb_tpu.expression.core import ScalarFunc
    from tidb_tpu.ops.hashagg import kernel_for
    f1 = ScalarFunc(Op.IN, [col(1, INT)], extra=[1, 2])
    f2 = ScalarFunc(Op.IN, [col(1, INT)], extra=[1, 3])
    aggs = [AggDesc(AggFunc.COUNT, None)]
    k1 = kernel_for(f1, [col(0, INT)], aggs)
    k2 = kernel_for(f2, [col(0, INT)], aggs)
    assert k1 is not k2
    assert runtime.plan_fingerprint(f1, [col(0, INT)], aggs) != \
        runtime.plan_fingerprint(f2, [col(0, INT)], aggs)


def test_plan_fingerprint_none_for_correlated():
    from tidb_tpu.expression.core import CorrelatedCol
    fp = runtime.plan_fingerprint(None, [CorrelatedCol(INT)], [])
    assert fp is None


# ---------------------------------------------------------------------------
# end-to-end: pipelined device execution must match the host executor


@pytest.fixture(scope="module")
def sess():
    from tidb_tpu.session import Session
    from tidb_tpu.store.storage import new_mock_storage
    s = Session(new_mock_storage())
    s.execute("CREATE DATABASE sc")
    s.execute("USE sc")
    s.execute("CREATE TABLE f (id BIGINT PRIMARY KEY, g BIGINT, "
              "tag VARCHAR(16), v DOUBLE)")
    rng = random.Random(3)
    rows = ",".join(
        f"({i},{rng.randrange(9)},'t{rng.randrange(5)}',{rng.random() * 100:.3f})"
        for i in range(6000))
    s.execute("INSERT INTO f VALUES " + rows)
    s.execute("CREATE TABLE dim (g BIGINT PRIMARY KEY, name VARCHAR(16))")
    s.execute("INSERT INTO dim VALUES " +
              ",".join(f"({i},'n{i}')" for i in range(9)))
    yield s
    s.close()


def _device_vs_host(sess, sql, sc_rows=4096, depth=2):
    with config.session_overlay({"tidb_tpu_device": 1,
                                 "tidb_tpu_superchunk_rows": sc_rows,
                                 "tidb_tpu_pipeline_depth": depth}):
        dev = sess.query(sql).rows
    with config.session_overlay({"tidb_tpu_device": 0}):
        host = sess.query(sql).rows
    assert len(dev) == len(host)
    for a, b in zip(dev, host):
        assert len(a) == len(b)
        for x, y in zip(a, b):
            if isinstance(x, float) or isinstance(y, float):
                assert float(x) == pytest.approx(float(y), rel=1e-9)
            else:
                assert x == y


class TestEndToEnd:
    def test_group_by_agg(self, sess):
        _device_vs_host(sess,
                        "SELECT g, COUNT(*), SUM(v), MIN(v), MAX(v) "
                        "FROM f GROUP BY g ORDER BY g")

    def test_string_group_keys(self, sess):
        _device_vs_host(sess,
                        "SELECT tag, COUNT(*), SUM(v) FROM f "
                        "GROUP BY tag ORDER BY tag")

    def test_join_then_agg(self, sess):
        _device_vs_host(sess,
                        "SELECT d.name, COUNT(*), SUM(f.v) FROM f "
                        "JOIN dim d ON f.g = d.g "
                        "GROUP BY d.name ORDER BY d.name")

    def test_tiny_superchunks_still_correct(self, sess):
        # superchunk smaller than a storage chunk: forces slicing +
        # many small buckets through the pipeline
        _device_vs_host(sess,
                        "SELECT g, COUNT(*), SUM(v) FROM f "
                        "GROUP BY g ORDER BY g", sc_rows=1024, depth=3)

    def test_pipeline_depth_one(self, sess):
        _device_vs_host(sess,
                        "SELECT g, COUNT(*), SUM(v) FROM f "
                        "GROUP BY g ORDER BY g", depth=1)

    def test_superchunk_off_matches_too(self, sess):
        _device_vs_host(sess,
                        "SELECT g, COUNT(*), SUM(v) FROM f "
                        "GROUP BY g ORDER BY g", sc_rows=0)

    def test_explain_analyze_shows_superchunks(self, sess):
        with config.session_overlay({"tidb_tpu_device": 1}):
            rs = sess.query("EXPLAIN ANALYZE SELECT g, COUNT(*), SUM(v) "
                            "FROM f GROUP BY g")
        pc = rs.columns.index("pipeline")
        cells = [r[pc] for r in rs.rows]
        coalesced = [c for c in cells if c != "-"]
        assert coalesced, rs.rows
        # "<N>sc/<M>ch fill=<r> stall=<t>"
        assert "sc/" in coalesced[0] and "fill=" in coalesced[0] \
            and "stall=" in coalesced[0]

    def test_superchunk_metrics_emitted(self, sess):
        from tidb_tpu import metrics
        with config.session_overlay({"tidb_tpu_device": 1}):
            sess.query("SELECT g, COUNT(*) FROM f GROUP BY g")
        snap = metrics.snapshot()
        assert any(k.startswith(metrics.SUPERCHUNKS) for k in snap), \
            sorted(snap)[:20]
        fill = sum(v for k, v in snap.items()
                   if k.startswith(metrics.SUPERCHUNK_FILL_ROWS))
        bucket = sum(v for k, v in snap.items()
                     if k.startswith(metrics.SUPERCHUNK_BUCKET_ROWS))
        assert 0 < fill <= bucket
