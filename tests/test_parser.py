"""Parser tests. Ref model: parser/parser_test.go (2.1k lines of cases);
here the cases that matter for the framework's executable surface,
including the verbatim TPC-H Q1/Q3/Q5 texts (the bench queries)."""

import decimal

import pytest

from tidb_tpu import sqltypes as st
from tidb_tpu.parser import ParseError, ast, parse, parse_one


def test_select_basic():
    s = parse_one("SELECT a, b+1 AS c FROM t WHERE a > 10 ORDER BY b DESC LIMIT 5")
    assert isinstance(s, ast.SelectStmt)
    assert len(s.fields) == 2
    assert s.fields[1].alias == "c"
    assert isinstance(s.where, ast.BinaryOp) and s.where.op == ">"
    assert s.order_by[0].desc
    assert s.limit == 5


def test_select_star_and_qualified():
    s = parse_one("SELECT *, t.*, db.t.c FROM db.t tt")
    assert isinstance(s.fields[0].expr, ast.Star)
    assert s.fields[1].expr.table == "t"
    c = s.fields[2].expr
    assert (c.db, c.table, c.name) == ("db", "t", "c")
    assert s.from_clause.db == "db" and s.from_clause.alias == "tt"


def test_operator_precedence():
    s = parse_one("SELECT 1+2*3")
    e = s.fields[0].expr
    assert e.op == "+" and e.right.op == "*"
    s2 = parse_one("SELECT a OR b AND c = d + 1")
    e2 = s2.fields[0].expr
    assert e2.op == "OR"
    assert e2.right.op == "AND"
    assert e2.right.right.op == "="


def test_predicates():
    s = parse_one("SELECT 1 FROM t WHERE a IN (1,2,3) AND b NOT LIKE 'x%' "
                  "AND c BETWEEN 1 AND 10 AND d IS NOT NULL")
    w = s.where
    # ((a IN .. AND b NOT LIKE ..) AND c BETWEEN ..) AND d IS NOT NULL
    assert isinstance(w.right, ast.IsNullExpr) and w.right.negated
    assert isinstance(w.left.right, ast.BetweenExpr)
    assert isinstance(w.left.left.right, ast.LikeExpr)
    assert w.left.left.right.negated
    assert isinstance(w.left.left.left, ast.InExpr)


def test_joins():
    s = parse_one("SELECT * FROM a JOIN b ON a.x = b.x LEFT JOIN c ON b.y = c.y")
    j = s.from_clause
    assert isinstance(j, ast.Join) and j.tp == ast.JoinType.LEFT
    assert isinstance(j.left, ast.Join) and j.left.tp == ast.JoinType.INNER
    s2 = parse_one("SELECT * FROM a, b WHERE a.x = b.x")
    assert isinstance(s2.from_clause, ast.Join)
    assert s2.from_clause.tp == ast.JoinType.CROSS


def test_aggregates_and_group():
    s = parse_one("SELECT k, COUNT(*), SUM(DISTINCT v), AVG(v) FROM t "
                  "GROUP BY k HAVING COUNT(*) > 1")
    assert s.fields[1].expr.star
    assert s.fields[2].expr.distinct
    assert len(s.group_by) == 1
    assert isinstance(s.having, ast.BinaryOp)


def test_case_cast():
    s = parse_one("SELECT CASE WHEN a>1 THEN 'x' ELSE 'y' END, "
                  "CASE a WHEN 1 THEN 2 END, CAST(a AS DECIMAL(10,2))")
    c1, c2, c3 = (f.expr for f in s.fields)
    assert c1.operand is None and c1.else_clause is not None
    assert c2.operand is not None
    assert c3.ft.tp == st.TypeCode.NEWDECIMAL and c3.ft.frac == 2


def test_subqueries():
    s = parse_one("SELECT a FROM t WHERE a IN (SELECT b FROM u) AND "
                  "EXISTS (SELECT 1 FROM v)")
    inx = s.where.left
    assert isinstance(inx, ast.InExpr)
    assert isinstance(inx.items, ast.SubqueryExpr)
    assert isinstance(s.where.right, ast.ExistsSubquery)
    s2 = parse_one("SELECT x FROM (SELECT a AS x FROM t) sub")
    assert isinstance(s2.from_clause, ast.SubqueryTable)
    assert s2.from_clause.alias == "sub"


def test_insert_forms():
    s = parse_one("INSERT INTO t (a, b) VALUES (1, 'x'), (2, 'y')")
    assert s.columns == ["a", "b"] and len(s.values) == 2
    s2 = parse_one("INSERT INTO t VALUES (1, DEFAULT)")
    assert isinstance(s2.values[0][1], ast.DefaultExpr)
    s3 = parse_one("INSERT INTO t SELECT * FROM u")
    assert s3.select is not None
    s4 = parse_one("INSERT INTO t (a) VALUES (1) ON DUPLICATE KEY UPDATE a = a + 1")
    assert len(s4.on_duplicate) == 1
    s5 = parse_one("REPLACE INTO t VALUES (1)")
    assert s5.is_replace


def test_update_delete():
    s = parse_one("UPDATE t SET a = 1, b = b + 1 WHERE c = 2 LIMIT 10")
    assert len(s.assignments) == 2 and s.limit == 10
    d = parse_one("DELETE FROM t WHERE a < 5")
    assert isinstance(d, ast.DeleteStmt) and d.where is not None


def test_create_table():
    s = parse_one("""
    CREATE TABLE IF NOT EXISTS t (
      id BIGINT PRIMARY KEY AUTO_INCREMENT,
      name VARCHAR(64) NOT NULL DEFAULT '',
      price DECIMAL(15,2),
      created DATETIME,
      KEY idx_name (name),
      UNIQUE KEY uk (price, created)
    ) ENGINE=InnoDB""")
    assert s.if_not_exists
    assert len(s.columns) == 4 and len(s.indexes) == 2
    idc = s.columns[0]
    assert idc.is_primary and idc.auto_increment
    assert s.columns[1].ft.not_null and s.columns[1].has_default
    assert s.columns[2].ft.frac == 2
    assert s.indexes[1].unique and s.indexes[1].columns == ["price", "created"]


def test_ddl_misc():
    assert isinstance(parse_one("CREATE DATABASE IF NOT EXISTS d"),
                      ast.CreateDatabaseStmt)
    assert isinstance(parse_one("CREATE UNIQUE INDEX i ON t (a, b)"),
                      ast.CreateIndexStmt)
    assert isinstance(parse_one("DROP TABLE IF EXISTS a, b"),
                      ast.DropTableStmt)
    a = parse_one("ALTER TABLE t ADD COLUMN c INT, DROP COLUMN d, "
                  "ADD INDEX i (c)")
    assert [sp.tp for sp in a.specs] == ["add_column", "drop_column",
                                         "add_index"]
    assert isinstance(parse_one("TRUNCATE TABLE t"), ast.TruncateTableStmt)
    r = parse_one("RENAME TABLE a TO b")
    assert r.pairs[0][0].name == "a"


def test_txn_and_session():
    assert isinstance(parse_one("BEGIN"), ast.BeginStmt)
    assert isinstance(parse_one("START TRANSACTION"), ast.BeginStmt)
    assert isinstance(parse_one("COMMIT"), ast.CommitStmt)
    assert isinstance(parse_one("ROLLBACK"), ast.RollbackStmt)
    s = parse_one("SET @@global.autocommit = 1, @x = 5, sql_mode = 'STRICT'")
    assert s.assignments[0].is_global
    assert s.assignments[1].name == "@x"
    assert s.assignments[2].is_system
    assert isinstance(parse_one("USE test"), ast.UseStmt)


def test_show_explain_admin():
    assert parse_one("SHOW DATABASES").tp == "databases"
    assert parse_one("SHOW TABLES").tp == "tables"
    s = parse_one("SHOW COLUMNS FROM t")
    assert s.tp == "columns" and s.table.name == "t"
    assert parse_one("SHOW VARIABLES LIKE 'max%'").pattern == "max%"
    e = parse_one("EXPLAIN SELECT 1")
    assert isinstance(e.stmt, ast.SelectStmt)
    assert parse_one("ANALYZE TABLE t").tables[0].name == "t"
    assert parse_one("ADMIN SHOW DDL").tp == "show_ddl"


def test_multi_statement():
    stmts = parse("SELECT 1; SELECT 2;")
    assert len(stmts) == 2


def test_string_escapes_and_comments():
    s = parse_one("SELECT 'it''s', \"a\\nb\" -- trailing\n FROM t /* c */")
    assert s.fields[0].expr.value == "it's"
    assert s.fields[1].expr.value == "a\nb"


def test_literals():
    s = parse_one("SELECT 1, 1.5, 1.5e3, -2, 'x', NULL, TRUE")
    vals = [f.expr for f in s.fields]
    assert vals[0].value == 1
    assert vals[1].value == decimal.Decimal("1.5")
    assert vals[2].value == 1500.0
    assert isinstance(vals[3], ast.UnaryOp)
    assert vals[5].value is None


def test_parse_errors():
    with pytest.raises(ParseError):
        parse_one("SELECT FROM t")
    with pytest.raises(ParseError):
        parse_one("SELEC 1")
    with pytest.raises(ParseError):
        parse_one("SELECT 1 FROM")
    with pytest.raises(ParseError):
        parse_one("INSERT INTO t")


TPCH_Q1 = """
SELECT l_returnflag, l_linestatus,
  SUM(l_quantity) AS sum_qty,
  SUM(l_extendedprice) AS sum_base_price,
  SUM(l_extendedprice * (1 - l_discount)) AS sum_disc_price,
  SUM(l_extendedprice * (1 - l_discount) * (1 + l_tax)) AS sum_charge,
  AVG(l_quantity) AS avg_qty,
  AVG(l_extendedprice) AS avg_price,
  AVG(l_discount) AS avg_disc,
  COUNT(*) AS count_order
FROM lineitem
WHERE l_shipdate <= DATE_SUB('1998-12-01', INTERVAL 90 DAY)
GROUP BY l_returnflag, l_linestatus
ORDER BY l_returnflag, l_linestatus
"""

TPCH_Q3 = """
SELECT l_orderkey,
  SUM(l_extendedprice * (1 - l_discount)) AS revenue,
  o_orderdate, o_shippriority
FROM customer, orders, lineitem
WHERE c_mktsegment = 'BUILDING'
  AND c_custkey = o_custkey
  AND l_orderkey = o_orderkey
  AND o_orderdate < '1995-03-15'
  AND l_shipdate > '1995-03-15'
GROUP BY l_orderkey, o_orderdate, o_shippriority
ORDER BY revenue DESC, o_orderdate
LIMIT 10
"""

TPCH_Q5 = """
SELECT n_name,
  SUM(l_extendedprice * (1 - l_discount)) AS revenue
FROM customer, orders, lineitem, supplier, nation, region
WHERE c_custkey = o_custkey
  AND l_orderkey = o_orderkey
  AND l_suppkey = s_suppkey
  AND c_nationkey = s_nationkey
  AND s_nationkey = n_nationkey
  AND n_regionkey = r_regionkey
  AND r_name = 'ASIA'
  AND o_orderdate >= '1994-01-01'
  AND o_orderdate < DATE_ADD('1994-01-01', INTERVAL 1 YEAR)
GROUP BY n_name
ORDER BY revenue DESC
"""


def test_tpch_queries_parse():
    q1 = parse_one(TPCH_Q1)
    assert len(q1.fields) == 10 and len(q1.group_by) == 2
    q3 = parse_one(TPCH_Q3)
    assert q3.limit == 10 and isinstance(q3.from_clause, ast.Join)
    q5 = parse_one(TPCH_Q5)
    assert len(q5.group_by) == 1
    # 6-way comma join nests 5 Joins deep
    depth = 0
    n = q5.from_clause
    while isinstance(n, ast.Join):
        depth += 1
        n = n.left
    assert depth == 5
