"""Device aggregation kernel tests: agree with a naive numpy/python oracle.

Ref model: executor/aggregate_test.go + mocktikv/aggregate.go behavior.
Runs on the CPU backend (conftest pins platforms) but the same XLA programs
compile for TPU.
"""

import decimal
import random
from collections import defaultdict

import numpy as np
import pytest

from tidb_tpu import sqltypes as st
from tidb_tpu.chunk import Chunk
from tidb_tpu.expression import AggDesc, AggFunc, Op, col, const, func
from tidb_tpu.ops.hashagg import (CapacityError, HashAggKernel,
                                  HashAggregator, ScalarAggKernel)

INT = st.new_int_field()
DBL = st.new_double_field()
DEC2 = st.new_decimal_field(frac=2)
STR = st.new_string_field()


def oracle_agg(rows, key_fn, val_fn, agg):
    groups = defaultdict(list)
    for r in rows:
        k = key_fn(r)
        if k is not None:
            groups[k].append(val_fn(r))
    return groups


def test_sum_count_by_int_key():
    rng = random.Random(1)
    rows = [(rng.randrange(5), rng.randrange(100)) for _ in range(3000)]
    ch = Chunk.from_rows([INT, INT], rows)
    k = HashAggKernel(None, [col(0, INT)],
                      [AggDesc(AggFunc.SUM, col(1, INT)),
                       AggDesc(AggFunc.COUNT, None)])
    agg = HashAggregator(k.aggs)
    agg.update(k(ch))
    got = {key[0]: tuple(v) for key, v in agg.results()}
    exp = defaultdict(lambda: [0, 0])
    for a, b in rows:
        exp[a][0] += b
        exp[a][1] += 1
    assert got == {k2: (v[0], v[1]) for k2, v in exp.items()}


def test_filter_and_group_with_nulls():
    rows = [(1, 10), (1, None), (2, 5), (None, 7), (2, 3), (1, 2)]
    ch = Chunk.from_rows([INT, INT], rows)
    # WHERE v >= 3
    k = HashAggKernel(col(1, INT).ge(3), [col(0, INT)],
                      [AggDesc(AggFunc.SUM, col(1, INT)),
                       AggDesc(AggFunc.COUNT, None),
                       AggDesc(AggFunc.MIN, col(1, INT)),
                       AggDesc(AggFunc.MAX, col(1, INT))])
    agg = HashAggregator(k.aggs)
    agg.update(k(ch))
    res = {key[0]: v for key, v in agg.results()}
    assert res[1] == [10, 1, 10, 10]
    assert res[2] == [8, 2, 3, 5]
    assert res[None] == [7, 1, 7, 7]  # NULL is its own group
    # row (1, None) dropped by filter; row (1,2) filtered out


def test_string_group_key():
    rows = [("aa", 1), ("bb", 2), ("aa", 3), (None, 4), ("cc", 5), ("bb", 6)]
    ch = Chunk.from_rows([STR, INT], rows)
    k = HashAggKernel(None, [col(0, STR)],
                      [AggDesc(AggFunc.SUM, col(1, INT))])
    agg = HashAggregator(k.aggs)
    agg.update(k(ch))
    res = {key[0]: v[0] for key, v in agg.results()}
    assert res == {"aa": 4, "bb": 8, "cc": 5, None: 4}


def test_multi_chunk_merge():
    k = HashAggKernel(None, [col(0, INT)],
                      [AggDesc(AggFunc.SUM, col(1, INT)),
                       AggDesc(AggFunc.AVG, col(1, DBL)),
                       AggDesc(AggFunc.MIN, col(1, INT))])
    agg = HashAggregator(k.aggs)
    all_rows = []
    rng = random.Random(2)
    for _ in range(4):
        rows = [(rng.randrange(3), rng.randrange(1000)) for _ in range(500)]
        all_rows += rows
        agg.update(k(Chunk.from_rows([INT, INT], rows)))
    res = {key[0]: v for key, v in agg.results()}
    for g in range(3):
        vals = [b for a, b in all_rows if a == g]
        assert res[g][0] == sum(vals)
        assert abs(res[g][1] - sum(vals) / len(vals)) < 1e-9
        assert res[g][2] == min(vals)


def test_decimal_sum_avg():
    rows = [(1, decimal.Decimal("1.50")), (1, decimal.Decimal("2.25")),
            (2, decimal.Decimal("-0.75")), (1, None)]
    ch = Chunk.from_rows([INT, DEC2], rows)
    aggs = [AggDesc(AggFunc.SUM, col(1, DEC2)),
            AggDesc(AggFunc.AVG, col(1, DEC2))]
    k = HashAggKernel(None, [col(0, INT)], aggs)
    agg = HashAggregator(aggs)
    agg.update(k(ch))
    res = {key[0]: v for key, v in agg.results()}
    assert res[1][0] == 375        # 3.75 @ frac2
    # avg result frac = 2+4 = 6: 1.875 -> 1875000
    assert aggs[1].result_ft.frac == 6
    assert res[1][1] == 1_875_000
    assert res[2][0] == -75


def test_avg_sum_real():
    rows = [(1, 1.5), (1, 2.5), (2, None)]
    ch = Chunk.from_rows([INT, DBL], rows)
    aggs = [AggDesc(AggFunc.SUM, col(1, DBL)),
            AggDesc(AggFunc.AVG, col(1, DBL)),
            AggDesc(AggFunc.COUNT, col(1, DBL))]
    k = HashAggKernel(None, [col(0, INT)], aggs)
    agg = HashAggregator(aggs)
    agg.update(k(ch))
    res = {key[0]: v for key, v in agg.results()}
    assert res[1] == [4.0, 2.0, 2]
    assert res[2] == [None, None, 0]  # all-null group


def test_expression_group_key():
    # GROUP BY a % 3
    rows = [(i, i * 10) for i in range(100)]
    ch = Chunk.from_rows([INT, INT], rows)
    gexpr = func(Op.MOD, col(0, INT), const(3))
    k = HashAggKernel(None, [gexpr], [AggDesc(AggFunc.COUNT, None)])
    agg = HashAggregator(k.aggs)
    agg.update(k(ch))
    res = {key[0]: v[0] for key, v in agg.results()}
    assert res == {0: 34, 1: 33, 2: 33}


def test_scalar_agg():
    rows = [(i, float(i)) for i in range(1000)]
    ch = Chunk.from_rows([INT, DBL], rows)
    aggs = [AggDesc(AggFunc.SUM, col(0, INT)),
            AggDesc(AggFunc.COUNT, None),
            AggDesc(AggFunc.MAX, col(1, DBL))]
    k = ScalarAggKernel(col(0, INT).lt(500), aggs)
    agg = HashAggregator(aggs)
    agg.update(k(ch))
    [(key, vals)] = agg.results()
    assert key == ()
    assert vals == [sum(range(500)), 500, 499.0]


def test_first_row():
    rows = [(1, "x"), (2, "y"), (1, "z")]
    ch = Chunk.from_rows([INT, STR], rows)
    aggs = [AggDesc(AggFunc.FIRST_ROW, col(1, STR))]
    k = HashAggKernel(None, [col(0, INT)], aggs)
    agg = HashAggregator(aggs)
    agg.update(k(ch))
    res = {key[0]: v[0] for key, v in agg.results()}
    assert res == {1: "x", 2: "y"}


def test_capacity_overflow_detected():
    rows = [(i,) for i in range(200)]
    ch = Chunk.from_rows([INT], rows)
    k = HashAggKernel(None, [col(0, INT)],
                      [AggDesc(AggFunc.COUNT, None)], capacity=64)
    with pytest.raises(CapacityError):
        k(ch)


def test_device_safety_validation():
    with pytest.raises(ValueError):
        HashAggKernel(func(Op.LIKE, col(0, STR), extra="%x%"), [col(1, INT)],
                      [AggDesc(AggFunc.COUNT, None)])
    with pytest.raises(ValueError):
        HashAggKernel(None, [func(Op.UPPER, col(0, STR))],
                      [AggDesc(AggFunc.COUNT, None)])
    with pytest.raises(ValueError):
        HashAggKernel(None, [col(1, INT)],
                      [AggDesc(AggFunc.MIN, col(0, STR))])


def test_empty_chunk_and_no_match_filter():
    ch = Chunk.from_rows([INT, INT], [(1, 2)])
    k = HashAggKernel(col(1, INT).gt(100), [col(0, INT)],
                      [AggDesc(AggFunc.SUM, col(1, INT))])
    agg = HashAggregator(k.aggs)
    agg.update(k(ch))
    assert agg.results() == []


def test_hashagg_exec_replans_capacity_overflow():
    """>capacity distinct groups: HashAggExec re-plans the device kernel
    with a larger table instead of losing the device path (the re-plan
    promised by the kernel docstring)."""
    from tidb_tpu.executor import HashAggExec
    from tidb_tpu.plan.physical import PhysHashAgg
    from tidb_tpu.plan.resolver import PlanSchema, SchemaCol

    n, ngroups = 6000, 5000
    rows = [(i % ngroups, i) for i in range(n)]
    ch = Chunk.from_rows([INT, INT], rows)

    class _Child:
        schema = None

        def chunks(self, ctx):
            yield ch

    plan = PhysHashAgg(
        schema=PlanSchema([SchemaCol("g", "", INT),
                           SchemaCol("s", "", st.new_int_field())]),
        children=[None],
        group_exprs=[col(0, INT)],
        aggs=[AggDesc(AggFunc.SUM, col(1, INT))])
    exe = HashAggExec.__new__(HashAggExec)
    exe.plan, exe.schema, exe.child, exe._kernel = plan, plan.schema, \
        _Child(), None
    out = list(exe.chunks(None))[0]
    assert out.num_rows == ngroups
    # the kernel was re-planned (not abandoned) with a larger capacity
    assert exe._kernel is not None and exe._kernel.capacity >= ngroups


def test_cond_direct_wide_span_takes_hash_branch():
    """BIGINT keys spanning more than 2^63: the int64 code math wraps,
    so the smallness decision must come from raw min/max in float64 and
    route to the hash branch (device path preserved, no collisions)."""
    import numpy as np
    from tidb_tpu.chunk import Chunk, Column
    from tidb_tpu.expression import AggDesc, AggFunc
    from tidb_tpu.expression.core import col
    from tidb_tpu.ops.hashagg import HashAggKernel
    from tidb_tpu.sqltypes import new_int_field
    n = 64
    keys = np.where(np.arange(n) % 2 == 0, -(2 ** 62), 2 ** 62)
    ch = Chunk([Column(new_int_field(), keys.astype(np.int64),
                       np.ones(n, bool)),
                Column(new_int_field(), np.ones(n, dtype=np.int64),
                       np.ones(n, bool))])
    k = HashAggKernel(None, [col(0, new_int_field(), "k")],
                      [AggDesc(AggFunc.SUM, col(1, new_int_field()))],
                      capacity=64)
    gr = k(ch)          # must not raise CollisionError
    assert sorted(int(c) for c in gr.counts) == [32, 32]
