"""Concurrent serving through the real wire protocol (ISSUE 10).

Eight client threads drive one server with a mixed analytic +
point-lookup replay and the suite asserts the serving contracts:
result isolation per connection, PROCESSLIST / memory_usage visibility
for every session, no cross-session digest bleed, background-worker
heartbeats NOT invalidating the columnar caches, the connection gauges,
the status-port /shed hook returning the hbm-cache ledger to zero, and
— under a pinched `tidb_tpu_server_mem_quota` — statements queueing or
shedding with the RETRYABLE 9008, never a mid-query
ER_MEM_EXCEED_QUOTA. The heavy bench leg (`python bench.py serve`)
rides behind the `slow` marker."""

import json
import threading
import time

import pytest

from tests.mysql_client import MiniClient, MySQLError
from tidb_tpu import config, errcode, memtrack, metrics, sched
from tidb_tpu.util import statusclient
from tidb_tpu.server import Server
from tidb_tpu.server.status import StatusServer
from tidb_tpu.store import new_mock_storage

N_CLIENTS = 8


@pytest.fixture
def env():
    saved = {v: config.get_var(v) for v in
             ("tidb_tpu_server_mem_quota", "tidb_tpu_admission_timeout_ms",
              "tidb_tpu_sched_inflight")}
    sched.reset_for_tests()
    storage = new_mock_storage()
    storage.async_commit_secondaries = False
    server = Server(storage, port=0)
    server.start()
    admin = MiniClient("127.0.0.1", server.port)
    admin.query("CREATE DATABASE IF NOT EXISTS test")
    admin.use("test")
    yield server, admin
    admin.close()
    server.close()
    storage.close()
    for k, v in saved.items():
        config.set_var(k, v)
    sched.reset_for_tests()


def _fanout(n, fn):
    """Run fn(i) on n threads; re-raise the first worker error."""
    errors: list = []
    barrier = threading.Barrier(n)

    def run(i):
        try:
            barrier.wait(timeout=30)
            fn(i)
        except Exception as e:  # noqa: BLE001 - surfaced below
            errors.append(e)

    threads = [threading.Thread(target=run, args=(i,)) for i in range(n)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(120)
    if errors:
        raise errors[0]


class TestMultiClientIsolation:
    def test_eight_clients_mixed_replay(self, env):
        """Mixed analytic + point-lookup replay on 8 connections: every
        client sees exactly its own data (result isolation), and every
        session shows up in PROCESSLIST and memory_usage."""
        server, admin = env
        admin.query("CREATE TABLE conc (id BIGINT PRIMARY KEY, "
                    "cli BIGINT, v BIGINT)")
        rows = ", ".join(f"({c * 1000 + i}, {c}, {i})"
                         for c in range(N_CLIENTS) for i in range(200))
        admin.query(f"INSERT INTO conc VALUES {rows}")
        seen_sessions: list = []

        def client(i):
            c = MiniClient("127.0.0.1", server.port, db="test")
            try:
                for _round in range(3):
                    # analytic: my partition's aggregate
                    _cols, rs = c.query(
                        "SELECT COUNT(*), SUM(v) FROM conc "
                        f"WHERE cli = {i}")
                    assert rs == [("200", str(sum(range(200))))], (i, rs)
                    # point lookups: my own rows only
                    for j in (0, 7, 199):
                        _cols, rs = c.query(
                            "SELECT v FROM conc WHERE id = "
                            f"{i * 1000 + j}")
                        assert rs == [(str(j),)], (i, j, rs)
                # PROCESSLIST sees my session while the conn is open
                _cols, pl = c.query("SHOW PROCESSLIST")
                assert len(pl) >= 2     # me + the admin at minimum
                seen_sessions.append(len(pl))
            finally:
                c.close()

        _fanout(N_CLIENTS, client)
        assert seen_sessions

    def test_sessions_visible_in_memory_usage(self, env):
        server, admin = env
        admin.query("CREATE TABLE mu (id BIGINT PRIMARY KEY, v BIGINT)")
        admin.query("INSERT INTO mu VALUES " + ", ".join(
            f"({i}, {i % 7})" for i in range(3000)))
        clients = [MiniClient("127.0.0.1", server.port, db="test")
                   for _ in range(4)]
        try:
            for c in clients:
                c.query("SELECT v, COUNT(*) FROM mu GROUP BY v")
            _cols, rs = admin.query(
                "SELECT scope, session_id, peak_host_bytes FROM "
                "information_schema.memory_usage")
            session_rows = [r for r in rs if r[0] == "session"]
            # every open connection's session is attributed (4 clients
            # + admin). At least the cache-cold client carries a real
            # peak; cache-warm ones legitimately track less (the scan
            # served from the columnar cache stages nothing)
            assert len(session_rows) >= 5
            busy = [r for r in session_rows if int(r[2]) > 10_000]
            assert len(busy) >= 1
        finally:
            for c in clients:
                c.close()

    def test_no_cross_session_digest_bleed(self, env):
        """Each client hammers a structurally distinct statement; the
        digest summary must attribute exactly its executions to each —
        concurrent sessions must not merge or miscount digests."""
        server, admin = env
        admin.query("CREATE TABLE dig (id BIGINT PRIMARY KEY, "
                    "a BIGINT, b BIGINT, c BIGINT)")
        admin.query("INSERT INTO dig VALUES " + ", ".join(
            f"({i}, {i}, {i * 2}, {i * 3})" for i in range(100)))
        col_of = {0: "a", 1: "b", 2: "c"}
        execs = {0: 4, 1: 5, 2: 6}

        def client(i):
            col, n = col_of[i % 3], execs[i % 3]
            c = MiniClient("127.0.0.1", server.port, db="test")
            try:
                for _ in range(n):
                    c.query(f"SELECT SUM({col}) FROM dig "
                            f"WHERE {col} > {i}")
            finally:
                c.close()

        _fanout(3, client)
        _cols, rs = admin.query(
            "SELECT digest_text, exec_count FROM "
            "performance_schema.events_statements_summary_by_digest")
        counts = {}
        for text, n in rs:
            low = text.lower()
            if "from dig" not in low:
                continue    # the summary is process-global: other
                #             suites' SUM(...) digests are not ours
            for i, col in col_of.items():
                if f"sum ( {col} )" in low:
                    counts[col] = int(n)
        assert counts == {"a": 4, "b": 5, "c": 6}, rs

    def test_connection_gauges(self, env):
        server, admin = env
        snap = metrics.snapshot()
        base = snap.get(metrics.CONNECTIONS_CURRENT, 0)
        assert base >= 1                # the admin connection
        extra = [MiniClient("127.0.0.1", server.port) for _ in range(3)]
        try:
            # gauge updates on the accept path
            assert metrics.snapshot()[metrics.CONNECTIONS_CURRENT] \
                == base + 3
        finally:
            for c in extra:
                c.close()
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline:
            if metrics.snapshot()[metrics.CONNECTIONS_CURRENT] == base:
                break
            time.sleep(0.02)
        assert metrics.snapshot()[metrics.CONNECTIONS_CURRENT] == base


class TestHeartbeatCacheStability:
    def test_workers_do_not_bump_data_version(self, env):
        """The schema worker publishes its version ~every half-lease;
        those bookkeeping commits must NOT invalidate the columnar
        caches — before this PR every cache entry died within a second
        of a wire server starting, so serving traffic never saw a warm
        cache."""
        server, admin = env
        storage = server.storage
        v0 = storage.engine.data_version
        time.sleep(1.6)                 # > one worker tick
        assert storage.engine.data_version == v0
        # a REAL write still invalidates
        admin.query("CREATE TABLE hb (id BIGINT PRIMARY KEY)")
        admin.query("INSERT INTO hb VALUES (1)")
        assert storage.engine.data_version > v0


class TestPinchedServerQuota:
    def test_statements_queue_or_shed_never_oom_cancel(self, env):
        """The acceptance bar: under a deliberately pinched server
        quota the 8-client replay completes; admission queues/sheds/
        rejects with the retryable 9008; NO statement dies mid-query
        with ER_MEM_EXCEED_QUOTA."""
        server, admin = env
        admin.query("CREATE TABLE pin (id BIGINT PRIMARY KEY, "
                    "g BIGINT, v BIGINT)")
        admin.query("INSERT INTO pin VALUES " + ", ".join(
            f"({i}, {i % 97}, {i % 13})" for i in range(6000)))
        agg = "SELECT g, COUNT(*), SUM(v) FROM pin GROUP BY g"
        admin.query(agg)                # record the digest's peak
        from tidb_tpu import perfschema
        peak = perfschema.digest_max_mem(agg)
        assert peak > 0
        quota = max(peak, 1 << 20)
        oom_key = 'tidb_tpu_mem_quota_exceeded_total{action="cancel"}'
        oom0 = metrics.snapshot().get(oom_key, 0)
        adm0 = sched.stats()["admission"]
        config.set_var("tidb_tpu_server_mem_quota", quota)
        config.set_var("tidb_tpu_admission_timeout_ms", 150)
        completed = []
        try:
            def client(i):
                c = MiniClient("127.0.0.1", server.port, db="test")
                try:
                    for _ in range(2):
                        tries = 0
                        while True:
                            try:
                                c.query(agg)
                                break
                            except MySQLError as e:
                                # ONLY the retryable admission code may
                                # surface; a mid-query OOM cancel
                                # (8175) fails the test right here
                                assert e.code == \
                                    errcode.ER_SERVER_BUSY_ADMISSION, e
                                tries += 1
                                assert tries < 200, "never admitted"
                                time.sleep(0.02)
                        completed.append(i)
                finally:
                    c.close()

            _fanout(N_CLIENTS, client)
        finally:
            config.set_var("tidb_tpu_server_mem_quota", 0)
        assert len(completed) == N_CLIENTS * 2      # workload completed
        adm1 = sched.stats()["admission"]
        contended = (adm1["queued"] - adm0["queued"]) + \
            (adm1["shed"] - adm0["shed"]) + \
            (adm1["rejected"] - adm0["rejected"])
        assert contended >= 1, adm1                 # the quota really bit
        assert metrics.snapshot().get(oom_key, 0) == oom0   # zero cancels
        assert memtrack.SERVER.total() >= 0


class TestStatusPort:
    def test_status_serving_block_and_shed_endpoint(self, env):
        server, admin = env
        status = StatusServer(server.storage, server)
        status.start()
        try:
            # warm an agg so the hbm-cache can hold residency: repeat
            # the SAME query (fills on the second, cache-resident scan)
            admin.query("CREATE TABLE sh (id BIGINT PRIMARY KEY, "
                        "v BIGINT)")
            admin.query("INSERT INTO sh VALUES " + ", ".join(
                f"({i}, {i % 5})" for i in range(4096)))
            for _ in range(3):
                admin.query("SELECT v, COUNT(*) FROM sh GROUP BY v")

            def get(path):
                return statusclient.get_json("127.0.0.1", status.port,
                                             path, timeout=10)

            st = get("/status")
            assert "serving" in st
            assert {"scheduler", "admission"} <= set(st["serving"])
            from tidb_tpu.store.device_cache import tracker
            resident = tracker().device
            shed = get("/shed")
            assert shed["freed_bytes"] >= resident
            # the satellite pin: one shed call returns the hbm-cache
            # ledger to zero
            assert tracker().device == 0
        finally:
            status.close()


class TestResourceMetering:
    def test_resource_usage_top_and_history(self, env):
        """The ISSUE 15 acceptance bar: under the concurrent serve
        workload, per-session device-time (resource_usage + GET /top)
        sums to the SERVER device busy-time within 10%, and the
        device-utilization series appears in GET /metrics/history."""
        from tidb_tpu import meter, metrics_history
        server, admin = env
        status = StatusServer(server.storage, server)
        status.start()
        try:
            admin.query("CREATE TABLE ru (id BIGINT PRIMARY KEY, "
                        "g BIGINT, v BIGINT)")
            admin.query("INSERT INTO ru VALUES " + ", ".join(
                f"({i}, {i % 53}, {i % 11})" for i in range(6000)))
            admin.query("SELECT g, COUNT(*), SUM(v) FROM ru GROUP BY g")

            # baseline: the meter is process-cumulative, so the 10%
            # reconciliation is over THIS leg's deltas
            srv0 = meter.SERVER.totals()
            sess0 = {s["session_id"]: s["device_ns"]
                     for s in meter.sessions_snapshot()}

            def client(i):
                c = MiniClient("127.0.0.1", server.port, db="test")
                try:
                    for _ in range(3):
                        c.query("SELECT g, COUNT(*), SUM(v) FROM ru "
                                f"WHERE id > {i} GROUP BY g")
                finally:
                    c.close()

            _fanout(4, client)
            srv1 = meter.SERVER.totals()
            busy = srv1["device_ns"] - srv0["device_ns"]
            attributed = sum(
                s["device_ns"] - sess0.get(s["session_id"], 0)
                for s in meter.sessions_snapshot())
            assert busy > 0, srv1
            assert 0.9 <= attributed / busy <= 1.1, (attributed, busy)

            # the memtable serves the same ledger
            _cols, rs = admin.query(
                "SELECT scope, session_id, device_time_ns, rows_sent "
                "FROM information_schema.resource_usage")
            scopes = {r[0] for r in rs}
            assert {"server", "user", "session"} <= scopes
            srv_row = [r for r in rs if r[0] == "server"][0]
            sess_sum = sum(int(r[2]) for r in rs if r[0] == "session")
            assert int(srv_row[2]) > 0
            assert sess_sum <= int(srv_row[2])

            def get(path):
                return statusclient.get_json("127.0.0.1", status.port,
                                             path, timeout=10)

            top = get("/top")
            assert top["server"]["device_ns"] > 0
            assert top["sessions"], top
            assert top["digests"], top
            assert 0 < top["attributed_device_ns"] \
                <= top["server"]["device_ns"] * 1.1
            # the busiest digest carries real device time
            assert top["digests"][0]["device_ns"] > 0

            # utilization history: force one sample, then the series
            # must serve on the status port
            metrics_history.sample_now()
            hist = get("/metrics/history")
            assert hist["history"]["points"] >= 1
            assert "tidb_tpu_device_utilization_ratio" in \
                hist["series"]
            for t, v in hist["series"][
                    "tidb_tpu_device_utilization_ratio"]:
                assert t > 0 and v >= 0
        finally:
            status.close()


@pytest.mark.slow
class TestServeBenchHeavy:
    def test_bench_serve_small_leg(self):
        """The load harness end to end in a subprocess (the heavy leg):
        concurrent rows/sec beats the serialized replay and the pinched
        leg completes with zero OOM cancels."""
        import os
        import subprocess
        import sys
        env = dict(os.environ, JAX_PLATFORMS="cpu",
                   BENCH_SERVE_CLIENTS="8", BENCH_SERVE_ROUNDS="1",
                   BENCH_SERVE_LOOKUPS="4", BENCH_SERVE_SF="0.01")
        root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        r = subprocess.run([sys.executable, "bench.py", "serve"],
                           cwd=root, env=env, capture_output=True,
                           text=True, timeout=560)
        assert r.returncode == 0, r.stderr[-2000:]
        rep = json.loads(r.stdout.strip().splitlines()[-1])
        d = rep["detail"]
        assert rep["value"] > 0
        assert d["pinched"]["completed"], d["pinched"]
        assert d["pinched"]["oom_cancels"] == 0
        assert d["concurrent"]["rows_per_sec"] > 0
