"""Binlog change capture (ref: sessionctx/binloginfo, 2pc.go:664) and
MySQL error-code classification on the wire (ref: mysql/errcode.go,
terror/terror.go:152)."""

import pytest

from tidb_tpu import binlog, errcode
from tidb_tpu.session import Session
from tidb_tpu.store.storage import new_mock_storage


class TestBinlog:
    @pytest.fixture
    def env(self):
        st = new_mock_storage()
        pump = binlog.MemoryPump()
        st.binlog_pump = pump
        s = Session(st)
        s.execute("CREATE DATABASE d")
        s.execute("USE d")
        s.execute("CREATE TABLE t (id BIGINT PRIMARY KEY, v BIGINT)")
        yield st, pump, s
        s.close()
        st.close()

    def test_dml_produces_ordered_events(self, env):
        st, pump, s = env
        before = len(pump.events())
        s.execute("INSERT INTO t VALUES (1, 10), (2, 20)")
        s.execute("UPDATE t SET v = 11 WHERE id = 1")
        s.execute("DELETE FROM t WHERE id = 2")
        evs = pump.events()[before:]
        assert len(evs) == 3
        # commit order is ts order, each event has both timestamps
        cts = [e.commit_ts for e in evs]
        assert cts == sorted(cts)
        assert all(e.commit_ts > e.start_ts for e in evs)

    def test_row_level_decode(self, env):
        st, pump, s = env
        info = s.domain.info_schema().table("d", "t")
        s.execute("INSERT INTO t VALUES (7, 70)")
        ins = binlog.decode_row_events(pump.events()[-1])
        puts = [r for r in ins if r.op == "PUT"]
        assert puts and puts[0].table_id == info.id
        assert puts[0].handle == 7
        assert 70 in puts[0].values.values()
        s.execute("DELETE FROM t WHERE id = 7")
        dels = binlog.decode_row_events(pump.events()[-1])
        assert any(r.op == "DELETE" and r.handle == 7 for r in dels)

    def test_rolled_back_txn_emits_nothing(self, env):
        st, pump, s = env
        before = len(pump.events())
        s.execute("BEGIN")
        s.execute("INSERT INTO t VALUES (9, 90)")
        s.execute("ROLLBACK")
        assert len(pump.events()) == before

    def test_subscriber_and_since_filter(self, env):
        st, pump, s = env
        got = []
        pump.subscribe(got.append)
        s.execute("INSERT INTO t VALUES (5, 50)")
        assert len(got) == 1
        cts = got[0].commit_ts
        s.execute("INSERT INTO t VALUES (6, 60)")
        later = pump.events(since_commit_ts=cts)
        assert len(later) == 1 and later[0].commit_ts > cts

    def test_no_pump_no_overhead(self):
        st = new_mock_storage()
        s = Session(st)
        s.execute("CREATE DATABASE d; USE d")
        s.execute("CREATE TABLE t (id BIGINT PRIMARY KEY)")
        s.execute("INSERT INTO t VALUES (1)")   # must not blow up
        assert s.query("SELECT COUNT(*) FROM t").rows == [(1,)]
        s.close()


class TestErrcode:
    def test_classify_typed(self):
        from tidb_tpu.parser import ParseError
        from tidb_tpu.schema.infoschema import SchemaError
        from tidb_tpu.table import DupKeyError
        assert errcode.classify(DupKeyError("dup"))[0] == 1062
        code, state, msg = errcode.classify(ParseError("bad"))
        assert (code, state) == (1064, "42000") and "syntax" in msg
        assert errcode.classify(
            SchemaError("Unknown database 'x'"))[0] == 1049
        assert errcode.classify(
            SchemaError("Table 'x' doesn't exist"))[0] == 1146

    def test_classify_storage_retryable_classes(self):
        """The 9xxx storage range (ref: terror.go): all retryable,
        including the region-stream-interrupted class raised when a
        streamed coprocessor reply dies past its resume budget."""
        from tidb_tpu import kv
        cases = {
            kv.StreamInterruptedError("mid"):
                errcode.ER_REGION_STREAM_INTERRUPTED,
            kv.EpochNotMatchError(3): errcode.ER_REGION_UNAVAILABLE,
            kv.NotLeaderError(1, 2): errcode.ER_REGION_UNAVAILABLE,
            kv.ServerBusyError("busy"): errcode.ER_TIKV_SERVER_BUSY,
        }
        for exc, want in cases.items():
            code, state, _ = errcode.classify(exc)
            assert code == want and state == "HY000"
            assert errcode.is_retryable(code)
        # GC-too-early maps but is NOT retryable: the snapshot aged out
        # and re-running the same ts can never succeed
        code, state, _ = errcode.classify(kv.GCTooEarlyError("old"))
        assert code == errcode.ER_GC_TOO_EARLY
        assert not errcode.is_retryable(code)
        # lock waits retry; user mistakes do not
        assert errcode.is_retryable(errcode.ER_LOCK_DEADLOCK)
        assert not errcode.is_retryable(errcode.ER_DUP_ENTRY)

    def test_sqlstate_catalog_consistent(self):
        """Every catalogued code carries a sqlstate; retryables are all
        in the catalog."""
        codes = {v for k, v in vars(errcode).items()
                 if k.startswith("ER_") and isinstance(v, int)}
        assert len(codes) >= 75
        for c in errcode.RETRYABLE:
            assert c in codes
        for c, state in errcode._SQLSTATE.items():
            assert c in codes and len(state) == 5

    def test_classify_by_message(self):
        from tidb_tpu.session import SQLError
        assert errcode.classify(
            SQLError("SELECT command denied to user"))[0] == 1142
        assert errcode.classify(
            SQLError("Unknown column 'q'"))[0] == 1054
        assert errcode.classify(SQLError("???"))[0] == errcode.ER_UNKNOWN

    def test_wire_codes(self):
        from mysql_client import MiniClient, MySQLError
        from tidb_tpu.server import Server
        st = new_mock_storage()
        srv = Server(st, port=0)
        srv.start()
        c = MiniClient("127.0.0.1", srv.port, user="root")
        c.query("CREATE DATABASE d")
        c.query("CREATE TABLE d.t (id BIGINT PRIMARY KEY)")
        c.query("INSERT INTO d.t VALUES (1)")
        with pytest.raises(MySQLError) as ei:
            c.query("INSERT INTO d.t VALUES (1)")
        assert ei.value.code == 1062
        with pytest.raises(MySQLError) as ei:
            c.query("SELECT * FROM d.nope")
        assert ei.value.code == 1146
        with pytest.raises(MySQLError) as ei:
            c.query("SELEKT 1")
        assert ei.value.code == 1064
        c.close()
        srv.close()
