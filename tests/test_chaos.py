"""Chaos under concurrency (docs/ROBUSTNESS.md): seeded fault
schedules over concurrent readers + writers, with the ledger/slot
hygiene fixture asserting SERVER memtrack ledgers and scheduler slots
drain to zero after every test. The light leg runs in-process on
direct sessions inside the tier-1 budget; the full wire-protocol
harness (`python bench.py chaos`, scripts/chaos_bench.sh) rides behind
the `slow` marker."""

import json
import random
import subprocess
import sys
import threading
import time

import pytest

from tidb_tpu import config, errcode, metrics, sched
from tidb_tpu.session import Session, SQLError
from tidb_tpu.store.storage import new_mock_storage
from tidb_tpu.util import failpoint

pytestmark = pytest.mark.usefixtures("ledger_hygiene")

N_ROWS = 3000
SEED = 20260804


@pytest.fixture
def env():
    saved = {k: config.get_var(k) for k in
             ("tidb_tpu_device", "tidb_tpu_device_min_rows",
              "tidb_tpu_dispatch_timeout_ms",
              "tidb_tpu_delta_merge_rows")}
    config.set_var("tidb_tpu_device_min_rows", 1)
    st = new_mock_storage()
    s = Session(st)
    s.execute("CREATE DATABASE c")
    s.execute("USE c")
    s.execute("CREATE TABLE t (id BIGINT PRIMARY KEY, seg BIGINT, "
              "v BIGINT)")
    rows = [f"({i},{i % 7},{(i * 37) % 500})" for i in range(N_ROWS)]
    s.execute("INSERT INTO t VALUES " + ",".join(rows))
    info = s.domain.info_schema().table("c", "t")
    st.cluster.split_table(info.id, 4, max_handle=N_ROWS)
    yield s, st
    failpoint.disable_all()
    sched.device_health().note_ok()
    s.close()
    st.close()
    for k, v in saved.items():
        config.set_var(k, v)


AGG = "SELECT seg, COUNT(*), SUM(v) FROM t GROUP BY seg ORDER BY seg"


class TestInProcessChaos:
    def test_concurrent_readers_writers_under_seeded_faults(self, env):
        """3 reader threads + 1 writer run ~3s under a seeded schedule
        of device faults, HBM faults and RPC bursts: every analytic
        answer matches the write-invariant reference columns, every
        error that surfaces is retryable-classified, and (fixture) the
        ledgers/slots drain afterwards."""
        s, st = env
        rng = random.Random(SEED)
        ref = s.query(AGG).rows
        ref_counts = [(r[0], r[1]) for r in ref]

        stop = threading.Event()
        wrong: list = []
        non_retryable: list = []
        done = [0]

        def reader(ri: int) -> None:
            rs = Session(st, db="c")
            while not stop.is_set():
                try:
                    rows = rs.query(AGG).rows
                    # seg/count columns are write-invariant (the
                    # writer only touches v): they must match exactly
                    if [(r[0], r[1]) for r in rows] != ref_counts:
                        wrong.append(rows[:2])
                    done[0] += 1
                except SQLError as e:
                    code = errcode.classify(e)[0]
                    if not errcode.is_retryable(code):
                        non_retryable.append(f"({code}) {e}")
                except failpoint.DeviceFaultError as e:
                    # a raw device fault (no SQL wrapping on the
                    # library path) is retryable by contract
                    assert errcode.classify(e)[0] == \
                        errcode.ER_DEVICE_FAULT
            rs.close()

        def writer() -> None:
            ws = Session(st, db="c")
            seq = 0
            while not stop.is_set():
                seq += 1
                k = (seq * 7919) % N_ROWS
                try:
                    ws.execute(f"UPDATE t SET v = v + 1 "
                               f"WHERE id = {k}")
                except SQLError as e:
                    code = errcode.classify(e)[0]
                    if not errcode.is_retryable(code):
                        non_retryable.append(f"write ({code}) {e}")
                time.sleep(0.01)
            ws.close()

        def driver() -> None:
            schedule = [
                ("device/dispatch",
                 lambda: f"{rng.randint(1, 3)}*raise(DeviceFaultError)"),
                ("hbm/fill",
                 lambda: f"{rng.randint(1, 2)}*raise(DeviceFaultError)"),
                ("hbm/patch", lambda: "2*return(1)"),
                ("rpc/request",
                 lambda: f"{rng.randint(2, 4)}*raise(ServerBusyError)"),
                ("device/finalize",
                 lambda: f"1-in-4:delay({rng.randint(5, 20)})"),
            ]
            while not stop.is_set():
                name, mk = schedule[rng.randrange(len(schedule))]
                failpoint.enable(name, mk())
                stop.wait(rng.uniform(0.05, 0.15))
                failpoint.disable(name)

        threads = [threading.Thread(target=reader, args=(i,),
                                    name=f"chaos-reader-{i}")
                   for i in range(3)]
        threads.append(threading.Thread(target=writer,
                                        name="chaos-writer"))
        dt = threading.Thread(target=driver, name="chaos-driver")
        for t in threads:
            t.start()
        dt.start()
        time.sleep(3.0)
        stop.set()
        dt.join(timeout=10)
        failpoint.disable_all()
        for t in threads:
            t.join(timeout=60)
            assert not t.is_alive(), f"{t.name} stuck"
        assert wrong == []
        assert non_retryable == []
        assert done[0] > 0
        # post-chaos: disarmed serving answers correctly again
        sched.device_health().note_ok()
        rows = s.query(AGG).rows
        assert [(r[0], r[1]) for r in rows] == ref_counts

    def test_watchdog_under_concurrency_never_wedges(self, env):
        """A watchdog-tripping delay under concurrent statements: the
        affected statements surface the retryable 9009 (or succeed on
        a retried path), nothing hangs, slots drain (fixture)."""
        s, st = env
        want = [(r[0], r[1]) for r in s.query(AGG).rows]
        config.set_var("tidb_tpu_dispatch_timeout_ms", 150)
        failpoint.enable("device/finalize", "2*delay(600)")
        errs: list = []
        oks = [0]

        def runner() -> None:
            rs = Session(st, db="c")
            for _ in range(3):
                try:
                    rows = rs.query(AGG).rows
                    assert [(r[0], r[1]) for r in rows] == want
                    oks[0] += 1
                except Exception as e:  # noqa: BLE001 - classified below
                    errs.append(errcode.classify(e)[0])
            rs.close()

        threads = [threading.Thread(target=runner, name=f"wd-{i}")
                   for i in range(2)]
        t0 = time.time()
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=120)
            assert not t.is_alive(), "statement wedged past watchdog"
        failpoint.disable("device/finalize")
        config.set_var("tidb_tpu_dispatch_timeout_ms", 0)
        # bounded wall time: 6 statements, two 600ms delays, no hang
        assert time.time() - t0 < 110
        assert oks[0] + len(errs) == 6
        for code in errs:
            assert code == errcode.ER_DEVICE_FAULT, errs


@pytest.mark.slow
class TestChaosBenchLeg:
    def test_bench_chaos_small_leg(self):
        """The full wire-protocol chaos harness, small: fixed seed,
        short window; the JSON must report passed=True with every
        invariant field clean (same assertions as
        scripts/chaos_bench.sh)."""
        import os
        env = dict(os.environ)
        env.update({"JAX_PLATFORMS": "cpu",
                    "BENCH_CHAOS_SECS": "8",
                    "BENCH_CHAOS_CLIENTS": "3",
                    "BENCH_CHAOS_SF": "0.005"})
        r = subprocess.run([sys.executable, "bench.py", "chaos"],
                           capture_output=True, text=True, env=env,
                           timeout=600, cwd=os.path.dirname(
                               os.path.dirname(os.path.abspath(
                                   __file__))))
        assert r.returncode == 0, r.stderr[-2000:]
        rep = json.loads(r.stdout.strip().splitlines()[-1])
        d = rep["detail"]
        assert d["passed"], d
        assert d["wrong_results"] == []
        assert d["non_retryable_errors"] == []
        assert d["stuck_statements"] == []
        assert d["oom_cancels"] == 0
        assert d["sched_inflight_end"] == 0
        assert d["server_ledger_host_end"] == 0
        assert d["server_ledger_device_end"] == 0
