"""Fragment fusion (ops/fragment.py, ISSUE 12): one XLA program per
probe superchunk executes match -> gather -> group -> partial agg under
an agg-over-inner-join. Fused == unfused byte-for-byte, pair-capacity
overflow self-heals, group-capacity misses escalate then degrade per
batch, ineligible shapes (outer joins, other_cond, skewed/hybrid
builds) keep the per-operator path, and EXPLAIN ANALYZE shows
`enc=fused:probe-agg`."""

import numpy as np
import pytest

from tidb_tpu import metrics
from tidb_tpu.expression.core import ColumnRef
from tidb_tpu.ops import fragment as op_fragment
from tidb_tpu.ops.hashagg import DeviceRejectError
from tidb_tpu.session import Session
from tidb_tpu.sqltypes import FieldType, TypeCode, new_string_field
from tidb_tpu.store.storage import new_mock_storage

FT_I = FieldType(tp=TypeCode.LONGLONG)
FT_S = new_string_field()


def _metric(prefix: str) -> float:
    return sum(v for k, v in metrics.snapshot().items()
               if k.startswith(prefix))


@pytest.fixture(scope="module")
def frag_sess():
    s = Session(new_mock_storage())
    s.execute("CREATE DATABASE frag")
    s.execute("USE frag")
    s.execute("CREATE TABLE fact (id BIGINT PRIMARY KEY, k BIGINT, "
              "amt DECIMAL(12,2), q BIGINT)")
    s.execute("CREATE TABLE dim (id BIGINT PRIMARY KEY, grp VARCHAR(8), "
              "w BIGINT)")
    rng = np.random.default_rng(12)
    n, nd = 8000, 300
    rows = []
    for i in range(n):
        # dangling keys past the dim table + a few NULL keys
        k = "NULL" if i % 97 == 0 else str(int(rng.integers(0, nd + 40)))
        rows.append(f"({i}, {k}, {rng.integers(0, 99999) / 100}, "
                    f"{i % 19})")
    for i in range(0, n, 500):
        s.execute("INSERT INTO fact VALUES " + ",".join(rows[i:i + 500]))
    s.execute("INSERT INTO dim VALUES " + ",".join(
        f"({i}, 'g{i % 7}', {i % 13})" for i in range(nd)))
    s.execute("SET tidb_tpu_device_min_rows = 1")
    yield s
    s.close()


def _fused_vs_not(s, q):
    s.execute("SET tidb_tpu_fuse_fragments = 1")
    fused = s.query(q).rows
    s.execute("SET tidb_tpu_fuse_fragments = 0")
    try:
        plain = s.query(q).rows
    finally:
        s.execute("SET tidb_tpu_fuse_fragments = 1")
    return fused, plain


class TestFusedEqualsUnfused:
    def test_group_by_build_string(self, frag_sess):
        q = ("SELECT dim.grp, COUNT(*), SUM(fact.amt), MIN(fact.q), "
             "MAX(dim.w) FROM fact JOIN dim ON fact.k = dim.id "
             "GROUP BY dim.grp ORDER BY dim.grp")
        fused, plain = _fused_vs_not(frag_sess, q)
        assert fused == plain

    def test_group_by_probe_key_highcard(self, frag_sess):
        """> capacity distinct groups: the fragment kernel escalates
        once and stays fused (or falls back per batch) — results must
        not change either way."""
        q = ("SELECT fact.id, SUM(fact.amt) FROM fact "
             "JOIN dim ON fact.k = dim.id "
             "GROUP BY fact.id ORDER BY fact.id LIMIT 17")
        fused, plain = _fused_vs_not(frag_sess, q)
        assert fused == plain

    def test_avg_and_mixed_side_columns(self, frag_sess):
        q = ("SELECT dim.grp, AVG(fact.amt), SUM(dim.w), COUNT(*) "
             "FROM fact JOIN dim ON fact.k = dim.id "
             "GROUP BY dim.grp ORDER BY dim.grp")
        fused, plain = _fused_vs_not(frag_sess, q)
        assert fused == plain

    def test_scalar_agg_over_join(self, frag_sess):
        q = ("SELECT COUNT(*), SUM(fact.amt) FROM fact "
             "JOIN dim ON fact.k = dim.id")
        fused, plain = _fused_vs_not(frag_sess, q)
        assert fused == plain

    def test_explain_shows_fused_mode(self, frag_sess):
        r = frag_sess.query(
            "EXPLAIN ANALYZE SELECT dim.grp, COUNT(*) FROM fact "
            "JOIN dim ON fact.k = dim.id GROUP BY dim.grp")
        pc = r.columns.index("pipeline")
        cell = next(row[pc] for row in r.rows if "HashAgg" in row[0])
        assert "enc=fused:probe-agg" in cell


class TestPairOverflow:
    def test_many_to_many_regrow(self):
        """All-one-key many-to-many: total pairs far exceed the initial
        pair capacity — finalize must regrow and stay exact."""
        s = Session(new_mock_storage())
        s.execute("CREATE DATABASE ovf")
        s.execute("USE ovf")
        s.execute("CREATE TABLE p (id BIGINT PRIMARY KEY, k BIGINT, "
                  "v BIGINT)")
        s.execute("CREATE TABLE b (id BIGINT PRIMARY KEY, k BIGINT)")
        rows = ",".join(f"({i}, 1, {i % 7})" for i in range(5000))
        s.execute("INSERT INTO p VALUES " + rows)
        s.execute("INSERT INTO b VALUES " + ",".join(
            f"({i}, 1)" for i in range(100)))
        s.execute("SET tidb_tpu_device_min_rows = 1")
        try:
            q = ("SELECT COUNT(*), SUM(p.v) FROM p JOIN b "
                 "ON p.k = b.k")
            fused, plain = _fused_vs_not(s, q)
            assert fused == plain == [(500000, 1499500)]
        finally:
            s.close()


class TestIneligibleShapes:
    def test_outer_join_not_fused_still_correct(self, frag_sess):
        q = ("SELECT dim.grp, COUNT(*) FROM fact LEFT JOIN dim "
             "ON fact.k = dim.id GROUP BY dim.grp ORDER BY dim.grp")
        fused, plain = _fused_vs_not(frag_sess, q)
        assert fused == plain

    def test_other_cond_not_fused_still_correct(self, frag_sess):
        q = ("SELECT dim.grp, COUNT(*) FROM fact JOIN dim "
             "ON fact.k = dim.id AND fact.q < dim.w "
             "GROUP BY dim.grp ORDER BY dim.grp")
        fused, plain = _fused_vs_not(frag_sess, q)
        assert fused == plain

    def test_first_row_agg_rejects(self):
        from tidb_tpu.expression import AggDesc, AggFunc
        with pytest.raises(DeviceRejectError):
            op_fragment.ProbeAggKernel(
                1, 2, 4, [ColumnRef(0, FT_I, "k")],
                [AggDesc(fn=AggFunc.FIRST_ROW,
                         arg=ColumnRef(3, FT_S, "s"))])

    def test_hybrid_build_stands_aside(self, frag_sess):
        """An over-superchunk build (> _DEVICE_MIN_BUILD rows, bigger
        than tidb_tpu_superchunk_rows) hands the probe to the hybrid
        join's machinery; results match the per-operator path."""
        s = frag_sess
        s.execute("SET tidb_tpu_superchunk_rows = 128")
        try:
            # self-join: BOTH sides exceed the hybrid's build floor, so
            # whichever side the planner builds engages partitioning
            q = ("SELECT f2.q, COUNT(*), SUM(f1.amt) FROM fact f1 "
                 "JOIN fact f2 ON f1.k = f2.id GROUP BY f2.q "
                 "ORDER BY f2.q")
            fused, plain = _fused_vs_not(s, q)
            assert fused == plain
        finally:
            s.execute("SET tidb_tpu_superchunk_rows = 262144")
