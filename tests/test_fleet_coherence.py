"""Fleet cache coherence (store/fleetcop.py): a stateless SQL server's
OWN chunk/HBM caches stay hot across another writer's commits by
pulling the store plane's delta-journal window over the wire and
patching resident blocks in place — never a full re-fill, never a
violation of snapshot isolation (a reader at T applies only deltas
with commit_ts <= T). The acceptance pins for ISSUE 16's tentpole
part 3."""

import pytest

from tidb_tpu import config, metrics
from tidb_tpu.session import Session
from tidb_tpu.store.remote import StorageServer, connect


def _counter(name: str, **labels) -> float:
    snap = metrics.snapshot()
    if labels:
        lab = ",".join(f'{k}="{v}"' for k, v in sorted(labels.items()))
        return snap.get(f"{name}{{{lab}}}", 0)
    return sum(v for k, v in snap.items()
               if k == name or k.startswith(name + "{"))


@pytest.fixture
def fleet_env():
    """One store-plane process-equivalent (StorageServer socket) plus a
    fleet-mode client storage (local caches + journal coherence) and a
    plain remote writer — two 'SQL servers' sharing one store plane."""
    srv = StorageServer()
    srv.start()
    st = connect("127.0.0.1", srv.port, local_cache=True)
    wst = connect("127.0.0.1", srv.port)
    s = Session(st)
    w = Session(wst)
    s.execute("CREATE DATABASE d")
    s.execute("USE d")
    w.execute("USE d")
    yield srv, st, s, w
    w.close()
    s.close()
    wst.close()
    st.close()
    srv.close()


def _load(s, n=64):
    s.execute("CREATE TABLE t (id BIGINT PRIMARY KEY, v BIGINT)")
    s.execute("INSERT INTO t VALUES " +
              ", ".join(f"({i}, {i % 7})" for i in range(n)))
    return sum(i % 7 for i in range(n))


class TestJournalCoherence:
    def test_remote_commit_served_by_patched_resident_block(
            self, fleet_env):
        """THE coherence acceptance test: after the local fill, a write
        committed by ANOTHER server is served from the resident block
        via a shipped journal window — cache hit + patch, no re-fill."""
        srv, st, s, w = fleet_env
        total = _load(s)
        assert s.query("SELECT SUM(v) FROM t").rows[0][0] == total
        hits0, miss0 = st.chunk_cache.hits, st.chunk_cache.misses
        d0 = _counter(metrics.CACHE_DELTA_SERVES)
        p0 = _counter(metrics.FLEET_PATCHED_ROWS)
        w0 = _counter(metrics.FLEET_JOURNAL_PULLS, outcome="window")
        w.execute("INSERT INTO t VALUES (1000, 3)")
        w.execute("DELETE FROM t WHERE id = 0")
        w.execute("UPDATE t SET v = v + 1 WHERE id = 1")
        want = total + 3 - 0 + 1
        assert s.query("SELECT SUM(v) FROM t").rows[0][0] == want
        assert st.chunk_cache.misses == miss0, "block was re-filled"
        assert st.chunk_cache.hits > hits0
        assert _counter(metrics.CACHE_DELTA_SERVES) > d0
        assert _counter(metrics.FLEET_PATCHED_ROWS) >= p0 + 3
        assert _counter(metrics.FLEET_JOURNAL_PULLS,
                        outcome="window") > w0

    def test_reader_at_t_never_sees_later_delta(self, fleet_env):
        """Snapshot isolation across the wire: a transaction reading at
        T must not observe a delta committed after T, even though the
        resident block could be patched to the newer state."""
        srv, st, s, w = fleet_env
        total = _load(s)
        s.execute("BEGIN")
        assert s.query("SELECT SUM(v) FROM t").rows[0][0] == total
        w.execute("INSERT INTO t VALUES (2000, 6)")
        # repeatable: the (fill_ts, T] window excludes the new commit
        assert s.query("SELECT SUM(v) FROM t").rows[0][0] == total
        s.execute("COMMIT")
        assert s.query("SELECT SUM(v) FROM t").rows[0][0] == total + 6

    def test_truncated_journal_falls_back_to_refill(self, fleet_env):
        """STALE handling: a store-plane merge that truncates the
        journal under the local fill snapshot (retention 0) forces a
        drop-and-refill — slower, never wrong."""
        srv, st, s, w = fleet_env
        total = _load(s)
        assert s.query("SELECT SUM(v) FROM t").rows[0][0] == total
        w.execute("INSERT INTO t VALUES (3000, 2)")
        assert srv.storage.delta_store.merge(trigger="rows") >= 1
        s0 = _counter(metrics.FLEET_JOURNAL_PULLS, outcome="stale")
        d0 = _counter(metrics.CACHE_DELTA_SERVES)
        assert s.query("SELECT SUM(v) FROM t").rows[0][0] == total + 2
        assert _counter(metrics.FLEET_JOURNAL_PULLS,
                        outcome="stale") > s0
        assert _counter(metrics.CACHE_DELTA_SERVES) == d0, \
            "a truncated window must re-scan, never patch"

    def test_local_cache_sysvar_delegates_to_store_plane(
            self, fleet_env):
        srv, st, s, w = fleet_env
        total = _load(s)
        prev = config.get_var("tidb_tpu_fleet_local_cache")
        config.set_var("tidb_tpu_fleet_local_cache", 0)
        try:
            r0 = _counter(metrics.FLEET_LOCAL_COP, path="store")
            assert s.query("SELECT SUM(v) FROM t").rows[0][0] == total
            assert _counter(metrics.FLEET_LOCAL_COP, path="store") > r0
        finally:
            config.set_var("tidb_tpu_fleet_local_cache", prev)
        c0 = _counter(metrics.FLEET_LOCAL_COP, path="cached")
        assert s.query("SELECT SUM(v) FROM t").rows[0][0] == total
        assert _counter(metrics.FLEET_LOCAL_COP, path="cached") > c0

    def test_disconnect_invalidates_region_epochs(self, fleet_env):
        """ISSUE 16 satellite fix: a dropped store-plane connection
        must flush every cached region epoch (and learned leader) so
        the reconnecting server re-resolves instead of looping on
        stream-interrupt retries with stale routing."""
        srv, st, s, w = fleet_env
        _load(s)
        s.query("SELECT SUM(v) FROM t")
        assert len(st.region_cache._by_start) > 0
        st.rpc._notify_disconnect()
        assert len(st.region_cache._by_start) == 0
        assert len(st.region_cache._start_by_id) == 0
        assert len(st.region_cache._leaders) == 0
        # routing recovers by re-resolving through the region map
        assert s.query("SELECT COUNT(*) FROM t").rows[0][0] == 64
