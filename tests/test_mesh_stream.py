"""Streaming mesh execution (BASELINE config 5): probes larger than
tidb_tpu_stream_rows are never materialized whole on the host — they feed
the mesh kernels in bounded, double-buffered super-batches.

Asserted here, through plain Session.execute on the 8-device virtual mesh:
  * results match the host path exactly (Q1 and Q3 shapes);
  * buffering is bounded: no batch ever exceeds stream_rows + one chunk;
  * the overlap happened: batch i+1's launch preceded batch i's readback.

Ref: the reference streams bounded chunk channels between distsql fetch
and executor consume (/root/reference/distsql/distsql.go:92-98); here the
bound is host-side super-batches sized for a TPU dispatch.
"""

import pytest

import tpch
from tidb_tpu import config, parallel
from tidb_tpu.executor import mesh as mesh_exec
from tidb_tpu.session import Session
from tidb_tpu.store.storage import new_mock_storage

STREAM_ROWS = 512          # tiny threshold so small test tables stream


@pytest.fixture(scope="module")
def sess():
    s = Session(new_mock_storage())
    s.execute("CREATE DATABASE tpch")
    s.execute("USE tpch")
    data = tpch.TpchData(seed=7)
    tpch.load(s, data)
    yield s
    s.close()


@pytest.fixture
def mesh():
    parallel.enable_mesh(8)
    yield parallel.active_mesh()
    parallel.disable_mesh()


@pytest.fixture
def small_stream():
    old = config.get_var("tidb_tpu_stream_rows")
    config.set_var("tidb_tpu_stream_rows", STREAM_ROWS)
    mesh_exec.reset_stream_stats()
    yield
    config.set_var("tidb_tpu_stream_rows", old)


def _host_rows(sess, sql):
    parallel.disable_mesh()
    try:
        return sess.query(sql).rows
    finally:
        parallel.enable_mesh(8)


def _check(got, want):
    assert want, "vacuous comparison: host result is empty"
    assert len(got) == len(want)
    for g, w in zip(got, want):
        for a, b in zip(g, w):
            if isinstance(a, float) or isinstance(b, float):
                assert float(a) == pytest.approx(float(b), rel=1e-9)
            else:
                assert a == b


@pytest.mark.parametrize("q", ["Q1", "Q3"])
def test_streamed_results_match_host(sess, mesh, small_stream, q):
    sql = getattr(tpch, q)
    got = sess.query(sql).rows
    stats = mesh_exec.stream_stats()
    assert stats["streams"] >= 1, "streaming path did not activate"
    assert stats["batches"] >= 2, "input did not split into batches"
    _check(got, _host_rows(sess, sql))


def test_buffering_is_bounded(sess, mesh, small_stream):
    sess.query(tpch.Q1)
    stats = mesh_exec.stream_stats()
    # one in-flight super-batch is the whole host footprint; a batch may
    # overshoot the threshold by at most one storage chunk
    max_chunk = 1024
    assert 0 < stats["max_batch_rows"] <= STREAM_ROWS + max_chunk


def test_double_buffer_overlap(sess, mesh, small_stream):
    sess.query(tpch.Q1)
    stats = mesh_exec.stream_stats()
    # every batch after the first must have been launched while the
    # previous batch was still in flight
    assert stats["overlapped_launches"] >= stats["batches"] - \
        stats["streams"] - stats["host_batches"]
    assert stats["overlapped_launches"] >= 1


def test_small_probe_keeps_memoized_path(sess, mesh):
    """Below the threshold nothing streams (the memoized whole-table path
    serves hot cached plans with zero re-transfer)."""
    mesh_exec.reset_stream_stats()
    sess.query(tpch.Q1)
    assert mesh_exec.stream_stats()["streams"] == 0
