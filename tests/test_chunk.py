"""Chunk/Column tests. Ref model: util/chunk/chunk_test.go."""

import decimal

import numpy as np
import pytest

from tidb_tpu import sqltypes as st
from tidb_tpu.chunk import Chunk, Column, dict_encode


def test_column_from_values_int():
    ft = st.new_int_field()
    c = Column.from_values(ft, [1, None, 3])
    assert len(c) == 3
    assert c.get(0) == 1
    assert c.get(1) is None
    assert c.get(2) == 3
    assert c.data.dtype == np.int64


def test_column_decimal_roundtrip():
    ft = st.new_decimal_field(frac=2)
    c = Column.from_values(ft, [decimal.Decimal("12.34"), None, 5])
    assert c.get(0) == decimal.Decimal("12.34")
    assert c.get(1) is None
    assert c.get(2) == decimal.Decimal("5")
    assert c.data[0] == 1234


def test_chunk_rows_filter_take():
    fts = [st.new_int_field(), st.new_double_field(), st.new_string_field()]
    rows = [(1, 1.5, "a"), (2, None, "b"), (3, 3.5, "c")]
    ch = Chunk.from_rows(fts, rows)
    assert ch.num_rows == 3
    assert ch.row(1) == (2, None, "b")
    f = ch.filter(np.array([True, False, True]))
    assert f.to_pylist() == [(1, 1.5, "a"), (3, 3.5, "c")]
    t = ch.take(np.array([2, 0]))
    assert t.row(0) == (3, 3.5, "c")


def test_chunk_concat_slice():
    fts = [st.new_int_field()]
    a = Chunk.from_rows(fts, [(1,), (2,)])
    b = Chunk.from_rows(fts, [(3,)])
    c = a.concat(b)
    assert c.to_pylist() == [(1,), (2,), (3,)]
    assert c.slice(1, 3).to_pylist() == [(2,), (3,)]


def test_dict_encode():
    ft = st.new_string_field()
    c = Column.from_values(ft, ["x", "y", None, "x"])
    codes, values = dict_encode(c)
    assert values == ["x", "y"]
    assert codes.tolist() == [0, 1, -1, 0]


def test_datetime_repr():
    us = st.parse_datetime("1998-09-02")
    assert st.format_datetime(us, st.TypeCode.DATE) == "1998-09-02"
    us2 = st.parse_datetime("2024-02-29 12:30:45")
    assert st.format_datetime(us2) == "2024-02-29 12:30:45"
