"""Pallas MXU segment-sum (ops/pallas_agg.py) — validated in interpret
mode on CPU; the identical kernel compiles for a real chip."""

import numpy as np
import pytest

import jax.numpy as jnp

pa = pytest.importorskip("tidb_tpu.ops.pallas_agg")
if not pa._HAS_PALLAS:
    pytest.skip("pallas unavailable in this jax build",
                allow_module_level=True)


def ref(values, ids, c):
    out = np.zeros((c, values.shape[1]), dtype=values.dtype)
    np.add.at(out, ids, values)
    return out


@pytest.mark.parametrize("n,k,c", [(8, 1, 4), (512, 3, 16),
                                   (1000, 2, 128), (4096, 4, 512),
                                   (777, 1, 33)])
def test_matches_reference(n, k, c):
    rng = np.random.default_rng(42)
    vals = rng.normal(size=(n, k)).astype(np.float32)
    ids = rng.integers(0, c, n).astype(np.int32)
    got = np.asarray(pa.segment_sum_pallas(
        jnp.asarray(vals), jnp.asarray(ids), c, interpret=True))
    want = ref(vals, ids, c)
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-4)


def test_empty_segments_are_zero():
    vals = np.ones((64, 2), dtype=np.float32)
    ids = np.zeros(64, dtype=np.int32)        # everything in slot 0
    got = np.asarray(pa.segment_sum_pallas(
        jnp.asarray(vals), jnp.asarray(ids), 8, interpret=True))
    assert got[0, 0] == 64.0
    assert np.all(got[1:] == 0.0)


def test_padding_rows_never_leak():
    # n not a tile multiple: padded rows land in the dead slot
    vals = np.full((5, 1), 7.0, dtype=np.float32)
    ids = np.array([0, 1, 0, 1, 2], dtype=np.int32)
    got = np.asarray(pa.segment_sum_pallas(
        jnp.asarray(vals), jnp.asarray(ids), 3, interpret=True))
    np.testing.assert_allclose(got[:, 0], [14.0, 14.0, 7.0])


def test_dispatcher_falls_back_off_tpu():
    # CPU backend: dispatcher must use the scatter path (exact int64)
    vals = jnp.asarray(np.array([[10], [20], [30]], dtype=np.int64))
    ids = jnp.asarray(np.array([0, 0, 1], dtype=np.int32))
    out = np.asarray(pa.segment_sum(vals, ids, 2))
    assert out.tolist() == [[30], [30]]
    assert not pa.available("cpu")


# -- fused predicate mask (_kernel_masked) ----------------------------------


@pytest.mark.parametrize("n,k,c", [(8, 1, 4), (512, 3, 16),
                                   (1000, 2, 128), (777, 1, 33)])
def test_masked_matches_where_reference(n, k, c):
    """Fused in-kernel mask == the unfused where(valid, v, 0) pre-pass,
    bit for bit (same contraction order either way)."""
    rng = np.random.default_rng(7)
    vals = rng.normal(size=(n, k)).astype(np.float32)
    ids = rng.integers(0, c, n).astype(np.int32)
    valid = rng.random(n) < 0.6
    got = np.asarray(pa.segment_sum_pallas(
        jnp.asarray(vals), jnp.asarray(ids), c, interpret=True,
        valid=jnp.asarray(valid)))
    want = ref(np.where(valid[:, None], vals, 0), ids, c)
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-4)


def test_masked_per_lane_mask():
    """[n, k] masks: each stacked lane carries its OWN validity (the
    _SegBatch stacking shape — live = filter_mask & arg_validity differs
    per aggregate)."""
    rng = np.random.default_rng(11)
    n, k, c = 600, 3, 32
    vals = rng.normal(size=(n, k)).astype(np.float32)
    ids = rng.integers(0, c, n).astype(np.int32)
    valid = rng.random((n, k)) < 0.5
    got = np.asarray(pa.segment_sum_pallas(
        jnp.asarray(vals), jnp.asarray(ids), c, interpret=True,
        valid=jnp.asarray(valid)))
    want = ref(np.where(valid, vals, 0), ids, c)
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-4)


def test_masked_kills_nan_under_dead_mask():
    """A NaN under a dead mask must not poison the sum — the kernel
    selects (jnp.where on the VMEM tile), it does not multiply."""
    vals = np.array([[1.0], [np.nan], [2.0]], dtype=np.float32)
    ids = np.array([0, 0, 0], dtype=np.int32)
    valid = np.array([True, False, True])
    got = np.asarray(pa.segment_sum_pallas(
        jnp.asarray(vals), jnp.asarray(ids), 2, interpret=True,
        valid=jnp.asarray(valid)))
    assert got[0, 0] == 3.0


def test_dispatcher_masked_scatter_path():
    """Off-TPU the dispatcher lowers the mask to where()+scatter — the
    exact unfused program."""
    vals = jnp.asarray(np.array([[10], [20], [30]], dtype=np.int64))
    ids = jnp.asarray(np.array([0, 0, 1], dtype=np.int32))
    valid = jnp.asarray(np.array([True, False, True]))
    out = np.asarray(pa.segment_sum(vals, ids, 2, valid=valid))
    assert out.tolist() == [[10], [30]]
