"""MySQL wire protocol server tests.

Ref model: server/conn_test.go + driving the stack through the real
protocol the way a MySQL client would (testkit goes through Session;
these go through the socket).
"""

import pytest

from tests.mysql_client import MiniClient, MySQLError
from tidb_tpu.server import Server
from tidb_tpu.store import new_mock_storage


@pytest.fixture
def srv():
    storage = new_mock_storage()
    storage.async_commit_secondaries = False
    server = Server(storage, port=0)
    server.start()
    yield server
    server.close()
    storage.close()


@pytest.fixture
def cli(srv):
    c = MiniClient("127.0.0.1", srv.port)
    c.query("CREATE DATABASE IF NOT EXISTS test")
    c.use("test")
    yield c
    c.close()


class TestProtocol:
    def test_handshake_ping(self, srv):
        c = MiniClient("127.0.0.1", srv.port)
        c.ping()
        c.close()

    def test_query_roundtrip(self, cli):
        assert cli.query(
            "CREATE TABLE t (id BIGINT PRIMARY KEY, v INT, s VARCHAR(10))"
        ) == 0
        assert cli.query(
            "INSERT INTO t VALUES (1, 10, 'a'), (2, 20, 'b'), (3, NULL, NULL)"
        ) == 3
        cols, rows = cli.query("SELECT * FROM t ORDER BY id")
        assert cols == ["id", "v", "s"]
        assert rows == [("1", "10", "a"), ("2", "20", "b"),
                        ("3", None, None)]

    def test_expressions_and_aggregates(self, cli):
        cli.query("CREATE TABLE a (x BIGINT PRIMARY KEY, y DOUBLE)")
        cli.query("INSERT INTO a VALUES (1, 1.5), (2, 2.5), (3, 4.0)")
        _cols, rows = cli.query(
            "SELECT COUNT(*), SUM(y), MIN(x) FROM a WHERE y > 1")
        assert rows == [("3", "8.0", "1")]

    def test_error_packet(self, cli):
        with pytest.raises(MySQLError):
            cli.query("SELECT * FROM missing_table")
        # connection still usable after an error
        assert cli.query("CREATE TABLE ok (a BIGINT PRIMARY KEY)") == 0

    def test_init_db_and_connect_with_db(self, srv):
        c1 = MiniClient("127.0.0.1", srv.port)
        c1.query("CREATE DATABASE IF NOT EXISTS d2")
        c1.close()
        c2 = MiniClient("127.0.0.1", srv.port, db="d2")
        c2.query("CREATE TABLE t (a BIGINT PRIMARY KEY)")
        c2.query("INSERT INTO t VALUES (9)")
        _cols, rows = c2.query("SELECT a FROM t")
        assert rows == [("9",)]
        c2.close()

    def test_unknown_db_errors(self, srv):
        c = MiniClient("127.0.0.1", srv.port)
        with pytest.raises(MySQLError):
            c.use("no_such_db")
        c.close()

    def test_chaos_surfaced_errors_carry_retryable_codes(self, cli):
        """Device-plane faults that exhaust the in-process recovery
        chain must reach the wire as RETRYABLE codes — the contract the
        chaos harness (docs/ROBUSTNESS.md) holds clients to. The armed
        DispatchTimeoutError flavor skips the retry/degrade chain, so
        exactly one statement fails with ER_DEVICE_FAULT (9009)."""
        from tidb_tpu import config, errcode, sched
        from tidb_tpu.util import failpoint
        cli.query("CREATE TABLE ft (a BIGINT PRIMARY KEY, v BIGINT)")
        cli.query("INSERT INTO ft VALUES " +
                  ",".join(f"({i},{i % 9})" for i in range(64)))
        old = config.get_var("tidb_tpu_device_min_rows")
        config.set_var("tidb_tpu_device_min_rows", 1)
        failpoint.enable(
            "device/dispatch",
            "1*raise(DispatchTimeoutError:device fault: injected)")
        try:
            with pytest.raises(MySQLError) as ei:
                cli.query("SELECT v, COUNT(*) FROM ft GROUP BY v")
        finally:
            failpoint.disable("device/dispatch")
            config.set_var("tidb_tpu_device_min_rows", old)
            sched.device_health().note_ok()
        assert ei.value.code == errcode.ER_DEVICE_FAULT == 9009
        assert errcode.is_retryable(ei.value.code)
        # the retryable contract means a verbatim replay succeeds
        _cols, rows = cli.query(
            "SELECT v, COUNT(*) FROM ft GROUP BY v ORDER BY v")
        assert len(rows) == 9


class TestConcurrency:
    def test_two_connections_txn_isolation(self, srv):
        c1 = MiniClient("127.0.0.1", srv.port)
        c1.query("CREATE DATABASE IF NOT EXISTS test")
        c1.use("test")
        c1.query("CREATE TABLE t (a BIGINT PRIMARY KEY, b INT)")
        c1.query("INSERT INTO t VALUES (1, 1)")
        c2 = MiniClient("127.0.0.1", srv.port)
        c2.use("test")
        # c1 opens a txn and writes; c2 must not see it until commit
        c1.query("BEGIN")
        c1.query("UPDATE t SET b = 99 WHERE a = 1")
        _c, rows = c2.query("SELECT b FROM t WHERE a = 1")
        assert rows == [("1",)]
        c1.query("COMMIT")
        _c, rows = c2.query("SELECT b FROM t WHERE a = 1")
        assert rows == [("99",)]
        c1.close()
        c2.close()

    def test_many_parallel_clients(self, srv):
        import threading
        boot = MiniClient("127.0.0.1", srv.port)
        boot.query("CREATE DATABASE IF NOT EXISTS test")
        boot.use("test")
        boot.query("CREATE TABLE p (a BIGINT PRIMARY KEY, b INT)")
        boot.close()
        errs = []

        def worker(i):
            try:
                c = MiniClient("127.0.0.1", srv.port, db="test")
                c.query(f"INSERT INTO p VALUES ({i}, {i * 10})")
                _cols, rows = c.query(f"SELECT b FROM p WHERE a = {i}")
                assert rows == [(str(i * 10),)]
                c.close()
            except Exception as e:  # noqa: BLE001
                errs.append(e)

        threads = [threading.Thread(target=worker, args=(i,))
                   for i in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert errs == []
        check = MiniClient("127.0.0.1", srv.port, db="test")
        _cols, rows = check.query("SELECT COUNT(*) FROM p")
        assert rows == [("8",)]
        check.close()
