"""MySQL wire protocol server tests.

Ref model: server/conn_test.go + driving the stack through the real
protocol the way a MySQL client would (testkit goes through Session;
these go through the socket).
"""

import pytest

from tests.mysql_client import MiniClient, MySQLError
from tidb_tpu.server import Server
from tidb_tpu.store import new_mock_storage


@pytest.fixture
def srv():
    storage = new_mock_storage()
    storage.async_commit_secondaries = False
    server = Server(storage, port=0)
    server.start()
    yield server
    server.close()
    storage.close()


@pytest.fixture
def cli(srv):
    c = MiniClient("127.0.0.1", srv.port)
    c.query("CREATE DATABASE IF NOT EXISTS test")
    c.use("test")
    yield c
    c.close()


class TestProtocol:
    def test_handshake_ping(self, srv):
        c = MiniClient("127.0.0.1", srv.port)
        c.ping()
        c.close()

    def test_query_roundtrip(self, cli):
        assert cli.query(
            "CREATE TABLE t (id BIGINT PRIMARY KEY, v INT, s VARCHAR(10))"
        ) == 0
        assert cli.query(
            "INSERT INTO t VALUES (1, 10, 'a'), (2, 20, 'b'), (3, NULL, NULL)"
        ) == 3
        cols, rows = cli.query("SELECT * FROM t ORDER BY id")
        assert cols == ["id", "v", "s"]
        assert rows == [("1", "10", "a"), ("2", "20", "b"),
                        ("3", None, None)]

    def test_expressions_and_aggregates(self, cli):
        cli.query("CREATE TABLE a (x BIGINT PRIMARY KEY, y DOUBLE)")
        cli.query("INSERT INTO a VALUES (1, 1.5), (2, 2.5), (3, 4.0)")
        _cols, rows = cli.query(
            "SELECT COUNT(*), SUM(y), MIN(x) FROM a WHERE y > 1")
        assert rows == [("3", "8.0", "1")]

    def test_error_packet(self, cli):
        with pytest.raises(MySQLError):
            cli.query("SELECT * FROM missing_table")
        # connection still usable after an error
        assert cli.query("CREATE TABLE ok (a BIGINT PRIMARY KEY)") == 0

    def test_init_db_and_connect_with_db(self, srv):
        c1 = MiniClient("127.0.0.1", srv.port)
        c1.query("CREATE DATABASE IF NOT EXISTS d2")
        c1.close()
        c2 = MiniClient("127.0.0.1", srv.port, db="d2")
        c2.query("CREATE TABLE t (a BIGINT PRIMARY KEY)")
        c2.query("INSERT INTO t VALUES (9)")
        _cols, rows = c2.query("SELECT a FROM t")
        assert rows == [("9",)]
        c2.close()

    def test_unknown_db_errors(self, srv):
        c = MiniClient("127.0.0.1", srv.port)
        with pytest.raises(MySQLError):
            c.use("no_such_db")
        c.close()


class TestConcurrency:
    def test_two_connections_txn_isolation(self, srv):
        c1 = MiniClient("127.0.0.1", srv.port)
        c1.query("CREATE DATABASE IF NOT EXISTS test")
        c1.use("test")
        c1.query("CREATE TABLE t (a BIGINT PRIMARY KEY, b INT)")
        c1.query("INSERT INTO t VALUES (1, 1)")
        c2 = MiniClient("127.0.0.1", srv.port)
        c2.use("test")
        # c1 opens a txn and writes; c2 must not see it until commit
        c1.query("BEGIN")
        c1.query("UPDATE t SET b = 99 WHERE a = 1")
        _c, rows = c2.query("SELECT b FROM t WHERE a = 1")
        assert rows == [("1",)]
        c1.query("COMMIT")
        _c, rows = c2.query("SELECT b FROM t WHERE a = 1")
        assert rows == [("99",)]
        c1.close()
        c2.close()

    def test_many_parallel_clients(self, srv):
        import threading
        boot = MiniClient("127.0.0.1", srv.port)
        boot.query("CREATE DATABASE IF NOT EXISTS test")
        boot.use("test")
        boot.query("CREATE TABLE p (a BIGINT PRIMARY KEY, b INT)")
        boot.close()
        errs = []

        def worker(i):
            try:
                c = MiniClient("127.0.0.1", srv.port, db="test")
                c.query(f"INSERT INTO p VALUES ({i}, {i * 10})")
                _cols, rows = c.query(f"SELECT b FROM p WHERE a = {i}")
                assert rows == [(str(i * 10),)]
                c.close()
            except Exception as e:  # noqa: BLE001
                errs.append(e)

        threads = [threading.Thread(target=worker, args=(i,))
                   for i in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert errs == []
        check = MiniClient("127.0.0.1", srv.port, db="test")
        _cols, rows = check.query("SELECT COUNT(*) FROM p")
        assert rows == [("8",)]
        check.close()
