"""Hierarchical memory tracking (memtrack.py): per-query host+HBM
accounting, tidb_tpu_mem_quota_query enforcement with the spill/cancel
OOM-action chain, cross-query isolation (no watermark bleed), and the
observability surfaces (EXPLAIN ANALYZE mem, SHOW PROCESSLIST,
information_schema.memory_usage, digest max_mem, metrics)."""

import re
import threading

import pytest

from tidb_tpu import memtrack, metrics
from tidb_tpu.session import Session, SQLError
from tidb_tpu.store.storage import new_mock_storage


# -- unit: the tracker tree -------------------------------------------------


class TestTracker:
    def test_rollup_peaks_and_ledgers(self):
        root = memtrack.MemTracker("root")
        sess = memtrack.statement_root(root, label="s")
        op = sess.node(object())
        op.consume(host=100, device=40)
        assert (op.host, op.device) == (100, 40)
        assert (sess.host, sess.device) == (100, 40)
        assert (root.host, root.device) == (100, 40)
        op.release(host=60)
        assert root.host == 40 and root.host_peak == 100
        assert root.device == 40 and root.device_peak == 40

    def test_detach_zeroes_the_parent(self):
        root = memtrack.MemTracker("root")
        sess = memtrack.statement_root(root, label="s")
        sess.node(object()).consume(host=512, device=64)
        sess.detach()
        assert root.total() == 0
        # peaks survive for post-mortem readers
        assert root.host_peak == 512 and sess.host_peak == 512

    def test_quota_fires_spill_then_cancel(self):
        root = memtrack.statement_root(None, label="q")
        root.quota = 1000
        shed = []

        def spill():
            shed.append(True)
            root.release(host=900)

        root.add_spill_action(spill)
        root.consume(host=950)
        root.consume(host=200)          # crosses: spill sheds 900
        assert shed and root.total() == 250
        root.remove_spill_action(spill)
        with pytest.raises(memtrack.QuotaExceededError,
                           match="Out Of Memory Quota"):
            root.consume(host=2000)

    def test_spill_action_is_rearmed(self):
        root = memtrack.statement_root(None, label="q")
        root.quota = 100
        fired = []
        root.add_spill_action(lambda: (fired.append(1),
                                       root.release(host=root.host)))
        root.consume(host=150)
        root.consume(host=150)
        assert len(fired) == 2

    def test_track_to_moves_absolute(self):
        root = memtrack.statement_root(None, label="t")
        plan = object()
        with memtrack.tracking(root):
            prev = memtrack.track_to(plan, 500)
            prev = memtrack.track_to(plan, 200, prev)
            assert root.total() == 200 and root.host_peak == 500
            memtrack.release(plan, host=prev)
        assert root.total() == 0

    def test_suspended_hides_the_tracker(self):
        root = memtrack.statement_root(None, label="t")
        with memtrack.tracking(root):
            with memtrack.suspended():
                memtrack.consume(object(), host=999)
        assert root.total() == 0


# -- session fixtures -------------------------------------------------------


@pytest.fixture(scope="module")
def store():
    st = new_mock_storage()
    s = Session(st)
    s.execute("CREATE DATABASE d; USE d")
    s.execute("CREATE TABLE t (id BIGINT PRIMARY KEY, a BIGINT, "
              "b BIGINT, v BIGINT)")
    vals = ",".join(f"({i},{i * 3 % 997},{i * 7 % 997},{i % 7})"
                    for i in range(3000))
    s.execute("INSERT INTO t VALUES " + vals)
    s.query("SELECT * FROM t ORDER BY a")          # warm compile/caches
    s.query("SELECT id, COUNT(*) FROM t GROUP BY id LIMIT 1")
    yield st
    s.close()


@pytest.fixture
def sess(store):
    s = Session(store, db="d")
    yield s
    s.execute("SET tidb_tpu_mem_quota_query = 0")
    s.close()


def _quota_count(action: str) -> float:
    return metrics.snapshot().get(
        'tidb_tpu_mem_quota_exceeded_total{action="%s"}' % action, 0)


# -- quota enforcement ------------------------------------------------------


class TestQuota:
    def test_sort_spills_instead_of_cancel(self, sess):
        """The plan contains a SpillSorter: crossing the quota sheds the
        buffered rows to disk, the query COMPLETES, and the tracker
        drops back (session root zero afterwards)."""
        before = _quota_count("spill")
        # 3000 rows x 4 bigint cols ~ 100KB buffered; keys ~27KB stay
        sess.execute("SET tidb_tpu_mem_quota_query = 60000")
        rows = sess.query("SELECT * FROM t ORDER BY a").rows
        assert len(rows) == 3000
        assert _quota_count("spill") > before
        assert sess.mem_tracker.total() == 0

    def test_hash_agg_over_quota_cancels(self, sess):
        before = _quota_count("cancel")
        sess.execute("SET tidb_tpu_mem_quota_query = 20000")
        with pytest.raises(SQLError, match="Out Of Memory Quota"):
            sess.query("SELECT id, COUNT(*) FROM t GROUP BY id")
        assert _quota_count("cancel") > before
        # session survives; the next (unquota'd) statement runs clean
        sess.execute("SET tidb_tpu_mem_quota_query = 0")
        assert sess.query("SELECT COUNT(*) FROM t").rows == [(3000,)]
        assert sess.mem_tracker.total() == 0

    def test_join_over_quota_cancels(self, sess):
        sess.execute("SET tidb_tpu_mem_quota_query = 20000")
        with pytest.raises(SQLError, match="Out Of Memory Quota"):
            sess.query("SELECT COUNT(*) FROM t x JOIN t y ON x.a = y.b")
        sess.execute("SET tidb_tpu_mem_quota_query = 0")
        assert sess.mem_tracker.total() == 0

    def test_worker_thread_cancel_surfaces_quota_error(self, store):
        """With a multi-region fan-out the quota usually trips inside a
        cop pool worker; the session thread's cooperative-kill check
        races the worker's exception — the client must still see the
        quota message (ER_MEM_EXCEED_QUOTA), never a generic
        'interrupted', and the root must come back to zero."""
        s = Session(store, db="d")
        try:
            s.query("SPLIT TABLE t REGIONS 8")
            s.execute("SET tidb_tpu_mem_quota_query = 20000")
            with pytest.raises(SQLError, match="Out Of Memory Quota"):
                s.query("SELECT id, COUNT(*) FROM t GROUP BY id")
            s.execute("SET tidb_tpu_mem_quota_query = 0")
            assert s.mem_tracker.total() == 0
        finally:
            s.close()

    def test_cancel_rolls_back_the_txn(self, sess):
        sess.execute("BEGIN")
        sess.execute("INSERT INTO t VALUES (99999, 1, 1, 1)")
        sess.execute("SET tidb_tpu_mem_quota_query = 20000")
        with pytest.raises(SQLError, match="Out Of Memory Quota"):
            sess.query("SELECT id, COUNT(*) FROM t GROUP BY id")
        assert sess.txn is None
        sess.execute("SET tidb_tpu_mem_quota_query = 0")
        assert sess.query(
            "SELECT COUNT(*) FROM t WHERE id = 99999").rows == [(0,)]

    def test_quota_error_classifies_as_mem_exceed(self):
        from tidb_tpu import errcode
        errno, state, _msg = errcode.classify(
            SQLError("Out Of Memory Quota! query tracked 9 bytes > "
                     "tidb_tpu_mem_quota_query 1"))
        assert errno == errcode.ER_MEM_EXCEED_QUOTA
        assert state == "HY000"


# -- release-on-close / leak check (util/testleak.py pattern) ---------------


class TestLeak:
    @pytest.mark.parametrize("sql", [
        "SELECT * FROM t ORDER BY a LIMIT 7",
        "SELECT v, SUM(a) FROM t GROUP BY v",
        "SELECT COUNT(*) FROM t x JOIN t y ON x.a = y.b",
        "EXPLAIN ANALYZE SELECT v, COUNT(*) FROM t GROUP BY v",
    ])
    def test_session_root_zero_after_each_statement(self, sess, sql):
        sess.query(sql)
        assert sess.mem_tracker.total() == 0, sql
        # and the statement root credited everything it ever held
        assert sess._last_mem.peak_total() > 0, sql


# -- isolation + surfaces ---------------------------------------------------


_UNITS = {"B": 1, "KB": 1 << 10, "MB": 1 << 20, "GB": 1 << 30}


def _parse_mem(cell: str) -> int:
    m = re.fullmatch(r"([0-9.]+)(B|KB|MB|GB)", cell)
    assert m, cell
    return int(float(m.group(1)) * _UNITS[m.group(2)])


class TestIsolation:
    def test_explain_analyze_mem_is_tracked_and_ungated(self, sess):
        """mem renders real tracked bytes with host collection alone —
        no tidb_tpu_runtime_stats_device needed any more."""
        rs = sess.query(
            "EXPLAIN ANALYZE SELECT v, SUM(a) FROM t GROUP BY v")
        mem_i = rs.columns.index("mem")
        cells = [r[mem_i] for r in rs.rows]
        assert all(c != "-" for c in cells), cells
        assert any(_parse_mem(c) > 0 for c in cells), cells

    def test_idle_session_mem_stays_near_zero(self, store):
        """The busy session's hash build must NOT inflate the idle
        session's mem column (the process-global watermark did exactly
        that). Sequential here; the threaded variant below races them."""
        busy = Session(store, db="d")
        idle = Session(store, db="d")
        try:
            busy.query("SELECT id, COUNT(*) FROM t GROUP BY id")
            assert busy._last_mem.host_peak > 100_000
            rs = idle.query(
                "EXPLAIN ANALYZE SELECT COUNT(*) FROM t WHERE id = 1")
            mem_i = rs.columns.index("mem")
            for r in rs.rows:
                assert _parse_mem(r[mem_i]) < 64 << 10, r
        finally:
            busy.close()
            idle.close()

    def test_concurrent_no_bleed(self, store):
        busy = Session(store, db="d")
        idle = Session(store, db="d")
        done = threading.Event()

        def run_busy():
            try:
                busy.query("SELECT id, COUNT(*) FROM t GROUP BY id")
            finally:
                done.set()

        t = threading.Thread(target=run_busy, name="memtrack-busy")
        t.start()
        try:
            rs = idle.query(
                "EXPLAIN ANALYZE SELECT COUNT(*) FROM t WHERE id = 1")
            mem_i = rs.columns.index("mem")
            for r in rs.rows:
                assert _parse_mem(r[mem_i]) < 64 << 10, r
        finally:
            done.wait(30)
            t.join(30)
            busy.close()
            idle.close()

    def test_memory_usage_memtable_attributes_sessions(self, store):
        busy = Session(store, db="d")
        probe = Session(store, db="d")
        try:
            busy.query("SELECT id, COUNT(*) FROM t GROUP BY id")
            rs = probe.query(
                "SELECT scope, session_id, peak_host_bytes, "
                "peak_device_bytes FROM information_schema.memory_usage")
            assert ("server", 0) in [(r[0], r[1]) for r in rs.rows]
            by_sid = {r[1]: r for r in rs.rows if r[0] == "session"}
            assert by_sid[busy.session_id][2] > 100_000
            # the probe session only ever ran tiny statements
            assert by_sid[probe.session_id][2] < \
                by_sid[busy.session_id][2]
        finally:
            busy.close()
            probe.close()

    def test_mesh_path_is_tracked(self, store):
        """The mesh-routed aggregation path must bill the trackers too —
        quota and the mem column cannot have a blind spot on the mesh."""
        from tidb_tpu import parallel
        s = Session(store, db="d")
        parallel.enable_mesh(8)
        try:
            rs = s.query(
                "EXPLAIN ANALYZE SELECT a, SUM(v) FROM t GROUP BY a")
            mesh_rows = [r for r in rs.rows if "MeshAgg" in r[0]]
            if mesh_rows:   # planner routed to the mesh
                mem_i = rs.columns.index("mem")
                assert _parse_mem(mesh_rows[0][mem_i]) > 0, mesh_rows
            assert s.mem_tracker.total() == 0
        finally:
            parallel.disable_mesh()
            s.close()

    def test_processlist_mem_column(self, sess):
        rs = sess.query("SHOW PROCESSLIST")
        mem_idx = rs.columns.index("Mem")
        me = [r for r in rs.rows if r[0] == sess.session_id]
        assert me and isinstance(me[0][mem_idx], int)

    def test_digest_summary_max_mem(self, sess):
        sess.query("SELECT v, SUM(b) FROM t GROUP BY v")
        rows = sess.query(
            "SELECT digest_text, max_mem_bytes FROM "
            "performance_schema.events_statements_summary_by_digest").rows
        mine = [r for r in rows if "SUM" in r[0].upper()
                and "summary" not in r[0]]
        assert mine and mine[0][1] > 0

    def test_query_mem_gauges_emitted(self, sess):
        sess.query("SELECT v, SUM(a) FROM t GROUP BY v")
        snap = metrics.snapshot()
        assert snap.get('tidb_tpu_query_mem_bytes{kind="host"}', 0) > 0
        assert 'tidb_tpu_device_peak_bytes' in snap

    def test_slow_log_mem_line(self, sess, caplog):
        import logging
        from tidb_tpu import config
        old = config.get_var("tidb_tpu_slow_query_ms")
        config.set_var("tidb_tpu_slow_query_ms", 0)
        try:
            with caplog.at_level(logging.WARNING,
                                 logger="tidb_tpu.slow_query"):
                sess.query("SELECT v, COUNT(*) FROM t GROUP BY v")
        finally:
            config.set_var("tidb_tpu_slow_query_ms", old)
        recs = [r.getMessage() for r in caplog.records
                if "slow query" in r.getMessage()]
        assert recs and "# Mem: " in recs[-1]
        assert "host=" in recs[-1] and "device=" in recs[-1]
