"""KILL [TIDB] [CONNECTION|QUERY] (ref: ast/misc.go:341 KillStmt;
server/server.go:333 Kill): cooperative query interruption through the
executor interrupt probe, connection kill through the server hook."""

import threading
import time

import numpy as np
import pytest

from tidb_tpu.session import Session, SQLError
from tidb_tpu.store.storage import new_mock_storage
from tidb_tpu.table import Table, bulkload


@pytest.fixture
def env():
    st = new_mock_storage()
    s1 = Session(st)
    s1.execute("CREATE DATABASE d")
    s1.execute("USE d")
    s1.execute("CREATE TABLE t (id BIGINT PRIMARY KEY, v BIGINT)")
    tb = Table(s1.domain.info_schema().table("d", "t"), st)
    bulkload.bulk_load(st, tb, {
        "id": np.arange(300000, dtype=np.int64),
        "v": np.arange(300000, dtype=np.int64) % 997})
    s1.query("SPLIT TABLE t REGIONS 64")
    yield st, s1
    s1.close()


class TestKillQuery:
    def test_interrupts_running_scan(self, env):
        st, s1 = env
        s2 = Session(st, db="d")
        s2.execute("SET @@tidb_tpu_device = 0")
        s2.execute("SET @@tidb_tpu_cop_concurrency = 1")
        errs = []

        def victim():
            try:
                s2.query("SELECT v, COUNT(*) FROM t GROUP BY v")
                errs.append("completed")
            except SQLError as e:
                errs.append(str(e))

        th = threading.Thread(target=victim)
        th.start()
        # synchronize on the statement actually running, then kill
        for _ in range(400):
            if s2.current_sql:
                break
            time.sleep(0.005)
        s1.execute(f"KILL QUERY {s2.session_id}")
        th.join(timeout=20)
        assert not th.is_alive()
        assert errs and "interrupted" in errs[0], errs
        # the kill flag clears: the session keeps working
        assert s2.query("SELECT COUNT(*) FROM t WHERE id < 5"
                        ).rows == [(5,)]
        s2.close()

    def test_unknown_thread(self, env):
        _st, s1 = env
        with pytest.raises(SQLError, match="Unknown thread"):
            s1.execute("KILL 999999")

    def test_kill_connection_invokes_hook(self, env):
        st, s1 = env
        s2 = Session(st, db="d")
        closed = []
        s2.kill_hook = lambda: closed.append(True)
        s1.execute(f"KILL {s2.session_id}")
        assert closed == [True]
        assert s2.killed
        s2.close()

    def test_idle_kill_is_noop_for_next_statement(self, env):
        st, s1 = env
        s2 = Session(st, db="d")
        s1.execute(f"KILL QUERY {s2.session_id}")   # s2 is idle
        assert s2.query("SELECT COUNT(*) FROM t WHERE id < 3"
                        ).rows == [(3,)]
        s2.close()

    def test_kill_other_user_needs_super(self):
        from tidb_tpu.bootstrap import bootstrap
        st = new_mock_storage()
        bootstrap(st)
        root = Session(st, user="root", host="%")
        root.execute("CREATE USER peon IDENTIFIED BY 'x'")
        peon = Session(st, user="peon", host="%")
        with pytest.raises(SQLError, match="denied"):
            peon.execute(f"KILL {root.session_id}")
        root.execute(f"KILL QUERY {peon.session_id}")   # SUPER ok
        peon.close()
        root.close()
