"""Server process plane: CLI entry, status API, metrics, slow-query log,
SHOW PROCESSLIST, SET GLOBAL persistence (ref: tidb-server/main.go,
server/http_status.go, util/logutil slow log)."""

import json
import logging
import subprocess
import sys

import pytest

from tidb_tpu import config, metrics
from tidb_tpu.util import statusclient
from tidb_tpu.server import Server
from tidb_tpu.server.status import StatusServer
from tidb_tpu.session import Session
from tidb_tpu.store.storage import new_mock_storage

from tests.mysql_client import MiniClient


def test_cli_starts_serves_and_stops():
    """Launch `python -m tidb_tpu` as a real process, connect with the
    wire client, run SQL, SIGTERM it."""
    proc = subprocess.Popen(
        [sys.executable, "-m", "tidb_tpu", "--port", "0", "--no-status",
         "--no-mesh", "--log-level", "info"],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        cwd="/root/repo", env={"PYTHONPATH": "/root/repo",
                               "PATH": "/usr/bin:/bin",
                               "JAX_PLATFORMS": "cpu",
                               "HOME": "/root"})
    port = None
    try:
        for _ in range(600):
            line = proc.stdout.readline()
            if "MySQL protocol on" in line:
                port = int(line.rsplit(":", 1)[1])
                break
        assert port, "server did not report its port"
        c = MiniClient("127.0.0.1", port, user="root")
        c.query("CREATE DATABASE d")
        c.query("CREATE TABLE d.t (id BIGINT PRIMARY KEY)")
        c.query("INSERT INTO d.t VALUES (1), (2)")
        assert c.query("SELECT COUNT(*) FROM d.t")[1] == [("2",)]
        c.close()
    finally:
        proc.terminate()
        assert proc.wait(timeout=20) == 0


def test_status_endpoint_and_metrics():
    st = new_mock_storage()
    srv = Server(st)
    srv.start()
    status = StatusServer(st, srv)
    status.start()
    try:
        c = MiniClient("127.0.0.1", srv.port, user="root")
        c.query("SELECT 1")
        body = statusclient.get_json("127.0.0.1", status.port,
                                     "/status")
        assert body["version"]
        assert body["regions"] >= 1
        # every member stamps its identity on /status and /metrics
        assert body["member"]["id"]
        text = statusclient.get_text("127.0.0.1", status.port,
                                     "/metrics")
        assert "tidb_tpu_queries_total" in text
        assert "tidb_tpu_query_duration_seconds_bucket" in text
        assert metrics.MEMBER_START_TIME in text
        c.close()
    finally:
        status.close()
        srv.close()


def test_slow_query_log(caplog):
    st = new_mock_storage()
    s = Session(st)
    s.execute("CREATE DATABASE d; USE d")
    s.execute("CREATE TABLE t (id BIGINT PRIMARY KEY)")
    old = config.get_var("tidb_tpu_slow_query_ms")
    config.set_var("tidb_tpu_slow_query_ms", 0)   # everything is slow
    try:
        with caplog.at_level(logging.WARNING, logger="tidb_tpu.slow_query"):
            s.query("SELECT COUNT(*) FROM t")
        assert any("slow query" in r.message for r in caplog.records)
    finally:
        config.set_var("tidb_tpu_slow_query_ms", old)
    assert metrics.snapshot().get("tidb_tpu_slow_queries_total", 0) >= 1


def test_show_processlist():
    st = new_mock_storage()
    s = Session(st, user="alice", host="somewhere")
    s.execute("CREATE DATABASE d; USE d")
    r = s.query("SHOW PROCESSLIST")
    assert r.columns[:3] == ["Id", "User", "Host"]
    me = [row for row in r.rows if row[0] == s.session_id]
    assert me and me[0][1] == "alice"
    assert me[0][7] and "PROCESSLIST" in me[0][7]   # own query visible


def test_set_global_persists_and_reloads():
    from tidb_tpu.bootstrap import bootstrap, load_global_variables
    st = new_mock_storage()
    bootstrap(st)
    s = Session(st)
    old = config.get_var("tidb_tpu_cop_concurrency")
    try:
        s.execute("SET GLOBAL tidb_tpu_cop_concurrency = 7")
        rows = Session(st, internal=True).query(
            "SELECT variable_value FROM mysql.global_variables WHERE "
            "variable_name = 'tidb_tpu_cop_concurrency'").rows
        assert rows == [("7",)]
        # simulate a fresh process: reset then reload from the table
        config.set_var("tidb_tpu_cop_concurrency", old)
        load_global_variables(st)
        assert config.get_var("tidb_tpu_cop_concurrency") == 7
    finally:
        config.set_var("tidb_tpu_cop_concurrency", old)
