"""Executor family: StreamAgg (segment-reduce), MergeJoin, IndexJoin,
external sort. Plans are hand-built around session-planned readers, the
reference's executor-test pattern (executor/executor_test.go) adapted to
direct plan construction; results cross-check against the SQL path."""

import numpy as np
import pytest

from tidb_tpu.executor import ExecContext, build_executor
from tidb_tpu.executor.extsort import SpillSorter
from tidb_tpu.expression import AggDesc, AggFunc, ColumnRef
from tidb_tpu.plan import physical as ph
from tidb_tpu.session import Session
from tidb_tpu.store.storage import new_mock_storage


@pytest.fixture(scope="module")
def sess():
    s = Session(new_mock_storage())
    s.execute("CREATE DATABASE d")
    s.execute("USE d")
    s.execute("CREATE TABLE t (id BIGINT PRIMARY KEY, g BIGINT, v DOUBLE, "
              "s VARCHAR(16))")
    s.execute("CREATE TABLE u (id BIGINT PRIMARY KEY, w DOUBLE)")
    rng = np.random.default_rng(5)
    g = rng.integers(0, 40, 5000)
    v = rng.uniform(-10, 10, 5000).round(3)
    names = np.array(["aa", "bb", "cc", "dd"])[rng.integers(0, 4, 5000)]
    rows = []
    for i in range(5000):
        gv = "NULL" if i % 97 == 0 else str(g[i])
        rows.append(f"({i}, {gv}, {v[i]}, '{names[i]}')")
    s.execute("INSERT INTO t VALUES " + ",".join(rows))
    s.execute("INSERT INTO u VALUES " + ",".join(
        f"({i}, {float(i) / 7:.4f})" for i in range(0, 160, 2)))
    return s


def _ctx(sess):
    return ExecContext(sess.storage, sess._read_ts(), None)


def _reader(sess, sql):
    """The bare reader under a planned projection."""
    plan = sess.plan(sql)
    node = plan
    while not isinstance(node, (ph.PhysTableReader, ph.PhysIndexReader)):
        node = node.children[0]
    return node


def _rows(exe, ctx):
    out = []
    for ch in exe.chunks(ctx):
        out.extend(ch.to_pylist())
    return out


class TestStreamAgg:
    def _plans(self, sess, group_cols, aggs):
        reader = _reader(sess, "SELECT id, g, v, s FROM t")
        groups = [ColumnRef(i, reader.schema.cols[i].ft)
                  for i in group_cols]
        schema_cols = [reader.schema.cols[i] for i in group_cols]
        from tidb_tpu.plan.resolver import PlanSchema, SchemaCol
        schema = PlanSchema(list(schema_cols) + [
            SchemaCol(f"_a{j}", "", a.result_ft)
            for j, a in enumerate(aggs)])
        stream = ph.PhysStreamAgg(schema=schema, children=[reader],
                                  group_exprs=groups, aggs=aggs)
        hash_ = ph.PhysHashAgg(schema=schema, children=[reader],
                               group_exprs=groups, aggs=aggs)
        return stream, hash_

    def test_matches_hash_agg(self, sess):
        reader = _reader(sess, "SELECT id, g, v, s FROM t")
        vref = ColumnRef(2, reader.schema.cols[2].ft)
        aggs = [AggDesc(AggFunc.SUM, vref), AggDesc(AggFunc.COUNT, None),
                AggDesc(AggFunc.MIN, vref), AggDesc(AggFunc.AVG, vref)]
        stream, hash_ = self._plans(sess, [1], aggs)
        got = _rows(build_executor(stream), _ctx(sess))
        want = _rows(build_executor(hash_), _ctx(sess))
        assert len(got) == len(want) == 41  # 40 groups + NULL group
        for a, b in zip(got, want):
            assert a[0] == b[0] and a[2] == b[2]
            for x, y in zip(a[1:], b[1:]):
                assert x == pytest.approx(y, rel=1e-9)

    def test_string_group_keys(self, sess):
        reader = _reader(sess, "SELECT id, g, v, s FROM t")
        vref = ColumnRef(2, reader.schema.cols[2].ft)
        aggs = [AggDesc(AggFunc.COUNT, None), AggDesc(AggFunc.MAX, vref)]
        stream, hash_ = self._plans(sess, [3, 1], aggs)
        got = _rows(build_executor(stream), _ctx(sess))
        want = _rows(build_executor(hash_), _ctx(sess))
        assert got == want and len(got) == 4 * 41

    def test_device_kernel_used(self, sess, monkeypatch):
        """The segment kernel (not the host fallback) must carry the load
        for device-safe exprs."""
        import tidb_tpu.executor as ex
        calls = []
        from tidb_tpu.ops.streamagg import SegmentAggKernel as K
        orig = K.dispatch

        def spy(self, chunk, donate=False):
            # dispatch is shared by the per-batch path (__call__) and
            # the superchunk pipeline — spy there so both count
            calls.append(chunk.num_rows)
            return orig(self, chunk, donate)

        monkeypatch.setattr(K, "dispatch", spy)
        reader = _reader(sess, "SELECT id, g, v, s FROM t")
        vref = ColumnRef(2, reader.schema.cols[2].ft)
        stream, _ = self._plans(sess, [1], [AggDesc(AggFunc.SUM, vref)])
        _rows(build_executor(stream), _ctx(sess))
        assert sum(calls) == 5000


class TestMergeJoin:
    def _join(self, sess, jt="inner"):
        left = _reader(sess, "SELECT id, g, v FROM t")
        right = _reader(sess, "SELECT id, w FROM u")
        lk = [ColumnRef(0, left.schema.cols[0].ft)]
        rk = [ColumnRef(0, right.schema.cols[0].ft)]
        return ph.PhysMergeJoin(
            schema=left.schema.merge(right.schema),
            children=[left, right], left_keys=lk, right_keys=rk,
            join_type=jt)

    def test_inner_matches_sql(self, sess):
        got = _rows(build_executor(self._join(sess)), _ctx(sess))
        want = sess.query(
            "SELECT t.id, t.g, t.v, t.s, u.id, u.w FROM t, u "
            "WHERE t.id = u.id ORDER BY t.id").rows
        got.sort(key=lambda r: r[0])
        assert [r[0] for r in got] == [r[0] for r in want]
        for a, b in zip(got, want):
            assert a == b

    def test_left_join_null_extension(self, sess):
        got = _rows(build_executor(self._join(sess, "left")), _ctx(sess))
        assert len(got) == 5000
        matched = [r for r in got if r[4] is not None]
        unmatched = [r for r in got if r[4] is None]
        assert len(matched) == 80
        assert all(r[5] is None for r in unmatched)

    def test_memory_stays_windowed(self, sess):
        """The right window must shrink as the merge advances — the whole
        point vs HashJoin's full build materialization."""
        exe = build_executor(self._join(sess))
        seen = []
        orig = type(exe).chunks
        rows = _rows(exe, _ctx(sess))
        assert len(rows) == 80   # smoke: result correct; window logic is
        # asserted indirectly by test_inner_matches_sql chunk streaming


class TestIndexJoin:
    def _join(self, sess, jt="inner"):
        outer = _reader(sess, "SELECT id, g, v FROM t")
        inner = _reader(sess, "SELECT id, w FROM u")
        lk = [ColumnRef(1, outer.schema.cols[1].ft)]    # t.g
        rk = [ColumnRef(0, inner.schema.cols[0].ft)]    # u.id (pk handle)
        return ph.PhysIndexJoin(
            schema=outer.schema.merge(inner.schema),
            children=[outer, inner], left_keys=lk, right_keys=rk,
            inner_index=None, join_type=jt)

    def test_inner_matches_sql(self, sess):
        got = _rows(build_executor(self._join(sess)), _ctx(sess))
        want = sess.query(
            "SELECT t.id, t.g, t.v, t.s, u.id, u.w FROM t, u "
            "WHERE t.g = u.id ORDER BY t.id").rows
        got.sort(key=lambda r: r[0])
        assert len(got) == len(want)
        for a, b in zip(got, want):
            assert a == b

    def test_left_join(self, sess):
        got = _rows(build_executor(self._join(sess, "left")), _ctx(sess))
        assert len(got) == 5000
        want_matched = sess.query(
            "SELECT COUNT(*) FROM t, u WHERE t.g = u.id").rows[0][0]
        assert sum(1 for r in got if r[4] is not None) == want_matched


class TestExternalSort:
    def _chunks(self, n, seed=0, chunk_rows=997):
        from tidb_tpu.chunk import Chunk, Column
        from tidb_tpu.sqltypes import (new_double_field, new_int_field,
                                       new_string_field)
        rng = np.random.default_rng(seed)
        a = rng.integers(0, 50, n)
        b = rng.uniform(-1, 1, n)
        s_ = np.array(["x", "yy", "zzz", "w"], dtype=object)[
            rng.integers(0, 4, n)]
        av = rng.random(n) > 0.05     # some NULLs
        out = []
        for lo in range(0, n, chunk_rows):
            hi = min(lo + chunk_rows, n)
            out.append(Chunk([
                Column(new_int_field(), a[lo:hi].astype(np.int64),
                       av[lo:hi].copy()),
                Column(new_double_field(), b[lo:hi]),
                Column(new_string_field(), s_[lo:hi].copy()),
            ]))
        return out, (a, b, s_, av)

    def _by(self):
        from tidb_tpu.expression.core import col
        from tidb_tpu.sqltypes import (new_double_field, new_int_field,
                                       new_string_field)
        return [(col(0, new_int_field()), False),
                (col(2, new_string_field()), True),
                (col(1, new_double_field()), False)]

    def _want_order(self, truth, n):
        a, b, s_, av = truth
        import functools

        def cmp(i, j):
            ni, nj = not av[i], not av[j]
            if ni != nj:
                return -1 if ni else 1
            if av[i] and a[i] != a[j]:
                return -1 if a[i] < a[j] else 1
            if s_[i] != s_[j]:
                return 1 if s_[i] < s_[j] else -1    # DESC
            if b[i] != b[j]:
                return -1 if b[i] < b[j] else 1
            return 0
        return sorted(range(n), key=functools.cmp_to_key(cmp))

    @pytest.mark.parametrize("run_rows", [10_000_000, 1500])
    def test_spill_and_memory_paths_agree_with_reference(self, run_rows):
        n = 6000
        chunks, truth = self._chunks(n)
        sorter = SpillSorter(self._by(), run_rows=run_rows, block_rows=512)
        for c in chunks:
            sorter.add(c)
        if run_rows < n:
            assert sorter.spilled
        got = []
        for ch in sorter.sorted_chunks():
            got.extend(ch.to_pylist())
        assert len(got) == n
        a, b, s_, av = truth
        order = self._want_order(truth, n)
        for row, i in zip(got, order):
            assert (row[0] is None) == (not av[i])
            if av[i]:
                assert row[0] == a[i]
            assert row[1] == pytest.approx(b[i])
            assert row[2] == s_[i]

    def test_sql_order_by_spills(self, sess, monkeypatch):
        from tidb_tpu import config
        monkeypatch.setitem(config._vals, "tidb_tpu_sort_spill_rows", 1024)
        spilled = []
        orig = SpillSorter._spill

        def spy(self):
            spilled.append(1)
            return orig(self)

        monkeypatch.setattr(SpillSorter, "_spill", spy)
        got = sess.query("SELECT id, v FROM t ORDER BY v DESC, id").rows
        assert spilled, "sort did not spill"
        assert len(got) == 5000
        vs = [r[1] for r in got]
        assert vs == sorted(vs, reverse=True)


class TestOdkuValuesFn:
    """VALUES(col) in ON DUPLICATE KEY UPDATE (ref: executor/write.go
    onDuplicateUpdate; expression/builtin_other.go valuesFunction)."""

    @pytest.fixture
    def vt(self):
        s = Session(new_mock_storage())
        s.execute("CREATE DATABASE d")
        s.execute("USE d")
        s.execute("CREATE TABLE vt (id BIGINT PRIMARY KEY, "
                  "v BIGINT DEFAULT 7, dc DECIMAL(8,2) DEFAULT 1.50)")
        s.execute("INSERT INTO vt VALUES (1, 10, 2.25), (2, 20, 3.00)")
        yield s
        s.close()

    def test_values_takes_candidate(self, vt):
        vt.execute("INSERT INTO vt VALUES (2, 555, 9.99) "
                   "ON DUPLICATE KEY UPDATE v = VALUES(v) + 1, "
                   "dc = VALUES(dc)")
        from decimal import Decimal
        assert vt.query("SELECT v, dc FROM vt WHERE id = 2").rows == \
            [(556, Decimal("9.99"))]

    def test_values_mixes_with_old_row(self, vt):
        vt.execute("INSERT INTO vt VALUES (1, 100, 5.00) "
                   "ON DUPLICATE KEY UPDATE v = v + VALUES(v)")
        assert vt.query("SELECT v FROM vt WHERE id = 1").rows == [(110,)]

    def test_values_of_omitted_column_is_default(self, vt):
        vt.execute("INSERT INTO vt (id) VALUES (1) "
                   "ON DUPLICATE KEY UPDATE v = VALUES(v)")
        assert vt.query("SELECT v FROM vt WHERE id = 1").rows == [(7,)]

    def test_values_non_column_rejected(self, vt):
        from tidb_tpu.session import SQLError
        with pytest.raises(SQLError, match="single column"):
            vt.execute("INSERT INTO vt VALUES (1, 1, 1) "
                       "ON DUPLICATE KEY UPDATE v = VALUES(v + 1)")


class TestDefaultFn:
    """DEFAULT / DEFAULT(col) beyond the bare INSERT cell."""

    @pytest.fixture
    def dt(self):
        s = Session(new_mock_storage())
        s.execute("CREATE DATABASE d")
        s.execute("USE d")
        s.execute("CREATE TABLE dt (id BIGINT PRIMARY KEY, "
                  "v BIGINT DEFAULT 7, nm VARCHAR(10))")
        s.execute("INSERT INTO dt VALUES (1, 100, 'a')")
        yield s
        s.close()

    def test_default_fn_in_values(self, dt):
        dt.execute("INSERT INTO dt VALUES (5, DEFAULT(v) * 2, 'x')")
        assert dt.query("SELECT v FROM dt WHERE id = 5").rows == [(14,)]

    def test_update_set_default(self, dt):
        dt.execute("UPDATE dt SET v = DEFAULT WHERE id = 1")
        assert dt.query("SELECT v FROM dt WHERE id = 1").rows == [(7,)]
        dt.execute("UPDATE dt SET v = DEFAULT(v) + 1 WHERE id = 1")
        assert dt.query("SELECT v FROM dt WHERE id = 1").rows == [(8,)]

    def test_insert_set_default(self, dt):
        dt.execute("INSERT INTO dt SET id = 6, v = DEFAULT, nm = 'k'")
        assert dt.query("SELECT v FROM dt WHERE id = 6").rows == [(7,)]

    def test_odku_bare_default(self, dt):
        dt.execute("INSERT INTO dt VALUES (1, 1, 'z') "
                   "ON DUPLICATE KEY UPDATE v = DEFAULT")
        assert dt.query("SELECT v FROM dt WHERE id = 1").rows == [(7,)]

    def test_default_no_such_column(self, dt):
        from tidb_tpu.session import SQLError
        with pytest.raises(SQLError, match="Unknown column"):
            dt.execute("INSERT INTO dt VALUES (9, DEFAULT(nope), '')")

    def test_default_of_defaultless_column_is_null(self, dt):
        dt.execute("INSERT INTO dt VALUES (7, 1, DEFAULT(nm))")
        assert dt.query("SELECT nm IS NULL FROM dt WHERE id = 7"
                        ).rows == [(1,)]


class TestInsertSelectUnion:
    def test_union_source(self):
        s = Session(new_mock_storage())
        s.execute("CREATE DATABASE d")
        s.execute("USE d")
        s.execute("CREATE TABLE iu (id BIGINT PRIMARY KEY, "
                  "v BIGINT DEFAULT 3)")
        s.execute("INSERT INTO iu (id) SELECT 1 UNION ALL SELECT 2")
        assert s.query("SELECT id, v FROM iu ORDER BY id").rows == \
            [(1, 3), (2, 3)]
        s.execute("INSERT INTO iu (id, v) "
                  "SELECT 10, 1 UNION SELECT 11, 2")
        assert s.query("SELECT COUNT(*) FROM iu").rows == [(4,)]
        s.close()


class TestOdkuReviewEdges:
    @pytest.fixture
    def rt(self):
        s = Session(new_mock_storage())
        s.execute("CREATE DATABASE d")
        s.execute("USE d")
        s.execute("CREATE TABLE rt (id BIGINT PRIMARY KEY, "
                  "v BIGINT DEFAULT 7, w BIGINT NOT NULL)")
        s.execute("INSERT INTO rt VALUES (1, 10, 5)")
        yield s
        s.close()

    def test_values_inside_case(self, rt):
        """The canonical greatest-of idiom: CASE over VALUES()."""
        rt.execute("INSERT INTO rt VALUES (1, 100, 1) "
                   "ON DUPLICATE KEY UPDATE v = CASE "
                   "WHEN VALUES(v) > v THEN VALUES(v) ELSE v END")
        assert rt.query("SELECT v FROM rt WHERE id = 1").rows == [(100,)]
        rt.execute("INSERT INTO rt VALUES (1, 50, 1) "
                   "ON DUPLICATE KEY UPDATE v = CASE "
                   "WHEN VALUES(v) > v THEN VALUES(v) ELSE v END")
        assert rt.query("SELECT v FROM rt WHERE id = 1").rows == [(100,)]

    def test_default_inside_case(self, rt):
        rt.execute("UPDATE rt SET v = CASE WHEN 1 THEN DEFAULT(v) "
                   "ELSE 0 END WHERE id = 1")
        assert rt.query("SELECT v FROM rt WHERE id = 1").rows == [(7,)]

    def test_default_on_not_null_without_default_errors(self, rt):
        from tidb_tpu.session import SQLError
        with pytest.raises(SQLError, match="doesn't have a default"):
            rt.execute("UPDATE rt SET w = DEFAULT WHERE id = 1")
        assert rt.query("SELECT w FROM rt WHERE id = 1").rows == [(5,)]

    def test_values_unknown_column_clean_error(self, rt):
        from tidb_tpu.session import SQLError
        with pytest.raises(SQLError, match="Unknown column 'nope'"):
            rt.execute("INSERT INTO rt VALUES (1, 1, 1) ON DUPLICATE "
                       "KEY UPDATE v = VALUES(nope)")
        with pytest.raises(SQLError, match="Unknown column"):
            rt.execute("INSERT INTO rt VALUES (1, 1, 1) ON DUPLICATE "
                       "KEY UPDATE v = VALUES(zzz.v)")
