"""Prepared statements: session API, SQL PREPARE/EXECUTE, binary protocol,
plan cache.

Ref model: session.go:777-855 prepared stmt lifecycle, server/conn_stmt.go
binary protocol, plan/cache.go + util/kvcache plan cache.
"""

import pytest

from tests.mysql_client import MiniClient, MySQLError
from tidb_tpu.server import Server
from tidb_tpu.session import Session, SQLError
from tidb_tpu.store import new_mock_storage


@pytest.fixture
def tk():
    storage = new_mock_storage()
    storage.async_commit_secondaries = False
    s = Session(storage)
    s.execute("CREATE DATABASE test; USE test")
    s.execute("CREATE TABLE t (a BIGINT PRIMARY KEY, b INT, s VARCHAR(20))")
    s.execute("INSERT INTO t VALUES (1, 10, 'x'), (2, 20, 'y'), "
              "(3, 30, 'z')")
    yield s
    s.close()
    storage.close()


class TestSessionAPI:
    def test_prepare_execute(self, tk):
        sid, nparams = tk.prepare("SELECT b FROM t WHERE a = ?")
        assert nparams == 1
        assert tk.execute_prepared(sid, [2]).rows == [(20,)]
        assert tk.execute_prepared(sid, [3]).rows == [(30,)]

    def test_param_count_mismatch(self, tk):
        sid, _ = tk.prepare("SELECT b FROM t WHERE a = ? AND b > ?")
        with pytest.raises(SQLError, match="parameters"):
            tk.execute_prepared(sid, [1])

    def test_prepared_dml(self, tk):
        sid, _ = tk.prepare("INSERT INTO t VALUES (?, ?, ?)")
        assert tk.execute_prepared(sid, [4, 40, "w"]) == 1
        assert tk.query("SELECT b FROM t WHERE a = 4").rows == [(40,)]

    def test_deallocate(self, tk):
        sid, _ = tk.prepare("SELECT 1")
        tk.deallocate_prepared(sid)
        with pytest.raises(SQLError, match="unknown prepared"):
            tk.execute_prepared(sid, [])


class TestSQLSyntax:
    def test_prepare_execute_using(self, tk):
        tk.execute("PREPARE ps FROM 'SELECT s FROM t WHERE a = ?'")
        tk.execute("SET @k = 2")
        assert tk.query("EXECUTE ps USING @k").rows == [("y",)]
        tk.execute("SET @k = 1")
        assert tk.query("EXECUTE ps USING @k").rows == [("x",)]
        tk.execute("DEALLOCATE PREPARE ps")
        with pytest.raises(SQLError):
            tk.execute("EXECUTE ps USING @k")


class TestPlanCache:
    def test_identical_select_hits_cache(self, tk):
        cache = tk.domain.plan_cache()
        cache.clear()
        sql = "SELECT b FROM t WHERE a = 2"
        r1 = tk.query(sql).rows
        m0 = cache.hits
        r2 = tk.query(sql).rows
        assert r1 == r2 == [(20,)]
        assert cache.hits == m0 + 1

    def test_cache_invalidated_by_ddl(self, tk):
        cache = tk.domain.plan_cache()
        sql = "SELECT b FROM t WHERE a = 2"
        assert tk.query(sql).rows == [(20,)]
        tk.execute("ALTER TABLE t ADD COLUMN c INT DEFAULT 5")
        # schema version moved: new key, fresh plan, correct result
        assert tk.query(sql).rows == [(20,)]
        assert tk.query("SELECT c FROM t WHERE a = 2").rows == [(5,)]

    def test_dml_visibility_not_broken_by_cache(self, tk):
        sql = "SELECT COUNT(*) FROM t"
        assert tk.query(sql).rows == [(3,)]
        tk.execute("INSERT INTO t VALUES (9, 90, 'q')")
        assert tk.query(sql).rows == [(4,)]


class TestBinaryProtocol:
    @pytest.fixture
    def srv(self):
        storage = new_mock_storage()
        storage.async_commit_secondaries = False
        server = Server(storage, port=0)
        server.start()
        boot = MiniClient("127.0.0.1", server.port)
        boot.query("CREATE DATABASE test")
        boot.use("test")
        boot.query("CREATE TABLE t (a BIGINT PRIMARY KEY, b DOUBLE, "
                   "s VARCHAR(20), d DATE)")
        boot.query("INSERT INTO t VALUES (1, 1.5, 'x', '2024-03-01'), "
                   "(2, 2.5, 'y', '2024-04-01'), (3, NULL, NULL, NULL)")
        boot.close()
        yield server
        server.close()
        storage.close()

    def test_stmt_roundtrip(self, srv):
        c = MiniClient("127.0.0.1", srv.port, db="test")
        sid, nparams = c.stmt_prepare("SELECT a, b, s, d FROM t "
                                      "WHERE a = ?")
        assert nparams == 1
        # prepare-time result metadata (standard drivers read it here)
        assert [n for n, _t in c.last_prepare_columns] == \
            ["a", "b", "s", "d"]
        cols, rows = c.stmt_execute(sid, [1])
        assert cols == ["a", "b", "s", "d"]
        assert rows == [(1, 1.5, "x", "2024-03-01")]
        cols, rows = c.stmt_execute(sid, [3])
        assert rows == [(3, None, None, None)]
        c.stmt_close(sid)
        c.close()

    def test_stmt_params_typed(self, srv):
        c = MiniClient("127.0.0.1", srv.port, db="test")
        sid, _ = c.stmt_prepare("SELECT a FROM t WHERE b > ? AND s = ?")
        _cols, rows = c.stmt_execute(sid, [2.0, "y"])
        assert rows == [(2,)]
        c.close()

    def test_stmt_dml(self, srv):
        c = MiniClient("127.0.0.1", srv.port, db="test")
        sid, _ = c.stmt_prepare("INSERT INTO t VALUES (?, ?, ?, ?)")
        assert c.stmt_execute(sid, [7, 7.5, "w", "2024-05-01"]) == 1
        _cols, rows = c.query("SELECT s FROM t WHERE a = 7")
        assert rows == [("w",)]
        c.close()
