"""Extension builtin families (expression/builtins_ext.py; ref:
expression/builtin.go:270 `funcs` table). Expected values follow MySQL
5.7 semantics, incl. the per-function NULL rules."""

import base64
import datetime as dt
from decimal import Decimal

import pytest

from tidb_tpu.session import Session, SQLError
from tidb_tpu.store.storage import new_mock_storage


@pytest.fixture(scope="module")
def sess():
    s = Session(new_mock_storage())
    s.execute("CREATE DATABASE bx")
    s.execute("USE bx")
    s.execute("CREATE TABLE t (id BIGINT PRIMARY KEY, x DOUBLE, "
              "s VARCHAR(60), d DATETIME, j JSON)")
    s.execute("INSERT INTO t VALUES "
              "(1, 2.0, 'hello', '2024-03-15 10:30:45', "
              "'{\"a\": {\"b\": [1, 2]}, \"c\": \"hi\"}'),"
              "(2, -9.5, 'a,b,c', '2024-12-31 23:59:59', '[5, 6]'),"
              "(3, 0.25, NULL, NULL, NULL)")
    yield s
    s.close()


def one(sess, expr, where="id=1"):
    return sess.query(f"SELECT {expr} FROM t WHERE {where}").rows[0][0]


class TestTimeConversions:
    @pytest.mark.parametrize("expr,want", [
        ("STR_TO_DATE('15,3,2024','%d,%m,%Y')", "2024-03-15 00:00:00"),
        ("STR_TO_DATE('2024-03-15 10:30:45','%Y-%m-%d %H:%i:%s')",
         "2024-03-15 10:30:45"),
        ("FROM_DAYS(739325)", "2024-03-15"),
        ("TO_DAYS('2024-03-15')", 739325),
        ("TO_SECONDS('1970-01-02 00:00:01')",
         719528 * 86400 + 86401),
        ("MAKEDATE(2024, 75)", "2024-03-15"),
        ("MAKEDATE(24, 75)", "2024-03-15"),       # 2-digit year
        ("PERIOD_ADD(202401, 13)", 202502),
        ("PERIOD_DIFF(202502, 202401)", 13),
        ("WEEKOFYEAR('2024-01-04')", 1),
        ("TIMESTAMP('2024-03-15')", "2024-03-15 00:00:00"),
        ("TIMESTAMP('2024-03-15', '10:30:45')", "2024-03-15 10:30:45"),
        ("CONVERT_TZ('2024-03-15 12:00:00','+00:00','+05:30')",
         "2024-03-15 17:30:00"),
        ("GET_FORMAT(DATE, 'ISO')", "%Y-%m-%d"),
        ("GET_FORMAT(DATETIME, 'JIS')", "%Y-%m-%d %H:%i:%s"),
    ])
    def test_values(self, sess, expr, want):
        assert one(sess, expr) == want

    def test_str_to_date_unparseable_is_null(self, sess):
        assert one(sess, "STR_TO_DATE('bogus','%Y-%m-%d')") is None

    def test_makedate_day_zero_is_null(self, sess):
        assert one(sess, "MAKEDATE(2024, 0)") is None

    def test_convert_tz_named_zone_is_null(self, sess):
        # parity: MySQL without tz tables loaded returns NULL
        assert one(sess, "CONVERT_TZ(d,'US/Pacific','+00:00')") is None

    def test_null_propagation(self, sess):
        assert one(sess, "STR_TO_DATE(s,'%Y')", "id=3") is None
        assert one(sess, "PERIOD_ADD(NULL, 1)") is None


class TestDurations:
    @pytest.mark.parametrize("expr,want", [
        ("SEC_TO_TIME(3661)", "01:01:01"),
        ("SEC_TO_TIME(-90)", "-00:01:30"),
        ("TIME_TO_SEC('01:01:01')", 3661),
        ("MAKETIME(12, 30, 15)", "12:30:15.000000"),
        ("TIME('2024-03-15 10:30:45')", "10:30:45.000000"),
        ("TIMEDIFF('2024-03-15 12:00:00','2024-03-15 10:30:00')",
         "01:30:00.000000"),
        ("TIMEDIFF('10:00:00','08:15:00')", "01:45:00.000000"),
        ("ADDTIME('2024-03-15 10:30:45','01:00:15')",
         "2024-03-15 11:31:00"),
        ("SUBTIME('2024-03-15 10:30:45','10:30:45')",
         "2024-03-15 00:00:00"),
        ("ADDTIME('10:00:00', '02:30:00')", "12:30:00"),
        ("TIME_FORMAT('25:30:45', '%H|%i|%s')", "25|30|45"),
    ])
    def test_values(self, sess, expr, want):
        assert one(sess, expr) == want

    def test_maketime_bad_minute_is_null(self, sess):
        assert one(sess, "MAKETIME(1, 61, 0)") is None

    def test_decimal_seconds_unscale(self, sess):
        # scaled-int DECIMAL lane must be unscaled, not read raw
        assert one(sess, "MAKETIME(0, 0, 10.5)") == "00:00:10.500000"
        assert one(sess, "SEC_TO_TIME(90.5)") == "00:01:30.5"
        assert one(sess, "TIME_TO_SEC('bogus')") is None
        assert one(sess, "TIMESTAMP('2024-03-15','bogus')") is None

    def test_time_day_prefix_form(self, sess):
        assert one(sess, "TIME('1 10:00:00')") == "34:00:00.000000"

    def test_get_format_timestamp_synonym(self, sess):
        assert one(sess, "GET_FORMAT(TIMESTAMP, 'ISO')") == \
            "%Y-%m-%d %H:%i:%s"

    def test_timediff_mixed_types_is_null(self, sess):
        # MySQL: datetime vs bare time -> NULL
        assert one(sess,
                   "TIMEDIFF('2024-03-15 12:00:00','10:00:00')") is None

    def test_sec_to_time_clamps(self, sess):
        assert one(sess, "SEC_TO_TIME(4000000)") == "838:59:59"

    def test_current_moment_functions_run(self, sess):
        year = dt.datetime.now().year
        assert str(year) in one(sess, "CURDATE()")
        assert str(year) in one(sess, "SYSDATE()")
        assert str(year) in one(sess, "LOCALTIME()")
        assert one(sess, "CURTIME()").count(":") == 2
        assert one(sess, "UTC_DATE()").count("-") == 2


class TestStrings:
    @pytest.mark.parametrize("expr,want", [
        ("FORMAT(1234567.8912, 2)", "1,234,567.89"),
        ("FORMAT(1234.5, 0)", "1,234"),
        ("TO_BASE64('abc')", base64.b64encode(b"abc").decode()),
        ("FROM_BASE64(TO_BASE64('hello'))", "hello"),
        ("INSERT('Quadratic', 3, 4, 'What')", "QuWhattic"),
        ("INSERT('Quadratic', -1, 4, 'What')", "Quadratic"),
        ("INSERT('Quadratic', 3, 100, 'What')", "QuWhat"),
        ("EXPORT_SET(5, 'Y', 'N', ',', 4)", "Y,N,Y,N"),
        ("EXPORT_SET(6, '1', '0', '', 10)", "0110000000"),
        ("MAKE_SET(5, 'a', 'b', 'c')", "a,c"),
        ("ORD('a')", 97),
        ("ORD('€')", 14844588),       # utf8 bytes E2 82 AC as base-256
        ("CHAR(77, 121, 83, 81, 76)", "MySQL"),
        ("CHAR(256)", "\x01\x00"),
    ])
    def test_values(self, sess, expr, want):
        assert one(sess, expr) == want

    def test_from_base64_invalid_is_null(self, sess):
        assert one(sess, "FROM_BASE64('!not-base64!')") is None

    def test_load_file_is_null(self, sess):
        assert one(sess, "LOAD_FILE('/etc/passwd')") is None

    def test_make_set_skips_null_strings(self, sess):
        assert one(sess, "MAKE_SET(3, 'a', NULL, 'c')") == "a"

    def test_char_skips_nulls(self, sess):
        assert one(sess, "CHAR(77, NULL, 121)") == "My"


class TestInfoAndMisc:
    @pytest.mark.parametrize("expr,want", [
        ("CHARSET('x')", "utf8mb4"),
        ("COLLATION('x')", "utf8mb4_bin"),
        ("COERCIBILITY('x')", 4),
        ("INET_ATON('10.0.5.9')", 167773449),
        ("INET_ATON('127.1')", 127 * (1 << 24) + 1),   # short form
        ("INET_NTOA(167773449)", "10.0.5.9"),
        ("IS_IPV4('10.0.0.1')", 1),
        ("IS_IPV4('::1')", 0),
        ("IS_IPV6('::1')", 1),
        ("IS_IPV6('10.0.0.1')", 0),
        ("IS_IPV4_MAPPED(INET6_ATON('::ffff:10.0.0.1'))", 1),
        ("IS_IPV4_COMPAT(INET6_ATON('::10.0.0.1'))", 1),
        ("IS_IPV4_COMPAT(INET6_ATON('::ffff:10.0.0.1'))", 0),
        ("INET6_NTOA(INET6_ATON('fdfe::5a55:caff:fefa:9089'))",
         "fdfe::5a55:caff:fefa:9089"),
        ("BIT_COUNT(29)", 4),
        ("BIT_COUNT(-1)", 64),        # two's complement
        ("INTERVAL(23, 1, 15, 17, 30, 44, 200)", 3),
        ("INTERVAL(10, 1, 10, 100)", 2),
        ("GET_LOCK('l', 10)", 1),
        ("RELEASE_LOCK('l')", 1),
        ("IS_FREE_LOCK('l')", 1),
        ("RELEASE_ALL_LOCKS()", 0),
        ("SLEEP(0)", 0),
        ("BENCHMARK(10, 1+1)", 0),
        ("NAME_CONST('k', 42)", 42),
        ("ANY_VALUE(5)", 5),
    ])
    def test_values(self, sess, expr, want):
        assert one(sess, expr) == want

    def test_inet_invalid_is_null(self, sess):
        assert one(sess, "INET_ATON('1.2.3.256')") is None
        assert one(sess, "INET_NTOA(-1)") is None
        assert one(sess, "INET6_ATON('bogus')") is None

    def test_interval_null_is_minus_one(self, sess):
        assert one(sess, "INTERVAL(NULL, 1, 2)") == -1

    def test_interval_decimal_args_unscale(self, sess):
        assert one(sess, "INTERVAL(1.5, 1, 2)") == 1

    def test_interval_nested_in_call(self, sess):
        assert one(sess, "IFNULL(INTERVAL(23, 1, 15), -1)") == 2

    def test_is_used_lock_null(self, sess):
        assert one(sess, "IS_USED_LOCK('l')") is None

    def test_uuid_shape(self, sess):
        u = one(sess, "UUID()")
        assert len(u) == 36 and u.count("-") == 4

    def test_uuid_short_monotonic(self, sess):
        a = one(sess, "UUID_SHORT()")
        b = one(sess, "UUID_SHORT()")
        assert b > a

    def test_tidb_version_string(self, sess):
        assert "tidb_tpu" in one(sess, "TIDB_VERSION()")


class TestCompressionCrypto:
    def test_aes128_fallback_matches_fips197(self):
        """The pure-python AES fallback (util/aes128.py, used when the
        `cryptography` package is absent) is the FIPS-197 cipher: the
        appendix C.1 vector must round-trip exactly."""
        from tidb_tpu.util.aes128 import decrypt_block, encrypt_block
        key = bytes(range(16))
        pt = bytes.fromhex("00112233445566778899aabbccddeeff")
        ct = encrypt_block(key, pt)
        assert ct.hex() == "69c4e0d86a7b0430d8cdb78070b4c55a"
        assert decrypt_block(key, ct) == pt

    def test_compress_round_trip(self, sess):
        assert one(sess, "UNCOMPRESS(COMPRESS('hello world'))") == \
            "hello world"

    def test_uncompressed_length(self, sess):
        assert one(sess, "UNCOMPRESSED_LENGTH(COMPRESS(s))") == 5

    def test_uncompress_garbage_is_null(self, sess):
        assert one(sess, "UNCOMPRESS('garbage-bytes')") is None

    def test_password_hash(self, sess):
        # PASSWORD('mypass') is the documented double-sha1 format
        assert one(sess, "PASSWORD('mypass')") == \
            "*6C8989366EAF75BB670AD8EA7A7FC1176A95CEF4"
        assert one(sess, "PASSWORD('')") == ""

    def test_random_bytes_length(self, sess):
        assert len(one(sess, "RANDOM_BYTES(16)")) == 16

    def test_random_bytes_range_error(self, sess):
        with pytest.raises(SQLError):
            one(sess, "RANDOM_BYTES(0)")

    def test_aes_round_trip(self, sess):
        assert one(sess,
                   "AES_DECRYPT(AES_ENCRYPT('secret','key'),'key')") == \
            "secret"

    def test_aes_is_one_block_and_deterministic(self, sess):
        # 'text' pads to one AES block; ECB is deterministic
        a = one(sess, "HEX(AES_ENCRYPT('text','key'))")
        b = one(sess, "HEX(AES_ENCRYPT('text','key'))")
        assert a == b and len(a) == 32

    def test_aes_decrypt_garbage_is_null(self, sess):
        assert one(sess, "AES_DECRYPT('oddlength','key')") is None


class TestJSONModify:
    @pytest.mark.parametrize("expr,want", [
        ('JSON_QUOTE(\'a"b\')', '"a\\"b"'),
        ("JSON_SET('{\"a\":1}', '$.a', 2)", '{"a":2}'),
        ("JSON_SET('{\"a\":1}', '$.b', 9)", '{"a":1,"b":9}'),
        ("JSON_INSERT('{\"a\":1}', '$.a', 2)", '{"a":1}'),
        ("JSON_INSERT('{\"a\":1}', '$.b', 2)", '{"a":1,"b":2}'),
        ("JSON_REPLACE('{\"a\":1}', '$.a', 2)", '{"a":2}'),
        ("JSON_REPLACE('{\"a\":1}', '$.b', 2)", '{"a":1}'),
        ("JSON_REMOVE('{\"a\":1,\"b\":2}', '$.b')", '{"a":1}'),
        ("JSON_REMOVE('[1,2,3]', '$[0]')", "[2,3]"),
        ("JSON_MERGE('[1,2]', '[3]')", "[1,2,3]"),
        ("JSON_MERGE('{\"a\":1}', '{\"b\":2}')", '{"a":1,"b":2}'),
        ("JSON_MERGE('1', '2')", "[1,2]"),
        ("JSON_ARRAY_APPEND('[1,2]', '$', 3)", "[1,2,3]"),
        ("JSON_ARRAY_APPEND('{\"a\":[1]}', '$.a', 2)", '{"a":[1,2]}'),
        ("JSON_CONTAINS_PATH('{\"a\":{\"b\":1}}', 'one', '$.a.b')", 1),
        ("JSON_CONTAINS_PATH('{\"a\":1}', 'all', '$.a', '$.b')", 0),
        ("JSON_CONTAINS_PATH('{\"a\":1}', 'one', '$.a', '$.b')", 1),
        ("JSON_DEPTH('3')", 1),
        ("JSON_DEPTH('[1,[2,3]]')", 3),
        ("JSON_SEARCH('[\"abc\",\"ghi\"]', 'one', 'abc')", '"$[0]"'),
        ("JSON_SEARCH('{\"a\":\"xx\",\"b\":\"xx\"}', 'all', 'xx')",
         '["$.a","$.b"]'),
        ("JSON_SEARCH('[\"ab\"]', 'one', 'a%')", '"$[0]"'),
    ])
    def test_values(self, sess, expr, want):
        assert one(sess, expr) == want

    def test_on_column(self, sess):
        assert one(sess, "JSON_SET(j, '$.c', 'yo')") == \
            '{"a":{"b":[1,2]},"c":"yo"}'

    def test_search_no_hit_is_null(self, sess):
        assert one(sess, "JSON_SEARCH(j, 'one', 'nope')") is None

    def test_bad_one_or_all_errors(self, sess):
        with pytest.raises(SQLError):
            one(sess, "JSON_CONTAINS_PATH(j, 'some', '$.a')")

    def test_bad_path_errors(self, sess):
        with pytest.raises(SQLError):
            one(sess, "JSON_SET(j, 'nopath', 1)")

    def test_null_doc_propagates(self, sess):
        assert one(sess, "JSON_SET(j, '$.a', 1)", "id=3") is None
