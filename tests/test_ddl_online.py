"""Online DDL (F1 state machine) tests.

Ref model: ddl/ddl_db_change_test.go, index_change_test.go,
column_change_test.go — callback hooks observe every intermediate state
and run concurrent DML against it; ddl/reorg tests for checkpointed
backfill resume; 2pc schema-lease validation.
"""

import pytest

from tidb_tpu import codec, kv, tablecodec
from tidb_tpu.ddl import DDL
from tidb_tpu.ddl.job import JobState, JobType
from tidb_tpu.ddl.worker import BACKFILL_BATCH, DDLWorker
from tidb_tpu.meta import Meta
from tidb_tpu.parser import parse
from tidb_tpu.schema.model import SchemaState
from tidb_tpu.session import Session, SQLError
from tidb_tpu.store import new_mock_storage


@pytest.fixture
def env():
    storage = new_mock_storage()
    storage.async_commit_secondaries = False
    s = Session(storage)
    s.execute("CREATE DATABASE test; USE test")
    yield storage, s
    s.close()
    storage.close()


def _index_entry_count(storage, table_id: int, index_id: int) -> int:
    txn = storage.begin()
    try:
        prefix = tablecodec.index_prefix(table_id, index_id)
        return sum(1 for _ in txn.iter_range(prefix,
                                             codec.prefix_next(prefix)))
    finally:
        txn.rollback()


def _ddl_with_hook(storage, hook):
    return DDL(storage, worker=DDLWorker(storage, on_state_change=hook))


def _run_ddl(storage, sql: str, db: str, hook=None):
    stmt = parse(sql)[0]
    _ddl_with_hook(storage, hook).execute(stmt, db)


class TestStateWalk:
    def test_add_index_states(self, env):
        storage, s = env
        s.execute("CREATE TABLE t (a BIGINT PRIMARY KEY, b INT)")
        s.execute("INSERT INTO t VALUES (1, 10), (2, 20)")
        states = []

        def hook(job):
            if job.tp == JobType.ADD_INDEX:
                states.append(SchemaState(job.schema_state))

        _run_ddl(storage, "CREATE INDEX ib ON t (b)", "test", hook)
        assert states == [SchemaState.DELETE_ONLY, SchemaState.WRITE_ONLY,
                          SchemaState.WRITE_REORG, SchemaState.PUBLIC]
        info = s.domain.info_schema().table("test", "t")
        idx = info.index_by_name("ib")
        assert idx.state == SchemaState.PUBLIC
        assert _index_entry_count(storage, info.id, idx.id) == 2

    def test_drop_table_states(self, env):
        storage, s = env
        s.execute("CREATE TABLE t (a BIGINT PRIMARY KEY)")
        s.execute("INSERT INTO t VALUES (1)")
        states = []

        def hook(job):
            if job.tp == JobType.DROP_TABLE:
                states.append(SchemaState(job.schema_state))

        _run_ddl(storage, "DROP TABLE t", "test", hook)
        assert states == [SchemaState.WRITE_ONLY, SchemaState.DELETE_ONLY,
                          SchemaState.DELETE_ONLY]
        # data deletion deferred to the delete-range queue (GC consumes it)
        txn = storage.begin()
        try:
            assert len(Meta(txn).pending_delete_ranges()) == 1
        finally:
            txn.rollback()
        with pytest.raises(SQLError):
            s.query("SELECT * FROM t")

    def test_add_and_drop_column_states(self, env):
        storage, s = env
        s.execute("CREATE TABLE t (a BIGINT PRIMARY KEY)")
        s.execute("INSERT INTO t VALUES (1), (2)")
        states = []

        def hook(job):
            states.append((job.tp, SchemaState(job.schema_state)))

        _run_ddl(storage, "ALTER TABLE t ADD COLUMN c INT DEFAULT 7",
                 "test", hook)
        assert [st for tp, st in states if tp == JobType.ADD_COLUMN] == [
            SchemaState.DELETE_ONLY, SchemaState.WRITE_ONLY,
            SchemaState.WRITE_REORG, SchemaState.PUBLIC]
        # existing rows see the default without a rewrite
        assert s.query("SELECT c FROM t ORDER BY a").rows == [(7,), (7,)]
        states.clear()
        _run_ddl(storage, "ALTER TABLE t DROP COLUMN c", "test", hook)
        assert [st for tp, st in states if tp == JobType.DROP_COLUMN] == [
            SchemaState.WRITE_ONLY, SchemaState.DELETE_ONLY,
            SchemaState.DELETE_REORG, SchemaState.DELETE_REORG]
        assert s.query("SELECT * FROM t ORDER BY a").rows == [(1,), (2,)]


class TestConcurrentDML:
    def test_insert_during_write_only_is_indexed(self, env):
        """A row inserted while the new index is WRITE_ONLY must end up in
        the index (the F1 invariant the state machine exists for)."""
        storage, s = env
        s.execute("CREATE TABLE t (a BIGINT PRIMARY KEY, b INT)")
        s.execute("INSERT INTO t VALUES (1, 10)")
        other = Session(storage, db="test")

        def hook(job):
            if job.tp == JobType.ADD_INDEX and \
                    job.schema_state == int(SchemaState.WRITE_ONLY):
                other.execute("INSERT INTO t VALUES (2, 20)")

        _run_ddl(storage, "CREATE INDEX ib ON t (b)", "test", hook)
        other.close()
        info = s.domain.info_schema().table("test", "t")
        idx = info.index_by_name("ib")
        assert _index_entry_count(storage, info.id, idx.id) == 2
        assert s.query("SELECT a FROM t WHERE b = 20").rows == [(2,)]

    def test_delete_during_delete_only_removes_entry(self, env):
        """DELETE while the index is DELETE_ONLY must remove nothing extra
        and leave no stale entry once PUBLIC."""
        storage, s = env
        s.execute("CREATE TABLE t (a BIGINT PRIMARY KEY, b INT)")
        s.execute("INSERT INTO t VALUES (1, 10), (2, 20), (3, 30)")
        other = Session(storage, db="test")

        def hook(job):
            if job.tp == JobType.ADD_INDEX and \
                    job.schema_state == int(SchemaState.DELETE_ONLY):
                other.execute("DELETE FROM t WHERE a = 2")

        _run_ddl(storage, "CREATE INDEX ib ON t (b)", "test", hook)
        other.close()
        info = s.domain.info_schema().table("test", "t")
        idx = info.index_by_name("ib")
        assert _index_entry_count(storage, info.id, idx.id) == 2
        assert s.query("SELECT a FROM t WHERE b = 20").rows == []


class TestConcurrentReorg:
    def test_update_during_reorg_is_not_resurrected(self, env):
        """A row updated between the reorg snapshot and its backfill batch
        must NOT get a phantom entry for its old value: backfill reads
        current row values, and the updating txn maintained the index."""
        storage, s = env
        n = BACKFILL_BATCH + 50
        target = BACKFILL_BATCH + 10          # lands in the second batch
        s.execute("CREATE TABLE t (a BIGINT PRIMARY KEY, b INT)")
        s.execute("INSERT INTO t VALUES " +
                   ",".join(f"({i}, {i})" for i in range(n)))
        other = Session(storage, db="test")
        fired = []

        def on_batch(jb, cnt):
            if not fired:
                fired.append(True)
                other.execute(f"UPDATE t SET b = 999999 WHERE a = {target}")

        w = DDLWorker(storage, on_backfill_batch=on_batch)
        DDL(storage, worker=w).execute(
            parse("CREATE INDEX ib ON t (b)")[0], "test")
        other.close()
        info = s.domain.info_schema().table("test", "t")
        idx = info.index_by_name("ib")
        assert _index_entry_count(storage, info.id, idx.id) == n
        assert s.query(f"SELECT a FROM t WHERE b = {target}").rows == []
        assert s.query("SELECT a FROM t WHERE b = 999999").rows == \
            [(target,)]


class TestBackfill:
    def test_batched_backfill_with_checkpoints(self, env):
        storage, s = env
        n = BACKFILL_BATCH * 2 + 37
        s.execute("CREATE TABLE t (a BIGINT PRIMARY KEY, b INT)")
        s.execute("INSERT INTO t VALUES " +
                   ",".join(f"({i}, {i % 97})" for i in range(n)))
        batches = []
        w = DDLWorker(storage,
                      on_backfill_batch=lambda jb, cnt:
                      batches.append((jb.reorg_handle, cnt)))
        DDL(storage, worker=w).execute(
            parse("CREATE INDEX ib ON t (b)")[0], "test")
        assert len(batches) == 3
        assert [c for _h, c in batches] == [BACKFILL_BATCH, BACKFILL_BATCH,
                                            37]
        # checkpoints advance monotonically
        handles = [h for h, _c in batches]
        assert handles == sorted(handles)
        info = s.domain.info_schema().table("test", "t")
        idx = info.index_by_name("ib")
        assert _index_entry_count(storage, info.id, idx.id) == n

    def test_backfill_resumes_from_checkpoint(self, env):
        """Kill the worker mid-reorg; a fresh worker resumes from the
        persisted checkpoint (ref: ddl/reorg.go:71 resumable reorgInfo)."""
        storage, s = env
        n = BACKFILL_BATCH * 3
        s.execute("CREATE TABLE t (a BIGINT PRIMARY KEY, b INT)")
        s.execute("INSERT INTO t VALUES " +
                   ",".join(f"({i}, {i})" for i in range(n)))

        # enqueue without driving: stub out run_job
        w0 = DDLWorker(storage)
        ddl = DDL(storage, worker=w0)
        ddl.worker.run_job = lambda job_id: None
        ddl.execute(parse("CREATE INDEX ib ON t (b)")[0], "test")

        # walk to WRITE_REORG (3 transitions)
        stepper = DDLWorker(storage)
        for _ in range(3):
            job = stepper.run_one_step_transition_only() \
                if hasattr(stepper, "run_one_step_transition_only") \
                else stepper.run_one_step()
            if job.schema_state == int(SchemaState.WRITE_REORG):
                break

        class Crash(Exception):
            pass

        def crash_after_first(jb, cnt):
            raise Crash()

        crasher = DDLWorker(storage, on_backfill_batch=crash_after_first)
        with pytest.raises(Crash):
            crasher._backfill_index(job)

        # checkpoint persisted by the first (committed) batch
        txn = storage.begin()
        try:
            jb = Meta(txn).first_job()
        finally:
            txn.rollback()
        assert jb.reorg_handle is not None
        assert jb.reorg_handle >= BACKFILL_BATCH - 1

        resumed = []
        fresh = DDLWorker(storage,
                          on_backfill_batch=lambda j, c: resumed.append(c))
        done = fresh.run_job(jb.id)
        assert done.state == JobState.DONE
        # the fresh worker did NOT redo the first batch
        assert sum(resumed) == n - (jb.reorg_handle + 1)
        info = s.domain.info_schema().table("test", "t")
        idx = info.index_by_name("ib")
        assert _index_entry_count(storage, info.id, idx.id) == n

    def test_unique_violation_rolls_back(self, env):
        storage, s = env
        s.execute("CREATE TABLE t (a BIGINT PRIMARY KEY, b INT)")
        s.execute("INSERT INTO t VALUES (1, 5), (2, 5)")
        with pytest.raises(SQLError, match="[Dd]uplicate"):
            s.execute("ALTER TABLE t ADD UNIQUE INDEX ub (b)")
        info = s.domain.info_schema().table("test", "t")
        assert info.index_by_name("ub") is None
        # job landed in history as CANCELLED; table still fully writable
        s.execute("INSERT INTO t VALUES (3, 5)")
        assert len(s.query("SELECT * FROM t").rows) == 3


class TestSchemaValidation:
    def test_commit_after_ddl_on_written_table_replays(self, env):
        """Txn writes t; DDL adds an index on t before the commit; the
        schema-lease check fires and the session replays the statements
        against the new schema, so the index sees the row."""
        storage, s = env
        s.execute("CREATE TABLE t (a BIGINT PRIMARY KEY, b INT)")
        s.execute("BEGIN")
        s.execute("INSERT INTO t VALUES (1, 10)")
        other = Session(storage, db="test")
        other.execute("CREATE INDEX ib ON t (b)")
        other.close()
        s.execute("COMMIT")     # SchemaChangedError -> replay
        info = s.domain.info_schema().table("test", "t")
        idx = info.index_by_name("ib")
        assert _index_entry_count(storage, info.id, idx.id) == 1
        assert s.query("SELECT a FROM t WHERE b = 10").rows == [(1,)]

    def test_commit_after_unrelated_ddl_passes(self, env):
        storage, s = env
        s.execute("CREATE TABLE t (a BIGINT PRIMARY KEY, b INT)")
        s.execute("CREATE TABLE u (x BIGINT PRIMARY KEY)")
        s.execute("BEGIN")
        s.execute("INSERT INTO t VALUES (1, 10)")
        other = Session(storage, db="test")
        other.execute("CREATE INDEX ix ON u (x)")
        other.close()
        s.execute("COMMIT")     # unrelated diff: no retry needed
        assert s.query("SELECT * FROM t").rows == [(1, 10)]


class TestJobQueue:
    def test_history_and_schema_version_per_transition(self, env):
        storage, s = env
        txn = storage.begin()
        v0 = Meta(txn).schema_version()
        txn.rollback()
        s.execute("CREATE TABLE t (a BIGINT PRIMARY KEY, b INT)")
        s.execute("CREATE INDEX ib ON t (b)")
        txn = storage.begin()
        try:
            m = Meta(txn)
            v1 = m.schema_version()
            assert m.first_job() is None          # queue drained
        finally:
            txn.rollback()
        # create table = 1 version, add index = 4 (one per transition)
        assert v1 - v0 == 5

    def test_index_ids_never_reused(self, env):
        storage, s = env
        s.execute("CREATE TABLE t (a BIGINT PRIMARY KEY, b INT, KEY k1 (b))")
        info1 = s.domain.info_schema().table("test", "t")
        id1 = info1.index_by_name("k1").id
        s.execute("DROP INDEX k1 ON t")
        s.execute("CREATE INDEX k2 ON t (b)")
        info2 = s.domain.info_schema().table("test", "t")
        assert info2.index_by_name("k2").id > id1
