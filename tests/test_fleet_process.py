"""Fleet process-level behavior (tidb_tpu/fleet.py): one store-plane
subprocess + N SQL-server subprocesses — the real multi-process
topology, not in-process lookalikes. Pins cross-process schema
coordination (DDL on A visible on B within the schema lease; a write
from B under the old schema version rejected, not silently applied;
DDL availability restored within a lease interval after a member
dies) and the chaos contract: SIGKILL one member mid-statement under
seeded faults and only retryable errors reach that member's clients
while survivors keep serving with drained gauges."""

import json
import threading
import time

import pytest

from tidb_tpu import errcode
from tidb_tpu.fleet import Fleet
from tidb_tpu.util import statusclient

from tests.mysql_client import MiniClient, MySQLError

pytestmark = pytest.mark.usefixtures("ledger_hygiene")

LEASE_MS = 2000          # Domain.SCHEMA_LEASE_MS default in the servers
CONVERGE_S = 30.0        # lease + worker tick + slow-CI slack


@pytest.fixture(scope="module")
def fleet():
    with Fleet(n_sql=2) as f:
        f.wait_healthy(timeout=120)
        yield f


def _client(fleet, index, db=""):
    c = fleet.client(index=index, db=db)
    c.sock.settimeout(120)
    return c


def _query_until(fleet, index, sql, db="", timeout=CONVERGE_S):
    """Poll one member until the statement succeeds (schema-lease
    convergence, owner failover); returns (rows, elapsed_seconds)."""
    t0 = time.monotonic()
    last = None
    while time.monotonic() - t0 < timeout:
        try:
            c = _client(fleet, index, db=db)
            try:
                res = c.query(sql)
                # SELECTs return (cols, rows); DML/DDL an OK rowcount
                rows = res[1] if isinstance(res, tuple) else res
                return rows, time.monotonic() - t0
            finally:
                c.close()
        except (MySQLError, OSError) as e:
            last = e
            time.sleep(0.25)
    raise AssertionError(
        f"member {index} never served {sql!r} within {timeout}s "
        f"(last: {last})")


def _arm_failpoint(fleet, index, name, spec):
    m = fleet.members[index]
    doc = statusclient.post_json(fleet.host, m.status_port,
                                 "/failpoint",
                                 {"name": name, "spec": spec},
                                 timeout=10)
    assert doc.get("ok"), doc


class TestCrossProcessSchema:
    def test_ddl_on_a_visible_on_b_within_lease(self, fleet):
        a = _client(fleet, 0)
        a.query("CREATE DATABASE fd")
        a.query("CREATE TABLE fd.t (id BIGINT PRIMARY KEY, v BIGINT)")
        a.query("INSERT INTO fd.t VALUES (1, 5)")
        a.close()
        rows, elapsed = _query_until(fleet, 1, "SELECT v FROM fd.t",
                                     db="fd")
        assert rows == [("5",)]
        assert elapsed < CONVERGE_S

    def test_write_under_old_schema_rejected_not_applied(self, fleet):
        """B opens a txn touching a column A then drops: commit-time
        schema validation must reject the write (replay cannot apply),
        never silently commit it under the old layout."""
        a = _client(fleet, 0)
        a.query("CREATE DATABASE sv")
        a.query("CREATE TABLE sv.t (id BIGINT PRIMARY KEY, v BIGINT, "
                "w BIGINT)")
        a.query("INSERT INTO sv.t VALUES (1, 1, 1)")
        a.close()
        _query_until(fleet, 1, "SELECT v FROM sv.t", db="sv")
        b = _client(fleet, 1, db="sv")
        b.query("BEGIN")
        b.query("UPDATE t SET w = 99 WHERE id = 1")
        a = _client(fleet, 0)
        a.query("ALTER TABLE sv.t DROP COLUMN w")
        with pytest.raises((MySQLError, OSError)):
            b.query("COMMIT")
        b.close()
        # the stale write is gone WITH the column; v untouched
        assert a.query("SELECT v FROM sv.t")[1] == [("1",)]
        with pytest.raises(MySQLError):
            a.query("SELECT w FROM sv.t")
        a.close()

    def test_ddl_available_within_lease_after_member_dies(self, fleet):
        """Owner failover: SIGKILL one member (it may hold the DDL
        owner lease); the survivor must run DDL as soon as the lease
        expires — bounded by the lease interval plus worker cadence,
        not a hang."""
        fleet.kill(0)
        try:
            rows, elapsed = _query_until(fleet, 1,
                                         "CREATE DATABASE failover_db")
            assert elapsed < CONVERGE_S
            names, _ = _query_until(fleet, 1, "SHOW DATABASES")
            assert ("failover_db",) in names
        finally:
            fleet.restart(0)
            fleet.wait_healthy(timeout=120)


class TestClusterObservability:
    def test_cluster_members_lists_every_process(self, fleet):
        """The membership registry seen from ANY member: both SQL
        servers and the store plane itself, each with its status port
        and lease."""
        rows, _ = _query_until(
            fleet, 1, "SELECT member_id, role, status_port FROM "
                      "information_schema.cluster_members")
        roles = [r[1] for r in rows]
        assert roles.count("sql") >= 2, rows
        assert "store" in roles, rows
        ports = {int(r[2]) for r in rows}
        assert {m.status_port for m in fleet.members} <= ports
        assert fleet.store_status_port in ports

    def test_cross_member_trace_correlation(self, fleet):
        """The ISSUE 17 acceptance bar: a statement TRACEd on member 0
        mints a fleet-unique trace id; one SELECT over
        cluster_statement_traces on a DIFFERENT member locates the
        store-plane-retained record whose origin_trace_id equals it
        (the origin stamp shipped inside the traced store RPCs)."""
        a = _client(fleet, 0)
        try:
            a.query("CREATE DATABASE obs_corr")
            a.query("CREATE TABLE obs_corr.t (id BIGINT PRIMARY KEY, "
                    "v BIGINT)")
            a.query("INSERT INTO obs_corr.t VALUES (1, 7)")
            res = a.query("TRACE FORMAT='json' SELECT v FROM "
                          "obs_corr.t WHERE id = 1")
            tid = json.loads(res[1][0][0])["trace_id"]
        finally:
            a.close()
        assert tid > 0xFFFFFF   # fleet-unique: member nonce folded in
        mrows, _ = _query_until(
            fleet, 1, "SELECT member_id, role FROM "
                      "information_schema.cluster_members")
        store_ids = {r[0] for r in mrows if r[1] == "store"}
        assert store_ids, mrows
        deadline = time.monotonic() + 20
        while True:
            srows, _ = _query_until(
                fleet, 1,
                "SELECT member, origin_member, origin_trace_id FROM "
                "information_schema.cluster_statement_traces "
                f"WHERE origin_trace_id = {tid}")
            hit = [r for r in srows if r[0] in store_ids]
            if hit:
                break
            if time.monotonic() > deadline:
                raise AssertionError(
                    f"no store-plane record for trace {tid}: {srows}")
            time.sleep(0.25)
        # the store-plane record names the ISSUING member (member 0),
        # not the store member that served the RPC
        issuer = f"{fleet.host}:{fleet.members[0].status_port}:"
        assert hit[0][1].startswith(issuer), hit


class TestFleetChaos:
    def test_sigkill_mid_statement_retryable_only(self, fleet):
        """The ISSUE 16 chaos leg: seeded faults armed on the victim,
        SIGKILL mid-statement. The victim's clients may see socket
        drops (reconnect-retryable by definition) or RETRYABLE SQL
        codes — never a non-retryable error, never a wrong row.
        Survivors keep serving and their level gauges drain."""
        setup = _client(fleet, 1)
        setup.query("CREATE DATABASE chaos")
        setup.query("CREATE TABLE chaos.t (id BIGINT PRIMARY KEY, "
                    "v BIGINT)")
        setup.query("INSERT INTO chaos.t VALUES " +
                    ", ".join(f"({i}, {i})" for i in range(32)))
        setup.close()
        _query_until(fleet, 0, "SELECT v FROM chaos.t WHERE id = 3",
                     db="chaos")
        # the seeded fault schedule on the victim: retryable-classed
        # device and RPC faults with small budgets (bench.py chaos
        # vocabulary), so statements are mid-flight through fault
        # handling when the SIGKILL lands
        _arm_failpoint(fleet, 0, "device/dispatch",
                       "3*raise(DeviceFaultError)")
        _arm_failpoint(fleet, 0, "rpc/request",
                       "3*raise(ServerBusyError)")

        bad: list = []
        wrong: list = []
        stop = threading.Event()

        def victim_client() -> None:
            while not stop.is_set():
                try:
                    c = MiniClient(fleet.host, fleet.members[0].port,
                                   db="chaos")
                    c.sock.settimeout(60)
                    while not stop.is_set():
                        _cols, rows = c.query(
                            "SELECT v FROM chaos.t WHERE id = 3")
                        if rows != [("3",)]:
                            wrong.append(rows)
                except MySQLError as e:
                    if e.code not in errcode.RETRYABLE:
                        bad.append(f"({e.code}) {e}")
                    time.sleep(0.05)
                except OSError:
                    time.sleep(0.05)   # connection drop: reconnect

        threads = [threading.Thread(target=victim_client)
                   for _ in range(2)]
        for t in threads:
            t.start()
        time.sleep(1.0)                # statements in flight
        try:
            fleet.kill(0)              # SIGKILL, mid-statement
            time.sleep(1.0)            # clients churn on the dead port
        finally:
            stop.set()
            for t in threads:
                t.join(timeout=30)
        assert not bad, f"non-retryable errors surfaced: {bad[:3]}"
        assert not wrong, f"wrong results under chaos: {wrong[:3]}"

        # survivors keep serving the same data
        rows, _ = _query_until(fleet, 1,
                               "SELECT v FROM chaos.t WHERE id = 3",
                               db="chaos")
        assert rows == [("3",)]
        assert fleet.health(1)["version"]

        # membership churn: while the dead member's lease is still
        # live, a cluster fan-out from the survivor returns partial
        # rows within the bounded timeout plus a warning — never a
        # stall, never a statement error; then the member ages out of
        # cluster_members within one TTL (it stopped heartbeating; no
        # deregistration path exists to miss)
        dead_pfx = f"{fleet.host}:{fleet.members[0].status_port}:"
        c = _client(fleet, 1)
        try:
            _cols, mrows = c.query(
                "SELECT member_id FROM "
                "information_schema.cluster_members")
            dead_listed = any(r[0].startswith(dead_pfx) for r in mrows)
            t0 = time.monotonic()
            _cols, prows = c.query(
                "SELECT member, id FROM "
                "information_schema.cluster_processlist")
            assert time.monotonic() - t0 < 10   # bounded degradation
            # the survivor itself answered (partial rows, not empty)
            assert any(not r[0].startswith(dead_pfx) for r in prows), \
                prows
            if dead_listed:
                _cols, wrows = c.query("SHOW WARNINGS")
                assert any("unreachable" in r[2] for r in wrows), wrows
        finally:
            c.close()
        deadline = time.monotonic() + 20        # TTL (3s) + CI slack
        while True:
            mrows, _ = _query_until(
                fleet, 1, "SELECT member_id FROM "
                          "information_schema.cluster_members")
            if not any(r[0].startswith(dead_pfx) for r in mrows):
                break
            if time.monotonic() > deadline:
                raise AssertionError(
                    f"dead member never aged out: {mrows}")
            time.sleep(0.25)

        # survivor gauge hygiene: every *_current/_depth level family
        # returns to zero once its clients are gone (no ledger leaks
        # from the dead peer or the chaos churn)
        deadline = time.monotonic() + 20
        while True:
            snap = fleet.health(1)["metrics"]
            leaked = {k: v for k, v in snap.items()
                      if (k.split("{")[0].endswith("_current") or
                          k.split("{")[0].endswith("_depth")) and v}
            if not leaked:
                break
            if time.monotonic() > deadline:
                raise AssertionError(f"survivor gauges leaked: {leaked}")
            time.sleep(0.25)
        fleet.restart(0)
        fleet.wait_healthy(timeout=120)
