"""benchkv throughput tool (ref: cmd/benchkv/main.go:122-140,
cmd/benchraw) and thread-leak detection (ref: util/testleak)."""

import pytest

from tidb_tpu.benchmarks import benchkv
from tidb_tpu.store.storage import new_mock_storage
from tidb_tpu.util import testleak


class TestBenchKV:
    @pytest.mark.parametrize("mode", ["txn", "raw"])
    def test_modes(self, mode):
        st = new_mock_storage()
        st.cluster.split(b"bench_w0_k00000500")
        out = benchkv.run(st, mode=mode, keys=1000, batch=100)
        assert out["metric"] == f"benchkv_{mode}_ops_per_sec"
        assert out["value"] > 0
        st.close()

    def test_workers_parallel(self):
        st = new_mock_storage()
        out = benchkv.run(st, mode="txn", keys=300, batch=50, workers=4)
        assert out["workers"] == 4
        # all four workers' keys landed
        t = st.begin()
        for w in range(4):
            assert t.get(b"bench_w%d_k%08d" % (w, 0)) is not None
        t.rollback()
        st.close()

    def test_cli(self, capsys):
        assert benchkv.main(["--keys", "200", "--batch", "50"]) == 0
        import json
        out = json.loads(capsys.readouterr().out)
        assert out["value"] > 0


class TestLeakCheck:
    def test_clean_workload_leaks_nothing(self):
        before = testleak.snapshot()
        st = new_mock_storage()
        benchkv.run(st, mode="txn", keys=200, batch=50, workers=2)
        st.close()
        assert testleak.check(before) == []

    def test_detects_a_leak(self):
        import threading
        before = testleak.snapshot()
        stop = threading.Event()
        t = threading.Thread(target=stop.wait, name="leaky-worker",
                             daemon=True)
        t.start()
        leaked = testleak.check(before, timeout=0.2)
        assert "leaky-worker" in leaked
        stop.set()
        t.join()
        assert testleak.check(before) == []
