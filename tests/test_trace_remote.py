"""Cross-process trace propagation (trace.py + store/remote.py; ref: the
reference's OpenTracing spans riding gRPC — session.go:692): storage-side
span trees come back over the RPC and graft into the statement trace."""

import pytest

from tidb_tpu import trace
from tidb_tpu.session import Session
from tidb_tpu.store.remote import StorageServer, connect


@pytest.fixture
def server():
    srv = StorageServer()
    srv.start()
    yield srv
    srv.close()


def _span_names(root):
    out = []

    def walk(s):
        out.append(s.name)
        for c in s.children:
            walk(c)
    walk(root)
    return out


def test_remote_spans_graft_into_statement_trace(server):
    st = connect("127.0.0.1", server.port)
    s = Session(st)
    s.execute("CREATE DATABASE tr; USE tr")
    s.execute("CREATE TABLE t (id BIGINT PRIMARY KEY, v BIGINT)")
    s.execute("INSERT INTO t VALUES (1, 10), (2, 20)")

    # capture the session's own statement root as it finishes
    roots = []
    orig_end = trace.end

    def capture(root):
        roots.append(root)
        return orig_end(root)

    trace.end = capture
    try:
        assert s.query("SELECT SUM(v) FROM t").rows == [(30,)]
    finally:
        trace.end = orig_end

    assert roots
    names = [n for r in roots for n in _span_names(r)]
    remote = [n for n in names if n.startswith("storage:")]
    assert remote, f"no storage-side spans grafted: {names}"
    # the storage process's own phases ride inside the grafted subtree
    assert any(n.startswith("storage:") and ("tso" in n or "kv_" in n
               or "coprocessor" in n or "region" in n)
               for n in remote), remote
    s.close()
    st.close()


def test_attach_remote_child_ends_at_now_duration_preserved():
    """Unit pin for trace.attach_remote: remote clocks don't align, so a
    grafted child is positioned to END at the moment of grafting ("now")
    with its reported duration preserved — and the same holds for nested
    children."""
    import time
    root = trace.begin("statement")
    try:
        before = time.perf_counter_ns()
        trace.attach_remote({
            "name": "storage:coprocessor", "duration_ns": 5_000_000,
            "children": [{"name": "storage:kv_scan",
                          "duration_ns": 2_000_000}],
        })
        after = time.perf_counter_ns()
    finally:
        trace.end(root)
    child = root.children[-1]
    assert child.name == "storage:coprocessor"
    assert child.duration_ns == 5_000_000
    # ends at "now": between the instants bracketing the graft call
    assert before <= child.end_ns <= after
    assert child.start_ns == child.end_ns - 5_000_000
    sub = child.children[0]
    assert sub.duration_ns == 2_000_000
    assert before <= sub.end_ns <= after


def test_untraced_calls_skip_propagation(server):
    st = connect("127.0.0.1", server.port)
    s = Session(st)
    s.execute("CREATE DATABASE tr2; USE tr2")
    s.execute("CREATE TABLE t (id BIGINT PRIMARY KEY)")
    # no active trace: calls must not error and nothing leaks
    s.execute("INSERT INTO t VALUES (1)")
    assert s.query("SELECT COUNT(*) FROM t").rows == [(1,)]
    assert trace.current_root() is None
    s.close()
    st.close()
