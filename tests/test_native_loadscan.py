"""Native C++ LOAD DATA scanner (tidb_tpu/native/loadscan.cc):
differential-tested against the general Python scanner on crafted and
randomized inputs — both must produce identical rows."""

import random

import pytest

from tidb_tpu.executor import loaddata
from tidb_tpu.native import scan_rows_native
from tidb_tpu.parser import ast

pytestmark = pytest.mark.skipif(
    scan_rows_native(b"", b",", b"\n", b"", b"\\", 0) is None,
    reason="native loadscan unavailable (no compiler)")


def _python_rows(text, stmt):
    """The general scanner, bypassing the native path."""
    lt = stmt.lines_terminated or "\n"
    ft = stmt.fields_terminated or "\t"
    out = []
    for line in loaddata._split_lines([text], lt, ft,
                                      stmt.fields_enclosed,
                                      stmt.fields_escaped,
                                      stmt.lines_starting or "",
                                      stmt.ignore_lines):
        if line:
            out.append(loaddata._split_fields(line, ft,
                                              stmt.fields_enclosed,
                                              stmt.fields_escaped))
    return out


def _native_rows(text, stmt):
    gen = loaddata._parse_lines_native(
        [text], stmt, stmt.lines_terminated or "\n",
        stmt.fields_terminated or "\t", stmt.fields_enclosed,
        stmt.fields_escaped)
    assert gen is not None
    return list(gen)


CASES = [
    ("a,b\n1,2\n", {}),
    ("a,b\n1,\\N\n", {}),
    ('x,"enclosed, comma",y\n', {"fields_enclosed": '"'}),
    ('"say ""hi""",2\n', {"fields_enclosed": '"'}),
    ("1\tt a b\t3\n", {"fields_terminated": "\t"}),
    ("h1,h2\nv1,v2\n", {"ignore_lines": 1}),
    ("a\\,b,c\n", {}),                       # escaped separator
    ("x,\n,y\n", {}),                        # empty fields
    ("\n\na,b\n", {}),                       # leading empty lines skipped
    ("no trailing newline", {}),
    ('mixed,"q"\nplain,r\n', {"fields_enclosed": '"'}),
    ('1,ab"c\n2,x\n', {"fields_enclosed": '"'}),   # stray quote -> bail
    ('"a\nb",2\n3,c\n', {"fields_enclosed": '"'}), # newline in quotes
]


class TestDifferential:
    @pytest.mark.parametrize("text,kw", CASES)
    def test_cases_match_python(self, text, kw):
        stmt = ast.LoadDataStmt(fields_terminated=kw.get(
            "fields_terminated", ","), **{k: v for k, v in kw.items()
                                          if k != "fields_terminated"})
        assert _native_rows(text, stmt) == _python_rows(text, stmt)

    def test_randomized(self):
        rng = random.Random(7)
        alphabet = 'ab,"\\\n\tx'
        for trial in range(300):
            text = "".join(rng.choice(alphabet)
                           for _ in range(rng.randrange(0, 60)))
            enc = rng.choice(['', '"'])
            stmt = ast.LoadDataStmt(fields_terminated=",",
                                    fields_enclosed=enc)
            assert _native_rows(text, stmt) == _python_rows(text, stmt), \
                (trial, repr(text), enc)

    def test_chunked_stream_matches_whole(self):
        text = ('id,"name, inc",3.5\n' * 500 +
                'x,\\N,"multi\nline"\n' * 50)
        stmt = ast.LoadDataStmt(fields_terminated=",",
                                fields_enclosed='"')
        whole = _native_rows(text, stmt)
        pieces = [text[i:i + 97] for i in range(0, len(text), 97)]
        gen = loaddata._parse_lines_native(
            iter(pieces), stmt, "\n", ",", '"', "\\")
        assert list(gen) == whole
        assert whole == _python_rows(text, stmt)


class TestEndToEnd:
    def test_load_data_uses_native(self, tmp_path):
        from tidb_tpu.session import Session
        from tidb_tpu.store.storage import new_mock_storage
        p = tmp_path / "big.csv"
        p.write_text("".join(f'{i},"name {i}",{i}.25\n'
                             for i in range(5000)))
        s = Session(new_mock_storage())
        s.execute("CREATE DATABASE d")
        s.execute("USE d")
        s.execute("CREATE TABLE t (id BIGINT PRIMARY KEY, "
                  "name VARCHAR(32), amt DECIMAL(10,2))")
        [n] = s.execute(f"LOAD DATA INFILE '{p}' INTO TABLE t "
                        f"FIELDS TERMINATED BY ',' ENCLOSED BY '\"'")
        assert n == 5000
        assert s.query("SELECT COUNT(*), MIN(name), MAX(id) FROM t"
                       ).rows == [(5000, "name 0", 4999)]
        s.close()


class TestBoundaries:
    def test_row_straddling_chunk_boundary(self):
        text = "".join(f'{i},"name {i}",{i}.25\n' for i in range(4000))
        stmt = ast.LoadDataStmt(fields_terminated=",",
                                fields_enclosed='"')
        cut = (1 << 16) + 7   # split mid-row beyond the batch floor
        gen = loaddata._parse_lines_native(
            iter([text[:cut], text[cut:]]), stmt, "\n", ",", '"', "\\")
        rows = list(gen)
        assert len(rows) == 4000
        assert all(len(r) == 3 for r in rows)

    def test_ignored_line_without_terminator(self):
        stmt = ast.LoadDataStmt(fields_terminated=",", ignore_lines=1)
        gen = loaddata._parse_lines_native(["a,b"], stmt, "\n", ",",
                                           "", "\\")
        assert list(gen) == []

    def test_multibyte_separator_uses_python_scanner(self):
        stmt = ast.LoadDataStmt(fields_terminated="§")
        rows = list(loaddata.parse_lines("a§b\nc§d\n", stmt))
        assert rows == [["a", "b"], ["c", "d"]]
