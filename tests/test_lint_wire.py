"""Static wire-path invariants, enforced as a test so they cannot
silently regress:

  1. No wire-path module imports pickle. The typed codec (store/wire.py)
     exists so that DECODING NEVER EXECUTES CODE; one convenient
     `pickle.loads` on a socket path would reopen that hole. Trusted
     local-disk snapshots live in store/snapshot.py, deliberately OFF
     this list.
  2. Every socket `recv` happens inside the one bounded, length-checked
     helper (`_recv_exact`), which itself must loop on an explicit
     remaining-byte count. Ad-hoc `sock.recv(65536)`-style loops are how
     partial reads turn into frame desync.

Checked by AST walk, not regex, so comments/strings can't fool it and
renamed imports (`import pickle as p`) can't slip through.
"""

import ast
import os

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# the wire path: every module that builds, parses, or routes frames
WIRE_PATH_FILES = [
    "tidb_tpu/store/wire.py",
    "tidb_tpu/store/remote.py",
    "tidb_tpu/store/stream.py",
    "tidb_tpu/store/copr.py",
    "tidb_tpu/store/region_cache.py",
    "tidb_tpu/mockstore/rpc.py",
]

# the only functions allowed to call socket .recv(); each must be a
# bounded loop over an explicit byte count
RECV_HELPERS = {"_recv_exact"}


def _tree(relpath):
    with open(os.path.join(REPO, relpath)) as f:
        return ast.parse(f.read(), filename=relpath)


@pytest.mark.parametrize("relpath", WIRE_PATH_FILES)
def test_no_pickle_on_wire_path(relpath):
    offenders = []
    for node in ast.walk(_tree(relpath)):
        if isinstance(node, ast.Import):
            offenders += [a.name for a in node.names
                          if a.name.split(".")[0] in ("pickle", "cPickle",
                                                      "dill", "shelve",
                                                      "marshal")]
        elif isinstance(node, ast.ImportFrom):
            if node.module and node.module.split(".")[0] in (
                    "pickle", "cPickle", "dill", "shelve", "marshal"):
                offenders.append(node.module)
    assert not offenders, (
        f"{relpath} imports {offenders}: wire-path modules must stay "
        "pickle-free (trusted on-disk snapshots belong in "
        "store/snapshot.py)")


def _functions_calling_recv(tree):
    """Function names (qualified by nesting) whose bodies call `.recv`."""
    out = {}

    class V(ast.NodeVisitor):
        def __init__(self):
            self.stack = []

        def _visit_func(self, node):
            self.stack.append(node.name)
            self.generic_visit(node)
            self.stack.pop()

        visit_FunctionDef = _visit_func
        visit_AsyncFunctionDef = _visit_func

        def visit_Call(self, node):
            fn = node.func
            if isinstance(fn, ast.Attribute) and fn.attr == "recv":
                name = self.stack[-1] if self.stack else "<module>"
                out.setdefault(name, []).append(node)
            self.generic_visit(node)

    V().visit(tree)
    return out


@pytest.mark.parametrize("relpath", WIRE_PATH_FILES)
def test_every_recv_is_length_prefixed_and_bounded(relpath):
    callers = _functions_calling_recv(_tree(relpath))
    rogue = set(callers) - RECV_HELPERS
    assert not rogue, (
        f"{relpath}: socket recv outside the bounded helper(s) "
        f"{sorted(RECV_HELPERS)}: {sorted(rogue)} — all frame reads "
        "must go through the length-prefixed _recv_exact loop")
    for name, calls in callers.items():
        for call in calls:
            # recv(k) must pass a computed remaining-count expression,
            # never no-arg / constant-buffer style
            assert call.args and not isinstance(call.args[0],
                                                ast.Constant), (
                f"{relpath}:{call.lineno}: recv must take the exact "
                "remaining byte count")


def test_recv_helper_exists_and_loops():
    """The helper itself: a while-loop accumulating toward an explicit
    n, raising on EOF (no silent short read)."""
    tree = _tree("tidb_tpu/store/remote.py")
    helper = None
    for node in ast.walk(tree):
        if isinstance(node, ast.FunctionDef) and \
                node.name == "_recv_exact":
            helper = node
            break
    assert helper is not None, "store/remote.py lost _recv_exact"
    has_loop = any(isinstance(n, ast.While) for n in ast.walk(helper))
    raises = any(isinstance(n, ast.Raise) for n in ast.walk(helper))
    assert has_loop and raises, (
        "_recv_exact must loop to the requested count and raise on EOF")


def test_wire_registry_is_closed():
    """decode() only constructs registry types: spot-check that the
    registry install function exists and no `eval`/`exec`/`__import__`
    appears anywhere in the codec."""
    tree = _tree("tidb_tpu/store/wire.py")
    for node in ast.walk(tree):
        if isinstance(node, ast.Call) and \
                isinstance(node.func, ast.Name) and \
                node.func.id in ("eval", "exec", "__import__", "compile"):
            raise AssertionError(
                f"store/wire.py:{node.lineno} calls {node.func.id}")
