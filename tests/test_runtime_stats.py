"""Per-operator runtime statistics (runtime_stats.py; ref: the
reference's RuntimeStatsColl + EXPLAIN ANALYZE): actual rows / loops /
host time per plan node, device time behind the
tidb_tpu_runtime_stats_device sysvar, the statement digest summary, and
the structured slow log."""

import logging
import time

import pytest

import tpch
from tidb_tpu import config, perfschema
from tidb_tpu.session import Session
from tidb_tpu.store.storage import new_mock_storage


@pytest.fixture
def sess():
    perfschema.reset()
    s = Session(new_mock_storage())
    s.execute("CREATE DATABASE d")
    s.execute("USE d")
    s.execute("CREATE TABLE t (id BIGINT PRIMARY KEY, v BIGINT)")
    s.execute("INSERT INTO t VALUES (1,10),(2,20),(3,30),(4,40),(5,50)")
    yield s
    s.close()


@pytest.fixture(scope="module")
def tpch_sess():
    s = Session(new_mock_storage())
    s.execute("CREATE DATABASE tpch")
    s.execute("USE tpch")
    data = tpch.TpchData()
    tpch.load(s, data)
    yield s
    s.close()


def _analyze(sess, sql):
    """-> (columns, rows) of EXPLAIN ANALYZE."""
    rs = sess.query("EXPLAIN ANALYZE " + sql)
    return rs.columns, rs.rows


class TestExplainAnalyze:
    def test_columns_and_basic_stats(self, sess):
        cols, rows = _analyze(sess, "SELECT * FROM t WHERE v >= 30")
        assert cols == ["id", "est_rows", "act_rows", "loops", "time",
                        "device_time", "mem", "cop_tasks", "pipeline",
                        "kernel"]
        assert rows, "no plan rows"
        # root operator produced exactly the result cardinality
        root = rows[0]
        assert root[2] == 3          # act_rows
        assert root[3] >= 1          # loops
        assert root[4].endswith(("ns", "us", "ms", "s"))
        # a reader somewhere in the tree dispatched >=1 cop task
        assert any(r[7] >= 1 for r in rows), rows

    def test_act_rows_match_cardinality(self, sess):
        want = len(sess.query("SELECT v, COUNT(*) FROM t GROUP BY v").rows)
        _cols, rows = _analyze(sess, "SELECT v, COUNT(*) FROM t GROUP BY v")
        assert rows[0][2] == want == 5

    def test_plain_explain_unchanged(self, sess):
        rs = sess.query("EXPLAIN SELECT * FROM t")
        assert rs.columns == ["plan"]
        assert "TableReader" in rs.rows[0][0] or \
            any("TableReader" in r[0] for r in rs.rows)

    def test_dml_supported(self, sess):
        _cols, rows = _analyze(sess, "UPDATE t SET v = v + 1 WHERE id <= 2")
        assert rows[0][0].startswith("Update")
        assert rows[0][2] == 2      # two rows updated
        assert sess.query("SELECT v FROM t WHERE id = 1").rows == [(11,)]

    def test_unsupported_statement_rejected(self, sess):
        with pytest.raises(Exception, match="EXPLAIN ANALYZE"):
            sess.execute("EXPLAIN ANALYZE SHOW TABLES")

    def test_device_time_gated_by_sysvar(self, sess):
        # below the sysvar everything shows "-": collection must not pay
        # block_until_ready when off
        _cols, rows = _analyze(sess, "SELECT SUM(v) FROM t")
        assert all(r[5] == "-" for r in rows)

    def test_device_time_recorded_when_on(self):
        perfschema.reset()
        s = Session(new_mock_storage())
        s.execute("CREATE DATABASE dd; USE dd")
        s.execute("CREATE TABLE big (id BIGINT PRIMARY KEY, v BIGINT)")
        vals = ",".join(f"({i},{i % 5})" for i in range(3000))
        s.execute("INSERT INTO big VALUES " + vals)
        config.set_var("tidb_tpu_runtime_stats_device", 1)
        try:
            _cols, rows = _analyze(
                s, "SELECT v, SUM(id) FROM big GROUP BY v")
        finally:
            config.set_var("tidb_tpu_runtime_stats_device", 0)
        reader = [r for r in rows if "TableReader" in r[0]]
        assert reader, rows
        # >=2048 rows hit the device agg kernel; its completion time is
        # attributed to the reader that pushed the partial agg down
        assert reader[0][5] not in ("-", "0ns"), rows
        s.close()


class TestExplainAnalyzeTpch:
    @pytest.mark.parametrize("q", ["Q1", "Q3", "Q5"])
    def test_act_rows_match(self, tpch_sess, q):
        sql = getattr(tpch, q)
        want = len(tpch_sess.query(sql).rows)
        cols, rows = _analyze(tpch_sess, sql)
        assert rows[0][2] == want, (q, rows[0])
        # every executed operator carries loops and a host time
        ran = [r for r in rows if r[3] > 0]
        assert ran
        assert all(r[4] != "0ns" for r in ran[:1])


class TestDigestSummary:
    def test_parameterized_statements_share_a_digest(self, sess):
        for i in (1, 2, 3):
            sess.query(f"SELECT * FROM t WHERE id = {i}")
        rows = sess.query(
            "SELECT digest, digest_text, exec_count, sum_rows FROM "
            "performance_schema.events_statements_summary_by_digest").rows
        mine = [r for r in rows if "WHERE id = ?" in r[1]]
        assert len(mine) == 1
        assert mine[0][2] == 3 and mine[0][3] == 3

    def test_latency_and_phase_sums(self, sess):
        sess.query("SELECT SUM(v) FROM t")
        sess.query("SELECT SUM(v) FROM t")
        rows = sess.query(
            "SELECT digest_text, exec_count, sum_latency_ns, "
            "max_latency_ns, avg_latency_ns, sum_exec_ns FROM "
            "performance_schema.events_statements_summary_by_digest").rows
        mine = [r for r in rows if "SUM" in r[0].upper()
                and "summary" not in r[0]]
        assert mine and mine[0][1] == 2
        _t, _n, s_lat, mx, avg, s_exec = mine[0]
        assert 0 < mx <= s_lat and avg <= s_lat
        assert s_exec > 0

    def test_operator_hot_spots(self, sess):
        sess.query("SELECT v, COUNT(*) FROM t GROUP BY v")
        rows = sess.query(
            "SELECT digest_text, top_operators FROM "
            "performance_schema.events_statements_summary_by_digest").rows
        mine = [r for r in rows if "GROUP BY" in r[0]
                and "summary" not in r[0]]
        assert mine
        assert "time=" in mine[0][1] and "rows=" in mine[0][1]

    def test_batch_statements_get_distinct_digests(self, sess):
        """A multi-statement batch shares one SQL text; each statement
        still lands in its own digest row (tagged by position+kind)
        instead of merging an INSERT's and a SELECT's stats."""
        sess.execute("INSERT INTO t VALUES (50, 500); SELECT * FROM t")
        rows = sess.query(
            "SELECT digest_text, exec_count FROM "
            "performance_schema.events_statements_summary_by_digest").rows
        tagged = [r for r in rows if "[stmt#" in r[0]]
        assert len(tagged) == 2, rows
        assert any(":insert]" in r[0] for r in tagged)
        assert any(":select]" in r[0] for r in tagged)

    def test_collector_sealed_after_statement(self, sess):
        """Post-statement the session keeps only name+number OpStats —
        never the executed plan tree (idle pooled sessions must not pin
        a bulk INSERT's literal plan)."""
        sess.query("SELECT COUNT(*) FROM t")
        coll = sess._last_stats
        assert sess._last_plan is None
        assert coll._nodes == {} and coll.ops()

    def test_digest_strips_strings_too(self):
        d1, n1 = perfschema.sql_digest("SELECT 'abc', 1 + 2")
        d2, n2 = perfschema.sql_digest("select  'xyz',3+ 4")
        assert d1 == d2 and n1 == n2 == "SELECT ? , ? + ?"


class TestSlowLog:
    def test_structured_record(self, sess, caplog):
        old = config.get_var("tidb_tpu_slow_query_ms")
        config.set_var("tidb_tpu_slow_query_ms", 0)
        try:
            with caplog.at_level(logging.WARNING,
                                 logger="tidb_tpu.slow_query"):
                sess.query("SELECT v, COUNT(*) FROM t GROUP BY v")
        finally:
            config.set_var("tidb_tpu_slow_query_ms", old)
        recs = [r.getMessage() for r in caplog.records
                if "slow query" in r.getMessage()]
        assert recs
        rec = recs[-1]
        assert "digest=" in rec
        assert "# Plan:" in rec
        assert "# Op:" in rec and "act_rows=" in rec and "loops=" in rec
        assert "# SQL: SELECT v, COUNT(*)" in rec


class TestOverhead:
    def test_wrapper_overhead_per_chunk_is_tiny(self):
        """The Q1 hot loop hands 64k-row chunks through each operator;
        processing one costs milliseconds. The stats wrapper adds one
        perf_counter read and three integer adds per chunk — budget it
        at <50us/chunk (measured ~1-2us), i.e. well under 2% of any
        real per-chunk cost, with device timing off."""
        from tidb_tpu import runtime_stats as rs

        class FakeChunk:
            num_rows = 65536

        ch = FakeChunk()
        n = 20_000

        def producer(_ctx):
            for _ in range(n):
                yield ch

        st = rs.OpStats("x")
        wrapped = rs._wrap_iter(producer, st)
        t0 = time.perf_counter()
        for _ in wrapped(None):
            pass
        per_chunk = (time.perf_counter() - t0) / n
        assert st.loops == n and st.act_rows == n * 65536
        assert per_chunk < 50e-6, f"{per_chunk * 1e6:.1f}us per chunk"

    def test_stats_off_means_no_collector(self, sess):
        config.set_var("tidb_tpu_runtime_stats", 0)
        try:
            sess.query("SELECT COUNT(*) FROM t")
            assert sess._last_stats is None
        finally:
            config.set_var("tidb_tpu_runtime_stats", 1)
        sess.query("SELECT COUNT(*) FROM t")
        assert sess._last_stats is not None

    def test_internal_sessions_never_pollute_active_collector(self, sess):
        """Internal catalog sessions (privilege loader, bootstrap) run
        inside a client statement; their mysql.* scans must not appear
        in that statement's operator stats."""
        from tidb_tpu import runtime_stats as rs
        coll = rs.StatsCollector()
        internal = Session(sess.storage, db="d", internal=True)
        with rs.collecting(coll):
            internal.execute("SELECT COUNT(*) FROM t")
        internal.close()
        assert coll.ops() == []

    def test_device_call_short_circuits_when_off(self):
        """With no collector (or device off) device_call must be a bare
        passthrough — the hot join/agg loops call it per batch."""
        from tidb_tpu import runtime_stats as rs
        calls = []
        out = rs.device_call(object(), lambda x: calls.append(x) or 42, 7)
        assert out == 42 and calls == [7]


class TestOpMetrics:
    def test_labeled_op_families_emitted(self, sess):
        from tidb_tpu import metrics
        sess.query("SELECT v, COUNT(*) FROM t GROUP BY v")
        snap = metrics.snapshot()
        keys = [k for k in snap
                if k.startswith(metrics.OP_ROWS) and "op=" in k]
        assert keys, sorted(snap)[:20]
        dur = [k for k in snap if k.startswith(metrics.OP_DURATIONS)
               and "op=" in k]
        assert dur
