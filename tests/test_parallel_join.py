"""Mesh lookup-join + aggregation tests (the Q3/Q5 distributed shape).

Ref model: executor/join.go HashJoinExec chains + aggregate.go, here as
one fused mesh program cross-checked against the pure-host reference.
Runs on the 8-virtual-device CPU mesh from conftest.
"""

import numpy as np
import pytest

from tidb_tpu.chunk import Chunk, Column
from tidb_tpu.expression import AggDesc, AggFunc
from tidb_tpu.expression.core import Op, col, const, func
from tidb_tpu.ops.hashagg import HashAggregator
from tidb_tpu.parallel import build_mesh
from tidb_tpu.parallel.dist_join import (BuildError, LookupSpec,
                                         MeshLookupAggKernel,
                                         host_lookup_agg)
from tidb_tpu.sqltypes import (new_double_field, new_int_field,
                               new_string_field)


def _finalize(aggs, gr):
    agg = HashAggregator(aggs)
    agg.update(gr)
    return agg.results()


def _mesh():
    return build_mesh(8)


def _assert_same(aggs, got_gr, want_gr):
    got = _finalize(aggs, got_gr)
    want = _finalize(aggs, want_gr)
    assert len(got) == len(want)
    for (gk, gv), (wk, wv) in zip(got, want):
        assert gk == wk
        for a, b in zip(gv, wv):
            if isinstance(b, float):
                assert abs(a - b) <= 1e-9 * max(1.0, abs(b)), (gk, a, b)
            else:
                assert a == b, (gk, a, b)


class TestSingleLookup:
    def _data(self, n=5000, dims=40):
        rng = np.random.default_rng(5)
        probe = Chunk([
            Column(new_int_field(), rng.integers(0, dims, n).astype(np.int64)),
            Column(new_double_field(), rng.uniform(0, 100, n)),
            Column(new_int_field(), rng.integers(0, 3650, n).astype(np.int64)),
        ])
        build = Chunk([
            Column(new_int_field(), np.arange(dims, dtype=np.int64)),
            Column(new_int_field(),
                   (np.arange(dims, dtype=np.int64) % 5)),
            Column(new_string_field(),
                   np.array([f"region{i % 5}" for i in range(dims)],
                            dtype=object)),
        ])
        return probe, build

    def test_q3_shape(self):
        """filter(probe) join dim group by dim.attr agg sums."""
        probe, build = self._data()
        flt = func(Op.LT, col(2, new_int_field()), const(1800))
        lookups = [LookupSpec(
            key_exprs=[col(0, new_int_field())],
            build_chunk=build, build_key_offsets=[0],
            payload_offsets=[1, 2])]
        # virtual schema: probe 0..2, then build cols at 3 (int), 4 (str)
        groups = [col(3, new_int_field())]
        aggs = [AggDesc(AggFunc.SUM, col(1, new_double_field())),
                AggDesc(AggFunc.COUNT, None)]
        k = MeshLookupAggKernel(_mesh(), flt, lookups, groups, aggs,
                                capacity=64)
        got = k(probe)
        want = host_lookup_agg(probe, flt, lookups, groups, aggs)
        _assert_same(aggs, got, want)

    def test_string_payload_group_key(self):
        probe, build = self._data()
        lookups = [LookupSpec(
            key_exprs=[col(0, new_int_field())],
            build_chunk=build, build_key_offsets=[0],
            payload_offsets=[2])]
        groups = [col(3, new_string_field())]   # the string payload
        aggs = [AggDesc(AggFunc.SUM, col(1, new_double_field())),
                AggDesc(AggFunc.MAX, col(2, new_int_field()))]
        k = MeshLookupAggKernel(_mesh(), None, lookups, groups, aggs,
                                capacity=64)
        got_gr = k(probe)
        _assert_same(aggs, got_gr,
                     host_lookup_agg(probe, None, lookups, groups, aggs))
        got = _finalize(aggs, got_gr)
        assert all(isinstance(k0[0], str) for k0, _ in got)

    def test_probe_misses_are_dropped(self):
        probe, build = self._data()
        # restrict the dimension table: keys >= 20 have no match
        small = build.filter(np.asarray(build.columns[0].data) < 20)
        lookups = [LookupSpec(key_exprs=[col(0, new_int_field())],
                              build_chunk=small, build_key_offsets=[0],
                              payload_offsets=[1])]
        groups = [col(3, new_int_field())]
        aggs = [AggDesc(AggFunc.COUNT, None)]
        k = MeshLookupAggKernel(_mesh(), None, lookups, groups, aggs,
                                capacity=64)
        got = _finalize(aggs, k(probe))
        want = _finalize(aggs, host_lookup_agg(probe, None, lookups,
                                               groups, aggs))
        assert got == want
        total = sum(v[0] for _k, v in got)
        expect = int((np.asarray(probe.columns[0].data) < 20).sum())
        assert total == expect

    def test_null_probe_keys_never_match(self):
        n = 64
        key = np.arange(n, dtype=np.int64) % 8
        valid = np.ones(n, dtype=bool)
        valid[::4] = False
        probe = Chunk([Column(new_int_field(), key, valid),
                       Column(new_double_field(), np.ones(n))])
        build = Chunk([Column(new_int_field(),
                              np.arange(8, dtype=np.int64)),
                       Column(new_int_field(),
                              np.arange(8, dtype=np.int64) * 10)])
        lookups = [LookupSpec(key_exprs=[col(0, new_int_field())],
                              build_chunk=build, build_key_offsets=[0],
                              payload_offsets=[1])]
        aggs = [AggDesc(AggFunc.COUNT, None)]
        k = MeshLookupAggKernel(_mesh(), None, lookups, [], aggs,
                                capacity=16)
        got = _finalize(aggs, k(probe))
        assert got[0][1][0] == int(valid.sum())


class TestChain:
    def test_q5_shape_two_hops(self):
        """probe -> dim1 (via fk) -> dim2 (via dim1 payload): the star
        chain; group on dim2's name, sum probe measure."""
        rng = np.random.default_rng(9)
        n = 4000
        probe = Chunk([
            Column(new_int_field(), rng.integers(0, 100, n).astype(np.int64)),
            Column(new_double_field(), rng.uniform(1, 10, n)),
        ])
        # dim1: 100 rows, fk -> dim2 (10 rows)
        dim1 = Chunk([
            Column(new_int_field(), np.arange(100, dtype=np.int64)),
            Column(new_int_field(),
                   (np.arange(100, dtype=np.int64) * 7 % 10)),
        ])
        dim2 = Chunk([
            Column(new_int_field(), np.arange(10, dtype=np.int64)),
            Column(new_string_field(),
                   np.array([f"nation{i}" for i in range(10)],
                            dtype=object)),
        ])
        lookups = [
            LookupSpec(key_exprs=[col(0, new_int_field())],
                       build_chunk=dim1, build_key_offsets=[0],
                       payload_offsets=[1]),           # virt[2] = dim1.fk
            LookupSpec(key_exprs=[col(2, new_int_field())],
                       build_chunk=dim2, build_key_offsets=[0],
                       payload_offsets=[1]),           # virt[3] = name
        ]
        groups = [col(3, new_string_field())]
        aggs = [AggDesc(AggFunc.SUM, col(1, new_double_field())),
                AggDesc(AggFunc.COUNT, None),
                AggDesc(AggFunc.AVG, col(1, new_double_field()))]
        k = MeshLookupAggKernel(_mesh(), None, lookups, groups, aggs,
                                capacity=32)
        got = k(probe)
        want = host_lookup_agg(probe, None, lookups, groups, aggs)
        _assert_same(aggs, got, want)

    def test_composite_key(self):
        rng = np.random.default_rng(2)
        n = 2000
        probe = Chunk([
            Column(new_int_field(), rng.integers(0, 6, n).astype(np.int64)),
            Column(new_int_field(), rng.integers(0, 5, n).astype(np.int64)),
            Column(new_double_field(), rng.uniform(0, 1, n)),
        ])
        a, b = np.meshgrid(np.arange(6), np.arange(5), indexing="ij")
        build = Chunk([
            Column(new_int_field(), a.ravel().astype(np.int64)),
            Column(new_int_field(), b.ravel().astype(np.int64)),
            Column(new_int_field(),
                   (a.ravel() * 10 + b.ravel()).astype(np.int64)),
        ])
        lookups = [LookupSpec(
            key_exprs=[col(0, new_int_field()), col(1, new_int_field())],
            build_chunk=build, build_key_offsets=[0, 1],
            payload_offsets=[2])]
        groups = [col(3, new_int_field())]
        aggs = [AggDesc(AggFunc.SUM, col(2, new_double_field()))]
        k = MeshLookupAggKernel(_mesh(), None, lookups, groups, aggs,
                                capacity=64)
        got = k(probe)
        want = host_lookup_agg(probe, None, lookups, groups, aggs)
        _assert_same(aggs, got, want)


class TestBuildValidation:
    def test_duplicate_build_keys_rejected(self):
        build = Chunk([Column(new_int_field(),
                              np.array([1, 1, 2], dtype=np.int64))])
        spec = LookupSpec(key_exprs=[col(0, new_int_field())],
                          build_chunk=build, build_key_offsets=[0])
        with pytest.raises(BuildError):
            MeshLookupAggKernel(_mesh(), None, [spec], [],
                                [AggDesc(AggFunc.COUNT, None)])

    def test_null_build_keys_dropped(self):
        data = np.array([1, 2, 3], dtype=np.int64)
        valid = np.array([True, False, True])
        build = Chunk([Column(new_int_field(), data, valid),
                       Column(new_int_field(), data * 10)])
        probe = Chunk([Column(new_int_field(),
                              np.array([1, 2, 3, 2], dtype=np.int64))])
        lookups = [LookupSpec(key_exprs=[col(0, new_int_field())],
                              build_chunk=build, build_key_offsets=[0],
                              payload_offsets=[1])]
        aggs = [AggDesc(AggFunc.COUNT, None)]
        k = MeshLookupAggKernel(_mesh(), None, lookups, [], aggs,
                                capacity=8)
        got = _finalize(aggs, k(probe))
        assert got[0][1][0] == 2     # rows 1 and 3 match; NULL-key row 2 not

    def test_string_build_key_rejected(self):
        build = Chunk([Column(new_string_field(),
                              np.array(["a", "b"], dtype=object))])
        spec = LookupSpec(key_exprs=[col(0, new_string_field())],
                          build_chunk=build, build_key_offsets=[0])
        with pytest.raises(BuildError):
            MeshLookupAggKernel(_mesh(), None, [spec], [],
                                [AggDesc(AggFunc.COUNT, None)])
