"""ENUM/SET column types (ref: types/enum.go, types/set.go; parser.y
EnumType/SetType). Values are stored as validated member strings
(ordering/comparison by string, a documented departure from MySQL's
member-index order)."""

import json

import pytest

from tidb_tpu.session import Session
from tidb_tpu.store.storage import new_mock_storage


@pytest.fixture
def sess():
    s = Session(new_mock_storage())
    s.execute("CREATE DATABASE d")
    s.execute("USE d")
    s.execute("CREATE TABLE t (id BIGINT PRIMARY KEY, "
              "sz ENUM('small','medium','large') NOT NULL, "
              "tags SET('a','b','c'))")
    yield s
    s.close()


class TestEnum:
    def test_insert_select_ordinal_and_ci(self, sess):
        sess.execute("INSERT INTO t VALUES (1, 'medium', NULL), "
                     "(2, 2, NULL), (3, 'LARGE', NULL)")
        rows = sess.query("SELECT id, sz FROM t ORDER BY id").rows
        # ordinal 2 resolves to the member; case-insensitive match
        # normalizes to the definition's spelling
        assert rows == [(1, "medium"), (2, "medium"), (3, "large")]

    def test_invalid_member_rejected(self, sess):
        with pytest.raises(Exception, match="invalid enum"):
            sess.execute("INSERT INTO t VALUES (9, 'gigantic', NULL)")
        with pytest.raises(Exception, match="invalid enum"):
            sess.execute("INSERT INTO t VALUES (9, 7, NULL)")

    def test_filter_group_by(self, sess):
        sess.execute("INSERT INTO t VALUES (1,'small',NULL),"
                     "(2,'small',NULL),(3,'large',NULL)")
        assert sess.query("SELECT COUNT(*) FROM t WHERE sz='small'"
                          ).rows == [(1 + 1,)]
        g = sess.query("SELECT sz, COUNT(*) FROM t GROUP BY sz "
                       "ORDER BY sz").rows
        assert g == [("large", 1), ("small", 2)]


class TestSet:
    def test_normalization(self, sess):
        sess.execute("INSERT INTO t VALUES (1, 'small', 'c,a'), "
                     "(2, 'small', ''), (3, 'small', 5), "
                     "(4, 'small', 'B,b')")
        rows = sess.query("SELECT id, tags FROM t ORDER BY id").rows
        # members dedupe and order by definition; bitmask 5 = a|c
        assert rows == [(1, "a,c"), (2, ""), (3, "a,c"), (4, "b")]

    def test_invalid_member_rejected(self, sess):
        with pytest.raises(Exception, match="invalid set"):
            sess.execute("INSERT INTO t VALUES (9, 'small', 'a,z')")
        with pytest.raises(Exception, match="invalid set"):
            sess.execute("INSERT INTO t VALUES (9, 'small', 8)")

    def test_update(self, sess):
        sess.execute("INSERT INTO t VALUES (1, 'small', 'a')")
        sess.execute("UPDATE t SET tags = 'c,b' WHERE id = 1")
        assert sess.query("SELECT tags FROM t WHERE id=1").rows == \
            [("b,c",)]


class TestSchema:
    def test_show_columns_and_json_roundtrip(self, sess):
        cols = sess.query("SHOW COLUMNS FROM t").rows
        assert any("enum('small','medium','large')" in r[1]
                   for r in cols), cols
        assert any("set('a','b','c')" in r[1] for r in cols), cols
        from tidb_tpu.schema.model import TableInfo
        info = sess.domain.info_schema().table("d", "t")
        rt = TableInfo.from_json(json.loads(info.dumps()))
        assert rt.col_by_name("sz").ft.elems == \
            ("small", "medium", "large")
        assert rt.col_by_name("tags").ft.elems == ("a", "b", "c")

    def test_survives_reload_and_index(self, sess):
        sess.execute("CREATE INDEX isz ON t (sz)")
        sess.execute("INSERT INTO t VALUES (1,'large','a'),"
                     "(2,'small','b')")
        assert sess.query("SELECT id FROM t WHERE sz = 'large'"
                          ).rows == [(1,)]
