"""JSON column type + function family (ref: types/json/binary.go,
expression/builtin_json.go) and the X-Protocol server skeleton (ref:
x-server/server.go, vestigial in the reference too)."""

import socket
import struct

import pytest

from tidb_tpu.session import Session
from tidb_tpu.store.storage import new_mock_storage


@pytest.fixture
def sess():
    s = Session(new_mock_storage())
    s.execute("CREATE DATABASE d")
    s.execute("USE d")
    s.execute("CREATE TABLE t (id BIGINT PRIMARY KEY, doc JSON)")
    s.execute("INSERT INTO t VALUES "
              '(1, \'{"a": 1, "b": [10, 20], "c": {"d": "x"}}\'), '
              "(2, '[1, 2, 3]'), (3, NULL)")
    yield s
    s.close()


def one(sess, expr, where="id=1"):
    return sess.query(f"SELECT {expr} FROM t WHERE {where}").rows[0][0]


class TestJSON:
    @pytest.mark.parametrize("expr,want", [
        ("JSON_EXTRACT(doc, '$.a')", "1"),
        ("JSON_EXTRACT(doc, '$.b[1]')", "20"),
        ("JSON_EXTRACT(doc, '$.c.d')", '"x"'),
        ("JSON_UNQUOTE(JSON_EXTRACT(doc, '$.c.d'))", "x"),
        ("JSON_EXTRACT(doc, '$.zzz')", None),
        ("JSON_EXTRACT(doc, '$.a', '$.c.d')", '[1,"x"]'),
        ("JSON_TYPE(doc)", "OBJECT"),
        ("JSON_VALID(doc)", 1),
        ("JSON_VALID('nope')", 0),
        ("JSON_LENGTH(doc)", 3),
        ("JSON_LENGTH(doc, '$.b')", 2),
        ("JSON_KEYS(doc)", '["a","b","c"]'),
        ("JSON_CONTAINS(doc, '1', '$.a')", 1),
        ("JSON_CONTAINS(doc, '5', '$.a')", 0),
        ("JSON_ARRAY(1, 'x', 2.5)", '[1,"x",2.5]'),
        ("JSON_OBJECT('k', 1)", '{"k":1}'),
    ])
    def test_value(self, sess, expr, want):
        assert one(sess, expr) == want

    def test_array_doc(self, sess):
        assert one(sess, "JSON_TYPE(doc)", "id=2") == "ARRAY"
        assert one(sess, "JSON_EXTRACT(doc, '$[2]')", "id=2") == "3"

    def test_null_and_invalid(self, sess):
        assert one(sess, "doc", "id=3") is None
        assert one(sess, "JSON_TYPE(doc)", "id=3") is None
        with pytest.raises(Exception, match="Invalid JSON"):
            sess.execute("INSERT INTO t VALUES (9, '{bad')")

    def test_canonical_storage_and_filter(self, sess):
        # stored compact; usable in WHERE through the function family
        assert one(sess, "doc", "id=2") == "[1,2,3]"
        rows = sess.query("SELECT id FROM t WHERE "
                          "JSON_VALID(doc) = 1 AND "
                          "JSON_TYPE(doc) = 'OBJECT'").rows
        assert rows == [(1,)]

    def test_show_columns(self, sess):
        cols = sess.query("SHOW COLUMNS FROM t").rows
        assert any(r[1] == "json" for r in cols), cols


class TestXServer:
    def test_capabilities_and_error(self):
        from tidb_tpu.server.xserver import XServer
        xs = XServer()
        xs.start()
        try:
            c = socket.create_connection(("127.0.0.1", xs.port),
                                         timeout=5)
            # CON_CAPABILITIES_GET -> CONN_CAPABILITIES
            c.sendall(struct.pack("<IB", 1, 1))
            ln, tp = struct.unpack("<IB", c.recv(5))
            assert tp == 2
            # any SQL-ish message -> ERROR frame
            c.sendall(struct.pack("<IB", 1, 12))
            hdr = c.recv(5)
            ln, tp = struct.unpack("<IB", hdr)
            body = c.recv(ln - 1)
            assert tp == 1 and b"not implemented" in body
            # CON_CLOSE -> OK and the server closes
            c.sendall(struct.pack("<IB", 1, 3))
            ln, tp = struct.unpack("<IB", c.recv(5))
            assert tp == 0
            c.close()
        finally:
            xs.close()


class TestJSONComposition:
    def test_nested_no_double_encode(self, sess):
        assert one(sess, "JSON_ARRAY(JSON_OBJECT('a', 1))") == '[{"a":1}]'
        assert one(sess, "JSON_EXTRACT(JSON_OBJECT('a', "
                         "JSON_ARRAY(1,2)), '$.a')") == "[1,2]"

    def test_array_containment_mysql_semantics(self, sess):
        assert one(sess, "JSON_CONTAINS('[1,2,3]', '[1,2]')") == 1
        assert one(sess, "JSON_CONTAINS('[1,2,3]', '[1,5]')") == 0
        assert one(sess, "JSON_CONTAINS('[1,2,3]', '2')") == 1


class TestJSONEdge:
    def test_decimal_into_json_is_a_number(self, sess):
        sess.execute("CREATE TABLE j (id BIGINT PRIMARY KEY, doc JSON)")
        sess.execute("INSERT INTO j VALUES (1, 1.5)")
        assert sess.query("SELECT doc FROM j").rows == [("1.5",)]

    def test_json_compares_as_text(self, sess):
        assert sess.query("SELECT id FROM t WHERE doc = '[1,2,3]'"
                          ).rows == [(2,)]

    def test_bad_path_is_clean_error(self, sess):
        with pytest.raises(Exception, match="Invalid JSON path"):
            sess.query("SELECT JSON_EXTRACT(doc, '$[*]') FROM t")


class TestEnumCIRead:
    def test_reads_match_any_member_spelling(self, sess):
        sess.execute("CREATE TABLE e (id BIGINT PRIMARY KEY, "
                     "sz ENUM('small','large'))")
        sess.execute("INSERT INTO e VALUES (1, 'LARGE')")
        for spelling in ("LARGE", "large", "Large"):
            assert sess.query("SELECT id FROM e WHERE sz = "
                              f"'{spelling}'").rows == [(1,)]
        assert sess.query("SELECT id FROM e WHERE sz = 'bogus'"
                          ).rows == []
        # IN and BETWEEN normalize members like = does
        assert sess.query("SELECT id FROM e WHERE sz IN ('LARGE')"
                          ).rows == [(1,)]
        assert sess.query("SELECT id FROM e WHERE sz BETWEEN "
                          "'Large' AND 'large'").rows == [(1,)]
