"""Journal-window RPC (mockstore/rpc.py `journal_window`, Cmd 80): the
store-plane primitive fleet cache coherence rides on. A remote SQL
server asks for the engine freshness meta plus the delta-journal window
(fill_ts, read_ts] over one region range; the reply must ship committed
row deltas, degrade to the STALE sentinel when the journal was
truncated above the fill (retention clamp: store/delta.py `_merge_table`
honors `tidb_tpu_delta_retain_ms`), and arbitrate region epochs exactly
like every other region RPC."""

import numpy as np
import pytest

from tidb_tpu import config, kv
from tidb_tpu.codec import prefix_next
from tidb_tpu.mockstore.rpc import RegionCtx
from tidb_tpu.session import Session
from tidb_tpu.store import fleetcop
from tidb_tpu.store.delta import STALE, PendingDelta
from tidb_tpu.store.remote import StorageServer, connect
from tidb_tpu.store.storage import new_mock_storage
from tidb_tpu.tablecodec import record_prefix


@pytest.fixture
def env():
    st = new_mock_storage()
    s = Session(st)
    s.execute("CREATE DATABASE d")
    s.execute("USE d")
    s.execute("CREATE TABLE t (id BIGINT PRIMARY KEY, v BIGINT)")
    s.execute("INSERT INTO t VALUES " +
              ", ".join(f"({i}, {i})" for i in range(8)))
    tid = s.domain.info_schema().table("d", "t").id
    yield st, s, tid
    s.close()
    st.close()


def _window(st, tid, fill_ts, read_ts, index_id=None):
    start = record_prefix(tid)
    loc = st.region_cache.locate(start)
    return st.shim.journal_window(loc.ctx, tid, start,
                                  prefix_next(start), fill_ts, read_ts,
                                  index_id=index_id)


class TestJournalWindowRPC:
    def test_meta_only_when_no_fill_snapshot(self, env):
        st, s, tid = env
        meta = _window(st, tid, None, st.current_ts())
        assert meta["delta"] is None
        assert meta["delta_enabled"] is True
        assert meta["data_version"] == st.engine.data_version
        assert meta["max_commit_ts"] == st.engine.max_commit_ts
        assert meta["locked"] is False

    def test_empty_window_between_writes(self, env):
        st, s, tid = env
        ts = st.current_ts()
        meta = _window(st, tid, ts, st.current_ts())
        assert meta["delta"] is None
        assert meta["delta_enabled"] is True

    def test_window_ships_committed_rows_and_deletes(self, env):
        st, s, tid = env
        fill = st.current_ts()
        s.execute("INSERT INTO t VALUES (100, 1), (101, 2)")
        s.execute("DELETE FROM t WHERE id = 0")
        meta = _window(st, tid, fill, st.current_ts())
        tag, watermark, rows, upserts, deletes = meta["delta"]
        assert tag == "win"
        assert fill < watermark <= st.current_ts()
        assert set(np.asarray(upserts).tolist()) == {100, 101}
        assert np.asarray(deletes).tolist() == [0]
        assert len(rows) == 2

    def test_truncated_journal_reports_stale(self, env):
        st, s, tid = env
        s.query("SELECT SUM(v) FROM t")          # cache fill
        fill = st.current_ts()
        s.execute("UPDATE t SET v = 9 WHERE id = 1")
        # retain 0 (the default): the merge truncates the whole journal
        assert st.delta_store.merge(trigger="rows") >= 1
        meta = _window(st, tid, fill, st.current_ts())
        assert meta["delta"] == "stale"

    def test_retention_keeps_window_across_merge(self, env):
        """The fleet coherence prerequisite: with a retention window
        configured, a merge may not truncate deltas younger than
        `tidb_tpu_delta_retain_ms` even when no LOCAL cache entry pins
        them — a remote server's fill snapshot is invisible here."""
        st, s, tid = env
        prev = config.get_var("tidb_tpu_delta_retain_ms")
        config.set_var("tidb_tpu_delta_retain_ms", 60_000)
        try:
            fill = st.current_ts()
            s.execute("INSERT INTO t VALUES (200, 5)")
            st.delta_store.merge(trigger="rows")
            meta = _window(st, tid, fill, st.current_ts())
            assert meta["delta"] is not None and \
                meta["delta"] != "stale", \
                "retained journal must still serve the window"
            assert set(np.asarray(meta["delta"][3]).tolist()) == {200}
        finally:
            config.set_var("tidb_tpu_delta_retain_ms", prev)

    def test_epoch_mismatch_raises_region_error(self, env):
        st, s, tid = env
        start = record_prefix(tid)
        loc = st.region_cache.locate(start)
        stale_ctx = RegionCtx(loc.ctx.region_id, loc.ctx.version + 1,
                              loc.ctx.conf_ver, loc.ctx.store_id)
        with pytest.raises(kv.RegionError):
            st.shim.journal_window(stale_ctx, tid, start,
                                   prefix_next(start), None,
                                   st.current_ts())

    def test_index_window_reports_staleness_flag(self, env):
        st, s, tid = env
        s.execute("CREATE INDEX iv ON t (v)")
        info = s.domain.info_schema().table("d", "t")
        idx = info.indexes[0].id
        fill = st.current_ts()
        meta = _window(st, tid, fill, st.current_ts(), index_id=idx)
        assert meta["index_stale"] is False and meta["delta"] is None
        s.execute("INSERT INTO t VALUES (300, 7)")   # index keys commit
        meta = _window(st, tid, fill, st.current_ts(), index_id=idx)
        assert meta["index_stale"] is True


class TestJournalWindowWire:
    def test_round_trip_decodes_to_pending_delta(self):
        """Over a real socket the window must arrive decodable into
        delta.py's vocabulary (tuples may become lists in transit; the
        STALE sentinel travels as the string "stale")."""
        srv = StorageServer()
        srv.start()
        st = connect("127.0.0.1", srv.port)
        s = Session(st)
        try:
            s.execute("CREATE DATABASE d")
            s.execute("USE d")
            s.execute("CREATE TABLE t (id BIGINT PRIMARY KEY, "
                      "v BIGINT)")
            s.execute("INSERT INTO t VALUES (1, 1)")
            tid = s.domain.info_schema().table("d", "t").id
            fill = st.current_ts()
            s.execute("INSERT INTO t VALUES (2, 2)")
            start = record_prefix(tid)
            loc = st.region_cache.locate(start)
            meta = st.shim.journal_window(loc.ctx, tid, start,
                                          prefix_next(start), fill,
                                          st.current_ts())
            pend = fleetcop._decode_wire_delta(meta["delta"])
            assert isinstance(pend, PendingDelta)
            assert list(pend.upsert_handles) == [2]
            assert list(pend.delete_handles) == []
            assert pend.watermark > fill
            assert fleetcop._decode_wire_delta("stale") is STALE
            assert fleetcop._decode_wire_delta(None) is None
        finally:
            s.close()
            st.close()
            srv.close()
