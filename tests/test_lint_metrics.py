"""Static metric-name invariant, enforced as a test (style of
test_lint_wire.py): every `metrics.counter(...)` / `metrics.histogram(...)`
call site inside the package passes a name CONSTANT declared in
metrics.py — never a string literal. A typo'd stringly family name would
silently fork a metric family; the registry of names in metrics.py is
the single place scrape dashboards are built against.

Checked by AST walk over every package module, so renamed imports and
f-string names can't slip through."""

import ast
import os

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PKG = os.path.join(REPO, "tidb_tpu")


def _package_files():
    for root, _dirs, files in os.walk(PKG):
        if "__pycache__" in root:
            continue
        for f in files:
            if f.endswith(".py"):
                yield os.path.join(root, f)


def _tree(path):
    with open(path) as f:
        return ast.parse(f.read(), filename=path)


def _declared_constants():
    """UPPERCASE module-level string constants of metrics.py."""
    out = {}
    for node in _tree(os.path.join(PKG, "metrics.py")).body:
        if isinstance(node, ast.Assign) and len(node.targets) == 1 and \
                isinstance(node.targets[0], ast.Name) and \
                node.targets[0].id.isupper() and \
                isinstance(node.value, ast.Constant) and \
                isinstance(node.value.value, str):
            out[node.targets[0].id] = node.value.value
    return out


def _metric_calls(tree):
    """Call nodes of the form <anything>.counter(...) / .histogram(...)
    / .gauge(...) where the receiver is the metrics module (imported as
    `metrics`)."""
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        fn = node.func
        if isinstance(fn, ast.Attribute) and \
                fn.attr in ("counter", "histogram", "gauge") and \
                isinstance(fn.value, ast.Name) and \
                fn.value.id == "metrics":
            yield node


def _name_arg(call):
    if call.args:
        return call.args[0]
    for kw in call.keywords:
        if kw.arg == "name":
            return kw.value
    return None


def test_every_metric_call_uses_a_declared_constant():
    consts = _declared_constants()
    assert consts, "metrics.py lost its name constants"
    offenders = []
    for path in _package_files():
        rel = os.path.relpath(path, REPO)
        for call in _metric_calls(_tree(path)):
            arg = _name_arg(call)
            if arg is None:
                offenders.append(f"{rel}:{call.lineno}: no name arg")
                continue
            if isinstance(arg, ast.Attribute) and \
                    isinstance(arg.value, ast.Name) and \
                    arg.value.id == "metrics" and arg.attr in consts:
                continue
            offenders.append(
                f"{rel}:{call.lineno}: metric name must be a "
                f"metrics.<CONSTANT> declared in metrics.py, got "
                f"{ast.dump(arg)[:60]}")
    assert not offenders, "\n".join(offenders)


def test_declared_names_follow_prometheus_conventions():
    for const, name in _declared_constants().items():
        assert name.startswith("tidb_tpu_"), (const, name)
        assert name == name.lower(), (const, name)
        # counters end _total, timings end _seconds, byte gauges end
        # _bytes (Prometheus idiom)
        assert name.endswith(("_total", "_seconds", "_bytes")), \
            (const, name)


def test_call_sites_exist():
    """The lint is vacuous if nothing calls metrics — pin that the
    session and coprocessor layers really emit."""
    hits = 0
    for path in _package_files():
        hits += sum(1 for _ in _metric_calls(_tree(path)))
    assert hits >= 10, hits
