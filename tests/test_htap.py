"""HTAP through the real wire protocol (ISSUE 11): a TPC-C-style
new-order/payment write mix on live connections while analytic readers
hammer the same table — the workload the MVCC delta store
(store/delta.py) exists for. The fast tests pin the wire-level
consistency contract; the full sweep (`python bench.py htap`, CI:
scripts/htap_bench.sh) rides behind the `slow` marker."""

import threading
import time

import numpy as np
import pytest

from tests.mysql_client import MiniClient, MySQLError
from tidb_tpu import metrics
from tidb_tpu.server import Server
from tidb_tpu.session import Session
from tidb_tpu.store import new_mock_storage
from tidb_tpu.table import Table, bulkload


@pytest.fixture
def env():
    storage = new_mock_storage()
    storage.async_commit_secondaries = False
    server = Server(storage, port=0)
    server.start()
    admin = MiniClient("127.0.0.1", server.port)
    admin.query("CREATE DATABASE IF NOT EXISTS htap")
    admin.use("htap")
    yield storage, server, admin
    admin.close()
    server.close()
    storage.close()


def _ints(cli, sql):
    """One wire resultset row, decoded to ints (the text protocol
    ships strings)."""
    _cols, rs = cli.query(sql)
    return tuple(None if x is None else int(x) for x in rs[0])


def _load_stock(storage, n=5000):
    s = Session(storage, db="htap")
    s.execute("CREATE TABLE stock (s_id BIGINT PRIMARY KEY, "
              "s_seg BIGINT, s_qty BIGINT, s_cnt BIGINT)")
    s.execute("CREATE TABLE orders (o_id BIGINT PRIMARY KEY, "
              "o_item BIGINT)")
    bulkload.bulk_load(storage, Table(
        s.domain.info_schema().table("htap", "stock"), storage), {
        "s_id": np.arange(n, dtype=np.int64),
        "s_seg": np.arange(n, dtype=np.int64) % 7,
        "s_qty": np.full(n, 50, dtype=np.int64),
        "s_cnt": np.zeros(n, dtype=np.int64)})
    s.close()
    return n


class TestHtapWire:
    def test_write_becomes_visible_through_cached_analytics(self, env):
        """A committed wire write is visible to the NEXT analytic read
        (freshness through the base⋈delta serve path, not a cache
        staleness window)."""
        storage, server, admin = env
        n = _load_stock(storage, n=3000)
        q = "SELECT COUNT(*), SUM(s_qty), MAX(s_cnt) FROM stock"
        assert _ints(admin, q) == (n, 50 * n, 0)
        assert _ints(admin, q) == (n, 50 * n, 0)   # warm
        wcli = MiniClient("127.0.0.1", server.port, db="htap")
        served0 = metrics.snapshot().get(metrics.CACHE_DELTA_SERVES, 0)
        for i in range(1, 6):
            wcli.query(f"UPDATE stock SET s_qty = s_qty - 1, "
                       f"s_cnt = {i} WHERE s_id = {i}")
            assert _ints(admin, q) == (n, 50 * n - i, i), \
                f"write {i} not visible to the next analytic read"
        wcli.close()
        assert metrics.snapshot().get(
            metrics.CACHE_DELTA_SERVES, 0) > served0

    @pytest.mark.slow
    def test_wire_write_mix_under_analytic_load(self, env):
        """2 writers x 2 analytic readers on live connections for a
        few hundred ops: every read is a consistent snapshot (COUNT
        never moves, SUM(s_qty) only falls as new-orders decrement),
        the final state matches the applied writes exactly, and the
        delta plane (not re-scans) served the reads."""
        storage, server, admin = env
        n = _load_stock(storage)
        q = "SELECT COUNT(*), SUM(s_qty) FROM stock"
        admin.query(q)
        admin.query(q)      # warm both cache tiers
        stop = threading.Event()
        applied = [0, 0]
        bad: list = []
        wire_errs: list = []

        def writer(w):
            cli = MiniClient("127.0.0.1", server.port, db="htap")
            k = 0
            while not stop.is_set() and k < 120:
                k += 1
                rid = (w * 2477 + k * 31) % 5000
                try:
                    cli.query(f"UPDATE stock SET s_qty = s_qty - 1 "
                              f"WHERE s_id = {rid}")
                    cli.query(f"INSERT INTO orders VALUES "
                              f"({w * 100000 + k}, {rid})")
                    applied[w] += 1
                except MySQLError as e:
                    wire_errs.append(str(e))
            cli.close()

        def reader(_r):
            cli = MiniClient("127.0.0.1", server.port, db="htap")
            prev_sum = 50 * 5000 + 1
            while not stop.is_set():
                cnt, sq = _ints(cli, q)
                if cnt != n or sq >= prev_sum + 1:
                    bad.append((cnt, sq, prev_sum))
                prev_sum = sq
                time.sleep(0.005)
            cli.close()

        rts = [threading.Thread(target=reader, args=(r,))
               for r in range(2)]
        wts = [threading.Thread(target=writer, args=(w,))
               for w in range(2)]
        for t in rts + wts:
            t.start()
        for t in wts:
            t.join(120)
        stop.set()
        for t in rts:
            t.join(30)
        assert wire_errs == []
        assert bad == [], f"inconsistent snapshots: {bad[:3]}"
        total = applied[0] + applied[1]
        assert total > 0
        assert _ints(admin, q) == (n, 50 * n - total)
        assert _ints(admin, "SELECT COUNT(*) FROM orders")[0] == total
        # a forced merge (the /shed path's fold) changes nothing
        storage.delta_store.merge(trigger="shed")
        assert _ints(admin, q) == (n, 50 * n - total)
