"""TPC-H Q1/Q3/Q5 end-to-end through the session, vs independent truth."""

from decimal import Decimal

import pytest

import tpch
from tidb_tpu.session import Session
from tidb_tpu.store.storage import new_mock_storage


@pytest.fixture(scope="module")
def sess():
    s = Session(new_mock_storage())
    s.execute("CREATE DATABASE tpch")
    s.execute("USE tpch")
    data = tpch.TpchData()
    tpch.load(s, data)
    s._data = data
    return s


def _approx(a, b, tol=1e-6):
    a = float(a) if isinstance(a, Decimal) else a
    b = float(b) if isinstance(b, Decimal) else b
    assert a == pytest.approx(b, rel=tol, abs=1e-6), (a, b)


def test_q1(sess):
    rows = sess.query(tpch.Q1).rows
    want = tpch.truth_q1(sess._data)
    assert len(rows) == len(want) == 6
    for got, exp in zip(rows, want):
        assert got[0] == exp[0] and got[1] == exp[1]
        for g, w in zip(got[2:], exp[2:]):
            _approx(g, w)


def test_q3(sess):
    rows = sess.query(tpch.Q3).rows
    want = tpch.truth_q3(sess._data)
    assert len(rows) == len(want)
    for got, exp in zip(rows, want):
        assert got[0] == exp[0], (got, exp)
        _approx(got[1], exp[1])
        assert got[2] == exp[2]
        assert got[3] == exp[3]


def test_q5(sess):
    rows = sess.query(tpch.Q5).rows
    want = tpch.truth_q5(sess._data)
    assert len(rows) == len(want)
    for got, exp in zip(rows, want):
        assert got[0] == exp[0]
        _approx(got[1], exp[1])


def test_q4(sess):
    """EXISTS-correlated subquery through the apply executor."""
    rows = sess.query(tpch.Q4).rows
    want = tpch.truth_q4(sess._data)
    assert rows == want


def test_q6(sess):
    rows = sess.query(tpch.Q6).rows
    want = tpch.truth_q6(sess._data)
    assert len(rows) == 1
    _approx(rows[0][0], want)


def test_q12(sess):
    """Shipping-mode-style two-table join with date predicates between
    columns (l_shipdate < l_commitdate < l_receiptdate)."""
    rows = sess.query(tpch.Q12).rows
    want = tpch.truth_q12(sess._data)
    assert [(r[0], r[1]) for r in rows] == want
