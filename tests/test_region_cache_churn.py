"""Region cache under churn (ref: region_cache.go:49,326 — btree lookup,
stale-overlap eviction on insert, epoch handling, leader switch)."""

import threading

import numpy as np
import pytest

from tidb_tpu.store.region_cache import RegionCache
from tidb_tpu.store.storage import new_mock_storage


@pytest.fixture
def storage():
    return new_mock_storage()


def _key(i: int) -> bytes:
    return b"k%08d" % i


class TestChurn:
    def test_thousands_of_regions_route_correctly(self, storage):
        cluster = storage.cluster
        for i in range(0, 4000, 2):
            cluster.split(_key(i))
        cache = RegionCache(cluster)
        for i in range(0, 4000, 97):
            loc = cache.locate(_key(i))
            assert loc.region.contains(_key(i))
        # cache now holds many regions; lookups stay consistent
        assert len(cache._by_start) > 20

    def test_split_evicts_stale_overlap(self, storage):
        cluster = storage.cluster
        cache = RegionCache(cluster)
        loc_before = cache.locate(_key(500))   # wide region cached
        cluster.split(_key(500))
        cluster.split(_key(600))
        # the cached wide region is now stale; a miss-path load of one
        # half must evict it so the other half doesn't route stale
        cache.invalidate(loc_before.region.id)
        mid = cache.locate(_key(550))
        assert mid.region.contains(_key(550))
        assert mid.region.start == _key(500)
        assert mid.region.end == _key(600)
        after = cache.locate(_key(650))
        assert after.region.start == _key(600)
        # no overlapping stale entries remain
        regions = list(cache._by_start.values())
        for a, b in zip(regions, regions[1:]):
            assert not a.end or a.end <= b.start

    def test_older_epoch_never_replaces_newer(self, storage):
        cluster = storage.cluster
        cache = RegionCache(cluster)
        old = cache.locate(_key(100)).region   # pre-split epoch
        cluster.split(_key(100))
        cache.invalidate(old.id)
        fresh = cache.locate(_key(100)).region
        assert (fresh.version, fresh.conf_ver) >= \
            (old.version, old.conf_ver)
        # re-inserting the stale epoch is a no-op
        cache._insert(old)
        assert cache.locate(_key(100)).region.version == fresh.version

    def test_concurrent_locate_and_split(self, storage):
        cluster = storage.cluster
        cache = RegionCache(cluster)
        stop = threading.Event()
        errors = []

        def splitter():
            rng = np.random.default_rng(7)
            for _ in range(200):
                cluster.split(_key(int(rng.integers(0, 10_000))))

        def reader():
            rng = np.random.default_rng(13)
            while not stop.is_set():
                k = _key(int(rng.integers(0, 10_000)))
                try:
                    loc = cache.locate(k)
                    if not loc.region.contains(k):
                        errors.append((k, loc.region))
                except Exception as e:   # noqa: BLE001
                    errors.append(e)

        readers = [threading.Thread(target=reader) for _ in range(4)]
        for t in readers:
            t.start()
        sp = threading.Thread(target=splitter)
        sp.start()
        sp.join()
        stop.set()
        for t in readers:
            t.join()
        assert not errors
