"""Unit tests for the whole-program concurrency analysis
(tidb_tpu/lint/flow: call graph, lock registry, flow facts) and the
three flow rules built on it (lock-order, guarded-by,
paired-resource). Synthetic forests throughout — the repo-level
assertions (all rules clean on the tree, vacuity floors) live in
tests/test_lint.py, and the runtime counterpart of lock-order is
exercised in tests/test_race_harness.py."""

from tidb_tpu.lint.engine import Forest, run
from tidb_tpu.lint.flow import flow_of
from tidb_tpu.lint.flow.lockreg import discover

A_REL = "tidb_tpu/store/a.py"
B_REL = "tidb_tpu/store/b.py"

THREADING = "import threading\n"


def lint(sources, rules=None):
    forest = Forest.from_sources(sources, root=None)
    return run(rules=rules, forest=forest, with_selfcheck=False,
               with_vacuity=False)


def forest_of(sources):
    return Forest.from_sources(sources, root=None)


def rules_of(report):
    return [f.rule for f in report.findings]


# -- lock registry ----------------------------------------------------------

def test_registry_discovers_and_names_sites():
    src = (THREADING +
           "_g = threading.Lock()\n"
           "class C:\n"
           "    def __init__(self):\n"
           "        self._mu = threading.RLock()\n"
           "        self._cv = threading.Condition()\n"
           "def f():\n"
           "    local = threading.Lock()\n"   # function-local: skipped
           "    return local\n")
    reg = discover(forest_of({A_REL: src}))
    names = {s.name: s.kind for s in reg.sites}
    assert names == {
        f"{A_REL}:_g": "Lock",
        f"{A_REL}:C._mu": "RLock",
        f"{A_REL}:C._cv": "Condition",
    }


def test_registry_resolution_policy():
    src = (THREADING +
           "_g = threading.Lock()\n"
           "class C:\n"
           "    def __init__(self):\n"
           "        self._mu = threading.Lock()\n")
    reg = discover(forest_of({A_REL: src}))
    import ast
    glob = ast.parse("_g").body[0].value
    selfmu = ast.parse("self._mu").body[0].value
    other = ast.parse("node._mu").body[0].value
    unknown = ast.parse("foo.bar").body[0].value
    assert reg.resolve(A_REL, None, glob).name == f"{A_REL}:_g"
    assert reg.resolve(A_REL, "C", selfmu).name == f"{A_REL}:C._mu"
    # receiver-typeless `node._mu`: unique class-scoped _mu in module
    assert reg.resolve(A_REL, None, other).name == f"{A_REL}:C._mu"
    assert reg.resolve(A_REL, None, unknown) is None


# -- lock-order -------------------------------------------------------------

def test_lockorder_intramodule_cycle():
    src = (THREADING +
           "_a = threading.Lock()\n"
           "_b = threading.Lock()\n"
           "def f():\n"
           "    with _a:\n"
           "        with _b:\n"
           "            pass\n"
           "def g():\n"
           "    with _b:\n"
           "        with _a:\n"
           "            pass\n")
    rep = lint({A_REL: src}, rules=["lock-order"])
    assert len(rep.findings) == 1
    assert "cycle" in rep.findings[0].message


def test_lockorder_consistent_nesting_is_clean():
    src = (THREADING +
           "_a = threading.Lock()\n"
           "_b = threading.Lock()\n"
           "def f():\n"
           "    with _a:\n"
           "        with _b:\n"
           "            pass\n"
           "def g():\n"
           "    with _a:\n"
           "        with _b:\n"
           "            pass\n")
    assert lint({A_REL: src}, rules=["lock-order"]).findings == []


def test_lockorder_interprocedural_cycle_across_modules():
    """f holds A and calls b.g, which takes B; h holds B and calls
    back into a.k, which takes A — no single function nests both
    orders, only the call graph sees the cycle."""
    a = (THREADING +
         "from tidb_tpu.store import b\n"
         "_a = threading.Lock()\n"
         "def f():\n"
         "    with _a:\n"
         "        b.g()\n"
         "def k():\n"
         "    with _a:\n"
         "        pass\n")
    b = (THREADING +
         "from tidb_tpu.store import a\n"
         "_b = threading.Lock()\n"
         "def g():\n"
         "    with _b:\n"
         "        pass\n"
         "def h():\n"
         "    with _b:\n"
         "        a.k()\n")
    rep = lint({A_REL: a, B_REL: b}, rules=["lock-order"])
    assert len(rep.findings) == 1
    assert "cycle" in rep.findings[0].message
    assert f"{A_REL}:_a" in rep.findings[0].message
    assert f"{B_REL}:_b" in rep.findings[0].message


def test_lockorder_acquire_release_sequences_count():
    src = (THREADING +
           "_a = threading.Lock()\n"
           "_b = threading.Lock()\n"
           "def f():\n"
           "    _a.acquire()\n"
           "    try:\n"
           "        _b.acquire()\n"
           "        try:\n"
           "            pass\n"
           "        finally:\n"
           "            _b.release()\n"
           "    finally:\n"
           "        _a.release()\n"
           "def g():\n"
           "    with _b:\n"
           "        with _a:\n"
           "            pass\n")
    rep = lint({A_REL: src}, rules=["lock-order"])
    assert len(rep.findings) == 1


def test_lockorder_nonreentrant_self_nesting_is_flagged():
    src = (THREADING +
           "_a = threading.Lock()\n"
           "def f():\n"
           "    with _a:\n"
           "        with _a:\n"
           "            pass\n")
    rep = lint({A_REL: src}, rules=["lock-order"])
    assert len(rep.findings) == 1
    assert "re-acquired" in rep.findings[0].message


def test_lockorder_rlock_reentrancy_is_clean():
    src = (THREADING +
           "class C:\n"
           "    def __init__(self):\n"
           "        self._mu = threading.RLock()\n"
           "    def outer(self):\n"
           "        with self._mu:\n"
           "            self.inner()\n"
           "    def inner(self):\n"
           "        with self._mu:\n"
           "            pass\n")
    assert lint({A_REL: src}, rules=["lock-order"]).findings == []


def test_lockorder_suppression_applies():
    src = (THREADING +
           "_a = threading.Lock()\n"
           "_b = threading.Lock()\n"
           "def f():\n"
           "    with _a:\n"
           "        # lint: exempt[lock-order] staged rollout, g dies next PR\n"
           "        with _b:\n"
           "            pass\n"
           "def g():\n"
           "    with _b:\n"
           "        with _a:\n"
           "            pass\n")
    rep = lint({A_REL: src}, rules=["lock-order"])
    # the cycle is reported at its first proof edge; tagging that edge
    # suppresses it (and the tag is therefore not unused)
    assert rep.findings == []


# -- guarded-by -------------------------------------------------------------

def test_guardedby_unlocked_write_flagged():
    src = (THREADING +
           "class C:\n"
           "    def __init__(self):\n"
           "        self._mu = threading.Lock()\n"
           "        self.n = 0   # guarded-by: _mu\n"
           "    def bump(self):\n"
           "        self.n += 1\n")
    rep = lint({A_REL: src}, rules=["guarded-by"])
    assert len(rep.findings) == 1
    assert "without holding" in rep.findings[0].message


def test_guardedby_locked_write_and_init_are_clean():
    src = (THREADING +
           "class C:\n"
           "    def __init__(self):\n"
           "        self._mu = threading.Lock()\n"
           "        self.n = 0   # guarded-by: _mu\n"
           "    def bump(self):\n"
           "        with self._mu:\n"
           "            self.n += 1\n")
    assert lint({A_REL: src}, rules=["guarded-by"]).findings == []


def test_guardedby_module_global_and_mutators():
    src = (THREADING +
           "_lock = threading.Lock()\n"
           "_stats = {}      # guarded-by: _lock\n"
           "def ok(k):\n"
           "    with _lock:\n"
           "        _stats[k] = 1\n"
           "        _stats.update(a=1)\n"
           "def bad(k):\n"
           "    _stats.update(b=2)\n")
    rep = lint({A_REL: src}, rules=["guarded-by"])
    assert len(rep.findings) == 1
    assert rep.findings[0].line == 9
    assert ".update" in rep.findings[0].message


def test_guardedby_tag_on_wrapped_assignment_continuation():
    """A trailing tag on the continuation line of a backslash-wrapped
    assignment binds to THAT assignment, not the next one."""
    src = (THREADING +
           "class C:\n"
           "    def __init__(self):\n"
           "        self._mu = threading.Lock()\n"
           "        self._by_start = \\\n"
           "            dict()           # guarded-by: _mu\n"
           "        self._leaders = {}\n"     # NOT annotated
           "    def bad(self):\n"
           "        self._by_start.clear()\n"
           "    def fine(self):\n"
           "        self._leaders.clear()\n")
    rep = lint({A_REL: src}, rules=["guarded-by"])
    assert len(rep.findings) == 1
    assert "_by_start" in rep.findings[0].message


def test_guardedby_caller_held_helper_is_clean():
    """A helper only ever invoked under the owner's lock checks as
    guarded without a lexical `with` (DeviceCache._drop_locked)."""
    src = (THREADING +
           "class C:\n"
           "    def __init__(self):\n"
           "        self._mu = threading.Lock()\n"
           "        self.n = 0   # guarded-by: _mu\n"
           "    def bump(self):\n"
           "        with self._mu:\n"
           "            self._bump_locked()\n"
           "    def drain(self):\n"
           "        with self._mu:\n"
           "            self._bump_locked()\n"
           "    def _bump_locked(self):\n"
           "        self.n += 1\n")
    assert lint({A_REL: src}, rules=["guarded-by"]).findings == []


def test_guardedby_helper_with_one_unlocked_caller_is_flagged():
    """caller-held is a meet over ALL call sites: one unlocked caller
    breaks the guarantee."""
    src = (THREADING +
           "class C:\n"
           "    def __init__(self):\n"
           "        self._mu = threading.Lock()\n"
           "        self.n = 0   # guarded-by: _mu\n"
           "    def bump(self):\n"
           "        with self._mu:\n"
           "            self._bump_locked()\n"
           "    def sneak(self):\n"
           "        self._bump_locked()\n"
           "    def _bump_locked(self):\n"
           "        self.n += 1\n")
    rep = lint({A_REL: src}, rules=["guarded-by"])
    assert len(rep.findings) == 1


def test_guardedby_unresolvable_lock_is_a_finding():
    src = (THREADING +
           "class C:\n"
           "    def __init__(self):\n"
           "        self._mu = threading.Lock()\n"
           "        self.n = 0   # guarded-by: _typo\n")
    rep = lint({A_REL: src}, rules=["guarded-by"])
    assert len(rep.findings) == 1
    assert "typo'd guard" in rep.findings[0].message


# -- paired-resource --------------------------------------------------------

def test_pairres_unprotected_consume_flagged():
    src = ("from tidb_tpu import memtrack\n"
           "def f(plan, rows):\n"
           "    memtrack.consume(plan, host=64)\n"
           "    return rows\n")
    rep = lint({A_REL: src}, rules=["paired-resource"])
    assert len(rep.findings) == 1
    assert "exception path" in rep.findings[0].message


def test_pairres_try_finally_release_is_clean():
    src = ("from tidb_tpu import memtrack\n"
           "def f(plan, rows):\n"
           "    memtrack.consume(plan, host=64)\n"
           "    try:\n"
           "        return rows\n"
           "    finally:\n"
           "        memtrack.release(plan, host=64)\n")
    assert lint({A_REL: src}, rules=["paired-resource"]).findings == []


def test_pairres_tracker_method_form_and_carveout():
    """tracker.consume(host=...) followed (bar trivial assignments) by
    the try whose finally releases — the sanctioned sequence shape."""
    src = ("def f(tracker, rows):\n"
           "    tracker.consume(host=64)\n"
           "    staged = 64\n"
           "    try:\n"
           "        return rows\n"
           "    finally:\n"
           "        tracker.release(host=staged)\n")
    assert lint({A_REL: src}, rules=["paired-resource"]).findings == []


def test_pairres_closure_charge_with_driver_finally_is_clean():
    """The pipeline_map shape: the charge sits in a nested closure, the
    release in the enclosing driver's finally."""
    src = ("def driver(tracker, items):\n"
           "    held = [0]\n"
           "    def stage(it):\n"
           "        tracker.consume(host=8)\n"
           "        held[0] += 8\n"
           "        return it\n"
           "    try:\n"
           "        return [stage(i) for i in items]\n"
           "    finally:\n"
           "        tracker.release(host=held[0])\n")
    assert lint({A_REL: src}, rules=["paired-resource"]).findings == []


def test_pairres_closure_charge_without_driver_finally_is_flagged():
    src = ("def driver(tracker, items):\n"
           "    def stage(it):\n"
           "        tracker.consume(host=8)\n"
           "        return it\n"
           "    return [stage(i) for i in items]\n")
    rep = lint({A_REL: src}, rules=["paired-resource"])
    assert len(rep.findings) == 1


def test_pairres_dispatch_without_finalize_flagged():
    src = ("def f(kernel, chunk):\n"
           "    tok = kernel.dispatch(chunk)\n"
           "    return tok\n")
    rep = lint({A_REL: src}, rules=["paired-resource"])
    assert len(rep.findings) == 1
    assert "finalize" in rep.findings[0].message


def test_pairres_dispatch_with_finalize_is_clean():
    src = ("def f(kernel, chunks):\n"
           "    toks = [kernel.dispatch(c) for c in chunks]\n"
           "    return [kernel.finalize(t) for t in toks]\n")
    assert lint({A_REL: src}, rules=["paired-resource"]).findings == []


def test_pairres_partition_loop_dispatch_finalize_is_clean():
    """The hybrid-join partition staging shape (ops/hybrid.py /
    HashJoinExec._hybrid_probe): dispatch in a loop over partitions,
    each charge settled by a per-task finalize whose finally releases —
    all inside one top-level function."""
    src = ("from tidb_tpu import memtrack\n"
           "def probe(kernel, parts, plan):\n"
           "    def dispatch_one(p):\n"
           "        db = kernel.dispatch_nbytes(p)\n"
           "        memtrack.consume(plan, device=db)\n"
           "        try:\n"
           "            tok = kernel.dispatch(p)\n"
           "        except BaseException:\n"
           "            memtrack.release(plan, device=db)\n"
           "            raise\n"
           "        return tok, db\n"
           "    def finalize_one(tok, db):\n"
           "        try:\n"
           "            return kernel.finalize(tok)\n"
           "        finally:\n"
           "            memtrack.release(plan, device=db)\n"
           "    out = []\n"
           "    for p in parts:\n"
           "        tok, db = dispatch_one(p)\n"
           "        out.append(finalize_one(tok, db))\n"
           "    return out\n")
    assert lint({A_REL: src}, rules=["paired-resource"]).findings == []


def test_pairres_partition_loop_without_finalize_flagged():
    """Same partition-loop shape but the dispatched tokens are dropped:
    both the abandoned futures and the closure charge with no driver
    release must be flagged."""
    src = ("from tidb_tpu import memtrack\n"
           "def probe(kernel, parts, plan):\n"
           "    toks = []\n"
           "    def dispatch_one(p):\n"
           "        memtrack.consume(plan, device=8)\n"
           "        return kernel.dispatch(p)\n"
           "    for p in parts:\n"
           "        toks.append(dispatch_one(p))\n"
           "    return toks\n")
    rep = lint({A_REL: src}, rules=["paired-resource"])
    assert len(rep.findings) == 2
    msgs = " ".join(f.message for f in rep.findings)
    assert "finalize" in msgs and "exception path" in msgs


def test_pairres_exempt_tag_for_ownership_transfer():
    src = ("def stash(tracker, cache, chunk):\n"
           "    # lint: exempt[paired-resource] residency releases on evict\n"
           "    tracker.consume(host=64)\n"
           "    cache.keep(chunk)\n")
    assert lint({A_REL: src}, rules=["paired-resource"]).findings == []


def test_pairres_plain_consume_without_ledger_kwargs_ignored():
    """Queue.consume()/iterator consume() shapes without host=/device=
    are not memtrack charges."""
    src = ("def f(q):\n"
           "    q.consume()\n"
           "    q.consume(5)\n")
    assert lint({A_REL: src}, rules=["paired-resource"]).findings == []


# -- the shared analysis ----------------------------------------------------

def test_flow_is_memoized_per_forest():
    forest = forest_of({A_REL: THREADING + "_a = threading.Lock()\n"})
    assert flow_of(forest) is flow_of(forest)


def test_callgraph_resolves_self_method_and_import():
    a = ("from tidb_tpu.store import b\n"
         "class C:\n"
         "    def f(self):\n"
         "        self.g()\n"
         "        b.top()\n"
         "    def g(self):\n"
         "        pass\n")
    b = "def top():\n    pass\n"
    fl = flow_of(forest_of({A_REL: a, B_REL: b}))
    facts = fl.facts[(A_REL, "C.f")]
    callees = {cs.callee.key for cs in facts.calls if cs.callee}
    assert (A_REL, "C.g") in callees
    assert (B_REL, "top") in callees


def test_dag_export_shape():
    src = (THREADING +
           "_a = threading.Lock()\n"
           "_b = threading.Lock()\n"
           "def f():\n"
           "    with _a:\n"
           "        with _b:\n"
           "            pass\n")
    dag = flow_of(forest_of({A_REL: src})).dag_export()
    assert (f"{A_REL}:_a", f"{A_REL}:_b") in dag["edges"]
    assert dag["kinds"][f"{A_REL}:_a"] == "Lock"
    assert dag["sites"][(A_REL, 2)] == (f"{A_REL}:_a", "Lock")