"""Typed wire codec (store/wire.py; ref: tikvrpc.go:31-53 CmdType +
kvproto's closed protobuf contract). Round-trips every registered type
and fuzzes the decoder: malformed frames must raise WireError, never
crash, hang, or execute anything."""

import random
import struct
from decimal import Decimal

import numpy as np
import pytest

from tidb_tpu import kv
from tidb_tpu.chunk import Chunk, Column
from tidb_tpu.expression.core import ColumnRef, Constant, Op, func
from tidb_tpu.mockstore.cluster import Region
from tidb_tpu.mockstore.rpc import RegionCtx, TimeoutError_
from tidb_tpu.sqltypes import (FieldType, TypeCode, new_double_field,
                               new_int_field, new_string_field)
from tidb_tpu.store import wire


def rt(v):
    return wire.decode(wire.encode(v))


class TestScalars:
    @pytest.mark.parametrize("v", [
        None, True, False, 0, 1, -1, 63, -64, 2**62, -(2**62),
        2**63 - 1, -(2**63), 2**100, -(2**200),   # bigint lane
        0.0, -1.5, 3.141592653589793, float("inf"),
        b"", b"\x00\xff" * 100, "", "héllo wörld", "a" * 10000,
        Decimal("123456789012345678901234567890.1234567890"),
        Decimal("-0.001"),
    ])
    def test_round_trip(self, v):
        got = rt(v)
        assert got == v and type(got) is type(v)

    def test_nan(self):
        got = rt(float("nan"))
        assert got != got

    def test_numpy_scalars_become_python(self):
        assert rt(np.int64(7)) == 7
        assert rt(np.float64(2.5)) == 2.5
        assert rt(np.bool_(True)) is True


class TestContainers:
    def test_nested(self):
        v = {"a": [1, (2, b"x"), {"k": None}], b"raw": (True,)}
        assert rt(v) == v

    def test_tuple_vs_list_preserved(self):
        assert isinstance(rt((1, 2)), tuple)
        assert isinstance(rt([1, 2]), list)

    def test_ndarray_lanes(self):
        for dt in (np.int64, np.float64, np.int32, np.float32,
                   np.uint8, np.uint64):
            a = np.arange(17).astype(dt)
            b = rt(a)
            assert b.dtype == a.dtype and np.array_equal(a, b)
        m = rt(np.array([True, False, True]))
        assert m.dtype == np.bool_ and m.tolist() == [True, False, True]

    def test_object_array(self):
        a = np.array(["x", None, b"y", 3], dtype=object)
        b = rt(a)
        assert b.dtype == object and list(b) == ["x", None, b"y", 3]

    def test_unregistered_type_rejected(self):
        class Foo:
            pass
        with pytest.raises(wire.WireError):
            wire.encode(Foo())


class TestStructs:
    def test_kv_structs(self):
        m = kv.Mutation(kv.MutationOp.PUT, b"k", b"v")
        got = rt(m)
        assert got == m
        rng = kv.KVRange(b"a", b"z")
        assert rt(rng) == rng
        li = kv.LockInfo(b"p", 7, b"k", 2500)
        got = rt(li)
        assert got == li

    def test_region(self):
        r = Region(id=3, start=b"a", end=b"q", version=2, conf_ver=1,
                   leader_store=1, peer_stores=(1, 2))
        got = rt(r)
        assert got == r and got.peer_stores == (1, 2)

    def test_region_ctx(self):
        c = RegionCtx(1, 2, 3, 4)
        got = rt(c)
        assert (got.region_id, got.version, got.conf_ver, got.store_id) \
            == (1, 2, 3, 4)

    def test_field_type(self):
        ft = FieldType(TypeCode.NEWDECIMAL, flags=1, flen=10, frac=2)
        assert rt(ft) == ft

    def test_expression_tree(self):
        e = func(Op.AND,
                 func(Op.GT, ColumnRef(0, new_int_field(), "a"),
                      Constant(5, new_int_field())),
                 func(Op.LT, ColumnRef(1, new_double_field(), "b"),
                      Constant(2.5, new_double_field())))
        got = rt(e)
        assert repr(got) == repr(e)
        cols = [(np.array([1, 10]), np.ones(2, bool)),
                (np.array([1.0, 2.0]), np.ones(2, bool))]
        d1, v1 = e.eval_xp(np, cols, 2)
        d2, v2 = got.eval_xp(np, cols, 2)
        assert np.array_equal(d1, d2) and np.array_equal(v1, v2)

    def test_generic_builtin_crosses_by_name(self):
        from tidb_tpu.expression.builtins import lookup
        spec = lookup("LPAD")
        e = func(Op.GENERIC, Constant("x", new_string_field()),
                 Constant(3, new_int_field()),
                 Constant("*", new_string_field()), extra=spec)
        got = rt(e)
        assert got.extra is spec      # rehydrated from the registry

    def test_chunk_columns_ride_as_buffers(self):
        c1 = Column(new_int_field(), np.arange(5),
                    np.array([1, 1, 0, 1, 1], bool))
        c2 = Column(new_string_field(),
                    np.array(["a", "b", "", "d", "e"], dtype=object),
                    np.ones(5, bool))
        ch = Chunk([c1, c2])
        got = rt(ch)
        assert got.num_rows == 5
        assert np.array_equal(got.columns[0].data, c1.data)
        assert np.array_equal(got.columns[0].valid, c1.valid)
        assert list(got.columns[1].data) == list(c2.data)


class TestErrors:
    @pytest.mark.parametrize("e", [
        kv.KVError("boom"),
        kv.NotFoundError("nope"),
        kv.ServerBusyError("busy"),
        kv.NotLeaderError(3, 2),
        kv.EpochNotMatchError(5),
        kv.WriteConflictError(b"k", 10, 20),
        kv.KeyLockedError(kv.LockInfo(b"p", 9, b"k", 100)),
        TimeoutError_("mid-flight"),
    ])
    def test_round_trip(self, e):
        got = rt(e)
        assert type(got) is type(e)
        assert str(got) == str(e)

    def test_lock_error_carries_lock(self):
        got = rt(kv.KeyLockedError(kv.LockInfo(b"p", 9, b"k", 100)))
        assert got.lock.primary == b"p" and got.lock.start_ts == 9

    def test_unregistered_exception_degrades(self):
        got = rt(ValueError("odd"))
        assert type(got) is kv.KVError and "ValueError" in str(got)


class TestFuzz:
    def test_truncations_rejected(self):
        payload = wire.encode({"k": [1, "two", b"three",
                                     np.arange(4)]})
        for cut in range(len(payload)):
            with pytest.raises(wire.WireError):
                wire.decode(payload[:cut])

    def test_random_mutations_never_crash(self):
        rnd = random.Random(42)
        base = wire.encode(
            (int(wire.Cmd.KV_GET),
             (RegionCtx(1, 1, 1, 1), b"key", 99), {}))
        for _ in range(3000):
            buf = bytearray(base)
            for _ in range(rnd.randint(1, 6)):
                buf[rnd.randrange(len(buf))] = rnd.randrange(256)
            try:
                wire.decode(bytes(buf))
            except wire.WireError:
                pass    # rejection is the contract
            # anything else (crash/hang/other exception) fails the test

    def test_random_garbage_never_crashes(self):
        rnd = random.Random(7)
        for _ in range(2000):
            n = rnd.randint(0, 64)
            buf = bytes(rnd.randrange(256) for _ in range(n))
            try:
                wire.decode(buf)
            except wire.WireError:
                pass

    def test_huge_declared_lengths_rejected(self):
        # LIST claiming 2^40 elements on a tiny buffer
        evil = bytes([7]) + b"\x80\x80\x80\x80\x80\x20"
        with pytest.raises(wire.WireError):
            wire.decode(evil)
        # NDARRAY claiming huge length
        evil = bytes([10, 0]) + b"\xff\xff\xff\xff\x0f" + b"xx"
        with pytest.raises(wire.WireError):
            wire.decode(evil)

    def test_depth_bomb_rejected(self):
        payload = wire.encode(0)
        for _ in range(100):
            payload = bytes([7]) + b"\x01" + payload   # LIST[1 x ...]
        with pytest.raises(wire.WireError):
            wire.decode(payload)

    def test_unknown_ids_rejected(self):
        with pytest.raises(wire.WireError):
            wire.decode(bytes([12]) + struct.pack("<H", 999) + b"\x00")
        with pytest.raises(wire.WireError):
            wire.decode(bytes([13]) + struct.pack("<H", 999) + b"\x00")
        with pytest.raises(wire.WireError):
            wire.decode(bytes([14]) + struct.pack("<H", 999) + b"\x00")
        with pytest.raises(wire.WireError):
            wire.decode(bytes([255]))

    def test_trailing_bytes_rejected(self):
        with pytest.raises(wire.WireError):
            wire.decode(wire.encode(1) + b"\x00")

    # (the no-pickle invariant lives in the `wire-discipline` lint rule
    # — tidb_tpu/lint, run by tests/test_lint.py — which checks the
    # whole wire path by AST walk instead of substring grep)


class TestStreamWire:
    """Multi-frame streamed replies (Cmd.COP_STREAM): the credit
    protocol's state machines must reject every malformed sequence
    LOUDLY — truncated frames, frames after end, credit violations,
    interleaved non-stream statuses — and can never deadlock (both
    machines are synchronous; rejection is an exception, not a wait)."""

    def _frame(self, last=False, start=b"a", end=b"b"):
        from tidb_tpu.store.stream import StreamFrame
        c1 = Column(new_int_field(), np.arange(4),
                    np.ones(4, bool))
        return StreamFrame(Chunk([c1]), kv.KVRange(start, end), last)

    def test_stream_frame_round_trip(self):
        f = self._frame(last=True)
        got = wire.decode(wire.encode(f))
        assert type(got) is type(f)
        assert got.last is True
        assert got.range == kv.KVRange(b"a", b"b")
        assert np.array_equal(got.chunk.columns[0].data,
                              f.chunk.columns[0].data)
        empty = wire.decode(wire.encode(
            self._frame().__class__(None, kv.KVRange(b"x", b"y"), False)))
        assert empty.chunk is None and not empty.last

    def test_stream_interrupted_error_round_trip(self):
        got = wire.decode(wire.encode(kv.StreamInterruptedError("mid")))
        assert type(got) is kv.StreamInterruptedError

    def test_truncated_stream_frames_rejected(self):
        payload = wire.encode(self._frame())
        r = wire.StreamReader(4)
        for cut in range(len(payload)):
            with pytest.raises(wire.WireError):
                wire.StreamReader(4).feed(wire.STATUS_STREAM_FRAME,
                                          payload[:cut])
        # the intact payload still feeds fine afterwards
        kind, frame = r.feed(wire.STATUS_STREAM_FRAME, payload)
        assert kind == "frame" and frame.range.start == b"a"

    def test_frame_after_end_rejected(self):
        r = wire.StreamReader(4)
        assert r.feed(wire.STATUS_STREAM_END, wire.encode(None)) == \
            ("end", None)
        with pytest.raises(wire.WireError):
            r.feed(wire.STATUS_STREAM_FRAME, wire.encode(self._frame()))

    def test_credit_violation_fails_loudly(self):
        r = wire.StreamReader(2)
        payload = wire.encode(self._frame())
        r.feed(wire.STATUS_STREAM_FRAME, payload)
        r.feed(wire.STATUS_STREAM_FRAME, payload)
        # third frame without a grant: the peer ignored backpressure
        with pytest.raises(wire.WireError, match="credit violation"):
            r.feed(wire.STATUS_STREAM_FRAME, payload)
        # granting reopens the window on a fresh reader
        r2 = wire.StreamReader(1)
        r2.feed(wire.STATUS_STREAM_FRAME, payload)
        r2.grant(1)
        kind, _ = r2.feed(wire.STATUS_STREAM_FRAME, payload)
        assert kind == "frame"

    def test_interleaved_plain_reply_rejected(self):
        """A non-stream status mid-stream = two replies interleaved on
        one connection: reject, never misparse."""
        r = wire.StreamReader(4)
        for status in (wire.STATUS_OK, wire.STATUS_OK_TRACED,
                       wire.STATUS_CREDIT, 99):
            with pytest.raises(wire.WireError):
                wire.StreamReader(4).feed(status, wire.encode(1))
        assert r.feed(wire.STATUS_STREAM_END, wire.encode(None))[0] == \
            "end"

    def test_non_streamframe_payload_rejected(self):
        with pytest.raises(wire.WireError, match="StreamFrame"):
            wire.StreamReader(4).feed(wire.STATUS_STREAM_FRAME,
                                      wire.encode({"not": "a frame"}))

    def test_malformed_frame_fields_rejected(self):
        """The struct codec will happily encode None/str into any
        field; the reader must reject shapes the consumer would
        dereference (range=None was an AttributeError in the resume
        path, not a WireError, before this check)."""
        from tidb_tpu.store.stream import StreamFrame
        bad = [
            StreamFrame(None, None, False),                 # range=None
            StreamFrame(None, kv.KVRange(b"a", None), True),
            StreamFrame(None, kv.KVRange(None, b"b"), False),
            StreamFrame(None, kv.KVRange(b"a", b"b"), None),  # last=None
        ]
        for f in bad:
            with pytest.raises(wire.WireError, match="malformed"):
                wire.StreamReader(4).feed(wire.STATUS_STREAM_FRAME,
                                          wire.encode(f))

    def test_typed_error_terminates_stream(self):
        r = wire.StreamReader(4)
        with pytest.raises(kv.ServerBusyError):
            r.feed(wire.STATUS_ERR, wire.encode(kv.ServerBusyError("b")))
        assert r.done

    def test_credit_gate_validates_grants(self):
        g = wire.CreditGate(2)
        g.consume()
        g.consume()
        with pytest.raises(wire.WireError):
            g.consume()                      # window exhausted
        with pytest.raises(wire.WireError):
            g.feed_grant(wire.STATUS_OK, wire.encode(1))
        with pytest.raises(wire.WireError):
            g.feed_grant(wire.STATUS_CREDIT, wire.encode(0))
        with pytest.raises(wire.WireError):
            g.feed_grant(wire.STATUS_CREDIT, wire.encode(-3))
        with pytest.raises(wire.WireError):
            g.feed_grant(wire.STATUS_CREDIT, wire.encode("lots"))
        with pytest.raises(wire.WireError):
            g.feed_grant(wire.STATUS_CREDIT, b"\xff\xff")   # truncated
        g.feed_grant(wire.STATUS_CREDIT, wire.encode(1))
        g.consume()
        assert g.sent == 3 and g.received == 1 and g.outstanding == 2

    def test_bad_credit_windows_rejected(self):
        for bad in (0, -1, wire.MAX_STREAM_CREDIT + 1):
            with pytest.raises(wire.WireError):
                wire.StreamReader(bad)
        for bad in (0, -1, True, "4", None, 1 << 40):
            with pytest.raises(wire.WireError):
                wire.CreditGate(bad)

    def test_fuzzed_stream_frames_never_crash(self):
        rnd = random.Random(99)
        base = wire.encode(self._frame())
        for _ in range(2000):
            buf = bytearray(base)
            for _ in range(rnd.randint(1, 6)):
                buf[rnd.randrange(len(buf))] = rnd.randrange(256)
            r = wire.StreamReader(4)
            try:
                r.feed(wire.STATUS_STREAM_FRAME, bytes(buf))
            except wire.WireError:
                pass    # rejection is the contract
            # anything else (crash/hang/other exception) fails the test
