"""UNION as a real chunk operator, ADMIN CHECK TABLE index-consistency
scans, and INFORMATION_SCHEMA virtual tables."""

import numpy as np
import pytest

from tidb_tpu import codec, tablecodec
from tidb_tpu.session import Session, SQLError
from tidb_tpu.store.storage import new_mock_storage


@pytest.fixture
def sess():
    st = new_mock_storage()
    s = Session(st)
    s.execute("CREATE DATABASE d")
    s.execute("USE d")
    yield s
    s.close()


class TestUnionExec:
    def _setup(self, sess):
        sess.execute("CREATE TABLE a (id BIGINT PRIMARY KEY, v BIGINT)")
        sess.execute("CREATE TABLE b (id BIGINT PRIMARY KEY, v BIGINT)")
        sess.execute("INSERT INTO a VALUES (1,10),(2,20),(3,30)")
        sess.execute("INSERT INTO b VALUES (7,20),(8,30),(9,40)")

    def test_union_all_and_distinct(self, sess):
        self._setup(sess)
        txt = sess.plan("SELECT v FROM a UNION ALL SELECT v FROM b"
                        ).explain()
        assert "Union" in txt, txt
        r = sess.query("SELECT v FROM a UNION ALL SELECT v FROM b")
        assert sorted(x[0] for x in r.rows) == [10, 20, 20, 30, 30, 40]
        r2 = sess.query("SELECT v FROM a UNION SELECT v FROM b")
        assert sorted(x[0] for x in r2.rows) == [10, 20, 30, 40]
        # DISTINCT runs through HashAgg, not a python set
        t2 = sess.plan("SELECT v FROM a UNION SELECT v FROM b").explain()
        assert "HashAgg" in t2, t2

    def test_union_order_limit(self, sess):
        self._setup(sess)
        r = sess.query("SELECT v FROM a UNION ALL SELECT v FROM b "
                       "ORDER BY v DESC LIMIT 3")
        assert [x[0] for x in r.rows] == [40, 30, 30]

    def test_mixed_all_distinct_mysql_rule(self, sess):
        self._setup(sess)
        # the DISTINCT union dedups everything to its left; the trailing
        # ALL branch appends raw
        r = sess.query("SELECT v FROM a UNION SELECT v FROM b "
                       "UNION ALL SELECT v FROM b")
        got = sorted(x[0] for x in r.rows)
        assert got == [10, 20, 20, 30, 30, 40, 40]

    def test_type_widening(self, sess):
        sess.execute("CREATE TABLE c (id BIGINT PRIMARY KEY, "
                     "d DECIMAL(8,2))")
        sess.execute("INSERT INTO c VALUES (1, 1.50)")
        self._setup(sess)
        r = sess.query("SELECT v FROM a UNION ALL SELECT d FROM c")
        vals = sorted(float(x[0]) for x in r.rows)
        assert vals == [1.5, 10.0, 20.0, 30.0]

    def test_union_large_cardinality(self, sess):
        from tidb_tpu.table import Table, bulkload
        sess.execute("CREATE TABLE big1 (id BIGINT PRIMARY KEY, "
                     "v BIGINT)")
        sess.execute("CREATE TABLE big2 (id BIGINT PRIMARY KEY, "
                     "v BIGINT)")
        n = 30000
        for name, off in (("big1", 0), ("big2", n // 2)):
            tbl = Table(sess.domain.info_schema().table("d", name),
                        sess.storage)
            bulkload.bulk_load(sess.storage, tbl, {
                "id": np.arange(n, dtype=np.int64),
                "v": np.arange(off, off + n, dtype=np.int64)})
        r = sess.query("SELECT COUNT(*) FROM (SELECT v FROM big1 UNION "
                       "SELECT v FROM big2) u")
        assert r.rows[0][0] == n + n // 2


class TestAdminCheck:
    def test_consistent_table_passes(self, sess):
        sess.execute("CREATE TABLE t (id BIGINT PRIMARY KEY, k BIGINT)")
        sess.execute("CREATE INDEX ik ON t (k)")
        sess.execute("INSERT INTO t VALUES " + ",".join(
            f"({i},{i % 5})" for i in range(200)))
        r = sess.execute("ADMIN CHECK TABLE t")[0]
        assert r.rows == [("check passed",)]

    def test_missing_index_entry_detected(self, sess):
        sess.execute("CREATE TABLE t (id BIGINT PRIMARY KEY, k BIGINT)")
        sess.execute("CREATE INDEX ik ON t (k)")
        sess.execute("INSERT INTO t VALUES (1, 7), (2, 8)")
        info = sess.domain.info_schema().table("d", "t")
        idx = info.indexes[0]
        # surgically delete one index entry behind SQL's back
        ik = tablecodec.index_key(info.id, idx.id, [7], handle=1)
        txn = sess.storage.begin()
        txn.delete(ik)
        txn.commit()
        sess.storage.chunk_cache.clear()
        with pytest.raises(SQLError, match="admin check"):
            sess.execute("ADMIN CHECK TABLE t")

    def test_dangling_index_entry_detected(self, sess):
        sess.execute("CREATE TABLE t (id BIGINT PRIMARY KEY, k BIGINT)")
        sess.execute("CREATE INDEX ik ON t (k)")
        sess.execute("INSERT INTO t VALUES (1, 7)")
        info = sess.domain.info_schema().table("d", "t")
        idx = info.indexes[0]
        ik = tablecodec.index_key(info.id, idx.id, [9], handle=99)
        txn = sess.storage.begin()
        txn.set(ik, b"0")
        txn.commit()
        with pytest.raises(SQLError, match="admin check"):
            sess.execute("ADMIN CHECK TABLE t")

    def test_admin_show_ddl(self, sess):
        r = sess.execute("ADMIN SHOW DDL")[0]
        assert r.columns[0] == "SCHEMA_VER"
        assert r.rows[0][0] >= 1


class TestInformationSchema:
    def test_schemata_tables_columns(self, sess):
        sess.execute("CREATE TABLE t (id BIGINT PRIMARY KEY, "
                     "name VARCHAR(20), amt DECIMAL(10,2))")
        sess.execute("CREATE INDEX iname ON t (name)")
        r = sess.query("SELECT schema_name FROM "
                       "information_schema.schemata ORDER BY schema_name")
        names = [x[0] for x in r.rows]
        assert "d" in names and "information_schema" in names
        r2 = sess.query(
            "SELECT table_name FROM information_schema.tables "
            "WHERE table_schema = 'd'")
        assert [x[0] for x in r2.rows] == ["t"]
        r3 = sess.query(
            "SELECT column_name, data_type, column_key FROM "
            "information_schema.columns WHERE table_name = 't' "
            "ORDER BY ordinal_position")
        assert r3.rows == [("id", "bigint", "PRI"),
                           ("name", "varchar", ""),
                           ("amt", "decimal", "")]
        r4 = sess.query(
            "SELECT index_name, column_name FROM "
            "information_schema.statistics WHERE table_name = 't' "
            "AND index_name <> 'PRIMARY'")
        assert r4.rows == [("iname", "name")]

    def test_use_and_world_readable(self, sess):
        from tidb_tpu.bootstrap import bootstrap
        bootstrap(sess.storage)
        sess.execute("CREATE USER nobody")
        nb = Session(sess.storage, user="nobody", host="h")
        nb.execute("USE information_schema")
        r = nb.query("SELECT COUNT(*) FROM schemata")
        assert r.rows[0][0] >= 2
        nb.close()

    def test_unknown_memtable_errors(self, sess):
        with pytest.raises(SQLError, match="information_schema"):
            sess.query("SELECT * FROM information_schema.nope")


class TestReviewRegressions:
    def test_parenthesized_union_branch(self, sess):
        sess.execute("CREATE TABLE a (id BIGINT PRIMARY KEY, v BIGINT)")
        sess.execute("INSERT INTO a VALUES (1,10),(2,20)")
        r = sess.query("(SELECT v FROM a UNION SELECT v FROM a) "
                       "UNION ALL SELECT v FROM a")
        assert sorted(x[0] for x in r.rows) == [10, 10, 20, 20]

    def test_parenthesized_branch_keeps_its_limit(self, sess):
        sess.execute("CREATE TABLE a (id BIGINT PRIMARY KEY, v BIGINT)")
        sess.execute("INSERT INTO a VALUES (1,10),(2,20)")
        r = sess.query("SELECT v FROM a UNION ALL "
                       "(SELECT v FROM a ORDER BY v DESC LIMIT 1)")
        assert sorted(x[0] for x in r.rows) == [10, 20, 20]

    def test_mixed_string_numeric_union(self, sess):
        r = sess.query("SELECT 1 UNION ALL SELECT 'abc'")
        assert sorted(str(x[0]) for x in r.rows) == ["1", "abc"]

    def test_show_tables_in_information_schema(self, sess):
        sess.execute("USE information_schema")
        r = sess.query("SHOW TABLES")
        assert ("tables",) in r.rows and ("columns",) in r.rows
        with pytest.raises(SQLError):
            sess.query("SHOW TABLES FROM no_such_db")

    def test_stale_value_index_entry_detected(self, sess):
        sess.execute("USE d")
        sess.execute("CREATE TABLE t (id BIGINT PRIMARY KEY, k BIGINT)")
        sess.execute("CREATE INDEX ik ON t (k)")
        sess.execute("INSERT INTO t VALUES (1, 7)")
        info = sess.domain.info_schema().table("d", "t")
        idx = info.indexes[0]
        # swap the index entry for a stale value: counts still match
        txn = sess.storage.begin()
        txn.delete(tablecodec.index_key(info.id, idx.id, [7], handle=1))
        txn.set(tablecodec.index_key(info.id, idx.id, [8], handle=1), b"0")
        txn.commit()
        sess.storage.chunk_cache.clear()
        with pytest.raises(SQLError, match="admin check"):
            sess.execute("ADMIN CHECK TABLE t")


class TestAdminShowDDLJobs:
    def test_history_listed(self, sess):
        sess.execute("CREATE TABLE jt (id BIGINT PRIMARY KEY)")
        rs = sess.query("ADMIN SHOW DDL JOBS")
        assert rs.columns[:2] == ["JOB_ID", "JOB_TYPE"]
        hist = [r for r in rs.rows if r[6] == "history"]
        assert any(r[1] == "create table" for r in hist)
        assert all(r[4] == "done" for r in hist)
