"""Kernel profiling plane (tidb_tpu/profiler.py): the bounded
per-(family, fingerprint, mesh) registry, its memtrack billing + shed
drain, mesh-aware keying at plane sizes 1 and 8, the roofline
estimator, the EXPLAIN ANALYZE / information_schema surfaces, the
per-digest mode-history memo, and the disarmed fast path's overhead
budget."""

import time

import pytest

import tpch
from tidb_tpu import config, devplane, memtrack, perfschema, profiler, sched
from tidb_tpu.session import Session
from tidb_tpu.store.storage import new_mock_storage

_ENTRY = profiler._ENTRY_BYTES


@pytest.fixture(autouse=True)
def _clean_registry():
    profiler.reset_for_tests()
    yield
    profiler.reset_for_tests()


class TestRegistry:
    def test_same_key_returns_same_entry(self):
        a = profiler.profile("hashagg", "fp-1")
        b = profiler.profile("hashagg", "fp-1")
        assert a is b
        assert profiler.profile("hashagg", "fp-2") is not a
        assert profiler.profile("streamagg", "fp-1") is not a

    def test_compile_vs_reuse_attribution(self):
        prof = profiler.profile("hashagg", "fp-c")
        profiler.note_construct(prof, reuse=False)
        # the first dispatch of a fresh entry is the compile dispatch
        profiler.note_dispatch(prof, 5_000, nbytes=1024)
        profiler.note_dispatch(prof, 1_000, nbytes=1024)
        profiler.note_construct(prof, reuse=True)
        d = prof.to_dict()
        assert d["compiles"] == 1 and d["reuses"] == 1
        assert d["dispatches"] == 2
        assert d["compile_ns"] == 5_000       # only the compile dispatch
        assert d["busy_ns"] == 6_000
        assert d["bytes_in"] == 2048
        assert d["compile_cache"] in ("hit", "miss", "cached")

    def test_precompiled_executable_attributes_reuse(self):
        # a dispatch through a profile row that never witnessed the
        # compile (the executable predates the row — e.g. the registry
        # was shed and the kernel re-registered) attributes "reuse"
        prof = profiler.profile("hashagg", "fp-r")
        profiler.note_construct(prof, reuse=True)
        profiler.note_dispatch(prof, 1_000, nbytes=512)
        d = prof.to_dict()
        assert d["compiles"] == 0 and d["compile_ns"] == 0
        assert d["compile_cache"] == "reuse"
        # a later real compile overwrites the placeholder
        profiler.note_construct(prof, reuse=False)
        profiler.note_dispatch(prof, 2_000, nbytes=512)
        assert prof.to_dict()["compile_cache"] in ("hit", "miss", "cached")

    def test_escalations_and_fallback_reasons(self):
        prof = profiler.profile("fragment", "fp-e")
        profiler.note_escalation(prof)
        profiler.note_kernel_fallback(prof, "capacity")
        profiler.note_kernel_fallback(prof, "capacity")
        profiler.note_kernel_fallback(prof, "unsupported")
        d = prof.to_dict()
        assert d["escalations"] == 1
        assert d["fallbacks"] == 3
        assert d["fallback_reasons"] == {"capacity": 2, "unsupported": 1}

    def test_long_fingerprints_are_bounded(self):
        prof = profiler.profile("mesh", "x" * 500)
        assert len(prof.fingerprint) == 16

    def test_lru_bound_and_eviction(self):
        old = config.get_var("tidb_tpu_kernel_profile_cap")
        config.set_var("tidb_tpu_kernel_profile_cap", 16)
        try:
            for i in range(24):
                profiler.profile("hashagg", f"fp-{i}")
            reg = profiler.registry()
            assert len(reg) == 16
            st = reg.stats()
            assert st["evictions"] == 8 and st["cap"] == 16
            # LRU, not FIFO: the oldest surviving entries are the
            # youngest 16 created
            fps = {p["fingerprint"] for p in profiler.snapshot()}
            assert fps == {f"fp-{i}" for i in range(8, 24)}
        finally:
            config.set_var("tidb_tpu_kernel_profile_cap", old)

    def test_disabled_profiling_returns_none(self):
        old = config.get_var("tidb_tpu_kernel_profile")
        config.set_var("tidb_tpu_kernel_profile", 0)
        try:
            assert profiler.profile("hashagg", "fp") is None
            # every note_* is None-tolerant (call sites stay unguarded)
            profiler.note_construct(None, reuse=True)
            profiler.note_dispatch(None, 100)
            profiler.note_busy(None, 100)
            profiler.note_bytes(None, nbytes=10)
            profiler.note_escalation(None)
            profiler.note_kernel_fallback(None, "x")
            with profiler.dispatch_section(None, nbytes=1):
                pass
            assert not profiler.stats()["enabled"]
        finally:
            config.set_var("tidb_tpu_kernel_profile", old)

    def test_dispatch_section_success_only(self):
        prof = profiler.profile("hashagg", "fp-s")
        with pytest.raises(ValueError):
            with profiler.dispatch_section(prof, nbytes=512):
                raise ValueError("dispatch blew up")
        assert prof.to_dict()["dispatches"] == 0
        with profiler.dispatch_section(prof, nbytes=512) as sec:
            sec.out_nbytes = 64
        d = prof.to_dict()
        assert d["dispatches"] == 1 and d["bytes_out"] == 64


class TestMemtrackBilling:
    pytestmark = pytest.mark.usefixtures("ledger_hygiene")

    def test_entries_billed_and_clear_releases(self):
        reg = profiler.registry()
        node = reg._billing_node()
        base = node.host
        for i in range(10):
            profiler.profile("hashagg", f"bill-{i}")
        assert node.host == base + 10 * _ENTRY
        reg.clear()
        assert node.host == base

    def test_eviction_releases_bytes(self):
        old = config.get_var("tidb_tpu_kernel_profile_cap")
        config.set_var("tidb_tpu_kernel_profile_cap", 16)
        try:
            node = profiler.registry()._billing_node()
            base = node.host
            for i in range(40):
                profiler.profile("hashagg", f"ev-{i}")
            # evicted entries gave their bytes back: only cap remain
            assert node.host == base + 16 * _ENTRY
        finally:
            config.set_var("tidb_tpu_kernel_profile_cap", old)
            profiler.reset_for_tests()

    def test_shed_chain_drains_registry(self):
        for i in range(8):
            profiler.profile("fragment", f"shed-{i}")
        assert len(profiler.registry()) == 8
        # the administrative shed (GET /shed, admission pressure) runs
        # every registered spill action — profile history must drop
        sched.shed_server(0)
        assert len(profiler.registry()) == 0
        assert profiler.registry()._billing_node().host == 0


class TestMeshKeying:
    @pytest.mark.parametrize("n", (1, 8), ids=["plane1", "plane8"])
    def test_rows_keyed_by_mesh(self, n):
        if n > 1:
            devplane.enable_mesh(n)
        try:
            prof = profiler.profile("hashagg", "mesh-key")
            assert prof.mesh == devplane.mesh_fingerprint(process=True)
        finally:
            if n > 1:
                devplane.disable_mesh()

    def test_topology_change_starts_fresh_rows(self):
        p1 = profiler.profile("hashagg", "mesh-key")
        devplane.enable_mesh(8)
        try:
            p8 = profiler.profile("hashagg", "mesh-key")
            assert p8 is not p1
            assert p8.mesh != p1.mesh
        finally:
            devplane.disable_mesh()
        # back at plane 1 the original row resumes (same key again)
        assert profiler.profile("hashagg", "mesh-key") is p1


class TestRoofline:
    def test_platform_peak_is_cached_and_positive(self):
        g1, src1 = profiler.platform_peak_gbps()
        g2, src2 = profiler.platform_peak_gbps()
        assert g1 > 0 and (g1, src1) == (g2, src2)
        # CPU CI: measured memcpy; chip: datasheet lookup
        assert src1.startswith(("datasheet(", "measured-memcpy("))

    def test_fraction_math(self):
        peak, _src = profiler.platform_peak_gbps()
        # exactly peak bandwidth -> fraction 1.0
        nbytes = int(peak * 1e9)
        assert profiler.achieved_gbps(nbytes, int(1e9)) == \
            pytest.approx(peak)
        assert profiler.roofline_fraction(nbytes, int(1e9)) == \
            pytest.approx(1.0)
        assert profiler.achieved_gbps(0, 100) is None
        assert profiler.roofline_fraction(100, 0) is None


class TestOverheadDisarmed:
    def test_disarmed_per_statement_overhead_is_tiny(self):
        """With tidb_tpu_kernel_profile off, the profiler's footprint
        on a statement is one config read returning None plus
        None-tolerant note_* early exits. Budget <5us per statement
        (same bar as the trace subsystem's disarmed pin)."""
        old = config.get_var("tidb_tpu_kernel_profile")
        config.set_var("tidb_tpu_kernel_profile", 0)
        try:
            n = 20_000
            t0 = time.perf_counter()
            for _ in range(n):
                prof = profiler.profile("hashagg", "overhead")
                profiler.note_construct(prof, reuse=True)
                with profiler.dispatch_section(prof, nbytes=4096):
                    pass
                profiler.note_dispatch(prof, 100, plan=None)
            per_stmt = (time.perf_counter() - t0) / n
            assert len(profiler.registry()) == 0    # truly disarmed
            assert per_stmt < 5e-6, \
                f"{per_stmt * 1e6:.2f}us per statement"
        finally:
            config.set_var("tidb_tpu_kernel_profile", old)


@pytest.fixture(scope="module")
def sess():
    s = Session(new_mock_storage())
    s.execute("CREATE DATABASE tpch")
    s.execute("USE tpch")
    tpch.load(s, tpch.TpchData(seed=7))
    yield s
    s.close()


class TestEndToEnd:
    def test_warm_q1_explain_analyze_kernel_note(self, sess):
        profiler.reset_for_tests()
        with config.session_overlay({"tidb_tpu_device": 1}):
            sess.query(tpch.Q1)                      # warm the caches
            r = sess.query("EXPLAIN ANALYZE " + tpch.Q1)
        assert r.columns[-1] == "kernel"
        cells = [row[-1] for row in r.rows if row[-1] != "-"]
        assert cells, r.rows
        # family + compile attribution + mode on the operator that
        # dispatched; roofline only when bytes were billed
        note = cells[0]
        assert "agg" in note
        assert "compile=" in note and "mode=" in note

    def test_kernel_profile_memtable_row(self, sess):
        profiler.reset_for_tests()
        with config.session_overlay({"tidb_tpu_device": 1}):
            for _ in range(2):
                sess.query(tpch.Q1)
        rows = sess.query(
            "SELECT family, compiles, dispatches, busy_ns, "
            "roofline_fraction FROM information_schema.kernel_profile"
        ).rows
        assert rows, "kernel_profile unpopulated after warm Q1"
        fam, compiles, dispatches, busy_ns, roof = rows[0]
        assert fam in profiler.FAMILIES
        assert dispatches >= 1 and busy_ns > 0
        # a warm second run must not recompile
        assert compiles <= 1

    def test_mode_memo_after_cardinality_sweep(self, sess):
        perfschema.memo_reset()
        with config.session_overlay({"tidb_tpu_device": 1}):
            # one digest, two observed cardinalities (literal stripped:
            # both WHERE bounds normalize into the same digest)
            sess.query("SELECT l_returnflag, COUNT(*) FROM lineitem "
                       "WHERE l_orderkey < 100 GROUP BY l_returnflag")
            sess.query("SELECT l_returnflag, COUNT(*) FROM lineitem "
                       "WHERE l_orderkey < 600 GROUP BY l_returnflag")
        memo = sess.query(
            "SELECT digest, op, mode, runs, last_groups, max_groups "
            "FROM information_schema.statement_profile").rows
        assert memo, "memo unpopulated"
        by_digest = {}
        for dg, op, mode, runs, last_g, max_g in memo:
            assert mode in ("direct", "hash", "sort", "fused",
                            "hybrid", "host")
            by_digest.setdefault(dg, []).append((op, runs, last_g,
                                                 max_g))
        # the swept digest folded both runs into one memo row
        assert any(sum(r for _op, r, _l, _m in rows) >= 2
                   for rows in by_digest.values()), memo
        assert all(max_g >= last_g >= 0
                   for rows in by_digest.values()
                   for _op, _r, last_g, max_g in rows)

    def test_memo_is_bounded(self, sess):
        perfschema.memo_reset()
        old = config.get_var("tidb_tpu_stmt_profile_cap")
        config.set_var("tidb_tpu_stmt_profile_cap", 16)
        try:
            for i in range(24):
                perfschema.memo_record(f"digest-{i}", [
                    {"name": "TableReader", "mode": "hash",
                     "act_rows": i, "device_time_ns": 10}])
            assert len(perfschema.memo_snapshot()) == 16
        finally:
            config.set_var("tidb_tpu_stmt_profile_cap", old)
            perfschema.memo_reset()

    def test_status_doc_carries_profiler_state(self, sess):
        from tidb_tpu import member
        doc = member.local_state()
        assert "kernel_profile" in doc
        st = profiler.stats()
        assert set(st) >= {"entries", "cap", "evictions", "compiles",
                           "dispatches", "busy_ns", "enabled"}
