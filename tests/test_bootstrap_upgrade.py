"""Versioned bootstrap upgrades (bootstrap.py; ref: bootstrap.go:40-180
upgradeToVerN chain): a store bootstrapped by round-N code opens under
round-N+1 code and migrates, idempotently."""

import pytest

from tidb_tpu import bootstrap as bs
from tidb_tpu.privilege import ALL_PRIVS
from tidb_tpu.session import Session
from tidb_tpu.store.storage import new_mock_storage


def _downgrade_to_v1(storage):
    """Rewind a freshly-bootstrapped store to what round-3 code wrote:
    version row '1', no help_topic, root with a pre-SUPER bitmask."""
    s = Session(storage, internal=True)
    s.execute("UPDATE mysql.tidb SET variable_value = '1' "
              "WHERE variable_name = 'bootstrapped'")
    s.execute("UPDATE mysql.user SET privs = 1 "
              "WHERE user = 'root' AND host = '%'")
    s.execute("DROP TABLE mysql.help_topic")
    s.close()


@pytest.fixture
def old_store():
    st = new_mock_storage()
    bs.bootstrap(st)             # current version
    _downgrade_to_v1(st)
    return st


def _version(storage) -> int:
    s = Session(storage, internal=True)
    try:
        return int(s.query(
            "SELECT variable_value FROM mysql.tidb "
            "WHERE variable_name = 'bootstrapped'").rows[0][0])
    finally:
        s.close()


class TestUpgradeChain:
    def test_old_store_migrates_on_open(self, old_store):
        bs.bootstrap(old_store)
        assert _version(old_store) == bs.BOOTSTRAP_VERSION
        s = Session(old_store, internal=True)
        # ver2: root re-granted the full bitmask
        assert s.query("SELECT privs FROM mysql.user WHERE user='root'"
                       ).rows == [(ALL_PRIVS,)]
        # ver3: help_topic exists and is queryable
        assert s.query("SELECT COUNT(*) FROM mysql.help_topic"
                       ).rows == [(0,)]
        s.close()

    def test_upgrade_is_idempotent(self, old_store):
        bs.bootstrap(old_store)
        before = _version(old_store)
        bs.bootstrap(old_store)      # second open: no-op, no errors
        bs.bootstrap(old_store)
        assert _version(old_store) == before

    def test_partial_upgrade_resumes(self, old_store):
        """Crash between a step and its version write replays the step:
        simulate by running only ver2 then reopening."""
        s = Session(old_store, internal=True)
        bs._upgrade_to_ver2(s)
        s.execute("UPDATE mysql.tidb SET variable_value = '2' "
                  "WHERE variable_name = 'bootstrapped'")
        s.close()
        bs.bootstrap(old_store)      # resumes at ver3
        assert _version(old_store) == bs.BOOTSTRAP_VERSION
        s = Session(old_store, internal=True)
        assert s.query("SELECT COUNT(*) FROM mysql.help_topic"
                       ).rows == [(0,)]
        s.close()

    def test_fresh_store_skips_chain(self):
        st = new_mock_storage()
        bs.bootstrap(st)
        s = Session(st)
        assert _version(st) == bs.BOOTSTRAP_VERSION
        assert s.query("SELECT COUNT(*) FROM mysql.help_topic"
                       ).rows == [(0,)]
        s.close()

    def test_upgrade_registry_is_contiguous(self):
        assert set(bs._UPGRADES) == \
            set(range(2, bs.BOOTSTRAP_VERSION + 1))
