"""TxStructure: typed string/hash/list structures on a KV txn (ref:
structure/structure.go:49, string.go:24, hash.go:46, list.go)."""

import pytest

from tidb_tpu.store.storage import new_mock_storage
from tidb_tpu.structure import TxStructure


@pytest.fixture
def txn():
    st = new_mock_storage()
    t = st.begin()
    yield t
    if getattr(t, "valid", True):
        try:
            t.rollback()
        except Exception:
            pass
    st.close()


@pytest.fixture
def s(txn):
    return TxStructure(txn, prefix=b"x")


class TestString:
    def test_set_get_inc(self, s):
        assert s.get(b"k") is None
        s.set(b"k", b"v")
        assert s.get(b"k") == b"v"
        assert s.inc(b"n") == 1
        assert s.inc(b"n", 5) == 6
        assert s.get_int(b"n") == 6
        s.clear(b"n")
        assert s.get_int(b"n") == 0


class TestHash:
    def test_ops_and_order(self, s):
        s.hset(b"h", b"b", b"2")
        s.hset(b"h", b"a", b"1")
        s.hset(b"h", b"c", b"3")
        assert s.hget(b"h", b"a") == b"1"
        assert s.hget(b"h", b"z") is None
        assert s.hgetall(b"h") == [(b"a", b"1"), (b"b", b"2"),
                                   (b"c", b"3")]
        assert s.hlen(b"h") == 3
        s.hdel(b"h", b"b")
        assert s.hlen(b"h") == 2
        s.hclear(b"h")
        assert s.hgetall(b"h") == []

    def test_keys_disjoint(self, s):
        # same name as string/hash/list: three separate objects
        s.set(b"k", b"sv")
        s.hset(b"k", b"f", b"hv")
        s.rpush(b"k", b"lv")
        assert s.get(b"k") == b"sv"
        assert s.hget(b"k", b"f") == b"hv"
        assert s.lindex(b"k", 0) == b"lv"

    def test_prefix_scan(self, s):
        s.hset(b"h", b"j1/a", b"1")
        s.hset(b"h", b"j1/b", b"2")
        s.hset(b"h", b"j2/a", b"3")
        assert s.hscan_prefix(b"h", b"j1/") == [(b"j1/a", b"1"),
                                                (b"j1/b", b"2")]


class TestList:
    def test_push_pop(self, s):
        s.rpush(b"l", b"1", b"2")
        s.lpush(b"l", b"0")
        assert s.llen(b"l") == 3
        assert s.litems(b"l") == [b"0", b"1", b"2"]
        assert s.lindex(b"l", 0) == b"0"
        assert s.lindex(b"l", -1) == b"2"
        assert s.lindex(b"l", 9) is None
        assert s.lpop(b"l") == b"0"
        assert s.rpop(b"l") == b"2"
        assert s.lpop(b"l") == b"1"
        assert s.lpop(b"l") is None
        assert s.llen(b"l") == 0

    def test_lset_lrem(self, s):
        s.rpush(b"l", b"a", b"b", b"c", b"d")
        s.lset(b"l", 1, b"B")
        assert s.litems(b"l") == [b"a", b"B", b"c", b"d"]
        s.lrem_at(b"l", 1)
        assert s.litems(b"l") == [b"a", b"c", b"d"]
        s.lrem_at(b"l", 2)
        assert s.litems(b"l") == [b"a", b"c"]
        with pytest.raises(IndexError):
            s.lset(b"l", 5, b"x")

    def test_txn_atomicity(self, txn):
        """Structure writes commit with the txn (the whole point)."""
        st = txn.storage if hasattr(txn, "storage") else None
        s = TxStructure(txn, prefix=b"x")
        s.rpush(b"q", b"job1")
        s.inc(b"ver")
        txn.commit()
        if st is None:
            return
        t2 = st.begin()
        s2 = TxStructure(t2, prefix=b"x")
        assert s2.litems(b"q") == [b"job1"]
        assert s2.get_int(b"ver") == 1
        t2.rollback()


class TestMetaOnStructure:
    def test_job_queue_fifo_update_finish(self):
        from tidb_tpu.ddl.job import Job, JobState
        from tidb_tpu.meta import Meta
        st = new_mock_storage()
        txn = st.begin()
        m = Meta(txn)
        j1 = Job(id=m.gen_global_id())
        j2 = Job(id=m.gen_global_id())
        m.enqueue_job(j1)
        m.enqueue_job(j2)
        assert m.first_job().id == j1.id
        j1.state = JobState.RUNNING
        m.update_job(j1)
        assert m.first_job().state == JobState.RUNNING
        m.finish_job(j1)
        assert m.first_job().id == j2.id
        assert m.history_job(j1.id).id == j1.id
        m.finish_job(j2)
        assert m.first_job() is None
        txn.rollback()
        st.close()
