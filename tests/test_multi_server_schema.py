"""Multi-server schema plane: lease-based DDL owner election, schema
version publication + convergence, cross-server DDL execution (ref:
owner/manager.go:40-53, ddl/syncer.go:58-78, domain/schema_validator.go).

Two RemoteStorage clients to one storage process = two SQL servers with
independent Domains — the reference's multi-tidb-server topology."""

import time

import pytest

from tidb_tpu.owner import OwnerManager
from tidb_tpu.session import Domain, Session
from tidb_tpu.store.remote import StorageServer, connect
from tidb_tpu.store.storage import new_mock_storage


class TestOwnerElection:
    def test_single_campaigner_wins_and_renews(self):
        st = new_mock_storage()
        a = OwnerManager(st, lease_ms=200)
        assert a.campaign()
        assert a.is_owner()
        assert a.campaign()          # renewal

    def test_second_campaigner_loses_until_lease_expires(self):
        st = new_mock_storage()
        a = OwnerManager(st, lease_ms=150)
        b = OwnerManager(st, lease_ms=150)
        assert a.campaign()
        assert not b.campaign()
        assert b.owner_id() == a.id
        time.sleep(0.2)              # lease expires
        assert b.campaign()
        assert b.is_owner() and not a.is_owner()

    def test_resign_hands_over(self):
        st = new_mock_storage()
        a = OwnerManager(st, lease_ms=10_000)
        b = OwnerManager(st, lease_ms=10_000)
        assert a.campaign()
        a.resign()
        assert b.campaign()


class TestTwoServers:
    @pytest.fixture
    def cluster(self):
        srv = StorageServer()
        srv.start()
        st_a = connect("127.0.0.1", srv.port)
        st_b = connect("127.0.0.1", srv.port)
        sa, sb = Session(st_a), Session(st_b)
        dom_a, dom_b = sa.domain, sb.domain
        dom_a.SCHEMA_LEASE_MS = dom_b.SCHEMA_LEASE_MS = 300
        yield sa, sb, dom_a, dom_b
        dom_a.stop_schema_worker()
        dom_b.stop_schema_worker()
        sa.close()
        sb.close()
        st_a.close()
        st_b.close()
        srv.close()

    def test_ddl_on_b_runs_on_owner_a_and_is_visible(self, cluster):
        sa, sb, dom_a, dom_b = cluster
        # A becomes the standing owner with a live worker loop
        dom_a.start_schema_worker(interval=0.05)
        deadline = time.time() + 2
        while not dom_a.ddl_owner().is_owner() and time.time() < deadline:
            time.sleep(0.02)
        assert dom_a.ddl_owner().is_owner()
        # DDL submitted on B: B loses the campaign, the job runs on A's
        # worker, B waits for history and proceeds
        sb.execute("CREATE DATABASE d")
        sb.execute("CREATE TABLE d.t (id BIGINT PRIMARY KEY, v BIGINT)")
        sb.execute("INSERT INTO d.t VALUES (1, 5)")
        assert sb.query("SELECT v FROM d.t").rows == [(5,)]
        # visible on A within the lease window (fresh snapshot read)
        assert sa.query("SELECT v FROM d.t").rows == [(5,)]

    def test_owner_failover(self, cluster):
        sa, sb, dom_a, dom_b = cluster
        dom_a.start_schema_worker(interval=0.05)
        deadline = time.time() + 2
        while not dom_a.ddl_owner().is_owner() and time.time() < deadline:
            time.sleep(0.02)
        sb.execute("CREATE DATABASE d1")
        # A dies (worker stopped, lease expires) -> B's next DDL campaigns
        # and runs locally
        dom_a.stop_schema_worker()
        time.sleep(0.4)
        sb.execute("CREATE DATABASE d2")
        assert sb.domain.ddl_owner().is_owner()
        names = [r[0] for r in sb.query("SHOW DATABASES").rows]
        assert "d1" in names and "d2" in names

    def test_schema_version_publication_and_convergence(self, cluster):
        sa, sb, dom_a, dom_b = cluster
        sa.execute("CREATE DATABASE seed")   # version > 0
        dom_b.publish_schema_version()
        vers = dom_a.live_schema_versions()
        assert dom_b.ddl_owner().id in vers
        # B is up to date -> convergence immediate
        assert dom_a.wait_schema_convergence(
            dom_b.info_schema().version, timeout_ms=300)
        # a lagging live publisher (stale version, unexpired lease) bounds
        # the owner's wait at the cap instead of hanging
        import json
        key = Domain.SCHEMA_SYNC_PREFIX + b"laggard"
        txn = sa.storage.begin()
        txn.set(key, json.dumps(
            {"ver": 0, "expiry": int(time.time() * 1000) + 60_000}
        ).encode())
        txn.commit()
        t0 = time.time()
        ok = dom_a.wait_schema_convergence(
            dom_a.info_schema().version, timeout_ms=250)
        assert not ok and time.time() - t0 >= 0.2
        # the laggard catches up -> convergence succeeds
        txn = sa.storage.begin()
        txn.set(key, json.dumps(
            {"ver": dom_a.info_schema().version,
             "expiry": int(time.time() * 1000) + 60_000}).encode())
        txn.commit()
        assert dom_a.wait_schema_convergence(
            dom_a.info_schema().version, timeout_ms=300)
        txn = sa.storage.begin()
        txn.delete(key)
        txn.commit()

    def test_txn_straddling_version_bump_detected(self, cluster):
        """Commit-time schema validation notices the concurrent DDL; the
        session replays the statement history against the fresh schema
        (ref: session.go doCommitWithRetry). A replay that can no longer
        apply (the column is gone) surfaces as an error; one that can
        (column added) commits consistently under the new schema."""
        sa, sb, dom_a, dom_b = cluster
        sa.execute("CREATE DATABASE d; USE d")
        sa.execute("CREATE TABLE t (id BIGINT PRIMARY KEY, v BIGINT, "
                   "w BIGINT)")
        sa.execute("INSERT INTO t VALUES (1, 1, 1)")
        sb.execute("USE d")
        sb.execute("BEGIN")
        sb.execute("UPDATE t SET w = 2 WHERE id = 1")
        sa.execute("ALTER TABLE d.t DROP COLUMN w")
        from tidb_tpu.session import SQLError
        from tidb_tpu import kv
        with pytest.raises((SQLError, kv.KVError)):
            sb.execute("COMMIT")
        # the add-column variant: replay succeeds under the new schema
        sb.execute("BEGIN")
        sb.execute("UPDATE t SET v = 9 WHERE id = 1")
        sa.execute("ALTER TABLE d.t ADD COLUMN extra BIGINT")
        sb.execute("COMMIT")
        assert sb.query("SELECT v, extra FROM t").rows == [(9, None)]
