"""The device-plane dataflow pass (tidb_tpu/lint/flow/device.py):
discovery of every traced-program construction site across its four
forms, dispatch resolution, the static compile-prediction contract the
`bench.py lintcheck` leg cross-checks against the profiler plane, and
the runtime pin for the audited `donate_argnums` sites in ops/hashagg
and ops/streamagg (ISSUE 20's donation audit: the donating branch
returns at the dispatch, the donated transfer skips the chunk memo,
and the non-donating twin re-transfers from host afterwards)."""

import random
import warnings

import pytest

from tidb_tpu.lint.engine import Forest
from tidb_tpu.lint.flow.device import DeviceFlow, device_flow_of


@pytest.fixture(scope="module")
def df():
    return device_flow_of(Forest.load())


# -- discovery --------------------------------------------------------------

def test_discovers_all_construction_forms(df):
    forms = {s.form for s in df.sites}
    assert forms == {"jit", "partial_jit", "plane_jit"}
    stores = {s.store[0] for s in df.sites}
    # instance attrs, bucket dicts, module globals, factory returns,
    # locals, and the functools.partial decorator form
    assert {"attr", "dict", "global", "return", "local",
            "decorator"} <= stores


def test_discovers_the_known_kernel_sites(df):
    by_rel = {}
    for s in df.sites:
        by_rel.setdefault(s.rel, []).append(s)
    assert len(by_rel["tidb_tpu/ops/hashagg.py"]) == 4    # _jit/_jitd x2
    assert len(by_rel["tidb_tpu/ops/streamagg.py"]) == 2  # _jit/_jitd
    assert len(by_rel["tidb_tpu/ops/meshjoin.py"]) == 3   # 3 stages
    assert any(s.rel == "tidb_tpu/ops/pallas_agg.py" and
               s.form == "partial_jit" for s in df.sites)


def test_donating_sites_are_exactly_the_jitd_twins(df):
    donating = sorted((s.rel, s.store[1]) for s in df.sites
                      if s.donating)
    assert donating == [("tidb_tpu/ops/hashagg.py", "_jitd"),
                        ("tidb_tpu/ops/hashagg.py", "_jitd"),
                        ("tidb_tpu/ops/streamagg.py", "_jitd")]
    for s in df.sites:
        if s.donating:
            assert s.donate == (0,)     # the padded input columns


def test_traced_bodies_resolve_through_owner_classes(df):
    names = set()
    for s in df.sites:
        names |= {f.qualname for f in s.fns}
    assert "HashAggKernel._kernel" in names
    assert "SegmentAggKernel._kernel" in names
    # factory-returns-nested-def and shard_map unwrapping
    assert "MeshLookupAggKernel._stage2_fn.<locals>.stage2" in names
    assert "MeshShuffleJoinKernel._program.<locals>.kernel" in names


def test_dispatches_resolve_to_sites(df):
    assert len(df.dispatches) >= 10
    donating = [d for d in df.dispatches if d.site.donating]
    assert len(donating) == 3
    # the bucketed factory-call-then-call shape is classified with its
    # inner factory call attached (the memo-key check's input)
    assert any(d.via_factory is not None for d in df.dispatches)


def test_memoized_on_forest(df):
    forest = Forest.load()
    a = device_flow_of(forest)
    assert device_flow_of(forest) is a
    assert isinstance(a, DeviceFlow)


# -- compile predictions ----------------------------------------------------

def test_compile_predictions_cover_every_profiler_family(df):
    from tidb_tpu import profiler
    preds = df.compile_predictions()
    assert set(preds) == set(profiler.FAMILIES)
    for fam, p in preds.items():
        assert p["warm_growth"] == 0
        if fam == "plane":
            # bucket dicts construct one program per pow2 bucket and
            # kernel instance: no static per-row bound
            assert p["per_row_bound"] is None
        else:
            assert p["per_row_bound"] == 1
    assert preds["plane"]["sites"] == sum(
        1 for s in df.sites if s.form == "plane_jit")


# -- donation audit (ISSUE 20 satellite): runtime pin -----------------------

def _mk_kernel_and_chunks():
    from tidb_tpu import sqltypes as st
    from tidb_tpu.chunk import Chunk
    from tidb_tpu.expression import AggDesc, AggFunc, col
    from tidb_tpu.ops.hashagg import HashAggKernel

    INT = st.new_int_field()
    rng = random.Random(7)
    rows = [(rng.randrange(6), rng.randrange(50)) for _ in range(500)]
    k = HashAggKernel(None, [col(0, INT)],
                      [AggDesc(AggFunc.SUM, col(1, INT)),
                       AggDesc(AggFunc.COUNT, None)])
    return (k, Chunk.from_rows([INT, INT], rows),
            Chunk.from_rows([INT, INT], rows))


def _result_map(k, res):
    from tidb_tpu.ops.hashagg import HashAggregator
    agg = HashAggregator(k.aggs)
    agg.update(res)
    return {key[0]: tuple(v) for key, v in agg.results()}


def test_hashagg_donating_dispatch_skips_memo_and_matches(monkeypatch):
    """The audited `_jitd` sites: with donation forced on, the
    donating branch must (a) produce the same result as the plain
    twin, (b) skip the chunk device memo (a memoized donated buffer is
    read-after-free), and (c) leave the chunk re-dispatchable through
    the NON-donating twin afterwards — the fresh host transfer, not
    the donated buffer, feeds the second dispatch."""
    from tidb_tpu.ops import runtime
    monkeypatch.setattr(runtime, "_donation_supported", True)
    k, ch_plain, ch_don = _mk_kernel_and_chunks()
    size = runtime.bucket_size(ch_don.num_rows)

    with warnings.catch_warnings():
        # CPU backends warn that donated buffers were unusable; the
        # dispatch path under test is identical either way
        warnings.simplefilter("ignore")
        plain = _result_map(k, k.finalize(
            ch_plain, k.dispatch(ch_plain, donate=False)))
        assert runtime.dev_cache_get(ch_plain, size) is not None

        donated = _result_map(k, k.finalize(
            ch_don, k.dispatch(ch_don, donate=True)))
        assert k._jitd is not None          # lazy twin materialized
        assert runtime.dev_cache_get(ch_don, size) is None

        again = _result_map(k, k.finalize(
            ch_don, k.dispatch(ch_don, donate=False)))

    assert donated == plain
    assert again == plain


def test_streamagg_donating_dispatch_skips_memo(monkeypatch):
    from tidb_tpu import sqltypes as st
    from tidb_tpu.chunk import Chunk
    from tidb_tpu.expression import AggDesc, AggFunc, col
    from tidb_tpu.ops import runtime
    from tidb_tpu.ops.streamagg import SegmentAggKernel

    monkeypatch.setattr(runtime, "_donation_supported", True)
    INT = st.new_int_field()
    rows = [(i // 5, i % 7) for i in range(200)]
    ch = Chunk.from_rows([INT, INT], rows)
    k = SegmentAggKernel([col(0, INT)],
                         [AggDesc(AggFunc.SUM, col(1, INT))])
    size = runtime.bucket_size(ch.num_rows)
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        pending = k.dispatch(ch, donate=True)
        res = k.finalize(ch, pending)
    assert runtime.dev_cache_get(ch, size) is None
    assert res is not None
