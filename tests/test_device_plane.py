"""One device plane: the accounting and observability seams fire
IDENTICALLY at mesh size 1 (the copTask path) and mesh size 8 (the
NamedSharding plane). The tentpole contract is that a statement's
externally visible machinery — memtrack ledgers, trace-span
vocabulary, meter attribution, scheduler slot grants, failpoint
recovery — must not depend on how many chips executed it; only the
numbers (per-chip spread, wall time) may differ.

Each check runs under both plane sizes via the parametrized `plane`
fixture; cross-size equality (span sets, query results) is asserted
once both sizes have recorded their observation.
"""

import pytest

import tpch
from tidb_tpu import config, devplane, memtrack, meter, metrics, sched, trace
from tidb_tpu.session import Session
from tidb_tpu.store.storage import new_mock_storage
from tidb_tpu.util import failpoint

pytestmark = pytest.mark.usefixtures("ledger_hygiene")

# every statement that reached the device must retain these spans,
# whatever the plane size (the trace-names lint vocabulary)
DEVICE_SPANS = {"sched.slot", "dispatch", "finalize"}

# storage-transport envelope spans: which ONE fires depends on the read
# path (framed streaming vs cached whole-region tasks), a per-scan
# choice that is orthogonal to the plane size contract below
TRANSPORT_SPANS = {"copr.task", "copr.stream"}

SIZES = (1, 8)


@pytest.fixture(scope="module")
def sess():
    s = Session(new_mock_storage())
    s.execute("CREATE DATABASE tpch")
    s.execute("USE tpch")
    # seed=7: Q1/Q3 both return non-empty results (tests/tpch.py)
    tpch.load(s, tpch.TpchData(seed=7))
    yield s
    s.close()


@pytest.fixture(params=SIZES, ids=["plane1", "plane8"])
def plane(request):
    n = request.param
    if n > 1:
        devplane.enable_mesh(n)
    sched.reset_for_tests()
    trace.reset_for_tests()
    old = config.get_var("tidb_tpu_trace_sample")
    config.set_var("tidb_tpu_trace_sample", 1)   # retain every trace
    yield n
    config.set_var("tidb_tpu_trace_sample", old)
    failpoint.disable_all()
    sched.device_health().note_ok()      # leave no quarantine behind
    if n > 1:
        devplane.disable_mesh()


def _span_names(rec) -> set:
    out = set()

    def walk(s):
        out.add(s.name)
        for c in s.children:
            walk(c)

    walk(rec["root"])
    return out


def _fallbacks(reason: str) -> int:
    snap = metrics.snapshot()
    return int(sum(v for k, v in snap.items()
                   if k.startswith(metrics.DEVICE_FALLBACKS)
                   and f'reason="{reason}"' in k))


def _assert_same_across_sizes(store: dict, size: int, value):
    """Record `value` under `size`; once every plane size has reported,
    the observations must be equal — the one-plane contract."""
    store[size] = value
    if all(s in store for s in SIZES):
        first = store[SIZES[0]]
        for s in SIZES[1:]:
            assert store[s] == first, (
                f"plane-size-dependent behavior: {SIZES[0]} chip(s) -> "
                f"{first!r}, {s} chip(s) -> {store[s]!r}")


class TestTraceSpans:
    _spans: dict = {}
    _rows: dict = {}

    def test_span_vocabulary_identical(self, sess, plane):
        r1 = sess.query(tpch.Q1).rows
        r3 = sess.query(tpch.Q3).rows
        assert r1 and r3
        names = set()
        for rec in trace.ring_records():
            names |= _span_names(rec)
        assert DEVICE_SPANS <= names, (
            f"plane size {plane}: missing device spans "
            f"{DEVICE_SPANS - names}")
        _assert_same_across_sizes(self._spans, plane,
                                  tuple(sorted(names - TRANSPORT_SPANS)))
        _assert_same_across_sizes(self._rows, plane,
                                  (sorted(map(tuple, r1)),
                                   sorted(map(tuple, r3))))


class TestSchedulerSlots:
    def test_grants_drain_and_spread(self, sess, plane):
        sess.query(tpch.Q1)
        sess.query(tpch.Q3)
        snap = sched.device_scheduler().snapshot()
        assert snap["grants"] >= 2
        assert snap["inflight"] == 0                 # every slot released
        chips = snap["chips"]
        assert set(chips) == set(range(plane))       # one stream per chip
        assert sum(v["grants"] for v in chips.values()) == snap["grants"]
        used = [c for c, v in chips.items() if v["grants"]]
        assert all(0 <= c < plane for c in used)
        if plane == 1:
            assert used == [0]
        else:
            # least-loaded placement rotates sequential statements off
            # the chip whose busy-time the previous grant accrued
            assert len(used) >= 2
        for c in used:
            assert chips[c]["busy_seconds"] > 0


class TestMemtrackLedgers:
    def test_device_ledger_drains(self, sess, plane):
        sess.query(tpch.Q1)
        sess.query(tpch.Q3)
        # dispatch-scoped device charges (padded uploads, scratch) are
        # all credited back at finalize on EVERY plane size; the ONLY
        # device bytes allowed to remain are the long-lived HBM
        # region-block cache's resident blocks (server-scope residency,
        # reclaimed by its LRU / the shed chain, not by statements)
        from tidb_tpu.store import device_cache
        assert memtrack.SERVER.device == device_cache.tracker().device


class TestMeterAttribution:
    def test_device_time_attributed(self, sess, plane):
        d0 = meter.SERVER.totals()["device_ns"]
        a0 = meter.attributed_device_ns()
        sess.query(tpch.Q1)
        assert meter.SERVER.totals()["device_ns"] > d0
        # the session meter (not just the server roll-up) carries it:
        # per-tenant attribution works on every plane size
        assert meter.attributed_device_ns() > a0


class TestFailpointRecovery:
    def test_dispatch_fault_recovers(self, sess, plane):
        want = sorted(map(tuple, sess.query(tpch.Q1).rows))
        fb = _fallbacks("fault")
        failpoint.enable("device/dispatch", "raise(DeviceFaultError)")
        try:
            got = sorted(map(tuple, sess.query(tpch.Q1).rows))
        finally:
            failpoint.disable("device/dispatch")
        sched.device_health().note_ok()
        assert got == want              # correct answer via host path
        assert _fallbacks("fault") > fb  # and the fault was counted
        snap = sched.device_scheduler().snapshot()
        assert snap["inflight"] == 0     # fault path released its slots
