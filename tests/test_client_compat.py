"""Client-preamble compatibility: the statements stock MySQL clients
and drivers send on connect (ref: expression/builtin_info.go VERSION/
USER/DATABASE/CONNECTION_ID; SET NAMES handling in the server)."""

import pytest

from tidb_tpu.session import Session, SQLError
from tidb_tpu.store.storage import new_mock_storage


@pytest.fixture
def sess():
    s = Session(new_mock_storage(), user="root", host="localhost")
    yield s
    s.close()


class TestPreamble:
    @pytest.mark.parametrize("q,want", [
        ("SELECT @@version_comment", [("tidb-tpu",)]),
        ("SELECT @@autocommit", [(1,)]),
        ("SELECT @@session.autocommit", [(1,)]),
        ("SELECT @@max_allowed_packet", [(67108864,)]),
        ("SELECT VERSION()", [("8.0.11-tidb-tpu-1.0",)]),
        ("SELECT USER()", [("root@localhost",)]),
        ("SELECT CURRENT_USER()", [("root@localhost",)]),
        ("SELECT DATABASE()", [(None,)]),
    ])
    def test_select_forms(self, sess, q, want):
        assert sess.query(q).rows == want

    def test_set_names_and_charset(self, sess):
        sess.execute("SET NAMES utf8mb4")
        sess.execute("SET NAMES utf8 COLLATE utf8_bin")
        sess.execute("SET CHARACTER SET latin1")
        rows = dict(sess.query(
            "SHOW VARIABLES LIKE 'character_set_client'").rows)
        assert rows["character_set_client"] == "latin1"

    def test_connection_id_and_database_follow_session(self, sess):
        assert sess.query("SELECT CONNECTION_ID()").rows == \
            [(sess.session_id,)]
        sess.execute("CREATE DATABASE d")
        sess.execute("USE d")
        assert sess.query("SELECT DATABASE()").rows == [("d",)]

    def test_user_vars_in_expressions(self, sess):
        sess.execute("SET @x = 41")
        assert sess.query("SELECT @x + 1").rows == [(42,)]
        assert sess.query("SELECT @undefined").rows == [(None,)]

    def test_sysvar_in_where(self, sess):
        sess.execute("CREATE DATABASE d")
        sess.execute("USE d")
        sess.execute("CREATE TABLE t (id BIGINT PRIMARY KEY)")
        sess.execute("INSERT INTO t VALUES (1), (2)")
        assert sess.query("SELECT id FROM t WHERE id = @@autocommit"
                          ).rows == [(1,)]

    def test_unknown_sysvar_errors(self, sess):
        with pytest.raises(SQLError, match="Unknown system variable"):
            sess.query("SELECT @@no_such_var")

    def test_session_scoped_value_reflected(self, sess):
        sess.execute("SET @@tidb_tpu_cop_concurrency = 4")
        assert sess.query("SELECT @@tidb_tpu_cop_concurrency"
                          ).rows == [(4,)]


class TestDoFlush:
    def test_do_evaluates_and_discards(self, sess):
        assert sess.execute("DO 1 + 1, SQRT(4)") == [None]
        with pytest.raises(SQLError):
            sess.execute("DO NO_SUCH_FN(1)")

    def test_flush(self, sess):
        assert sess.execute("FLUSH PRIVILEGES; FLUSH STATUS; "
                            "FLUSH TABLES") == [None, None, None]
        with pytest.raises(SQLError, match="unsupported FLUSH"):
            sess.execute("FLUSH LOGS")

    def test_flush_privileges_reloads_grants(self):
        from tidb_tpu.bootstrap import bootstrap
        from tidb_tpu.privilege import Priv
        st = new_mock_storage()
        bootstrap(st)
        r = Session(st, user="root", host="%")
        r.execute("CREATE USER fp IDENTIFIED BY 'x'")
        r.execute("CREATE DATABASE d")
        r.execute("CREATE TABLE d.t (id BIGINT PRIMARY KEY)")
        u = Session(st, user="fp", host="%")
        with pytest.raises(SQLError):
            u.query("SELECT * FROM d.t")
        # out-of-band grant-table edit: visible after FLUSH PRIVILEGES
        r.execute("INSERT INTO mysql.tables_priv VALUES "
                  f"('%', 'fp', 'd', 't', {Priv.SELECT})")
        r.execute("FLUSH PRIVILEGES")
        assert u.query("SELECT * FROM d.t").rows == []
        u.close()
        r.close()
