"""SQL joins large enough to ride the device join kernel path."""

import numpy as np
import pytest

from tidb_tpu.session import Session
from tidb_tpu.store.storage import new_mock_storage


@pytest.fixture(scope="module")
def sess():
    s = Session(new_mock_storage())
    s.execute("CREATE DATABASE d")
    s.execute("USE d")
    s.execute("CREATE TABLE c (id BIGINT PRIMARY KEY, seg BIGINT)")
    s.execute("CREATE TABLE o (id BIGINT PRIMARY KEY, cid BIGINT, "
              "amt DOUBLE)")
    rng = np.random.default_rng(11)
    crows = ",".join(f"({i}, {i % 4})" for i in range(300))
    s.execute(f"INSERT INTO c VALUES {crows}")
    cid = rng.integers(0, 400, 3000)  # some orders dangle (cid >= 300)
    amt = rng.uniform(1, 100, 3000).round(2)
    orows = ",".join(f"({i}, {cid[i]}, {amt[i]})" for i in range(3000))
    s.execute(f"INSERT INTO o VALUES {orows}")
    s._truth = (cid, amt)
    return s


def test_device_join_agg(sess):
    cid, amt = sess._truth
    rows = sess.query(
        "SELECT c.seg, COUNT(*), SUM(o.amt) FROM o JOIN c ON o.cid = c.id "
        "GROUP BY c.seg ORDER BY c.seg").rows
    want = {}
    for i in range(3000):
        if cid[i] < 300:
            e = want.setdefault(cid[i] % 4, [0, 0.0])
            e[0] += 1
            e[1] += amt[i]
    assert len(rows) == len(want)
    for seg, cnt, s_ in rows:
        assert cnt == want[seg][0]
        assert s_ == pytest.approx(want[seg][1], rel=1e-9)


def test_device_left_join_null_extension(sess):
    cid, amt = sess._truth
    rows = sess.query(
        "SELECT COUNT(*) FROM o LEFT JOIN c ON o.cid = c.id "
        "WHERE c.id IS NULL").rows
    dangling = int(np.sum(cid >= 300))
    assert rows[0][0] == dangling


def test_device_join_topn(sess):
    cid, amt = sess._truth
    rows = sess.query(
        "SELECT o.id, o.amt FROM o JOIN c ON o.cid = c.id "
        "WHERE c.seg = 1 ORDER BY o.amt DESC LIMIT 5").rows
    cand = sorted(
        ((i, amt[i]) for i in range(3000)
         if cid[i] < 300 and cid[i] % 4 == 1),
        key=lambda t: -t[1])[:5]
    assert [(r[0], pytest.approx(r[1])) for r in rows] == \
        [(i, pytest.approx(a)) for i, a in cand]
