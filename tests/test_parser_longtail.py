"""Grammar long tail mined from the reference's parser corpus
(ref: parser/parser_test.go, 2.1k LoC of table cases; VERDICT r4 #6).
Each case here parses AND the statement classes carry the right data."""

import pytest

from tidb_tpu.parser import ast
from tidb_tpu.parser.parser import ParseError, parse


def one(sql):
    stmts = parse(sql)
    assert len(stmts) == 1
    return stmts[0]


PARSES = [
    # column/type long tail
    "CREATE TABLE foo (name CHAR(50) BINARY)",
    "CREATE TABLE foo (name CHAR(50) CHARACTER SET utf8)",
    "CREATE TABLE foo (name CHAR(50) BINARY CHARACTER SET utf8 "
    "COLLATE utf8_bin)",
    "CREATE TABLE t (c TEXT) default CHARACTER SET utf8, "
    "default COLLATE utf8_general_ci",
    "CREATE TABLE t (a int1, b int2, c int3, d int4, e int8)",
    "CREATE TABLE t (c1 national char(2), c2 national varchar(2))",
    "CREATE TABLE t (ts timestamp NOT NULL DEFAULT CURRENT_TIMESTAMP "
    "ON UPDATE CURRENT_TIMESTAMP)",
    "CREATE TABLE c (sd integer CHECK (sd > 0), nm varchar(30))",
    "CREATE TABLE t (c1 bool, check (c1 in (0, 1)))",
    "CREATE TABLE t (id int, PRIMARY KEY pk_id (id))",
    "CREATE TABLE t (v varbinary(16), m mediumtext, l longblob)",
    # table options / partitioning
    "CREATE TABLE p (id bigint) ENGINE=InnoDB AUTO_INCREMENT=6 "
    "DEFAULT CHARSET=utf8 ROW_FORMAT=COMPRESSED KEY_BLOCK_SIZE=8",
    "CREATE TABLE t (c int) PARTITION BY HASH (c) PARTITIONS 32",
    # indexes
    "CREATE INDEX idx ON t (a) USING HASH COMMENT 'foo'",
    "CREATE INDEX idx USING BTREE ON t (a)",
    "CREATE TABLE t (a int, INDEX ia (a) COMMENT 'x', "
    "FULLTEXT KEY ft (a))",
    # ALTER long tail
    "ALTER TABLE t ADD COLUMN (a SMALLINT UNSIGNED, b varchar(255))",
    "ALTER TABLE t DISABLE KEYS",
    "ALTER TABLE t ENABLE KEYS",
    "ALTER TABLE t CHANGE COLUMN a b varchar(255) FIRST",
    "ALTER TABLE t ALTER COLUMN a SET DEFAULT 1",
    "ALTER TABLE t ALTER a DROP DEFAULT",
    "ALTER TABLE t ADD COLUMN a SMALLINT UNSIGNED, lock=none",
    "ALTER TABLE t ADD UNIQUE (a) COMMENT 'a'",
    "ALTER TABLE t ENGINE = innodb",
    "ALTER TABLE t ADD FULLTEXT INDEX ft (nm ASC)",
    # SELECT long tail
    "SELECT DISTINCTROW * FROM t",
    "SELECT a.b.* FROM t",
    "SELECT * from t lock in share mode",
    "SELECT SUBSTRING('Quadratically' FROM 5)",
    "SELECT SUBSTRING('Quadratically' FROM 5 FOR 3)",
    "SELECT CAST(data AS CHAR CHARACTER SET utf8) FROM t",
    "SELECT CAST(data AS JSON) FROM t",
    "SELECT CAST(1 AS SIGNED INT)",
    "SELECT X'0a', 0x0b, b'1010'",
    "SELECT N'string'",
    "SELECT 1 AS 'a'",
    "select * from t1 straight_join t2 on t1.id = t2.id",
    "(select c1 from t1) union distinctrow select c2 from t2",
    # SET long tail
    "SET LOCAL autocommit = 1",
    "SET @@local.autocommit = 1",
    "SET PASSWORD FOR 'root'@'localhost' = 'password'",
    "SET SESSION TRANSACTION ISOLATION LEVEL REPEATABLE READ",
    "SET GLOBAL TRANSACTION ISOLATION LEVEL READ COMMITTED",
    "SET SESSION TRANSACTION READ ONLY",
    # SHOW / FLUSH / DROP / ADMIN / ANALYZE
    "SHOW CHARACTER SET",
    "SHOW CHARSET",
    "SHOW FULL COLUMNS IN t",
    "SHOW STATS_META",
    "SHOW STATS_BUCKETS WHERE table_name = 't'",
    "FLUSH NO_WRITE_TO_BINLOG TABLES tbl1 WITH READ LOCK",
    "FLUSH TABLES tbl1, tbl2",
    "DROP TABLES xxx, yyy",
    "DROP VIEW IF EXISTS xxx",
    "DROP STATS t",
    "ADMIN CANCEL DDL JOBS 1, 2",
    "ANALYZE TABLE t1 INDEX a, b",
    # misc
    "INSERT INTO foo () VALUES ()",
    "CREATE TABLE a LIKE b",
    "CREATE TABLE IF NOT EXISTS a LIKE b",
    "ALTER TABLE db.t RENAME db.t1",
    "GRANT ALL ON db1.* TO 'jeffrey'@'localhost' WITH GRANT OPTION",
]


@pytest.mark.parametrize("sql", PARSES)
def test_parses(sql):
    parse(sql)


class TestSemantics:
    def test_hex_literal_value(self):
        s = one("SELECT X'0a' + 0")
        assert isinstance(s, ast.SelectStmt)

    def test_create_like_ast(self):
        s = one("CREATE TABLE a LIKE b")
        assert s.like_table.name == "b"

    def test_alter_set_default(self):
        s = one("ALTER TABLE t ALTER COLUMN a SET DEFAULT 1")
        assert s.specs[0].tp == "set_default"
        assert s.specs[0].name == "a"

    def test_substring_from_desugars(self):
        s = one("SELECT SUBSTRING('abcdef' FROM 2 FOR 3)")
        f = s.fields[0].expr
        assert isinstance(f, ast.FuncCall) and len(f.args) == 3

    def test_admin_cancel_ids(self):
        s = one("ADMIN CANCEL DDL JOBS 3, 4")
        assert s.tp == "cancel_ddl_jobs" and s.job_ids == [3, 4]

    def test_grant_option_adds_grant_priv(self):
        s = one("GRANT SELECT ON d.* TO 'u'@'%' WITH GRANT OPTION")
        assert "GRANT" in s.privs

    def test_multi_schema_alter_still_rejected(self):
        with pytest.raises(ParseError):
            parse("ALTER TABLE t ADD COLUMN a INT ADD COLUMN b INT")


class TestEndToEnd:
    """The new syntax runs through the session, not just the parser."""

    @pytest.fixture
    def sess(self):
        from tidb_tpu.bootstrap import bootstrap
        from tidb_tpu.session import Session
        from tidb_tpu.store.storage import new_mock_storage
        st = new_mock_storage()
        bootstrap(st)           # SET PASSWORD touches mysql.user
        s = Session(st)
        s.execute("CREATE DATABASE lt; USE lt")
        yield s
        s.close()

    def test_create_like_clones_schema(self, sess):
        sess.execute("CREATE TABLE src (id BIGINT PRIMARY KEY, "
                     "v VARCHAR(10) COLLATE utf8mb4_general_ci)")
        sess.execute("CREATE INDEX iv ON src (v)")
        sess.execute("CREATE TABLE dst LIKE src")
        sess.execute("INSERT INTO dst VALUES (1, 'X')")
        assert sess.query("SELECT COUNT(*) FROM dst WHERE v = 'x'"
                          ).rows == [(1,)]
        # independent tables
        assert sess.query("SELECT COUNT(*) FROM src").rows == [(0,)]

    def test_set_password_and_transaction(self, sess):
        sess.execute("CREATE USER 'u1'@'%'")
        sess.execute("SET PASSWORD FOR 'u1'@'%' = 'secret'")
        from tidb_tpu.privilege import encode_password
        assert sess.query(
            "SELECT authentication_string FROM mysql.user "
            "WHERE user = 'u1'").rows == [(encode_password("secret"),)]
        sess.execute("SET SESSION TRANSACTION ISOLATION LEVEL "
                     "READ COMMITTED")

    def test_alter_set_default_applies(self, sess):
        sess.execute("CREATE TABLE d (id BIGINT PRIMARY KEY, v BIGINT)")
        sess.execute("ALTER TABLE d ALTER COLUMN v SET DEFAULT 42")
        sess.execute("INSERT INTO d (id) VALUES (1)")
        assert sess.query("SELECT v FROM d").rows == [(42,)]
        sess.execute("ALTER TABLE d ALTER COLUMN v DROP DEFAULT")

    def test_show_stats_after_analyze(self, sess):
        sess.execute("CREATE TABLE st (id BIGINT PRIMARY KEY, v BIGINT)")
        sess.execute("INSERT INTO st VALUES " + ",".join(
            f"({i},{i % 7})" for i in range(100)))
        sess.execute("ANALYZE TABLE st")
        rows = sess.query("SHOW STATS_META WHERE table_name = 'st'").rows
        assert len(rows) == 1 and rows[0][4] == 100
        assert sess.query("SHOW STATS_HISTOGRAMS "
                          "WHERE table_name = 'st'").rows
        assert sess.query("SHOW STATS_BUCKETS "
                          "WHERE table_name = 'st'").rows

    def test_drop_stats(self, sess):
        sess.execute("CREATE TABLE ds (id BIGINT PRIMARY KEY)")
        sess.execute("INSERT INTO ds VALUES (1)")
        sess.execute("ANALYZE TABLE ds")
        sess.execute("DROP STATS ds")
        assert sess.query("SHOW STATS_META WHERE table_name = 'ds'"
                          ).rows == []

    def test_admin_cancel_missing_job(self, sess):
        rows = sess.query("ADMIN CANCEL DDL JOBS 99999").rows
        assert rows == [(99999, "not found")]

    def test_flush_tables_and_drop_view(self, sess):
        sess.execute("FLUSH TABLES")
        sess.execute("DROP VIEW IF EXISTS nothing")
        from tidb_tpu.session import SQLError
        with pytest.raises(SQLError):
            sess.execute("DROP VIEW nothing")


class TestMultiTableDelete:
    @pytest.fixture
    def sess(self):
        from tidb_tpu.session import Session
        from tidb_tpu.store.storage import new_mock_storage
        s = Session(new_mock_storage())
        s.execute("CREATE DATABASE md; USE md")
        s.execute("CREATE TABLE t1 (id BIGINT PRIMARY KEY, v BIGINT)")
        s.execute("CREATE TABLE t2 (id BIGINT PRIMARY KEY, v BIGINT)")
        s.execute("CREATE TABLE t3 (id BIGINT PRIMARY KEY)")
        s.execute("INSERT INTO t1 VALUES (1, 10), (2, 20), (3, 30)")
        s.execute("INSERT INTO t2 VALUES (1, 1), (3, 3), (4, 4)")
        s.execute("INSERT INTO t3 VALUES (1), (3)")
        yield s
        s.close()

    def test_delete_from_two_targets(self, sess):
        sess.execute("DELETE t1, t2 FROM t1 INNER JOIN t2 "
                     "ON t1.id = t2.id WHERE t1.id > 0")
        # matched ids 1 and 3 deleted from both; unmatched stay
        assert sess.query("SELECT id FROM t1 ORDER BY id").rows == [(2,)]
        assert sess.query("SELECT id FROM t2 ORDER BY id").rows == [(4,)]

    def test_using_form_with_extra_table(self, sess):
        sess.execute("DELETE FROM t1 USING t1 INNER JOIN t3 "
                     "ON t1.id = t3.id")
        assert sess.query("SELECT id FROM t1 ORDER BY id").rows == [(2,)]
        # t3 was only a filter source, untouched
        assert sess.query("SELECT COUNT(*) FROM t3").rows == [(2,)]

    def test_indexes_maintained(self, sess):
        sess.execute("CREATE INDEX iv ON t1 (v)")
        sess.execute("DELETE t1 FROM t1 INNER JOIN t2 ON t1.id = t2.id")
        assert sess.query("SELECT id FROM t1 WHERE v = 10").rows == []
        assert sess.query("SELECT id FROM t1 WHERE v = 20").rows == [(2,)]

    def test_rollback(self, sess):
        sess.execute("BEGIN")
        sess.execute("DELETE t1, t2 FROM t1 INNER JOIN t2 "
                     "ON t1.id = t2.id")
        sess.execute("ROLLBACK")
        assert sess.query("SELECT COUNT(*) FROM t1").rows == [(3,)]
        assert sess.query("SELECT COUNT(*) FROM t2").rows == [(3,)]


class TestReviewRegressions:
    @pytest.fixture
    def sess(self):
        from tidb_tpu.session import Session
        from tidb_tpu.store.storage import new_mock_storage
        s = Session(new_mock_storage())
        s.execute("CREATE DATABASE rr; USE rr")
        yield s
        s.close()

    def test_change_column_first_reorders(self, sess):
        sess.execute("CREATE TABLE c (a BIGINT PRIMARY KEY, b BIGINT)")
        sess.execute("INSERT INTO c VALUES (1, 2)")
        sess.execute("ALTER TABLE c CHANGE COLUMN b b2 BIGINT FIRST")
        rows = sess.query("SELECT * FROM c").rows
        assert rows == [(2, 1)]          # b2 now leads
        cols = [r[0] for r in sess.query("SHOW COLUMNS FROM c").rows]
        assert cols[0] == "b2"

    def test_multi_delete_needs_privs(self):
        from tidb_tpu.bootstrap import bootstrap
        from tidb_tpu.session import Session, SQLError
        from tidb_tpu.store.storage import new_mock_storage
        st = new_mock_storage()
        bootstrap(st)
        r = Session(st, user="root", host="%")
        r.execute("CREATE DATABASE pd2; USE pd2")
        r.execute("CREATE TABLE t1 (id BIGINT PRIMARY KEY)")
        r.execute("CREATE TABLE t2 (id BIGINT PRIMARY KEY)")
        r.execute("INSERT INTO t1 VALUES (1)")
        r.execute("INSERT INTO t2 VALUES (1)")
        r.execute("CREATE USER w")
        r.execute("GRANT DELETE ON pd2.t1 TO w")
        s = Session(st, user="w", host="localhost")
        s.execute("USE pd2")
        # DELETE priv on t1 but no SELECT on t2: the join read is denied
        with pytest.raises(SQLError, match="SELECT"):
            s.execute("DELETE t1 FROM t1 INNER JOIN t2 "
                      "ON t1.id = t2.id")
        r.execute("GRANT SELECT ON pd2.t1 TO w")
        r.execute("GRANT SELECT ON pd2.t2 TO w")
        s.execute("DELETE t1 FROM t1 INNER JOIN t2 ON t1.id = t2.id")
        s.close()
        assert r.query("SELECT COUNT(*) FROM t1").rows == [(0,)]
        r.close()

    def test_set_own_password_matches_host_pattern(self):
        from tidb_tpu.bootstrap import bootstrap
        from tidb_tpu.privilege import encode_password
        from tidb_tpu.session import Session, SQLError
        from tidb_tpu.store.storage import new_mock_storage
        st = new_mock_storage()
        bootstrap(st)
        r = Session(st, user="root", host="%")
        r.execute("CREATE USER 'u'@'localhost'")
        s = Session(st, user="u", host="localhost")
        s.execute("SET PASSWORD = 'mine'")      # no FOR: own account
        assert r.query("SELECT authentication_string FROM mysql.user "
                       "WHERE user = 'u'").rows == \
            [(encode_password("mine"),)]
        # FOR any account needs CREATE USER
        with pytest.raises(SQLError):
            s.execute("SET PASSWORD FOR 'root'@'%' = 'x'")
        s.close()
        r.close()


class TestThirdReviewRegressions:
    def test_multi_delete_where_subquery_needs_select(self):
        from tidb_tpu.bootstrap import bootstrap
        from tidb_tpu.session import Session, SQLError
        from tidb_tpu.store.storage import new_mock_storage
        st = new_mock_storage()
        bootstrap(st)
        r = Session(st, user="root", host="%")
        r.execute("CREATE DATABASE p3; USE p3")
        r.execute("CREATE TABLE t1 (id BIGINT PRIMARY KEY)")
        r.execute("CREATE TABLE t2 (id BIGINT PRIMARY KEY)")
        r.execute("CREATE DATABASE other")
        r.execute("CREATE TABLE other.secret (id BIGINT PRIMARY KEY)")
        r.execute("CREATE USER w2")
        for t in ("t1", "t2"):
            r.execute(f"GRANT DELETE ON p3.{t} TO w2")
            r.execute(f"GRANT SELECT ON p3.{t} TO w2")
        s = Session(st, user="w2", host="localhost")
        s.execute("USE p3")
        with pytest.raises(SQLError, match="SELECT"):
            s.execute("DELETE t1 FROM t1 INNER JOIN t2 ON t1.id=t2.id "
                      "WHERE t1.id IN (SELECT id FROM other.secret)")
        s.close(); r.close()

    def test_set_password_prefers_specific_host(self):
        from tidb_tpu.bootstrap import bootstrap
        from tidb_tpu.privilege import encode_password
        from tidb_tpu.session import Session
        from tidb_tpu.store.storage import new_mock_storage
        st = new_mock_storage()
        bootstrap(st)
        r = Session(st, user="root", host="%")
        r.execute("CREATE USER 'u'@'%' IDENTIFIED BY 'wild'")
        r.execute("CREATE USER 'u'@'localhost' IDENTIFIED BY 'loc'")
        s = Session(st, user="u", host="localhost")
        s.execute("SET PASSWORD = 'newpw'")
        rows = dict(r.query(
            "SELECT host, authentication_string FROM mysql.user "
            "WHERE user = 'u'").rows)
        assert rows["localhost"] == encode_password("newpw")
        assert rows["%"] == encode_password("wild")   # untouched
        s.close(); r.close()

    def test_change_after_self_rejected_at_submit(self):
        from tidb_tpu.session import Session, SQLError
        from tidb_tpu.store.storage import new_mock_storage
        s = Session(new_mock_storage())
        s.execute("CREATE DATABASE a3; USE a3")
        s.execute("CREATE TABLE c (a BIGINT PRIMARY KEY, b BIGINT)")
        with pytest.raises(SQLError, match="Unknown column"):
            s.execute("ALTER TABLE c CHANGE COLUMN b b2 BIGINT AFTER b")
        with pytest.raises(SQLError, match="Unknown column"):
            s.execute("ALTER TABLE c CHANGE COLUMN b b2 BIGINT "
                      "AFTER b2")
        s.close()

    def test_pallas_dispatcher_1d_shape(self):
        import numpy as np
        import jax.numpy as jnp
        from tidb_tpu.ops import pallas_agg as pa
        v = jnp.asarray(np.ones(10, dtype=np.float32))
        ids = jnp.asarray(np.zeros(10, dtype=np.int32))
        out = pa.segment_sum(v, ids, 4)
        assert out.ndim == 1 and out.shape[0] == 4
        # the pallas path itself also squeezes via the dispatcher
        out2 = pa.segment_sum_pallas(v, ids, 4, interpret=True)
        assert out2.shape == (4, 1)      # raw kernel keeps the lane axis


class TestMinedExprCases:
    """Harvested from the reference's executor test corpus (table-free
    MustQuery cases run against our session)."""

    @pytest.fixture
    def sess(self):
        from tidb_tpu.session import Session
        from tidb_tpu.store.storage import new_mock_storage
        s = Session(new_mock_storage())
        s.execute("CREATE DATABASE mx; USE mx")
        yield s
        s.close()

    def test_last_insert_id(self, sess):
        sess.execute("CREATE TABLE a (id BIGINT PRIMARY KEY "
                     "AUTO_INCREMENT, v BIGINT)")
        sess.execute("INSERT INTO a (v) VALUES (7), (8)")
        first = sess.query("SELECT LAST_INSERT_ID()").rows[0][0]
        assert first >= 1
        sess.execute("INSERT INTO a (v) VALUES (9)")
        second = sess.query("SELECT LAST_INSERT_ID()").rows[0][0]
        assert second > first    # first id of the LATEST insert

    def test_show_warnings_and_empty_catalogs(self, sess):
        assert sess.query("SHOW WARNINGS").rows == []
        assert sess.query("SHOW ERRORS").rows == []
        assert sess.query("SHOW PLUGINS").rows == []
        assert sess.query("SHOW PROFILES").rows == []
        assert sess.query("SHOW TRIGGERS").rows == []
        assert sess.query("SHOW EVENTS WHERE Db = 'x'").rows == []
        assert sess.query("SHOW PROCEDURE STATUS").rows == []
        assert sess.query("SHOW MASTER STATUS").rows == []

    def test_unhex_binary_round_trip(self, sess):
        assert sess.query("SELECT HEX(UNHEX('FF'))").rows == [("FF",)]
        assert sess.query(
            "SELECT INET6_NTOA(UNHEX("
            "'FDFE0000000000005A55CAFFFEFA9089'))").rows == \
            [("fdfe::5a55:caff:fefa:9089",)]

    def test_sleep_bad_arg_clean_error(self, sess):
        from tidb_tpu.session import SQLError
        with pytest.raises(SQLError, match="sleep"):
            sess.query("SELECT SLEEP('a')")

    def test_wide_literal_multiply_exact(self, sess):
        from decimal import Decimal, localcontext
        got = sess.query(
            "select 123344532434234234267890.0 * "
            "1234567118923479823749823749.230").rows[0][0]
        with localcontext() as ctx:
            ctx.prec = 70
            want = (Decimal("123344532434234234267890.0") *
                    Decimal("1234567118923479823749823749.230"))
            assert Decimal(got) == want


class TestFifthReviewRegressions:
    """Fixes from the fifth review pass."""

    @pytest.fixture
    def sess(self):
        from tidb_tpu.session import Session
        from tidb_tpu.store.storage import new_mock_storage
        s = Session(new_mock_storage())
        s.execute("CREATE DATABASE rv5; USE rv5")
        yield s
        s.close()

    def test_last_insert_id_ignores_hidden_rowid(self, sess):
        sess.execute("CREATE TABLE noauto (a INT, b INT)")
        sess.execute("INSERT INTO noauto VALUES (1, 2)")
        # hidden _tidb_rowid allocation must NOT leak into
        # LAST_INSERT_ID (MySQL: 0 when no AUTO_INCREMENT was used)
        assert sess.query("SELECT LAST_INSERT_ID()").rows == [(0,)]

    def test_unhex_uniform_bytes_sort_and_compare(self, sess):
        sess.execute("CREATE TABLE hx (h VARCHAR(32))")
        sess.execute("INSERT INTO hx VALUES ('41'), ('FF'), ('42')")
        rows = sess.query("SELECT HEX(UNHEX(h)) FROM hx "
                          "ORDER BY UNHEX(h)").rows
        assert [r[0] for r in rows] == ["41", "42", "FF"]
        # bytes vs str literal comparison must not raise
        rows = sess.query(
            "SELECT h FROM hx WHERE UNHEX(h) = 'A'").rows
        assert rows == [("41",)]
        assert sess.query(
            "SELECT LENGTH(UNHEX('FF41'))").rows == [(2,)]

    def test_show_warnings_populated_and_cleared(self, sess):
        sess.execute("DROP TABLE IF EXISTS ghost")
        rows = sess.query("SHOW WARNINGS").rows
        assert rows == [("Note", 1051, "Unknown table 'rv5.ghost'")]
        # SHOW WARNINGS itself does not clear the area
        assert sess.query("SHOW WARNINGS").rows == rows
        # errors-only view filters out notes
        assert sess.query("SHOW ERRORS").rows == []
        # any other statement resets the diagnostics area
        sess.query("SELECT 1")
        assert sess.query("SHOW WARNINGS").rows == []


class TestSessionLongtail:
    """SHOW ... WHERE, no-FROM aggregates, user variables, PREPARE FROM."""

    @pytest.fixture
    def sess(self):
        from tidb_tpu.session import Session
        from tidb_tpu.store.storage import new_mock_storage
        s = Session(new_mock_storage())
        s.execute("CREATE DATABASE lt; USE lt")
        yield s
        s.close()

    def test_show_variables_where(self, sess):
        rows = sess.query("show global variables where "
                          "variable_name = 'autocommit'").rows
        assert rows == [("autocommit", "1")]
        rows = sess.query("show variables where "
                          "Variable_name = 'sql_mode'").rows
        assert rows == [("sql_mode", "STRICT_TRANS_TABLES")]

    def test_no_from_aggregates(self, sess):
        assert sess.query("select sum(1.2e2) * 0.1").rows == [(12.0,)]
        assert sess.query("select count(*)").rows == [(1,)]
        assert sess.query("select max(3) + min(2)").rows == [(5,)]

    def test_user_var_assignment(self, sess):
        assert sess.query("select @tmp1 := 11, @tmp2").rows == \
            [(11, None)]
        assert sess.query("select @tmp1").rows == [(11,)]
        # left-to-right: later items see earlier assignments
        assert sess.query(
            "select @x := 1 + 2, @y := concat('a','b'), @x + 1"
        ).rows == [(3, "ab", 4)]

    def test_prepare_from_user_variable(self, sess):
        sess.execute("SET @q = 'select ? + 1'")
        sess.execute("PREPARE st FROM @q")
        sess.execute("SET @v = 41")
        assert sess.query("execute st using @v").rows == [(42,)]
        sess.execute("DEALLOCATE PREPARE st")
        from tidb_tpu.session import SQLError
        with pytest.raises(SQLError):
            sess.query("execute st using @v")

    def test_sixth_review_regressions(self, sess):
        from tidb_tpu.session import SQLError
        # UNHEX IN-list: binary column lifts for the membership test
        sess.execute("CREATE TABLE hx6 (h VARCHAR(32))")
        sess.execute("INSERT INTO hx6 VALUES ('41'), ('FF'), ('42')")
        rows = sess.query("SELECT h FROM hx6 WHERE UNHEX(h) IN "
                          "('A','B') ORDER BY h").rows
        assert [r[0] for r in rows] == ["41", "42"]
        # no-FROM aggregate honors LIMIT/OFFSET
        assert sess.query("SELECT COUNT(*) LIMIT 0").rows == []
        assert sess.query("SELECT COUNT(*) LIMIT 1").rows == [(1,)]
        # SHOW ... WHERE compares case-insensitively
        assert sess.query("show variables where variable_name = "
                          "'AUTOCOMMIT'").rows == [("autocommit", "1")]
        # @v := <bad expr> keeps the SQLError contract
        with pytest.raises(SQLError):
            sess.query("select @e := sleep('x')")


class TestMinedFlowFixes:
    """Fixes surfaced by replaying reference executor-test flows."""

    @pytest.fixture
    def sess(self):
        from tidb_tpu.session import Session
        from tidb_tpu.store.storage import new_mock_storage
        s = Session(new_mock_storage())
        s.execute("CREATE DATABASE mf; USE mf")
        yield s
        s.close()

    def test_having_without_group_by(self, sess):
        sess.execute("CREATE TABLE t (c1 INT, c3 INT)")
        sess.execute("INSERT INTO t VALUES (1,3),(2,1),(3,2)")
        assert sess.query(
            "select c1 as c2, c3 from t having c2 = 2").rows == [(2, 1)]
        assert sess.query(
            "select t.c1 from t having c1 = 1").rows == [(1,)]

    def test_positional_order_by_star(self, sess):
        sess.execute("CREATE TABLE t (a INT, b INT)")
        sess.execute("INSERT INTO t VALUES (1,2),(2,1)")
        assert sess.query("select * from t order by 2").rows == \
            [(2, 1), (1, 2)]

    def test_insert_empty_values(self, sess):
        sess.execute("CREATE TABLE t (id BIGINT PRIMARY KEY "
                     "AUTO_INCREMENT, v INT DEFAULT 7)")
        sess.execute("INSERT INTO t VALUES ()")
        sess.execute("INSERT INTO t VALUES (), ()")
        assert sess.query("select * from t order by id").rows == \
            [(1, 7), (2, 7), (3, 7)]

    def test_auto_increment_sequential_across_statements(self, sess):
        sess.execute("CREATE TABLE t (id BIGINT PRIMARY KEY "
                     "AUTO_INCREMENT, v INT)")
        for v in (11, 22, 33):
            sess.execute(f"INSERT INTO t (v) VALUES ({v})")
        assert sess.query("select id from t order by id").rows == \
            [(1,), (2,), (3,)]
        # explicit id inside the cached batch: skip past it, not +4000
        sess.execute("INSERT INTO t VALUES (100, 44)")
        sess.execute("INSERT INTO t (v) VALUES (55)")
        assert sess.query("select max(id) from t").rows == [(101,)]

    def test_index_hints_and_prefix_index(self, sess):
        sess.execute("CREATE TABLE t (id INT PRIMARY KEY, v INT, "
                     "KEY idx(v))")
        sess.execute("INSERT INTO t VALUES (1, 10), (2, 20)")
        assert sess.query("select * from t ignore index(idx) "
                          "where v = 10").rows == [(1, 10)]
        assert sess.query("select * from t force index(idx) "
                          "where v = 20").rows == [(2, 20)]
        sess.execute("create index idx_p on t (v(3))")

    def test_set_do_user_vars_and_current_ts(self, sess):
        sess.execute("SET @tmp = 1; SET @tmp := @tmp + 1")
        assert sess.query("select @tmp").rows == [(2,)]
        sess.execute("do 1, @a := 5")
        assert sess.query("select @a").rows == [(5,)]
        assert sess.query("select @@tidb_current_ts").rows == [(0,)]

    def test_enum_numeric_context(self, sess):
        sess.execute("CREATE TABLE t (c ENUM('a','b','c'))")
        sess.execute("INSERT INTO t VALUES ('b'), ('a')")
        assert sess.query("select c + 1 from t where c = 2").rows == \
            [(3,)]
        assert sess.query("select c from t where c = 'b'").rows == \
            [("b",)]

    def test_sum_string_prefix_coercion(self, sess):
        sess.execute("CREATE TABLE t (id INT, b VARCHAR(10))")
        sess.execute("INSERT INTO t VALUES (1, '1ff'), (1, '2')")
        assert sess.query("select id, sum(b) from t group by id"
                          ).rows == [(1, 3.0)]

    def test_information_schema_charsets(self, sess):
        rows = sess.query(
            "SELECT CHARACTER_SET_NAME FROM "
            "INFORMATION_SCHEMA.CHARACTER_SETS WHERE MAXLEN = 4").rows
        assert rows == [("utf8mb4",)]
        assert len(sess.query(
            "SELECT * FROM INFORMATION_SCHEMA.COLLATIONS").rows) >= 4

    def test_seventh_review_regressions(self, sess):
        from tidb_tpu.session import SQLError
        # SET applies left-to-right within one statement
        sess.execute("SET @a7 = 1, @b7 = @a7 + 1")
        assert sess.query("select @a7, @b7").rows == [(1, 2)]
        # HAVING: a real column shadows the select alias
        sess.execute("CREATE TABLE sh (c1 INT, c2 INT)")
        sess.execute("INSERT INTO sh VALUES (5, 9)")
        assert sess.query("SELECT c1 AS c2, c2 AS x FROM sh "
                          "HAVING c2 = 5").rows == []
        assert sess.query("SELECT c1 AS z FROM sh HAVING z = 5"
                          ).rows == [(5,)]
        # () shorthand is illegal with an explicit column list
        sess.execute("CREATE TABLE a7 (id BIGINT PRIMARY KEY "
                     "AUTO_INCREMENT, v INT)")
        with pytest.raises(SQLError, match="Column count"):
            sess.execute("INSERT INTO a7 (v) VALUES ()")
