"""Device join kernel vs a python ground truth."""

import numpy as np
import pytest

from tidb_tpu.ops.join import JoinKernel, JoinKeyEncoder


def _truth_pairs(bk, pk):
    table = {}
    for i in range(len(bk[0][0])):
        if all(v[i] for _d, v in bk):
            table.setdefault(tuple(d[i] for d, _v in bk), []).append(i)
    pairs = set()
    for i in range(len(pk[0][0])):
        if any(not v[i] for _d, v in pk):
            continue
        for r in table.get(tuple(d[i] for d, _v in pk), ()):
            pairs.add((i, r))
    return pairs


def _got_pairs(kernel, bk, pk):
    li, ri = kernel(bk, pk, len(bk[0][0]), len(pk[0][0]))
    return set(zip(li.tolist(), ri.tolist()))


def test_join_int_keys_with_dups_and_nulls():
    rng = np.random.default_rng(1)
    nb, npr = 5000, 7000
    bkd = rng.integers(0, 800, nb).astype(np.int64)
    bkv = rng.random(nb) > 0.05
    pkd = rng.integers(0, 1000, npr).astype(np.int64)
    pkv = rng.random(npr) > 0.05
    bk, pk = [(bkd, bkv)], [(pkd, pkv)]
    k = JoinKernel(1)
    assert _got_pairs(k, bk, pk) == _truth_pairs(bk, pk)


def test_join_multi_key():
    rng = np.random.default_rng(2)
    nb, npr = 3000, 4000
    bk = [(rng.integers(0, 40, nb).astype(np.int64),
           np.ones(nb, dtype=bool)),
          (rng.normal(size=nb).round(1), rng.random(nb) > 0.1)]
    pk = [(rng.integers(0, 40, npr).astype(np.int64),
           np.ones(npr, dtype=bool)),
          (rng.normal(size=npr).round(1), rng.random(npr) > 0.1)]
    k = JoinKernel(2)
    assert _got_pairs(k, bk, pk) == _truth_pairs(bk, pk)


def test_join_overflow_retry():
    # heavy skew: one key matches everything -> output 1024*64 pairs,
    # forcing at least one capacity doubling from the initial bucket
    nb, npr = 64, 4096
    bk = [(np.zeros(nb, dtype=np.int64), np.ones(nb, dtype=bool))]
    pk = [(np.zeros(npr, dtype=np.int64), np.ones(npr, dtype=bool))]
    k = JoinKernel(1)
    got = _got_pairs(k, bk, pk)
    assert len(got) == nb * npr


def test_join_string_keys_shared_dict():
    rng = np.random.default_rng(3)
    nb, npr = 2000, 3000
    words_b = np.array([f"w{v}" for v in rng.integers(0, 50, nb)],
                       dtype=object)
    words_p = np.array([f"w{v}" for v in rng.integers(0, 70, npr)],
                       dtype=object)
    bv = rng.random(nb) > 0.05
    pv = rng.random(npr) > 0.05
    enc = JoinKeyEncoder(1)
    bk = enc.fit_build([(words_b, bv)])
    pk = enc.transform_probe([(words_p, pv)])
    k = JoinKernel(1)
    got = _got_pairs(k, bk, pk)
    # truth over original string values
    truth = _truth_pairs([(words_b, bv)], [(words_p, pv)])
    assert got == truth


def test_join_empty_sides():
    k = JoinKernel(1)
    e = (np.empty(0, dtype=np.int64), np.empty(0, dtype=bool))
    d = (np.arange(10, dtype=np.int64), np.ones(10, dtype=bool))
    assert _got_pairs(k, [e], [d]) == set()
    assert _got_pairs(k, [d], [e]) == set()
