"""Unit tests for the lint engine itself (tidb_tpu/lint/engine.py):
suppression parsing and scoping, the unused-suppression and vacuity
guards, legacy alias tags, and positive/negative fixture snippets for
each of the six project-specific rules. The repo-level assertions (all
rules clean on the tree) live in tests/test_lint.py."""

import pytest

from tidb_tpu.lint import REGISTRY, selfcheck
from tidb_tpu.lint.engine import (BAD_RULE, REPO, UNUSED_RULE, Forest,
                                  Rule, run)

EXEC_REL = "tidb_tpu/executor/x.py"
OPS_REL = "tidb_tpu/ops/x.py"
STORE_REL = "tidb_tpu/store/x.py"

ALLOC = "import numpy as np\n"          # line 1


def lint(sources, rules=None, root=None):
    forest = Forest.from_sources(sources, root=root)
    return run(rules=rules, forest=forest, with_selfcheck=False,
               with_vacuity=False)


def rules_of(report):
    return [f.rule for f in report.findings]


# -- suppression parsing and scope ------------------------------------------

def test_tag_on_line_above_suppresses():
    src = (ALLOC +
           "def f(n):\n"
           "    # lint: exempt[memtrack-alloc] caller bills these rows\n"
           "    return np.empty(n)\n")
    rep = lint({EXEC_REL: src}, rules=["memtrack-alloc"])
    assert rep.findings == []


def test_tag_trailing_same_line_suppresses():
    src = (ALLOC +
           "def f(n):\n"
           "    return np.empty(n)"
           "  # lint: exempt[memtrack-alloc] caller bills these rows\n")
    rep = lint({EXEC_REL: src}, rules=["memtrack-alloc"])
    assert rep.findings == []


def test_tag_two_lines_up_does_not_suppress():
    src = (ALLOC +
           "def f(n):\n"
           "    # lint: exempt[memtrack-alloc] too far from the site\n"
           "    n = n + 1\n"
           "    return np.empty(n)\n")
    rep = lint({EXEC_REL: src}, rules=["memtrack-alloc"])
    assert "memtrack-alloc" in rules_of(rep)        # finding survives
    assert UNUSED_RULE in rules_of(rep)             # and the tag is stale


def test_wrong_rule_name_does_not_suppress():
    src = (ALLOC +
           "def f(n):\n"
           "    # lint: exempt[bare-except] names a different rule\n"
           "    return np.empty(n)\n")
    rep = lint({EXEC_REL: src}, rules=["memtrack-alloc"])
    assert "memtrack-alloc" in rules_of(rep)


def test_def_level_tag_covers_whole_function():
    src = (ALLOC +
           "# lint: exempt[memtrack-alloc] whole helper is audited\n"
           "def f(n):\n"
           "    a = np.empty(n)\n"
           "    b = np.empty(n)\n"
           "    return a, b\n")
    rep = lint({EXEC_REL: src}, rules=["memtrack-alloc"])
    assert rep.findings == []                       # both sites, no unused


def test_trailing_tag_covers_its_own_line_only():
    """A tag trailing statement A must not also sanction statement B
    on the next line."""
    src = (ALLOC +
           "def f(n):\n"
           "    a = np.empty(n)"
           "  # lint: exempt[memtrack-alloc] a is billed by caller\n"
           "    b = np.empty(n)\n"
           "    return a, b\n")
    rep = lint({EXEC_REL: src}, rules=["memtrack-alloc"])
    assert [f.line for f in rep.findings
            if f.rule == "memtrack-alloc"] == [4]   # b only


def test_tag_inside_string_literal_is_inert():
    """A string QUOTING the tag syntax is not a suppression: it neither
    hides an adjacent violation nor trips unused-suppression."""
    src = (ALLOC +
           "def f(n):\n"
           "    m = \"use '# lint: exempt[memtrack-alloc] why' here\"\n"
           "    return np.empty(n), m\n")
    rep = lint({EXEC_REL: src}, rules=["memtrack-alloc"])
    assert rules_of(rep) == ["memtrack-alloc"]      # real finding only


def test_trailing_tag_above_def_stays_line_scoped():
    """A tag trailing a code line that happens to sit above a def must
    NOT widen into a whole-function exemption."""
    src = (ALLOC +
           "B = np.empty(9000)  # lint: exempt[memtrack-alloc] module "
           "buffer, billed at import\n"
           "def f(n):\n"
           "    return np.empty(n)\n")
    rep = lint({EXEC_REL: src}, rules=["memtrack-alloc"])
    assert [f.rule for f in rep.findings] == ["memtrack-alloc"]
    assert rep.findings[0].line == 4                # the one inside f


def test_class_level_tag_is_not_a_blanket():
    src = (ALLOC +
           "# lint: exempt[memtrack-alloc] one reason for everything\n"
           "class Big:\n"
           "    def a(self, n):\n"
           "        return np.empty(n)\n")
    rep = lint({EXEC_REL: src}, rules=["memtrack-alloc"])
    assert "memtrack-alloc" in rules_of(rep)        # method still flagged
    assert UNUSED_RULE in rules_of(rep)             # tag covered nothing


def test_multi_rule_tag():
    src = ("import numpy as np\nimport jax.numpy as jnp\n"
           "# lint: exempt[memtrack-alloc,dtype-discipline] staging "
           "buffer billed at dispatch; exact int64 lanes\n"
           "def f(n):\n"
           "    return np.empty(n), jnp.zeros(n, dtype=jnp.int64)\n")
    rep = lint({OPS_REL: src},
               rules=["memtrack-alloc", "dtype-discipline"])
    assert rep.findings == []


def test_stacked_tags_above_one_site_all_apply():
    src = ("import numpy as np\nimport jax.numpy as jnp\n"
           "def f(n):\n"
           "    # lint: exempt[memtrack-alloc] staging billed at dispatch\n"
           "    # lint: exempt[dtype-discipline] exact int64 lanes\n"
           "    return np.empty(n), jnp.zeros(n, dtype=jnp.int64)\n")
    rep = lint({OPS_REL: src},
               rules=["memtrack-alloc", "dtype-discipline"])
    assert rep.findings == []


def test_tag_trailing_decorated_def_gets_function_scope():
    src = (ALLOC +
           "class C:\n"
           "    @staticmethod\n"
           "    def f(n):"
           "  # lint: exempt[memtrack-alloc] audited helper\n"
           "        return np.empty(n)\n")
    rep = lint({EXEC_REL: src}, rules=["memtrack-alloc"])
    assert rep.findings == []


def test_legacy_memtrack_alias_still_works():
    src = (ALLOC +
           "def f(n):\n"
           "    # memtrack: exempt - caller bills these rows\n"
           "    return np.empty(n)\n")
    rep = lint({EXEC_REL: src}, rules=["memtrack-alloc"])
    assert rep.findings == []


def test_unused_suppression_detected():
    src = (ALLOC +
           "# lint: exempt[memtrack-alloc] nothing here needs it\n"
           "X = 1\n")
    rep = lint({EXEC_REL: src}, rules=["memtrack-alloc"])
    assert rules_of(rep) == [UNUSED_RULE]


def test_reasonless_tag_is_a_finding():
    src = (ALLOC +
           "def f(n):\n"
           "    # lint: exempt[memtrack-alloc]\n"
           "    return np.empty(n)\n")
    rep = lint({EXEC_REL: src}, rules=["memtrack-alloc"])
    assert BAD_RULE in rules_of(rep)


def test_unknown_rule_tag_is_a_finding():
    src = "# lint: exempt[no-such-rule] misspelled\nX = 1\n"
    rep = lint({EXEC_REL: src})
    assert BAD_RULE in rules_of(rep)


# -- vacuity guards ---------------------------------------------------------

def test_vacuity_guard_fixture_leg():
    class HollowRule(Rule):
        """A rule whose fixture no longer triggers it."""
        fixture = "X = 1\n"

        def check(self, forest):
            return iter(())

    HollowRule.name = "hollow-rule"
    problems = selfcheck(HollowRule)
    assert problems and "fixture produced no finding" in \
        problems[0].message


def test_vacuity_guard_requires_a_fixture():
    class NoFixtureRule(Rule):
        """A rule that never declared a positive fixture."""

        def check(self, forest):
            return iter(())

    NoFixtureRule.name = "no-fixture-rule"
    problems = selfcheck(NoFixtureRule)
    assert problems and "no positive fixture" in problems[0].message


def test_vacuity_guard_min_sites_leg():
    """A rule whose scope stops matching real code fails loudly: the
    memtrack rule demands >= 30 in-tree allocation sites."""
    forest = Forest.from_sources({EXEC_REL: "X = 1\n"})
    rep = run(rules=["memtrack-alloc"], forest=forest,
              with_selfcheck=False, with_vacuity=True)
    assert any("vacuity guard" in f.message for f in rep.findings)


@pytest.mark.parametrize("name", list(REGISTRY))
def test_every_registered_rule_passes_selfcheck(name):
    """Positive fixtures: each rule still fires on the pattern it
    documents (this is the fixture leg the engine runs in CI)."""
    assert selfcheck(REGISTRY[name]) == []


# -- the six new rules: positive/negative snippets --------------------------

def test_lock_discipline_negatives():
    src = ("import threading\n"
           "_lock = threading.Lock()\n"
           "def ok_try(work):\n"
           "    _lock.acquire()\n"
           "    try:\n"
           "        work()\n"
           "    finally:\n"
           "        _lock.release()\n"
           "def ok_with(work):\n"
           "    with _lock:\n"
           "        work()\n"
           "def ok_assigns_between(work):\n"
           "    _lock.acquire()\n"
           "    state = 0\n"
           "    try:\n"
           "        work(state)\n"
           "    finally:\n"
           "        _lock.release()\n"
           "def ok_inside_try(work):\n"
           "    try:\n"
           "        _lock.acquire()\n"
           "        work()\n"
           "    finally:\n"
           "        _lock.release()\n")
    rep = lint({STORE_REL: src}, rules=["lock-discipline"])
    assert rep.findings == []


def test_lock_discipline_assign_form_with_try_finally_is_clean():
    src = ("import threading\n"
           "_lock = threading.Lock()\n"
           "def f(work):\n"
           "    got = _lock.acquire(timeout=1)\n"
           "    try:\n"
           "        work(got)\n"
           "    finally:\n"
           "        _lock.release()\n")
    rep = lint({STORE_REL: src}, rules=["lock-discipline"])
    assert rep.findings == []


def test_lock_discipline_positives():
    src = ("import threading\n"
           "_lock = threading.Lock()\n"
           "def bad_plain(work):\n"
           "    _lock.acquire()\n"
           "    work()\n"
           "    _lock.release()\n"
           "def bad_expression(work):\n"
           "    if _lock.acquire(timeout=1):\n"
           "        work()\n")
    rep = lint({STORE_REL: src}, rules=["lock-discipline"])
    assert len(rep.findings) == 2


def test_lock_discipline_ignores_files_outside_scope():
    src = "def f(lock):\n    lock.acquire()\n"
    rep = lint({"tidb_tpu/parser/x.py": src}, rules=["lock-discipline"])
    assert rep.findings == []


def test_lock_discipline_rlock_same_shape():
    """Reentrancy forgives double-acquire, not a leak on the exception
    path: RLocks are held to the same with/try-finally shape."""
    bad = ("import threading\n"
           "_mu = threading.RLock()\n"
           "def f(work):\n"
           "    _mu.acquire()\n"
           "    work()\n"
           "    _mu.release()\n")
    ok = ("import threading\n"
          "_mu = threading.RLock()\n"
          "def f(work):\n"
          "    with _mu:\n"
          "        _mu.acquire()\n"
          "        try:\n"
          "            work()\n"
          "        finally:\n"
          "            _mu.release()\n")
    assert len(lint({STORE_REL: bad},
                    rules=["lock-discipline"]).findings) == 1
    assert lint({STORE_REL: ok},
                rules=["lock-discipline"]).findings == []


def test_lock_discipline_condition_wait_notify_outside_with():
    src = ("import threading\n"
           "_cond = threading.Condition()\n"
           "def bad_wait():\n"
           "    _cond.wait()\n"
           "def bad_notify():\n"
           "    _cond.notify()\n"
           "def bad_notify_all():\n"
           "    _cond.notify_all()\n"
           "def bad_wait_for(p):\n"
           "    _cond.wait_for(p)\n")
    rep = lint({STORE_REL: src}, rules=["lock-discipline"])
    assert len(rep.findings) == 4
    assert all("RuntimeError" in f.message for f in rep.findings)


def test_lock_discipline_condition_inside_with_is_clean():
    src = ("import threading\n"
           "class Q:\n"
           "    def __init__(self):\n"
           "        self._cond = threading.Condition()\n"
           "    def get(self):\n"
           "        with self._cond:\n"
           "            while True:\n"
           "                self._cond.wait(0.1)\n"
           "    def put(self):\n"
           "        with self._cond:\n"
           "            self._cond.notify_all()\n")
    rep = lint({STORE_REL: src}, rules=["lock-discipline"])
    assert rep.findings == []


def test_lock_discipline_event_wait_not_confused_with_condition():
    """`Event.wait` / `Thread.join`-style receivers are not Conditions
    constructed in the file — no finding."""
    src = ("import threading\n"
           "_done = threading.Event()\n"
           "def f():\n"
           "    _done.wait(1.0)\n")
    rep = lint({STORE_REL: src}, rules=["lock-discipline"])
    assert rep.findings == []


def test_sysvar_registry_negative_and_positive():
    config = '_DEFS = {"tidb_tpu_knob": ("int", 1)}\n'
    ok = 'V = "tidb_tpu_knob"\n'
    rogue = 'V = "tidb_tpu_knob"\nW = "tidb_tpu_tpyo"\n'
    assert lint({"tidb_tpu/config.py": config,
                 STORE_REL: ok}, rules=["sysvar-registry"]).findings == []
    rep = lint({"tidb_tpu/config.py": config, STORE_REL: rogue},
               rules=["sysvar-registry"])
    assert len(rep.findings) == 1 and "tidb_tpu_tpyo" in \
        rep.findings[0].message


def test_sysvar_registry_docs_drift():
    """With a real repo root, a declared-but-undocumented sysvar is a
    finding (the docs leg)."""
    config = '_DEFS = {"tidb_tpu_never_documented_xyz": ("int", 1)}\n'
    rep = lint({"tidb_tpu/config.py": config}, rules=["sysvar-registry"],
               root=REPO)
    assert any("appears nowhere" in f.message for f in rep.findings)


def test_metric_cardinality_negative_and_positive():
    """Bounded-enum labels pass; per-tenant keys, per-session value
    identifiers, computed values, and non-literal label dicts are each
    findings (the label-cardinality bound: high-cardinality attribution
    belongs in the resource meter, not Prometheus series)."""
    ok = ("from tidb_tpu import metrics\n"
          "def f(outcome, s):\n"
          "    metrics.counter(metrics.Q, {'outcome': outcome})\n"
          "    metrics.histogram(metrics.Q, 1.0, {'op': s.name})\n"
          "    metrics.gauge(metrics.Q, 2.0)\n"
          "    metrics.counter(metrics.Q, None, inc=3)\n")
    support = {"tidb_tpu/metrics.py": 'Q = "tidb_tpu_queries_total"\n'}
    rep = lint({STORE_REL: ok, **support},
               rules=["metric-cardinality"])
    assert rep.findings == []
    bad = ("from tidb_tpu import metrics\n"
           "def f(session_id, labels, q):\n"
           "    metrics.counter(metrics.Q, {'session': 1})\n"
           "    metrics.counter(metrics.Q, {'op': session_id})\n"
           "    metrics.counter(metrics.Q, {'op': f'x-{q}'})\n"
           "    metrics.counter(metrics.Q, labels)\n")
    rep = lint({STORE_REL: bad, **support},
               rules=["metric-cardinality"])
    assert len(rep.findings) == 4
    msgs = " | ".join(f.message for f in rep.findings)
    assert "per-tenant" in msgs and "per-session" in msgs
    assert "computed label value" in msgs
    assert "inline dict literal" in msgs


def test_errcode_discipline_negative():
    src = ("from tidb_tpu import errcode\n"
           "def f(sess, SQLError):\n"
           "    sess.add_warning('Note', errcode.ER_DUP_ENTRY, 'dup')\n"
           "    raise SQLError('no code at all')\n")
    rep = lint({STORE_REL: src}, rules=["errcode-discipline"])
    assert rep.findings == []


def test_errcode_discipline_positive_kwarg_and_warning():
    src = ("def f(sess, SQLError):\n"
           "    sess.add_warning('Note', 1051, 'gone')\n"
           "    raise SQLError('dup', code=1062)\n")
    rep = lint({STORE_REL: src}, rules=["errcode-discipline"])
    assert len(rep.findings) == 2


def test_device_sync_negative_finalize_is_sanctioned():
    src = ("import jax\n"
           "class K:\n"
           "    def finalize(self, pending):\n"
           "        return jax.device_get(pending)\n")
    rep = lint({OPS_REL: src}, rules=["device-sync"])
    assert rep.findings == []


def test_device_sync_positive_item_and_asarray():
    src = ("import jax\nimport jax.numpy as jnp\nimport numpy as np\n"
           "def g(x):\n"
           "    a = jnp.max(x).item()\n"
           "    b = np.asarray(jnp.sum(x))\n"
           "    c = jax.device_get(x)\n"
           "    return a, b, c\n")
    rep = lint({OPS_REL: src}, rules=["device-sync"])
    assert len(rep.findings) == 3


def test_dtype_discipline_negative():
    src = ("import jax.numpy as jnp\nimport numpy as np\n"
           "def f(n):\n"
           "    a = jnp.zeros(n, dtype=jnp.int32)\n"
           "    b = np.empty(n, dtype=np.int64)   # host lanes: fine\n"
           "    return a, b\n")
    rep = lint({OPS_REL: src}, rules=["dtype-discipline"])
    assert rep.findings == []


def test_dtype_discipline_only_scans_ops():
    src = ("import jax.numpy as jnp\n"
           "def f(n):\n"
           "    return jnp.zeros(n, dtype=jnp.int64)\n")
    rep = lint({STORE_REL: src}, rules=["dtype-discipline"])
    assert rep.findings == []


def test_bare_except_negative():
    src = ("def f(work, log):\n"
           "    try:\n"
           "        work()\n"
           "    except ValueError:\n"
           "        log()\n"
           "    try:\n"
           "        work()\n"
           "    except BaseException:\n"
           "        log()\n"
           "        raise\n")
    rep = lint({STORE_REL: src}, rules=["bare-except"])
    assert rep.findings == []


def test_bare_except_positive_bare_colon():
    src = ("def f(work):\n"
           "    try:\n"
           "        work()\n"
           "    except:\n"
           "        return None\n")
    rep = lint({STORE_REL: src}, rules=["bare-except"])
    assert len(rep.findings) == 1


def test_bare_except_try_finally_reraise_is_sanctioned():
    """The canonical cleanup shape — re-raise through a try/finally
    with no except clauses — must pass."""
    src = ("def f(work, ledger):\n"
           "    try:\n"
           "        work()\n"
           "    except BaseException:\n"
           "        try:\n"
           "            raise\n"
           "        finally:\n"
           "            ledger.release()\n")
    rep = lint({STORE_REL: src}, rules=["bare-except"])
    assert rep.findings == []


def test_bare_except_raise_swallowed_by_nested_try_still_flagged():
    """A raise the handler itself catches cannot sanction the
    handler."""
    src = ("def f(work, log):\n"
           "    try:\n"
           "        work()\n"
           "    except BaseException:\n"
           "        try:\n"
           "            raise ValueError('x')\n"
           "        except ValueError:\n"
           "            log()\n")
    rep = lint({STORE_REL: src}, rules=["bare-except"])
    assert len(rep.findings) == 1


def test_reasonless_alias_tag_is_a_finding():
    src = (ALLOC +
           "def f(n):\n"
           "    # memtrack: exempt\n"
           "    return np.empty(n)\n")
    rep = lint({EXEC_REL: src}, rules=["memtrack-alloc"])
    assert BAD_RULE in rules_of(rep)


# -- decode-discipline -------------------------------------------------------

def test_decode_call_outside_registered_helper_flagged():
    src = ("from tidb_tpu.ops.encoded import decode_codes\n"
           "def serve(values, codes):\n"
           "    return decode_codes(values, codes)\n")
    rep = lint({OPS_REL: src}, rules=["decode-discipline"])
    assert rules_of(rep) == ["decode-discipline"]


def test_decode_gather_comprehension_flagged():
    src = ("def serve(dict_values, codes):\n"
           "    return [dict_values[c] for c in codes]\n")
    rep = lint({OPS_REL: src}, rules=["decode-discipline"])
    assert rules_of(rep) == ["decode-discipline"]


def test_decode_out_of_scope_file_clean():
    src = ("def serve(dict_values, codes):\n"
           "    return [dict_values[c] for c in codes]\n")
    rep = lint({"tidb_tpu/session/x.py": src},
               rules=["decode-discipline"])
    assert rep.findings == []


def test_decode_plain_comprehension_not_decode_shaped_clean():
    src = ("def f(rows):\n"
           "    return [r[0] for r in rows]\n")
    rep = lint({OPS_REL: src}, rules=["decode-discipline"])
    assert rep.findings == []


def test_decode_tagged_site_suppressed():
    src = ("def serve(dict_values, codes):\n"
           "    # lint: exempt[decode-discipline] result formatting at the wire boundary\n"
           "    return [dict_values[c] for c in codes]\n")
    rep = lint({OPS_REL: src}, rules=["decode-discipline"])
    assert rep.findings == []


# -- failpoint-discipline ----------------------------------------------------

FP_REL = "tidb_tpu/util/failpoint.py"
FP_DECL = 'REGISTRY = {"hbm/fill": "device cache upload"}\n'


def test_failpoint_declared_eval_clean():
    src = ("from tidb_tpu.util import failpoint\n"
           "def fill():\n"
           "    failpoint.eval('hbm/fill')\n")
    rep = lint({FP_REL: FP_DECL, STORE_REL: src},
               rules=["failpoint-discipline"])
    assert rep.findings == []


def test_failpoint_undeclared_eval_flagged():
    src = ("from tidb_tpu.util import failpoint\n"
           "def fill():\n"
           "    failpoint.eval('hbm/fill')\n"
           "    failpoint.eval('not/declared')\n")
    rep = lint({FP_REL: FP_DECL, STORE_REL: src},
               rules=["failpoint-discipline"])
    assert len(rep.findings) == 1
    assert "not/declared" in rep.findings[0].message


def test_failpoint_declared_never_evaluated_flagged():
    decl = ('REGISTRY = {"hbm/fill": "upload",\n'
            '            "hbm/ghost": "nothing fires this"}\n')
    src = ("from tidb_tpu.util import failpoint\n"
           "def fill():\n"
           "    failpoint.eval('hbm/fill')\n")
    rep = lint({FP_REL: decl, STORE_REL: src},
               rules=["failpoint-discipline"])
    assert len(rep.findings) == 1
    assert rep.findings[0].file == FP_REL
    assert "hbm/ghost" in rep.findings[0].message


def test_failpoint_computed_name_flagged():
    src = ("from tidb_tpu.util import failpoint\n"
           "def fill(name):\n"
           "    failpoint.eval(name)\n")
    rep = lint({FP_REL: FP_DECL + "def fill():\n"
                "    eval_marker = None\n",
                STORE_REL: src}, rules=["failpoint-discipline"])
    assert any("string literal" in f.message for f in rep.findings)


# -- trace-names -------------------------------------------------------------

TR_REL = "tidb_tpu/trace.py"
TR_DECL = 'SPAN_NAMES = {"plan": "planning", "dispatch": "enqueue"}\n'


def test_trace_declared_span_clean():
    src = ("from tidb_tpu import trace\n"
           "def f():\n"
           "    with trace.span('plan'):\n"
           "        pass\n"
           "    with trace.span('dispatch'):\n"
           "        pass\n")
    rep = lint({TR_REL: TR_DECL, STORE_REL: src}, rules=["trace-names"])
    assert rep.findings == []


def test_trace_undeclared_span_flagged():
    src = ("from tidb_tpu import trace\n"
           "def f():\n"
           "    with trace.span('plan'):\n"
           "        pass\n"
           "    with trace.span('not/declared'):\n"
           "        pass\n"
           "    with trace.span('dispatch'):\n"
           "        pass\n")
    rep = lint({TR_REL: TR_DECL, STORE_REL: src}, rules=["trace-names"])
    assert len(rep.findings) == 1
    assert "not/declared" in rep.findings[0].message


def test_trace_computed_name_flagged():
    src = ("from tidb_tpu import trace\n"
           "def f(method):\n"
           "    trace.begin(f'storage:{method}')\n"
           "    with trace.span('plan'):\n"
           "        pass\n"
           "    with trace.span('dispatch'):\n"
           "        pass\n")
    rep = lint({TR_REL: TR_DECL, STORE_REL: src}, rules=["trace-names"])
    assert any("string literal" in f.message for f in rep.findings)


def test_trace_declared_never_opened_flagged():
    decl = ('SPAN_NAMES = {"plan": "planning",\n'
            '              "ghost": "nothing opens this"}\n')
    src = ("from tidb_tpu import trace\n"
           "def f():\n"
           "    with trace.span('plan'):\n"
           "        pass\n")
    rep = lint({TR_REL: decl, STORE_REL: src}, rules=["trace-names"])
    assert len(rep.findings) == 1
    assert rep.findings[0].file == TR_REL
    assert "ghost" in rep.findings[0].message


def test_trace_span_constructor_counts_as_use():
    # session builds its pre-closed parse span via trace.Span(...): the
    # constructor is a site (both for literal checking and liveness)
    decl = 'SPAN_NAMES = {"parse": "batch parse share"}\n'
    src = ("from tidb_tpu import trace\n"
           "def f():\n"
           "    s = trace.Span('parse')\n"
           "    return s\n")
    rep = lint({TR_REL: decl, STORE_REL: src}, rules=["trace-names"])
    assert rep.findings == []


def test_trace_alias_receiver_and_tag_suppresses():
    src = ("from tidb_tpu import trace as _trace\n"
           "def f(method):\n"
           "    _trace.begin('dispatch')\n"
           "    # lint: exempt[trace-names] wire-data method names\n"
           "    _trace.begin(f'storage:{method}')\n"
           "    with _trace.span('plan'):\n"
           "        pass\n")
    rep = lint({TR_REL: TR_DECL, STORE_REL: src}, rules=["trace-names"])
    assert rep.findings == []


def test_failpoint_enable_checked_and_tag_suppresses():
    src = ("from tidb_tpu.util import failpoint\n"
           "def arm(name):\n"
           "    failpoint.enable('typo/name', 'raise')\n"
           "    # lint: exempt[failpoint-discipline] dynamic admin front end\n"
           "    failpoint.enable(name, 'raise')\n")
    decl = FP_DECL.replace("}", "}\n") + (
        "def seam():\n    pass\n")
    hbm = ("from tidb_tpu.util import failpoint\n"
           "def fill():\n"
           "    failpoint.eval('hbm/fill')\n")
    rep = lint({FP_REL: decl, STORE_REL: src,
                "tidb_tpu/ops/x.py": hbm},
               rules=["failpoint-discipline"])
    assert len(rep.findings) == 1
    assert "typo/name" in rep.findings[0].message


# -- device-plane dataflow rules (tidb_tpu/lint/flow/device) ----------------

def test_donated_then_read_is_flagged():
    """A read of the donated buffer after a non-returning dispatch is
    a read-after-free on hardware that honors donation."""
    src = ("import jax\n"
           "from tidb_tpu.ops import runtime\n"
           "class K:\n"
           "    def __init__(self):\n"
           "        self._jitd = None\n"
           "    def _kernel(self, cols, n):\n"
           "        return cols\n"
           "    def dispatch(self, chunk):\n"
           "        cols, _d = runtime.device_put_chunk(chunk,\n"
           "                                            memo=False)\n"
           "        if self._jitd is None:\n"
           "            self._jitd = jax.jit(self._kernel,\n"
           "                                 donate_argnums=(0,))\n"
           "        pending = self._jitd(cols, 4)\n"
           "        total = cols[0].sum()\n"
           "        return pending, total\n")
    rep = lint({OPS_REL: src}, rules=["donation-safety"])
    assert "donation-safety" in rules_of(rep)
    assert any("read after" in f.message for f in rep.findings)


def test_return_dispatch_with_nondonating_twin_is_sanctioned():
    """The in-tree ops/hashagg dispatch shape: the donating branch
    RETURNS at the dispatch, so the non-donating twin on the line
    after can never see the donated buffer."""
    src = ("import jax\n"
           "from tidb_tpu.ops import runtime\n"
           "class K:\n"
           "    def __init__(self):\n"
           "        self._jit = jax.jit(self._kernel)\n"
           "        self._jitd = None\n"
           "    def _kernel(self, cols, n):\n"
           "        return cols\n"
           "    def dispatch(self, chunk, donate=False):\n"
           "        cols, _d = runtime.device_put_chunk(\n"
           "            chunk, memo=not donate)\n"
           "        if donate:\n"
           "            if self._jitd is None:\n"
           "                self._jitd = jax.jit(self._kernel,\n"
           "                                     donate_argnums=(0,))\n"
           "            return self._jitd(cols, chunk.num_rows)\n"
           "        return self._jit(cols, chunk.num_rows)\n")
    rep = lint({OPS_REL: src}, rules=["donation-safety"])
    assert rep.findings == []


def test_donating_retry_loop_is_flagged():
    """Re-dispatching a buffer bound OUTSIDE the loop donates freed
    memory on the second iteration."""
    src = ("import jax\n"
           "class K:\n"
           "    def __init__(self):\n"
           "        self._jitd = jax.jit(self._kernel,\n"
           "                             donate_argnums=(0,))\n"
           "    def _kernel(self, cols, n):\n"
           "        return cols\n"
           "    def run(self, cols):\n"
           "        out = None\n"
           "        for _ in range(3):\n"
           "            out = self._jitd(cols, 4)\n"
           "        return out\n")
    rep = lint({OPS_REL: src}, rules=["donation-safety"])
    assert any("retry loop" in f.message for f in rep.findings)


def test_nondonating_retry_reuse_is_sanctioned():
    """The PR 8 overflow-retry shape (ops/join.py): lanes carried on a
    pending token and re-dispatched through a NON-donating program are
    not donation hazards, and the program-memo key rides .cap."""
    src = ("import jax\n"
           "_PROGRAMS = {}\n"
           "def _matcher_program(cap):\n"
           "    prog = _PROGRAMS.get(cap)\n"
           "    if prog is None:\n"
           "        def kernel(bk, pk):\n"
           "            return bk\n"
           "        prog = jax.jit(kernel)\n"
           "        _PROGRAMS[cap] = prog\n"
           "    return prog\n"
           "def finalize(p):\n"
           "    res = None\n"
           "    while res is None:\n"
           "        res = _matcher_program(p.cap)(p.bk, p.pk)\n"
           "    return res\n")
    rep = lint({OPS_REL: src},
               rules=["donation-safety", "retrace-hazard"])
    assert rep.findings == []


def test_donating_transfer_with_default_memo_is_flagged():
    """memo=not donate is the contract: a memoized donated buffer is a
    dangling cache entry."""
    src = ("import jax\n"
           "from tidb_tpu.ops import runtime\n"
           "class K:\n"
           "    def __init__(self):\n"
           "        self._jitd = jax.jit(self._kernel,\n"
           "                             donate_argnums=(0,))\n"
           "    def _kernel(self, cols, n):\n"
           "        return cols\n"
           "    def dispatch(self, chunk):\n"
           "        cols, _d = runtime.device_put_chunk(chunk)\n"
           "        return self._jitd(cols, chunk.num_rows)\n")
    rep = lint({OPS_REL: src}, rules=["donation-safety"])
    assert any("memo" in f.message for f in rep.findings)


def test_config_read_not_in_fingerprint_is_flagged():
    """A config read inside a traced body and a ctor arg missing from
    the cache key are both stale-executable bugs."""
    src = ("import jax\n"
           "from tidb_tpu.ops import runtime\n"
           "from tidb_tpu import config, devplane\n"
           "class K:\n"
           "    def __init__(self, exprs, width):\n"
           "        self.exprs = exprs\n"
           "        self.width = width\n"
           "        self._jit = jax.jit(self._kernel)\n"
           "    def _kernel(self, cols, n):\n"
           "        lim = config.direct_agg_slots()\n"
           "        return (cols, self.width, lim)\n"
           "_KERNELS = runtime.FingerprintCache(8)\n"
           "def kernel_for(exprs, width):\n"
           "    fp = runtime.plan_fingerprint(None, exprs, [])\n"
           "    key = (fp, devplane.mesh_fingerprint(process=True))\n"
           "    def make():\n"
           "        return K(exprs, width)\n"
           "    return _KERNELS.get_or_create(key, make)\n")
    rep = lint({OPS_REL: src}, rules=["cache-key"])
    msgs = [f.message for f in rep.findings]
    assert any("config.direct_agg_slots" in m for m in msgs)
    assert any("width" in m and "not folded" in m for m in msgs)


def test_complete_cache_key_is_clean():
    """Folding every ctor arg and the mesh fingerprint into the key
    satisfies the completeness check."""
    src = ("import jax\n"
           "from tidb_tpu.ops import runtime\n"
           "from tidb_tpu import devplane\n"
           "class K:\n"
           "    def __init__(self, exprs, width):\n"
           "        self.exprs = exprs\n"
           "        self.width = width\n"
           "        self._jit = jax.jit(self._kernel)\n"
           "    def _kernel(self, cols, n):\n"
           "        return (cols, self.width)\n"
           "_KERNELS = runtime.FingerprintCache(8)\n"
           "def kernel_for(exprs, width):\n"
           "    fp = runtime.plan_fingerprint(None, exprs, [])\n"
           "    key = (fp, width,\n"
           "           devplane.mesh_fingerprint(process=True))\n"
           "    def make():\n"
           "        return K(exprs, width)\n"
           "    return _KERNELS.get_or_create(key, make)\n")
    rep = lint({OPS_REL: src}, rules=["cache-key"])
    assert rep.findings == []


def test_cache_key_without_mesh_fingerprint_is_flagged():
    src = ("import jax\n"
           "from tidb_tpu.ops import runtime\n"
           "class K:\n"
           "    def __init__(self, exprs):\n"
           "        self.exprs = exprs\n"
           "        self._jit = jax.jit(self._kernel)\n"
           "    def _kernel(self, cols, n):\n"
           "        return cols\n"
           "_KERNELS = runtime.FingerprintCache(8)\n"
           "def kernel_for(exprs):\n"
           "    fp = runtime.plan_fingerprint(None, exprs, [])\n"
           "    def make():\n"
           "        return K(exprs)\n"
           "    return _KERNELS.get_or_create((fp,), make)\n")
    rep = lint({OPS_REL: src}, rules=["cache-key"])
    assert any("mesh_fingerprint" in f.message for f in rep.findings)


def test_bucketed_jit_dict_is_sanctioned():
    """The meshjoin._stage2_jits[bucket] shape: a program memo keyed by
    a pow2 bucket is bounded, and the dispatch function's shaper call
    sanctions its operands."""
    src = ("import jax\n"
           "from tidb_tpu.ops import runtime\n"
           "class K:\n"
           "    def __init__(self):\n"
           "        self._jits = {}\n"
           "    def _kernel(self, cols, n):\n"
           "        return cols\n"
           "    def _get(self, bucket):\n"
           "        j = self._jits.get(bucket)\n"
           "        if j is None:\n"
           "            j = self._jits[bucket] = jax.jit(self._kernel)\n"
           "        return j\n"
           "    def launch(self, probe):\n"
           "        cols, _d = runtime.device_put_chunk(probe)\n"
           "        bkt = runtime.bucket_size(probe.num_rows)\n"
           "        return self._get(bkt)(cols, probe.num_rows)\n")
    rep = lint({OPS_REL: src}, rules=["retrace-hazard"])
    assert rep.findings == []


def test_raw_shape_dispatch_is_flagged():
    """The old ops/stats.py bug: a module-level jit dispatched on a raw
    parameter compiles one executable per input shape."""
    src = ("import jax\n"
           "import jax.numpy as jnp\n"
           "_sort = jax.jit(jnp.sort)\n"
           "def device_sort(data):\n"
           "    return _sort(data)\n")
    rep = lint({OPS_REL: src}, rules=["retrace-hazard"])
    assert any("raw size" in f.message for f in rep.findings)


def test_traced_bool_coercion_is_flagged():
    src = ("import jax\n"
           "def kernel_body(cols, n):\n"
           "    return bool(cols.sum())\n"
           "_K = jax.jit(kernel_body)\n")
    rep = lint({OPS_REL: src}, rules=["retrace-hazard"])
    assert any("bool()" in f.message for f in rep.findings)


def test_device_rule_tags_suppress_and_stale_tags_report():
    """The standard suppression machinery applies to the device rules:
    a tagged coercion is sanctioned, an unused tag is stale."""
    src = ("import jax\n"
           "def kernel_body(cols, n):\n"
           "    # lint: exempt[retrace-hazard] shape-derived static\n"
           "    return bool(cols.sum())\n"
           "_K = jax.jit(kernel_body)\n")
    rep = lint({OPS_REL: src}, rules=["retrace-hazard"])
    assert rep.findings == []
