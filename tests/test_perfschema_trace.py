"""PERFORMANCE_SCHEMA statement events + statement tracing (ref:
perfschema/const.go:120-298; the OpenTracing spans of session.go:692)."""

import logging

import pytest

from tidb_tpu import perfschema, trace
from tidb_tpu.session import Session
from tidb_tpu.store.storage import new_mock_storage


@pytest.fixture
def sess():
    perfschema.reset()
    s = Session(new_mock_storage())
    s.execute("CREATE DATABASE d")
    s.execute("USE d")
    s.execute("CREATE TABLE t (id BIGINT PRIMARY KEY, v BIGINT)")
    s.execute("INSERT INTO t VALUES (1, 10), (2, 20), (3, 30)")
    yield s
    s.close()


class TestStatementEvents:
    def test_history_records_phases(self, sess):
        sess.query("SELECT SUM(v) FROM t")
        rows = sess.query(
            "SELECT sql_text, state, timer_wait_ns, parse_ns, plan_ns, "
            "exec_ns FROM performance_schema.events_statements_history "
            "ORDER BY event_id").rows
        # the SELECT SUM itself is in-flight, not yet in history
        assert any("INSERT INTO t" in r[0] for r in rows)
        done = [r for r in rows if "SUM(v)" in r[0]]
        assert done and done[0][1] == "completed"
        _sql, _state, wait, parse, plan, execute = done[0]
        assert wait > 0 and parse > 0
        assert plan > 0 and execute > 0
        assert plan + execute <= wait

    def test_commit_phase_recorded(self, sess):
        sess.execute("BEGIN")
        sess.execute("INSERT INTO t VALUES (9, 90)")
        sess.execute("COMMIT")
        rows = sess.query(
            "SELECT sql_text, commit_ns FROM "
            "performance_schema.events_statements_history").rows
        commits = [r for r in rows if r[0] == "COMMIT"]
        assert commits and commits[-1][1] > 0

    def test_error_state(self, sess):
        with pytest.raises(Exception):
            sess.query("SELECT * FROM does_not_exist")
        rows = sess.query(
            "SELECT sql_text, state, error FROM "
            "performance_schema.events_statements_history").rows
        bad = [r for r in rows if "does_not_exist" in r[0]]
        assert bad and bad[-1][1] == "error" and bad[-1][2]

    def test_current_shows_running_statement(self, sess):
        rows = sess.query(
            "SELECT thread_id, state, sql_text FROM "
            "performance_schema.events_statements_current").rows
        me = [r for r in rows if r[0] == sess.session_id]
        # this very query is the session's current event
        assert me and me[0][1] == "running"
        assert "events_statements_current" in me[0][2]

    def test_rows_sent(self, sess):
        sess.query("SELECT * FROM t")
        rows = sess.query(
            "SELECT sql_text, rows_sent FROM "
            "performance_schema.events_statements_history").rows
        sel = [r for r in rows if r[0] == "SELECT * FROM t"]
        assert sel and sel[-1][1] == 3

    def test_show_tables_and_use(self, sess):
        sess.execute("USE performance_schema")
        rows = sess.query("SHOW TABLES").rows
        assert ("events_statements_history",) in rows
        sess.execute("USE d")

    def test_internal_sessions_invisible(self, sess):
        rows = sess.query(
            "SELECT COUNT(*) FROM "
            "performance_schema.events_statements_current "
            "WHERE thread_id <> %d" % sess.session_id).rows
        assert rows == [(0,)]


class TestTrace:
    def test_span_tree_shape(self):
        root = trace.begin("statement")
        with trace.span("plan"):
            pass
        with trace.span("execute"):
            with trace.span("cop"):
                pass
        trace.end(root)
        names = [c.name for c in root.children]
        assert names == ["plan", "execute"]
        assert root.children[1].children[0].name == "cop"
        assert trace.phase_ns(root, "plan") > 0
        assert root.duration_ns >= sum(c.duration_ns
                                       for c in root.children)

    def test_worker_thread_spans_detached(self):
        import threading
        root = trace.begin("statement")
        seen = []

        def worker():
            with trace.span("inner") as s:
                seen.append(s)

        t = threading.Thread(target=worker)
        t.start()
        t.join()
        trace.end(root)
        # a span opened on another thread never attaches to this root
        assert root.children == [] and seen

    def test_trace_log_sysvar(self, sess, caplog):
        from tidb_tpu import config
        config.set_var("tidb_tpu_trace_log", 1)
        try:
            with caplog.at_level(logging.INFO, logger="tidb_tpu.trace"):
                sess.query("SELECT COUNT(*) FROM t")
            assert any("trace for" in r.message for r in caplog.records)
            assert any("execute" in r.message for r in caplog.records)
        finally:
            config.set_var("tidb_tpu_trace_log", 0)
