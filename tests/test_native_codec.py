"""Native (C++) codec parity tests.

Ref model: util/codec/codec_test.go + bench — the native decoder must be
bit-identical with the Python reference implementation on every input,
including NULLs, defaults for rows written before ALTER ADD COLUMN,
decimal rescaling, and fallback on varlen columns.
"""

import decimal
import random

import numpy as np
import pytest

from tidb_tpu import native, tablecodec
from tidb_tpu.schema.model import ColumnInfo, TableInfo
from tidb_tpu.sqltypes import (FieldType, TypeCode, new_decimal_field,
                               new_double_field, new_int_field,
                               new_string_field)
from tidb_tpu.table import kvrows_to_chunk

pytestmark = pytest.mark.skipif(native.lib() is None,
                                reason="no C++ toolchain")


def _mk_table(cols):
    info = TableInfo(id=77, name="t", columns=[
        ColumnInfo(id=i + 1, name=f"c{i}", offset=i, ft=ft,
                   default=dflt, has_default=dflt is not None or nullable)
        for i, (ft, dflt, nullable) in enumerate(cols)])
    return info


def _encode_rows(info, rows):
    """rows: list of {col_id: datum} -> [(key, value)] record pairs."""
    out = []
    for h, r in enumerate(rows):
        ids = sorted(r)
        out.append((tablecodec.record_key(info.id, h + 1),
                    tablecodec.encode_row(ids, [r[i] for i in ids])))
    return out


def _python_chunk(info, cols, kvrows, handle_col=None):
    """Force the pure-Python decode path."""
    import tidb_tpu.table as table_mod
    orig = table_mod._kvrows_to_chunk_native
    table_mod._kvrows_to_chunk_native = lambda *a, **k: None
    try:
        return kvrows_to_chunk(info, cols, kvrows, handle_col)
    finally:
        table_mod._kvrows_to_chunk_native = orig


def _assert_chunks_equal(a, b):
    assert a.num_rows == b.num_rows
    for ca, cb in zip(a.columns, b.columns):
        np.testing.assert_array_equal(np.asarray(ca.valid),
                                      np.asarray(cb.valid))
        va, vb = np.asarray(ca.data), np.asarray(cb.data)
        if va.dtype == np.float64:
            np.testing.assert_allclose(va[ca.valid], vb[cb.valid])
        else:
            np.testing.assert_array_equal(va[ca.valid], vb[cb.valid])


class TestParity:
    def test_mixed_types_with_nulls(self):
        info = _mk_table([(new_int_field(), None, True),
                          (new_double_field(), None, True),
                          (new_decimal_field(12, 2), None, True)])
        rng = random.Random(3)
        rows = []
        for _ in range(500):
            r = {}
            if rng.random() < 0.9:
                r[1] = rng.randint(-2**62, 2**62)
            else:
                r[1] = None
            if rng.random() < 0.9:
                r[2] = rng.uniform(-1e9, 1e9)
            if rng.random() < 0.9:
                r[3] = (2, rng.randint(-10**14, 10**14))
            rows.append(r)
        kvrows = _encode_rows(info, rows)
        got = kvrows_to_chunk(info, info.columns, kvrows, None)
        want = _python_chunk(info, info.columns, kvrows, None)
        _assert_chunks_equal(got, want)

    def test_handle_column_and_subset(self):
        info = _mk_table([(new_int_field(), None, True),
                          (new_double_field(), None, True)])
        rows = [{1: i * 3, 2: i * 0.5} for i in range(100)]
        kvrows = _encode_rows(info, rows)
        cols = [info.columns[1]]      # just the double col
        got = kvrows_to_chunk(info, cols, kvrows, 0)   # handle at pos 0
        want = _python_chunk(info, cols, kvrows, 0)
        _assert_chunks_equal(got, want)
        assert list(got.columns[0].data) == list(range(1, 101))

    def test_missing_column_uses_default(self):
        # rows written before ALTER ADD COLUMN c2 DEFAULT 42
        info = _mk_table([(new_int_field(), None, True),
                          (new_int_field(), 42, False)])
        rows = [{1: i} for i in range(50)]              # c2 absent
        kvrows = _encode_rows(info, rows)
        got = kvrows_to_chunk(info, info.columns, kvrows, None)
        want = _python_chunk(info, info.columns, kvrows, None)
        _assert_chunks_equal(got, want)
        assert all(got.columns[1].data == 42)

    def test_missing_column_null_default(self):
        info = _mk_table([(new_int_field(), None, True),
                          (new_int_field(), None, True)])
        rows = [{1: i} for i in range(10)]
        kvrows = _encode_rows(info, rows)
        got = kvrows_to_chunk(info, info.columns, kvrows, None)
        assert not got.columns[1].valid.any()

    def test_decimal_rescale(self):
        # stored at frac 2, column declared frac 4 (post-MODIFY)
        info = _mk_table([(new_decimal_field(14, 4), None, True)])
        rows = [{1: (2, 12345)}, {1: (4, 98765432)}]
        kvrows = _encode_rows(info, rows)
        got = kvrows_to_chunk(info, info.columns, kvrows, None)
        want = _python_chunk(info, info.columns, kvrows, None)
        _assert_chunks_equal(got, want)
        assert got.columns[0].get(0) == decimal.Decimal("123.45")

    def test_decimal_downscale_rounds_half_away_from_zero(self):
        # stored at frac 4, column declared frac 2: MySQL rounding, both
        # signs, must match the Python path exactly
        info = _mk_table([(new_decimal_field(14, 2), None, True)])
        rows = [{1: (4, 1234567)}, {1: (4, -1234567)},
                {1: (4, 1234550)}, {1: (4, -1234550)},
                {1: (4, 1234449)}, {1: (1, -155)}]
        kvrows = _encode_rows(info, rows)
        got = kvrows_to_chunk(info, info.columns, kvrows, None)
        want = _python_chunk(info, info.columns, kvrows, None)
        _assert_chunks_equal(got, want)
        assert list(got.columns[0].data) == [
            12346, -12346, 12346, -12346, 12344, -1550]

    def test_huge_frac_shift_falls_back(self):
        # a >18-digit downscale would overflow pow10_i64: native declines,
        # python divides exactly
        info = _mk_table([(new_decimal_field(30, 0), None, True)])
        rows = [{1: (20, 12345)}, {1: (0, 42)}]
        kvrows = _encode_rows(info, rows)
        got = kvrows_to_chunk(info, info.columns, kvrows, None)
        want = _python_chunk(info, info.columns, kvrows, None)
        _assert_chunks_equal(got, want)
        assert list(got.columns[0].data) == [0, 42]

    def test_string_column_falls_back(self):
        info = _mk_table([(new_int_field(), None, True),
                          (new_string_field(), None, True)])
        rows = [{1: i, 2: f"s{i}"} for i in range(20)]
        kvrows = _encode_rows(info, rows)
        from tidb_tpu.table import _kvrows_to_chunk_native
        assert _kvrows_to_chunk_native(info.columns, kvrows, None) is None
        ch = kvrows_to_chunk(info, info.columns, kvrows, None)
        assert ch.columns[1].get(5) == "s5"

    def test_extra_stored_columns_skipped(self):
        # rows contain a dropped column's leftovers (incl. a string)
        info = _mk_table([(new_int_field(), None, True)])
        rows = [{1: i, 9: f"dead{i}", 10: 3.25} for i in range(30)]
        kvrows = _encode_rows(info, rows)
        got = kvrows_to_chunk(info, info.columns, kvrows, None)
        want = _python_chunk(info, info.columns, kvrows, None)
        _assert_chunks_equal(got, want)

    def test_fuzz_roundtrip(self):
        rng = random.Random(11)
        for _trial in range(20):
            ncols = rng.randint(1, 5)
            cols = []
            for _ in range(ncols):
                cols.append(rng.choice([
                    (new_int_field(), None, True),
                    (new_double_field(), None, True),
                    (new_decimal_field(12, rng.randint(0, 4)), None, True),
                ]))
            info = _mk_table(cols)
            rows = []
            for _ in range(rng.randint(0, 60)):
                r = {}
                for ci in info.columns:
                    if rng.random() < 0.15:
                        continue            # absent
                    if rng.random() < 0.1:
                        r[ci.id] = None     # explicit NULL
                    elif ci.ft.tp == TypeCode.NEWDECIMAL:
                        r[ci.id] = (ci.ft.frac,
                                    rng.randint(-10**12, 10**12))
                    elif ci.ft.tp == TypeCode.DOUBLE:
                        r[ci.id] = rng.uniform(-1e12, 1e12)
                    else:
                        r[ci.id] = rng.randint(-2**60, 2**60)
                rows.append(r)
            kvrows = _encode_rows(info, rows)
            got = kvrows_to_chunk(info, info.columns, kvrows, None)
            want = _python_chunk(info, info.columns, kvrows, None)
            _assert_chunks_equal(got, want)


class TestBatchPrimitives:
    def test_encode_decode_int_batch(self):
        import ctypes
        cdll = native.lib()
        cdll.encode_int_batch.argtypes = [
            ctypes.POINTER(ctypes.c_int64), ctypes.c_int64,
            ctypes.c_char_p]
        cdll.decode_int_batch.argtypes = [
            ctypes.c_char_p, ctypes.c_int64,
            ctypes.POINTER(ctypes.c_int64)]
        vals = np.array([0, 1, -1, 2**62, -2**62, 123456789],
                        dtype=np.int64)
        out = ctypes.create_string_buffer(len(vals) * 8)
        cdll.encode_int_batch(
            vals.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            len(vals), out)
        from tidb_tpu import codec
        for i, v in enumerate(vals):
            assert out.raw[i * 8:(i + 1) * 8] == codec.encode_int(int(v))
        back = np.zeros(len(vals), dtype=np.int64)
        cdll.decode_int_batch(
            out.raw, len(vals),
            back.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)))
        np.testing.assert_array_equal(back, vals)


class TestPerf:
    def test_native_not_slower(self):
        """Decode 20k rows both ways; native must at least keep up (it is
        typically ~10-30x faster; generous 1.0x bound avoids CI flakes)."""
        import time
        info = _mk_table([(new_int_field(), None, True),
                          (new_double_field(), None, True),
                          (new_int_field(), None, True)])
        rows = [{1: i, 2: i * 0.5, 3: i * 7} for i in range(20000)]
        kvrows = _encode_rows(info, rows)
        t0 = time.perf_counter()
        got = kvrows_to_chunk(info, info.columns, kvrows, None)
        t_native = time.perf_counter() - t0
        t0 = time.perf_counter()
        want = _python_chunk(info, info.columns, kvrows, None)
        t_python = time.perf_counter() - t0
        _assert_chunks_equal(got, want)
        assert t_native <= t_python, (t_native, t_python)
