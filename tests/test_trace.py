"""End-to-end statement tracing (tidb_tpu/trace.py): lifecycle span
trees, cross-thread propagation into the coprocessor fan-out,
deterministic sampling + slow-trace capture into the bounded
memtrack-billed ring, the TRACE statement (row and json forms), the
statement_traces memtable / digest / slow-log linkage, the /trace
status endpoints, the Chrome trace-event export, and the disarmed
overhead pin."""

import json
import logging
import time
import urllib.error

from tidb_tpu.util import statusclient

import pytest

from tidb_tpu import config, memtrack, perfschema, sched, trace
from tidb_tpu.session import Session
from tidb_tpu.store.storage import new_mock_storage


@pytest.fixture(autouse=True)
def _trace_isolation():
    """Fresh sampling counters + empty ring per test; sampling and
    slow-capture OFF unless the test arms them (retention is what's
    under test, not an accident of counter position)."""
    saved = {k: config.get_var(k) for k in
             ("tidb_tpu_trace_sample", "tidb_tpu_slow_trace_ms")}
    config.set_var("tidb_tpu_trace_sample", 0)
    config.set_var("tidb_tpu_slow_trace_ms", 0)
    trace.reset_for_tests()
    yield
    for k, v in saved.items():
        config.set_var(k, v)
    trace.reset_for_tests()


@pytest.fixture
def sess():
    s = Session(new_mock_storage())
    s.execute("CREATE DATABASE td")
    s.execute("USE td")
    s.execute("CREATE TABLE t (id BIGINT PRIMARY KEY, v BIGINT)")
    s.execute("INSERT INTO t VALUES " +
              ",".join(f"({i},{i % 7})" for i in range(4000)))
    yield s
    s.close()


def _names(d: dict, acc: set) -> set:
    acc.add(d["name"])
    for c in d.get("children", ()):
        _names(c, acc)
    return acc


def _span_tids(root, acc):
    acc.append(root.tid)
    for c in root.children:
        _span_tids(c, acc)
    return acc


# -- sampling / retention ----------------------------------------------------


class TestSampling:
    def test_deterministic_one_in_n(self, sess):
        config.set_var("tidb_tpu_trace_sample", 3)
        for _ in range(7):
            sess.query("SELECT COUNT(*) FROM t")
        recs = trace.ring_snapshot()
        # statements 3 and 6 of the window retain, deterministically
        assert len(recs) == 2, recs
        assert all(r["reason"] == "sampled" for r in recs)

    def test_sampling_off_retains_nothing(self, sess):
        for _ in range(5):
            sess.query("SELECT COUNT(*) FROM t")
        assert trace.ring_snapshot() == []

    def test_slow_trace_capture_links_digest_and_slow_log(
            self, sess, caplog):
        config.set_var("tidb_tpu_slow_trace_ms", 1)   # everything slow
        slow_prev = config.get_var("tidb_tpu_slow_query_ms")
        config.set_var("tidb_tpu_slow_query_ms", 0)
        try:
            with caplog.at_level(logging.WARNING,
                                 logger="tidb_tpu.slow_query"):
                sess.query("SELECT v, COUNT(*) FROM t GROUP BY v")
        finally:
            config.set_var("tidb_tpu_slow_query_ms", slow_prev)
        recs = trace.ring_snapshot()
        assert recs and recs[-1]["reason"] == "slow"
        tid = recs[-1]["trace_id"]
        # slow log carries the captured trace id
        msgs = [r.getMessage() for r in caplog.records
                if "slow query" in r.getMessage()]
        assert any(f"# Trace_id: {tid}" in m for m in msgs), msgs
        # ... and the digest summary row points at the same trace (the
        # summary is process-global, so match by the EXACT digest — a
        # prior test's GROUP BY statement may rank higher)
        dg, _ = perfschema.sql_digest(
            "SELECT v, COUNT(*) FROM t GROUP BY v")
        row = next(
            (r for r in sess.query(
                "SELECT digest, last_trace_id FROM "
                "performance_schema.events_statements_summary_by_digest"
            ).rows if r[0] == dg), None)
        assert row is not None and row[1] == tid

    def test_session_scope_set_is_honored(self, sess):
        """SET (session scope) of the trace knobs must shadow the
        globals like every other sysvar: sampling is decided under the
        overlay at begin, and the slow threshold is captured while the
        overlay is still installed (regression: both used to read only
        the global registry)."""
        sess.execute("SET tidb_tpu_trace_sample = 1")
        sess.query("SELECT COUNT(*) FROM t")
        recs = trace.ring_snapshot()
        assert recs and recs[-1]["reason"] == "sampled"
        sess.execute("SET tidb_tpu_trace_sample = 0")
        sess.execute("SET tidb_tpu_slow_trace_ms = 1")
        sess.query("SELECT v, COUNT(*) FROM t GROUP BY v")
        recs = trace.ring_snapshot()
        assert recs and recs[-1]["reason"] == "slow"
        # another session (global values: both off) retains nothing
        other = Session(sess.storage, db="td")
        try:
            n0 = len(trace.ring_snapshot())
            other.query("SELECT COUNT(*) FROM t")
            assert len(trace.ring_snapshot()) == n0
        finally:
            other.close()

    def test_internal_sessions_never_retained(self, sess):
        config.set_var("tidb_tpu_trace_sample", 1)
        internal = Session(sess.storage, db="td", internal=True)
        try:
            internal.query("SELECT COUNT(*) FROM t")
        finally:
            internal.close()
        assert trace.ring_snapshot() == []


class TestRing:
    def _retain(self, n: int) -> None:
        for _ in range(n):
            root = trace.begin("statement")
            root.forced = True
            trace.end(root)
            trace.finish_statement(root, "SELECT 1")

    def test_record_cap_bounds_the_ring(self):
        self._retain(trace._RING_CAP + 50)
        snap = trace.ring_stats()
        assert snap["records"] == trace._RING_CAP
        # ids keep counting; the ring keeps the NEWEST records
        recs = trace.ring_snapshot()
        assert recs[-1]["trace_id"] > trace._RING_CAP

    def test_ring_bytes_billed_to_server_node_and_shed_action(self):
        self._retain(10)
        snap = trace.ring_stats()
        assert snap["records"] == 10 and snap["bytes"] > 0
        node = trace._RING._node
        assert node is not None and node.host == snap["bytes"]
        # the registered shed action (driven via the SERVER chain, the
        # same door admission shedding and GET /shed use) clears it
        freed = sched.shed_server(0)
        assert freed >= snap["bytes"]
        assert trace.ring_snapshot() == []
        assert trace.ring_stats()["bytes"] == 0
        assert node.host == 0

    def test_eviction_releases_ledger_bytes(self):
        self._retain(trace._RING_CAP + 20)
        node = trace._RING._node
        assert node.host == trace.ring_stats()["bytes"]


# -- span coverage / cross-thread propagation --------------------------------


class TestSpanCoverage:
    def test_copr_fanout_spans_attach_cross_thread(self, sess):
        # multiple regions force the pool fan-out; the workers re-install
        # the dispatching span like the stats collector / memtracker
        sess.execute("SPLIT TABLE t REGIONS 4")
        captured = []
        orig_end = trace.end

        def capture(root):
            captured.append(root)
            return orig_end(root)

        trace.end = capture
        try:
            sess.query("SELECT v, COUNT(*) FROM t GROUP BY v")
        finally:
            trace.end = orig_end
        root = captured[-1]
        names = set()

        def walk(s):
            names.add(s.name)
            for c in s.children:
                walk(c)

        walk(root)
        assert {"copr.task", "copr.stream"} & names, names
        # worker spans carry worker-thread ids: the tree spans threads
        tids = set(_span_tids(root, []))
        assert len(tids) > 1, "no cross-thread spans attached"

    def test_device_spans_present_for_agg(self, sess):
        min_prev = config.get_var("tidb_tpu_device_min_rows")
        config.set_var("tidb_tpu_device_min_rows", 1)
        try:
            doc = json.loads(sess.query(
                "TRACE FORMAT='json' SELECT v, COUNT(*) FROM t "
                "GROUP BY v").rows[0][0])
        finally:
            config.set_var("tidb_tpu_device_min_rows", min_prev)
        names = _names(doc["spans"], set())
        assert {"dispatch", "finalize", "sched.slot"} <= names, names

    def test_fault_events_land_on_spans(self):
        root = trace.begin("statement")
        try:
            with trace.span("dispatch") as s:
                trace.event("device.fault", attempt=1)
        finally:
            trace.end(root)
        assert s.events and s.events[0][0] == "device.fault"
        d = trace.tree(root)
        ev = d["children"][0]["events"][0]
        assert ev["name"] == "device.fault"
        assert ev["tags"] == {"attempt": 1}


# -- TRACE statement ---------------------------------------------------------


class TestTraceStatement:
    def test_row_form(self, sess):
        rs = sess.query("TRACE SELECT COUNT(*) FROM t")
        assert rs.columns == ["operation", "start", "duration"]
        ops = [r[0] for r in rs.rows]
        assert ops[0].startswith("statement")
        assert any(o.strip().startswith("plan") for o in ops)
        assert any(o.strip().startswith("execute") for o in ops)
        # depth-indented, start/duration rendered in ms
        assert all(r[1].endswith("ms") and (r[2].endswith("ms") or
                                            r[2] == "-")
                   for r in rs.rows)

    def test_json_form_balanced_and_retained(self, sess):
        doc = json.loads(sess.query(
            "TRACE FORMAT='json' SELECT COUNT(*) FROM t").rows[0][0])
        assert doc["trace_id"] > 0

        def check(d):
            assert d["duration_us"] >= 0
            assert d["start_us"] >= 0 or d["name"] == "statement"
            for c in d.get("children", ()):
                check(c)

        check(doc["spans"])
        names = _names(doc["spans"], set())
        assert {"statement", "parse", "plan", "execute"} <= names
        # forced retention: the ring serves the same tree by id
        rec = trace.ring_get(doc["trace_id"])
        assert rec is not None and rec["reason"] == "forced"
        assert trace.validate(rec["root"]) == []

    def test_admission_span_when_admission_armed(self, sess):
        prev = config.get_var("tidb_tpu_server_mem_quota")
        config.set_var("tidb_tpu_server_mem_quota", 8 << 30)
        try:
            doc = json.loads(sess.query(
                "TRACE FORMAT='json' SELECT COUNT(*) FROM t"
            ).rows[0][0])
        finally:
            config.set_var("tidb_tpu_server_mem_quota", prev)
        assert "admission" in _names(doc["spans"], set())

    def test_trace_of_dml_executes_it(self, sess):
        sess.query("TRACE INSERT INTO t VALUES (99999, 1)")
        assert sess.query("SELECT COUNT(*) FROM t WHERE id = 99999"
                          ).rows == [(1,)]
        rec = trace.ring_snapshot()[0]
        assert rec["reason"] == "forced"

    def test_nested_trace_rejected(self, sess):
        from tidb_tpu.session import SQLError
        with pytest.raises(SQLError, match="nest"):
            sess.query("TRACE TRACE SELECT 1")

    def test_bad_format_rejected(self, sess):
        from tidb_tpu.parser import ParseError
        with pytest.raises(ParseError, match="FORMAT"):
            sess.query("TRACE FORMAT='xml' SELECT 1")

    def test_memtable_row_joinable_to_digest(self, sess):
        doc = json.loads(sess.query(
            "TRACE FORMAT='json' SELECT COUNT(*) FROM t").rows[0][0])
        rows = sess.query(
            "SELECT trace_id, digest, reason, span_count FROM "
            "information_schema.statement_traces").rows
        mine = [r for r in rows if r[0] == doc["trace_id"]]
        assert mine and mine[0][2] == "forced" and mine[0][3] >= 4
        # the digest column matches the perfschema digest of the SQL
        dg, _ = perfschema.sql_digest(
            "TRACE FORMAT='json' SELECT COUNT(*) FROM t")
        assert mine[0][1] == dg


# -- status endpoints / Chrome export ----------------------------------------


def _get_json(port: int, path: str):
    return statusclient.get_json("127.0.0.1", port, path, timeout=10)


class TestTraceEndpoints:
    def test_list_fetch_and_chrome(self, sess):
        from tidb_tpu.server.status import StatusServer
        doc = json.loads(sess.query(
            "TRACE FORMAT='json' SELECT COUNT(*) FROM t").rows[0][0])
        status = StatusServer(sess.storage, None)
        status.start()
        try:
            listing = _get_json(status.port, "/trace")
            ids = [r["trace_id"] for r in listing["traces"]]
            assert doc["trace_id"] in ids
            assert listing["ring"]["records"] == len(ids)
            one = _get_json(status.port, f"/trace/{doc['trace_id']}")
            assert one["spans"]["name"] == "statement"
            chrome = _get_json(status.port,
                               f"/trace/{doc['trace_id']}/chrome")
            _validate_chrome_doc(chrome)
            with pytest.raises(urllib.error.HTTPError) as ei:
                _get_json(status.port, "/trace/999999")
            assert ei.value.code == 404
        finally:
            status.close()


def _validate_chrome_doc(doc: dict) -> None:
    evs = doc["traceEvents"]
    assert isinstance(evs, list) and evs
    assert any(e["ph"] == "X" for e in evs)
    for e in evs:
        assert e["ph"] in ("X", "i", "M"), e
        assert isinstance(e["name"], str)
        assert isinstance(e["pid"], int) and isinstance(e["tid"], int)
        if e["ph"] in ("X", "i"):
            assert isinstance(e["ts"], (int, float)) and e["ts"] >= 0
        if e["ph"] == "X":
            assert isinstance(e["dur"], (int, float)) and e["dur"] >= 0


class TestChromeExport:
    def test_schema_and_event_instants(self):
        root = trace.begin("statement")
        root.forced = True
        try:
            with trace.span("dispatch", superchunk=0):
                trace.event("device.fault")
            with trace.span("finalize"):
                pass
        finally:
            trace.end(root)
        tid = trace.finish_statement(root, "SELECT 1")
        doc = trace.to_chrome(trace.ring_get(tid))
        _validate_chrome_doc(doc)
        xs = [e for e in doc["traceEvents"] if e["ph"] == "X"]
        assert {e["name"] for e in xs} == {"statement", "dispatch",
                                           "finalize"}
        instants = [e for e in doc["traceEvents"] if e["ph"] == "i"]
        assert instants and instants[0]["name"] == "device.fault"

    def test_phases_of_sums_to_total(self):
        root = trace.begin("statement")
        with trace.span("plan"):
            time.sleep(0.002)
        with trace.span("execute"):
            with trace.span("dispatch"):
                time.sleep(0.002)
        trace.end(root)
        ph = trace.phases_of(root)
        assert ph["plan"] > 0 and ph["device_dispatch"] > 0
        assert ph["total"] >= ph["plan"] + ph["device_dispatch"]
        assert ph["other"] >= 0


# -- overhead ----------------------------------------------------------------


class TestOverhead:
    def test_disarmed_per_statement_overhead_is_tiny(self):
        """Sampling disarmed (the N-1 of N statements): what the
        tracing subsystem adds per statement beyond the phase-skeleton
        spans perfschema always needed is the root lifecycle — begin
        (sampling decision) + end + finish_statement (retention
        check). Budget <5us per untraced statement (measured ~3us on
        the CI container)."""
        n = 20_000
        t0 = time.perf_counter()
        for _ in range(n):
            root = trace.begin("statement")
            trace.end(root)
            trace.finish_statement(root, "SELECT 1")
        per_stmt = (time.perf_counter() - t0) / n
        assert trace.ring_snapshot() == []     # truly disarmed
        assert per_stmt < 5e-6, f"{per_stmt * 1e6:.2f}us per statement"

    def test_span_skeleton_stays_cheap(self):
        """Regression guard on span() itself (it runs per dispatch and
        per phase): the full 2-phase-span statement skeleton stays
        under a loose 15us — the slotted context manager must never
        regress back to generator-based @contextmanager cost."""
        n = 10_000
        t0 = time.perf_counter()
        for _ in range(n):
            root = trace.begin("statement")
            with trace.span("plan"):
                pass
            with trace.span("execute"):
                pass
            trace.end(root)
            trace.finish_statement(root, "SELECT 1")
        per_stmt = (time.perf_counter() - t0) / n
        assert per_stmt < 15e-6, f"{per_stmt * 1e6:.2f}us per statement"
