"""SQL -> mesh execution: TPC-H Q1/Q3/Q5 routed onto the 8-device virtual
mesh through plain Session.execute, cross-checked against the host path.

This is the repo's copTask-pushdown-equivalent test tier (ref:
/root/reference/plan/dag_plan_test.go asserts pushdown plan shapes;
executor tests assert results) — here we assert BOTH the routed plan
shape (EXPLAIN) and result equality with the mesh disabled.
"""

import pytest

import tpch
from tidb_tpu import parallel
from tidb_tpu.executor import mesh as mesh_exec
from tidb_tpu.session import Session
from tidb_tpu.store.storage import new_mock_storage


@pytest.fixture(scope="module")
def sess():
    s = Session(new_mock_storage())
    s.execute("CREATE DATABASE tpch")
    s.execute("USE tpch")
    # seed=7: every one of Q1/Q3/Q5 has a NON-empty result (Q5 is empty
    # on the default seed, which would make result comparison vacuous)
    data = tpch.TpchData(seed=7)
    tpch.load(s, data)
    yield s
    s.close()


@pytest.fixture
def mesh():
    parallel.enable_mesh(8)
    yield parallel.active_mesh()
    parallel.disable_mesh()


def _explain(sess, sql):
    return "\n".join(r[0] for r in sess.query("EXPLAIN " + sql).rows)


class TestRouting:
    def test_q1_routes_to_mesh_agg(self, sess, mesh):
        assert "MeshAgg" in _explain(sess, tpch.Q1)

    def test_q3_q5_route_to_mesh_lookup(self, sess, mesh):
        e3 = _explain(sess, tpch.Q3)
        assert "MeshLookupAgg" in e3
        # probe must be the fact table, dims the unique-keyed ones
        assert "table:lineitem" in e3
        assert "dims:[orders,customer]" in e3
        e5 = _explain(sess, tpch.Q5)
        assert "MeshLookupAgg" in e5
        assert "dims:[" in e5

    def test_no_mesh_no_routing(self, sess):
        assert parallel.active_mesh() is None
        assert "MeshAgg" not in _explain(sess, tpch.Q1)
        assert "MeshLookupAgg" not in _explain(sess, tpch.Q3)

    def test_single_device_mesh_keeps_cop_path(self, sess):
        """A 1-device mesh must NOT reroute: sharding over one chip only
        adds gather overhead and routes scans around the storage-side
        columnar caches — the copTask path serves them fused from the
        HBM device cache (store/device_cache.py), measured 1.2-2.6x
        faster warm on Q1/Q3/Q5 (plan/mesh_route.route_mesh)."""
        parallel.enable_mesh(1)
        try:
            assert "MeshAgg" not in _explain(sess, tpch.Q1)
            assert "MeshLookupAgg" not in _explain(sess, tpch.Q3)
        finally:
            parallel.disable_mesh()


class TestResults:
    @pytest.mark.parametrize("q", ["Q1", "Q3", "Q5"])
    def test_matches_host(self, sess, mesh, q):
        sql = getattr(tpch, q)
        got = sess.query(sql).rows
        parallel.disable_mesh()
        try:
            want = sess.query(sql).rows
        finally:
            parallel.enable_mesh(8)
        assert want, "vacuous comparison: host result is empty"
        assert len(got) == len(want)
        for g, w in zip(got, want):
            assert len(g) == len(w)
            for a, b in zip(g, w):
                if isinstance(a, float) or isinstance(b, float):
                    assert float(a) == pytest.approx(float(b), rel=1e-9)
                else:
                    assert a == b

    def test_mesh_respects_txn_dirty_reads(self, sess, mesh):
        sess.execute("BEGIN")
        try:
            sess.execute("DELETE FROM region WHERE r_name = 'ASIA'")
            rows = sess.query(tpch.Q5).rows
            assert rows == []
        finally:
            sess.execute("ROLLBACK")
        assert len(sess.query(tpch.Q5).rows) > 0

    def test_capacity_escalation(self, sess, mesh, monkeypatch):
        # force the initial capacity below Q1's 6 groups: the executor
        # must re-plan with a larger table, not fall back
        monkeypatch.setattr(mesh_exec, "DEFAULT_CAPACITY", 4)
        calls = []
        orig = mesh_exec.MeshAggExec._run_with_escalation

        def spy(self, make, run):
            calls.append(1)
            return orig(self, make, run)

        monkeypatch.setattr(mesh_exec.MeshAggExec,
                            "_run_with_escalation", spy)
        rows = sess.query(tpch.Q1).rows
        assert len(rows) == 6 and calls


class TestShuffleJoinSQL:
    def test_duplicate_key_join_uses_shuffle(self, sess, mesh, monkeypatch):
        """A join with duplicate keys on both sides cannot be a lookup
        chain; with a mesh active HashJoinExec repartitions both sides
        via the all_to_all shuffle kernel instead."""
        from tidb_tpu import executor as ex
        from tidb_tpu.parallel import shuffle_join as sj

        sql = ("SELECT o_custkey, COUNT(*) FROM orders, lineitem "
               "WHERE o_custkey = l_suppkey GROUP BY o_custkey "
               "ORDER BY o_custkey")
        e = _explain(sess, sql)
        assert "MeshLookupAgg" not in e and "HashJoin" in e

        parallel.disable_mesh()
        try:
            want = sess.query(sql).rows
        finally:
            parallel.enable_mesh(8)
        assert want

        monkeypatch.setattr(ex.HashJoinExec, "_DEVICE_MIN_BUILD", 64)
        monkeypatch.setattr(ex.HashJoinExec, "_DEVICE_MIN_PROBE", 64)
        used = []
        orig = sj.MeshShuffleJoinKernel.__call__

        def spy(self, *a, **kw):
            out = orig(self, *a, **kw)
            used.append(1)   # count only a SUCCESSFUL mesh join
            return out

        monkeypatch.setattr(sj.MeshShuffleJoinKernel, "__call__", spy)
        assert sess.query(sql).rows == want
        assert used, "mesh shuffle kernel was not exercised"

    def test_small_probe_skips_shuffle(self, sess, mesh, monkeypatch):
        """A tiny probe must NOT pay an all_to_all repartition even when
        the build side qualifies (advisor r2): the join falls through to
        the per-chunk single-chip paths."""
        from tidb_tpu import executor as ex
        from tidb_tpu.parallel import shuffle_join as sj

        # n_regionkey is NOT unique-keyed, so this cannot become a
        # MeshLookupAgg chain — it must stay a HashJoin
        sql = ("SELECT n_name, COUNT(*) FROM nation, lineitem "
               "WHERE n_regionkey = l_suppkey GROUP BY n_name "
               "ORDER BY n_name")
        e = _explain(sess, sql)
        assert "MeshLookupAgg" not in e and "HashJoin" in e
        # probe (left) = nation: far below _DEVICE_MIN_PROBE
        monkeypatch.setattr(ex.HashJoinExec, "_DEVICE_MIN_BUILD", 64)
        used = []
        orig = sj.MeshShuffleJoinKernel.__call__

        def spy(self, *a, **kw):
            used.append(1)
            return orig(self, *a, **kw)

        monkeypatch.setattr(sj.MeshShuffleJoinKernel, "__call__", spy)
        parallel.disable_mesh()
        try:
            want = sess.query(sql).rows
        finally:
            parallel.enable_mesh(8)
        got = sess.query(sql).rows
        assert got == want and want
        assert not used, "small probe still paid the mesh shuffle"


class TestMeshAggRawReaderSchema:
    def test_stripped_reader_schema_matches_scan(self, sess, mesh):
        """PhysMeshAgg.children[0] (the agg-stripped raw scan) must carry
        the raw scan schema, not the agg output schema (advisor r2)."""
        from tidb_tpu.plan.mesh_route import PhysMeshAgg

        plan = sess.plan(tpch.Q1)

        def find(p):
            if isinstance(p, PhysMeshAgg):
                return p
            for c in p.children:
                r = find(c)
                if r is not None:
                    return r
            return None

        node = find(plan)
        assert node is not None, "Q1 did not route to MeshAgg"
        raw = node.children[0]
        assert len(raw.schema) == len(raw.cop.cols) + \
            (1 if raw.cop.handle_col is not None else 0)
        names = [c.name for c in raw.schema.cols]
        assert names[:len(raw.cop.cols)] == \
            [c.name.lower() for c in raw.cop.cols]
