"""GC worker / safepoint tests.

Ref model: store/tikv/gcworker tests + safepoint checks — safepoint
computation, expired-lock resolution, delete-range drain after DDL,
version pruning, read rejection below the safepoint.

gc_life_time is 0 throughout so the safepoint lands at "now"; a short
sleep puts earlier writes strictly below it (timestamps are hybrid
physical-ms << 18).
"""

import time

import pytest

from tidb_tpu import kv
from tidb_tpu.meta import Meta
from tidb_tpu.session import Session
from tidb_tpu.store import new_mock_storage
from tidb_tpu.store.gcworker import GCWorker
from tidb_tpu.store.oracle import compose_ts, physical_ms


@pytest.fixture
def env():
    storage = new_mock_storage()
    storage.async_commit_secondaries = False
    s = Session(storage)
    s.execute("CREATE DATABASE test; USE test")
    yield storage, s
    s.close()
    storage.close()


def _gc(storage) -> dict:
    time.sleep(0.02)    # move the ms clock past every prior commit
    return GCWorker(storage, gc_life_time_ms=0).run_once()


class TestSafepoint:
    def test_advances_and_persists(self, env):
        storage, _s = env
        w = GCWorker(storage, gc_life_time_ms=0)
        time.sleep(0.02)
        stats = w.run_once()
        assert stats["leader"] and stats["advanced"]
        assert 0 < stats["safepoint"] <= storage.current_ts()
        assert w.saved_safepoint() == stats["safepoint"]
        assert storage.safepoint == stats["safepoint"]
        # same tick again: safepoint can only move forward
        again = w.run_once(now_ts=stats["safepoint"])
        assert not again["advanced"]

    def test_reads_below_safepoint_rejected(self, env):
        storage, s = env
        s.execute("CREATE TABLE t (a BIGINT PRIMARY KEY)")
        s.execute("INSERT INTO t VALUES (1)")
        old_ts = storage.current_ts()
        stats = _gc(storage)
        assert stats["advanced"] and storage.safepoint > old_ts
        snap = storage.snapshot(old_ts)
        with pytest.raises(kv.GCTooEarlyError):
            snap.get(b"anything")
        # fresh reads fine
        assert s.query("SELECT * FROM t").rows == [(1,)]

    def test_second_worker_not_leader(self, env):
        storage, _s = env
        w1 = GCWorker(storage, gc_life_time_ms=0)
        time.sleep(0.02)
        assert w1.run_once()["leader"]
        w2 = GCWorker(storage, gc_life_time_ms=0)
        assert w2.run_once() == {"leader": False}


class TestPruning:
    def test_old_versions_pruned(self, env):
        storage, s = env
        s.execute("CREATE TABLE t (a BIGINT PRIMARY KEY, b INT)")
        s.execute("INSERT INTO t VALUES (1, 0)")
        for i in range(1, 6):
            s.execute(f"UPDATE t SET b = {i} WHERE a = 1")
        stats = _gc(storage)
        assert stats["pruned"] >= 5     # five superseded row versions
        assert s.query("SELECT b FROM t").rows == [(5,)]

    def test_delete_range_drained(self, env):
        storage, s = env
        s.execute("CREATE TABLE t (a BIGINT PRIMARY KEY, b INT, KEY kb (b))")
        s.execute("INSERT INTO t VALUES " +
                  ",".join(f"({i}, {i})" for i in range(50)))
        keys_before = storage.engine.num_keys()
        s.execute("DROP TABLE t")
        txn = storage.begin()
        try:
            assert len(Meta(txn).pending_delete_ranges()) == 1
        finally:
            txn.rollback()
        stats = _gc(storage)
        assert stats["delete_ranges"] == 1
        txn = storage.begin()
        try:
            assert Meta(txn).pending_delete_ranges() == []
        finally:
            txn.rollback()
        assert storage.engine.num_keys() < keys_before

    def test_drop_index_range_drained(self, env):
        storage, s = env
        s.execute("CREATE TABLE t (a BIGINT PRIMARY KEY, b INT, KEY kb (b))")
        s.execute("INSERT INTO t VALUES " +
                  ",".join(f"({i}, {i})" for i in range(50)))
        s.execute("DROP INDEX kb ON t")
        stats = _gc(storage)
        assert stats["delete_ranges"] == 1
        # table data intact
        assert len(s.query("SELECT * FROM t").rows) == 50


class TestLockResolution:
    def test_stale_lock_resolved(self, env):
        storage, s = env
        # dead writer: prewrite an hour-old txn, never commit
        old_ts = compose_ts(physical_ms(storage.current_ts()) - 3_600_000)
        txn = storage.begin(start_ts=old_ts)
        txn.set(b"zz_orphan", b"v")
        muts = txn.mutations()
        from tidb_tpu.store.backoff import Backoffer
        from tidb_tpu.store.txn import TwoPhaseCommitter
        c = TwoPhaseCommitter(storage.shim, storage.region_cache,
                              storage.oracle, storage.resolver, muts,
                              old_ts, async_secondaries=False)
        c._on_batches(Backoffer(5000), list(muts.keys()),
                      c._prewrite_batch, primary_first=False)
        stats = _gc(storage)
        assert stats["resolved_locks"] >= 1
        # the key is readable again (rolled back -> absent)
        snap = storage.snapshot(storage.current_ts())
        assert snap.get(b"zz_orphan") is None
