"""Closing the statistics loop: auto-analyze on DML deltas + range-scan
query feedback (ref: statistics/update.go:53-135, handle.go:106)."""

import numpy as np
import pytest

from tidb_tpu.session import Domain, Session
from tidb_tpu.store.storage import new_mock_storage
from tidb_tpu.table import Table, bulkload


@pytest.fixture
def sess():
    st = new_mock_storage()
    s = Session(st)
    s.execute("CREATE DATABASE d")
    s.execute("USE d")
    yield s
    s.close()


class TestAutoAnalyze:
    def test_tick_analyzes_after_heavy_dml(self, sess):
        sess.execute("CREATE TABLE t (id BIGINT PRIMARY KEY, v BIGINT)")
        sess.execute("INSERT INTO t VALUES " + ",".join(
            f"({i},{i % 7})" for i in range(200)))
        sess.execute("ANALYZE TABLE t")
        handle = sess.domain.stats_handle()
        tid = sess.domain.info_schema().table("d", "t").id
        assert handle.get(tid).count == 200
        # +150 rows = 75% of analyzed count >= ratio 0.5
        sess.execute("INSERT INTO t VALUES " + ",".join(
            f"({i},{i % 7})" for i in range(200, 350)))
        assert handle.need_auto_analyze(tid)
        analyzed = sess.domain.auto_analyze_tick()
        assert tid in analyzed
        assert handle.get(tid).count == 350
        assert not handle.need_auto_analyze(tid)
        # second tick: nothing to do
        assert sess.domain.auto_analyze_tick() == []

    def test_never_analyzed_table_with_dml_gets_stats(self, sess):
        sess.execute("CREATE TABLE u (id BIGINT PRIMARY KEY)")
        sess.execute("INSERT INTO u VALUES (1), (2), (3)")
        tid = sess.domain.info_schema().table("d", "u").id
        assert tid in sess.domain.auto_analyze_tick()
        assert sess.domain.stats_handle().get(tid).count == 3

    def test_dropped_table_delta_cleared(self, sess):
        sess.execute("CREATE TABLE w (id BIGINT PRIMARY KEY)")
        sess.execute("INSERT INTO w VALUES (1)")
        tid = sess.domain.info_schema().table("d", "w").id
        sess.execute("DROP TABLE w")
        assert tid not in sess.domain.auto_analyze_tick()
        assert tid not in sess.domain.stats_handle()._deltas

    def test_worker_start_stop_idempotent(self, sess):
        d = sess.domain
        d.start_stats_worker(interval=3600)
        d.start_stats_worker(interval=3600)
        d.stop_stats_worker()
        d.stop_stats_worker()


class TestQueryFeedback:
    def _setup(self, sess, n=10000):
        sess.execute("CREATE TABLE t (id BIGINT PRIMARY KEY, v BIGINT)")
        tbl = Table(sess.domain.info_schema().table("d", "t"),
                    sess.storage)
        bulkload.bulk_load(sess.storage, tbl, {
            "id": np.arange(n, dtype=np.int64),
            "v": np.arange(n, dtype=np.int64)})
        sess.execute("ANALYZE TABLE t")
        return sess.domain.info_schema().table("d", "t")

    def test_range_scan_corrects_stale_histogram(self, sess):
        info = self._setup(sess)
        handle = sess.domain.stats_handle()
        ts = handle.get(info.id)
        pk_id = info.col_by_name("id").id
        from tidb_tpu import ranger as rg
        dr = [rg.DatumRange(low=[0], high=[2000], high_incl=False)]
        good = ts.col_ranges_row_count(pk_id, dr)
        assert good == pytest.approx(2000, rel=0.2)
        # simulate staleness: crush the histogram to 10% of reality
        hist = ts.columns[pk_id].hist
        hist.counts = [c // 10 for c in hist.counts]
        hist.total = hist.counts[-1]
        stale = ts.col_ranges_row_count(pk_id, dr)
        assert stale < 400
        # a pure range scan observes the true cardinality
        r = sess.query("SELECT id FROM t WHERE id >= 0 AND id < 2000")
        assert len(r.rows) == 2000
        corrected = ts.col_ranges_row_count(pk_id, dr)
        assert corrected > stale * 2, (stale, corrected)

    def test_feedback_plan_flag_only_on_pure_range(self, sess):
        self._setup(sess)
        p = sess.plan("SELECT id FROM t WHERE id < 100")
        assert p.children[0].cop.feedback is not None
        # residual filter: actual counts no longer equal the range count
        p2 = sess.plan("SELECT id FROM t WHERE id < 100 AND v > 5")
        assert p2.children[0].cop.feedback is None
