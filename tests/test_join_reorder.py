"""Greedy join reordering (ref: plan/join_reorder.go joinReOrderSolver:
order inner-join leaves by estimated cardinality instead of syntactic
FROM order; left-deep with the smaller side as the hash build)."""

import numpy as np
import pytest

from tidb_tpu.session import Session
from tidb_tpu.store.storage import new_mock_storage
from tidb_tpu.table import Table, bulkload


@pytest.fixture
def sess():
    s = Session(new_mock_storage())
    s.execute("CREATE DATABASE d")
    s.execute("USE d")
    s.execute("CREATE TABLE fact (id BIGINT PRIMARY KEY, dk BIGINT, "
              "sk BIGINT, v BIGINT)")
    s.execute("CREATE TABLE dim (dk BIGINT PRIMARY KEY, "
              "name VARCHAR(10))")
    s.execute("CREATE TABLE sub (sk BIGINT PRIMARY KEY, grp BIGINT)")
    t = Table(s.domain.info_schema().table("d", "fact"), s.storage)
    n = 20000
    bulkload.bulk_load(s.storage, t, {
        "id": np.arange(n, dtype=np.int64),
        "dk": np.arange(n, dtype=np.int64) % 20,
        "sk": np.arange(n, dtype=np.int64) % 5,
        "v": np.arange(n, dtype=np.int64)})
    s.execute("INSERT INTO dim VALUES " + ",".join(
        f"({i},'n{i}')" for i in range(20)))
    s.execute("INSERT INTO sub VALUES " + ",".join(
        f"({i},{i % 2})" for i in range(5)))
    s.execute("ANALYZE TABLE fact; ANALYZE TABLE dim; ANALYZE TABLE sub")
    yield s
    s.close()


BAD_ORDER = ("SELECT dim.name, COUNT(*), SUM(fact.v) "
             "FROM fact, dim, sub "
             "WHERE fact.dk = dim.dk AND fact.sk = sub.sk "
             "AND sub.grp = 0 "
             "GROUP BY dim.name ORDER BY dim.name")


def _expected(n=20000):
    import collections
    dk = np.arange(n) % 20
    sk = np.arange(n) % 5
    v = np.arange(n)
    mask = (sk % 2 == 0)
    agg = collections.defaultdict(lambda: [0, 0])
    for d_, vv in zip(dk[mask], v[mask]):
        agg[f"n{d_}"][0] += 1
        agg[f"n{d_}"][1] += int(vv)
    return sorted((k, c, sv) for k, (c, sv) in agg.items())


class TestReorder:
    def test_small_filtered_side_builds_first(self, sess):
        txt = sess.plan(BAD_ORDER).explain()
        # fact must be the streaming probe of the innermost join, the
        # filtered 'sub' its build side, dim the next build
        inner = [ln for ln in txt.splitlines() if "table:" in ln]
        order = [ln.split("table:")[1].split(",")[0].split()[0]
                 for ln in inner]
        assert order.index("fact") < order.index("sub"), txt
        assert "pushed_filter" in [ln for ln in inner
                                   if "sub" in ln][0], txt

    def test_results_unchanged_by_reorder(self, sess):
        assert [tuple(r) for r in sess.query(BAD_ORDER).rows] == \
            _expected()

    def test_all_from_orders_agree(self, sess):
        q = ("SELECT sub.grp, COUNT(*) FROM {} "
             "WHERE fact.dk = dim.dk AND fact.sk = sub.sk "
             "GROUP BY sub.grp ORDER BY sub.grp")
        results = [sess.query(q.format(fr)).rows for fr in
                   ("fact, dim, sub", "dim, fact, sub",
                    "sub, dim, fact", "dim, sub, fact")]
        assert all(r == results[0] for r in results)
        assert results[0] == [(0, 12000), (1, 8000)]

    def test_outer_joins_not_reordered(self, sess):
        q = ("SELECT COUNT(*) FROM dim LEFT JOIN fact "
             "ON dim.dk = fact.dk LEFT JOIN sub ON fact.sk = sub.sk")
        # 20k fact rows each matched; left joins preserve dim side
        assert sess.query(q).rows == [(20000,)]

    def test_cross_leaf_never_seeds(self, sess):
        """A disconnected (cross-joined) leaf must come LAST — seeding
        with it would multiply every later join by its cardinality."""
        import re
        txt = sess.plan("SELECT COUNT(*) FROM fact, dim, sub "
                        "WHERE fact.dk = dim.dk").explain()
        lines = [ln for ln in txt.splitlines() if "table:" in ln]
        order = [re.search(r"table:(\w+)", ln).group(1) for ln in lines]
        assert order.index("sub") == 2, txt

    def test_maximal_tree_reorders_four_tables(self, sess):
        sess.execute("CREATE TABLE tiny (sk BIGINT PRIMARY KEY, "
                     "f BIGINT)")
        sess.execute("INSERT INTO tiny VALUES (1,0), (2,1)")
        sess.execute("ANALYZE TABLE tiny")
        q = ("SELECT COUNT(*) FROM fact, dim, sub, tiny "
             "WHERE fact.dk = dim.dk AND fact.sk = sub.sk "
             "AND sub.sk = tiny.sk")
        import re
        txt = sess.plan(q).explain()
        lines = [ln for ln in txt.splitlines() if "table:" in ln]
        order = [re.search(r"table:(\w+)", ln).group(1) for ln in lines]
        # the whole 4-leaf tree reorders as one unit: tiny (2 rows)
        # participates early, not wherever FROM put it
        assert order.index("tiny") < order.index("dim"), txt
        sk = np.arange(20000) % 5
        want = int(np.isin(sk, [1, 2]).sum())
        assert sess.query(q).rows == [(want,)]

    def test_two_way_join_untouched(self, sess):
        txt = sess.plan("SELECT COUNT(*) FROM fact, dim "
                        "WHERE fact.dk = dim.dk").explain()
        assert "Projection exprs:[id, dk" not in txt  # no reorder shim
