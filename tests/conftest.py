"""Test harness config: force an 8-device virtual CPU mesh BEFORE jax import.

Mirrors the reference's mocktikv strategy (SURVEY.md §4): all distributed
behavior is exercised hermetically on one host — here, multi-chip sharding
runs on 8 virtual CPU devices via XLA's host-platform device count.
"""

import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# The axon TPU plugin (sitecustomize) force-sets jax_platforms="axon,cpu" in
# the CONFIG, overriding the env var — so tests would try to reach the real
# chip (and hang if the tunnel is down). Pin the config itself to cpu.
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "slow: heavy legs excluded from the tier-1 budget")


import pytest  # noqa: E402


@pytest.fixture
def ledger_hygiene():
    """Ledger/slot/gauge hygiene under faults (docs/ROBUSTNESS.md):
    after the test, every armed failpoint is disarmed, the device
    scheduler holds zero in-flight slots and zero waiters, the SERVER
    memtrack host+device ledgers drain to zero once dead storages are
    collected and the shed chain (forced delta merges, HBM sheds) has
    run, and every *_current/*_depth gauge series returns to zero — a
    leaked decrement on an abnormal disconnect/error path shows up as a
    gauge stuck above zero forever. Applied module-wide by the
    failpoint/chaos suites via
    `pytestmark = pytest.mark.usefixtures("ledger_hygiene")`."""
    yield
    import gc
    import time as _time

    from tidb_tpu import memtrack, metrics, sched
    from tidb_tpu.util import failpoint

    failpoint.disable_all()
    snap = sched.device_scheduler().snapshot()
    assert snap["inflight"] == 0, f"scheduler slots leaked: {snap}"
    assert snap["waiting"] == 0, f"scheduler waiters leaked: {snap}"
    # drain loop: a background delta merge may hold staged bytes for a
    # moment (merge() is single-flight, so one shed can miss it)
    deadline = _time.time() + 5.0
    while True:
        gc.collect()
        sched.shed_server(0)
        if memtrack.SERVER.host == 0 and memtrack.SERVER.device == 0:
            break
        if _time.time() >= deadline:
            raise AssertionError(
                f"SERVER ledgers not drained: host={memtrack.SERVER.host}"
                f" device={memtrack.SERVER.device} "
                f"children={[c.snapshot() for c in memtrack.SERVER.children.values()]}")
        _time.sleep(0.05)

    def _leaked_gauges() -> dict:
        """Instantaneous-count gauge series still above zero. The
        series name precedes any {label} suffix; only the unit-less
        level families (_current/_depth) must return to zero — ratio
        and last-statement-peak gauges legitimately hold values."""
        out = {}
        for key, v in metrics.gauges_snapshot().items():
            name = key.split("{", 1)[0]
            if name.endswith(("_current", "_depth")) and v != 0:
                out[key] = v
        return out

    # gauges drain asynchronously (a disconnecting client's server
    # thread decrements the connection gauge after the socket drops)
    deadline = _time.time() + 5.0
    while True:
        leaked = _leaked_gauges()
        if not leaked:
            break
        if _time.time() >= deadline:
            raise AssertionError(
                f"level gauges not drained to zero: {leaked}")
        _time.sleep(0.05)
