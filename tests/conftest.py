"""Test harness config: force an 8-device virtual CPU mesh BEFORE jax import.

Mirrors the reference's mocktikv strategy (SURVEY.md §4): all distributed
behavior is exercised hermetically on one host — here, multi-chip sharding
runs on 8 virtual CPU devices via XLA's host-platform device count.
"""

import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# The axon TPU plugin (sitecustomize) force-sets jax_platforms="axon,cpu" in
# the CONFIG, overriding the env var — so tests would try to reach the real
# chip (and hang if the tunnel is down). Pin the config itself to cpu.
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "slow: heavy legs excluded from the tier-1 budget")
