"""Continuous resource metering (tidb_tpu/meter.py): per-tenant
device-time/bytes/rows attribution rolled up statement→session→user→
SERVER, cross-thread attribution through the coprocessor pool AND
stream fan-outs (the no-bleed mirror of test_memtrack's isolation
tests, sequential + threaded), the metrics-history ring + sampler
(tidb_tpu/metrics_history.py), and the surfaces:
information_schema.resource_usage, SHOW [FULL] PROCESSLIST
DeviceTime/RowsSent, and the derived utilization gauges."""

import threading

import pytest

from tidb_tpu import config, memtrack, meter, metrics, metrics_history
from tidb_tpu.session import Session
from tidb_tpu.store.storage import new_mock_storage


@pytest.fixture(scope="module", autouse=True)
def _quiet_sampler():
    """Idle the background history sampler for this module: the
    interval-roll assertions below must not race a 1 Hz background
    roll_interval() from a sampler an earlier suite's server started."""
    prev = config.get_var("tidb_tpu_metrics_history_interval_ms")
    config.set_var("tidb_tpu_metrics_history_interval_ms", 0)
    yield
    config.set_var("tidb_tpu_metrics_history_interval_ms", prev)


# -- unit: the meter tree ---------------------------------------------------


class TestMeterTree:
    def test_rollup_walks_the_parent_chain(self):
        meter.reset_for_tests()
        sm = meter.session_meter(7001, "alice")
        stmt = meter.statement_meter(sm)
        stmt.add(device_ns=1000, rows_sent=5)
        stmt.add(host_fallback_ns=300, slot_wait_ns=20)
        assert stmt.totals()["device_ns"] == 1000
        assert sm.totals()["device_ns"] == 1000
        assert sm.totals()["rows_sent"] == 5
        user = [u for u in meter.users_snapshot()
                if u["user"] == "alice"][0]
        assert user["device_ns"] == 1000
        assert user["host_fallback_ns"] == 300
        assert meter.SERVER.totals()["device_ns"] == 1000
        assert meter.SERVER.totals()["slot_wait_ns"] == 20

    def test_unattributed_work_lands_on_server_only(self):
        meter.reset_for_tests()
        meter.session_meter(7002, "bob")
        meter.note_device(500)         # no meter installed on thread
        assert meter.SERVER.totals()["device_ns"] == 500
        assert meter.attributed_device_ns() == 0

    def test_metering_installs_and_suspends(self):
        meter.reset_for_tests()
        sm = meter.session_meter(7003, "carol")
        with meter.metering(sm):
            meter.note_device(100)
            with meter.suspended():
                meter.note_device(40)   # internal: SERVER only
            with meter.metering(None):  # None nests transparently
                meter.note_device(60)
        assert sm.totals()["device_ns"] == 160
        assert meter.SERVER.totals()["device_ns"] == 200

    def test_busy_sections_never_double_count(self):
        """Nested busy intervals (a finalize whose escalation re-enters
        device_slot, or degrades work to a host region) bill each
        nanosecond once, with the inner classification winning: the
        billed total can never exceed the outer wall interval."""
        import time as _t
        meter.reset_for_tests()
        sm = meter.session_meter(7005, "erin")
        t0 = _t.perf_counter_ns()
        with meter.metering(sm):
            with meter.busy_section("device"):
                _t.sleep(0.002)
                with meter.busy_section("device"):   # nested retry
                    _t.sleep(0.002)
                meter.note_host_fallback(1_000_000)  # degraded slice
        wall = _t.perf_counter_ns() - t0
        tot = sm.totals()
        assert tot["host_fallback_ns"] == 1_000_000
        assert tot["device_ns"] > 0
        assert tot["device_ns"] + tot["host_fallback_ns"] <= wall

    def test_pipeline_map_classifies_host_tokens(self):
        """pipeline_map's work split: None and ('host', ...) tokens are
        host-path (the fused probe-agg's small-batch convention), any
        other token is device work."""
        from tidb_tpu.ops import runtime as rt
        meter.reset_for_tests()
        sm = meter.session_meter(7006, "frank")

        def dispatch(it):
            return ("host", it, 0) if it % 2 else object()

        with meter.metering(sm):
            out = list(rt.pipeline_map(
                [0, 1, 2, 3], dispatch, lambda it, tok: it, depth=2))
        assert out == [0, 1, 2, 3]
        tot = sm.totals()
        assert tot["device_ns"] > 0
        assert tot["host_fallback_ns"] > 0

    def test_interval_roll_and_digest_fold(self):
        meter.reset_for_tests()
        sm = meter.session_meter(7004, "dave")
        stmt = meter.statement_meter(sm)
        stmt.add(device_ns=900, statements=1)
        meter.finish_statement(stmt, "digest-x", "SELECT ?")
        meter.roll_interval()
        snap = [s for s in meter.sessions_snapshot()
                if s["session_id"] == 7004][0]
        assert snap["interval"]["device_ns"] == 900
        stmt2 = meter.statement_meter(sm)
        stmt2.add(device_ns=100, statements=1)
        meter.finish_statement(stmt2, "digest-x", "SELECT ?")
        meter.roll_interval()
        snap = [s for s in meter.sessions_snapshot()
                if s["session_id"] == 7004][0]
        # second window: only the second statement's work
        assert snap["interval"]["device_ns"] == 100
        assert snap["device_ns"] == 1000
        top = meter.top_digests()
        assert top[0]["digest"] == "digest-x"
        assert top[0]["device_ns"] == 1000
        assert top[0]["statements"] == 2


# -- session fixtures -------------------------------------------------------


@pytest.fixture(scope="module")
def store():
    st = new_mock_storage()
    s = Session(st)
    s.execute("CREATE DATABASE m; USE m")
    s.execute("CREATE TABLE t (id BIGINT PRIMARY KEY, a BIGINT, "
              "v BIGINT)")
    vals = ",".join(f"({i},{i * 3 % 97},{i % 7})" for i in range(6000))
    s.execute("INSERT INTO t VALUES " + vals)
    # several regions so the fan-out really runs pool/stream WORKERS
    info = s.domain.info_schema().table("m", "t")
    st.cluster.split_table(info.id, 4, max_handle=6000)
    s.query("SELECT a, COUNT(*), SUM(v) FROM t GROUP BY a")  # warm
    yield st
    s.close()
    st.close()


AGG = "SELECT a, COUNT(*), SUM(v) FROM t GROUP BY a"


def _session_meter_of(s: Session):
    return [m for m in meter.sessions_snapshot()
            if m["session_id"] == s.session_id][0]


# -- cross-thread attribution (the memtrack no-bleed mirror) ----------------


class TestCrossThreadAttribution:
    def test_pool_workers_credit_the_issuing_session(self, store):
        """Sequential: the copr pool fan-out re-installs the issuing
        session's meter inside its workers, so storage-side device
        work lands on that session — and on nobody else's."""
        busy = Session(store, db="m")
        idle = Session(store, db="m")
        try:
            # force the POOL fan-out (streaming covers the other path)
            busy.execute("SET tidb_tpu_copr_stream = 0")
            busy.query(AGG)
            b = _session_meter_of(busy)
            i = _session_meter_of(idle)
            assert b["statements"] >= 1
            assert b["device_ns"] + b["host_fallback_ns"] > 0
            # the idle session ran nothing: zero work of any kind
            assert i["device_ns"] == 0
            assert i["host_fallback_ns"] == 0
            assert i["rows_sent"] == 0
        finally:
            busy.close()
            idle.close()

    def test_stream_workers_credit_the_issuing_session(self, store):
        """The streaming fan-out path (tidb_tpu_copr_stream=1 is the
        default) attributes the same way; force a fresh pass through
        the stream workers and assert the work landed."""
        s = Session(store, db="m")
        try:
            before = _session_meter_of(s)["device_ns"] + \
                _session_meter_of(s)["host_fallback_ns"]
            s.execute("SET tidb_tpu_copr_stream = 1")
            s.query("SELECT a, COUNT(*), SUM(v) FROM t "
                    "WHERE id > 17 GROUP BY a")
            after = _session_meter_of(s)
            assert after["device_ns"] + after["host_fallback_ns"] \
                > before
            assert after["rows_sent"] > 0
        finally:
            s.close()

    def test_threaded_no_bleed(self, store):
        """Two sessions running CONCURRENTLY keep their ledgers apart:
        each session's rows_sent is exactly its own result rows, and
        the busy session's execution work never credits the light one."""
        heavy = Session(store, db="m")
        light = Session(store, db="m")
        rounds = 3
        errs: list = []
        barrier = threading.Barrier(2)

        def run(s, sql, n):
            try:
                barrier.wait(timeout=30)
                for _ in range(n):
                    s.query(sql)
            except Exception as e:  # noqa: BLE001 - surfaced below
                errs.append(e)

        t1 = threading.Thread(
            target=run, args=(heavy, AGG, rounds), name="meter-heavy")
        t2 = threading.Thread(
            target=run, args=(light, "SELECT v FROM t WHERE id = 3",
                              rounds), name="meter-light")
        try:
            t1.start()
            t2.start()
            t1.join(60)
            t2.join(60)
            assert not errs, errs
            h = _session_meter_of(heavy)
            li = _session_meter_of(light)
            n_groups = 97
            assert h["rows_sent"] == rounds * n_groups
            assert li["rows_sent"] == rounds
            # the heavy session did real execution work; the light
            # session's point lookups stay orders of magnitude below
            h_work = h["device_ns"] + h["host_fallback_ns"]
            l_work = li["device_ns"] + li["host_fallback_ns"]
            assert h_work > 0
            assert l_work < h_work
            # rollup consistency: the server total carries at least
            # the attributed sum (plus any unattributed work)
            assert meter.SERVER.totals()["device_ns"] >= \
                meter.attributed_device_ns()
        finally:
            heavy.close()
            light.close()


# -- surfaces ---------------------------------------------------------------


class TestSurfaces:
    def test_resource_usage_memtable(self, store):
        s = Session(store, db="m")
        try:
            s.query(AGG)
            rs = s.query(
                "SELECT scope, session_id, user, statements, "
                "device_time_ns, host_fallback_ns, rows_sent "
                "FROM information_schema.resource_usage")
            scopes = {r[0] for r in rs.rows}
            assert {"server", "user", "session"} <= scopes
            mine = [r for r in rs.rows
                    if r[0] == "session" and r[1] == s.session_id]
            assert mine and mine[0][3] >= 1          # statements
            assert mine[0][6] > 0                    # rows_sent
            srv = [r for r in rs.rows if r[0] == "server"][0]
            # per-session work is a slice of the server total
            assert srv[4] >= mine[0][4]
            assert srv[6] >= mine[0][6]
        finally:
            s.close()

    def test_processlist_device_time_and_rows(self, store):
        s = Session(store, db="m")
        try:
            s.query(AGG)
            rs = s.query("SHOW PROCESSLIST")
            assert rs.columns[-2:] == ["DeviceTime", "RowsSent"]
            me = [r for r in rs.rows if r[0] == s.session_id][0]
            assert isinstance(me[-2], int)
            assert me[-1] > 0                        # rows served

            # SHOW FULL PROCESSLIST: same columns, untruncated Info.
            # A multi-statement batch pins the truncation contract:
            # current_sql is the whole batch text (>100 chars), so the
            # plain SHOW truncates it and FULL serves it verbatim
            longsel = ("SELECT COUNT(*) FROM t WHERE id IN (" +
                       ",".join(str(i) for i in range(40)) + ")")
            full_batch = longsel + "; SHOW FULL PROCESSLIST"
            rs_full = s.execute(full_batch)[1]
            assert rs_full.columns[-2:] == ["DeviceTime", "RowsSent"]
            info_idx = rs_full.columns.index("Info")
            me_full = [r for r in rs_full.rows
                       if r[0] == s.session_id][0]
            assert me_full[info_idx] == full_batch
            assert len(me_full[info_idx]) > 100
            plain_batch = longsel + "; SHOW PROCESSLIST"
            rs_plain = s.execute(plain_batch)[1]
            me_plain = [r for r in rs_plain.rows
                        if r[0] == s.session_id][0]
            assert len(me_plain[info_idx] or "") == 100
            assert plain_batch.startswith(me_plain[info_idx])
        finally:
            s.close()

    def test_statement_folds_into_digest_top(self, store):
        from tidb_tpu import perfschema
        s = Session(store, db="m")
        try:
            sql = "SELECT COUNT(*) FROM t WHERE a = 11"
            s.query(sql)
            dg = perfschema.sql_digest(sql)[0]
            recs = {r["digest"]: r for r in meter.digests_snapshot()}
            assert dg in recs
            assert recs[dg]["statements"] >= 1
            assert recs[dg]["rows_sent"] >= 1
        finally:
            s.close()


# -- metrics history (tidb_tpu/metrics_history.py) --------------------------


class TestMetricsHistory:
    def test_sample_now_records_derived_series(self, store):
        metrics_history.reset_for_tests()
        s = Session(store, db="m")
        try:
            metrics_history.sample_now()     # baseline tick
            s.query(AGG)
            point = metrics_history.sample_now()
            assert "tidb_tpu_device_utilization_ratio" in point
            assert "tidb_tpu_hbm_occupancy_ratio" in point
            assert "server_host_bytes" in point
            ser = metrics_history.series()
            assert "tidb_tpu_device_utilization_ratio" in ser
            ts = ser["tidb_tpu_device_utilization_ratio"]
            assert len(ts) >= 1
            assert all(len(pair) == 2 for pair in ts)
            # the derived gauge publishes live too
            assert metrics.DEVICE_UTILIZATION in metrics.snapshot()
        finally:
            s.close()

    def test_ring_is_bounded_and_billed_and_sheds(self):
        metrics_history.reset_for_tests()
        prev = config.get_var("tidb_tpu_metrics_history_points")
        config.set_var("tidb_tpu_metrics_history_points", 16)
        try:
            for _ in range(40):
                metrics_history.sample_now()
            assert metrics_history.stats()["points"] == 16
            billed = metrics_history.stats()["bytes"]
            assert billed > 0
            # billed to a memtrack SERVER node...
            node = [c for c in memtrack.SERVER.children.values()
                    if c.label == "metrics-history"]
            assert node and node[0].host == billed
            # ...with a registered shed action the server chain drives
            from tidb_tpu import sched
            sched.shed_server(0)
            assert metrics_history.stats()["points"] == 0
            assert node[0].host == 0
        finally:
            config.set_var("tidb_tpu_metrics_history_points", prev)

    def test_interval_sysvar_gates_the_beat(self):
        metrics_history.reset_for_tests()
        prev = config.get_var("tidb_tpu_metrics_history_interval_ms")
        config.set_var("tidb_tpu_metrics_history_interval_ms", 0)
        try:
            before = metrics_history.stats()["points"]
            metrics_history._beat()
            assert metrics_history.stats()["points"] == before
            config.set_var("tidb_tpu_metrics_history_interval_ms", 1)
            metrics_history._beat()
            assert metrics_history.stats()["points"] >= before
        finally:
            config.set_var("tidb_tpu_metrics_history_interval_ms", prev)
