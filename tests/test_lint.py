"""The project lint suite, as a single parametrized pytest shim.

Replaces the four standalone AST-walking test files (test_lint_wire.py,
test_lint_sync.py, test_lint_metrics.py, test_lint_memtrack.py), each
of which re-parsed the whole ~100-module package with its own ad-hoc
suppression convention. The engine (tidb_tpu/lint) parses the package
ONCE into a shared forest; every registered rule — the four ported
invariants, the twelve project-specific additions, the three
whole-program flow rules (tidb_tpu/lint/flow), and the three
device-plane dataflow rules (tidb_tpu/lint/flow/device) — runs over
it, and each gets its own test id here so a regression names the rule
that caught it.

The single-parse guarantee is pinned by PARSE COUNTS, not wall time:
the engine counts every `ast.parse` it performs
(tidb_tpu.lint.engine.parse_count), and the assertions below hold
whatever the CI load — the old wall-time pin flaked whenever the tight
tier-1 budget ran this file under concurrent CPU pressure.

The same rule set backs `python -m tidb_tpu.lint` (CI / pre-commit,
scripts/lint.sh); test_cli_* pins that front end's exit-code contract
and the `--json` schema.
"""

import json
import os
import subprocess
import sys

import pytest

from tidb_tpu.lint import REGISTRY, run
from tidb_tpu.lint.engine import (BAD_RULE, REPO, UNUSED_RULE,
                                  parse_count)

RULE_NAMES = list(REGISTRY)


@pytest.fixture(scope="module")
def report():
    """One engine run — one parse of the package — shared by every
    per-rule assertion below. The process-wide parse counter is
    bracketed around the run so the instrumentation tests can account
    for every single ast.parse it triggered."""
    before = parse_count()
    rep = run()
    rep.parse_calls_run = parse_count() - before
    return rep


def test_catalog_is_complete():
    """4 ported + 12 project-specific + 3 whole-program flow rules
    + 3 device-plane dataflow rules."""
    assert len(RULE_NAMES) == 22, RULE_NAMES
    for ported in ("wire-discipline", "hot-path-sync", "metric-names",
                   "memtrack-alloc"):
        assert ported in RULE_NAMES
    for new in ("lock-discipline", "sysvar-registry",
                "errcode-discipline", "device-sync", "dtype-discipline",
                "bare-except", "device-cache", "decode-discipline",
                "failpoint-discipline", "trace-names",
                "no-parallel-import", "metric-cardinality"):
        assert new in RULE_NAMES
    for flow in ("lock-order", "guarded-by", "paired-resource"):
        assert flow in RULE_NAMES
    for dev in ("donation-safety", "cache-key", "retrace-hazard"):
        assert dev in RULE_NAMES


@pytest.mark.parametrize("rule", RULE_NAMES)
def test_rule_clean(report, rule):
    """The repo is clean under this rule (includes the rule's vacuity
    guard: its fixture still fires and it examined real in-tree
    sites)."""
    bad = [f for f in report.findings if f.rule == rule]
    assert not bad, "\n".join(str(f) for f in bad)


def test_suppression_hygiene(report):
    """No stale (unused) exempt tags, no reasonless or unknown-rule
    tags anywhere in the package."""
    bad = [f for f in report.findings
           if f.rule in (UNUSED_RULE, BAD_RULE)]
    assert not bad, "\n".join(str(f) for f in bad)


def test_no_unattributed_findings(report):
    known = set(RULE_NAMES) | {UNUSED_RULE, BAD_RULE}
    assert not [f for f in report.findings if f.rule not in known]


def test_single_parse_instrumentation(report):
    """The whole point of the shared forest: parse once per module,
    and every rule — the flow rules' call graph and lock registry
    included — walks that one parse. Asserted on the engine's
    `ast.parse` counter (load-independent), not wall time:

    * Forest.load parsed exactly one AST per package module;
    * the only parses beyond the load are the vacuity guard's fixture
      forests (a known, enumerable set) — the rule walks themselves
      added ZERO.
    """
    assert report.files >= 90          # it really saw the package
    assert report.parse_calls == report.files
    fixture_parses = sum(1 + len(cls.fixture_support)
                         for cls in REGISTRY.values())
    assert report.parse_calls_run == report.files + fixture_parses, (
        f"{report.parse_calls_run - report.files - fixture_parses} "
        f"unexpected ast.parse call(s) during the rule walks — a rule "
        f"is re-parsing instead of using the forest")


# -- CLI front end (CI / pre-commit contract) -------------------------------

def test_cli_json_smoke():
    """One real `python -m tidb_tpu.lint --json` subprocess (the
    scripts/lint.sh invocation): exit 0 on the clean tree and the
    stable machine-readable schema — file/line/rule/message findings,
    rule list, and the parse-count instrumentation that replaces
    wall-time pins."""
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.run(
        [sys.executable, "-m", "tidb_tpu.lint", "--json"],
        capture_output=True, text=True, timeout=240, env=env, cwd=REPO)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    doc = json.loads(proc.stdout)
    assert doc["version"] == 1
    assert doc["clean"] is True
    assert doc["findings"] == []
    assert doc["files"] >= 90
    assert doc["rules"] == RULE_NAMES
    timing = doc["timing"]
    assert set(timing) == {"parse_ms", "total_ms", "parse_calls",
                           "rule_ms"}
    assert timing["parse_calls"] == doc["files"]    # single parse
    assert set(timing["rule_ms"]) == set(RULE_NAMES)


def test_cli_exit_codes_in_process(capsys):
    """Exit-code contract without paying three jax-importing
    subprocess spawns: 0 clean / 2 usage (1-on-findings is covered by
    main() returning bool(report.findings) over the clean repo run)."""
    from tidb_tpu.lint.__main__ import main
    assert main(["--rule", "no-such-rule"]) == 2
    assert "unknown rule" in capsys.readouterr().err
    assert main(["--list-rules"]) == 0
    out = capsys.readouterr().out
    for name in RULE_NAMES:
        assert name in out


def test_findings_report_is_not_clean(tmp_path):
    """The 1-exit half of the contract, in process: a tree with a real
    lock-order cycle produces a non-clean report (main() exits
    bool(findings)); the JSON rows carry file/line/rule/message."""
    pkg = tmp_path / "tidb_tpu"
    pkg.mkdir()
    (pkg / "__init__.py").write_text(
        "import threading\n"
        "_a = threading.Lock()\n"
        "_b = threading.Lock()\n"
        "def f():\n"
        "    with _a:\n"
        "        with _b:\n"
        "            pass\n"
        "def g():\n"
        "    with _b:\n"
        "        with _a:\n"
        "            pass\n")
    from tidb_tpu.lint import engine
    rep = engine.run(rules=["lock-order"], root=str(tmp_path),
                     with_selfcheck=False, with_vacuity=False)
    assert not rep.clean
    hit = [f for f in rep.findings
           if f.rule == "lock-order" and "cycle" in f.message]
    assert hit, rep.findings
    row = {"file": hit[0].file, "line": hit[0].line,
           "rule": hit[0].rule, "message": hit[0].message}
    assert json.loads(json.dumps(row)) == row