"""The project lint suite, as a single parametrized pytest shim.

Replaces the four standalone AST-walking test files (test_lint_wire.py,
test_lint_sync.py, test_lint_metrics.py, test_lint_memtrack.py), each
of which re-parsed the whole ~100-module package with its own ad-hoc
suppression convention. The engine (tidb_tpu/lint) parses the package
ONCE into a shared forest; every registered rule — the four ported
invariants plus the six project-specific additions — runs over it, and
each gets its own test id here so a regression names the rule that
caught it. Inside the tight tier-1 budget this cuts four full
walks+parses down to one.

The same rule set backs `python -m tidb_tpu.lint` (CI / pre-commit);
test_cli_* pins that front end's exit-code contract.
"""

import os
import re
import subprocess
import sys

import pytest

from tidb_tpu.lint import REGISTRY, run
from tidb_tpu.lint.engine import BAD_RULE, UNUSED_RULE, REPO

RULE_NAMES = list(REGISTRY)


@pytest.fixture(scope="module")
def report():
    """One engine run — one parse of the package — shared by every
    per-rule assertion below."""
    return run()


def test_catalog_is_complete():
    """4 ported rules + 7 project-specific rules."""
    assert len(RULE_NAMES) == 11, RULE_NAMES
    for ported in ("wire-discipline", "hot-path-sync", "metric-names",
                   "memtrack-alloc"):
        assert ported in RULE_NAMES
    for new in ("lock-discipline", "sysvar-registry",
                "errcode-discipline", "device-sync", "dtype-discipline",
                "bare-except", "device-cache"):
        assert new in RULE_NAMES


@pytest.mark.parametrize("rule", RULE_NAMES)
def test_rule_clean(report, rule):
    """The repo is clean under this rule (includes the rule's vacuity
    guard: its fixture still fires and it examined real in-tree
    sites)."""
    bad = [f for f in report.findings if f.rule == rule]
    assert not bad, "\n".join(str(f) for f in bad)


def test_suppression_hygiene(report):
    """No stale (unused) exempt tags, no reasonless or unknown-rule
    tags anywhere in the package."""
    bad = [f for f in report.findings
           if f.rule in (UNUSED_RULE, BAD_RULE)]
    assert not bad, "\n".join(str(f) for f in bad)


def test_no_unattributed_findings(report):
    known = set(RULE_NAMES) | {UNUSED_RULE, BAD_RULE}
    assert not [f for f in report.findings if f.rule not in known]


def test_single_parse_wall_time(report):
    """The whole point of the shared forest: parse once, not once per
    rule file. The four deleted walkers cost ~4.8s wall on this
    container (each re-parsing all ~100 modules); the engine's full
    run, self-checks included, must stay well inside that. The bound is
    deliberately loose against CI load spikes — the PR description
    records the measured numbers."""
    assert report.files >= 90          # it really saw the package
    assert report.parse_time < report.total_time
    assert report.total_time < 10.0, (
        f"lint engine took {report.total_time:.1f}s — the single-parse "
        f"advantage over the old four-walk suite has regressed")


# -- CLI front end (CI / pre-commit contract) -------------------------------

def test_cli_runs_clean_smoke():
    """One real `python -m tidb_tpu.lint` subprocess: exit 0, no
    findings, all 11 rules, and the CLI's self-reported lint time well
    under the old four-walk cost (~4.8s wall on this container). The
    reported time is the honest comparison basis: it excludes the
    interpreter+jax import, which the old walkers amortized across the
    whole pytest session."""
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.run(
        [sys.executable, "-m", "tidb_tpu.lint"],
        capture_output=True, text=True, timeout=240, env=env, cwd=REPO)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "11 rule(s)" in proc.stdout
    assert "0 finding(s)" in proc.stdout
    ms = int(re.search(r"finding\(s\) in (\d+) ms", proc.stdout).group(1))
    # measured: 2.3-3.7s standalone vs ~4.8s for the old four walkers;
    # the asserted bound is deliberately loose (load during a full
    # tier-1 run inflates wall time ~2x) — a regression backstop, not
    # the benchmark. The PR description records the real numbers.
    assert ms < 10000, f"lint suite took {ms} ms — the single-parse " \
                       f"advantage over the old four-walk suite is gone"


def test_cli_exit_codes_in_process(capsys):
    """Exit-code contract without paying three jax-importing
    subprocess spawns: 0 clean / 2 usage (1-on-findings is covered by
    main() returning bool(report.findings) over the clean repo run)."""
    from tidb_tpu.lint.__main__ import main
    assert main(["--rule", "no-such-rule"]) == 2
    assert "unknown rule" in capsys.readouterr().err
    assert main(["--list-rules"]) == 0
    out = capsys.readouterr().out
    for name in RULE_NAMES:
        assert name in out
