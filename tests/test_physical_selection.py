"""Physical algorithm selection: the planner must CHOOSE MergeJoin /
IndexJoin / StreamAgg for the right SQL shapes (ref:
plan/gen_physical_plans.go:114-417, plan/task.go costing) — and the chosen
plans must return the same rows as the default hash operators."""

import numpy as np
import pytest

from tidb_tpu.session import Session
from tidb_tpu.store.storage import new_mock_storage
from tidb_tpu.table import Table, bulkload


@pytest.fixture
def sess():
    st = new_mock_storage()
    s = Session(st)
    s.execute("CREATE DATABASE d")
    s.execute("USE d")
    yield s
    s.close()


def _plan_text(sess, sql) -> str:
    return sess.plan(sql).explain()


class TestMergeJoin:
    def _setup(self, sess):
        sess.execute("CREATE TABLE a (id BIGINT PRIMARY KEY, x BIGINT)")
        sess.execute("CREATE TABLE b (id BIGINT PRIMARY KEY, y BIGINT)")
        sess.execute("INSERT INTO a VALUES " + ",".join(
            f"({i},{i * 2})" for i in range(0, 200, 2)))
        sess.execute("INSERT INTO b VALUES " + ",".join(
            f"({i},{i * 3})" for i in range(0, 150)))

    def test_pk_pk_join_uses_merge(self, sess):
        self._setup(sess)
        q = "SELECT a.id, a.x, b.y FROM a JOIN b ON a.id = b.id"
        txt = _plan_text(sess, q)
        assert "MergeJoin" in txt, txt
        assert "keep_order" in txt, txt
        rows = sorted(sess.query(q).rows)
        want = sorted((i, i * 2, i * 3) for i in range(0, 150, 2))
        assert rows == want

    def test_left_join_and_filters(self, sess):
        self._setup(sess)
        q = ("SELECT a.id, b.y FROM a LEFT JOIN b ON a.id = b.id "
             "WHERE a.x > 100")
        txt = _plan_text(sess, q)
        assert "MergeJoin" in txt, txt
        rows = sorted(sess.query(q).rows, key=lambda r: r[0])
        want = []
        for i in range(0, 200, 2):
            if i * 2 > 100:
                want.append((i, i * 3 if i < 150 else None))
        assert rows == want

    def test_non_pk_key_stays_hash(self, sess):
        self._setup(sess)
        txt = _plan_text(sess,
                         "SELECT a.id FROM a JOIN b ON a.x = b.id")
        assert "HashJoin" in txt and "MergeJoin" not in txt, txt


class TestIndexJoin:
    def _setup(self, sess, analyze=True):
        sess.execute("CREATE TABLE small (k BIGINT PRIMARY KEY, "
                     "grp BIGINT)")
        sess.execute("CREATE TABLE big (id BIGINT PRIMARY KEY, "
                     "v BIGINT)")
        sess.execute("INSERT INTO small VALUES " + ",".join(
            f"({i},{i % 40})" for i in range(200)))
        tbl = Table(sess.domain.info_schema().table("d", "big"),
                    sess.storage)
        bulkload.bulk_load(sess.storage, tbl, {
            "id": np.arange(20000, dtype=np.int64),
            "v": np.arange(20000, dtype=np.int64) * 7})
        if analyze:
            sess.execute("ANALYZE TABLE small")
            sess.execute("ANALYZE TABLE big")

    def test_small_outer_pk_inner_uses_index_join(self, sess):
        self._setup(sess)
        q = ("SELECT small.k, big.v FROM small JOIN big "
             "ON small.k = big.id WHERE small.grp = 1")
        txt = _plan_text(sess, q)
        assert "IndexJoin" in txt, txt
        rows = sorted(sess.query(q).rows)
        want = sorted((i, i * 7) for i in range(200) if i % 40 == 1)
        assert rows == want

    def test_without_stats_stays_hash(self, sess):
        self._setup(sess, analyze=False)
        txt = _plan_text(sess,
                         "SELECT small.k FROM small JOIN big "
                         "ON small.k = big.id WHERE small.grp = 1")
        assert "IndexJoin" not in txt, txt

    def test_secondary_index_inner(self, sess):
        self._setup(sess)
        sess.execute("CREATE TABLE dim (pk BIGINT PRIMARY KEY, "
                     "code BIGINT, lbl BIGINT)")
        sess.execute("CREATE INDEX icode ON dim (code)")
        sess.execute("INSERT INTO dim VALUES " + ",".join(
            f"({i},{i % 500},{i})" for i in range(5000)))
        sess.execute("ANALYZE TABLE dim")
        q = ("SELECT small.k, dim.lbl FROM small JOIN dim "
             "ON small.k = dim.code WHERE small.grp = 2")
        txt = _plan_text(sess, q)
        assert "IndexJoin" in txt and "via:icode" in txt, txt
        rows = sorted(sess.query(q).rows)
        want = sorted((i, j) for i in range(200) if i % 40 == 2
                      for j in range(5000) if j % 500 == i)
        assert rows == want

    def test_large_outer_stays_hash(self, sess):
        self._setup(sess)
        # unfiltered outer: 200 rows * LOOKUP_FACTOR ~ 800 < 20000 still
        # picks index join; join small as the INNER instead (count 200 <
        # outer 20000 * factor) must stay hash
        txt = _plan_text(sess, "SELECT small.k FROM big JOIN small "
                               "ON big.id = small.k")
        assert "MergeJoin" in txt or "HashJoin" in txt, txt


class TestStreamAgg:
    def test_high_ndv_group_by_uses_stream_agg(self, sess):
        sess.execute("CREATE TABLE f (id BIGINT PRIMARY KEY, "
                     "k BIGINT, v BIGINT)")
        n = 70000
        tbl = Table(sess.domain.info_schema().table("d", "f"),
                    sess.storage)
        bulkload.bulk_load(sess.storage, tbl, {
            "id": np.arange(n, dtype=np.int64),
            "k": np.arange(n, dtype=np.int64),        # ndv == n > 65536
            "v": np.ones(n, dtype=np.int64)})
        sess.execute("ANALYZE TABLE f")
        q = "SELECT k, SUM(v) FROM f GROUP BY k"
        txt = _plan_text(sess, q)
        assert "StreamAgg" in txt, txt
        r = sess.query("SELECT COUNT(*) FROM (SELECT k, SUM(v) s "
                       "FROM f GROUP BY k) t")
        assert r.rows[0][0] == n

    def test_low_ndv_stays_hash_pushdown(self, sess):
        sess.execute("CREATE TABLE g (id BIGINT PRIMARY KEY, k BIGINT)")
        sess.execute("INSERT INTO g VALUES " + ",".join(
            f"({i},{i % 5})" for i in range(500)))
        sess.execute("ANALYZE TABLE g")
        txt = _plan_text(sess, "SELECT k, COUNT(*) FROM g GROUP BY k")
        assert "StreamAgg" not in txt, txt

    def test_join_output_group_by_high_ndv(self, sess):
        sess.execute("CREATE TABLE fact (id BIGINT PRIMARY KEY, "
                     "ok BIGINT, v BIGINT)")
        sess.execute("CREATE TABLE o (okey BIGINT PRIMARY KEY, "
                     "flag BIGINT)")
        n = 70000
        tf = Table(sess.domain.info_schema().table("d", "fact"),
                   sess.storage)
        bulkload.bulk_load(sess.storage, tf, {
            "id": np.arange(n, dtype=np.int64),
            "ok": np.arange(n, dtype=np.int64),
            "v": np.full(n, 2, dtype=np.int64)})
        to = Table(sess.domain.info_schema().table("d", "o"),
                   sess.storage)
        bulkload.bulk_load(sess.storage, to, {
            "okey": np.arange(n, dtype=np.int64),
            "flag": np.arange(n, dtype=np.int64) % 2})
        sess.execute("ANALYZE TABLE fact")
        sess.execute("ANALYZE TABLE o")
        q = ("SELECT fact.ok, SUM(fact.v) FROM fact JOIN o "
             "ON fact.ok = o.okey WHERE o.flag = 0 GROUP BY fact.ok")
        txt = _plan_text(sess, q)
        assert "StreamAgg" in txt, txt
        r = sess.query(q)
        assert len(r.rows) == n // 2
        assert all(row[1] == 2 for row in r.rows[:50])


class TestIndexJoinDirtyTxn:
    """Own writes visible through point lookups — never a whole-table
    inner scan (the former fallback; verdict r3 weak #7)."""

    def _setup(self, sess):
        TestIndexJoin._setup(self, sess)

    def test_dirty_pk_inner_sees_own_writes(self, sess, monkeypatch):
        self._setup(sess)
        from tidb_tpu import executor as ex
        full_scans = []
        orig = ex.TableReaderExec.chunks
        monkeypatch.setattr(
            ex.TableReaderExec, "chunks",
            lambda self, ctx: full_scans.append(self.plan.cop.table.name)
            or orig(self, ctx))
        q = ("SELECT small.k, big.v FROM small JOIN big "
             "ON small.k = big.id WHERE small.grp = 3")
        sess.execute("BEGIN")
        sess.execute("UPDATE big SET v = -1 WHERE id = 3")
        sess.execute("DELETE FROM big WHERE id = 43")
        sess.execute("INSERT INTO small VALUES (20001, 3)")
        sess.execute("INSERT INTO big VALUES (20001, 777)")
        rows = sorted(sess.query(q).rows)
        sess.execute("ROLLBACK")
        want = sorted([(i, i * 7) for i in range(200)
                       if i % 40 == 3 and i not in (3, 43)] +
                      [(3, -1), (20001, 777)])
        assert rows == want
        # the dirty inner path must not have scanned table `big`
        assert "big" not in full_scans, full_scans

    def test_dirty_secondary_index_inner(self, sess):
        self._setup(sess)
        sess.execute("CREATE TABLE dim (pk BIGINT PRIMARY KEY, "
                     "code BIGINT, lbl BIGINT)")
        sess.execute("CREATE INDEX icode ON dim (code)")
        sess.execute("INSERT INTO dim VALUES " + ",".join(
            f"({i},{i % 500},{i})" for i in range(5000)))
        sess.execute("ANALYZE TABLE dim")
        q = ("SELECT small.k, dim.lbl FROM small JOIN dim "
             "ON small.k = dim.code WHERE small.grp = 2")
        txt = _plan_text(sess, q)
        assert "IndexJoin" in txt
        sess.execute("BEGIN")
        sess.execute("INSERT INTO dim VALUES (9001, 2, 424242)")
        sess.execute("DELETE FROM dim WHERE pk = 2")   # code 2, lbl 2
        rows = sorted(sess.query(q).rows)
        sess.execute("ROLLBACK")
        want = sorted([(i, j) for i in range(200) if i % 40 == 2
                       for j in range(5000)
                       if j % 500 == i and j != 2] + [(2, 424242)])
        assert rows == want
