"""MVCC delta store (store/delta.py): committed writes keep the
columnar/HBM cache planes hot — served as base ⋈ delta — without ever
violating snapshot isolation. Pins the consistency contract (a reader
at ts T never sees a delta committed after T, repeatable reads across a
background merge, delete-then-scan), the regression that a single-row
UPDATE no longer evicts unrelated tables' cache entries, and the
staged-bytes spill action on the SERVER root."""

import numpy as np
import pytest

from tidb_tpu import config, memtrack, metrics, sched
from tidb_tpu.session import Session
from tidb_tpu.store import delta as deltamod
from tidb_tpu.store.storage import new_mock_storage
from tidb_tpu.table import Table, bulkload


@pytest.fixture
def sess():
    st = new_mock_storage()
    s = Session(st)
    s.execute("CREATE DATABASE d")
    s.execute("USE d")
    yield s
    s.close()
    st.close()


def _load(sess, name, n=4000, mod=7):
    sess.execute(f"CREATE TABLE {name} (id BIGINT PRIMARY KEY, "
                 f"v BIGINT, s VARCHAR(8))")
    ti = sess.domain.info_schema().table("d", name)
    bulkload.bulk_load(sess.storage, Table(ti, sess.storage), {
        "id": np.arange(n), "v": np.arange(n) % mod,
        "s": np.array(["x", "yy", "zzz"], dtype=object)[
            np.arange(n) % 3]})
    return sum(i % mod for i in range(n))


def _served_with_delta():
    return metrics.snapshot().get(metrics.CACHE_DELTA_SERVES, 0)


class TestDeltaServe:
    def test_row_commit_does_not_bump_version(self, sess):
        total = _load(sess, "t")
        assert sess.query("SELECT SUM(v) FROM t").rows[0][0] == total
        dv0 = sess.storage.engine.data_version
        sess.execute("UPDATE t SET v = v + 10 WHERE id = 5")
        sess.execute("DELETE FROM t WHERE id = 6")
        sess.execute("INSERT INTO t VALUES (99999, 3, 'ins')")
        assert sess.storage.engine.data_version == dv0
        want = total + 10 - (6 % 7) + 3
        assert sess.query("SELECT SUM(v) FROM t").rows[0][0] == want
        assert sess.storage.delta_store.rows_current() >= 3

    def test_served_as_base_plus_delta_not_rescan(self, sess):
        total = _load(sess, "t")
        sess.query("SELECT SUM(v) FROM t")      # cache fill
        c0 = _served_with_delta()
        sess.execute("UPDATE t SET v = 0 WHERE id = 0")
        assert sess.query("SELECT SUM(v) FROM t").rows[0][0] == total
        assert _served_with_delta() > c0
        # repeated hot reads at the same delta state reuse the memo
        assert sess.query("SELECT SUM(v) FROM t").rows[0][0] == total

    def test_update_does_not_evict_unrelated_tables(self, sess):
        """Regression pin: before the delta store, ANY committed write
        bumped data_version and invalidated EVERY table's entries."""
        _load(sess, "a")
        b_total = _load(sess, "b", n=1000)
        sess.query("SELECT SUM(v) FROM a")
        sess.query("SELECT SUM(v) FROM b")
        cc = sess.storage.chunk_cache
        keys_b = {k for k in cc._entries if k[2] ==
                  sess.domain.info_schema().table("d", "b").id}
        assert keys_b
        sess.execute("UPDATE a SET v = 1 WHERE id = 1")
        assert keys_b <= set(cc._entries), \
            "table b's entries were evicted by a write to table a"
        cc.hits = cc.misses = 0
        assert sess.query("SELECT SUM(v) FROM b").rows[0][0] == b_total
        assert cc.hits >= 1 and cc.misses == 0

    def test_dict_columns_extend_incrementally(self, sess):
        _load(sess, "t")
        sess.query("SELECT s, COUNT(*) FROM t GROUP BY s")
        sess.execute("UPDATE t SET s = 'fresh' WHERE id = 0")
        rows = dict(sess.query(
            "SELECT s, COUNT(*) FROM t GROUP BY s").rows)
        assert rows["fresh"] == 1

    def test_delete_then_scan(self, sess):
        total = _load(sess, "t", n=500)
        assert sess.query("SELECT COUNT(*) FROM t").rows[0][0] == 500
        sess.execute("DELETE FROM t WHERE id < 10")
        gone = sum(i % 7 for i in range(10))
        r = sess.query("SELECT COUNT(*), SUM(v) FROM t").rows[0]
        assert r == (490, total - gone)
        sess.execute("DELETE FROM t")
        assert sess.query("SELECT COUNT(*) FROM t").rows[0][0] == 0
        assert sess.query("SELECT SUM(v) FROM t").rows[0][0] is None


class TestDeltaMVCC:
    def test_reader_at_t_never_sees_later_delta(self, sess):
        total = _load(sess, "t")
        s2 = Session(sess.storage, db="d")
        s2.execute("BEGIN")
        assert s2.query("SELECT SUM(v) FROM t").rows[0][0] == total
        sess.execute("UPDATE t SET v = v + 100 WHERE id = 1")
        sess.execute("DELETE FROM t WHERE id = 2")
        # the old snapshot re-reads its own view, repeatedly
        for _ in range(3):
            assert s2.query("SELECT SUM(v) FROM t").rows[0][0] == total
        s2.execute("COMMIT")
        want = total + 100 - (2 % 7)
        assert s2.query("SELECT SUM(v) FROM t").rows[0][0] == want
        s2.close()

    def test_repeatable_reads_across_background_merge(self, sess):
        total = _load(sess, "t")
        sess.query("SELECT SUM(v) FROM t")
        sess.execute("UPDATE t SET v = v + 1 WHERE id < 50")
        s2 = Session(sess.storage, db="d")
        s2.execute("BEGIN")
        assert s2.query("SELECT SUM(v) FROM t").rows[0][0] == total + 50
        sess.execute("UPDATE t SET v = v + 1 WHERE id < 20")
        folded = sess.storage.delta_store.merge(trigger="rows")
        assert folded > 0
        # the merge promoted newer bases; the old reader must either
        # keep serving its snapshot or transparently re-scan — never
        # see the post-snapshot writes
        assert s2.query("SELECT SUM(v) FROM t").rows[0][0] == total + 50
        s2.execute("COMMIT")
        assert s2.query("SELECT SUM(v) FROM t").rows[0][0] == total + 70
        s2.close()

    def test_merge_truncates_journal_and_metric(self, sess):
        _load(sess, "t")
        sess.query("SELECT SUM(v) FROM t")
        sess.execute("UPDATE t SET v = 0 WHERE id = 3")
        sess.query("SELECT SUM(v) FROM t")    # memoize base⋈delta
        st = sess.storage
        assert st.delta_store.rows_current() >= 1
        snap0 = metrics.snapshot().get(
            metrics.DELTA_MERGES + '{trigger="rows"}', 0)
        assert st.delta_store.merge(trigger="rows") >= 1
        assert st.delta_store.rows_current() == 0
        assert metrics.snapshot().get(
            metrics.DELTA_MERGES + '{trigger="rows"}', 0) == snap0 + 1

    def test_locked_range_veto(self, sess):
        """A pending lock a reader must observe routes the range to the
        real scan path; the cached entries survive the write."""
        from tidb_tpu import tablecodec
        _load(sess, "t", n=100)
        sess.query("SELECT SUM(v) FROM t")
        engine = sess.storage.engine
        tid = sess.domain.info_schema().table("d", "t").id
        s, e = tablecodec.table_prefix_range(tid)
        ts = sess.storage.current_ts()
        assert not engine.locked_in_range(s, e, ts)
        from tidb_tpu.kv import Mutation, MutationOp
        key = tablecodec.record_key(tid, 1)
        engine.prewrite([Mutation(MutationOp.PUT, key, b"x")],
                        key, ts, ttl_ms=30000)
        assert engine.locked_in_range(s, e, sess.storage.current_ts())
        # an OLDER reader (snapshot before the lock's txn) is not blocked
        assert not engine.locked_in_range(s, e, ts - 1)
        engine.rollback([key], ts)
        assert not engine.locked_in_range(s, e,
                                          sess.storage.current_ts())

    def test_index_commit_invalidates_index_entries_only(self, sess):
        _load(sess, "a")
        sess.execute("CREATE TABLE ix (id BIGINT PRIMARY KEY, "
                     "v BIGINT)")
        sess.execute("CREATE INDEX iv ON ix (v)")
        for i in range(40):
            sess.execute(f"INSERT INTO ix VALUES ({i}, {i % 5})")
        sess.query("SELECT SUM(v) FROM a")
        assert sess.query(
            "SELECT COUNT(*) FROM ix WHERE v = 2").rows[0][0] == 8
        cc = sess.storage.chunk_cache
        a_id = sess.domain.info_schema().table("d", "a").id
        keys_a = {k for k in cc._entries if k[2] == a_id}
        sess.execute("UPDATE ix SET v = 0 WHERE id = 2")
        # index reads stay correct after the index-key commit
        assert sess.query(
            "SELECT COUNT(*) FROM ix WHERE v = 2").rows[0][0] == 7
        # ...and table a's entries were untouched by ix's write
        assert keys_a <= set(cc._entries)

    def test_disabled_reverts_to_legacy_invalidation(self, sess):
        _load(sess, "t", n=200)
        prev = config.get_var("tidb_tpu_delta_store")
        config.set_var("tidb_tpu_delta_store", 0)
        try:
            dv0 = sess.storage.engine.data_version
            sess.execute("UPDATE t SET v = 9 WHERE id = 0")
            assert sess.storage.engine.data_version > dv0
            assert sess.query(
                "SELECT SUM(v) FROM t").rows[0][0] is not None
        finally:
            config.set_var("tidb_tpu_delta_store", prev)

    def test_disable_flip_flushes_staged_journal(self, sess):
        """Flipping the store OFF with staged (journaled, never
        version-bumped) deltas must not leave cached entries serving
        pre-update data: the first consult after the flip flushes the
        journal and bumps the structural version once."""
        total = _load(sess, "t", n=300)
        sess.query("SELECT SUM(v) FROM t")      # cache fill
        sess.execute("UPDATE t SET v = v + 7 WHERE id = 0")
        assert sess.storage.delta_store.rows_current() >= 1
        prev = config.get_var("tidb_tpu_delta_store")
        config.set_var("tidb_tpu_delta_store", 0)
        try:
            assert sess.query(
                "SELECT SUM(v) FROM t").rows[0][0] == total + 7
            assert sess.storage.delta_store.rows_current() == 0
        finally:
            config.set_var("tidb_tpu_delta_store", prev)


class TestStagingAndShed:
    def test_staged_bytes_on_server_root_and_shed(self, sess):
        _load(sess, "t")
        sess.query("SELECT SUM(v) FROM t")
        sess.execute("UPDATE t SET v = 0 WHERE id < 30")
        sess.query("SELECT SUM(v) FROM t")    # memoize for the fold
        st = sess.storage
        staged = st.delta_store.staged_bytes()
        assert staged > 0
        assert deltamod.tracker().host >= staged
        shed0 = metrics.snapshot().get(
            metrics.DELTA_MERGES + '{trigger="shed"}', 0)
        # the SERVER shed chain (GET /shed, admission overflow) forces
        # an early merge that frees the staged journal bytes. The chain
        # sheds EVERY live store (other suites' storages linger until
        # GC), so the counter moves by at least one, not exactly one.
        sched.shed_server(0)
        assert st.delta_store.staged_bytes() == 0
        assert metrics.snapshot().get(
            metrics.DELTA_MERGES + '{trigger="shed"}', 0) >= shed0 + 1
        # reads stay correct after the forced merge
        want = sum(i % 7 for i in range(30, 4000))
        assert sess.query("SELECT SUM(v) FROM t").rows[0][0] == want

    def test_row_threshold_triggers_background_merge(self, sess):
        _load(sess, "t", n=600)
        sess.query("SELECT SUM(v) FROM t")
        prev = config.get_var("tidb_tpu_delta_merge_rows")
        config.set_var("tidb_tpu_delta_merge_rows", 8)
        try:
            for i in range(12):
                sess.execute(f"UPDATE t SET v = {i} WHERE id = {i}")
                sess.query("SELECT SUM(v) FROM t")   # keep memo fresh
            import time
            for _ in range(100):
                if sess.storage.delta_store.rows_current() < 12:
                    break
                time.sleep(0.05)
            assert sess.storage.delta_store.rows_current() < 12, \
                "background merge never fired past the row threshold"
        finally:
            config.set_var("tidb_tpu_delta_merge_rows", prev)

    def test_close_releases_ledger(self):
        st = new_mock_storage()
        s = Session(st)
        s.execute("CREATE DATABASE d2; USE d2")
        s.execute("CREATE TABLE t (id BIGINT PRIMARY KEY, v BIGINT)")
        s.execute("INSERT INTO t VALUES (1, 1)")
        s.execute("UPDATE t SET v = 2 WHERE id = 1")
        before = deltamod.tracker().host
        staged = st.delta_store.staged_bytes()
        assert staged > 0
        s.close()
        st.close()
        assert deltamod.tracker().host == before - staged


class TestDeviceDeltaPatch:
    def test_hbm_block_patched_in_place(self, sess):
        """An UPDATE folds into the resident device block (fill_ts
        advances, same entry) instead of dropping it."""
        _load(sess, "t")
        # twice: a cold streamed read fills the host cache at stream
        # end; the device block fills on the first cache-resident serve
        sess.query("SELECT SUM(v) FROM t")
        sess.query("SELECT SUM(v) FROM t")
        dc = sess.storage.device_cache
        if len(dc) == 0:
            pytest.skip("device path off in this environment")
        tid = sess.domain.info_schema().table("d", "t").id
        snap0 = {k: ts for k, _dv, ts in dc.snapshot_table(tid)}
        sess.execute("UPDATE t SET v = v + 5 WHERE id = 7")
        total = sess.query("SELECT SUM(v) FROM t").rows[0][0]
        assert total == sum(i % 7 for i in range(4000)) + 5
        snap1 = {k: ts for k, _dv, ts in dc.snapshot_table(tid)}
        advanced = [k for k, ts in snap1.items()
                    if k in snap0 and ts > snap0[k]]
        assert advanced, "no resident block advanced its fill_ts"

    def test_insert_lands_in_padding_tail(self, sess):
        _load(sess, "t", n=100)
        sess.query("SELECT COUNT(*), SUM(v) FROM t")
        for i in range(5):
            sess.execute(f"INSERT INTO t VALUES ({1000 + i}, 1, 'n')")
        r = sess.query("SELECT COUNT(*), SUM(v) FROM t").rows[0]
        assert r == (105, sum(i % 7 for i in range(100)) + 5)
