"""Static memory-accounting invariant, enforced as a test (style of
test_lint_metrics.py): every DATA-SIZED numpy allocation site in
executor/ and ops/ — `np.empty` / `np.zeros` / `np.concatenate` whose
size scales with input data — must either live inside a function
registered in `memtrack.AUDITED_HELPERS` (its bytes are covered by
tracker accounting, directly or through its caller) or carry an
explicit `# memtrack: exempt <reason>` tag on its line or the line
above. A new operator buffering rows without billing a tracker fails
this lint instead of silently bypassing per-query accounting.

Below-threshold sites are auto-exempt:
- constant sizes <= 4096 elements (cannot scale with data; anything
  larger must be audited even if constant),
- bool masks (`dtype=bool`): 1 byte/row, an order of magnitude below
  the column payloads the trackers bound.
"""

import ast
import os

from tidb_tpu import memtrack

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PKG = os.path.join(REPO, "tidb_tpu")
SCAN_DIRS = ("executor", "ops")
ALLOC_FNS = ("empty", "zeros", "concatenate")
CONST_MAX = 4096
EXEMPT_TAG = "# memtrack: exempt"


def _files():
    for d in SCAN_DIRS:
        for root, _dirs, files in os.walk(os.path.join(PKG, d)):
            if "__pycache__" in root:
                continue
            for f in sorted(files):
                if f.endswith(".py"):
                    yield os.path.join(root, f)


def _alloc_calls(tree):
    """-> [(Call, enclosing qualname)] for np.empty/zeros/concatenate."""
    out = []

    def visit(node, qual):
        for child in ast.iter_child_nodes(node):
            q = qual
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.ClassDef)):
                q = f"{qual}.{child.name}" if qual else child.name
            visit(child, q)
        if isinstance(node, ast.Call):
            fn = node.func
            if isinstance(fn, ast.Attribute) and fn.attr in ALLOC_FNS \
                    and isinstance(fn.value, ast.Name) \
                    and fn.value.id == "np":
                out.append((node, qual))

    visit(tree, "")
    return out


def _const_size(arg) -> int | None:
    """Statically-known element count of a size argument, else None."""
    if isinstance(arg, ast.Constant) and isinstance(arg.value, int):
        return arg.value
    if isinstance(arg, (ast.Tuple, ast.List)):
        prod = 1
        for el in arg.elts:
            if not (isinstance(el, ast.Constant) and
                    isinstance(el.value, int)):
                return None
            prod *= el.value
        return prod
    return None


def _is_bool_dtype(call) -> bool:
    cands = [kw.value for kw in call.keywords if kw.arg == "dtype"]
    if len(call.args) > 1:
        cands.append(call.args[1])
    return any(isinstance(c, ast.Name) and c.id == "bool" for c in cands)


def _below_threshold(call) -> bool:
    if not call.args:
        return True                     # no size: nothing to bound
    size = _const_size(call.args[0])
    if size is not None and size <= CONST_MAX:
        return True
    return _is_bool_dtype(call)


def _tagged(lines, lineno: int) -> bool:
    for ln in (lineno, lineno - 1):
        if 1 <= ln <= len(lines) and EXEMPT_TAG in lines[ln - 1]:
            return True
    return False


def test_data_sized_allocations_are_accounted_or_exempt():
    offenders = []
    for path in _files():
        rel = os.path.relpath(path, PKG)
        with open(path) as f:
            src = f.read()
        lines = src.splitlines()
        for call, qual in _alloc_calls(ast.parse(src, filename=path)):
            if _below_threshold(call):
                continue
            if f"{rel}::{qual}" in memtrack.AUDITED_HELPERS:
                continue
            if _tagged(lines, call.lineno):
                continue
            offenders.append(
                f"{rel}:{call.lineno} (in {qual or '<module>'}): "
                f"data-sized np.{call.func.attr} outside an audited "
                f"helper — bill a memtrack node or tag "
                f"'{EXEMPT_TAG} <reason>'")
    assert not offenders, "\n".join(offenders)


def test_audited_helpers_still_exist():
    """A stale registry entry would exempt nothing (renamed function
    keeps allocating unaudited) — every entry must resolve."""
    quals_by_file = {}
    for path in _files():
        rel = os.path.relpath(path, PKG)
        with open(path) as f:
            tree = ast.parse(f.read(), filename=path)
        quals = set()

        def collect(node, qual):
            for child in ast.iter_child_nodes(node):
                q = qual
                if isinstance(child, (ast.FunctionDef,
                                      ast.AsyncFunctionDef, ast.ClassDef)):
                    q = f"{qual}.{child.name}" if qual else child.name
                    quals.add(q)
                collect(child, q)

        collect(tree, "")
        quals_by_file[rel] = quals
    for entry in memtrack.AUDITED_HELPERS:
        rel, qual = entry.split("::")
        assert rel in quals_by_file, entry
        assert qual in quals_by_file[rel], entry


def test_lint_is_not_vacuous():
    """The scan must actually see the allocation sites it governs."""
    hits = 0
    for path in _files():
        with open(path) as f:
            hits += len(_alloc_calls(ast.parse(f.read(), filename=path)))
    assert hits >= 30, hits
