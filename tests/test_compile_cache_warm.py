"""Warm-run persistent-compile-cache regression (ISSUE 6 satellite).

BENCH r05 showed Q1 `first_run_secs: 48.82` DESPITE the persistent XLA
cache from PR 3 — the bench's CPU-fallback path disabled the cache
outright (to avoid loading AOT entries compiled for a different
virtualized feature set), so every bench process re-paid the first
compile. The fix scopes the cache to a per-host-feature-set CPU
subdirectory (`util/compile_cache.scoped_cpu_dir`) instead of
disabling it. Pinned here:

  * the scoping helper is stable, distinct from the base dir, and
    distinct per feature set;
  * the bench-level contract — a SECOND process over the same scoped
    cache dir reports compile-cache misses == 0 (everything loads from
    disk) and at least one hit.
"""

import json
import os
import subprocess
import sys

from tidb_tpu.util import compile_cache

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_PROG = r"""
import json, os
from tidb_tpu.util import compile_cache
# the package enables the cache at import with the production 1s
# min-compile floor; this probe's programs compile in ms, so lower the
# floor to catch them (bench's real Q1 program is far above the floor)
compile_cache.enable(min_compile_secs=0.0)
import jax
import jax.numpy as jnp

@jax.jit
def f(x):
    return (jnp.sin(x) @ jnp.cos(x.T)).sum()

f(jnp.arange(2048.0, dtype=jnp.float32).reshape(32, 64))
print("STATS " + json.dumps(compile_cache.stats()))
"""


def test_scoped_cpu_dir_stable_and_distinct():
    base = os.path.join("/tmp", "cc-base")
    d1 = compile_cache.scoped_cpu_dir(base)
    assert d1 == compile_cache.scoped_cpu_dir(base)     # deterministic
    assert d1.startswith(os.path.join(base, "cpu-"))
    assert len(os.path.basename(d1)) == len("cpu-") + 12
    # the tag really fingerprints the feature set (arch+jax+cpu flags)
    assert compile_cache.cpu_feature_tag() == \
        compile_cache.cpu_feature_tag()


def _run(cache_dir: str) -> dict:
    env = dict(os.environ,
               JAX_PLATFORMS="cpu",
               TIDB_TPU_COMPILE_CACHE=cache_dir,
               JAX_COMPILATION_CACHE_DIR=cache_dir,
               JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS="0")
    proc = subprocess.run([sys.executable, "-c", _PROG],
                          capture_output=True, text=True, timeout=240,
                          env=env, cwd=REPO)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    for line in proc.stdout.splitlines():
        if line.startswith("STATS "):
            return json.loads(line[len("STATS "):])
    raise AssertionError(f"no STATS line in: {proc.stdout!r}")


def test_warm_run_compile_cache_misses_zero(tmp_path):
    """The bench regression pin: process 1 compiles into the scoped
    dir; process 2 (the 'warm bench run') must load everything —
    misses == 0 — exactly what kills the 48.8s Q1 first-run stall."""
    scoped = compile_cache.scoped_cpu_dir(str(tmp_path))
    cold = _run(scoped)
    assert cold["dir"] == scoped          # cache ENABLED, not poisoned
    assert cold["misses"] >= 1            # really compiled
    assert cold["entries"] >= 1           # really persisted
    warm = _run(scoped)
    assert warm["dir"] == scoped
    assert warm["misses"] == 0, warm      # the whole point of the fix
    assert warm["hits"] >= 1, warm
