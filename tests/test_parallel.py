"""Mesh-distributed aggregation vs the host ground truth.

Runs on the 8-virtual-CPU-device mesh from conftest.py — the hermetic
multi-"node" strategy of the reference's mocktikv (SURVEY.md §4), at the
chip level.
"""

import numpy as np
import pytest

import jax

from tidb_tpu.chunk import Chunk, Column
from tidb_tpu.expression import AggDesc, AggFunc
from tidb_tpu.expression.core import Op, col, const, func
from tidb_tpu.ops.hashagg import HashAggregator
from tidb_tpu.ops.hostagg import host_hash_agg
from tidb_tpu.parallel import MeshAggKernel, build_mesh
from tidb_tpu.sqltypes import new_double_field, new_int_field, new_string_field


def _mk_chunk(n, num_groups=37, with_strings=False, seed=0):
    rng = np.random.default_rng(seed)
    g = rng.integers(0, num_groups, n).astype(np.int64)
    gv = rng.random(n) > 0.05
    x = rng.integers(-1000, 1000, n).astype(np.int64)
    xv = rng.random(n) > 0.1
    y = rng.normal(size=n)
    cols = [Column(new_int_field(), g, gv),
            Column(new_int_field(), x, xv),
            Column(new_double_field(), y)]
    if with_strings:
        names = np.array([f"name-{v}" for v in g % 7], dtype=object)
        cols.append(Column(new_string_field(32), names,
                           rng.random(n) > 0.03))
    return Chunk(cols)


def _results(group_exprs, aggs, gr):
    agg = HashAggregator(aggs)
    agg.update(gr)
    return agg.results()


def _assert_same(res_a, res_b):
    assert len(res_a) == len(res_b)
    for (ka, va), (kb, vb) in zip(res_a, res_b):
        assert ka == kb
        for a, b in zip(va, vb):
            if isinstance(a, float) or isinstance(b, float):
                assert a == pytest.approx(b, rel=1e-9)
            else:
                assert a == b


@pytest.fixture(scope="module")
def mesh():
    assert len(jax.devices()) == 8, "conftest must force 8 virtual devices"
    return build_mesh(8)


def test_mesh_shape(mesh):
    assert mesh.shape == {"batch": 8}


def test_dist_agg_matches_host(mesh):
    ch = _mk_chunk(10_000)
    gcol = col(0, new_int_field(), "g")
    xcol = col(1, new_int_field(), "x")
    ycol = col(2, new_double_field(), "y")
    flt = func(Op.GT, xcol, const(-500))
    aggs = [AggDesc(AggFunc.COUNT, None),
            AggDesc(AggFunc.SUM, xcol),
            AggDesc(AggFunc.AVG, ycol),
            AggDesc(AggFunc.MIN, xcol),
            AggDesc(AggFunc.MAX, ycol),
            AggDesc(AggFunc.FIRST_ROW, gcol)]
    k = MeshAggKernel(mesh, flt, [gcol], aggs, capacity=256)
    got = _results([gcol], aggs, k(ch))
    # host ground truth: filter first, then group
    mask = np.asarray((ch.columns[1].data > -500) & ch.columns[1].valid)
    want = _results([gcol], aggs,
                    host_hash_agg(ch.filter(mask), None, [gcol], aggs))
    _assert_same(got, want)


def test_dist_agg_string_group_keys(mesh):
    ch = _mk_chunk(5_000, with_strings=True, seed=3)
    scol = col(3, new_string_field(32), "name")
    gcol = col(0, new_int_field(), "g")
    aggs = [AggDesc(AggFunc.COUNT, None),
            AggDesc(AggFunc.FIRST_ROW, scol)]
    k = MeshAggKernel(mesh, None, [scol, gcol], aggs, capacity=512)
    got = _results([scol, gcol], aggs, k(ch))
    want = _results([scol, gcol], aggs,
                    host_hash_agg(ch, None, [scol, gcol], aggs))
    _assert_same(got, want)


def test_dist_agg_scalar_no_groups(mesh):
    ch = _mk_chunk(4_000, seed=7)
    xcol = col(1, new_int_field(), "x")
    aggs = [AggDesc(AggFunc.COUNT, None), AggDesc(AggFunc.SUM, xcol)]
    k = MeshAggKernel(mesh, None, [], aggs, capacity=8)
    got = _results([], aggs, k(ch))
    want = _results([], aggs, host_hash_agg(ch, None, [], aggs))
    _assert_same(got, want)


def test_dist_agg_capacity_overflow(mesh):
    from tidb_tpu.ops.hashagg import CapacityError
    n = 4096
    ch = Chunk([Column(new_int_field(), np.arange(n, dtype=np.int64))])
    gcol = col(0, new_int_field(), "g")
    k = MeshAggKernel(mesh, None, [gcol], [AggDesc(AggFunc.COUNT, None)],
                      capacity=64)
    with pytest.raises(CapacityError):
        k(ch)


def test_dist_agg_empty_chunk(mesh):
    ch = Chunk([Column(new_int_field(), np.empty(0, dtype=np.int64))])
    gcol = col(0, new_int_field(), "g")
    aggs = [AggDesc(AggFunc.COUNT, None)]
    k = MeshAggKernel(mesh, None, [gcol], aggs, capacity=8)
    gr = k(ch)
    assert gr.keys == []


def test_dist_agg_float_group_keys(mesh):
    # regression: value-cast hashing truncated 2.3 and 2.7 to the same
    # group under both seeds; bitcast hashing must keep them distinct
    n = 4096
    vals = np.tile(np.array([2.3, 2.7, -0.0, 0.0]), n // 4)
    ch = Chunk([Column(new_double_field(), vals)])
    gcol = col(0, new_double_field(), "g")
    aggs = [AggDesc(AggFunc.COUNT, None)]
    k = MeshAggKernel(mesh, None, [gcol], aggs, capacity=16)
    got = dict((key[0], v[0]) for key, v in _results([gcol], aggs, k(ch)))
    assert got == {2.3: n // 4, 2.7: n // 4, 0.0: n // 2}
