"""HTTP region/MVCC debug API + raw KV client (ref:
server/region_handler.go:73-91; store/tikv/rawkv.go)."""

import json
import urllib.error

import pytest

from tidb_tpu.server.status import StatusServer
from tidb_tpu.util import statusclient
from tidb_tpu.session import Session
from tidb_tpu.store.rawkv import RawKVClient
from tidb_tpu.store.storage import new_mock_storage


@pytest.fixture
def env():
    st = new_mock_storage()
    s = Session(st)
    s.execute("CREATE DATABASE d")
    s.execute("USE d")
    s.execute("CREATE TABLE t (id BIGINT PRIMARY KEY, v VARCHAR(16))")
    s.execute("INSERT INTO t VALUES (1, 'one'), (2, 'two')")
    s.execute("UPDATE t SET v = 'uno' WHERE id = 1")
    status = StatusServer(st, None)
    status.start()
    yield st, s, status.port
    status.close()
    s.close()


def _get(port, path):
    try:
        return 200, statusclient.get_json("127.0.0.1", port, path,
                                          timeout=5)
    except urllib.error.HTTPError as e:
        body = e.read()
        try:
            return e.code, json.loads(body)
        except ValueError:
            return e.code, {}


class TestDebugAPI:
    def test_table_regions(self, env):
        st, s, port = env
        s.query("SPLIT TABLE t AT (10), (20)")
        code, body = _get(port, "/tables/d/t/regions")
        assert code == 200
        assert body["table"] == "d.t"
        assert len(body["regions"]) >= 3
        starts = [r["start_key"] for r in body["regions"]]
        assert len(set(starts)) == len(starts)

    def test_regions_and_by_id(self, env):
        st, _s, port = env
        code, regions = _get(port, "/regions")
        assert code == 200 and regions
        rid = regions[0]["id"]
        code, one = _get(port, f"/regions/{rid}")
        assert code == 200 and one["id"] == rid
        code, _ = _get(port, "/regions/999999")
        assert code == 404

    def test_mvcc_by_key_shows_versions(self, env):
        st, _s, port = env
        code, body = _get(port, "/mvcc/key/d/t/1")
        assert code == 200
        assert body["handle"] == 1
        # INSERT + UPDATE = two committed write versions, no lock
        assert body["lock"] is None
        assert len(body["writes"]) == 2
        assert body["writes"][0]["commit_ts"] > \
            body["writes"][1]["commit_ts"]
        assert all(w["type"] == "PUT" for w in body["writes"])

    def test_mvcc_by_txn(self, env):
        st, _s, port = env
        code, body = _get(port, "/mvcc/key/d/t/2")
        start_ts = body["writes"][0]["start_ts"]
        code, hits = _get(port, f"/mvcc/txn/{start_ts}")
        assert code == 200
        # the INSERT txn touched both rows
        assert len(hits) == 2

    def test_missing_table_errors(self, env):
        _st, _s, port = env
        code, body = _get(port, "/tables/d/nope/regions")
        assert code == 500 and "error" in body


class TestRawKV:
    def test_basic_ops(self, env):
        st, _s, _port = env
        c = RawKVClient(st)
        c.put(b"rk1", b"v1")
        c.put(b"rk2", b"v2")
        assert c.get(b"rk1") == b"v1"
        assert c.get(b"missing") is None
        assert c.batch_get([b"rk1", b"rk2", b"nope"]) == \
            {b"rk1": b"v1", b"rk2": b"v2"}
        c.delete(b"rk1")
        assert c.get(b"rk1") is None

    def test_raw_invisible_to_sql(self, env):
        st, s, _port = env
        RawKVClient(st).put(b"zzz", b"raw")
        # raw namespace is a separate "column family": MVCC reads and
        # SQL scans never see it
        assert s.query("SELECT COUNT(*) FROM t").rows == [(2,)]

    def test_scan_and_delete_range_across_splits(self, env):
        st, _s, _port = env
        c = RawKVClient(st)
        pairs = [(b"k%03d" % i, b"v%d" % i) for i in range(50)]
        c.batch_put(pairs)
        st.cluster.split(b"k010")
        st.cluster.split(b"k030")
        got = c.scan(b"k000", b"k050", limit=100)
        assert got == pairs
        got = c.scan(b"k005", b"k015", limit=100)
        assert got == pairs[5:15]
        c.delete_range(b"k010", b"k040")
        left = c.scan(b"k000", b"k999", limit=100)
        assert left == pairs[:10] + pairs[40:]


class TestRemoteRawMvcc:
    def test_raw_and_mvcc_over_the_wire(self):
        """raw_*/mvcc_* ride the storage RPC like every kv_* call."""
        from tidb_tpu.store.remote import StorageServer, connect
        srv = StorageServer()
        srv.start()
        st = connect("127.0.0.1", srv.port)
        try:
            c = RawKVClient(st)
            c.put(b"wk", b"wv")
            assert c.get(b"wk") == b"wv"
            s = Session(st)
            s.execute("CREATE DATABASE r")
            s.execute("CREATE TABLE r.t (id BIGINT PRIMARY KEY)")
            s.execute("INSERT INTO r.t VALUES (9)")
            from tidb_tpu import tablecodec
            info = s.domain.info_schema().table("r", "t")
            m = st.shim.mvcc_by_key(tablecodec.record_key(info.id, 9))
            assert len(m["writes"]) == 1
            s.close()
        finally:
            st.close()
            srv.close()
