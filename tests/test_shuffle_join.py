"""Mesh shuffle hash join vs a naive host oracle.

Covers what the replicated lookup join (dist_join.py) rejects: duplicate
keys on BOTH sides, large build sides, NULL keys, multi-column keys,
skewed hash distributions (bucket overflow retry), and string keys via
the shared-dictionary encoder. Ref model: executor/join_test.go cases
over mocktikv, here against the 8-device virtual mesh.
"""

import numpy as np
import pytest

from tidb_tpu.ops.join import JoinKeyEncoder
from tidb_tpu.parallel import build_mesh
from tidb_tpu.parallel.shuffle_join import MeshShuffleJoinKernel


@pytest.fixture(scope="module")
def mesh():
    return build_mesh(8)


def oracle_pairs(pk, bk):
    """All (probe_i, build_i) with equal, fully-non-NULL keys."""
    out = set()
    index = {}
    for i in range(len(bk[0][0])):
        if all(v[i] for _d, v in bk):
            index.setdefault(tuple(d[i] for d, _v in bk), []).append(i)
    for i in range(len(pk[0][0])):
        if not all(v[i] for _d, v in pk):
            continue
        for b in index.get(tuple(d[i] for d, _v in pk), ()):
            out.add((i, b))
    return out


def lanes(*cols):
    return [(np.asarray(d), np.asarray(v, dtype=bool)) for d, v in cols]


def check(mesh, pk, bk):
    k = MeshShuffleJoinKernel(mesh, len(pk))
    li, ri = k(pk, bk, len(bk[0][0]), len(pk[0][0]))
    got = set(zip(li.tolist(), ri.tolist()))
    assert got == oracle_pairs(pk, bk)


def test_duplicate_keys_both_sides(mesh):
    rng = np.random.default_rng(0)
    n, m = 5000, 3000
    pk = lanes((rng.integers(0, 50, n), np.ones(n)))
    bk = lanes((rng.integers(0, 50, m), np.ones(m)))
    check(mesh, pk, bk)


def test_multi_key_with_nulls(mesh):
    rng = np.random.default_rng(1)
    n, m = 2000, 2500
    pk = lanes((rng.integers(0, 30, n), rng.random(n) > 0.1),
               (rng.integers(0, 4, n), rng.random(n) > 0.1))
    bk = lanes((rng.integers(0, 30, m), rng.random(m) > 0.1),
               (rng.integers(0, 4, m), rng.random(m) > 0.1))
    check(mesh, pk, bk)


def test_float_keys(mesh):
    rng = np.random.default_rng(2)
    n, m = 1500, 1500
    vals = np.array([0.5, 1.25, -3.75, 2.0, 1e9])
    pk = lanes((vals[rng.integers(0, 5, n)], np.ones(n)))
    bk = lanes((vals[rng.integers(0, 5, m)], np.ones(m)))
    check(mesh, pk, bk)


def test_skewed_single_key_forces_bucket_retry(mesh):
    # 90% of rows share one key: one destination chip receives almost
    # everything, far past the 4x slack buckets
    rng = np.random.default_rng(3)
    n, m = 4000, 4000
    p = np.where(rng.random(n) < 0.9, 7, rng.integers(0, 1000, n))
    b = np.where(rng.random(m) < 0.9, 7, rng.integers(0, 1000, m))
    pk, bk = lanes((p, np.ones(n))), lanes((b, np.ones(m)))
    k = MeshShuffleJoinKernel(mesh, 1)
    li, ri = k(pk, bk, m, n)
    assert set(zip(li.tolist(), ri.tolist())) == oracle_pairs(pk, bk)


def test_string_keys_via_encoder(mesh):
    rng = np.random.default_rng(4)
    n, m = 1200, 900
    words = np.array(["asia", "europe", "africa", "america", None],
                     dtype=object)
    pv = words[rng.integers(0, 5, n)]
    bv = words[rng.integers(0, 5, m)]
    enc = JoinKeyEncoder(1)
    bk = enc.fit_build([(bv, np.array([x is not None for x in bv]))])
    pk = enc.transform_probe([(pv, np.array([x is not None for x in pv]))])
    check(mesh, pk, bk)


def test_empty_sides(mesh):
    k = MeshShuffleJoinKernel(mesh, 1)
    e = lanes((np.empty(0, np.int64), np.empty(0, bool)))
    p = lanes((np.arange(10), np.ones(10)))
    assert k(p, e, 0, 10) == (pytest.approx([]), pytest.approx([]))
    li, ri = k(e, p, 10, 0)
    assert len(li) == 0 and len(ri) == 0


def test_single_device_mesh_delegates(mesh):
    m1 = build_mesh(1)
    rng = np.random.default_rng(5)
    pk = lanes((rng.integers(0, 20, 500), np.ones(500)))
    bk = lanes((rng.integers(0, 20, 400), np.ones(400)))
    k = MeshShuffleJoinKernel(m1, 1)
    li, ri = k(pk, bk, 400, 500)
    assert set(zip(li.tolist(), ri.tolist())) == oracle_pairs(pk, bk)
