"""Out-of-process storage (store/remote.py): the SQL layer over sockets.

Ref: store/tikv/client.go (conn pool), region_request.go (network-error
retry), and the reference's defining stateless-SQL-over-RPC shape."""

import signal
import socket
import subprocess
import sys
import time

import numpy as np
import pytest

from tidb_tpu.session import Session
from tidb_tpu.store.remote import RemoteStorage, StorageServer, connect


@pytest.fixture
def server():
    srv = StorageServer()
    srv.start()
    yield srv
    srv.close()


@pytest.fixture
def sess(server):
    st = connect("127.0.0.1", server.port)
    s = Session(st)
    s.execute("CREATE DATABASE d")
    s.execute("USE d")
    yield s
    s.close()
    st.close()


class TestRemoteSQL:
    def test_ddl_dml_query(self, sess):
        sess.execute("CREATE TABLE t (id BIGINT PRIMARY KEY, v BIGINT, "
                     "s VARCHAR(10))")
        sess.execute("INSERT INTO t VALUES " + ",".join(
            f"({i},{i * 3},'s{i % 5}')" for i in range(500)))
        r = sess.query("SELECT s, COUNT(*), SUM(v) FROM t GROUP BY s "
                       "ORDER BY s")
        assert len(r.rows) == 5
        assert sum(x[1] for x in r.rows) == 500
        sess.execute("UPDATE t SET v = 0 WHERE id < 100")
        assert sess.query("SELECT SUM(v) FROM t").rows[0][0] == \
            sum(i * 3 for i in range(100, 500))
        sess.execute("DELETE FROM t WHERE id >= 400")
        assert sess.query("SELECT COUNT(*) FROM t").rows[0][0] == 400

    def test_joins_and_index(self, sess):
        sess.execute("CREATE TABLE a (id BIGINT PRIMARY KEY, k BIGINT)")
        sess.execute("CREATE TABLE b (id BIGINT PRIMARY KEY, lbl "
                     "VARCHAR(8))")
        sess.execute("CREATE INDEX ik ON a (k)")
        sess.execute("INSERT INTO b VALUES " + ",".join(
            f"({i},'L{i}')" for i in range(20)))
        sess.execute("INSERT INTO a VALUES " + ",".join(
            f"({i},{i % 20})" for i in range(300)))
        r = sess.query("SELECT b.lbl, COUNT(*) FROM a JOIN b "
                       "ON a.k = b.id GROUP BY b.lbl")
        assert len(r.rows) == 20
        r2 = sess.query("SELECT id FROM a WHERE k = 3 ORDER BY id")
        assert [x[0] for x in r2.rows] == list(range(3, 300, 20))

    def test_txn_conflict_and_isolation(self, server):
        st1 = connect("127.0.0.1", server.port)
        st2 = connect("127.0.0.1", server.port)
        s1, s2 = Session(st1), Session(st2)
        s1.execute("CREATE DATABASE d; USE d")
        s1.execute("CREATE TABLE t (id BIGINT PRIMARY KEY, v BIGINT)")
        s1.execute("INSERT INTO t VALUES (1, 0)")
        s2.execute("USE d")
        s2.execute("BEGIN")
        assert s2.query("SELECT v FROM t").rows == [(0,)]
        s1.execute("UPDATE t SET v = 5 WHERE id = 1")
        assert s2.query("SELECT v FROM t").rows == [(0,)]   # snapshot
        s2.execute("COMMIT")
        assert s2.query("SELECT v FROM t").rows == [(5,)]
        # optimistic conflict replay
        s1.execute("BEGIN")
        s1.execute("UPDATE t SET v = v + 1 WHERE id = 1")
        s2.execute("UPDATE t SET v = v + 10 WHERE id = 1")
        s1.execute("COMMIT")
        assert s1.query("SELECT v FROM t").rows == [(16,)]
        for s, st in ((s1, st1), (s2, st2)):
            s.close()
            st.close()

    def test_bulk_load_and_region_split(self, server, sess):
        from tidb_tpu.table import Table, bulkload
        sess.execute("CREATE TABLE big (id BIGINT PRIMARY KEY, v BIGINT)")
        tbl = Table(sess.domain.info_schema().table("d", "big"),
                    sess.storage)
        n = 20000
        bulkload.bulk_load(sess.storage, tbl, {
            "id": np.arange(n, dtype=np.int64),
            "v": np.arange(n, dtype=np.int64) % 97})
        sess.storage.cluster.split_table(tbl.info.id, 4, max_handle=n)
        r = sess.query("SELECT COUNT(*), SUM(v) FROM big")
        assert r.rows[0] == (n, int((np.arange(n) % 97).sum()))

    def test_connection_failure_retries_transparently(self, server, sess):
        sess.execute("CREATE TABLE t (id BIGINT PRIMARY KEY)")
        sess.execute("INSERT INTO t VALUES (1), (2)")
        assert sess.query("SELECT COUNT(*) FROM t").rows == [(2,)]
        # sever every pooled connection behind the client's back
        for pool in sess.storage.rpc._pools.values():
            for c in list(pool):
                c.sock.shutdown(socket.SHUT_RDWR)
        assert sess.query("SELECT COUNT(*) FROM t").rows == [(2,)]


class TestProcessBoundary:
    def _free_port(self):
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
        s.close()
        return port

    def _spawn(self, port, snapshot):
        proc = subprocess.Popen(
            [sys.executable, "-m", "tidb_tpu.store.remote",
             "--port", str(port), "--snapshot", snapshot],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            cwd="/root/repo", env={"PYTHONPATH": "/root/repo",
                                   "PATH": "/usr/bin:/bin",
                                   "JAX_PLATFORMS": "cpu",
                                   "HOME": "/root"})
        line = proc.stdout.readline()
        assert "storage listening" in line, line
        return proc

    def test_kill_and_restart_with_snapshot(self, tmp_path):
        """The reference's stateless-SQL property: storage goes away and
        comes back; the SQL layer's session keeps working."""
        port = self._free_port()
        snap = str(tmp_path / "store.snap")
        proc = self._spawn(port, snap)
        try:
            st = connect("127.0.0.1", port)
            s = Session(st)
            s.execute("CREATE DATABASE d; USE d")
            s.execute("CREATE TABLE t (id BIGINT PRIMARY KEY, v BIGINT)")
            s.execute("INSERT INTO t VALUES (1, 10), (2, 20)")
            assert s.query("SELECT SUM(v) FROM t").rows == [(30,)]

            # graceful stop persists the snapshot
            proc.send_signal(signal.SIGTERM)
            assert proc.wait(timeout=20) == 0
            proc = self._spawn(port, snap)

            # SAME session object: reads and writes continue
            assert s.query("SELECT SUM(v) FROM t").rows == [(30,)]
            s.execute("INSERT INTO t VALUES (3, 12)")
            assert s.query("SELECT SUM(v) FROM t").rows == [(42,)]
            s.close()
            st.close()
        finally:
            proc.terminate()
            proc.wait(timeout=20)


class TestTpchOverRemote:
    def test_tpch_queries_through_the_wire(self, server):
        """VERDICT acceptance: the TPC-H suite passes with storage
        out-of-process."""
        from tests import tpch
        st = connect("127.0.0.1", server.port)
        s = Session(st)
        s.execute("CREATE DATABASE tpch; USE tpch")
        d = tpch.TpchData()
        tpch.load(s, d)
        for q, truth in ((tpch.Q1, tpch.truth_q1), (tpch.Q3, tpch.truth_q3),
                         (tpch.Q5, tpch.truth_q5), (tpch.Q4, tpch.truth_q4),
                         (tpch.Q6, tpch.truth_q6)):
            got = s.query(q).rows
            want = truth(d)
            if q is tpch.Q6:
                assert float(got[0][0]) == pytest.approx(want)
                continue
            assert len(got) == len(want), (len(got), len(want))
            for g, w in zip(got, want):
                for x, y in zip(g, w):
                    if isinstance(y, float):
                        # decimal AVG columns round at the column scale
                        assert float(x) == pytest.approx(y, rel=1e-4,
                                                         abs=1e-6)
                    else:
                        assert str(x) == str(y) or x == y
        s.close()
        st.close()
