"""Replica/partition management: store failure, leader failover,
replica repair, leader balancing (ref: region_request.go onSendFail
store failover; PD's balance schedulers; SURVEY §2.7-6)."""

import pytest

from tidb_tpu.kv import StoreUnavailableError
from tidb_tpu.session import Session
from tidb_tpu.store.storage import new_mock_storage


@pytest.fixture
def storage():
    st = new_mock_storage(num_stores=3)
    yield st
    st.close()


class TestFailover:
    def test_reads_and_writes_survive_leader_store_death(self, storage):
        t = storage.begin()
        t.set(b"k1", b"v1")
        t.commit()
        region = storage.cluster.region_by_key(b"k1")
        storage.region_cache.locate(b"k1")          # cache old leader
        storage.cluster.drop_store(region.leader_store)
        # read: client hits the dead store, reloads, follows new leader
        assert storage.begin().get(b"k1") == b"v1"
        t2 = storage.begin()
        t2.set(b"k2", b"v2")
        t2.commit()
        assert storage.begin().get(b"k2") == b"v2"

    def test_new_leader_is_surviving_peer(self, storage):
        region = storage.cluster.region_by_key(b"k")
        old_leader = region.leader_store
        storage.cluster.drop_store(old_leader)
        r2 = storage.cluster.region_by_key(b"k")
        assert r2.leader_store != old_leader
        assert old_leader not in r2.peer_stores
        assert r2.conf_ver > region.conf_ver      # peer set changed

    def test_replica_repair_after_drop(self, storage):
        extra = storage.cluster.add_store()
        region = storage.cluster.region_by_key(b"k")
        assert extra not in region.peer_stores
        n_before = len(region.peer_stores)
        storage.cluster.drop_store(region.peer_stores[0])
        r2 = storage.cluster.region_by_key(b"k")
        # replication factor restored using the spare store
        assert len(r2.peer_stores) == n_before
        assert extra in r2.peer_stores

    def test_dead_store_rpc_raises_store_unavailable(self, storage):
        loc = storage.region_cache.locate(b"k")
        storage.cluster.stores[loc.ctx.store_id].dropped = True
        with pytest.raises(StoreUnavailableError):
            storage.shim.kv_get(loc.ctx, b"k", storage.current_ts())

    def test_sql_survives_failover_mid_session(self, storage):
        s = Session(storage)
        s.execute("CREATE DATABASE d")
        s.execute("USE d")
        s.execute("CREATE TABLE t (id BIGINT PRIMARY KEY, v BIGINT)")
        s.execute("INSERT INTO t VALUES " + ",".join(
            f"({i},{i})" for i in range(200)))
        s.query("SPLIT TABLE t REGIONS 4")
        assert s.query("SELECT COUNT(*) FROM t").rows == [(200,)]
        # kill whichever store leads the table's first region
        region = storage.cluster.region_by_key(b"t")
        storage.cluster.drop_store(region.leader_store)
        assert s.query("SELECT COUNT(*), SUM(v) FROM t").rows == \
            [(200, sum(range(200)))]
        s.execute("INSERT INTO t VALUES (999, 999)")
        assert s.query("SELECT v FROM t WHERE id=999").rows == [(999,)]
        s.close()


class TestBalance:
    def test_balance_leaders_evens_counts(self, storage):
        for i in range(1, 12):
            storage.cluster.split(b"k%02d" % i)
        counts = storage.cluster.leader_counts()
        assert max(counts.values()) - min(counts.values()) > 1
        moved = storage.cluster.balance_leaders()
        assert moved > 0
        counts = storage.cluster.leader_counts()
        assert max(counts.values()) - min(counts.values()) <= 1
        # reads still route correctly after the transfers
        assert storage.begin().get(b"k05") is None

    def test_balance_idempotent(self, storage):
        storage.cluster.balance_leaders()
        assert storage.cluster.balance_leaders() == 0

    def test_leader_transfer_keeps_epoch(self, storage):
        """Leadership is not part of the region epoch: a cached ctx only
        sees NotLeader (with the new leader), never EpochNotMatch."""
        sid = storage.cluster.add_store()
        region = storage.cluster.region_by_key(b"k")
        t = storage.begin()
        t.set(b"k", b"v")
        t.commit()
        storage.region_cache.locate(b"k")
        storage.cluster.change_leader(region.id, sid)
        r2 = storage.cluster.region_by_key(b"k")
        assert r2.version == region.version
        assert storage.begin().get(b"k") == b"v"
