"""Storage layer tests: MVCC semantics, 2PC, region retries, lock resolution.

Ref models: store/tikv/2pc_test.go, isolation_test.go, lock_test.go,
scan_test.go, region_cache_test.go, 2pc_fail_test.go (failpoints).
"""

import threading

import pytest

from tidb_tpu.kv import (IsolationLevel, KeyLockedError, KVError, Mutation,
                         MutationOp, TxnAbortedError, UndeterminedError,
                         WriteConflictError)
from tidb_tpu.mockstore import MVCCStore, TimeoutError_
from tidb_tpu.store import new_mock_storage
from tidb_tpu.store.backoff import Backoffer
from tidb_tpu.util import failpoint


def fastbo(ms=5000):
    return Backoffer(ms, sleep_fn=lambda s: None)


@pytest.fixture
def storage():
    s = new_mock_storage()
    s.async_commit_secondaries = False  # deterministic tests
    # no real sleeps in tests
    yield s
    s.close()


# -- raw MVCC engine ---------------------------------------------------------

class TestMVCC:
    def put(self, store, key, val, ts, commit_ts):
        store.prewrite([Mutation(MutationOp.PUT, key, val)], key, ts)
        store.commit([key], ts, commit_ts)

    def test_snapshot_versions(self):
        s = MVCCStore()
        self.put(s, b"k", b"v1", 10, 11)
        self.put(s, b"k", b"v2", 20, 21)
        assert s.get(b"k", 15) == b"v1"
        assert s.get(b"k", 21) == b"v2"
        assert s.get(b"k", 5) is None

    def test_delete_visibility(self):
        s = MVCCStore()
        self.put(s, b"k", b"v", 10, 11)
        s.prewrite([Mutation(MutationOp.DELETE, b"k")], b"k", 20)
        s.commit([b"k"], 20, 21)
        assert s.get(b"k", 15) == b"v"
        assert s.get(b"k", 25) is None

    def test_lock_blocks_si_read_not_rc(self):
        s = MVCCStore()
        self.put(s, b"k", b"v", 10, 11)
        s.prewrite([Mutation(MutationOp.PUT, b"k", b"new")], b"k", 20)
        with pytest.raises(KeyLockedError):
            s.get(b"k", 25)
        assert s.get(b"k", 25, IsolationLevel.RC) == b"v"
        # reads below the lock ts are not blocked
        assert s.get(b"k", 15) == b"v"

    def test_write_conflict(self):
        s = MVCCStore()
        self.put(s, b"k", b"v", 10, 30)
        with pytest.raises(WriteConflictError):
            s.prewrite([Mutation(MutationOp.PUT, b"k", b"x")], b"k", 20)

    def test_rollback_then_prewrite_aborts(self):
        s = MVCCStore()
        s.rollback([b"k"], 20)
        with pytest.raises(TxnAbortedError):
            s.prewrite([Mutation(MutationOp.PUT, b"k", b"x")], b"k", 20)

    def test_commit_after_rollback_fails(self):
        s = MVCCStore()
        s.prewrite([Mutation(MutationOp.PUT, b"k", b"x")], b"k", 20)
        s.rollback([b"k"], 20)
        with pytest.raises(TxnAbortedError):
            s.commit([b"k"], 20, 21)

    def test_commit_idempotent(self):
        s = MVCCStore()
        self.put(s, b"k", b"v", 10, 11)
        s.commit([b"k"], 10, 11)  # no error

    def test_cleanup_expired_rolls_back(self):
        s = MVCCStore()
        s.prewrite([Mutation(MutationOp.PUT, b"k", b"x")], b"k", 20,
                   ttl_ms=100)
        # current_ts far in the future (physical ms domain)
        far = (1 << 40) << 18
        assert s.cleanup(b"k", 20, far) == 0
        with pytest.raises(TxnAbortedError):
            s.commit([b"k"], 20, 21)

    def test_cleanup_alive_lock_raises(self):
        s = MVCCStore()
        ts = (1000 << 18)
        s.prewrite([Mutation(MutationOp.PUT, b"k", b"x")], b"k", ts,
                   ttl_ms=10_000_000)
        with pytest.raises(KeyLockedError):
            s.cleanup(b"k", ts, ts + 1)

    def test_cleanup_committed_returns_commit_ts(self):
        s = MVCCStore()
        self.put(s, b"k", b"v", 10, 11)
        assert s.cleanup(b"k", 10, 99 << 18) == 11

    def test_resolve_lock_commit_and_rollback(self):
        s = MVCCStore()
        s.prewrite([Mutation(MutationOp.PUT, b"a", b"1"),
                    Mutation(MutationOp.PUT, b"b", b"2")], b"a", 20)
        s.resolve_lock(b"", b"", 20, 25)
        assert s.get(b"a", 30) == b"1"
        assert s.get(b"b", 30) == b"2"

    def test_scan_skips_deleted(self):
        s = MVCCStore()
        for i, k in enumerate([b"a", b"b", b"c"]):
            self.put(s, k, b"v" + k, 10 + i * 10, 11 + i * 10)
        s.prewrite([Mutation(MutationOp.DELETE, b"b")], b"b", 50)
        s.commit([b"b"], 50, 51)
        assert [k for k, _ in s.scan(b"", b"", 0, 100)] == [b"a", b"c"]
        assert [k for k, _ in s.scan(b"a", b"c", 0, 100)] == [b"a"]

    def test_gc_prunes_old_versions(self):
        s = MVCCStore()
        for i in range(5):
            self.put(s, b"k", b"v%d" % i, 10 + i * 10, 11 + i * 10)
        pruned = s.gc(45)
        assert pruned == 3  # 11, 21, 31 pruned; 41 is newest <= safepoint
        assert s.get(b"k", 100) == b"v4"
        assert s.get(b"k", 45) == b"v3"  # newest visible at safepoint survives


# -- txn through storage (unionstore + 2PC) ----------------------------------

class TestTxn:
    def test_basic_commit_and_read(self, storage):
        txn = storage.begin()
        txn.set(b"ta", b"1")
        txn.set(b"tb", b"2")
        txn.commit()
        txn2 = storage.begin()
        assert txn2.get(b"ta") == b"1"
        assert txn2.get(b"tb") == b"2"

    def test_read_own_writes_and_tombstone(self, storage):
        t1 = storage.begin()
        t1.set(b"k", b"v")
        t1.commit()
        t = storage.begin()
        assert t.get(b"k") == b"v"
        t.delete(b"k")
        assert t.get(b"k") is None
        t.set(b"k", b"v2")
        assert t.get(b"k") == b"v2"
        t.rollback()
        assert storage.begin().get(b"k") == b"v"

    def test_snapshot_isolation(self, storage):
        t0 = storage.begin()
        t0.set(b"k", b"old")
        t0.commit()
        reader = storage.begin()
        writer = storage.begin()
        writer.set(b"k", b"new")
        writer.commit()
        assert reader.get(b"k") == b"old"          # SI: pre-commit view
        assert storage.begin().get(b"k") == b"new"

    def test_write_conflict_surfaces(self, storage):
        t0 = storage.begin()
        t0.set(b"k", b"0")
        t0.commit()
        t1 = storage.begin()
        t2 = storage.begin()
        t1.set(b"k", b"1")
        t2.set(b"k", b"2")
        t2.commit()
        with pytest.raises(KVError):
            t1.commit()
        assert storage.begin().get(b"k") == b"2"

    def test_iter_union(self, storage):
        t0 = storage.begin()
        for k in (b"a", b"b", b"d"):
            t0.set(k, b"s" + k)
        t0.commit()
        t = storage.begin()
        t.set(b"c", b"bc")       # buffer-only
        t.delete(b"b")           # shadow delete
        t.set(b"a", b"ba")       # shadow overwrite
        got = list(t.iter_range(b"a", b"e"))
        assert got == [(b"a", b"ba"), (b"c", b"bc"), (b"d", b"sd")]


class TestTwoPCPool:
    def test_nested_on_batches_runs_inline_no_deadlock(self, storage):
        """_on_batches invoked ON a 2pc pool worker (async secondaries,
        RegionError re-splits) must fan out inline: submitting the
        sub-batches to the same bounded pool and blocking on their
        results deadlocks once every worker is a blocked parent — the
        stuck non-daemon workers then hang interpreter shutdown."""
        from tidb_tpu.kv import Mutation, MutationOp
        from tidb_tpu.store.txn import TwoPhaseCommitter
        storage.cluster.split(b"k2")
        storage.cluster.split(b"k4")
        muts = {k: Mutation(MutationOp.PUT, k, b"v")
                for k in (b"k1", b"k3", b"k5")}   # three regions
        c = TwoPhaseCommitter(
            storage.shim, storage.region_cache, storage.oracle,
            storage.resolver, muts, storage.oracle.get_timestamp(),
            concurrency=1)
        try:
            done = threading.Event()
            ran = []

            def act(bo, batch):
                ran.extend(batch.keys)

            def on_worker():   # occupies the committer's ONLY worker
                c._on_batches(fastbo(), list(muts), act,
                              primary_first=False)
                done.set()

            f = c._pool.submit(on_worker)
            assert done.wait(10.0), \
                "nested _on_batches deadlocked on its own pool"
            f.result()
            assert sorted(ran) == sorted(muts)
        finally:
            # wait=False so a reintroduced deadlock fails the assert
            # above instead of hanging the join here
            c._pool.shutdown(wait=False)


# -- distributed behavior: regions, retries, faults --------------------------

class TestDistributed:
    def test_multi_region_txn_and_scan(self, storage):
        # write across a split, then split again mid-life
        storage.cluster.split(b"m")
        t = storage.begin()
        for k in (b"a", b"k", b"n", b"z"):
            t.set(k, b"v" + k)
        t.commit()
        assert len(storage.cluster.all_regions()) == 2
        snap = storage.snapshot(storage.current_ts())
        got = [k for k, _ in snap.iter_range(b"", None)]
        assert got == [b"a", b"k", b"n", b"z"]

    def test_stale_region_cache_retries(self, storage):
        t = storage.begin()
        for k in (b"a", b"p", b"z"):
            t.set(k, b"1")
        t.commit()
        # warm the cache, then split behind its back
        storage.region_cache.locate(b"p")
        storage.cluster.split(b"m")
        storage.cluster.split(b"t")
        # reads must transparently recover from EpochNotMatch
        snap = storage.snapshot(storage.current_ts())
        snap_vals = snap.batch_get([b"a", b"p", b"z"])
        assert len(snap_vals) == 3
        # writes too
        t2 = storage.begin()
        t2.set(b"a", b"2")
        t2.set(b"z", b"2")
        t2.commit()
        assert storage.begin().get(b"z") == b"2"

    def test_leader_change_retry(self, storage):
        sid2 = storage.cluster.add_store()
        t = storage.begin()
        t.set(b"k", b"v")
        t.commit()
        region = storage.cluster.region_by_key(b"k")
        storage.region_cache.locate(b"k")  # cache current leader
        storage.cluster.change_leader(region.id, sid2)
        assert storage.begin().get(b"k") == b"v"  # NotLeader -> follow

    def test_abandoned_lock_resolved_by_reader(self, storage):
        # writer prewrites but never commits (crash): reader must roll it
        # back via the resolver once TTL expires
        t0 = storage.begin()
        t0.set(b"k", b"committed")
        t0.commit()
        start_ts = storage.current_ts()
        storage.engine.prewrite(
            [Mutation(MutationOp.PUT, b"k", b"orphan")], b"k", start_ts,
            ttl_ms=0)  # instantly expired
        assert storage.begin().get(b"k") == b"committed"
        # orphan txn is gone: its commit must now fail
        with pytest.raises(TxnAbortedError):
            storage.engine.commit([b"k"], start_ts, start_ts + 1)

    def test_committed_primary_rolls_forward(self, storage):
        # primary committed, secondary lock left behind (async commit death)
        t0 = storage.begin()
        t0.set(b"p", b"0")
        t0.set(b"s", b"0")
        t0.commit()
        start_ts = storage.current_ts()
        storage.engine.prewrite(
            [Mutation(MutationOp.PUT, b"p", b"1"),
             Mutation(MutationOp.PUT, b"s", b"1")], b"p", start_ts, ttl_ms=0)
        commit_ts = storage.current_ts()
        storage.engine.commit([b"p"], start_ts, commit_ts)  # primary only
        # reader hits the stale lock on s -> resolver sees committed primary
        # -> rolls forward; reads the new value
        assert storage.begin().get(b"s") == b"1"

    def test_server_busy_then_recover(self, storage):
        t = storage.begin()
        t.set(b"k", b"v")
        t.commit()
        calls = {"n": 0}

        def inject(cmd, ctx):
            if cmd == "Get" and calls["n"] < 2:
                calls["n"] += 1
                from tidb_tpu.kv import ServerBusyError
                raise ServerBusyError("busy")

        failpoint.enable("rpc/request", inject)
        try:
            # patch sleeps out of the snapshot's backoffers via short
            # budget
            snap = storage.snapshot(storage.current_ts())
            assert snap.get(b"k") == b"v"
        finally:
            failpoint.disable("rpc/request")
        assert calls["n"] == 2

    def test_commit_timeout_undetermined(self, storage):
        t = storage.begin()
        t.set(b"k", b"v")

        def inject(cmd, ctx):
            if cmd == "Commit":
                raise TimeoutError_("network timeout")

        failpoint.enable("rpc/request", inject)
        try:
            with pytest.raises(UndeterminedError):
                t.commit()
        finally:
            failpoint.disable("rpc/request")

    def test_concurrent_writers_one_wins(self, storage):
        t0 = storage.begin()
        t0.set(b"cnt", b"0")
        t0.commit()
        results = []

        def worker(i):
            try:
                t = storage.begin()
                t.set(b"cnt", b"%d" % i)
                t.commit()
                results.append(("ok", i))
            except KVError:
                results.append(("err", i))

        ts = [threading.Thread(target=worker, args=(i,)) for i in range(4)]
        for th in ts:
            th.start()
        for th in ts:
            th.join()
        oks = [r for r in results if r[0] == "ok"]
        assert len(oks) >= 1
        final = storage.begin().get(b"cnt")
        assert final in {b"%d" % i for _, i in oks}


class TestOrderedCopParallel:
    def test_keep_order_parallel_under_splits(self):
        """Ordered scans run tasks concurrently yet deliver region results
        in key order (ref: coprocessor.go:342-457 per-task channels)."""
        import threading
        import numpy as np
        from tidb_tpu.session import Session
        from tidb_tpu.store import copr as copr_mod
        from tidb_tpu.store.storage import new_mock_storage
        from tidb_tpu.table import Table, bulkload

        st = new_mock_storage()
        s = Session(st)
        s.execute("CREATE DATABASE d; USE d")
        s.execute("CREATE TABLE a (id BIGINT PRIMARY KEY, x BIGINT)")
        s.execute("CREATE TABLE b (id BIGINT PRIMARY KEY, y BIGINT)")
        n = 40000
        ta = Table(s.domain.info_schema().table("d", "a"), st)
        tb = Table(s.domain.info_schema().table("d", "b"), st)
        bulkload.bulk_load(st, ta, {"id": np.arange(n),
                                    "x": np.arange(n) * 2})
        bulkload.bulk_load(st, tb, {"id": np.arange(n),
                                    "y": np.arange(n) * 3})
        st.cluster.split_table(ta.info.id, 8, max_handle=n)
        st.cluster.split_table(tb.info.id, 8, max_handle=n)

        # count concurrently-running cop tasks during the merge join —
        # on BOTH storage surfaces: the materialized handler and the
        # streaming producer (the default path streams; its KeepOrder
        # mode runs a sliding window of parallel per-task streams
        # drained in range order — copr._send_streaming_ordered)
        st.client()   # installs the cop handlers
        active, seen_parallel = [0], [False]
        mu = threading.Lock()
        orig = st.shim._cop_handler
        orig_stream = st.shim.coprocessor_stream

        def spy(region, req):
            with mu:
                active[0] += 1
                if active[0] > 1:
                    seen_parallel[0] = True
            try:
                import time as _t
                _t.sleep(0.01)
                return orig(region, req)
            finally:
                with mu:
                    active[0] -= 1

        def spy_stream(ctx, req, **kw):
            with mu:
                active[0] += 1
                if active[0] > 1:
                    seen_parallel[0] = True
            try:
                import time as _t
                _t.sleep(0.01)
                yield from orig_stream(ctx, req, **kw)
            finally:
                with mu:
                    active[0] -= 1

        st.shim.install_cop_handler(spy)
        st.shim.coprocessor_stream = spy_stream
        # pk-pk join -> MergeJoin over keep_order readers
        q = "SELECT a.id, a.x, b.y FROM a JOIN b ON a.id = b.id"
        plan_txt = s.plan(q).explain()
        assert "MergeJoin" in plan_txt and "keep_order" in plan_txt
        rows = s.query(q).rows
        assert len(rows) == n
        assert seen_parallel[0], "ordered cop tasks ran serially"
        # the merge join streams the left side in key order, so its
        # output preserves it — a real order assertion over many regions
        ids = [r[0] for r in s.query("SELECT a.id, a.x FROM a JOIN b "
                                     "ON a.id = b.id WHERE a.id < 30000"
                                     ).rows]
        assert ids == sorted(ids) and len(ids) == 30000
