"""Tiny deterministic TPC-H data generator + loader.

Shapes follow the TPC-H spec's tables/columns (the reference exposes them
through plain SQL; BASELINE.md configs 2-4 name Q1/Q3/Q5 as the perf
targets). Row counts are scaled way down for hermetic tests; value
distributions keep the queries' selectivity non-trivial.
"""

from __future__ import annotations

import datetime

import numpy as np

REGIONS = ["AFRICA", "AMERICA", "ASIA", "EUROPE", "MIDDLE EAST"]
NATIONS = [  # (name, region_idx)
    ("ALGERIA", 0), ("ARGENTINA", 1), ("BRAZIL", 1), ("CANADA", 1),
    ("EGYPT", 4), ("ETHIOPIA", 0), ("FRANCE", 3), ("GERMANY", 3),
    ("INDIA", 2), ("INDONESIA", 2), ("IRAN", 4), ("IRAQ", 4),
    ("JAPAN", 2), ("JORDAN", 4), ("KENYA", 0), ("MOROCCO", 0),
    ("MOZAMBIQUE", 0), ("PERU", 1), ("CHINA", 2), ("ROMANIA", 3),
    ("SAUDI ARABIA", 4), ("VIETNAM", 2), ("RUSSIA", 3),
    ("UNITED KINGDOM", 3), ("UNITED STATES", 1),
]
SEGMENTS = ["AUTOMOBILE", "BUILDING", "FURNITURE", "MACHINERY", "HOUSEHOLD"]
PRIORITIES = ["1-URGENT", "2-HIGH", "3-MEDIUM", "4-NOT SPECIFIED", "5-LOW"]
FLAGS = ["A", "N", "R"]
STATUSES = ["F", "O"]

_EPOCH = datetime.date(1992, 1, 1)


def _d(days: int) -> str:
    return (_EPOCH + datetime.timedelta(days=int(days))).isoformat()


class TpchData:
    """Numpy-array tables, deterministic for a given (scale, seed)."""

    def __init__(self, customers=120, orders=600, lineitems=2400,
                 suppliers=40, seed=42):
        rng = np.random.default_rng(seed)
        self.n_nation = len(NATIONS)
        # customer
        self.c_custkey = np.arange(customers)
        self.c_nationkey = rng.integers(0, self.n_nation, customers)
        self.c_mktsegment = rng.integers(0, len(SEGMENTS), customers)
        # supplier
        self.s_suppkey = np.arange(suppliers)
        self.s_nationkey = rng.integers(0, self.n_nation, suppliers)
        # orders (orderdate in days since epoch, 1992-01-01 .. 1998-08-02)
        self.o_orderkey = np.arange(orders)
        self.o_custkey = rng.integers(0, customers, orders)
        self.o_orderdate = rng.integers(0, 2405, orders)
        self.o_shippriority = np.zeros(orders, dtype=np.int64)
        self.o_orderpriority = rng.integers(0, len(PRIORITIES), orders)
        # lineitem
        self.l_orderkey = rng.integers(0, orders, lineitems)
        self.l_suppkey = rng.integers(0, suppliers, lineitems)
        self.l_quantity = rng.integers(1, 51, lineitems)
        self.l_extendedprice = rng.integers(90000, 10500000, lineitems)  # cents
        self.l_discount = rng.integers(0, 11, lineitems)   # percent
        self.l_tax = rng.integers(0, 9, lineitems)         # percent
        self.l_returnflag = rng.integers(0, 3, lineitems)
        self.l_linestatus = rng.integers(0, 2, lineitems)
        base = self.o_orderdate[self.l_orderkey]
        self.l_shipdate = base + rng.integers(1, 122, lineitems)
        self.l_commitdate = base + rng.integers(30, 92, lineitems)
        self.l_receiptdate = self.l_shipdate + rng.integers(1, 31, lineitems)


DDL = """
CREATE TABLE region (r_regionkey BIGINT PRIMARY KEY, r_name VARCHAR(25));
CREATE TABLE nation (n_nationkey BIGINT PRIMARY KEY, n_name VARCHAR(25),
                     n_regionkey BIGINT);
CREATE TABLE customer (c_custkey BIGINT PRIMARY KEY,
                       c_nationkey BIGINT, c_mktsegment VARCHAR(10));
CREATE TABLE supplier (s_suppkey BIGINT PRIMARY KEY, s_nationkey BIGINT);
CREATE TABLE orders (o_orderkey BIGINT PRIMARY KEY, o_custkey BIGINT,
                     o_orderdate DATE, o_shippriority BIGINT,
                     o_orderpriority VARCHAR(15));
CREATE TABLE lineitem (l_id BIGINT PRIMARY KEY, l_orderkey BIGINT,
                       l_suppkey BIGINT,
                       l_quantity DECIMAL(15,2),
                       l_extendedprice DECIMAL(15,2),
                       l_discount DECIMAL(15,2), l_tax DECIMAL(15,2),
                       l_returnflag CHAR(1), l_linestatus CHAR(1),
                       l_shipdate DATE, l_commitdate DATE,
                       l_receiptdate DATE);
"""


def load(session, data: TpchData, batch=500):
    for stmt in DDL.strip().split(";"):
        if stmt.strip():
            session.execute(stmt)

    def ins(table, rows_iter):
        buf = []
        for r in rows_iter:
            buf.append("(" + ",".join(r) + ")")
            if len(buf) >= batch:
                session.execute(f"INSERT INTO {table} VALUES {','.join(buf)}")
                buf = []
        if buf:
            session.execute(f"INSERT INTO {table} VALUES {','.join(buf)}")

    ins("region", ((str(i), f"'{n}'") for i, n in enumerate(REGIONS)))
    ins("nation", ((str(i), f"'{n}'", str(r))
                   for i, (n, r) in enumerate(NATIONS)))
    ins("customer", ((str(k), str(data.c_nationkey[k]),
                      f"'{SEGMENTS[data.c_mktsegment[k]]}'")
                     for k in data.c_custkey))
    ins("supplier", ((str(k), str(data.s_nationkey[k]))
                     for k in data.s_suppkey))
    ins("orders", ((str(k), str(data.o_custkey[k]),
                    f"'{_d(data.o_orderdate[k])}'",
                    str(data.o_shippriority[k]),
                    f"'{PRIORITIES[data.o_orderpriority[k]]}'")
                   for k in data.o_orderkey))
    n = len(data.l_orderkey)
    ins("lineitem", ((str(i), str(data.l_orderkey[i]),
                      str(data.l_suppkey[i]),
                      f"{data.l_quantity[i]}.00",
                      f"{data.l_extendedprice[i] // 100}."
                      f"{data.l_extendedprice[i] % 100:02d}",
                      f"0.{data.l_discount[i]:02d}",
                      f"0.{data.l_tax[i]:02d}",
                      f"'{FLAGS[data.l_returnflag[i]]}'",
                      f"'{STATUSES[data.l_linestatus[i]]}'",
                      f"'{_d(data.l_shipdate[i])}'",
                      f"'{_d(data.l_commitdate[i])}'",
                      f"'{_d(data.l_receiptdate[i])}'")
                     for i in range(n)))


Q1 = """
SELECT l_returnflag, l_linestatus,
       SUM(l_quantity) AS sum_qty,
       SUM(l_extendedprice) AS sum_base_price,
       SUM(l_extendedprice * (1 - l_discount)) AS sum_disc_price,
       SUM(l_extendedprice * (1 - l_discount) * (1 + l_tax)) AS sum_charge,
       AVG(l_quantity) AS avg_qty,
       AVG(l_extendedprice) AS avg_price,
       AVG(l_discount) AS avg_disc,
       COUNT(*) AS count_order
FROM lineitem
WHERE l_shipdate <= DATE '1998-12-01' - INTERVAL '90' DAY
GROUP BY l_returnflag, l_linestatus
ORDER BY l_returnflag, l_linestatus
"""

Q3 = """
SELECT l_orderkey,
       SUM(l_extendedprice * (1 - l_discount)) AS revenue,
       o_orderdate, o_shippriority
FROM customer, orders, lineitem
WHERE c_mktsegment = 'BUILDING'
  AND c_custkey = o_custkey
  AND l_orderkey = o_orderkey
  AND o_orderdate < DATE '1995-03-15'
  AND l_shipdate > DATE '1995-03-15'
GROUP BY l_orderkey, o_orderdate, o_shippriority
ORDER BY revenue DESC, o_orderdate
LIMIT 10
"""

Q5 = """
SELECT n_name,
       SUM(l_extendedprice * (1 - l_discount)) AS revenue
FROM customer, orders, lineitem, supplier, nation, region
WHERE c_custkey = o_custkey
  AND l_orderkey = o_orderkey
  AND l_suppkey = s_suppkey
  AND c_nationkey = s_nationkey
  AND s_nationkey = n_nationkey
  AND n_regionkey = r_regionkey
  AND r_name = 'ASIA'
  AND o_orderdate >= DATE '1994-01-01'
  AND o_orderdate < DATE '1994-01-01' + INTERVAL '1' YEAR
GROUP BY n_name
ORDER BY revenue DESC
"""


# -- independent ground truth (pure python/numpy over the arrays) -----------

def truth_q1(d: TpchData):
    cutoff = (datetime.date(1998, 12, 1) - datetime.timedelta(days=90)
              - _EPOCH).days
    out = {}
    for i in range(len(d.l_orderkey)):
        if d.l_shipdate[i] > cutoff:
            continue
        key = (FLAGS[d.l_returnflag[i]], STATUSES[d.l_linestatus[i]])
        e = out.setdefault(key, [0, 0, 0.0, 0.0, 0, 0])
        px = d.l_extendedprice[i] / 100
        disc = d.l_discount[i] / 100
        tax = d.l_tax[i] / 100
        e[0] += int(d.l_quantity[i])
        e[1] += d.l_extendedprice[i]
        e[2] += px * (1 - disc)
        e[3] += px * (1 - disc) * (1 + tax)
        e[4] += d.l_discount[i]
        e[5] += 1
    rows = []
    for key in sorted(out):
        q, b, dp, ch, disc, n = out[key]
        rows.append((key[0], key[1], float(q), b / 100, dp, ch,
                     q / n, b / 100 / n, disc / 100 / n, n))
    return rows


def truth_q3(d: TpchData):
    cut = (datetime.date(1995, 3, 15) - _EPOCH).days
    seg = SEGMENTS.index("BUILDING")
    bldg = set(np.flatnonzero(d.c_mktsegment == seg))
    orders_ok = {}
    for k in d.o_orderkey:
        if d.o_custkey[k] in bldg and d.o_orderdate[k] < cut:
            orders_ok[k] = d.o_orderdate[k]
    rev = {}
    for i in range(len(d.l_orderkey)):
        ok = d.l_orderkey[i]
        if ok in orders_ok and d.l_shipdate[i] > cut:
            px = d.l_extendedprice[i] / 100 * (1 - d.l_discount[i] / 100)
            rev[ok] = rev.get(ok, 0.0) + px
    rows = sorted(((k, v, orders_ok[k]) for k, v in rev.items()),
                  key=lambda t: (-t[1], t[2]))[:10]
    return [(int(k), v, _d(od), 0) for k, v, od in rows]


def truth_q5(d: TpchData):
    lo = (datetime.date(1994, 1, 1) - _EPOCH).days
    hi = (datetime.date(1995, 1, 1) - _EPOCH).days
    asia = {i for i, (_n, r) in enumerate(NATIONS)
            if REGIONS[r] == "ASIA"}
    rev = {}
    for i in range(len(d.l_orderkey)):
        ok = d.l_orderkey[i]
        if not (lo <= d.o_orderdate[ok] < hi):
            continue
        sk = d.l_suppkey[i]
        snat = d.s_nationkey[sk]
        if snat not in asia:
            continue
        ck = d.o_custkey[ok]
        if d.c_nationkey[ck] != snat:
            continue
        px = d.l_extendedprice[i] / 100 * (1 - d.l_discount[i] / 100)
        nname = NATIONS[snat][0]
        rev[nname] = rev.get(nname, 0.0) + px
    return sorted(rev.items(), key=lambda t: -t[1])


Q4 = """
SELECT o_orderpriority, COUNT(*) AS order_count
FROM orders
WHERE o_orderdate >= DATE '1993-07-01'
  AND o_orderdate < DATE '1993-10-01'
  AND EXISTS (
    SELECT 1 FROM lineitem
    WHERE l_orderkey = o_orderkey AND l_commitdate < l_receiptdate)
GROUP BY o_orderpriority
ORDER BY o_orderpriority
"""


Q6 = """
SELECT SUM(l_extendedprice * l_discount) AS revenue
FROM lineitem
WHERE l_shipdate >= DATE '1994-01-01'
  AND l_shipdate < DATE '1995-01-01'
  AND l_discount BETWEEN 0.05 AND 0.07
  AND l_quantity < 24
"""


def truth_q4(d: TpchData):
    lo = (datetime.date(1993, 7, 1) - _EPOCH).days
    hi = (datetime.date(1993, 10, 1) - _EPOCH).days
    late = set()
    for i in range(len(d.l_orderkey)):
        if d.l_commitdate[i] < d.l_receiptdate[i]:
            late.add(int(d.l_orderkey[i]))
    out = {}
    for k in d.o_orderkey:
        if lo <= d.o_orderdate[k] < hi and int(k) in late:
            p = PRIORITIES[d.o_orderpriority[k]]
            out[p] = out.get(p, 0) + 1
    return sorted(out.items())


def truth_q6(d: TpchData):
    lo = (datetime.date(1994, 1, 1) - _EPOCH).days
    hi = (datetime.date(1995, 1, 1) - _EPOCH).days
    rev = 0.0
    for i in range(len(d.l_orderkey)):
        if not (lo <= d.l_shipdate[i] < hi):
            continue
        if not (5 <= d.l_discount[i] <= 7):
            continue
        if d.l_quantity[i] >= 24:
            continue
        rev += (d.l_extendedprice[i] / 100) * (d.l_discount[i] / 100)
    return rev


Q12 = """
SELECT l_linestatus, COUNT(*) AS n
FROM orders, lineitem
WHERE o_orderkey = l_orderkey
  AND l_receiptdate >= DATE '1994-01-01'
  AND l_receiptdate < DATE '1994-01-01' + INTERVAL '1' YEAR
  AND l_commitdate < l_receiptdate
  AND l_shipdate < l_commitdate
GROUP BY l_linestatus
ORDER BY l_linestatus
"""


def truth_q12(d: TpchData):
    lo = (datetime.date(1994, 1, 1) - _EPOCH).days
    hi = (datetime.date(1995, 1, 1) - _EPOCH).days
    out = {}
    for i in range(len(d.l_orderkey)):
        if not (lo <= d.l_receiptdate[i] < hi):
            continue
        if not (d.l_commitdate[i] < d.l_receiptdate[i]):
            continue
        if not (d.l_shipdate[i] < d.l_commitdate[i]):
            continue
        key = STATUSES[d.l_linestatus[i]]
        out[key] = out.get(key, 0) + 1
    return sorted((k, v) for k, v in out.items())
