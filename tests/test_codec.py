"""Codec order-preservation and roundtrip properties.
Ref model: util/codec/*_test.go property tables."""

import random

import pytest

from tidb_tpu import codec, tablecodec


def test_int_roundtrip_and_order():
    vals = [-(1 << 63), -12345, -1, 0, 1, 42, (1 << 63) - 1]
    encs = [codec.encode_int(v) for v in vals]
    assert encs == sorted(encs)
    for v, e in zip(vals, encs):
        assert codec.decode_int(e)[0] == v


def test_float_order():
    vals = [float("-inf"), -1e300, -2.5, -0.0, 0.0, 1e-300, 3.14, 1e300,
            float("inf")]
    encs = [codec.encode_float(v) for v in vals]
    assert encs == sorted(encs)
    for v, e in zip(vals, encs):
        assert codec.decode_float(e)[0] == v


def test_bytes_roundtrip_order():
    rng = random.Random(42)
    vals = [b"", b"a", b"ab", b"abcdefgh", b"abcdefghi", b"abcdefgh" * 3,
            bytes(rng.randrange(256) for _ in range(17))]
    for v in vals:
        enc = codec.encode_bytes(v)
        dec, off = codec.decode_bytes(enc)
        assert dec == v and off == len(enc)
    svals = sorted(vals)
    sencs = sorted(codec.encode_bytes(v) for v in vals)
    assert [codec.decode_bytes(e)[0] for e in sencs] == svals


def test_bytes_prefix_order():
    # "abc" < "abcd" must hold through encoding (the stuffing subtlety)
    assert codec.encode_bytes(b"abc") < codec.encode_bytes(b"abcd")
    assert codec.encode_bytes(b"abcdefgh") < codec.encode_bytes(b"abcdefgh\x00")


def test_mixed_key_order():
    rows = [(1, "apple"), (1, "banana"), (2, "a"), (10, ""), (10, "z")]
    encs = [codec.encode_key(list(r)) for r in rows]
    assert encs == sorted(encs)
    for r, e in zip(rows, encs):
        dec = codec.decode_key(e)
        assert dec[0] == r[0] and dec[1].decode() == r[1]


def test_null_sorts_first_max_sorts_last():
    e_null = codec.encode_datum(None)
    e_int = codec.encode_datum(-(1 << 63))
    assert e_null < e_int
    assert codec.key_max() > codec.encode_datum((1 << 63) - 1)


def test_desc_encoding_reverses_order():
    vals = [1, 5, 100]
    encs = [codec.encode_datum(v, desc=True) for v in vals]
    assert encs == sorted(encs, reverse=True)
    for v, e in zip(vals, encs):
        assert codec.decode_one(e, 0, desc=True)[0] == v


def test_desc_bytes():
    vals = [b"a", b"ab", b"b"]
    encs = [codec.encode_datum(v, desc=True) for v in vals]
    assert encs == sorted(encs, reverse=True)
    for v, e in zip(vals, encs):
        assert codec.decode_one(e, 0, desc=True)[0] == v


def test_decimal_datum():
    enc = codec.encode_datum((2, 1234))
    assert codec.decode_one(enc)[0] == (2, 1234)
    # order within same frac
    assert codec.encode_datum((2, -500)) < codec.encode_datum((2, 1234))


def test_record_key_roundtrip_order():
    k1 = tablecodec.record_key(1, 5)
    k2 = tablecodec.record_key(1, 100)
    k3 = tablecodec.record_key(2, 0)
    assert k1 < k2 < k3
    assert tablecodec.decode_record_key(k2) == (1, 100)
    lo, hi = tablecodec.table_prefix_range(1)
    assert lo < k1 < k2 < hi < k3


def test_index_key_roundtrip():
    k = tablecodec.index_key(7, 2, [42, "xy"], handle=9)
    tid, iid, rest = tablecodec.decode_index_key(k)
    assert (tid, iid) == (7, 2)
    vals = codec.decode_key(rest)
    assert vals[0] == 42 and vals[1] == b"xy" and vals[2] == 9


def test_row_value_roundtrip():
    row = tablecodec.encode_row([1, 2, 3, 4], [10, "hello", 2.5, None])
    d = tablecodec.decode_row(row)
    assert d[1] == 10 and d[2] == b"hello" and d[3] == 2.5 and d[4] is None


def test_key_next():
    k = codec.encode_key([5])
    assert codec.encode_key([5]) < codec.key_next(k) < codec.encode_key([6])


def test_fuzz_composite_key_order():
    """2000 random (int, bytes, float) keys: encoded order == value order."""
    rng = random.Random(7)

    def rand_key():
        i = rng.randrange(-100, 100)
        s = bytes(rng.randrange(97, 123) for _ in range(rng.randrange(0, 12)))
        f = rng.uniform(-1000, 1000)
        return (i, s, f)

    keys = [rand_key() for _ in range(2000)]
    encs = [codec.encode_key(list(k)) for k in keys]
    assert [k for _, k in sorted(zip(encs, keys))] == sorted(keys)
    for k, e in zip(keys, encs):
        assert tuple(codec.decode_key(e)) == k


def test_null_desc_sorts_last():
    e_null = codec.encode_datum(None, desc=True)
    e_big = codec.encode_datum(1 << 62, desc=True)
    e_small = codec.encode_datum(-5, desc=True)
    assert e_big < e_small < e_null  # desc: big first, NULL last
    assert codec.decode_one(e_null, 0, desc=True)[0] is None
    assert e_null < codec.key_max()


def test_uint_upper_half():
    big = (1 << 63) + 7
    e = codec.encode_datum(big)
    assert codec.decode_one(e)[0] == big
    assert codec.encode_datum((1 << 63) - 1) < e  # int64 max < uint upper half


def test_prefix_next_all_ff_raises():
    with pytest.raises(ValueError):
        codec.prefix_next(b"\xff\xff")
    assert codec.prefix_next(b"ab\xff") == b"ac"
