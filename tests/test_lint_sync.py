"""Static hot-path invariant, enforced as a test (style of
test_lint_metrics.py): `block_until_ready` must not appear anywhere in
the tidb_tpu package except runtime_stats.py (the gated profiling
path). The dispatch-ahead pipeline's whole win is that superchunk k+1
transfers while k executes; ONE accidental block_until_ready on the hot
path serializes every dispatch and silently erases the overlap. Syncs
at operator output boundaries use jax.device_get, which is visible in
review precisely because it returns the data. bench.py and tests sit
outside the package and may sync freely (profiling / assertions).

Checked by AST walk, so any receiver spelling (jax.block_until_ready,
arr.block_until_ready, aliased imports) is caught."""

import ast
import os

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PKG = os.path.join(REPO, "tidb_tpu")

# the one sanctioned site: device-time profiling, gated behind the
# tidb_tpu_runtime_stats_device sysvar
ALLOWED = {os.path.join("tidb_tpu", "runtime_stats.py")}


def _package_files():
    for root, _dirs, files in os.walk(PKG):
        if "__pycache__" in root:
            continue
        for f in files:
            if f.endswith(".py"):
                yield os.path.join(root, f)


def _sync_sites(path):
    with open(path) as f:
        tree = ast.parse(f.read(), filename=path)
    for node in ast.walk(tree):
        if isinstance(node, ast.Attribute) and \
                node.attr == "block_until_ready":
            yield node.lineno
        elif isinstance(node, ast.Name) and \
                node.id == "block_until_ready":
            yield node.lineno
        elif isinstance(node, ast.Constant) and \
                node.value == "block_until_ready":
            # getattr(jax, "block_until_ready") and friends
            yield node.lineno


def test_no_sync_points_outside_runtime_stats():
    offenders = []
    for path in _package_files():
        rel = os.path.relpath(path, REPO)
        if rel in ALLOWED:
            continue
        for lineno in _sync_sites(path):
            offenders.append(f"{rel}:{lineno}: block_until_ready on the "
                             f"hot path (use jax.device_get at an output "
                             f"boundary, or runtime_stats.device_call for "
                             f"gated profiling)")
    assert not offenders, "\n".join(offenders)


def test_sanctioned_site_still_exists():
    """The lint is vacuous if the profiling path moved: pin that
    runtime_stats.py still owns the one block_until_ready."""
    sites = list(_sync_sites(os.path.join(PKG, "runtime_stats.py")))
    assert sites, "runtime_stats.py lost its gated block_until_ready"
