"""Expression evaluation tests — numpy host path and jax device path agree.

Ref model: expression/builtin_*_test.go (row-based); here columnar.
"""

import decimal

import numpy as np
import pytest

from tidb_tpu import sqltypes as st
from tidb_tpu.chunk import Chunk
from tidb_tpu.expression import Op, col, const, func


INT = st.new_int_field()
DBL = st.new_double_field()
DEC2 = st.new_decimal_field(frac=2)
STR = st.new_string_field()
DT = st.new_datetime_field()


def mkchunk():
    return Chunk.from_rows(
        [INT, DBL, DEC2, STR],
        [
            (1, 1.5, decimal.Decimal("10.00"), "apple"),
            (2, -2.0, decimal.Decimal("0.05"), "Banana"),
            (None, 3.25, None, "cherry"),
            (4, None, decimal.Decimal("-1.25"), None),
        ],
    )


def ev(expr, ch=None):
    ch = ch or mkchunk()
    d, v = expr.eval(ch)
    return [None if not v[i] else (d[i].item() if hasattr(d[i], "item") else d[i])
            for i in range(len(d))]


def test_arith_int():
    e = col(0, INT) + const(10)
    assert ev(e) == [11, 12, None, 14]


def test_arith_mixed_real():
    e = col(0, INT) * col(1, DBL)
    assert ev(e) == [1.5, -4.0, None, None]


def test_decimal_add_rescale():
    e = col(2, DEC2) + const(decimal.Decimal("0.5"))
    out = ev(e)
    assert out == [1050, 55, None, -75]  # scaled int frac=2


def test_decimal_mul_scale():
    e = col(2, DEC2) * col(2, DEC2)
    assert e.ft.frac == 4
    out = ev(e)
    assert out[0] == 100_0000  # 10.00^2 = 100.0000 @ frac4


def test_division_null_on_zero():
    ch = Chunk.from_rows([INT, INT], [(10, 2), (7, 0)])
    e = col(0, INT) / col(1, INT)
    assert ev(e, ch) == [5.0, None]


def test_compare_and_logic():
    e = func(Op.AND, col(0, INT).gt(1), col(1, DBL).lt(0))
    # rows: (1,1.5)->F, (2,-2)->T, (None,3.25)->null&F=F? gt(1) null, lt(0) false -> AND=false
    assert ev(e) == [0, 1, 0, None]


def test_or_kleene():
    e = func(Op.OR, col(0, INT).gt(100), func(Op.IS_NULL, col(1, DBL)))
    assert ev(e) == [0, 0, None, 1]


def test_in_list():
    e = func(Op.IN, col(0, INT), extra=[1, 4])
    assert ev(e) == [1, 0, None, 1]


def test_string_like():
    e = func(Op.LIKE, col(3, STR), extra="%an%")
    assert ev(e) == [0, 1, 0, None]


def test_string_fns():
    e = func(Op.UPPER, col(3, STR))
    assert ev(e)[:2] == ["APPLE", "BANANA"]
    e2 = func(Op.LENGTH, col(3, STR))
    assert ev(e2) == [5, 6, 6, None]


def test_case_when():
    e = func(Op.CASE, col(0, INT).gt(1), const(100), col(0, INT).eq(1),
             const(50), const(0))
    assert ev(e) == [50, 100, 0, 100]


def test_if_ifnull():
    e = func(Op.IFNULL, col(0, INT), const(-1))
    assert ev(e) == [1, 2, -1, 4]


def test_year_month_extract():
    ch = Chunk.from_rows([DT], [(st.parse_datetime("1994-03-15"),),
                                (st.parse_datetime("2000-12-31 23:59:59"),)])
    assert ev(func(Op.YEAR, col(0, DT)), ch) == [1994, 2000]
    assert ev(func(Op.MONTH, col(0, DT)), ch) == [3, 12]
    assert ev(func(Op.DAY, col(0, DT)), ch) == [15, 31]


def test_date_cmp():
    ch = Chunk.from_rows([DT], [(st.parse_datetime("1994-03-15"),),
                                (st.parse_datetime("1998-09-02"),)])
    e = col(0, DT).le(const(st.parse_datetime("1995-01-01"), DT))
    assert ev(e, ch) == [1, 0]


def test_jax_matches_numpy():
    import jax
    import jax.numpy as jnp

    ch = mkchunk()
    e = (col(0, INT) + const(3)) * col(2, DEC2)
    assert e.is_device_safe()

    d_np, v_np = e.eval(ch)

    def jfn(c0d, c0v, c2d, c2v):
        cols = [(c0d, c0v), None, (c2d, c2v), None]
        return e.eval_xp(jnp, cols, 4)

    d_j, v_j = jax.jit(jfn)(
        jnp.asarray(ch.col(0).data), jnp.asarray(ch.col(0).valid),
        jnp.asarray(ch.col(2).data), jnp.asarray(ch.col(2).valid))
    np.testing.assert_array_equal(np.asarray(v_j), v_np)
    np.testing.assert_array_equal(np.asarray(d_j)[v_np], d_np[v_np])


def test_round_decimal():
    ch = Chunk.from_rows([DEC2], [(decimal.Decimal("2.35"),),
                                  (decimal.Decimal("-2.35"),)])
    e = func(Op.ROUND, col(0, DEC2), const(1))
    assert ev(e, ch) == [240, -240]  # 2.4 / -2.4 at frac 2


class TestRowExpressions:
    """(a,b) <cmp> (c,d) and (a,b) IN ((..),(..)) desugar to scalar
    logic (ref: expression/expression.go row expressions); NULL rows
    follow Kleene semantics — a decided first component decides."""

    @pytest.fixture(scope="class")
    def rs(self):
        from tidb_tpu.session import Session
        from tidb_tpu.store.storage import new_mock_storage
        s = Session(new_mock_storage())
        s.execute("CREATE DATABASE d")
        s.execute("USE d")
        s.execute("CREATE TABLE r (a BIGINT PRIMARY KEY, b BIGINT, "
                  "c VARCHAR(8))")
        s.execute("INSERT INTO r VALUES (1,10,'x'),(2,20,'y'),"
                  "(3,30,'z'),(4,NULL,'w')")
        yield s
        s.close()

    def test_eq_ne(self, rs):
        assert rs.query("SELECT a FROM r WHERE (a, b) = (2, 20)"
                        ).rows == [(2,)]
        # (4,NULL) <> (2,20): first component decides -> TRUE
        assert rs.query("SELECT a FROM r WHERE (a, b) <> (2, 20) "
                        "ORDER BY a").rows == [(1,), (3,), (4,)]

    def test_in_not_in(self, rs):
        assert rs.query("SELECT a FROM r WHERE (a, b) IN ((1,10),(3,30))"
                        " ORDER BY a").rows == [(1,), (3,)]
        assert rs.query("SELECT a FROM r WHERE (a, b) NOT IN "
                        "((1,10),(3,30)) ORDER BY a").rows == \
            [(2,), (4,)]

    def test_lexicographic_ordering(self, rs):
        assert rs.query("SELECT a FROM r WHERE (a, b) < (2, 25) "
                        "ORDER BY a").rows == [(1,), (2,)]
        assert rs.query("SELECT a FROM r WHERE (a, b) <= (2, 19)"
                        ).rows == [(1,)]
        assert rs.query("SELECT a FROM r WHERE (a, b) >= (2, 20) "
                        "ORDER BY a").rows == [(2,), (3,), (4,)]

    def test_null_component_undecided(self, rs):
        assert rs.query("SELECT a FROM r WHERE (a, b) = (4, NULL)"
                        ).rows == []

    def test_arity_and_position_errors(self, rs):
        from tidb_tpu.session import SQLError
        with pytest.raises(SQLError, match="2 column"):
            rs.query("SELECT a FROM r WHERE (a,b) = (1,2,3)")
        with pytest.raises(SQLError, match="2 column"):
            rs.query("SELECT a FROM r WHERE (a,b) IN ((1,2,3))")
        with pytest.raises(SQLError):
            rs.query("SELECT (a,b) FROM r")

    def test_interval_amount_folds(self, rs):
        assert rs.query("SELECT DATE_ADD('2024-01-01', "
                        "INTERVAL 1+1 DAY)").rows == \
            [("2024-01-03 00:00:00",)]
        assert rs.query("SELECT DATE_ADD('2024-01-01', "
                        "INTERVAL NULL DAY) IS NULL").rows == [(1,)]

    def test_decimal_interval_amount_rounds(self, rs):
        # folded decimal amounts descale (not the scaled int!) and
        # fractional amounts round half-up like MySQL
        assert rs.query(
            "SELECT DATE_ADD('2024-01-01', INTERVAL 1.5+0 DAY), "
            "DATE_ADD('2024-01-01', INTERVAL 1.5 DAY), "
            "DATE_ADD('2024-01-01', INTERVAL 0.4 DAY)").rows == \
            [("2024-01-03 00:00:00", "2024-01-03 00:00:00",
              "2024-01-01 00:00:00")]
