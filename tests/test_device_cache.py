"""HBM-resident region-block cache (store/device_cache.py) and the
fused scan->filter->partial-agg dispatch it feeds (store/copr.py).

Pins the acceptance contract of the cache: invalidation on write/DDL
version bumps (no stale reads, ever), LRU eviction under a small
`tidb_tpu_device_cache_bytes`, memtrack `hbm-cache` ledger exactness
through fill/evict/shed (no leak), the registered SERVER OOM shed
action, and bit-identical results between the fused device path, the
unfused device path, and the host executors across dtypes, varlen dict
columns and masked (non-power-of-two) tails."""

import numpy as np
import pytest

from tidb_tpu import config, memtrack, metrics
from tidb_tpu.session import Session
from tidb_tpu.store import device_cache as dc
from tidb_tpu.store.storage import new_mock_storage

N_ROWS = 3000          # deliberately not a power of two: masked tails


def q(s, sql):
    return s.query(sql).rows


def hbm():
    snap = metrics.snapshot()
    return {"hits": int(snap.get(metrics.HBM_CACHE_HITS, 0)),
            "misses": int(snap.get(metrics.HBM_CACHE_MISSES, 0)),
            "evictions": int(snap.get(metrics.HBM_CACHE_EVICTIONS, 0))}


_VARS = ("tidb_tpu_device", "tidb_tpu_device_min_rows",
         "tidb_tpu_device_cache_bytes", "tidb_tpu_fused_scan",
         "tidb_tpu_copr_stream", "tidb_tpu_chunk_cache")


@pytest.fixture
def sysvars():
    old = {k: config.get_var(k) for k in _VARS}
    config.set_var("tidb_tpu_device_min_rows", 1)
    yield
    for k, v in old.items():
        config.set_var(k, v)


@pytest.fixture
def sess(sysvars):
    st = new_mock_storage()
    s = Session(st)
    s.execute("CREATE DATABASE d")
    s.execute("USE d")
    s.execute("CREATE TABLE t (id BIGINT PRIMARY KEY, v BIGINT, "
              "d DOUBLE, m DECIMAL(12,2), s VARCHAR(16))")
    rows = []
    for i in range(N_ROWS):
        # NULL lanes every 11th row; negative values; repeated dict keys
        v = "NULL" if i % 11 == 7 else str((i * 37) % 500 - 250)
        d = "NULL" if i % 13 == 5 else repr((i % 97) * 0.25 - 12.0)
        m = f"{(i % 701) - 350}.{i % 100:02d}"
        rows.append(f"({i},{v},{d},{m},'k{i % 23}')")
    s.execute("INSERT INTO t VALUES " + ",".join(rows))
    info = s.domain.info_schema().table("d", "t")
    st.cluster.split_table(info.id, 4, max_handle=N_ROWS)
    yield s, st
    s.close()


AGG_SQLS = (
    "SELECT COUNT(*), SUM(v), MIN(v), MAX(v) FROM t",
    "SELECT SUM(d), AVG(d), COUNT(d) FROM t WHERE v > -100",
    "SELECT s, COUNT(*), SUM(v), AVG(m) FROM t GROUP BY s ORDER BY s",
    "SELECT s, MIN(d), MAX(m) FROM t WHERE v % 3 != 1 "
    "GROUP BY s ORDER BY s",
)


def _approx_eq(a, b):
    assert len(a) == len(b)
    for ra, rb in zip(a, b):
        assert len(ra) == len(rb)
        for x, y in zip(ra, rb):
            if isinstance(x, float) or isinstance(y, float):
                assert abs(float(x) - float(y)) <= \
                    max(1e-6, abs(float(y)) * 1e-9), (ra, rb)
            else:
                assert x == y, (ra, rb)


class TestFusedParity:
    def test_fused_unfused_host_agree(self, sess):
        """The acceptance criterion: fused(scan->filter->partial-agg
        over the cached device block) == unfused device == host, across
        int/double/decimal lanes, varlen dict group keys, NULLs and
        masked non-pow2 tails — cold AND warm."""
        s, _st = sess
        for sql in AGG_SQLS:
            config.set_var("tidb_tpu_device", 0)
            host = q(s, sql)
            config.set_var("tidb_tpu_device", 1)
            config.set_var("tidb_tpu_fused_scan", 0)
            unfused = [q(s, sql), q(s, sql)]        # cold + warm
            config.set_var("tidb_tpu_fused_scan", 1)
            fused = [q(s, sql), q(s, sql)]          # fill + hit
            for got in unfused + fused:
                _approx_eq(got, host)

    def test_warm_fused_runs_hit_the_cache(self, sess):
        s, st = sess
        config.set_var("tidb_tpu_fused_scan", 1)
        sql = AGG_SQLS[0]
        q(s, sql)                       # cold: host-cache fill
        q(s, sql)                       # device-cache fill
        before = hbm()
        q(s, sql)                       # warm: pure hits
        delta = {k: hbm()[k] - before[k] for k in before}
        assert delta["hits"] >= 4       # one per region
        assert delta["misses"] == 0
        assert st.device_cache.resident_bytes() > 0

    def test_fused_scan_off_never_touches_device_cache(self, sess):
        s, st = sess
        config.set_var("tidb_tpu_fused_scan", 0)
        for _ in range(3):
            q(s, AGG_SQLS[0])
        assert len(st.device_cache) == 0


class TestInvalidation:
    def test_write_invalidates(self, sess):
        """A committed write bumps the engine version: the next fused
        read must see it (stale entries drop, counted as evictions)."""
        s, _st = sess
        sql = "SELECT COUNT(*), SUM(v) FROM t"
        for _ in range(2):
            q(s, sql)
        warm = q(s, sql)
        s.execute("INSERT INTO t VALUES (900001, 1000000, 1.5, "
                  "'7.25', 'fresh')")
        got = q(s, sql)     # version bumped: no stale read
        assert got[0][0] == warm[0][0] + 1
        assert got[0][1] == warm[0][1] + 1000000
        # and the refreshed entries serve the NEW truth warm
        assert q(s, sql) == got

    def test_delete_invalidates(self, sess):
        s, _st = sess
        sql = "SELECT COUNT(*), MAX(v) FROM t"
        for _ in range(2):
            q(s, sql)
        s.execute("DELETE FROM t WHERE v > 200")
        got = q(s, sql)
        config.set_var("tidb_tpu_device", 0)
        _approx_eq(got, q(s, sql))

    def test_ddl_invalidates(self, sess):
        """DDL changes the schema fingerprint (and bumps the engine
        version through its meta writes): post-DDL reads are fresh."""
        s, _st = sess
        sql = "SELECT s, COUNT(*) FROM t GROUP BY s ORDER BY s"
        for _ in range(2):
            q(s, sql)
        s.execute("ALTER TABLE t ADD COLUMN extra BIGINT")
        s.execute("UPDATE t SET extra = 5 WHERE id < 10")
        got = q(s, "SELECT s, COUNT(*), SUM(extra) FROM t "
                   "GROUP BY s ORDER BY s")
        config.set_var("tidb_tpu_device", 0)
        _approx_eq(got, q(s, "SELECT s, COUNT(*), SUM(extra) FROM t "
                             "GROUP BY s ORDER BY s"))


class TestBudgetAndLedger:
    def test_eviction_under_small_budget(self, sess):
        """A budget sized below the working set forces LRU evictions;
        resident bytes stay within it and the ledger stays exact."""
        s, st = sess
        q(s, AGG_SQLS[0])
        q(s, AGG_SQLS[0])           # fill once at the default budget
        per_block = st.device_cache.resident_bytes() // max(
            1, len(st.device_cache))
        st.device_cache.shed()
        base = dc.tracker().snapshot()["device"]
        # room for ~2 of the 4 region blocks
        config.set_var("tidb_tpu_device_cache_bytes", int(per_block * 2.5))
        before = hbm()
        q(s, AGG_SQLS[0])           # host-cache hot: straight to fills
        delta = {k: hbm()[k] - before[k] for k in before}
        assert delta["evictions"] >= 1
        assert 0 < st.device_cache.resident_bytes() <= per_block * 2.5
        assert dc.tracker().snapshot()["device"] - base == \
            st.device_cache.resident_bytes()

    def test_ledger_exact_through_fill_evict_shed(self, sess):
        """No leak: the hbm-cache node's device ledger == resident
        bytes at every stage, and returns to baseline after shed —
        the device twin of test_mesh_path_is_tracked's exactness
        contract."""
        s, st = sess
        base = dc.tracker().snapshot()["device"]
        for sql in AGG_SQLS[:2]:
            q(s, sql)
            q(s, sql)
        assert dc.tracker().snapshot()["device"] - base == \
            st.device_cache.resident_bytes() > 0
        s.execute("INSERT INTO t VALUES (900002, 1, 1.0, '1.00', 'x')")
        q(s, AGG_SQLS[0])
        q(s, AGG_SQLS[0])           # stale evict + refill
        assert dc.tracker().snapshot()["device"] - base == \
            st.device_cache.resident_bytes()
        st.device_cache.shed()
        assert dc.tracker().snapshot()["device"] == base
        assert st.device_cache.resident_bytes() == 0
        assert len(st.device_cache) == 0

    def test_oom_action_registered_on_server_and_sheds(self, sess):
        """The cache's shed is a memtrack OOM action on the SERVER
        root: firing the registered action chain empties every live
        cache and returns the ledger to baseline."""
        s, st = sess
        q(s, AGG_SQLS[0])
        q(s, AGG_SQLS[0])
        assert st.device_cache.resident_bytes() > 0
        assert dc._shed_all in memtrack.SERVER._actions
        assert st.device_cache.resident_bytes() > 0
        for act in list(memtrack.SERVER._actions):
            act()
        # the action empties EVERY live cache (it is a server-wide
        # pressure valve), so the shared ledger returns to zero exactly
        assert st.device_cache.resident_bytes() == 0
        assert dc.tracker().snapshot()["device"] == 0

    def test_budget_shrink_takes_effect_on_lookup(self, sess):
        """SET tidb_tpu_device_cache_bytes below current residency must
        shrink the cache on the NEXT lookup, not only at the next fill —
        warm workloads whose every access is a hit would otherwise pin
        the old budget forever (found by an end-to-end drive: resident
        bytes stayed 6x over a shrunken budget across whole queries)."""
        s, st = sess
        q(s, AGG_SQLS[0])
        q(s, AGG_SQLS[0])           # resident at the default budget
        resident = st.device_cache.resident_bytes()
        assert resident > 0
        new_budget = resident // 2
        config.set_var("tidb_tpu_device_cache_bytes", new_budget)
        base = dc.tracker().snapshot()["device"] - resident
        before = hbm()
        q(s, AGG_SQLS[0])           # hits enforce the shrunken budget
        assert st.device_cache.resident_bytes() <= new_budget
        assert hbm()["evictions"] > before["evictions"]
        # ledger follows the evictions exactly
        assert dc.tracker().snapshot()["device"] - base == \
            st.device_cache.resident_bytes()

    def test_budget_zero_sheds_on_next_consult(self, sess):
        """SET tidb_tpu_device_cache_bytes = 0 must RECLAIM, not just
        stop lookups: the 0 gate short-circuits before get(), so the
        shrink-on-lookup path above can never run — enabled() itself
        sheds instead. Without this, the documented '0 disables' leaves
        the full residency pinned in HBM until storage close."""
        s, st = sess
        q(s, AGG_SQLS[0])
        q(s, AGG_SQLS[0])
        resident = st.device_cache.resident_bytes()
        assert resident > 0
        base = dc.tracker().snapshot()["device"] - resident
        config.set_var("tidb_tpu_device_cache_bytes", 0)
        q(s, AGG_SQLS[0])           # the consult observes budget 0
        assert st.device_cache.resident_bytes() == 0
        assert dc.tracker().snapshot()["device"] == base   # ledger settles

    def test_storage_close_sheds(self, sysvars):
        st = new_mock_storage()
        s = Session(st)
        s.execute("CREATE DATABASE d; USE d")
        s.execute("CREATE TABLE t (id BIGINT PRIMARY KEY, v BIGINT)")
        s.execute("INSERT INTO t VALUES " + ",".join(
            f"({i},{i})" for i in range(2500)))
        q(s, "SELECT SUM(v) FROM t")
        q(s, "SELECT SUM(v) FROM t")
        assert st.device_cache.resident_bytes() > 0
        s.close()
        st.close()
        assert st.device_cache.resident_bytes() == 0


class TestUnitMVCC:
    """Entry-level MVCC semantics without a session: version mismatch
    drops for everyone; an old reader misses without dropping."""

    def _chunk(self):
        from tidb_tpu.chunk import Chunk, Column
        from tidb_tpu.sqltypes import new_int_field
        col = Column.from_values(new_int_field(), list(range(100)))
        return Chunk([col])

    def test_version_mismatch_drops(self, sysvars):
        cache = dc.DeviceCache()
        blk = cache.fill("k", 1, 10, self._chunk())
        assert blk is not None
        assert cache.get("k", 1, 10) is blk
        assert cache.get("k", 2, 10) is None       # stale: dropped
        assert len(cache) == 0
        assert cache.resident_bytes() == 0

    def test_old_reader_misses_entry_survives(self, sysvars):
        cache = dc.DeviceCache()
        blk = cache.fill("k", 1, 10, self._chunk())
        assert cache.get("k", 1, 9) is None        # too old for reader
        assert len(cache) == 1                     # but not dropped
        assert cache.get("k", 1, 11) is blk        # newer reader serves
        cache.shed()

    def test_block_over_budget_not_cached(self, sysvars):
        config.set_var("tidb_tpu_device_cache_bytes", 64)
        cache = dc.DeviceCache()
        assert cache.fill("k", 1, 10, self._chunk()) is None
        assert len(cache) == 0
        assert cache.resident_bytes() == 0
