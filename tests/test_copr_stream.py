"""Streaming coprocessor subsystem (store/stream.py + wire + copr).

Ref: the CmdCopStream mode of store/tikv/coprocessor.go:547-555 —
incremental per-range responses, stream re-created from the last
returned range on region errors. Asserted here:

  * bounded memory: a region strictly larger than the response cap
    streams in frames of <= cap raw bytes, and the client never buffers
    more than the credit window of frames;
  * KeepOrder parity: streamed results are IDENTICAL to the
    materialized path, ordered scans included;
  * resume: a failpoint kills the stream mid-region and the client
    re-issues from the last acked range boundary — no duplicate, no
    missing row;
  * the same path serves in-process (mockstore/rpc.py) and
    out-of-process (store/remote.py) storage.
"""

import os

import pytest

from tidb_tpu import config, metrics
from tidb_tpu.kv import EpochNotMatchError
from tidb_tpu.session import Session
from tidb_tpu.store import stream as costream
from tidb_tpu.store.storage import new_mock_storage
from tidb_tpu.util import failpoint

N_ROWS = 2000
FRAME_BYTES = 1024       # each row is ~45 raw bytes: dozens of frames
CREDIT = 3
REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def q(s, sql):
    return s.query(sql).rows


@pytest.fixture
def sess():
    st = new_mock_storage()
    s = Session(st)
    s.execute("CREATE DATABASE d")
    s.execute("USE d")
    s.execute("CREATE TABLE t (id BIGINT PRIMARY KEY, v BIGINT, "
              "s VARCHAR(10))")
    s.execute("INSERT INTO t VALUES " + ",".join(
        f"({i},{i * 7 % 1000},'s{i % 13}')" for i in range(N_ROWS)))
    info = s.domain.info_schema().table("d", "t")
    st.cluster.split_table(info.id, 4, max_handle=N_ROWS)
    yield s
    s.close()


@pytest.fixture
def streaming():
    old = {k: config.get_var(k) for k in
           ("tidb_tpu_copr_stream", "tidb_tpu_copr_stream_frame_bytes",
            "tidb_tpu_copr_stream_credit", "tidb_tpu_chunk_cache")}
    config.set_var("tidb_tpu_copr_stream", 1)
    config.set_var("tidb_tpu_copr_stream_frame_bytes", FRAME_BYTES)
    config.set_var("tidb_tpu_copr_stream_credit", CREDIT)
    # the frame contracts pinned in this file (byte cap, exact range
    # tiling, resume boundaries) are the COLD-path guarantees: with the
    # chunk cache on, a re-read of a resident range legitimately serves
    # as ONE final frame straight from the cached block instead
    # (TestStreamCacheIntegration pins that shape) — so these tests run
    # cache-off to exercise the real framed scan every time
    config.set_var("tidb_tpu_chunk_cache", 0)
    costream.reset_stream_stats()
    yield
    for k, v in old.items():
        config.set_var(k, v)


def _materialized(s, sql):
    config.set_var("tidb_tpu_copr_stream", 0)
    try:
        return q(s, sql)
    finally:
        config.set_var("tidb_tpu_copr_stream", 1)


class TestBoundedMemory:
    def test_region_larger_than_cap_streams_in_frames(self, sess,
                                                      streaming):
        """The acceptance shape: each region's data is strictly larger
        than the frame cap, so every region MUST multi-frame; no frame
        exceeds the cap and client buffering never exceeds the credit
        window."""
        want = _materialized(sess, "SELECT COUNT(*), SUM(v) FROM t")
        got = q(sess, "SELECT COUNT(*), SUM(v) FROM t")
        assert got == want == [(N_ROWS, sum(i * 7 % 1000
                                            for i in range(N_ROWS)))]
        st = costream.stream_stats()
        assert st["streams"] >= 4                  # one per region
        assert st["frames"] > st["streams"]        # regions multi-framed
        assert st["bytes"] > 4 * FRAME_BYTES       # data outgrew the cap
        assert 0 < st["frame_bytes_max"] <= FRAME_BYTES
        assert st["peak_buffered"] <= CREDIT

    def test_metrics_counters_advance(self, sess, streaming):
        before = metrics.snapshot().get(metrics.COP_STREAM_FRAMES, 0)
        q(sess, "SELECT SUM(v) FROM t")
        snap = metrics.snapshot()
        assert snap.get(metrics.COP_STREAM_FRAMES, 0) > before
        assert snap.get(metrics.COP_STREAM_BYTES, 0) > 0


class TestKeepOrderParity:
    def test_ordered_scan_identical(self, sess, streaming):
        sql = "SELECT id, v FROM t WHERE v >= 500 ORDER BY id"
        got = q(sess, sql)
        assert got == _materialized(sess, sql)
        assert [r[0] for r in got] == sorted(r[0] for r in got)

    def test_group_by_partials_merge(self, sess, streaming):
        sql = ("SELECT s, COUNT(*), SUM(v), MIN(id), MAX(id) FROM t "
               "GROUP BY s ORDER BY s")
        assert q(sess, sql) == _materialized(sess, sql)

    def test_limit_early_stop(self, sess, streaming):
        sql = "SELECT id FROM t ORDER BY id LIMIT 7"
        assert q(sess, sql) == [(i,) for i in range(7)]


class TestFrameContiguity:
    def test_frames_cover_contiguous_ranges(self, sess, streaming):
        """Unit-level: the producer's frames tile the region exactly —
        frame i+1 starts where frame i ended, the final frame is marked
        last and ends at the region-clamped scan end."""
        from tidb_tpu.kv import CopRequest, KVRange, ReqType
        from tidb_tpu.plan.physical import CopPlan  # noqa: F401 (shape)

        st = sess.storage
        # record per-stream through the client with a wrapping recorder
        streams = []
        orig = st.shim.coprocessor_stream

        def recording(ctx, req, **kw):
            mine = {"req_start": req.ranges[0].start, "frames": []}
            streams.append(mine)
            for f in orig(ctx, req, **kw):
                mine["frames"].append(f)
                yield f

        st.shim.coprocessor_stream = recording
        try:
            q(sess, "SELECT id FROM t")
        finally:
            st.shim.coprocessor_stream = orig
        # an attempt aborted before its first frame (e.g. KeyLockedError
        # while the fixture INSERT's async lock resolution is pending)
        # records as an empty stream; the client resumes it — only the
        # attempts that delivered frames carry tiling obligations
        streams = [s for s in streams if s["frames"]]
        assert len(streams) >= 4               # one per region
        multi = 0
        for s in streams:
            frames = s["frames"]
            assert frames[0].range.start >= s["req_start"]
            for a, b in zip(frames, frames[1:]):
                assert not a.last
                assert b.range.start == a.range.end   # exact tiling
            assert frames[-1].last
            multi += len(frames) > 1
        assert multi >= 4           # regions outgrew the cap: multi-framed


class TestFailpointResume:
    def test_mid_stream_kill_resumes_no_dup_no_loss(self, sess,
                                                    streaming):
        """Kill the stream after a few delivered frames via the shim
        failpoint; the client must resume from the last acked range
        boundary: the full ordered id list comes back exactly once."""
        shim = sess.storage.shim
        calls = {"n": 0, "fired": 0}

        def inject(cmd, ctx):
            if cmd != "CopStream":
                return
            calls["n"] += 1
            # fire twice, mid-region (every 5th frame check), to prove
            # repeated interruption still converges
            if calls["n"] in (5, 11):
                calls["fired"] += 1
                raise EpochNotMatchError(ctx.region_id)

        failpoint.enable("rpc/request", inject)
        try:
            got = q(sess, "SELECT id FROM t ORDER BY id")
        finally:
            failpoint.disable("rpc/request")
        assert calls["fired"] == 2
        assert [r[0] for r in got] == list(range(N_ROWS))
        assert costream.stream_stats()["resumes"] >= 2

    def test_kill_during_agg_partials(self, sess, streaming):
        """Resume must also hold for partial aggregates: an un-acked
        frame's partial is never merged, so re-scanning its range cannot
        double-count."""
        shim = sess.storage.shim
        state = {"n": 0}

        def inject(cmd, ctx):
            if cmd != "CopStream":
                return
            state["n"] += 1
            if state["n"] == 7:
                raise EpochNotMatchError(ctx.region_id)

        want = _materialized(sess, "SELECT COUNT(*), SUM(v) FROM t")
        failpoint.enable("rpc/request", inject)
        try:
            got = q(sess, "SELECT COUNT(*), SUM(v) FROM t")
        finally:
            failpoint.disable("rpc/request")
        assert got == want

    def test_real_region_split_mid_stream(self, sess, streaming):
        """An actual epoch change (region split) mid-stream: the
        per-frame epoch re-check surfaces it, the client re-splits and
        finishes both halves."""
        from tidb_tpu import tablecodec
        st = sess.storage
        info = sess.domain.info_schema().table("d", "t")
        state = {"n": 0, "split": 0}

        def inject(cmd, ctx):
            if cmd != "CopStream":
                return
            state["n"] += 1
            if state["n"] == 4 and not state["split"]:
                state["split"] = 1
                st.cluster.split(
                    tablecodec.record_key(info.id, N_ROWS // 8))

        failpoint.enable("rpc/request", inject)
        try:
            got = q(sess, "SELECT id FROM t ORDER BY id")
        finally:
            failpoint.disable("rpc/request")
        assert state["split"] == 1
        assert [r[0] for r in got] == list(range(N_ROWS))


class TestClosurePhaseInterruption:
    def test_drop_after_final_frame_does_not_rescan(self, sess,
                                                    streaming):
        """An interruption AFTER the final frame was delivered (e.g. a
        connection drop before STREAM_END) must not resume: for an
        open-ended final frame the resume cursor is b'' — re-issuing
        from it would replay the whole table as duplicates."""
        st = sess.storage
        orig = st.shim.coprocessor_stream
        fired = {"n": 0}

        def dying(ctx, req, **kw):
            for f in orig(ctx, req, **kw):
                yield f
                if f.last:
                    fired["n"] += 1
                    from tidb_tpu.kv import StreamInterruptedError
                    raise StreamInterruptedError("drop before END")

        st.shim.coprocessor_stream = dying
        try:
            got = q(sess, "SELECT id FROM t ORDER BY id")
        finally:
            st.shim.coprocessor_stream = orig
        assert fired["n"] >= 4          # every region's stream died late
        assert [r[0] for r in got] == list(range(N_ROWS))   # no dups


class TestMeshFeed:
    def test_streamed_frames_feed_mesh_superbatches(self, sess,
                                                    streaming):
        """Streamed coprocessor frames flow straight into the mesh
        executor's double-buffered host->HBM super-batches
        (executor/mesh.py _stream_groups) with NO intermediate full
        materialization: both streaming layers engage and the result
        matches the host path."""
        from tidb_tpu import parallel
        from tidb_tpu.executor import mesh as mesh_exec

        sql = "SELECT s, COUNT(*), SUM(v) FROM t GROUP BY s ORDER BY s"
        want = _materialized(sess, sql)
        parallel.enable_mesh(8)
        old = config.get_var("tidb_tpu_stream_rows")
        config.set_var("tidb_tpu_stream_rows", 256)
        mesh_exec.reset_stream_stats()
        try:
            got = q(sess, sql)
        finally:
            config.set_var("tidb_tpu_stream_rows", old)
            parallel.disable_mesh()
        mstats = mesh_exec.stream_stats()
        assert mstats["streams"] >= 1 and mstats["batches"] >= 2, mstats
        cstats = costream.stream_stats()
        assert cstats["frames"] > cstats["streams"]
        assert len(got) == len(want)
        for g, w in zip(got, want):
            assert g[0] == w[0] and g[1] == w[1]
            assert abs(float(g[2]) - float(w[2])) <= \
                1e-9 * max(1.0, abs(float(w[2])))


class TestRemoteStream:
    def test_wire_path_parity_and_backpressure(self, streaming):
        from tidb_tpu.store.remote import StorageServer, connect
        srv = StorageServer()
        srv.start()
        st = connect("127.0.0.1", srv.port)
        s = Session(st)
        try:
            s.execute("CREATE DATABASE d; USE d")
            s.execute("CREATE TABLE t (id BIGINT PRIMARY KEY, v BIGINT)")
            s.execute("INSERT INTO t VALUES " + ",".join(
                f"({i},{i * 3})" for i in range(1200)))
            want = _materialized(s, "SELECT COUNT(*), SUM(v) FROM t")
            costream.reset_stream_stats()
            got = q(s, "SELECT COUNT(*), SUM(v) FROM t")
            assert got == want
            stats = costream.stream_stats()
            assert stats["frames"] > 1
            assert stats["frame_bytes_max"] <= FRAME_BYTES
            # server-side blocking on the credit window happened: the
            # producer outran the consumer and was backpressured
            assert stats["credit_stalls"] >= 1
            # ordered scan over the wire, then plain requests still work
            # on the pooled connections (stream left them clean)
            rows = q(s, "SELECT id FROM t WHERE v > 30 ORDER BY id")
            assert [r[0] for r in rows] == list(range(11, 1200))
            assert q(s, "SELECT COUNT(*) FROM t") == [(1200,)]
        finally:
            s.close()
            st.close()
            srv.close()

    def test_frame_cap_is_the_clients_not_the_servers(self, streaming):
        """The frame cap ships WITH the request: against a storage node
        in another PROCESS (whose own sysvar default is 4 MiB), the
        client's SET must still bound every frame."""
        import subprocess
        import sys as _sys
        import time as _time
        proc = subprocess.Popen(
            [_sys.executable, "-m", "tidb_tpu.store.remote", "--port",
             "0"], stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True, cwd=REPO)
        try:
            line = proc.stdout.readline()
            assert "listening" in line, line
            port = int(line.rsplit(":", 1)[1])
            from tidb_tpu.store.remote import connect
            st = connect("127.0.0.1", port)
            s = Session(st)
            try:
                s.execute("CREATE DATABASE d; USE d")
                s.execute("CREATE TABLE t (id BIGINT PRIMARY KEY, "
                          "v BIGINT)")
                s.execute("INSERT INTO t VALUES " + ",".join(
                    f"({i},{i})" for i in range(1000)))
                # count frames client-side: raw rows are ~30B, so a
                # 512B cap over 1000 rows MUST multi-frame per region —
                # if the server used its own 4MiB default, one frame
                # per region would suffice
                config.set_var("tidb_tpu_copr_stream_frame_bytes", 512)
                frames = [0]
                orig = st.shim.coprocessor_stream

                def counting(ctx, req, **kw):
                    for f in orig(ctx, req, **kw):
                        frames[0] += 1
                        yield f

                st.shim.coprocessor_stream = counting
                assert q(s, "SELECT COUNT(*) FROM t") == [(1000,)]
                assert frames[0] > 20, frames
            finally:
                s.close()
                st.close()
        finally:
            proc.terminate()
            for _ in range(50):
                if proc.poll() is not None:
                    break
                _time.sleep(0.1)
            proc.kill()

    def test_wire_limit_abandons_stream_cleanly(self, streaming):
        """LIMIT abandons the stream mid-flight: the dropped connection
        must not poison the pool for later calls."""
        from tidb_tpu.store.remote import StorageServer, connect
        srv = StorageServer()
        srv.start()
        st = connect("127.0.0.1", srv.port)
        s = Session(st)
        try:
            s.execute("CREATE DATABASE d; USE d")
            s.execute("CREATE TABLE t (id BIGINT PRIMARY KEY, v BIGINT)")
            s.execute("INSERT INTO t VALUES " + ",".join(
                f"({i},{i})" for i in range(1500)))
            for _ in range(3):
                assert q(s, "SELECT id FROM t ORDER BY id LIMIT 2") == \
                    [(0,), (1,)]
                assert q(s, "SELECT COUNT(*) FROM t") == [(1500,)]
        finally:
            s.close()
            st.close()
            srv.close()


class TestStreamCacheIntegration:
    """COP_STREAM consults and fills the columnar cache hierarchy
    (store/stream.py module docstring) — the fix that let
    tidb_tpu_copr_stream default ON. Cold streams keep the bounded
    framed contract and fill the host chunk cache at stream end; warm
    streams serve one final frame per region straight from residency,
    and fused agg plans hit the HBM device cache."""

    @pytest.fixture
    def cached_streaming(self):
        old = {k: config.get_var(k) for k in
               ("tidb_tpu_copr_stream", "tidb_tpu_copr_stream_frame_bytes",
                "tidb_tpu_copr_stream_credit", "tidb_tpu_chunk_cache",
                "tidb_tpu_device_min_rows")}
        config.set_var("tidb_tpu_copr_stream", 1)
        config.set_var("tidb_tpu_copr_stream_frame_bytes", FRAME_BYTES)
        config.set_var("tidb_tpu_copr_stream_credit", CREDIT)
        config.set_var("tidb_tpu_chunk_cache", 1)
        config.set_var("tidb_tpu_device_min_rows", 1)
        costream.reset_stream_stats()
        yield
        for k, v in old.items():
            config.set_var(k, v)

    def test_streaming_defaults_on(self):
        """The documented default (docs/PERF.md): streaming no longer
        trades away cache residency, so it is on out of the box."""
        import tidb_tpu.config as cfg
        assert cfg._DEFS["tidb_tpu_copr_stream"][1] == 1

    def test_cold_fills_then_warm_single_frames(self, sess,
                                                cached_streaming):
        sql = "SELECT COUNT(*), SUM(v) FROM t"
        cold = q(sess, sql)
        st1 = costream.stream_stats()
        assert st1["streams"] >= 4
        assert st1["frames"] > st1["streams"]   # cold: real framed scan
        costream.reset_stream_stats()
        warm = q(sess, sql)
        st2 = costream.stream_stats()
        assert warm == cold
        # warm: every region serves as ONE final frame from the cache
        assert st2["streams"] >= 4
        assert st2["frames"] == st2["streams"]

    def test_warm_stream_hits_device_cache(self, sess, cached_streaming):
        sql = "SELECT COUNT(*), SUM(v) FROM t"
        q(sess, sql)            # cold: host-cache fill
        q(sess, sql)            # warm: device-cache fill (fused path)
        before = metrics.snapshot()
        got = q(sess, sql)      # warm: fused dispatch from HBM
        snap = metrics.snapshot()
        assert got == [(N_ROWS, sum(i * 7 % 1000 for i in range(N_ROWS)))]
        assert snap.get(metrics.HBM_CACHE_HITS, 0) - \
            before.get(metrics.HBM_CACHE_HITS, 0) >= 4
        assert snap.get(metrics.HBM_CACHE_MISSES, 0) == \
            before.get(metrics.HBM_CACHE_MISSES, 0)

    def test_write_between_streams_is_fresh(self, sess,
                                            cached_streaming):
        sql = "SELECT COUNT(*) FROM t"
        assert q(sess, sql) == [(N_ROWS,)]
        assert q(sess, sql) == [(N_ROWS,)]      # warm, from residency
        sess.execute(f"INSERT INTO t VALUES ({N_ROWS + 5}, 1, 'zz')")
        assert q(sess, sql) == [(N_ROWS + 1,)]  # version bump: fresh
        assert q(sess, sql) == [(N_ROWS + 1,)]  # and warm again

    def test_filter_scan_parity_warm_and_cold(self, sess,
                                              cached_streaming):
        sql = "SELECT id, v FROM t WHERE v >= 500 ORDER BY id"
        cold = q(sess, sql)
        warm = q(sess, sql)
        assert cold == warm == _materialized(sess, sql)

    def test_oversized_warm_agg_partial_streams_framed(self, sess,
                                                       cached_streaming):
        """A warm high-cardinality GROUP BY partial approaches the raw
        block size; shipping it as ONE cached frame would bust the
        streamed constant-client-memory contract. _cached_frame refuses
        (returns None) and the region streams framed from the raw scan
        instead — still correct, and the block stays resident for
        materialized readers."""
        sql = "SELECT v, COUNT(*) FROM t GROUP BY v ORDER BY v"
        cold = q(sess, sql)
        costream.reset_stream_stats()
        warm = q(sess, sql)
        st = costream.stream_stats()
        assert warm == cold == _materialized(sess, sql)
        # the ~1000-group partial busts the 1KB cap: every region must
        # fall back to the framed raw scan, never one unbounded frame
        assert st["streams"] >= 4
        assert st["frames"] > st["streams"]
        assert st["frame_bytes_max"] <= FRAME_BYTES
        # the refusal memoized the over-cap size: the next warm stream
        # skips the wasted fused dispatch and goes straight to the raw
        # framed scan — _cached_frame must not run at all
        calls = []
        orig = costream._cached_frame
        costream._cached_frame = lambda *a, **k: calls.append(1) or \
            orig(*a, **k)
        try:
            assert q(sess, sql) == cold
        finally:
            costream._cached_frame = orig
        assert not calls
