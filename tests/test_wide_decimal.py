"""Wide DECIMAL (p>18): exact scaled python ints on the host object lane
(ref: types/mydecimal.go:1 — 65-digit precision via 9-digit words; here
bignum arithmetic). VERDICT r4 #8 acceptance: DECIMAL(38,10) CRUD +
SUM/AVG + comparisons exact; narrow columns still ride device kernels."""

import decimal
from decimal import Decimal

import pytest

decimal.getcontext().prec = 70   # test-side arithmetic must not round

from tidb_tpu.session import Session, SQLError
from tidb_tpu.store.storage import new_mock_storage

BIG1 = Decimal("1234567890123456789012345678.1234567890")
BIG2 = Decimal("9999999999999999999999999999.9999999999")
NEG = Decimal("-8765432109876543210987654321.0987654321")


@pytest.fixture
def sess():
    s = Session(new_mock_storage())
    s.execute("CREATE DATABASE wd")
    s.execute("USE wd")
    yield s
    s.close()


@pytest.fixture
def t(sess):
    sess.execute("CREATE TABLE t (id BIGINT PRIMARY KEY, "
                 "v DECIMAL(38,10), w DECIMAL(10,2))")
    sess.execute(f"INSERT INTO t VALUES (1, {BIG1}, 1.50), "
                 f"(2, {BIG2}, 2.25), (3, {NEG}, 3.00), "
                 "(4, NULL, NULL)")
    return sess


class TestCrud:
    def test_round_trip_exact(self, t):
        rows = t.query("SELECT v FROM t ORDER BY id").rows
        assert rows[0][0] == BIG1
        assert rows[1][0] == BIG2
        assert rows[2][0] == NEG
        assert rows[3][0] is None

    def test_update_delete(self, t):
        t.execute(f"UPDATE t SET v = {BIG1} WHERE id = 2")
        assert t.query("SELECT v FROM t WHERE id = 2").rows == [(BIG1,)]
        t.execute("DELETE FROM t WHERE id = 3")
        assert t.query("SELECT COUNT(*) FROM t").rows == [(3,)]

    def test_out_of_range_rejected(self, sess):
        sess.execute("CREATE TABLE r (id BIGINT PRIMARY KEY, "
                     "v DECIMAL(20,2))")
        with pytest.raises((SQLError, Exception)):
            sess.execute("INSERT INTO r VALUES "
                         "(1, 1234567890123456789012345.00)")

    def test_p65_allowed_p66_rejected(self, sess):
        sess.execute("CREATE TABLE p65 (id BIGINT PRIMARY KEY, "
                     "v DECIMAL(65,30))")
        with pytest.raises(SQLError):
            sess.execute("CREATE TABLE p66 (id BIGINT PRIMARY KEY, "
                         "v DECIMAL(66,30))")


class TestAggregation:
    def test_sum_exact(self, t):
        want = BIG1 + BIG2 + NEG
        assert t.query("SELECT SUM(v) FROM t").rows == [(want,)]

    def test_avg_exact(self, t):
        got = t.query("SELECT AVG(v) FROM t").rows[0][0]
        want = (BIG1 + BIG2 + NEG) / 3
        assert abs(Decimal(got) - want) < Decimal("0.001")

    def test_min_max_count(self, t):
        r = t.query("SELECT MIN(v), MAX(v), COUNT(v) FROM t").rows[0]
        assert r == (NEG, BIG2, 3)

    def test_group_by_wide_key(self, sess):
        sess.execute("CREATE TABLE g (id BIGINT PRIMARY KEY, "
                     "k DECIMAL(30,5), x BIGINT)")
        sess.execute(f"INSERT INTO g VALUES "
                     f"(1, 12345678901234567890123.00001, 10), "
                     f"(2, 12345678901234567890123.00001, 20), "
                     f"(3, 99999999999999999999999.99999, 5)")
        rows = sess.query("SELECT k, SUM(x) FROM g GROUP BY k "
                          "ORDER BY k").rows
        assert rows[0] == (Decimal("12345678901234567890123.00001"), 30)
        assert rows[1] == (Decimal("99999999999999999999999.99999"), 5)


class TestComparisons:
    def test_filters_exact(self, t):
        assert t.query(f"SELECT id FROM t WHERE v = {BIG1}").rows == \
            [(1,)]
        assert t.query(f"SELECT id FROM t WHERE v > {BIG1} "
                       "ORDER BY id").rows == [(2,)]
        assert t.query("SELECT id FROM t WHERE v < 0").rows == [(3,)]

    def test_adjacent_values_distinct(self, sess):
        """Values that collide in float64 stay distinct (exactness)."""
        sess.execute("CREATE TABLE a (id BIGINT PRIMARY KEY, "
                     "v DECIMAL(38,0))")
        base = 10**30
        sess.execute(f"INSERT INTO a VALUES (1, {base}), "
                     f"(2, {base + 1})")
        assert sess.query(f"SELECT id FROM a WHERE v = {base}").rows == \
            [(1,)]
        assert sess.query(f"SELECT id FROM a WHERE v = {base + 1}"
                          ).rows == [(2,)]

    def test_order_by_wide(self, t):
        rows = t.query("SELECT id FROM t WHERE v IS NOT NULL "
                       "ORDER BY v").rows
        assert [r[0] for r in rows] == [3, 1, 2]

    def test_mixed_width_compare(self, t):
        # narrow column w compared against wide-precision literal
        assert t.query("SELECT id FROM t WHERE w < 2 ORDER BY id"
                       ).rows == [(1,)]

    def test_arithmetic(self, t):
        got = t.query(f"SELECT v + 1 FROM t WHERE id = 1").rows[0][0]
        assert Decimal(got) == BIG1 + 1
        got = t.query("SELECT v * 2 FROM t WHERE id = 1").rows[0][0]
        assert Decimal(got) == BIG1 * 2


class TestNarrowStaysDevice:
    def test_narrow_decimal_still_fixed_width(self):
        from tidb_tpu.sqltypes import new_decimal_field
        narrow = new_decimal_field(flen=15, frac=2)
        wide = new_decimal_field(flen=38, frac=10)
        assert narrow.fixed_width and not narrow.is_wide_decimal
        assert not wide.fixed_width and wide.is_wide_decimal

    def test_codec_order_preserved_across_widths(self):
        from tidb_tpu import codec
        vals = [-(10**25), -(2**63) - 1, -(2**63), -5, 0, 7,
                2**63 - 1, 2**63, 10**25, 10**37]
        encs = [codec.encode_datum((10, v)) for v in vals]
        assert encs == sorted(encs)
        for v, e in zip(vals, encs):
            assert codec.decode_one(e)[0] == (10, v)
