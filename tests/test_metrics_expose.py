"""/metrics exposition format (metrics.py): a minimal Prometheus
text-format parser verifies what real scrapers depend on — # HELP/# TYPE
metadata per family, monotone cumulative buckets, +Inf == _count, and
labeled series (counters AND histograms) that parse cleanly."""

import re

from tidb_tpu import metrics

_SERIES = re.compile(
    r"^([a-zA-Z_:][a-zA-Z0-9_:]*)(\{[^}]*\})?\s+(\S+)$")
_LABEL = re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)="([^"]*)"')


def parse_exposition(text: str):
    """-> (series, meta): series maps (name, frozenset(labels)) -> float,
    meta maps family name -> {"help": str, "type": str}. Raises on any
    line a Prometheus scraper would reject."""
    series: dict = {}
    meta: dict = {}
    for line in text.splitlines():
        if not line:
            continue
        if line.startswith("# HELP "):
            _h, _k, name, rest = line.split(" ", 3)
            meta.setdefault(name, {})["help"] = rest
            continue
        if line.startswith("# TYPE "):
            parts = line.split(" ")
            assert len(parts) == 4, f"bad TYPE line: {line!r}"
            assert parts[3] in ("counter", "gauge", "histogram",
                                "summary", "untyped"), line
            meta.setdefault(parts[2], {})["type"] = parts[3]
            continue
        assert not line.startswith("#"), f"unknown comment: {line!r}"
        m = _SERIES.match(line)
        assert m, f"unparseable series line: {line!r}"
        name, lbl, val = m.groups()
        labels = frozenset(_LABEL.findall(lbl)) if lbl else frozenset()
        series[(name, labels)] = float(val)
    return series, meta


def _family(series, name):
    return {k: v for k, v in series.items() if k[0] == name}


def test_counters_have_help_and_type():
    metrics.counter("tidb_tpu_test_expo_total", {"kind": "a"}, inc=2)
    series, meta = parse_exposition(metrics.expose())
    fam = _family(series, "tidb_tpu_test_expo_total")
    assert ("tidb_tpu_test_expo_total",
            frozenset({("kind", "a")})) in fam
    assert meta["tidb_tpu_test_expo_total"]["type"] == "counter"
    assert meta["tidb_tpu_test_expo_total"]["help"]


def test_histogram_buckets_monotone_and_inf_equals_count():
    name = "tidb_tpu_test_expo_hist_seconds"
    for v in (0.0001, 0.003, 0.02, 0.2, 2.0, 100.0):
        metrics.histogram(name, v)
    series, meta = parse_exposition(metrics.expose())
    assert meta[name]["type"] == "histogram"
    buckets = []
    for (n, labels), v in series.items():
        if n == name + "_bucket":
            le = dict(labels)["le"]
            buckets.append((float("inf") if le == "+Inf" else float(le),
                            v))
    buckets.sort()
    assert buckets, "no bucket series"
    counts = [c for _le, c in buckets]
    assert counts == sorted(counts), "buckets must be cumulative"
    total = series[(name + "_count", frozenset())]
    assert buckets[-1][0] == float("inf")
    assert buckets[-1][1] == total == 6
    assert series[(name + "_sum", frozenset())] > 100.0


def test_labeled_histogram_series():
    name = "tidb_tpu_test_expo_op_seconds"
    metrics.histogram(name, 0.01, {"op": "HashAgg"})
    metrics.histogram(name, 0.5, {"op": "HashJoin"})
    series, meta = parse_exposition(metrics.expose())
    assert meta[name]["type"] == "histogram"
    for op in ("HashAgg", "HashJoin"):
        key = (name + "_count", frozenset({("op", op)}))
        assert series[key] == 1, sorted(
            k for k in series if k[0].startswith(name))
        # every bucket line of a labeled series carries BOTH labels
        bucket_labels = [dict(labels) for (n, labels) in series
                         if n == name + "_bucket"
                         and dict(labels).get("op") == op]
        assert bucket_labels and all("le" in d for d in bucket_labels)


def test_snapshot_keeps_flat_keys_for_unlabeled():
    metrics.counter("tidb_tpu_test_expo_flat_total")
    metrics.histogram("tidb_tpu_test_expo_flat_seconds", 0.1)
    snap = metrics.snapshot()
    assert snap["tidb_tpu_test_expo_flat_total"] >= 1
    assert snap["tidb_tpu_test_expo_flat_seconds_count"] >= 1
    assert "tidb_tpu_test_expo_flat_seconds_sum" in snap


def test_meta_emitted_once_per_family():
    metrics.counter("tidb_tpu_test_expo_once_total", {"a": "1"})
    metrics.counter("tidb_tpu_test_expo_once_total", {"a": "2"})
    text = metrics.expose()
    assert text.count("# TYPE tidb_tpu_test_expo_once_total ") == 1
    assert text.count("# HELP tidb_tpu_test_expo_once_total ") == 1
