"""Statistics & CBO tests.

Ref model: statistics/histogram_test.go, cmsketch_test.go,
selectivity_test.go, plan/cbo_test.go (plans flip after ANALYZE).
"""

import numpy as np
import pytest

from tidb_tpu.chunk import Column
from tidb_tpu.session import Session
from tidb_tpu.sqltypes import new_int_field
from tidb_tpu.statistics import (CMSketch, StatsHandle, TableStats,
                                 build_column_stats, build_histogram)
from tidb_tpu.store import new_mock_storage


@pytest.fixture
def tk():
    storage = new_mock_storage()
    storage.async_commit_secondaries = False
    s = Session(storage)
    s.execute("CREATE DATABASE test; USE test")
    yield s
    s.close()
    storage.close()


class TestHistogram:
    def _uniform_hist(self, n=10000, lo=0, hi=1000):
        rng = np.random.default_rng(7)
        data = rng.integers(lo, hi, n).astype(np.int64)
        col = Column(new_int_field(), data)
        cs = build_column_stats(col)
        return data, cs.hist

    def test_total_and_ndv(self):
        data, h = self._uniform_hist()
        assert h.total == len(data)
        assert h.ndv == len(np.unique(data))

    def test_less_row_count(self):
        data, h = self._uniform_hist()
        for v in (100, 500, 900):
            est = h.less_row_count(v)
            actual = int((data < v).sum())
            assert abs(est - actual) <= 0.05 * len(data)

    def test_between_row_count(self):
        data, h = self._uniform_hist()
        est = h.between_row_count(200, 400)
        actual = int(((data >= 200) & (data < 400)).sum())
        assert abs(est - actual) <= 0.05 * len(data)

    def test_out_of_range(self):
        _, h = self._uniform_hist()
        assert h.equal_row_count(-5) == 0.0
        assert h.equal_row_count(10**6) == 0.0
        assert h.less_row_count(-5) == 0.0
        assert h.less_row_count(10**7) == h.total

    def test_skewed_repeats(self):
        # one heavy value: its bucket repeat should catch it exactly-ish
        data = np.concatenate([np.full(5000, 42, np.int64),
                               np.arange(1000, dtype=np.int64)])
        cs = build_column_stats(Column(new_int_field(), data))
        assert cs.equal_count(42) >= 4999
        assert cs.equal_count(999) <= 10

    def test_serialization_roundtrip(self):
        data, h = self._uniform_hist(2000)
        h2 = type(h).from_obj(h.to_obj())
        assert h2.total == h.total and h2.ndv == h.ndv
        assert h2.less_row_count(500) == h.less_row_count(500)


class TestCMSketch:
    def test_exact_for_inserted(self):
        cm = CMSketch()
        cm.insert(b"alpha", 10)
        cm.insert(b"beta", 3)
        assert cm.query(b"alpha") >= 10      # overestimate only
        assert cm.query(b"beta") >= 3
        assert cm.query(b"gamma") <= 1       # tiny collision noise at most

    def test_roundtrip(self):
        cm = CMSketch()
        for i in range(100):
            cm.insert(str(i).encode(), i + 1)
        cm2 = CMSketch.from_obj(cm.to_obj())
        assert cm2.query(b"50") == cm.query(b"50")
        assert cm2.count == cm.count


class TestDeviceSort:
    """ops/stats.device_sort: the ANALYZE sort goes through the pow2
    shape discipline — padded to runtime.bucket_size, pad values sort
    last, sliced back — so histogram builds over growing tables reuse
    one compiled program per bucket instead of retracing per row
    count (the repo-wide retrace-hazard lint contract)."""

    def test_pads_sort_correctly(self):
        from tidb_tpu.ops.stats import device_sort
        ints = np.arange(1000, 0, -1).astype(np.int64)   # non-pow2 n
        np.testing.assert_array_equal(device_sort(ints), np.sort(ints))
        fl = np.array([3.5, -1.0, 2.0, 7.0, 0.5])        # NaN pad path
        np.testing.assert_array_equal(device_sort(fl), np.sort(fl))
        maxed = np.array([np.iinfo(np.int64).max, 1, 5], dtype=np.int64)
        np.testing.assert_array_equal(device_sort(maxed), np.sort(maxed))

    def test_same_bucket_reuses_one_program(self):
        from tidb_tpu.ops.stats import _jit_sort, device_sort
        device_sort(np.arange(900).astype(np.int64))     # warm 1024
        before = _jit_sort._cache_size()
        device_sort(np.arange(1000).astype(np.int64))    # same bucket
        device_sort(np.arange(513).astype(np.int64))
        assert _jit_sort._cache_size() == before


class TestAnalyze:
    def _load(self, tk, n=2000):
        tk.execute("CREATE TABLE t (a BIGINT PRIMARY KEY, b INT, c INT, "
                   "KEY idx_b (b))")
        rows = ",".join(f"({i}, {i % 2}, {i})" for i in range(n))
        tk.execute(f"INSERT INTO t VALUES {rows}")

    def test_analyze_builds_stats(self, tk):
        self._load(tk)
        tk.execute("ANALYZE TABLE t")
        info = tk.domain.info_schema().table("test", "t")
        st = tk.domain.stats_handle().get(info.id)
        assert not st.pseudo
        assert st.count == 2000
        assert len(st.columns) == 3
        assert len(st.indexes) == 1

    def test_plan_flips_to_table_scan_on_unselective_predicate(self, tk):
        self._load(tk)
        # pseudo stats: heuristic picks the index for b = 1
        before = "\n".join(
            r[0] for r in tk.query("EXPLAIN SELECT c FROM t WHERE b = 1").rows)
        assert "IndexLookUp" in before
        tk.execute("ANALYZE TABLE t")
        # b = 1 matches half the table: lookup cost 1000*4 > scan cost 2000
        after = "\n".join(
            r[0] for r in tk.query("EXPLAIN SELECT c FROM t WHERE b = 1").rows)
        assert "IndexLookUp" not in after
        assert "TableReader" in after
        # results identical either way
        assert len(tk.query("SELECT c FROM t WHERE b = 1").rows) == 1000

    def test_selective_predicate_keeps_index(self, tk):
        tk.execute("CREATE TABLE s (a BIGINT PRIMARY KEY, b INT, c INT, "
                   "KEY idx_b (b))")
        rows = ",".join(f"({i}, {i}, {i})" for i in range(2000))
        tk.execute(f"INSERT INTO s VALUES {rows}")
        tk.execute("ANALYZE TABLE s")
        plan = "\n".join(
            r[0] for r in
            tk.query("EXPLAIN SELECT c FROM s WHERE b = 57").rows)
        assert "IndexLookUp" in plan
        assert tk.query("SELECT c FROM s WHERE b = 57").rows == [(57,)]

    def test_est_rows_in_explain(self, tk):
        self._load(tk)
        tk.execute("ANALYZE TABLE t")
        plan = "\n".join(
            r[0] for r in
            tk.query("EXPLAIN SELECT c FROM t WHERE b = 1").rows)
        assert "est_rows:" in plan

    def test_range_estimation_drives_choice(self, tk):
        self._load(tk)
        tk.execute("ANALYZE TABLE t")
        # c spans 0..1999 with idx? no index on c: range on pk instead
        plan = "\n".join(
            r[0] for r in
            tk.query("EXPLAIN SELECT b FROM t WHERE a < 100").rows)
        assert "TableReader" in plan
        assert len(tk.query("SELECT b FROM t WHERE a < 100").rows) == 100


class TestPersistence:
    def test_stats_survive_new_handle(self, tk):
        tk.execute("CREATE TABLE p (a BIGINT PRIMARY KEY, b INT)")
        tk.execute("INSERT INTO p VALUES " +
                   ",".join(f"({i}, {i})" for i in range(500)))
        tk.execute("ANALYZE TABLE p")
        info = tk.domain.info_schema().table("test", "p")
        fresh = StatsHandle(tk.storage)      # simulates a restarted server
        st = fresh.get(info.id)
        assert not st.pseudo
        assert st.count == 500

    def test_drop_table_drops_stats(self, tk):
        tk.execute("CREATE TABLE p (a BIGINT PRIMARY KEY, b INT)")
        tk.execute("INSERT INTO p VALUES (1, 1)")
        tk.execute("ANALYZE TABLE p")
        info = tk.domain.info_schema().table("test", "p")
        tk.execute("DROP TABLE p")
        fresh = StatsHandle(tk.storage)
        assert fresh.get(info.id).pseudo


class TestDeltas:
    def test_note_dml_and_auto_analyze_threshold(self, tk):
        tk.execute("CREATE TABLE d (a BIGINT PRIMARY KEY, b INT)")
        tk.execute("INSERT INTO d VALUES " +
                   ",".join(f"({i}, {i})" for i in range(100)))
        tk.execute("ANALYZE TABLE d")
        h = tk.domain.stats_handle()
        info = tk.domain.info_schema().table("test", "d")
        assert not h.need_auto_analyze(info.id)
        tk.execute("INSERT INTO d VALUES " +
                   ",".join(f"({i}, {i})" for i in range(100, 180)))
        assert h.need_auto_analyze(info.id)

    def test_pseudo_default(self):
        st = TableStats(table_id=1)
        assert st.pseudo
        # pseudo rates
        assert st._pseudo_range(5, 5) == st.count / 1000
