"""Subquery decorrelation: correlated EXISTS / IN rewritten to (anti-)
semi hash joins (ref: decorrelateSolver, plan/optimizer.go:42-50) so a
Q4-shaped query runs two scans + one join instead of one inner execution
per outer row."""

import numpy as np
import pytest

from tidb_tpu.session import Session
from tidb_tpu.store.storage import new_mock_storage
from tidb_tpu.table import Table, bulkload


@pytest.fixture
def sess():
    st = new_mock_storage()
    s = Session(st)
    s.execute("CREATE DATABASE d")
    s.execute("USE d")
    yield s
    s.close()


def _load(sess, n_o=5000, n_l=12000, seed=0):
    sess.execute("CREATE TABLE o (ok BIGINT PRIMARY KEY, pri BIGINT)")
    sess.execute("CREATE TABLE l (id BIGINT PRIMARY KEY, ok BIGINT, "
                 "c BIGINT, r BIGINT)")
    rng = np.random.default_rng(seed)
    to = Table(sess.domain.info_schema().table("d", "o"), sess.storage)
    tl = Table(sess.domain.info_schema().table("d", "l"), sess.storage)
    pri = rng.integers(0, 5, n_o)
    bulkload.bulk_load(sess.storage, to,
                       {"ok": np.arange(n_o), "pri": pri})
    lok = rng.integers(0, n_o, n_l)
    c = rng.integers(0, 100, n_l)
    r = rng.integers(0, 100, n_l)
    bulkload.bulk_load(sess.storage, tl, {
        "id": np.arange(n_l), "ok": lok, "c": c, "r": r})
    return pri, lok, c, r


class TestDecorrelate:
    def test_exists_becomes_semi_join(self, sess):
        pri, lok, c, r = _load(sess)
        q = ("SELECT pri, COUNT(*) FROM o WHERE EXISTS ("
             "SELECT 1 FROM l WHERE l.ok = o.ok AND l.c < l.r) "
             "GROUP BY pri ORDER BY pri")
        txt = sess.plan(q).explain()
        assert "semi" in txt and "Apply" not in txt, txt
        got = dict(sess.query(q).rows)
        import collections
        late = set(lok[c < r].tolist())
        want = dict(collections.Counter(
            int(pri[i]) for i in range(len(pri)) if i in late))
        assert got == want

    def test_not_exists_becomes_anti_join(self, sess):
        pri, lok, c, r = _load(sess)
        q = ("SELECT COUNT(*) FROM o WHERE NOT EXISTS "
             "(SELECT 1 FROM l WHERE l.ok = o.ok)")
        txt = sess.plan(q).explain()
        assert "anti" in txt and "Apply" not in txt, txt
        assert sess.query(q).rows[0][0] == \
            len(pri) - len(set(lok.tolist()))

    def test_correlated_in_becomes_semi_join(self, sess):
        pri, lok, c, r = _load(sess)
        q = ("SELECT COUNT(*) FROM o WHERE pri IN "
             "(SELECT c FROM l WHERE l.ok = o.ok)")
        txt = sess.plan(q).explain()
        assert "semi" in txt and "Apply" not in txt, txt
        pairs = set(zip(lok.tolist(), c.tolist()))
        want = sum(1 for i in range(len(pri))
                   if (i, int(pri[i])) in pairs)
        assert sess.query(q).rows[0][0] == want

    def test_not_in_keeps_apply_for_null_semantics(self, sess):
        _load(sess)
        txt = sess.plan(
            "SELECT COUNT(*) FROM o WHERE pri NOT IN "
            "(SELECT c FROM l WHERE l.ok = o.ok)").explain()
        assert "Apply" in txt, txt

    def test_not_in_with_inner_nulls_matches_mysql(self, sess):
        sess.execute("CREATE TABLE a (id BIGINT PRIMARY KEY, v BIGINT)")
        sess.execute("CREATE TABLE b (id BIGINT PRIMARY KEY, w BIGINT)")
        sess.execute("INSERT INTO a VALUES (1, 1), (2, 2)")
        sess.execute("INSERT INTO b VALUES (1, 1), (2, NULL)")
        # NULL in the inner set: NOT IN is never TRUE
        r = sess.query("SELECT id FROM a WHERE v NOT IN "
                       "(SELECT w FROM b WHERE b.id >= a.id)")
        assert r.rows == []

    def test_leftover_correlation_falls_back(self, sess):
        _load(sess)
        # non-equality correlation cannot become a hash join key
        txt = sess.plan(
            "SELECT COUNT(*) FROM o WHERE EXISTS "
            "(SELECT 1 FROM l WHERE l.ok = o.ok AND l.c > o.pri)").explain()
        assert "Apply" in txt, txt
        # but it still executes correctly (per-row apply path)
        r = sess.query(
            "SELECT COUNT(*) FROM o WHERE o.ok < 50 AND EXISTS "
            "(SELECT 1 FROM l WHERE l.ok = o.ok AND l.c > o.pri)")
        assert isinstance(r.rows[0][0], int)

    def test_exists_with_extra_outer_filter_and_projection(self, sess):
        pri, lok, c, r = _load(sess)
        q = ("SELECT ok FROM o WHERE pri = 2 AND EXISTS ("
             "SELECT 1 FROM l WHERE l.ok = o.ok AND l.c >= 95) "
             "ORDER BY ok LIMIT 20")
        txt = sess.plan(q).explain()
        assert "semi" in txt, txt
        hot = set(lok[c >= 95].tolist())
        want = sorted(i for i in range(len(pri))
                      if pri[i] == 2 and i in hot)[:20]
        assert [x[0] for x in sess.query(q).rows] == want

    def test_scalar_aggregate_subquery_not_decorrelated(self, sess):
        """EXISTS over a scalar aggregate is ALWAYS true (one row), and
        IN compares per-group — the join rewrite must not fire."""
        sess.execute("CREATE TABLE t (a BIGINT PRIMARY KEY)")
        sess.execute("CREATE TABLE u (x BIGINT PRIMARY KEY, y BIGINT)")
        sess.execute("INSERT INTO t VALUES (1), (2), (3)")
        sess.execute("INSERT INTO u VALUES (1, 10), (2, 20)")
        r = sess.query("SELECT a FROM t WHERE EXISTS "
                       "(SELECT MAX(y) FROM u WHERE u.x = t.a) ORDER BY a")
        assert [x[0] for x in r.rows] == [1, 2, 3]
        r2 = sess.query("SELECT a FROM t WHERE a IN "
                        "(SELECT MAX(x) FROM u WHERE u.x = t.a) ORDER BY a")
        assert [x[0] for x in r2.rows] == [1, 2]
