"""Encoded execution end-to-end (ops/encoded.py, ISSUE 12): code-space
filter translation, join re-keying through code-translation arrays, the
direct-indexed agg's degrade-to-hash boundary, encoded==decoded result
equivalence across filter/join/agg on NULL-heavy / high-cardinality /
shared-dict / mismatched-dict inputs, fallback accounting
({reason="encoding"}), the EXPLAIN ANALYZE encoding-mode note, and
dictionary-code stability across delta patches."""

import numpy as np
import pytest

from tidb_tpu import config, metrics
from tidb_tpu.chunk import Chunk, Column, dict_encode
from tidb_tpu.expression.core import ColumnRef, Constant, Op, func
from tidb_tpu.ops import encoded
from tidb_tpu.ops.hashagg import kernel_for
from tidb_tpu.ops.join import JoinKeyEncoder
from tidb_tpu.session import Session
from tidb_tpu.sqltypes import FieldType, TypeCode, new_string_field
from tidb_tpu.store.storage import new_mock_storage

FT_I = FieldType(tp=TypeCode.LONGLONG)
FT_S = new_string_field()


def _metric(prefix: str) -> float:
    return sum(v for k, v in metrics.snapshot().items()
               if k.startswith(prefix))


def _enc_fallbacks() -> float:
    return sum(v for k, v in metrics.snapshot().items()
               if k.startswith(metrics.DEVICE_FALLBACKS) and
               'reason="encoding"' in k)


def _str_chunk(values, extra_int=None):
    cols = [Column(FT_S,
                   np.array([v if v is not None else "" for v in values],
                            dtype=object),
                   np.array([v is not None for v in values]))]
    if extra_int is not None:
        cols.append(Column(FT_I, np.asarray(extra_int, dtype=np.int64)))
    return Chunk(cols)


class TestTranslateFilter:
    def test_eq_translates_to_code_space(self):
        chunk = _str_chunk(["a", "b", None, "a"])
        f = func(Op.EQ, ColumnRef(0, FT_S, "f"), Constant("a", FT_S))
        t = encoded.translate_filter(f, chunk)
        assert t is not None and t.is_device_safe()
        codes, values = dict_encode(chunk.columns[0])
        d, v = t.eval_xp(np, [(codes, chunk.columns[0].valid)], 4)
        assert list((v & (d != 0)).tolist()) == [True, False, False, True]

    def test_missing_constant_matches_nothing(self):
        chunk = _str_chunk(["a", "b"])
        f = func(Op.EQ, ColumnRef(0, FT_S, "f"), Constant("zz", FT_S))
        t = encoded.translate_filter(f, chunk)
        codes, _ = dict_encode(chunk.columns[0])
        d, v = t.eval_xp(np, [(codes, chunk.columns[0].valid)], 2)
        assert not (v & (d != 0)).any()
        # NE against a missing constant: every valid row passes
        f = func(Op.NE, ColumnRef(0, FT_S, "f"), Constant("zz", FT_S))
        t = encoded.translate_filter(f, chunk)
        d, v = t.eval_xp(np, [(codes, chunk.columns[0].valid)], 2)
        assert (v & (d != 0)).all()

    def test_in_and_logic_mix(self):
        chunk = _str_chunk(["a", "b", "c", None], [1, 2, 3, 4])
        f = func(Op.AND,
                 func(Op.IN, ColumnRef(0, FT_S, "f"),
                      extra=["a", "c", "zz"]),
                 func(Op.GT, ColumnRef(1, FT_I, "i"), Constant(1, FT_I)))
        t = encoded.translate_filter(f, chunk)
        assert t is not None and t.is_device_safe()
        codes, _ = dict_encode(chunk.columns[0])
        cols = [(codes, chunk.columns[0].valid),
                (chunk.columns[1].data, chunk.columns[1].valid)]
        d, v = t.eval_xp(np, cols, 4)
        assert list((v & (d != 0)).tolist()) == [False, False, True,
                                                False]

    def test_is_null_over_codes(self):
        chunk = _str_chunk(["a", None])
        t = encoded.translate_filter(
            func(Op.IS_NULL, ColumnRef(0, FT_S, "f")), chunk)
        codes, _ = dict_encode(chunk.columns[0])
        d, v = t.eval_xp(np, [(codes, chunk.columns[0].valid)], 2)
        assert list((v & (d != 0)).tolist()) == [False, True]

    def test_unsupported_shapes_return_none(self):
        chunk = _str_chunk(["a", "b"])
        ref = ColumnRef(0, FT_S, "f")
        # order comparisons over codes would follow CODE order, not
        # lexical order: must refuse
        assert encoded.translate_filter(
            func(Op.LT, ref, Constant("b", FT_S)), chunk) is None
        assert encoded.translate_filter(
            func(Op.LIKE, ref, Constant("a%", FT_S)), chunk) is None
        # col-vs-col string equality: no constant to pre-encode
        chunk2 = Chunk([chunk.columns[0], chunk.columns[0]])
        assert encoded.translate_filter(
            func(Op.EQ, ref, ColumnRef(1, FT_S, "g")), chunk2) is None

    def test_host_eval_of_code_ref_raises(self):
        chunk = _str_chunk(["a", "b"])
        t = encoded.translate_filter(
            func(Op.EQ, ColumnRef(0, FT_S, "f"), Constant("a", FT_S)),
            chunk)
        ref = t.args[0]
        with pytest.raises(RuntimeError):
            ref.eval(chunk)


class TestCodeTranslation:
    def test_translation_and_null(self):
        src = ["a", "b", "c"]
        dst = ["c", "a"]
        t = encoded.code_translation(src, dst, ci=False)
        codes = np.array([0, 1, 2, -1], dtype=np.int64)
        out = t[codes]
        assert out[0] == 1          # 'a' -> dst code 1
        assert out[1] <= encoded.MISSING_CODE   # 'b' absent
        assert out[2] == 0          # 'c' -> dst code 0
        assert out[3] == -1         # NULL stays NULL

    def test_unmatched_codes_distinct_per_entry(self):
        t = encoded.code_translation(["x", "y"], [], ci=False)
        assert t[0] != t[1] and t[0] <= encoded.MISSING_CODE

    def test_decode_codes_round_trip(self):
        values = ["a", "bb", "ccc"]
        codes = np.array([2, 0, -1, 1], dtype=np.int64)
        out = encoded.decode_codes(values, codes)
        assert list(out) == ["ccc", "a", None, "bb"]


class TestEncoderFastPaths:
    """JoinKeyEncoder's encoded lanes agree with the per-value loop."""

    def _raw(self, vals):
        d = np.array([v if v is not None else "" for v in vals],
                     dtype=object)
        v = np.array([x is not None for x in vals])
        return d, v

    def test_shared_dict_passthrough(self):
        vals = ["a", "b", None, "a", "c"]
        col = _str_chunk(vals).columns[0]
        codes, values = dict_encode(col)
        enc = JoinKeyEncoder(1)
        bk = enc.fit_build([self._raw(vals)],
                           encoded=[(codes, values)], ci=[False])
        pk = enc.transform_probe([self._raw(vals)],
                                 encoded=[(codes, values)])
        # shared dictionary object: codes pass through untranslated
        assert pk[0][0] is codes and bk[0][0] is codes

    def test_mismatched_dicts_rekey_like_raw(self):
        bvals = ["a", "b", "c", None]
        pvals = ["c", "zz", None, "a", "b"]
        bcol = _str_chunk(bvals).columns[0]
        pcol = _str_chunk(pvals).columns[0]
        enc = JoinKeyEncoder(1)
        bk = enc.fit_build([self._raw(bvals)],
                           encoded=[dict_encode(bcol)], ci=[False])
        pk = enc.transform_probe([self._raw(pvals)],
                                 encoded=[dict_encode(pcol)])
        enc2 = JoinKeyEncoder(1)
        bk2 = enc2.fit_build([self._raw(bvals)])
        pk2 = enc2.transform_probe([self._raw(pvals)])
        # identical matching semantics: equal values -> equal codes,
        # absent values negative, NULLs -1
        for j in range(len(pvals)):
            for i in range(len(bvals)):
                match_enc = pk[0][0][j] == bk[0][0][i] and \
                    pk[0][1][j] and bk[0][1][i]
                match_raw = pk2[0][0][j] == bk2[0][0][i] and \
                    pk2[0][1][j] and bk2[0][1][i]
                assert bool(match_enc) == bool(match_raw)
        assert pk[0][0][1] < 0 and pk[0][0][2] == -1

    def test_encoded_build_raw_probe(self):
        """Asymmetric arrival: the lazy mapping from the encoded build
        dictionary serves the raw probe loop."""
        bvals = ["a", "b"]
        bcol = _str_chunk(bvals).columns[0]
        enc = JoinKeyEncoder(1)
        bk = enc.fit_build([self._raw(bvals)],
                           encoded=[dict_encode(bcol)], ci=[False])
        pk = enc.transform_probe([self._raw(["b", "zz", None])])
        assert pk[0][0][0] == bk[0][0][1]
        assert pk[0][0][1] < -1 and pk[0][0][2] == -1


@pytest.fixture(scope="module")
def enc_sess():
    """NULL-heavy, skewed, high-cardinality corpus for the SQL
    property suite; DECIMAL measure so encoded==decoded is exact
    byte-for-byte (scaled-int sums), not approximate."""
    s = Session(new_mock_storage())
    s.execute("CREATE DATABASE enc")
    s.execute("USE enc")
    s.execute("CREATE TABLE t (id BIGINT PRIMARY KEY, f VARCHAR(16), "
              "g VARCHAR(16), amt DECIMAL(12,2), i BIGINT)")
    s.execute("CREATE TABLE dim (id BIGINT PRIMARY KEY, k VARCHAR(16), "
              "seg VARCHAR(8))")
    rng = np.random.default_rng(20260804)
    n = 6000
    rows = []
    for i in range(n):
        # ~20% NULLs, skewed head + high-cardinality tail
        f = None if rng.random() < 0.2 else (
            f"hot{i % 3}" if rng.random() < 0.5 else f"v{i % 997}")
        g = f"g{i % 11}"
        rows.append(f"({i}, "
                    f"{'NULL' if f is None else repr(f)}, '{g}', "
                    f"{rng.integers(0, 99999) / 100}, {i % 53})")
    for i in range(0, n, 500):
        s.execute("INSERT INTO t VALUES " + ",".join(rows[i:i + 500]))
    dim = [f"({i}, 'v{i}', 'seg{i % 5}')" for i in range(400)]
    s.execute("INSERT INTO dim VALUES " + ",".join(dim))
    s.execute("SET tidb_tpu_device_min_rows = 1")
    yield s
    s.close()


def _both(s, q):
    """(encoded rows, decoded rows) for one query — byte-for-byte
    comparable (DECIMAL/int outputs only)."""
    s.execute("SET tidb_tpu_encoded_exec = 1")
    enc = s.query(q).rows
    s.execute("SET tidb_tpu_encoded_exec = 0")
    try:
        dec = s.query(q).rows
    finally:
        s.execute("SET tidb_tpu_encoded_exec = 1")
    return enc, dec


class TestEncodedEqualsDecoded:
    @pytest.mark.parametrize("pred", [
        "f = 'hot1'",
        "f != 'hot1'",
        "f IN ('hot0', 'v13', 'absent')",
        "f = 'no-such-value'",
        "f IS NULL",
        "f IS NOT NULL AND i > 25",
        "f = 'hot2' OR f = 'v41'",
    ])
    def test_filtered_agg(self, enc_sess, pred):
        q = (f"SELECT g, COUNT(*), SUM(amt), MIN(i), MAX(i) FROM t "
             f"WHERE {pred} GROUP BY g ORDER BY g")
        enc, dec = _both(enc_sess, q)
        assert enc == dec

    def test_high_cardinality_group(self, enc_sess):
        q = ("SELECT f, COUNT(*), SUM(amt) FROM t WHERE f IS NOT NULL "
             "GROUP BY f ORDER BY f LIMIT 20")
        enc, dec = _both(enc_sess, q)
        assert enc == dec

    def test_string_key_join(self, enc_sess):
        # mismatched dictionaries: t.f's dict vs dim.k's dict
        q = ("SELECT dim.seg, COUNT(*), SUM(t.amt) FROM t "
             "JOIN dim ON t.f = dim.k GROUP BY dim.seg ORDER BY dim.seg")
        enc, dec = _both(enc_sess, q)
        assert enc == dec

    def test_self_join_shared_dict(self, enc_sess):
        # both sides scan the SAME cached column: one dictionary object
        q = ("SELECT COUNT(*) FROM t a JOIN t b ON a.f = b.f "
             "WHERE a.i = 7 AND b.i = 7")
        enc, dec = _both(enc_sess, q)
        assert enc == dec

    def test_left_join_null_semantics(self, enc_sess):
        q = ("SELECT COUNT(*) FROM t LEFT JOIN dim ON t.f = dim.k "
             "WHERE dim.id IS NULL")
        enc, dec = _both(enc_sess, q)
        assert enc == dec


class TestDegradeBoundary:
    def test_force_hash_past_slots(self):
        groups = [ColumnRef(0, FT_S, "f")]
        aggs = []
        k_small = kernel_for(None, groups, aggs, capacity=1024)
        assert not k_small.force_hash       # within the direct bound
        k_big = kernel_for(None, groups, aggs, capacity=16384)
        assert k_big.force_hash             # past tidb_tpu_direct_agg_slots

    def test_degraded_results_match(self, enc_sess):
        s = enc_sess
        prev = config.get_var("tidb_tpu_direct_agg_slots")
        q = ("SELECT f, COUNT(*) FROM t WHERE f IS NOT NULL "
             "GROUP BY f ORDER BY f LIMIT 15")
        want = s.query(q).rows
        try:
            # bound far below the distinct-f domain: every direct-mode
            # kernel degrades to the packed-sort hash table
            s.execute("SET tidb_tpu_direct_agg_slots = 16")
            got = s.query(q).rows
        finally:
            s.execute(f"SET tidb_tpu_direct_agg_slots = {prev}")
        assert got == want


class TestFallbackAccounting:
    def test_unsupported_filter_counts_encoding_reason(self, enc_sess):
        s = enc_sess
        fb0 = _enc_fallbacks()
        rows = s.query("SELECT g, COUNT(*) FROM t WHERE f LIKE 'hot%' "
                       "GROUP BY g ORDER BY g").rows
        assert rows          # sane result through the decoded path
        assert _enc_fallbacks() > fb0

    def test_supported_filter_does_not_count(self, enc_sess):
        s = enc_sess
        fb0 = _enc_fallbacks()
        s.query("SELECT g, COUNT(*) FROM t WHERE f = 'hot0' GROUP BY g")
        assert _enc_fallbacks() == fb0


class TestExplainEncodingMode:
    def test_enc_note_in_pipeline_column(self, enc_sess):
        s = enc_sess
        r = s.query("EXPLAIN ANALYZE SELECT g, COUNT(*) FROM t "
                    "WHERE f = 'hot0' GROUP BY g")
        pc = r.columns.index("pipeline")
        cell = next(row[pc] for row in r.rows
                    if "TableReader" in row[0])
        assert "enc=" in cell and ("direct-agg" in cell or
                                   "encoded" in cell)

    def test_decoded_note_when_translation_fails(self, enc_sess):
        s = enc_sess
        r = s.query("EXPLAIN ANALYZE SELECT g, COUNT(*) FROM t "
                    "WHERE f LIKE 'hot%' GROUP BY g")
        pc = r.columns.index("pipeline")
        cell = next(row[pc] for row in r.rows
                    if "TableReader" in row[0])
        assert "enc=decoded" in cell


class TestDeltaCodeStability:
    """PR 11 pins delta patches extending HBM-block dictionaries in
    place; encoded filters must encode constants against the EXTENDED
    dictionary (code stability: old codes keep their values, new
    values append)."""

    @pytest.fixture()
    def delta_sess(self):
        s = Session(new_mock_storage())
        s.execute("CREATE DATABASE encd")
        s.execute("USE encd")
        s.execute("CREATE TABLE w (id BIGINT PRIMARY KEY, "
                  "f VARCHAR(16), v BIGINT)")
        vals = ",".join(f"({i}, 'k{i % 5}', {i})" for i in range(4096))
        s.execute("INSERT INTO w VALUES " + vals)
        s.execute("SET tidb_tpu_device_min_rows = 1")
        yield s
        s.close()

    def test_codes_stable_across_delta_patch(self, delta_sess):
        s = delta_sess
        q_old = ("SELECT COUNT(*), SUM(v) FROM w WHERE f = 'k1'")
        base = s.query(q_old).rows
        s.query(q_old)          # warm: HBM block + dicts resident
        # the delta introduces a BRAND-NEW dictionary value: the block's
        # dict must extend (not re-encode), and the encoded filter must
        # find the appended code
        s.execute("UPDATE w SET f = 'brandnew' WHERE id = 7")
        fb0 = _enc_fallbacks()
        got_new = s.query(
            "SELECT COUNT(*), SUM(v) FROM w WHERE f = 'brandnew'").rows
        assert got_new == [(1, 7)]
        got_old = s.query(q_old).rows
        assert got_old[0][0] == base[0][0] - (1 if 7 % 5 == 1 else 0)
        assert _enc_fallbacks() == fb0
        # and the unfiltered totals stay exact across the patch
        tot = s.query("SELECT COUNT(*) FROM w").rows
        assert tot == [(4096,)]

    def test_background_merge_keeps_results(self, delta_sess):
        s = delta_sess
        q = "SELECT f, COUNT(*) FROM w WHERE f != 'k3' GROUP BY f " \
            "ORDER BY f"
        s.query(q)
        for i in range(0, 600, 7):
            s.execute(f"UPDATE w SET f = 'moved' WHERE id = {i}")
        s.execute("SET tidb_tpu_device = 0")
        try:
            want = s.query(q).rows
        finally:
            s.execute("SET tidb_tpu_device = 1")
        assert s.query(q).rows == want
