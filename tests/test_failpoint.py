"""Unified fault-injection subsystem (tidb_tpu/util/failpoint.py) and
the device-plane recovery machinery it proves out (tidb_tpu/sched.py
DispatchWatchdog + DeviceHealth, util/supervisor.py).

Covers: the registry/spec/arming surfaces (enable/disable, budgets,
1-in-N periods, callables, env-format bulk arming, the SET-style
sysvar, the POST /failpoint endpoint); the dispatch watchdog
cancelling a slow finalize with the RETRYABLE ER_DEVICE_FAULT while
slots and ledgers drain; the device fault chain (retry once via the
Backoffer → degrade the statement to the host path → quarantine the
device, shed HBM residency, re-probe and readmit); the background-
worker supervisor restarting crashed workers with counted restarts;
and mid-resultset wire teardown leaving the server healthy. Every test
runs under the ledger_hygiene fixture (tests/conftest.py): SERVER
memtrack ledgers and scheduler slots must be zero afterwards."""

import threading
import time

import pytest

from tidb_tpu import config, errcode, memtrack, metrics, sched
from tidb_tpu.session import Session, SQLError
from tidb_tpu.store.storage import new_mock_storage
from tidb_tpu.util import failpoint, supervisor

pytestmark = pytest.mark.usefixtures("ledger_hygiene")

N_ROWS = 3000


def q(s, sql):
    return s.query(sql).rows


def counter(name, labels=""):
    return int(metrics.snapshot().get(name + labels, 0))



def fallbacks(reason):
    """Sum tidb_tpu_device_fallback_total across ops for one reason."""
    snap = metrics.snapshot()
    return int(sum(v for k, v in snap.items()
                   if k.startswith(metrics.DEVICE_FALLBACKS)
                   and f'reason="{reason}"' in k))

_VARS = ("tidb_tpu_device", "tidb_tpu_device_min_rows",
         "tidb_tpu_dispatch_timeout_ms", "tidb_tpu_failpoints",
         "tidb_tpu_copr_stream")


@pytest.fixture
def sysvars():
    old = {k: config.get_var(k) for k in _VARS}
    config.set_var("tidb_tpu_device_min_rows", 1)
    yield
    failpoint.disable_all()
    sched.device_health().note_ok()     # leave no quarantine behind
    for k, v in old.items():
        config.set_var(k, v)


@pytest.fixture
def sess(sysvars):
    st = new_mock_storage()
    s = Session(st)
    s.execute("CREATE DATABASE d")
    s.execute("USE d")
    s.execute("CREATE TABLE t (id BIGINT PRIMARY KEY, v BIGINT, "
              "s VARCHAR(16))")
    rows = [f"({i},{(i * 37) % 500},'k{i % 23}')"
            for i in range(N_ROWS)]
    s.execute("INSERT INTO t VALUES " + ",".join(rows))
    info = s.domain.info_schema().table("d", "t")
    st.cluster.split_table(info.id, 4, max_handle=N_ROWS)
    yield s, st
    s.close()
    st.close()


AGG = "SELECT s, COUNT(*), SUM(v) FROM t GROUP BY s ORDER BY s"


# -- registry / spec / arming surfaces ---------------------------------------

class TestRegistry:
    def test_disarmed_eval_is_none_and_free(self):
        assert failpoint.eval("hbm/fill") is None

    def test_spec_raise_budget(self):
        failpoint.enable("hbm/fill", "2*raise(DeviceFaultError:boom)")
        for _ in range(2):
            with pytest.raises(failpoint.DeviceFaultError,
                               match="boom"):
                failpoint.eval("hbm/fill")
        # budget exhausted: self-disarmed
        assert failpoint.eval("hbm/fill") is None
        assert "hbm/fill" not in failpoint.armed()

    def test_spec_one_in_n_is_deterministic(self):
        failpoint.enable("hbm/fill", "1-in-3:return(7)")
        got = [failpoint.eval("hbm/fill") for _ in range(9)]
        assert got == [None, None, 7] * 3
        failpoint.disable("hbm/fill")

    def test_spec_delay(self):
        failpoint.enable("hbm/fill", "delay(30)")
        t0 = time.perf_counter()
        assert failpoint.eval("hbm/fill") is None
        assert time.perf_counter() - t0 >= 0.025
        failpoint.disable("hbm/fill")

    def test_callable_action_gets_args(self):
        got = []
        failpoint.enable("rpc/request",
                         lambda cmd, ctx: got.append(cmd))
        failpoint.eval("rpc/request", "Get", None)
        failpoint.disable("rpc/request")
        assert got == ["Get"]

    def test_unknown_name_and_bad_specs_fail_loudly(self):
        with pytest.raises(failpoint.UnknownFailpointError):
            failpoint.enable("no/such/point", "raise")
        for bad in ("explode", "raise(NoSuchExc)", "delay(abc)",
                    "0*raise", "1-in-0:raise", "return()"):
            with pytest.raises(failpoint.BadFailpointSpecError):
                failpoint.parse_spec(bad)
        with pytest.raises(failpoint.BadFailpointSpecError):
            failpoint.arm_from_string("hbm/fill")   # no '='

    def test_bulk_arming_env_format(self):
        names = failpoint.arm_from_string(
            "hbm/fill=raise; delta/merge=delay(1)")
        assert set(names) == {"hbm/fill", "delta/merge"}
        assert set(failpoint.armed()) == {"hbm/fill", "delta/merge"}
        failpoint.disable_all()

    def test_bulk_arming_is_atomic(self):
        """A bad entry anywhere in the list arms NOTHING — a rejected
        SET must not half-apply faults it then cannot disarm."""
        with pytest.raises(failpoint.UnknownFailpointError):
            failpoint.arm_from_string("hbm/fill=raise;typo/x=raise")
        assert failpoint.armed() == {}
        with pytest.raises(failpoint.BadFailpointSpecError):
            failpoint.arm_from_string("hbm/fill=raise;hbm/patch=bogus")
        assert failpoint.armed() == {}

    def test_rejected_sysvar_set_rolls_back(self, sysvars):
        prev = config.get_var("tidb_tpu_failpoints")
        with pytest.raises(failpoint.UnknownFailpointError):
            config.set_var("tidb_tpu_failpoints",
                           "hbm/fill=raise;typo/x=raise")
        # nothing armed, and the registry still reads the old value
        assert failpoint.armed() == {}
        assert config.get_var("tidb_tpu_failpoints") == prev

    def test_fired_metric_counts_by_name(self):
        before = counter(metrics.FAILPOINT_FIRES, '{name="hbm/fill"}')
        failpoint.enable("hbm/fill", "return(1)")
        failpoint.eval("hbm/fill")
        failpoint.disable("hbm/fill")
        assert counter(metrics.FAILPOINT_FIRES,
                       '{name="hbm/fill"}') == before + 1

    def test_sysvar_set_is_declarative(self, sysvars):
        config.set_var("tidb_tpu_failpoints", "hbm/fill=raise")
        assert "hbm/fill" in failpoint.armed()
        # replacing the SET-armed set disarms the old name...
        config.set_var("tidb_tpu_failpoints", "hbm/patch=return(1)")
        assert "hbm/fill" not in failpoint.armed()
        assert "hbm/patch" in failpoint.armed()
        # ...but never touches points armed via other surfaces
        failpoint.enable("delta/merge", "delay(1)")
        config.set_var("tidb_tpu_failpoints", "")
        assert failpoint.armed().keys() == {"delta/merge"}
        failpoint.disable_all()

    def test_sql_set_global_arms(self, sess):
        s, _st = sess
        s.execute("SET GLOBAL tidb_tpu_failpoints = 'hbm/fill=raise'")
        assert "hbm/fill" in failpoint.armed()
        s.execute("SET GLOBAL tidb_tpu_failpoints = ''")
        assert "hbm/fill" not in failpoint.armed()

    def test_sql_session_scope_set_rejected(self, sess):
        """A session-scope SET would shadow the spec on one thread
        while arming NOTHING — the silently-green chaos run. It must
        reject with ER_GLOBAL_VARIABLE, and arm nothing."""
        s, _st = sess
        with pytest.raises(SQLError) as ei:
            s.execute("SET tidb_tpu_failpoints = 'hbm/fill=raise'")
        assert errcode.classify(ei.value)[0] == \
            errcode.ER_GLOBAL_VARIABLE
        assert failpoint.armed() == {}


class TestStatusEndpoint:
    def test_post_arms_get_lists_disarm(self, sess):
        import json
        import urllib.error

        from tidb_tpu.server.status import StatusServer
        from tidb_tpu.util import statusclient
        _s, st = sess
        srv = StatusServer(st)
        srv.start()
        try:
            def post(body):
                try:
                    return 200, statusclient.post_json(
                        "127.0.0.1", srv.port, "/failpoint", body)
                except urllib.error.HTTPError as e:
                    return e.code, json.loads(e.read())

            code, out = post({"name": "hbm/fill", "spec": "2*raise"})
            assert code == 200 and "hbm/fill" in out["armed"]
            listing = statusclient.get_json("127.0.0.1", srv.port,
                                            "/failpoint")
            assert listing["registry"] == failpoint.REGISTRY
            assert "hbm/fill" in listing["armed"]
            code, out = post({"name": "hbm/fill", "spec": None})
            assert code == 200 and out["armed"] == {}
            code, out = post({"name": "nope/nope", "spec": "raise"})
            assert code == 404
            code, out = post({"name": "hbm/fill", "spec": "garbage("})
            assert code == 400
        finally:
            srv.close()
            failpoint.disable_all()


# -- dispatch watchdog -------------------------------------------------------

class TestWatchdog:
    def test_slow_finalize_cancels_retryable(self, sess):
        s, _st = sess
        want = q(s, AGG)
        config.set_var("tidb_tpu_dispatch_timeout_ms", 120)
        failpoint.enable("device/finalize", "delay(400)")
        before = counter(metrics.DISPATCH_TIMEOUTS)
        try:
            # DispatchTimeoutError or (when the cooperative kill wins
            # the race on the issuing thread) the rewritten SQLError —
            # both classify to the retryable 9009
            with pytest.raises(Exception) as ei:
                q(s, AGG)
        finally:
            failpoint.disable("device/finalize")
            config.set_var("tidb_tpu_dispatch_timeout_ms", 0)
        code, _state, msg = errcode.classify(ei.value)
        assert code == errcode.ER_DEVICE_FAULT
        assert errcode.is_retryable(code)
        assert "watchdog" in msg
        assert counter(metrics.DISPATCH_TIMEOUTS) > before
        # the session survives and the replay (faults disarmed) is clean
        assert q(s, AGG) == want

    def test_slow_sync_dispatch_also_watched(self, sess):
        s, _st = sess
        config.set_var("tidb_tpu_dispatch_timeout_ms", 100)
        failpoint.enable("sched/slot", "delay(350)")
        try:
            with pytest.raises(Exception) as ei:
                q(s, AGG)
        finally:
            failpoint.disable("sched/slot")
            config.set_var("tidb_tpu_dispatch_timeout_ms", 0)
        assert errcode.classify(ei.value)[0] == errcode.ER_DEVICE_FAULT

    def test_watchdog_off_by_default_no_thread(self, sess):
        s, _st = sess
        assert config.dispatch_timeout_ms() == 0
        q(s, AGG)
        assert sched.dispatch_watchdog().snapshot()["watching"] == 0


# -- device fault chain: retry -> degrade -> quarantine ----------------------

class TestDeviceFaults:
    def test_single_fault_retries_and_succeeds(self, sess):
        s, _st = sess
        want = q(s, AGG)
        fb = fallbacks("fault")
        failpoint.enable("device/dispatch",
                         "1*raise(DeviceFaultError)")
        got = q(s, AGG)
        failpoint.disable("device/dispatch")
        assert got == want
        # one fault, one retry, zero fallbacks: stays on device
        assert fallbacks("fault") == fb

    def test_persistent_fault_degrades_statement_to_host(self, sess):
        s, _st = sess
        want = q(s, AGG)
        fb = fallbacks("fault")
        failpoint.enable("device/dispatch", "raise(DeviceFaultError)")
        try:
            got = q(s, AGG)
        finally:
            failpoint.disable("device/dispatch")
        sched.device_health().note_ok()     # cleanup any quarantine
        assert got == want                  # correct answer, host path
        assert fallbacks("fault") > fb

    def test_hbm_fill_fault_is_absorbed(self, sess):
        s, _st = sess
        want = q(s, AGG)
        failpoint.enable("hbm/fill", "raise(DeviceFaultError)")
        try:
            got = q(s, AGG)
        finally:
            failpoint.disable("hbm/fill")
        sched.device_health().note_ok()
        assert got == want

    def test_quarantine_sheds_hbm_and_reprobes(self, sess):
        s, st = sess
        want = q(s, AGG)                    # warm: HBM block resident
        health = sched.DeviceHealth()
        # unit-level: 3 consecutive faults quarantine, the probe window
        # admits exactly one dispatch, success readmits
        qcount = counter(metrics.DEVICE_QUARANTINES,
                         '{event="quarantine"}')
        for _ in range(3):
            assert health.available()
            health.note_fault()
        assert not health.available()       # quarantined, window open
        assert counter(metrics.DEVICE_QUARANTINES,
                       '{event="quarantine"}') == qcount + 1
        # quarantine invalidated the resident HBM plane
        from tidb_tpu.store import device_cache as dc
        assert dc.tracker().device == 0
        snap = health.snapshot()
        assert snap["quarantined"] and snap["quarantines"] == 1
        # fast-forward the window: one probe is admitted, others queued
        health._probe_at = time.monotonic() - 0.01
        assert health.available()           # the probe
        assert not health.available()       # everyone else: host path
        health.note_ok()                    # probe succeeded
        assert not health.snapshot()["quarantined"]
        assert counter(metrics.DEVICE_QUARANTINES,
                       '{event="readmit"}') >= 1
        # serving recovers end-to-end (cache refills)
        assert q(s, AGG) == want

    def test_end_to_end_quarantine_via_sql(self, sess):
        s, _st = sess
        want = q(s, AGG)
        failpoint.enable("device/dispatch", "raise(DeviceFaultError)")
        try:
            # each statement pays fault+retry then degrades; multiple
            # statements push consecutive faults past the threshold
            for _ in range(3):
                assert q(s, AGG) == want
            assert sched.device_health().snapshot()["quarantined"]
            # while quarantined, statements skip the device entirely
            fb = fallbacks("quarantine")
            assert q(s, AGG) == want
            assert fallbacks("quarantine") > fb
        finally:
            failpoint.disable("device/dispatch")
        # past the window the probe dispatch readmits the device
        sched.device_health()._probe_at = time.monotonic() - 0.01
        assert q(s, AGG) == want
        assert not sched.device_health().snapshot()["quarantined"]


# -- worker supervisor -------------------------------------------------------

class TestSupervisor:
    def test_crashing_beat_restarts_with_metric(self):
        calls = {"n": 0}
        stop = threading.Event()

        def beat():
            calls["n"] += 1
            if calls["n"] <= 2:
                raise RuntimeError("injected crash")

        before = counter(metrics.WORKER_RESTARTS,
                         '{worker="test-worker"}')
        t = supervisor.supervise("test-worker", beat, stop,
                                 interval=0.01)
        deadline = time.time() + 5.0
        while calls["n"] < 4 and time.time() < deadline:
            time.sleep(0.01)
        stop.set()
        t.join(timeout=6.0)
        assert calls["n"] >= 4              # survived both crashes
        assert counter(metrics.WORKER_RESTARTS,
                       '{worker="test-worker"}') == before + 2

    def test_worker_tick_failpoint_crashes_by_name(self):
        stop = threading.Event()
        beats = []
        failpoint.enable(
            "worker/tick",
            lambda name: (_ for _ in ()).throw(RuntimeError(name))
            if name == "fp-worker" else None)
        before = counter(metrics.WORKER_RESTARTS,
                         '{worker="fp-worker"}')
        t = supervisor.supervise("fp-worker", lambda: beats.append(1),
                                 stop, interval=0.01)
        deadline = time.time() + 5.0
        while counter(metrics.WORKER_RESTARTS,
                      '{worker="fp-worker"}') < before + 2 and \
                time.time() < deadline:
            time.sleep(0.01)
        failpoint.disable("worker/tick")
        # disarmed: the worker beats normally again
        deadline = time.time() + 5.0
        while not beats and time.time() < deadline:
            time.sleep(0.01)
        stop.set()
        t.join(timeout=6.0)
        assert beats, "worker never recovered after disarm"
        assert counter(metrics.WORKER_RESTARTS,
                       '{worker="fp-worker"}') >= before + 2

    def test_run_once_retries_then_gives_up_loudly(self):
        calls = {"n": 0}

        def job_flaky():
            calls["n"] += 1
            if calls["n"] < 2:
                raise RuntimeError("first attempt dies")

        assert supervisor.run_once("flaky-job", job_flaky, retries=2)
        assert calls["n"] == 2

        def job_dead():
            raise RuntimeError("always dies")

        before = counter(metrics.WORKER_RESTARTS,
                         '{worker="dead-job"}')
        assert not supervisor.run_once("dead-job", job_dead, retries=1)
        assert counter(metrics.WORKER_RESTARTS,
                       '{worker="dead-job"}') == before + 2

    def test_delta_merge_crash_restarts_and_merges(self, sess):
        s, st = sess
        # force some staged deltas, crash the first merge attempt
        failpoint.enable("delta/merge", "1*raise(RuntimeError:crash)")
        restarts = counter(metrics.WORKER_RESTARTS,
                           '{worker="delta-merge"}')
        try:
            for i in range(8):
                s.execute(f"UPDATE t SET v = v + 1 WHERE id = {i}")
            assert st.delta_store.rows_current() > 0
            # the shed-path merge runs synchronously through run_once's
            # caller-side machinery? No: drive a merge directly through
            # the supervisor, as the trigger thread does
            from tidb_tpu.util.supervisor import run_once
            assert run_once("delta-merge",
                            lambda: st.delta_store.merge("rows"))
        finally:
            failpoint.disable("delta/merge")
        assert counter(metrics.WORKER_RESTARTS,
                       '{worker="delta-merge"}') == restarts + 1
        assert st.delta_store.rows_current() == 0


# -- wire teardown mid-resultset ---------------------------------------------

class TestWireTeardown:
    def test_teardown_mid_resultset_server_survives(self, sysvars):
        import sys
        sys.path.insert(0, "tests")
        from mysql_client import MiniClient

        from tidb_tpu.server import Server
        st = new_mock_storage()
        s = Session(st)
        s.execute("CREATE DATABASE w")
        s.execute("USE w")
        s.execute("CREATE TABLE r (a BIGINT PRIMARY KEY)")
        s.execute("INSERT INTO r VALUES " +
                  ",".join(f"({i})" for i in range(64)))
        server = Server(st)
        server.start()
        try:
            # kill the connection after 5 rows shipped
            def teardown(conn, n):
                if n == 5:
                    conn.sock.close()

            failpoint.enable("wire/resultset", teardown)
            c = MiniClient("127.0.0.1", server.port, db="w")
            c.sock.settimeout(10)
            with pytest.raises(Exception):
                c.query("SELECT a FROM r ORDER BY a")
            failpoint.disable("wire/resultset")
            try:
                c.close()
            except Exception:
                pass
            # the server keeps serving new connections, full resultset
            c2 = MiniClient("127.0.0.1", server.port, db="w")
            _cols, rows = c2.query("SELECT a FROM r ORDER BY a")
            assert [int(r[0]) for r in rows] == list(range(64))
            c2.close()
        finally:
            failpoint.disable("wire/resultset")
            server.close()
            s.close()
            st.close()


# -- retryable classification pin --------------------------------------------

class TestRetryableContract:
    def test_device_fault_code_is_retryable_9xxx(self):
        assert errcode.ER_DEVICE_FAULT == 9009
        assert errcode.is_retryable(errcode.ER_DEVICE_FAULT)
        code, state, _ = errcode.classify(
            failpoint.DeviceFaultError("device fault: injected"))
        assert (code, state) == (errcode.ER_DEVICE_FAULT, "HY000")

    def test_watchdog_message_classifies_as_device_fault(self):
        # the cooperative-kill rewrite path surfaces the watchdog's
        # message as a plain SQLError: the pattern net must route it to
        # 9009, not the generic ER_QUERY_INTERRUPTED
        code, _state, _ = errcode.classify(SQLError(
            "device fault: dispatch watchdog — pipeline-finalize "
            "exceeded tidb_tpu_dispatch_timeout_ms=100ms; statement "
            "cancelled (retryable)"))
        assert code == errcode.ER_DEVICE_FAULT
