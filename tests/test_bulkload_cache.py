"""Bulk load (vectorized offline import), columnar chunk cache MVCC
semantics, sysvar-backed config, and the vectorized host operators."""

import numpy as np
import pytest

from tidb_tpu import config, tablecodec
from tidb_tpu.session import Session
from tidb_tpu.store.storage import new_mock_storage
from tidb_tpu.table import Table, bulkload


@pytest.fixture
def sess():
    st = new_mock_storage()
    s = Session(st)
    s.execute("CREATE DATABASE d")
    s.execute("USE d")
    yield s
    s.close()


def _table(sess, name):
    return Table(sess.domain.info_schema().table("d", name), sess.storage)


class TestBulkLoad:
    def test_roundtrip_matches_scalar_encoder(self, sess):
        sess.execute("CREATE TABLE t (a BIGINT PRIMARY KEY, b BIGINT, "
                     "c DOUBLE, d DECIMAL(12,2), e VARCHAR(10), f DATE)")
        ti = sess.domain.info_schema().table("d", "t")
        n = 500
        rng = np.random.default_rng(1)
        a = np.arange(n, dtype=np.int64)
        b = rng.integers(-500, 500, n)
        bv = rng.random(n) > 0.2
        c = rng.standard_normal(n) * 100
        dd = rng.integers(-10**6, 10**6, n)
        from tidb_tpu.sqltypes import parse_datetime
        segs = np.array(["AUTO", "BUILD", "x" * 9, ""], dtype=object)
        e = segs[rng.integers(0, 4, n)]
        ev = rng.random(n) > 0.2
        f = parse_datetime("1994-01-01") + \
            rng.integers(0, 2000, n) * 86_400_000_000
        bulkload.bulk_load(sess.storage, _table(sess, "t"), {
            "a": a, "b": (b, bv), "c": c, "d": dd, "e": (e, ev), "f": f})

        snap = sess.storage.current_ts()
        cols = ti.public_columns()
        byname = {x.name.lower(): x for x in cols}
        cids = sorted(x.id for x in cols)
        for i in (0, 3, 499):
            got = sess.storage.engine.get(
                tablecodec.record_key(ti.id, int(a[i])), snap)
            vals = {byname["a"].id: int(a[i]),
                    byname["b"].id: int(b[i]) if bv[i] else None,
                    byname["c"].id: float(c[i]),
                    byname["d"].id: (2, int(dd[i])),
                    byname["e"].id: str(e[i]) if ev[i] else None,
                    byname["f"].id: int(f[i])}
            want = tablecodec.encode_row(cids, [vals[c2] for c2 in cids])
            assert got == want

        r = sess.query("SELECT COUNT(*), SUM(b) FROM t")
        assert r.rows[0] == (n, int(b[bv].sum()))

    def test_visible_through_sql_and_dml_after(self, sess):
        sess.execute("CREATE TABLE t (a BIGINT PRIMARY KEY, b BIGINT)")
        bulkload.bulk_load(sess.storage, _table(sess, "t"),
                           {"a": np.arange(100), "b": np.arange(100) * 2})
        # ordinary DML interleaves correctly with imported rows
        sess.execute("INSERT INTO t VALUES (100, 7)")
        sess.execute("UPDATE t SET b = 0 WHERE a = 3")
        sess.execute("DELETE FROM t WHERE a = 4")
        r = sess.query("SELECT COUNT(*), SUM(b) FROM t")
        want_sum = sum(i * 2 for i in range(100)) - 6 - 8 + 7
        assert r.rows[0] == (100, want_sum)

    def test_autoid_rebased_past_imported_handles(self, sess):
        sess.execute("CREATE TABLE t (a BIGINT PRIMARY KEY AUTO_INCREMENT,"
                     " b BIGINT)")
        bulkload.bulk_load(sess.storage, _table(sess, "t"),
                           {"a": np.arange(1, 51), "b": np.zeros(50,
                                                                 np.int64)})
        sess.execute("INSERT INTO t (b) VALUES (1)")
        r = sess.query("SELECT MAX(a) FROM t")
        assert r.rows[0][0] > 50

    def test_rejects_dup_and_secondary_index(self, sess):
        sess.execute("CREATE TABLE t (a BIGINT PRIMARY KEY, b BIGINT)")
        from tidb_tpu import kv
        with pytest.raises(kv.KVError, match="duplicate"):
            bulkload.bulk_load(sess.storage, _table(sess, "t"),
                               {"a": np.array([1, 1]),
                                "b": np.array([1, 2])})
        sess.execute("CREATE TABLE u (a BIGINT PRIMARY KEY, b BIGINT)")
        sess.execute("CREATE INDEX ib ON u (b)")
        with pytest.raises(kv.KVError, match="secondary"):
            bulkload.bulk_load(sess.storage, _table(sess, "u"),
                               {"a": np.array([1]), "b": np.array([2])})


class TestChunkCacheMVCC:
    def test_hot_scan_hits_cache(self, sess):
        sess.execute("CREATE TABLE t (a BIGINT PRIMARY KEY, b BIGINT)")
        bulkload.bulk_load(sess.storage, _table(sess, "t"),
                           {"a": np.arange(50), "b": np.arange(50)})
        sess.query("SELECT SUM(b) FROM t")
        cc = sess.storage.chunk_cache
        cc.hits = cc.misses = 0
        assert sess.query("SELECT SUM(b) FROM t").rows[0][0] == 49 * 25
        assert cc.hits >= 1

    def test_write_invalidates(self, sess):
        sess.execute("CREATE TABLE t (a BIGINT PRIMARY KEY, b BIGINT)")
        sess.execute("INSERT INTO t VALUES (1, 1), (2, 2)")
        assert sess.query("SELECT SUM(b) FROM t").rows[0][0] == 3
        sess.execute("INSERT INTO t VALUES (3, 10)")
        assert sess.query("SELECT SUM(b) FROM t").rows[0][0] == 13

    def test_old_snapshot_fill_does_not_poison_new_readers(self, sess):
        """A txn holding an old snapshot re-scans after a newer commit;
        its (correct-for-it) stale view must not be served to newer
        readers. Regression: the fill-ts-covers-max-commit-ts rule."""
        sess.execute("CREATE TABLE t (id BIGINT PRIMARY KEY, v BIGINT)")
        sess.execute("INSERT INTO t VALUES (1, 1)")
        s2 = Session(sess.storage, db="d")
        s2.execute("BEGIN")
        assert s2.query("SELECT v FROM t").rows == [(1,)]
        sess.execute("UPDATE t SET v = 2 WHERE id = 1")
        # s2's re-scan at its old snapshot: still 1, and must NOT cache
        assert s2.query("SELECT v FROM t").rows == [(1,)]
        s2.execute("COMMIT")
        assert s2.query("SELECT v FROM t").rows == [(2,)]
        assert sess.query("SELECT v FROM t").rows == [(2,)]
        s2.close()


class TestConfigSysvars:
    def test_set_and_show(self, sess):
        g0 = config.cop_concurrency()
        sess.execute("SET @@tidb_tpu_cop_concurrency = 3")
        # session scope shadows; the process registry is untouched
        assert config.cop_concurrency() == g0
        rows = dict(sess.query("SHOW VARIABLES LIKE 'tidb_tpu%'").rows)
        assert rows["tidb_tpu_cop_concurrency"] == "3"
        sess.execute("SET GLOBAL tidb_tpu_cop_concurrency = 10")
        assert config.cop_concurrency() == 10
        # session value still wins in this session's view
        rows = dict(sess.query("SHOW VARIABLES LIKE 'tidb_tpu%'").rows)
        assert rows["tidb_tpu_cop_concurrency"] == "3"
        config.set_var("tidb_tpu_cop_concurrency", g0)

    def test_device_switch_changes_path_not_results(self, sess):
        sess.execute("CREATE TABLE t (a BIGINT PRIMARY KEY, b BIGINT)")
        bulkload.bulk_load(
            sess.storage, _table(sess, "t"),
            {"a": np.arange(5000), "b": np.arange(5000) % 7})
        q = "SELECT b, COUNT(*) FROM t GROUP BY b ORDER BY b"
        sess.execute("SET @@tidb_tpu_device = 0")
        try:
            host = sess.query(q).rows
        finally:
            sess.execute("SET @@tidb_tpu_device = 1")
        dev = sess.query(q).rows
        assert host == dev

    def test_unknown_value_rejected(self, sess):
        from tidb_tpu.session import SQLError
        with pytest.raises(SQLError):
            sess.execute("SET @@tidb_tpu_device = 'banana'")


class TestHostOps:
    def test_host_match_pairs_vs_dict(self):
        from tidb_tpu.ops.join import host_match_pairs
        rng = np.random.default_rng(0)
        nb, npr = 800, 1200
        bkey = rng.integers(0, 300, nb)
        pkey = rng.integers(0, 400, npr)
        bv = rng.random(nb) > 0.1
        pv = rng.random(npr) > 0.1
        li, ri = host_match_pairs([(bkey, bv)], [(pkey, pv)], nb, npr)
        want = set()
        from collections import defaultdict
        d = defaultdict(list)
        for i in range(nb):
            if bv[i]:
                d[bkey[i]].append(i)
        for i in range(npr):
            if pv[i]:
                for r in d.get(pkey[i], []):
                    want.add((i, r))
        assert set(zip(li.tolist(), ri.tolist())) == want

    def test_vectorized_hostagg_matches_rowloop(self):
        from tidb_tpu.chunk import Chunk, Column
        from tidb_tpu.expression import AggDesc, AggFunc, ColumnRef
        from tidb_tpu.ops.hostagg import (_host_agg_rowloop,
                                          _host_agg_vectorized,
                                          host_hash_agg)
        from tidb_tpu.ops.hashagg import HashAggregator
        from tidb_tpu.sqltypes import (new_double_field, new_int_field,
                                       new_string_field)
        rng = np.random.default_rng(3)
        n = 2000
        g1 = Column(new_int_field(), rng.integers(0, 9, n),
                    rng.random(n) > 0.1)
        g2 = Column(new_string_field(5),
                    np.array(["a", "bb", "c"], dtype=object)[
                        rng.integers(0, 3, n)],
                    rng.random(n) > 0.1)
        v1 = Column(new_double_field(), rng.standard_normal(n),
                    rng.random(n) > 0.2)
        v2 = Column(new_int_field(), rng.integers(-50, 50, n),
                    rng.random(n) > 0.2)
        ch = Chunk([g1, g2, v1, v2])
        groups = [ColumnRef(0, g1.ft), ColumnRef(1, g2.ft)]
        aggs = [AggDesc(AggFunc.COUNT, None),
                AggDesc(AggFunc.SUM, ColumnRef(2, v1.ft)),
                AggDesc(AggFunc.AVG, ColumnRef(2, v1.ft)),
                AggDesc(AggFunc.MIN, ColumnRef(3, v2.ft)),
                AggDesc(AggFunc.MAX, ColumnRef(3, v2.ft)),
                AggDesc(AggFunc.FIRST_ROW, ColumnRef(1, g2.ft))]
        mask = np.ones(n, dtype=bool)
        out_v = HashAggregator(aggs)
        out_v.update(_host_agg_vectorized(ch, mask, groups, aggs))
        out_r = HashAggregator(aggs)
        out_r.update(_host_agg_rowloop(ch, mask, groups, aggs))
        rv, rr = out_v.results(), out_r.results()
        assert len(rv) == len(rr)
        for (kv_, vv_), (kr, vr) in zip(rv, rr):
            assert kv_ == kr
            for x, y in zip(vv_, vr):
                if isinstance(y, float):
                    assert x == pytest.approx(y)
                else:
                    assert x == y
        # empty-mask path keeps lane shapes merge-compatible
        empty = host_hash_agg(ch, None, groups, aggs)
        assert empty is not None


class TestFilterMemo:
    """Filtered cop results memoize on the cached raw chunk: hot scans
    return identical chunk objects so device memos keep hitting."""

    def test_hot_filtered_scan_returns_same_objects(self, sess):
        import numpy as np
        sess.execute("CREATE TABLE fm (a BIGINT PRIMARY KEY, b BIGINT)")
        bulkload.bulk_load(
            sess.storage, _table(sess, "fm"),
            {"a": np.arange(5000), "b": np.arange(5000) % 9})
        # plain filter scan (aggregation pushdowns intentionally stay
        # un-memoized so host/device modes both really compute)
        q = "SELECT a FROM fm WHERE b < 4 ORDER BY a LIMIT 5"
        assert sess.query(q).rows == sess.query(q).rows
        memos = 0
        for ent in sess.storage.chunk_cache._entries.values():
            memo = getattr(ent[2], "_cop_filter_memo", None)
            if memo:
                memos += len(memo)
        assert memos >= 1

    def test_correlated_filters_never_memoize(self, sess):
        sess.execute("CREATE TABLE c1 (a BIGINT PRIMARY KEY)")
        sess.execute("CREATE TABLE c2 (b BIGINT PRIMARY KEY, "
                     "name VARCHAR(8))")
        sess.execute("INSERT INTO c1 VALUES (1), (5), (9)")
        sess.execute("INSERT INTO c2 VALUES (3,'x'), (7,'y')")
        q = ("SELECT a FROM c1 WHERE EXISTS (SELECT 1 FROM c2 "
             "WHERE c2.b > c1.a AND c2.name LIKE '%') ORDER BY a")
        assert sess.query(q).rows == [(1,), (5,)]
        assert sess.query(q).rows == [(1,), (5,)]   # hot: not frozen
