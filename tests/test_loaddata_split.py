"""LOAD DATA INFILE + SPLIT TABLE (ref: executor/write.go:1373 LoadData;
store/tikv/split_region.go:29 manual region split)."""

from decimal import Decimal

import pytest

from tidb_tpu.session import Session, SQLError
from tidb_tpu.table import DupKeyError
from tidb_tpu.store.storage import new_mock_storage


@pytest.fixture
def sess():
    s = Session(new_mock_storage())
    s.execute("CREATE DATABASE d")
    s.execute("USE d")
    s.execute("CREATE TABLE t (id BIGINT PRIMARY KEY, name VARCHAR(32), "
              "price DECIMAL(10,2), qty BIGINT, dt DATETIME)")
    yield s
    s.close()


def _write(tmp_path, name, content):
    p = tmp_path / name
    p.write_text(content, encoding="utf-8")
    return str(p)


class TestLoadData:
    def test_csv_with_header_nulls_types(self, sess, tmp_path):
        path = _write(tmp_path, "t.csv",
                      'id,name,price,qty,dt\n'
                      '1,"alpha",12.50,7,2024-01-02 03:04:05\n'
                      '2,"beta, inc",0.99,\\N,2024-06-30 00:00:00\n'
                      '3,gamma,100,0,2024-12-31 23:59:59\n')
        [n] = sess.execute(
            f"LOAD DATA INFILE '{path}' INTO TABLE t "
            f"FIELDS TERMINATED BY ',' ENCLOSED BY '\"' "
            f"LINES TERMINATED BY '\\n' IGNORE 1 LINES")
        assert n == 3
        rows = sess.query("SELECT id, name, price, qty FROM t "
                          "ORDER BY id").rows
        assert rows[0][:3] == (1, "alpha", Decimal("12.50"))
        assert rows[1][1] == "beta, inc"       # enclosed comma survives
        assert rows[1][3] is None              # \N is NULL
        assert rows[2][2] == Decimal("100.00")  # rescaled to frac 2
        assert sess.query("SELECT COUNT(*) FROM t WHERE "
                          "dt = '2024-01-02 03:04:05'").rows == [(1,)]

    def test_tab_defaults_and_column_list(self, sess, tmp_path):
        path = _write(tmp_path, "t.tsv", "10\tx\n11\ty\n")
        [n] = sess.execute(
            f"LOAD DATA INFILE '{path}' INTO TABLE t (id, name)")
        assert n == 2
        assert sess.query("SELECT name, price FROM t WHERE id=11").rows \
            == [("y", None)]

    def test_dup_modes(self, sess, tmp_path):
        sess.execute("INSERT INTO t (id, name) VALUES (1, 'old')")
        path = _write(tmp_path, "dup.tsv", "1\tnew\n2\tfresh\n")
        with pytest.raises(DupKeyError):
            sess.execute(
                f"LOAD DATA INFILE '{path}' INTO TABLE t (id, name)")
        # statement atomicity: the failed load wrote nothing
        assert sess.query("SELECT COUNT(*) FROM t").rows == [(1,)]
        [n] = sess.execute(
            f"LOAD DATA INFILE '{path}' IGNORE INTO TABLE t (id, name)")
        assert n == 1
        assert sess.query("SELECT name FROM t WHERE id=1").rows \
            == [("old",)]
        sess.execute(
            f"LOAD DATA INFILE '{path}' REPLACE INTO TABLE t (id, name)")
        assert sess.query("SELECT name FROM t WHERE id=1").rows \
            == [("new",)]

    def test_local_implies_ignore_and_escapes(self, sess, tmp_path):
        sess.execute("INSERT INTO t (id, name) VALUES (5, 'keep')")
        path = _write(tmp_path, "esc.tsv", "5\tx\n6\ta\\tb\n")
        [n] = sess.execute(
            f"LOAD DATA LOCAL INFILE '{path}' INTO TABLE t (id, name)")
        assert n == 1
        assert sess.query("SELECT name FROM t WHERE id=5").rows \
            == [("keep",)]
        assert sess.query("SELECT name FROM t WHERE id=6").rows \
            == [("a\tb",)]

    def test_missing_file(self, sess):
        with pytest.raises(SQLError):
            sess.execute("LOAD DATA INFILE '/nonexistent/x' INTO TABLE t")

    def test_in_explicit_txn_rolls_back(self, sess, tmp_path):
        path = _write(tmp_path, "txn.tsv", "100\tz\n")
        sess.execute("BEGIN")
        sess.execute(f"LOAD DATA INFILE '{path}' INTO TABLE t (id, name)")
        assert sess.query("SELECT COUNT(*) FROM t WHERE id=100").rows \
            == [(1,)]
        sess.execute("ROLLBACK")
        assert sess.query("SELECT COUNT(*) FROM t WHERE id=100").rows \
            == [(0,)]


class TestSplitTable:
    def test_split_at(self, sess):
        sess.execute("INSERT INTO t (id, name) VALUES (1,'a'), (500,'b'), "
                     "(1500,'c')")
        before = len(sess.storage.cluster.all_regions())
        rs = sess.query("SPLIT TABLE t AT (1000)")
        assert rs.rows == [(1,)]
        assert len(sess.storage.cluster.all_regions()) == before + 1
        # reads still correct across the new boundary
        assert sess.query("SELECT COUNT(*) FROM t").rows == [(3,)]

    def test_split_regions(self, sess):
        before = len(sess.storage.cluster.all_regions())
        rs = sess.query("SPLIT TABLE t REGIONS 4")
        assert rs.rows == [(3,)]
        assert len(sess.storage.cluster.all_regions()) == before + 3

    def test_split_bad_arg(self, sess):
        with pytest.raises(SQLError):
            sess.query("SPLIT TABLE t AT ('abc')")


class TestSplitRerun:
    def test_split_regions_rerun_is_noop(self, sess):
        assert sess.query("SPLIT TABLE t REGIONS 4").rows == [(3,)]
        # same boundaries again: nothing new, NO error
        assert sess.query("SPLIT TABLE t REGIONS 4").rows == [(0,)]

    def test_split_missing_table(self, sess):
        with pytest.raises(SQLError):
            sess.query("SPLIT TABLE nope REGIONS 2")


class TestLoadDataPrivilege:
    def test_nonlocal_needs_super_local_needs_insert(self, tmp_path):
        from tidb_tpu.bootstrap import bootstrap
        from tidb_tpu.store.storage import new_mock_storage
        st = new_mock_storage()
        bootstrap(st)
        r = Session(st, user="root", host="%")
        r.execute("CREATE DATABASE d")
        r.execute("CREATE TABLE d.t (id BIGINT PRIMARY KEY)")
        r.execute("CREATE USER 'bob'@'%' IDENTIFIED BY 'pw'")
        r.execute("GRANT INSERT ON d.t TO 'bob'@'%'")
        path = str(tmp_path / "f.tsv")
        (tmp_path / "f.tsv").write_text("7\n")
        bob = Session(st, user="bob", host="%", db="d")
        # server-side file read is gated like MySQL's FILE privilege
        with pytest.raises(SQLError, match="denied"):
            bob.execute(f"LOAD DATA INFILE '{path}' INTO TABLE t (id)")
        # LOCAL form only needs INSERT on the table
        [n] = bob.execute(f"LOAD DATA LOCAL INFILE '{path}' "
                          f"INTO TABLE t (id)")
        assert n == 1
        bob.close()
        r.close()
