"""Range extraction + index access paths: unit tests for tidb_tpu.ranger
plus SQL-level tests that indexed queries pick index plans and agree with
full-scan results.

Ref model: util/ranger tests + executor index-read tests
(executor/executor_test.go index scan cases).
"""

import pytest

from tidb_tpu import codec, ranger, tablecodec
from tidb_tpu.expression import Op, col, const, func
from tidb_tpu.plan import physical as ph
from tidb_tpu.session import Session
from tidb_tpu.sqltypes import (new_double_field, new_int_field,
                               new_string_field)
from tidb_tpu.store import new_mock_storage


@pytest.fixture
def tk():
    storage = new_mock_storage()
    storage.async_commit_secondaries = False
    s = Session(storage)
    s.execute("CREATE DATABASE test; USE test")
    yield s
    s.close()
    storage.close()


def q(tk, sql):
    return tk.query(sql).rows


IF = new_int_field()
SF = new_string_field()


class TestDetach:
    def test_eq_chain(self):
        c0, c1 = col(0, IF, "a"), col(1, IF, "b")
        conj = [func(Op.EQ, c0, const(5)), func(Op.EQ, c1, const(7))]
        p = ranger.detach_index_conditions(conj, [0, 1], [IF, IF])
        assert p.eq_count == 2 and not p.has_interval
        assert len(p.ranges) == 1
        assert p.ranges[0].low == [5, 7] and p.ranges[0].high == [5, 7]

    def test_eq_then_interval(self):
        c0, c1 = col(0, IF, "a"), col(1, IF, "b")
        conj = [func(Op.EQ, c0, const(5)), func(Op.GT, c1, const(3)),
                func(Op.LE, c1, const(9))]
        p = ranger.detach_index_conditions(conj, [0, 1], [IF, IF])
        assert p.eq_count == 1 and p.has_interval
        r = p.ranges[0]
        assert r.low == [5, 3] and not r.low_incl
        assert r.high == [5, 9] and r.high_incl

    def test_reversed_operands(self):
        c0 = col(0, IF, "a")
        p = ranger.detach_index_conditions(
            [func(Op.LT, const(10), c0)], [0], [IF])
        assert p.has_interval
        r = p.ranges[0]
        assert r.low == [10] and not r.low_incl and r.high_unbounded

    def test_in_points(self):
        c0 = col(0, IF, "a")
        p = ranger.detach_index_conditions(
            [func(Op.IN, c0, extra=[3, 1, 2])], [0], [IF])
        assert p.eq_count == 1
        assert [r.low[0] for r in p.ranges] == [1, 2, 3]

    def test_inexact_float_bound_on_int(self):
        c0 = col(0, IF, "a")
        # a <= 3.5 -> range high becomes inclusive 3 (floor)
        p = ranger.detach_index_conditions(
            [func(Op.LE, c0, const(3.5))], [0], [IF])
        assert p.has_interval
        r = p.ranges[0]
        assert r.high == [3] and r.high_incl

    def test_unusable_condition_left_out(self):
        c0, c1 = col(0, IF, "a"), col(1, IF, "b")
        # condition on a non-prefix column only -> useless path
        p = ranger.detach_index_conditions(
            [func(Op.EQ, c1, const(5))], [0, 1], [IF, IF])
        assert not p.useful

    def test_empty_interval(self):
        c0 = col(0, IF, "a")
        p = ranger.detach_index_conditions(
            [func(Op.GT, c0, const(9)), func(Op.LT, c0, const(3))], [0], [IF])
        assert p.ranges == []

    def test_string_range_kv_order(self):
        c0 = col(0, SF, "s")
        p = ranger.detach_index_conditions(
            [func(Op.GE, c0, const("b")), func(Op.LT, c0, const("d"))],
            [0], [SF])
        kvr = ranger.index_ranges_to_kv(1, 1, p.ranges)
        assert len(kvr) == 1
        k_b = tablecodec.index_key(1, 1, ["b"])
        k_c = tablecodec.index_key(1, 1, ["c"])
        k_d = tablecodec.index_key(1, 1, ["d"])
        assert kvr[0].start <= k_b < kvr[0].end
        assert kvr[0].start <= k_c < kvr[0].end
        assert not (kvr[0].start <= k_d < kvr[0].end)

    def test_null_skip_on_unbounded_low(self):
        c0 = col(0, IF, "a")
        p = ranger.detach_index_conditions(
            [func(Op.LT, c0, const(5))], [0], [IF])
        kvr = ranger.index_ranges_to_kv(1, 1, p.ranges)
        null_key = tablecodec.index_key(1, 1, [None])
        assert not (kvr[0].start <= null_key < kvr[0].end)

    def test_handle_ranges(self):
        c0 = col(0, IF, "id")
        p = ranger.detach_handle_conditions(
            [func(Op.GE, c0, const(10)), func(Op.LT, c0, const(20))], 0)
        kvr = ranger.handle_ranges_to_kv(7, p.ranges)
        assert kvr is not None and len(kvr) == 1
        assert kvr[0].start == tablecodec.record_key(7, 10)
        assert kvr[0].end == tablecodec.record_key(7, 20)


class TestPlanChoice:
    def _plan(self, tk, sql):
        from tidb_tpu.parser import parse_one
        from tidb_tpu.plan.planner import Planner
        p = Planner(tk.domain.info_schema(), tk.current_db)
        return p.plan(parse_one(sql))

    def test_pk_range_narrows_scan(self, tk):
        tk.execute("CREATE TABLE t (id BIGINT PRIMARY KEY, v INT)")
        plan = self._plan(tk, "SELECT v FROM t WHERE id >= 5 AND id < 8")
        readers = _find(plan, ph.PhysTableReader)
        assert readers and readers[0].cop.ranges is not None

    def test_pk_point_becomes_point_get(self, tk):
        tk.execute("CREATE TABLE t (id BIGINT PRIMARY KEY, v INT)")
        plan = self._plan(tk, "SELECT v FROM t WHERE id = 5")
        assert _find(plan, ph.PhysPointGet)

    def test_unique_index_point_get(self, tk):
        tk.execute("CREATE TABLE t (id BIGINT PRIMARY KEY, u INT UNIQUE)")
        plan = self._plan(tk, "SELECT id FROM t WHERE u = 5")
        assert _find(plan, ph.PhysPointGet)

    def test_index_lookup_chosen(self, tk):
        tk.execute("CREATE TABLE t (id BIGINT PRIMARY KEY, a INT, b INT)")
        tk.execute("CREATE INDEX ia ON t (a)")
        plan = self._plan(tk, "SELECT b FROM t WHERE a = 3")
        assert _find(plan, ph.PhysIndexLookUp)

    def test_agg_reader_keeps_pushdown(self, tk):
        tk.execute("CREATE TABLE t (id BIGINT PRIMARY KEY, a INT, b INT)")
        tk.execute("CREATE INDEX ia ON t (a)")
        plan = self._plan(tk, "SELECT SUM(b) FROM t WHERE a = 3")
        readers = _find(plan, ph.PhysTableReader)
        assert readers and readers[0].cop.is_agg
        assert not _find(plan, ph.PhysIndexLookUp)


def _find(plan, tp):
    out = []

    def walk(p):
        if isinstance(p, tp):
            out.append(p)
        for c in getattr(p, "children", []):
            walk(c)
        for attr in ("source", "reader"):
            sub = getattr(p, attr, None)
            if sub is not None:
                walk(sub)
    walk(plan)
    return out


class TestIndexReads:
    def test_pk_range_results(self, tk):
        tk.execute("CREATE TABLE t (id BIGINT PRIMARY KEY, v INT)")
        tk.execute("INSERT INTO t VALUES " +
                   ",".join(f"({i},{i * 10})" for i in range(1, 21)))
        assert q(tk, "SELECT v FROM t WHERE id = 7") == [(70,)]
        assert q(tk, "SELECT v FROM t WHERE id >= 18 ORDER BY id") == \
            [(180,), (190,), (200,)]
        assert q(tk, "SELECT COUNT(*) FROM t WHERE id > 5 AND id <= 15") == \
            [(10,)]

    def test_secondary_index_results(self, tk):
        tk.execute("CREATE TABLE t (id BIGINT PRIMARY KEY, a INT, s VARCHAR(10))")
        tk.execute("CREATE INDEX ia ON t (a)")
        tk.execute("INSERT INTO t VALUES " +
                   ",".join(f"({i},{i % 5},'s{i}')" for i in range(1, 51)))
        got = q(tk, "SELECT id FROM t WHERE a = 3 ORDER BY id")
        assert got == [(i,) for i in range(1, 51) if i % 5 == 3]
        got = q(tk, "SELECT s FROM t WHERE a IN (1, 2) AND id <= 10 ORDER BY id")
        assert got == [(f"s{i}",) for i in range(1, 11) if i % 5 in (1, 2)]

    def test_unique_index_point(self, tk):
        tk.execute("CREATE TABLE t (id BIGINT PRIMARY KEY, u INT UNIQUE, v INT)")
        tk.execute("INSERT INTO t VALUES (1, 100, 7), (2, 200, 8)")
        assert q(tk, "SELECT v FROM t WHERE u = 200") == [(8,)]
        assert q(tk, "SELECT v FROM t WHERE u = 999") == []

    def test_composite_index(self, tk):
        tk.execute("CREATE TABLE t (id BIGINT PRIMARY KEY, a INT, b INT, v INT)")
        tk.execute("CREATE INDEX iab ON t (a, b)")
        rows = [(i, i % 3, i % 7, i * 2) for i in range(1, 43)]
        tk.execute("INSERT INTO t VALUES " +
                   ",".join(f"({a},{b},{c},{d})" for a, b, c, d in rows))
        got = q(tk, "SELECT id FROM t WHERE a = 1 AND b > 2 AND b <= 5 ORDER BY id")
        want = [(i,) for i, a, b, _ in rows if a == 1 and 2 < b <= 5]
        assert got == want

    def test_index_with_nulls(self, tk):
        tk.execute("CREATE TABLE t (id BIGINT PRIMARY KEY, a INT)")
        tk.execute("CREATE INDEX ia ON t (a)")
        tk.execute("INSERT INTO t VALUES (1, NULL), (2, 5), (3, NULL), (4, 1)")
        # range scan must not return NULL rows
        assert q(tk, "SELECT id FROM t WHERE a < 10 ORDER BY id") == [(2,), (4,)]
        assert q(tk, "SELECT id FROM t WHERE a IS NULL ORDER BY id") == \
            [(1,), (3,)]

    def test_dirty_txn_sees_own_writes_through_index(self, tk):
        tk.execute("CREATE TABLE t (id BIGINT PRIMARY KEY, a INT)")
        tk.execute("CREATE INDEX ia ON t (a)")
        tk.execute("INSERT INTO t VALUES (1, 10)")
        tk.execute("BEGIN")
        tk.execute("INSERT INTO t VALUES (2, 10)")
        assert q(tk, "SELECT id FROM t WHERE a = 10 ORDER BY id") == \
            [(1,), (2,)]
        tk.execute("COMMIT")
        assert q(tk, "SELECT id FROM t WHERE a = 10 ORDER BY id") == \
            [(1,), (2,)]

    def test_update_delete_via_index(self, tk):
        tk.execute("CREATE TABLE t (id BIGINT PRIMARY KEY, a INT, v INT)")
        tk.execute("CREATE INDEX ia ON t (a)")
        tk.execute("INSERT INTO t VALUES (1, 1, 0), (2, 2, 0), (3, 1, 0)")
        tk.execute("UPDATE t SET v = 9 WHERE a = 1")
        assert q(tk, "SELECT id, v FROM t ORDER BY id") == \
            [(1, 9), (2, 0), (3, 9)]
        tk.execute("DELETE FROM t WHERE a = 1")
        assert q(tk, "SELECT id FROM t ORDER BY id") == [(2,)]
        # index entries for deleted rows must be gone
        assert q(tk, "SELECT id FROM t WHERE a = 1") == []

    def test_index_maintained_on_update(self, tk):
        tk.execute("CREATE TABLE t (id BIGINT PRIMARY KEY, a INT)")
        tk.execute("CREATE INDEX ia ON t (a)")
        tk.execute("INSERT INTO t VALUES (1, 5)")
        tk.execute("UPDATE t SET a = 6 WHERE id = 1")
        assert q(tk, "SELECT id FROM t WHERE a = 6") == [(1,)]
        assert q(tk, "SELECT id FROM t WHERE a = 5") == []

    def test_decimal_index_inexact_bound(self, tk):
        # regression: decimal_to_scaled rounds 1.5 -> 2 at scale 0; the
        # range bound must floor (not round) or rows silently escape DML
        tk.execute("CREATE TABLE t (id BIGINT PRIMARY KEY, d DECIMAL(10,0))")
        tk.execute("CREATE INDEX kd ON t (d)")
        tk.execute("INSERT INTO t VALUES (1, 1), (2, 2), (3, 3)")
        tk.execute("DELETE FROM t WHERE d > 1.5")
        assert q(tk, "SELECT id FROM t ORDER BY id") == [(1,)]

    def test_decimal_index_scale_normalized(self, tk):
        # regression: stored index keys must carry the COLUMN's frac, not
        # the literal's, or range probes at column scale never match
        tk.execute("CREATE TABLE t (id BIGINT PRIMARY KEY, c DECIMAL(10,2))")
        tk.execute("CREATE INDEX ic ON t (c)")
        tk.execute("INSERT INTO t VALUES (1, 1.5), (2, 2.5), (3, 3.25)")
        assert q(tk, "SELECT id FROM t WHERE c = 2.5") == [(2,)]
        assert q(tk, "SELECT id FROM t WHERE c > 2.0 AND c < 3.0") == [(2,)]
        assert q(tk, "SELECT id FROM t WHERE c >= 1.5 AND c <= 3.25 "
                     "ORDER BY id") == [(1,), (2,), (3,)]

    def test_out_of_int64_literal_no_crash(self, tk):
        tk.execute("CREATE TABLE t (id BIGINT PRIMARY KEY, v INT)")
        tk.execute("INSERT INTO t VALUES (1, 10)")
        assert q(tk, "SELECT id FROM t WHERE id > 9223372036854775808") == []
        assert q(tk, "SELECT id FROM t WHERE id < 9223372036854775808") == \
            [(1,)]

    def test_covering_index_reader(self, tk):
        tk.execute("CREATE TABLE t (id BIGINT PRIMARY KEY, a INT, b INT)")
        tk.execute("CREATE INDEX iab ON t (a, b)")
        tk.execute("INSERT INTO t VALUES (1, 1, 10), (2, 1, 20), (3, 2, 30)")
        from tidb_tpu.parser import parse_one
        from tidb_tpu.plan.planner import Planner
        plan = Planner(tk.domain.info_schema(), tk.current_db).plan(
            parse_one("SELECT a, b FROM t WHERE a = 1"))
        assert _find(plan, ph.PhysIndexReader)
        assert q(tk, "SELECT a, b FROM t WHERE a = 1 ORDER BY b") == \
            [(1, 10), (1, 20)]

    def test_select_actually_uses_index_plan(self, tk):
        # regression: session SELECT path must run access-path optimization
        tk.execute("CREATE TABLE t (id BIGINT PRIMARY KEY, a INT, b INT)")
        tk.execute("CREATE INDEX ia ON t (a)")
        tk.execute("INSERT INTO t VALUES (1, 5, 1), (2, 6, 2)")
        import tidb_tpu.executor as ex
        seen = []
        orig = ex.IndexLookUpExec.chunks

        def spy(self, ctx):
            seen.append(True)
            return orig(self, ctx)
        ex.IndexLookUpExec.chunks = spy
        try:
            assert q(tk, "SELECT b FROM t WHERE a = 5") == [(1,)]
        finally:
            ex.IndexLookUpExec.chunks = orig
        assert seen

    def test_large_index_scan_batches(self, tk):
        tk.execute("CREATE TABLE t (id BIGINT PRIMARY KEY, a INT)")
        tk.execute("CREATE INDEX ia ON t (a)")
        n = 3000
        for base in range(0, n, 500):
            tk.execute("INSERT INTO t VALUES " + ",".join(
                f"({i},{i % 2})" for i in range(base + 1, base + 501)))
        assert q(tk, "SELECT COUNT(*) FROM t WHERE a = 1") == [(1500,)]
