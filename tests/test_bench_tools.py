"""Bench tool parity (ref: cmd/benchdb, cmd/benchraw, cmd/benchfilesort)
— smoke runs at tiny sizes proving each harness executes end-to-end."""

from tidb_tpu.benchmarks import benchdb, benchfilesort, benchraw
from tidb_tpu.session import Session
from tidb_tpu.store.storage import new_mock_storage


def test_benchdb_jobs():
    s = Session(new_mock_storage())
    s.execute("CREATE DATABASE bench; USE bench")
    results = benchdb.run_jobs(
        s, "create|insert:0_300|update-random:0_300:100|"
           "select:0_300:3|update-range:50_60:20|gc|truncate",
        batch=50, blob=32)
    assert len(results) == 7
    assert all(dt >= 0 for _j, dt in results)
    assert s.query("SELECT COUNT(*) FROM benchdb").rows == [(0,)]
    s.close()


def test_benchraw():
    out = benchraw.run(new_mock_storage(), num=500, batch=64,
                       value_size=16, workers=2)
    assert out["num"] == 500
    assert all(v > 0 for k, v in out.items() if k.endswith("secs"))


def test_benchfilesort_spills_and_sorts():
    out = benchfilesort.run(rows=30_000, run_rows=8_000, chunk_rows=4096)
    assert out["rows"] == 30_000
    assert out["rows_per_sec"] > 0


def test_ssb_streaming_wide_scan():
    """BASELINE config 5 shape: regions stream through the mesh agg in
    super-batches; device and host agree."""
    import jax
    jax.config.update("jax_platforms", "cpu")
    from tidb_tpu.benchmarks import ssb
    out = ssb.run(sf=0.005, regions=4, stream_rows=8192)
    assert out["rows"] == 30_000
    assert out["q11"]["rows_per_sec"] > 0
    assert out["qgrp"]["speedup"] > 0
