"""Bootstrap, authentication, and privilege checks (ref: bootstrap.go,
privilege/privileges/, session.go:928 Auth)."""

import pytest

from tidb_tpu.bootstrap import BOOTSTRAP_VERSION, bootstrap
from tidb_tpu.privilege import (ALL_PRIVS, Priv, check_scramble,
                                encode_password)
from tidb_tpu.session import Session, SQLError
from tidb_tpu.store.storage import new_mock_storage


@pytest.fixture
def store():
    st = new_mock_storage()
    bootstrap(st)
    return st


def root(store):
    return Session(store, user="root", host="%")


class TestBootstrap:
    def test_idempotent(self, store):
        bootstrap(store)
        bootstrap(store)
        s = root(store)
        rows = s.query("SELECT variable_value FROM mysql.tidb "
                       "WHERE variable_name = 'bootstrapped'").rows
        assert rows == [(str(BOOTSTRAP_VERSION),)]
        users = s.query("SELECT user, privs FROM mysql.user").rows
        assert ("root", ALL_PRIVS) in users

    def test_system_tables_exist(self, store):
        s = root(store)
        for t in ("user", "db", "tables_priv", "global_variables", "tidb"):
            s.query(f"SELECT COUNT(*) FROM mysql.{t}")


class TestPasswordHash:
    def test_scramble_roundtrip(self):
        import hashlib
        pw, salt = "s3cret", b"A" * 20
        stored = encode_password(pw)
        h1 = hashlib.sha1(pw.encode()).digest()
        h2 = hashlib.sha1(h1).digest()
        mask = hashlib.sha1(salt + h2).digest()
        scr = bytes(a ^ b for a, b in zip(h1, mask))
        assert check_scramble(scr, salt, stored)
        assert not check_scramble(scr, b"B" * 20, stored)
        assert not check_scramble(b"x" * 20, salt, stored)
        assert check_scramble(b"", salt, "")          # empty password
        assert not check_scramble(b"", salt, stored)  # pw set, none given


class TestAccounts:
    def test_create_grant_revoke_drop(self, store):
        r = root(store)
        r.execute("CREATE DATABASE app")
        r.execute("CREATE TABLE app.t (id BIGINT PRIMARY KEY, v BIGINT)")
        r.execute("INSERT INTO app.t VALUES (1, 10)")
        r.execute("CREATE USER 'alice'@'%' IDENTIFIED BY 'pw'")

        alice = Session(store, user="alice", host="1.2.3.4")
        with pytest.raises(SQLError, match="denied"):
            alice.query("SELECT * FROM app.t")

        r.execute("GRANT SELECT ON app.* TO 'alice'@'%'")
        assert alice.query("SELECT v FROM app.t").rows == [(10,)]
        with pytest.raises(SQLError, match="denied"):
            alice.execute("INSERT INTO app.t VALUES (2, 20)")

        r.execute("GRANT INSERT ON app.t TO 'alice'@'%'")
        alice.execute("INSERT INTO app.t VALUES (2, 20)")

        r.execute("REVOKE SELECT ON app.* FROM 'alice'@'%'")
        with pytest.raises(SQLError, match="denied"):
            alice.query("SELECT v FROM app.t")

        r.execute("DROP USER 'alice'@'%'")
        assert r.query("SELECT COUNT(*) FROM mysql.user "
                       "WHERE user = 'alice'").rows == [(0,)]
        # grant rows cleaned up too
        assert r.query("SELECT COUNT(*) FROM mysql.tables_priv "
                       "WHERE user = 'alice'").rows == [(0,)]

    def test_join_requires_select_on_both(self, store):
        r = root(store)
        r.execute("CREATE DATABASE app; USE app")
        r.execute("CREATE TABLE a (id BIGINT PRIMARY KEY)")
        r.execute("CREATE TABLE b (id BIGINT PRIMARY KEY)")
        r.execute("CREATE USER bob")
        r.execute("GRANT SELECT ON app.a TO bob")
        bob = Session(store, db="app", user="bob", host="h")
        bob.query("SELECT * FROM a")
        with pytest.raises(SQLError, match="denied"):
            bob.query("SELECT * FROM a JOIN b ON a.id = b.id")

    def test_non_superuser_cannot_grant(self, store):
        r = root(store)
        r.execute("CREATE USER carol")
        carol = Session(store, user="carol", host="h")
        with pytest.raises(SQLError, match="denied"):
            carol.execute("CREATE USER dave")
        with pytest.raises(SQLError, match="denied"):
            carol.execute("GRANT SELECT ON *.* TO carol")

    def test_ddl_privs(self, store):
        r = root(store)
        r.execute("CREATE DATABASE app")
        r.execute("CREATE USER eve")
        eve = Session(store, db="app", user="eve", host="h")
        with pytest.raises(SQLError, match="denied"):
            eve.execute("CREATE TABLE t (id BIGINT PRIMARY KEY)")
        r.execute("GRANT CREATE, DROP ON app.* TO eve")
        eve.execute("CREATE TABLE t (id BIGINT PRIMARY KEY)")
        eve.execute("DROP TABLE t")

    def test_grant_unknown_user_rejected(self, store):
        with pytest.raises(SQLError, match="does not exist"):
            root(store).execute("GRANT SELECT ON *.* TO ghost")

    def test_drop_database_checks_target_db(self, store):
        r = root(store)
        r.execute("CREATE DATABASE db1; CREATE DATABASE db2")
        r.execute("CREATE USER u")
        r.execute("GRANT CREATE, DROP ON db1.* TO u")
        u = Session(store, db="db1", user="u", host="h")
        with pytest.raises(SQLError, match="denied"):
            u.execute("DROP DATABASE db2")
        u.execute("DROP DATABASE db1")   # allowed: grant scoped to db1

    def test_grant_on_bare_star_is_current_db(self, store):
        r = root(store)
        r.execute("CREATE DATABASE db1; CREATE DATABASE secret")
        r.execute("CREATE TABLE db1.t (id BIGINT PRIMARY KEY)")
        r.execute("CREATE TABLE secret.s (id BIGINT PRIMARY KEY)")
        r.execute("CREATE USER u")
        r.execute("USE db1")
        r.execute("GRANT SELECT ON * TO u")
        u = Session(store, db="db1", user="u", host="h")
        u.query("SELECT * FROM t")
        with pytest.raises(SQLError, match="denied"):
            u.query("SELECT * FROM secret.s")

    def test_update_only_grant_suffices_without_where(self, store):
        r = root(store)
        r.execute("CREATE DATABASE db1")
        r.execute("CREATE TABLE db1.t (id BIGINT PRIMARY KEY, a BIGINT)")
        r.execute("INSERT INTO db1.t VALUES (1, 0)")
        r.execute("CREATE USER w")
        r.execute("GRANT UPDATE ON db1.t TO w")
        w = Session(store, db="db1", user="w", host="h")
        w.execute("UPDATE t SET a = 1")
        with pytest.raises(SQLError, match="denied"):
            w.execute("UPDATE t SET a = 2 WHERE id = 1")   # WHERE reads

    def test_insert_select_from_target_needs_select(self, store):
        r = root(store)
        r.execute("CREATE DATABASE db1")
        r.execute("CREATE TABLE db1.t (id BIGINT PRIMARY KEY, a BIGINT)")
        r.execute("INSERT INTO db1.t VALUES (1, 5)")
        r.execute("CREATE USER x")
        r.execute("GRANT INSERT ON db1.t TO x")
        x = Session(store, db="db1", user="x", host="h")
        with pytest.raises(SQLError, match="denied"):
            x.execute("INSERT INTO t SELECT id + 10, a FROM t")

    def test_localhost_matches_loopback(self, store):
        from tidb_tpu.privilege import _host_match
        assert _host_match("localhost", "127.0.0.1")
        assert _host_match("::1", "localhost")
        assert not _host_match("localhost", "10.0.0.1")

    def test_with_grant_option_grants_grant_priv(self, store):
        """WITH GRANT OPTION grants the GRANT bit: the grantee can then
        grant onward (previously rejected; now real semantics)."""
        r = root(store)
        r.execute("CREATE USER u")
        r.execute("CREATE USER v")
        r.execute("CREATE DATABASE gdb")
        r.execute("GRANT SELECT ON gdb.* TO u WITH GRANT OPTION")
        s = Session(store, user="u", host="localhost")
        s.execute("GRANT SELECT ON gdb.* TO v")   # GRANT bit at work
        s.close()


class TestServerAuth:
    def test_wrong_password_rejected_right_accepted(self):
        from tidb_tpu.server import Server
        from tests.mysql_client import MiniClient, MySQLError
        st = new_mock_storage()
        srv = Server(st)
        srv.start()
        try:
            r = MiniClient("127.0.0.1", srv.port, user="root")
            r.query("CREATE DATABASE app")
            r.query("CREATE USER app IDENTIFIED BY 'hunter2'")
            r.query("GRANT ALL ON app.* TO app")
            r.close()

            with pytest.raises(MySQLError) as ei:
                MiniClient("127.0.0.1", srv.port, user="app",
                           password="wrong")
            assert ei.value.code == 1045

            with pytest.raises(MySQLError) as ei:
                MiniClient("127.0.0.1", srv.port, user="nobody")
            assert ei.value.code == 1045

            c = MiniClient("127.0.0.1", srv.port, db="app", user="app",
                           password="hunter2")
            c.query("CREATE TABLE t (id BIGINT PRIMARY KEY, v BIGINT)")
            c.query("INSERT INTO t VALUES (1, 42)")
            assert c.query("SELECT v FROM t")[1] == [("42",)]
            # no grant outside app
            with pytest.raises(MySQLError):
                c.query("SELECT * FROM mysql.user")
            c.close()
        finally:
            srv.close()


class TestReviewRegressions:
    def test_dml_subquery_requires_select(self, store):
        r = root(store)
        r.execute("CREATE DATABASE db1; CREATE DATABASE sec")
        r.execute("CREATE TABLE db1.t (id BIGINT PRIMARY KEY, a BIGINT)")
        r.execute("CREATE TABLE sec.s (id BIGINT PRIMARY KEY, v BIGINT)")
        r.execute("INSERT INTO db1.t VALUES (1, 0)")
        r.execute("INSERT INTO sec.s VALUES (1, 7)")
        r.execute("CREATE USER u")
        r.execute("GRANT UPDATE, DELETE, SELECT ON db1.* TO u")
        u = Session(store, db="db1", user="u", host="h")
        with pytest.raises(SQLError, match="denied"):
            u.execute("DELETE FROM t WHERE id IN (SELECT id FROM sec.s)")

    def test_set_global_requires_super(self, store):
        r = root(store)
        r.execute("CREATE USER u")
        u = Session(store, user="u", host="h")
        with pytest.raises(SQLError, match="denied"):
            u.execute("SET GLOBAL tidb_tpu_cop_concurrency = 3")
        # session-scope SET of a registry var shadows per session and is
        # free; the process registry must stay untouched
        from tidb_tpu import config
        g0 = config.cop_concurrency()
        u.execute("SET @@tidb_tpu_cop_concurrency = 3")
        assert config.cop_concurrency() == g0
        u.execute("SET @myvar = 1")              # user variables are free
        u.execute("SET @@sql_mode = ''")          # plain session sysvar ok
        # SUPER alone (not ALL) is grantable and unlocks SET GLOBAL
        r.execute("GRANT SUPER ON *.* TO u")
        u.execute("SET GLOBAL tidb_tpu_cop_concurrency = 10")

    def test_partial_grant_failure_still_invalidates_cache(self, store):
        r = root(store)
        r.execute("CREATE DATABASE db1")
        r.execute("CREATE TABLE db1.t (id BIGINT PRIMARY KEY)")
        r.execute("CREATE USER alice")
        alice = Session(store, db="db1", user="alice", host="h")
        with pytest.raises(SQLError, match="denied"):
            alice.query("SELECT * FROM t")   # cache now loaded
        with pytest.raises(SQLError, match="does not exist"):
            r.execute("GRANT SELECT ON db1.* TO alice, ghost")
        # alice's grant committed before the error; cache must see it
        alice.query("SELECT * FROM t")

    def test_create_user_redacted_in_processlist_log(self, store, caplog):
        import logging
        from tidb_tpu import config
        r = root(store)
        old = config.get_var("tidb_tpu_slow_query_ms")
        config.set_var("tidb_tpu_slow_query_ms", 0)
        try:
            with caplog.at_level(logging.WARNING,
                                 logger="tidb_tpu.slow_query"):
                r.execute("CREATE USER leaky IDENTIFIED BY 'hunter2'")
            assert not any("hunter2" in rec.getMessage()
                           for rec in caplog.records)
            assert any("redacted" in rec.getMessage()
                       for rec in caplog.records)
        finally:
            config.set_var("tidb_tpu_slow_query_ms", old)

    def test_update_subquery_on_target_needs_select(self, store):
        r = root(store)
        r.execute("CREATE DATABASE db1")
        r.execute("CREATE TABLE db1.t (id BIGINT PRIMARY KEY, a BIGINT)")
        r.execute("INSERT INTO db1.t VALUES (1, 5)")
        r.execute("CREATE USER w2")
        r.execute("GRANT UPDATE ON db1.t TO w2")
        w = Session(store, db="db1", user="w2", host="h")
        with pytest.raises(SQLError, match="denied"):
            w.execute("UPDATE t SET a = (SELECT MAX(a) FROM t)")

    def test_batch_create_user_redacted(self, store, caplog):
        import logging
        from tidb_tpu import config
        r = root(store)
        old = config.get_var("tidb_tpu_slow_query_ms")
        config.set_var("tidb_tpu_slow_query_ms", 0)
        try:
            with caplog.at_level(logging.WARNING,
                                 logger="tidb_tpu.slow_query"):
                r.execute("CREATE DATABASE batchy; "
                          "CREATE USER leak2 IDENTIFIED BY 'hunter3'")
            assert not any("hunter3" in rec.getMessage()
                           for rec in caplog.records)
        finally:
            config.set_var("tidb_tpu_slow_query_ms", old)

    def test_bootstrap_v2_upgrade_regrants_root(self, store):
        from tidb_tpu.bootstrap import BOOTSTRAP_VERSION, bootstrap
        from tidb_tpu.privilege import ALL_PRIVS
        r = root(store)
        # simulate a v1 store: strip SUPER from root, set version back
        s = Session(store, internal=True)
        s.execute(f"UPDATE mysql.user SET privs = {ALL_PRIVS & ~Priv.SUPER}"
                  " WHERE user = 'root'")
        s.execute("UPDATE mysql.tidb SET variable_value = '1' "
                  "WHERE variable_name = 'bootstrapped'")
        s.close()
        store.chunk_cache.clear()
        bootstrap(store)
        rows = Session(store, internal=True).query(
            "SELECT privs FROM mysql.user WHERE user = 'root'").rows
        assert rows == [(ALL_PRIVS,)]


class TestShowVariants:
    def test_show_index_grants_status(self, store):
        r = root(store)
        r.execute("CREATE DATABASE sv")
        r.execute("CREATE TABLE sv.t (id BIGINT PRIMARY KEY, v BIGINT)")
        r.execute("CREATE INDEX iv ON sv.t (v)")
        idx = r.query("SHOW INDEX FROM sv.t").rows
        assert ("t", 0, "PRIMARY", 1, "id", "BTREE") in idx
        assert ("t", 1, "iv", 1, "v", "BTREE") in idx
        r.execute("CREATE USER showme IDENTIFIED BY 'x'")
        r.execute("GRANT SELECT ON sv.t TO showme")
        g = [x[0] for x in r.query("SHOW GRANTS FOR showme").rows]
        assert any("GRANT SELECT ON `sv`.`t`" in x for x in g), g
        own = [x[0] for x in r.query("SHOW GRANTS").rows]
        assert any("ALL PRIVILEGES" in x for x in own), own
        st = dict(r.query("SHOW STATUS").rows)
        assert st, "status should expose counters"
        assert r.query("SHOW ENGINES").rows[0][1] == "DEFAULT"
        r.close()

    def test_show_grants_forms_and_access(self, store):
        r = root(store)
        r.execute("CREATE USER nosy IDENTIFIED BY 'x'")
        own = [x[0] for x in
               r.query("SHOW GRANTS FOR CURRENT_USER").rows]
        assert any("ALL PRIVILEGES" in x for x in own)
        quoted = [x[0] for x in
                  r.query("SHOW GRANTS FOR 'nosy'@'%'").rows]
        assert any("USAGE" in x for x in quoted)
        nosy = Session(store, user="nosy", host="%")
        with pytest.raises(SQLError, match="denied"):
            nosy.query("SHOW GRANTS FOR root")
        assert nosy.query("SHOW GRANTS").rows    # own grants always ok
        nosy.close()
        r.close()
