"""SELECT ... FOR UPDATE (ref: executor/executor.go:389 SelectLockExec;
Txn.LockKeys): row keys lock in the txn, commit conflicts if another
txn wrote them, and optimistic replay is disabled for such txns."""

import pytest

from tidb_tpu import kv
from tidb_tpu.session import Session, SQLError
from tidb_tpu.store.storage import new_mock_storage


@pytest.fixture
def env():
    st = new_mock_storage()
    a = Session(st)
    a.execute("CREATE DATABASE d")
    a.execute("USE d")
    a.execute("CREATE TABLE t (id BIGINT PRIMARY KEY, v BIGINT)")
    a.execute("INSERT INTO t VALUES (1, 10), (2, 20), (3, 30)")
    b = Session(st, db="d")
    yield a, b
    a.close()
    b.close()


class TestForUpdate:
    def test_conflict_detected_no_silent_replay(self, env):
        a, b = env
        a.execute("BEGIN")
        assert a.query("SELECT v FROM t WHERE id = 1 FOR UPDATE"
                       ).rows == [(10,)]
        b.execute("UPDATE t SET v = 99 WHERE id = 1")
        a.execute("INSERT INTO t VALUES (9, 90)")
        # a plain txn would replay its history; FOR UPDATE must NOT
        with pytest.raises((SQLError, kv.KVError)):
            a.execute("COMMIT")
        # b's write survives, a's insert did not
        assert b.query("SELECT v FROM t WHERE id = 1").rows == [(99,)]
        assert b.query("SELECT COUNT(*) FROM t WHERE id = 9"
                       ).rows == [(0,)]

    def test_clean_commit_and_lock_only_txn(self, env):
        a, b = env
        a.execute("BEGIN")
        a.query("SELECT v FROM t WHERE id = 2 FOR UPDATE")
        a.execute("UPDATE t SET v = 21 WHERE id = 2")
        a.execute("COMMIT")
        assert b.query("SELECT v FROM t WHERE id = 2").rows == [(21,)]
        # pure-lock txn: LOCK mutations commit without touching data
        a.execute("BEGIN")
        a.query("SELECT v FROM t WHERE id = 2 FOR UPDATE")
        a.execute("COMMIT")
        assert b.query("SELECT v FROM t WHERE id = 2").rows == [(21,)]

    def test_unwritten_rows_not_locked(self, env):
        a, b = env
        a.execute("BEGIN")
        a.query("SELECT v FROM t WHERE id = 1 FOR UPDATE")
        b.execute("UPDATE t SET v = 111 WHERE id = 3")  # different row
        a.execute("UPDATE t SET v = 11 WHERE id = 1")
        a.execute("COMMIT")                              # no conflict
        assert b.query("SELECT v FROM t ORDER BY id").rows == \
            [(11,), (20,), (111,)]

    def test_joins_refused_loudly(self, env):
        """Silently taking no locks would break the FOR UPDATE promise
        (the reference no-ops; we choose the honest error)."""
        a, _b = env
        a.execute("BEGIN")
        with pytest.raises(SQLError, match="single-table"):
            a.query("SELECT x.v FROM t x, t y WHERE x.id = y.id "
                    "AND x.id = 1 FOR UPDATE")
        a.execute("ROLLBACK")

    def test_nested_for_update_refused(self, env):
        a, _b = env
        a.execute("BEGIN")
        with pytest.raises(SQLError, match="single-table"):
            a.query("SELECT v FROM t UNION "
                    "SELECT v FROM t FOR UPDATE")
        with pytest.raises(SQLError, match="single-table"):
            a.query("SELECT * FROM (SELECT v FROM t FOR UPDATE) x")
        assert a.query("SELECT 1 FOR UPDATE").rows == [(1,)]  # no-op
        a.execute("ROLLBACK")

    def test_autocommit_off_starts_txn(self, env):
        a, _b = env
        a.execute("SET @@autocommit = 0")
        try:
            assert a.txn is None
            a.query("SELECT v FROM t WHERE id = 1 FOR UPDATE")
            assert a.txn is not None and a.txn.lock_keys
            a.execute("ROLLBACK")
        finally:
            a.execute("SET @@autocommit = 1")

    def test_autocommit_for_update_without_txn(self, env):
        a, _b = env
        # outside a txn FOR UPDATE reads normally (nothing to hold)
        assert a.query("SELECT v FROM t WHERE id = 1 FOR UPDATE"
                       ).rows == [(10,)]
