"""Minimal MySQL text-protocol client for server tests.

Implements just enough of the client half of the wire protocol (handshake
response 41, COM_QUERY, text resultset decoding) to exercise
tidb_tpu.server hermetically — no external driver dependency.
"""

from __future__ import annotations

import socket
import struct

from tidb_tpu.server.packet import (PacketIO, read_lenenc_bytes,
                                    read_lenenc_int)

CLIENT_PROTOCOL_41 = 0x200
CLIENT_SECURE_CONNECTION = 0x8000
CLIENT_CONNECT_WITH_DB = 8
CLIENT_PLUGIN_AUTH = 0x80000


class MySQLError(Exception):
    def __init__(self, code: int, msg: str):
        super().__init__(f"({code}) {msg}")
        self.code = code


class MiniClient:
    def __init__(self, host: str, port: int, db: str = "",
                 user: str = "root"):
        self.sock = socket.create_connection((host, port), timeout=10)
        self.pkt = PacketIO(self.sock)
        self._handshake(user, db)

    def _handshake(self, user: str, db: str) -> None:
        greeting = self.pkt.read_packet()
        assert greeting[0] == 10, "expected protocol v10"
        caps = CLIENT_PROTOCOL_41 | CLIENT_SECURE_CONNECTION \
            | CLIENT_PLUGIN_AUTH
        if db:
            caps |= CLIENT_CONNECT_WITH_DB
        resp = struct.pack("<I", caps)
        resp += struct.pack("<I", 1 << 24)
        resp += bytes([33]) + b"\0" * 23
        resp += user.encode() + b"\0"
        resp += bytes([0])                       # empty auth response
        if db:
            resp += db.encode() + b"\0"
        resp += b"mysql_native_password\0"
        self.pkt.write_packet(resp)
        ok = self.pkt.read_packet()
        if ok and ok[0] == 0xFF:
            raise self._err(ok)

    @staticmethod
    def _err(pkt: bytes) -> MySQLError:
        code = struct.unpack_from("<H", pkt, 1)[0]
        return MySQLError(code, pkt[9:].decode("utf8", "replace"))

    def _command(self, cmd: int, data: bytes) -> bytes:
        self.pkt.reset_seq()
        self.pkt.write_packet(bytes([cmd]) + data)
        return self.pkt.read_packet()

    def ping(self) -> None:
        first = self._command(0x0E, b"")
        if first[0] == 0xFF:
            raise self._err(first)

    def use(self, db: str) -> None:
        first = self._command(0x02, db.encode())
        if first[0] == 0xFF:
            raise self._err(first)

    def query(self, sql: str):
        """-> (columns, rows) for resultsets, affected-rows int for OK."""
        first = self._command(0x03, sql.encode())
        if first[0] == 0xFF:
            raise self._err(first)
        if first[0] == 0x00:
            affected, _ = read_lenenc_int(first, 1)
            return affected
        ncols, _ = read_lenenc_int(first, 0)
        cols = []
        for _ in range(ncols):
            cols.append(self._parse_coldef(self.pkt.read_packet()))
        eof = self.pkt.read_packet()
        assert eof[0] == 0xFE
        rows = []
        while True:
            pkt = self.pkt.read_packet()
            if pkt[0] == 0xFE and len(pkt) < 9:
                break
            if pkt[0] == 0xFF:
                raise self._err(pkt)
            rows.append(self._parse_row(pkt, ncols))
        return [c for c, _t in cols], rows

    @staticmethod
    def _parse_coldef(pkt: bytes) -> tuple[str, int]:
        off = 0
        for _ in range(4):                       # catalog schema table org
            _v, off = read_lenenc_bytes(pkt, off)
        name, off = read_lenenc_bytes(pkt, off)
        _org, off = read_lenenc_bytes(pkt, off)
        off += 1 + 2 + 4                         # 0x0c, charset, length
        tp = pkt[off]
        return name.decode(), tp

    @staticmethod
    def _parse_row(pkt: bytes, ncols: int) -> tuple:
        out = []
        off = 0
        for _ in range(ncols):
            if pkt[off] == 0xFB:
                out.append(None)
                off += 1
            else:
                v, off = read_lenenc_bytes(pkt, off)
                out.append(v.decode())
        return tuple(out)

    def close(self) -> None:
        try:
            self.pkt.reset_seq()
            self.pkt.write_packet(b"\x01")       # COM_QUIT
        except OSError:
            pass
        self.sock.close()
