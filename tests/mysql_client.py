"""Minimal MySQL text-protocol client for server tests.

Implements just enough of the client half of the wire protocol (handshake
response 41, COM_QUERY, text resultset decoding) to exercise
tidb_tpu.server hermetically — no external driver dependency.
"""

from __future__ import annotations

import hashlib
import socket
import struct

from tidb_tpu.server.packet import (PacketIO, read_lenenc_bytes,
                                    read_lenenc_int)


def native_scramble(password: str, salt: bytes) -> bytes:
    """mysql_native_password client scramble:
    SHA1(pwd) XOR SHA1(salt + SHA1(SHA1(pwd)))."""
    if not password:
        return b""
    h1 = hashlib.sha1(password.encode()).digest()
    h2 = hashlib.sha1(h1).digest()
    mask = hashlib.sha1(salt + h2).digest()
    return bytes(a ^ b for a, b in zip(h1, mask))

CLIENT_PROTOCOL_41 = 0x200
CLIENT_SECURE_CONNECTION = 0x8000
CLIENT_CONNECT_WITH_DB = 8
CLIENT_PLUGIN_AUTH = 0x80000


class MySQLError(Exception):
    def __init__(self, code: int, msg: str):
        super().__init__(f"({code}) {msg}")
        self.code = code


class MiniClient:
    def __init__(self, host: str, port: int, db: str = "",
                 user: str = "root", password: str = ""):
        self.sock = socket.create_connection((host, port), timeout=10)
        self.pkt = PacketIO(self.sock)
        self._handshake(user, db, password)

    @staticmethod
    def _parse_salt(greeting: bytes) -> bytes:
        # protocol v10: version\0, conn id (4), salt1 (8), \0, caps_lo (2),
        # charset (1), status (2), caps_hi (2), auth len (1), 10 zeros,
        # salt2 (12), \0
        off = 1
        off = greeting.index(b"\0", off) + 1     # server version
        off += 4
        salt1 = greeting[off:off + 8]
        off += 8 + 1 + 2 + 1 + 2 + 2 + 1 + 10
        salt2 = greeting[off:off + 12]
        return salt1 + salt2

    def _handshake(self, user: str, db: str, password: str) -> None:
        greeting = self.pkt.read_packet()
        assert greeting[0] == 10, "expected protocol v10"
        auth = native_scramble(password, self._parse_salt(greeting))
        caps = CLIENT_PROTOCOL_41 | CLIENT_SECURE_CONNECTION \
            | CLIENT_PLUGIN_AUTH
        if db:
            caps |= CLIENT_CONNECT_WITH_DB
        resp = struct.pack("<I", caps)
        resp += struct.pack("<I", 1 << 24)
        resp += bytes([33]) + b"\0" * 23
        resp += user.encode() + b"\0"
        resp += bytes([len(auth)]) + auth
        if db:
            resp += db.encode() + b"\0"
        resp += b"mysql_native_password\0"
        self.pkt.write_packet(resp)
        ok = self.pkt.read_packet()
        if ok and ok[0] == 0xFF:
            raise self._err(ok)

    @staticmethod
    def _err(pkt: bytes) -> MySQLError:
        code = struct.unpack_from("<H", pkt, 1)[0]
        return MySQLError(code, pkt[9:].decode("utf8", "replace"))

    def _command(self, cmd: int, data: bytes) -> bytes:
        self.pkt.reset_seq()
        self.pkt.write_packet(bytes([cmd]) + data)
        return self.pkt.read_packet()

    def ping(self) -> None:
        first = self._command(0x0E, b"")
        if first[0] == 0xFF:
            raise self._err(first)

    def use(self, db: str) -> None:
        first = self._command(0x02, db.encode())
        if first[0] == 0xFF:
            raise self._err(first)

    def query(self, sql: str):
        """-> (columns, rows) for resultsets, affected-rows int for OK."""
        first = self._command(0x03, sql.encode())
        if first[0] == 0xFF:
            raise self._err(first)
        if first[0] == 0x00:
            affected, _ = read_lenenc_int(first, 1)
            return affected
        ncols, _ = read_lenenc_int(first, 0)
        cols = []
        for _ in range(ncols):
            cols.append(self._parse_coldef(self.pkt.read_packet()))
        eof = self.pkt.read_packet()
        assert eof[0] == 0xFE
        rows = []
        while True:
            pkt = self.pkt.read_packet()
            if pkt[0] == 0xFE and len(pkt) < 9:
                break
            if pkt[0] == 0xFF:
                raise self._err(pkt)
            rows.append(self._parse_row(pkt, ncols))
        return [c for c, _t in cols], rows

    @staticmethod
    def _parse_coldef(pkt: bytes) -> tuple[str, int]:
        off = 0
        for _ in range(4):                       # catalog schema table org
            _v, off = read_lenenc_bytes(pkt, off)
        name, off = read_lenenc_bytes(pkt, off)
        _org, off = read_lenenc_bytes(pkt, off)
        off += 1 + 2 + 4                         # 0x0c, charset, length
        tp = pkt[off]
        return name.decode(), tp

    @staticmethod
    def _parse_row(pkt: bytes, ncols: int) -> tuple:
        out = []
        off = 0
        for _ in range(ncols):
            if pkt[off] == 0xFB:
                out.append(None)
                off += 1
            else:
                v, off = read_lenenc_bytes(pkt, off)
                out.append(v.decode())
        return tuple(out)

    # -- binary protocol (prepared statements) ------------------------------

    def stmt_prepare(self, sql: str):
        """-> (stmt_id, num_params)"""
        first = self._command(0x16, sql.encode())
        if first[0] == 0xFF:
            raise self._err(first)
        sid = struct.unpack_from("<I", first, 1)[0]
        ncols = struct.unpack_from("<H", first, 5)[0]
        nparams = struct.unpack_from("<H", first, 7)[0]
        for _ in range(nparams):
            self.pkt.read_packet()           # param definitions
        if nparams:
            self.pkt.read_packet()           # EOF
        self.last_prepare_columns = []
        for _ in range(ncols):
            self.last_prepare_columns.append(
                self._parse_coldef(self.pkt.read_packet()))
        if ncols:
            self.pkt.read_packet()
        return sid, nparams

    def stmt_execute(self, sid: int, params=()):
        """-> (columns, rows) or affected-rows int. Params typed by python
        value: int -> LONGLONG, float -> DOUBLE, else VARCHAR."""
        body = struct.pack("<IBI", sid, 0, 1)
        n = len(params)
        null_bitmap = bytearray((n + 7) // 8)
        types = b""
        values = b""
        for i, p in enumerate(params):
            if p is None:
                null_bitmap[i // 8] |= 1 << (i % 8)
                types += bytes([6, 0])       # MYSQL_TYPE_NULL
            elif isinstance(p, int):
                types += bytes([8, 0])       # LONGLONG
                values += struct.pack("<q", p)
            elif isinstance(p, float):
                types += bytes([5, 0])       # DOUBLE
                values += struct.pack("<d", p)
            else:
                types += bytes([15, 0])      # VARCHAR
                raw = str(p).encode("utf8")
                values += bytes([len(raw)]) if len(raw) < 251 else b""
                if len(raw) >= 251:
                    raise ValueError("long param strings unsupported here")
                values += raw
        if n:
            body += bytes(null_bitmap) + b"\x01" + types + values
        first = self._command(0x17, body)
        if first[0] == 0xFF:
            raise self._err(first)
        if first[0] == 0x00:                 # OK packet (no resultset)
            affected, _ = read_lenenc_int(first, 1)
            return affected
        ncols, _ = read_lenenc_int(first, 0)
        cols = []
        for _ in range(ncols):
            cols.append(self._parse_coldef(self.pkt.read_packet()))
        eof = self.pkt.read_packet()
        assert eof[0] == 0xFE
        rows = []
        while True:
            pkt = self.pkt.read_packet()
            if pkt[0] == 0xFE and len(pkt) < 9:
                break
            if pkt[0] == 0xFF:
                raise self._err(pkt)
            rows.append(self._parse_binary_row(pkt, cols))
        return [c for c, _t in cols], rows

    def stmt_close(self, sid: int) -> None:
        self.pkt.reset_seq()
        self.pkt.write_packet(bytes([0x19]) + struct.pack("<I", sid))

    @staticmethod
    def _parse_binary_row(pkt: bytes, cols) -> tuple:
        ncols = len(cols)
        nb = (ncols + 9) // 8
        bitmap = pkt[1:1 + nb]
        off = 1 + nb
        out = []
        for i, (_name, tp) in enumerate(cols):
            if bitmap[(i + 2) // 8] & (1 << ((i + 2) % 8)):
                out.append(None)
                continue
            if tp == 8:                      # LONGLONG
                out.append(struct.unpack_from("<q", pkt, off)[0])
                off += 8
            elif tp in (3, 9):               # LONG / INT24
                out.append(struct.unpack_from("<i", pkt, off)[0])
                off += 4
            elif tp in (2, 13):
                out.append(struct.unpack_from("<h", pkt, off)[0])
                off += 2
            elif tp == 1:
                out.append(struct.unpack_from("<b", pkt, off)[0])
                off += 1
            elif tp == 5:                    # DOUBLE
                out.append(struct.unpack_from("<d", pkt, off)[0])
                off += 8
            elif tp == 4:                    # FLOAT
                out.append(struct.unpack_from("<f", pkt, off)[0])
                off += 4
            elif tp in (7, 10, 12):          # TIMESTAMP/DATE/DATETIME
                ln = pkt[off]
                off += 1
                y = mo = d = h = mi = s = 0
                if ln >= 4:
                    y, mo, d = struct.unpack_from("<HBB", pkt, off)
                if ln >= 7:
                    h, mi, s = struct.unpack_from("<BBB", pkt, off + 4)
                off += ln
                if ln <= 4:
                    out.append(f"{y:04d}-{mo:02d}-{d:02d}")
                else:
                    out.append(f"{y:04d}-{mo:02d}-{d:02d} "
                               f"{h:02d}:{mi:02d}:{s:02d}")
            else:                            # lenenc string
                raw, off = read_lenenc_bytes(pkt, off)
                out.append(raw.decode())
        return tuple(out)

    def close(self) -> None:
        try:
            self.pkt.reset_seq()
            self.pkt.write_packet(b"\x01")       # COM_QUIT
        except OSError:
            pass
        self.sock.close()
