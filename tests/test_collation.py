"""ci collations (utf8mb4_general_ci approximated by unicode casefold;
ref: util/charset/charset.go, collation-aware compares across the
reference's expression package). VERDICT r4 #7 acceptance: 'a'='A' on a
ci column, GROUP BY merges case variants, unique index rejects
case-duplicates, SHOW COLLATION reflects reality."""

import pytest

from tidb_tpu.session import Session, SQLError
from tidb_tpu.store.storage import new_mock_storage
from tidb_tpu.table import DupKeyError


@pytest.fixture
def sess():
    s = Session(new_mock_storage())
    s.execute("CREATE DATABASE ci")
    s.execute("USE ci")
    yield s
    s.close()


@pytest.fixture
def t(sess):
    sess.execute("CREATE TABLE t (id BIGINT PRIMARY KEY, "
                 "s VARCHAR(30) COLLATE utf8mb4_general_ci, "
                 "b VARCHAR(30))")
    sess.execute("INSERT INTO t VALUES "
                 "(1, 'Alpha', 'Alpha'), (2, 'ALPHA', 'ALPHA'), "
                 "(3, 'beta', 'beta'), (4, 'Beta', 'Beta'), "
                 "(5, NULL, NULL)")
    return sess


class TestCompare:
    def test_ci_equality(self, t):
        assert t.query("SELECT COUNT(*) FROM t WHERE s = 'alpha'"
                       ).rows == [(2,)]
        assert t.query("SELECT id FROM t WHERE s = 'BETA' ORDER BY id"
                       ).rows == [(3,), (4,)]

    def test_bin_column_stays_case_sensitive(self, t):
        assert t.query("SELECT COUNT(*) FROM t WHERE b = 'alpha'"
                       ).rows == [(0,)]
        assert t.query("SELECT COUNT(*) FROM t WHERE b = 'Alpha'"
                       ).rows == [(1,)]

    def test_ci_inequality_and_in(self, t):
        assert t.query("SELECT COUNT(*) FROM t WHERE s <> 'alpha'"
                       ).rows == [(2,)]
        assert t.query("SELECT COUNT(*) FROM t WHERE s IN ('ALPHA', 'x')"
                       ).rows == [(2,)]

    def test_ci_like(self, t):
        assert t.query("SELECT COUNT(*) FROM t WHERE s LIKE 'alp%'"
                       ).rows == [(2,)]
        assert t.query("SELECT COUNT(*) FROM t WHERE b LIKE 'alp%'"
                       ).rows == [(0,)]


class TestGroupSort:
    def test_group_by_merges_case_variants(self, t):
        rows = t.query("SELECT s, COUNT(*) FROM t WHERE s IS NOT NULL "
                       "GROUP BY s").rows
        assert sorted(c for _s, c in rows) == [2, 2]
        # surfaced value is one of the variants
        names = {s.casefold() for s, _c in rows}
        assert names == {"alpha", "beta"}

    def test_bin_group_keeps_variants(self, t):
        rows = t.query("SELECT b, COUNT(*) FROM t WHERE b IS NOT NULL "
                       "GROUP BY b").rows
        assert len(rows) == 4

    def test_order_by_ci(self, t):
        rows = t.query("SELECT id FROM t WHERE s IS NOT NULL "
                       "ORDER BY s, id").rows
        # casefolded order: alpha variants (1,2) before beta variants (3,4)
        assert [r[0] for r in rows] == [1, 2, 3, 4]

    def test_distinct_ci(self, t):
        rows = t.query("SELECT DISTINCT s FROM t WHERE s IS NOT NULL").rows
        assert len(rows) == 2


class TestUniqueIndex:
    def test_unique_rejects_case_duplicates(self, sess):
        sess.execute("CREATE TABLE u (id BIGINT PRIMARY KEY, "
                     "s VARCHAR(20) COLLATE utf8mb4_general_ci UNIQUE)")
        sess.execute("INSERT INTO u VALUES (1, 'Hello')")
        with pytest.raises((SQLError, DupKeyError)):
            sess.execute("INSERT INTO u VALUES (2, 'HELLO')")
        # exact duplicate also rejected, different value fine
        with pytest.raises((SQLError, DupKeyError)):
            sess.execute("INSERT INTO u VALUES (3, 'Hello')")
        sess.execute("INSERT INTO u VALUES (4, 'World')")

    def test_index_lookup_is_ci(self, sess):
        sess.execute("CREATE TABLE v (id BIGINT PRIMARY KEY, "
                     "s VARCHAR(20) COLLATE utf8mb4_general_ci)")
        sess.execute("CREATE INDEX isx ON v (s)")
        sess.execute("INSERT INTO v VALUES (1, 'MixEd'), (2, 'other')")
        assert sess.query("SELECT id FROM v WHERE s = 'mixed'"
                          ).rows == [(1,)]
        # the lookup returns the ORIGINAL value, not the folded key
        assert sess.query("SELECT s FROM v WHERE s = 'MIXED'"
                          ).rows == [("MixEd",)]

    def test_unique_bin_allows_case_variants(self, sess):
        sess.execute("CREATE TABLE w (id BIGINT PRIMARY KEY, "
                     "s VARCHAR(20) UNIQUE)")
        sess.execute("INSERT INTO w VALUES (1, 'Hello'), (2, 'HELLO')")
        assert sess.query("SELECT COUNT(*) FROM w").rows == [(2,)]


class TestJoinsAndMeta:
    def test_ci_join_keys(self, sess):
        sess.execute("CREATE TABLE a (id BIGINT PRIMARY KEY, "
                     "k VARCHAR(10) COLLATE utf8mb4_general_ci)")
        sess.execute("CREATE TABLE b (id BIGINT PRIMARY KEY, "
                     "k VARCHAR(10) COLLATE utf8mb4_general_ci)")
        sess.execute("INSERT INTO a VALUES (1, 'x'), (2, 'Y')")
        sess.execute("INSERT INTO b VALUES (10, 'X'), (20, 'y')")
        rows = sess.query("SELECT a.id, b.id FROM a JOIN b "
                          "ON a.k = b.k ORDER BY a.id").rows
        assert rows == [(1, 10), (2, 20)]

    def test_table_default_collation(self, sess):
        sess.execute("CREATE TABLE d (id BIGINT PRIMARY KEY, "
                     "s VARCHAR(10)) COLLATE=utf8mb4_general_ci")
        sess.execute("INSERT INTO d VALUES (1, 'Q')")
        assert sess.query("SELECT COUNT(*) FROM d WHERE s = 'q'"
                          ).rows == [(1,)]

    def test_explicit_bin_beats_table_default(self, sess):
        sess.execute("CREATE TABLE eb (id BIGINT PRIMARY KEY, "
                     "s VARCHAR(10) COLLATE utf8mb4_bin) "
                     "COLLATE=utf8mb4_general_ci")
        sess.execute("INSERT INTO eb VALUES (1, 'Q')")
        assert sess.query("SELECT COUNT(*) FROM eb WHERE s = 'q'"
                          ).rows == [(0,)]

    def test_group_by_merges_across_regions(self, sess):
        """Cross-chunk/region partial merge must fold ci keys too
        (HashAggregator final merge, not just per-chunk grouping)."""
        sess.execute("CREATE TABLE mr (id BIGINT PRIMARY KEY, "
                     "s VARCHAR(20) COLLATE utf8mb4_general_ci)")
        sess.execute("INSERT INTO mr VALUES (1, 'Alpha'), (15, 'ALPHA'),"
                     " (2, 'beta'), (16, 'Beta')")
        sess.execute("SPLIT TABLE mr AT (10)")
        rows = sess.query("SELECT s, COUNT(*) FROM mr GROUP BY s").rows
        assert sorted(c for _s, c in rows) == [2, 2]
        rows = sess.query("SELECT DISTINCT s FROM mr").rows
        assert len(rows) == 2

    def test_show_collation(self, sess):
        rows = sess.query("SHOW COLLATION").rows
        colls = {r[0] for r in rows}
        assert "utf8mb4_bin" in colls and "utf8mb4_general_ci" in colls

    def test_collation_function(self, sess):
        sess.execute("CREATE TABLE cf (id BIGINT PRIMARY KEY, "
                     "s VARCHAR(10) COLLATE utf8mb4_general_ci)")
        sess.execute("INSERT INTO cf VALUES (1, 'x')")
        assert sess.query("SELECT COLLATION(s) FROM cf").rows == \
            [("utf8mb4_general_ci",)]

    def test_schema_round_trip_preserves_collation(self, sess):
        """Collation survives the meta JSON round trip (new session sees
        the same ci semantics)."""
        sess.execute("CREATE TABLE rt (id BIGINT PRIMARY KEY, "
                     "s VARCHAR(10) COLLATE utf8mb4_general_ci)")
        sess.execute("INSERT INTO rt VALUES (1, 'Z')")
        s2 = Session(sess.storage)
        s2.execute("USE ci")
        assert s2.query("SELECT COUNT(*) FROM rt WHERE s = 'z'"
                        ).rows == [(1,)]
        s2.close()


class TestShowCreateRoundTrip:
    def test_show_create_table_round_trips(self, sess):
        sess.execute("CREATE TABLE rt2 (id BIGINT PRIMARY KEY "
                     "AUTO_INCREMENT, s VARCHAR(20) COLLATE "
                     "utf8mb4_general_ci, b VARCHAR(8) NOT NULL)")
        sess.execute("CREATE INDEX isx ON rt2 (s)")
        ddl = sess.query("SHOW CREATE TABLE rt2").rows[0][1]
        assert "COLLATE utf8mb4_general_ci" in ddl
        assert "AUTO_INCREMENT" in ddl and "NOT NULL" in ddl
        assert "PRIMARY KEY" in ddl and "KEY `isx`" in ddl
        # the emitted DDL re-executes and preserves ci semantics
        sess.execute("CREATE DATABASE rt2db; USE rt2db")
        sess.execute(ddl.replace("`rt2`", "`clone`", 1))
        sess.execute("INSERT INTO clone (id, s, b) VALUES (1, 'Q', 'x')")
        assert sess.query("SELECT COUNT(*) FROM clone WHERE s = 'q'"
                          ).rows == [(1,)]
