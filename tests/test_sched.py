"""Device scheduler + admission controller units (tidb_tpu/sched.py).

Pins the concurrent-serving contracts: the global dispatch window is
granted round-robin per statement and can throttle but never hang
(timeout -> drain -> bypass valve), slots release on every path
pipeline_map can take (including generator abandonment), and admission
against `tidb_tpu_server_mem_quota` resolves to exactly one of
admitted / shed / queued / rejected — with the shed chain really
returning the hbm-cache ledger to zero, min-progress guaranteeing a
lone statement always runs, and the reject surfacing as the RETRYABLE
ER_SERVER_BUSY_ADMISSION (9008)."""

import threading
import time

import numpy as np
import pytest

from tidb_tpu import config, devplane, errcode, memtrack, sched


@pytest.fixture
def fresh():
    """Isolated scheduler/admission singletons + restored sysvars."""
    saved = {v: config.get_var(v) for v in
             ("tidb_tpu_sched_inflight", "tidb_tpu_sched_inflight_bytes",
              "tidb_tpu_server_mem_quota", "tidb_tpu_admission_timeout_ms")}
    sched.reset_for_tests()
    try:
        yield
    finally:
        for k, v in saved.items():
            config.set_var(k, v)
        sched.reset_for_tests()


class TestDeviceScheduler:
    def test_slot_cap_and_release(self, fresh):
        config.set_var("tidb_tpu_sched_inflight", 2)
        s = sched.DeviceScheduler()
        a = s.acquire()
        b = s.acquire()
        assert a.granted and b.granted
        assert s.acquire(timeout=0.05) is None      # window full
        s.release(a)
        c = s.acquire(timeout=1.0)
        assert c is not None and c.granted
        s.release(b)
        s.release(c)
        snap = s.snapshot()
        assert snap["inflight"] == 0 and snap["waiting"] == 0

    def test_disabled_is_noop(self, fresh):
        config.set_var("tidb_tpu_sched_inflight", 0)
        s = sched.DeviceScheduler()
        slots = [s.acquire() for _ in range(100)]
        assert all(sl is not None for sl in slots)
        assert s.snapshot()["inflight"] == 0      # nothing ever counted

    def test_round_robin_across_statements(self, fresh):
        """Two statements on a 1-slot window must alternate — the
        starvation fix: a long analytic query cannot hold the device
        while a point lookup waits behind its whole stream."""
        config.set_var("tidb_tpu_sched_inflight", 1)
        s = sched.DeviceScheduler()
        order: list = []

        def worker(name: str) -> None:
            root = memtrack.statement_root(None, label=name)
            with memtrack.tracking(root):
                for _ in range(5):
                    slot = s.acquire_or_bypass()
                    order.append(name)
                    time.sleep(0.004)
                    s.release(slot)

        ts = [threading.Thread(target=worker, args=(n,)) for n in "AB"]
        for t in ts:
            t.start()
        for t in ts:
            t.join(30)
        # once both streams contend, no stream runs 3+ slots back to back
        longest = run = 1
        for i in range(1, len(order)):
            run = run + 1 if order[i] == order[i - 1] else 1
            longest = max(longest, run)
        assert longest <= 2, order

    def test_bytes_gate_reads_server_ledger(self, fresh):
        config.set_var("tidb_tpu_sched_inflight", 4)
        config.set_var("tidb_tpu_sched_inflight_bytes", 1000)
        s = sched.DeviceScheduler()
        node = memtrack.server_node("sched-test-resident")
        node.consume(device=4096)       # ledger over the gate
        try:
            a = s.acquire(timeout=0.2)
            # min-progress: with nothing in flight one dispatch passes
            assert a is not None and a.granted
            b = s.acquire(timeout=0.1)
            assert b is None            # gate holds past the first
            s.release(a)
        finally:
            node.release(device=4096)
            node.detach()
        c = s.acquire(timeout=0.5)      # ledger drained: grants again
        assert c is not None and c.granted
        s.release(c)

    def test_bypass_valve_never_hangs(self, fresh, monkeypatch):
        config.set_var("tidb_tpu_sched_inflight", 1)
        monkeypatch.setattr(sched, "_BYPASS_S", 0.05)
        s = sched.DeviceScheduler()
        a = s.acquire()
        t0 = time.monotonic()
        b = s.acquire_or_bypass()       # window full: bypasses
        assert time.monotonic() - t0 < 2.0
        assert not b.granted
        s.release(b)                    # releasing a bypass slot no-ops
        assert s.snapshot()["inflight"] == 1
        assert s.snapshot()["bypasses"] == 1
        s.release(a)

    def test_pipeline_map_releases_on_abandonment(self, fresh):
        """A consumer that stops early (LIMIT) abandons the generator
        with dispatched slots in flight — the finally must hand every
        scheduler slot back or the server-wide window shrinks forever."""
        from tidb_tpu.ops import runtime as rt
        config.set_var("tidb_tpu_sched_inflight", 2)
        sched.reset_for_tests()
        gen = rt.pipeline_map(range(100), lambda i: i, lambda i, t: t,
                              depth=2)
        assert next(gen) == 0
        gen.close()                     # abandon with tokens in flight
        snap = sched.device_scheduler().snapshot()
        assert snap["inflight"] == 0 and snap["waiting"] == 0

    def test_pipeline_map_order_preserved_under_tiny_window(self, fresh):
        from tidb_tpu.ops import runtime as rt
        config.set_var("tidb_tpu_sched_inflight", 1)
        sched.reset_for_tests()
        out = list(rt.pipeline_map(range(20), lambda i: i * 3,
                                   lambda i, t: (i, t), depth=4))
        assert out == [(i, i * 3) for i in range(20)]


class TestEwmaPlacement:
    """Least-loaded chip placement consults the DECAYED busy signal
    (busy-ns EWMA, 30s halflife), not the cumulative ledger: a chip
    that absorbed a heavy scan an hour ago must not be penalized
    forever, and one that JUST did must shed load until it drains."""

    def test_placement_avoids_recently_busy_chip(self, fresh):
        config.set_var("tidb_tpu_sched_inflight", 4)
        devplane.enable_mesh(8)
        try:
            s = sched.DeviceScheduler()
            with s._cv:
                s._chip_busy_ewma[0] = 5e9   # chip 0: 5s of recent work
            slot = s.acquire()
            assert slot is not None and slot.granted
            # equal held-slot counts: the lowest-EWMA chip wins
            assert slot.chip == 1
            s.release(slot)
        finally:
            devplane.disable_mesh()

    def test_recent_signal_beats_cumulative_ledger(self, fresh):
        config.set_var("tidb_tpu_sched_inflight", 4)
        devplane.enable_mesh(8)
        try:
            s = sched.DeviceScheduler()
            with s._cv:
                # chip 0 was hammered long ago (huge cumulative, EWMA
                # fully drained); every other chip is busy RIGHT NOW
                s._chip_busy_ns[0] = int(3600e9)
                for c in range(1, 8):
                    s._chip_busy_ewma[c] = 1e9
            slot = s.acquire()
            assert slot is not None and slot.chip == 0
            s.release(slot)
        finally:
            devplane.disable_mesh()

    def test_decay_drains_ewma_not_cumulative(self, fresh):
        s = sched.DeviceScheduler()
        with s._cv:
            s._chip_busy_ewma[0] = 1e9
            s._chip_busy_ns[0] = int(1e9)
            # 10 halflives elapse: the placement signal is ~0.1% of
            # the original; the sampler's cumulative ledger is intact
            s._decay_ewma_locked(
                now=s._ewma_t + 10 * s.EWMA_HALFLIFE_S)
            assert s._chip_busy_ewma[0] < 1e9 * 2e-3
            assert s._chip_busy_ns[0] == int(1e9)

    def test_release_feeds_both_ledgers(self, fresh):
        config.set_var("tidb_tpu_sched_inflight", 4)
        devplane.enable_mesh(8)
        try:
            s = sched.DeviceScheduler()
            slot = s.acquire()
            assert slot is not None
            time.sleep(0.002)
            s.release(slot)
            chips = s.snapshot()["chips"]
            assert chips[slot.chip]["busy_seconds"] > 0
            assert chips[slot.chip]["busy_ewma_seconds"] > 0
        finally:
            devplane.disable_mesh()


class TestAdmission:
    def test_off_by_default(self, fresh):
        config.set_var("tidb_tpu_server_mem_quota", 0)
        adm = sched.AdmissionController()
        assert adm.admit(1 << 30) is None
        adm.finish(None)                # None-safe

    def test_admit_and_finish_bookkeeping(self, fresh):
        config.set_var("tidb_tpu_server_mem_quota", 1 << 30)
        adm = sched.AdmissionController()
        t1 = adm.admit(1 << 20)
        t2 = adm.admit(1 << 20)
        snap = adm.snapshot()
        assert snap["running"] == 2 and snap["reserved_bytes"] == 2 << 20
        adm.finish(t1)
        adm.finish(t2)
        snap = adm.snapshot()
        assert snap["running"] == 0 and snap["reserved_bytes"] == 0
        assert snap["admitted"] == 2

    def test_min_progress_under_tiny_quota(self, fresh):
        """A quota below any projection must serialize, not brick: the
        head statement always runs when nothing else is admitted."""
        config.set_var("tidb_tpu_server_mem_quota", 1)
        config.set_var("tidb_tpu_admission_timeout_ms", 200)
        adm = sched.AdmissionController()
        t1 = adm.admit(1 << 20)
        assert t1 is not None
        adm.finish(t1)

    def test_queue_then_admit_on_finish(self, fresh):
        config.set_var("tidb_tpu_server_mem_quota", 100)  # reserve-bound
        config.set_var("tidb_tpu_admission_timeout_ms", 5000)
        adm = sched.AdmissionController()
        t1 = adm.admit(1 << 20)         # min-progress head
        got: list = []

        def second() -> None:
            got.append(adm.admit(1 << 20))

        th = threading.Thread(target=second)
        th.start()
        time.sleep(0.25)
        assert not got                  # still queued behind t1
        adm.finish(t1)
        th.join(30)
        assert got and got[0] is not None
        adm.finish(got[0])
        snap = adm.snapshot()
        # the waiter admitted only after finish(); it counts as `queued`
        # — or as `shed` when the full suite left SERVER residency whose
        # registered spill action freed bytes along the way
        assert snap["queued"] + snap["shed"] == 1, snap

    def test_reject_is_retryable_9008(self, fresh):
        config.set_var("tidb_tpu_server_mem_quota", 100)
        config.set_var("tidb_tpu_admission_timeout_ms", 100)
        adm = sched.AdmissionController()
        t1 = adm.admit(1 << 20)
        with pytest.raises(sched.AdmissionRejectedError) as ei:
            adm.admit(1 << 20)
        code, state, msg = errcode.classify(ei.value)
        assert code == errcode.ER_SERVER_BUSY_ADMISSION == 9008
        assert errcode.is_retryable(code)
        assert "retry" in msg
        adm.finish(t1)
        assert adm.snapshot()["rejected"] == 1

    def test_overflow_drives_shed_chain(self, fresh):
        """Projected overflow fires the SERVER shed chain BEFORE
        queueing: resident bytes with a registered spill action are
        reclaimed and the statement admits with outcome `shed`."""
        node = memtrack.server_node("admission-test-resident")
        node.consume(device=8 << 20)

        def drop() -> None:
            with node._mu:
                held = node.device
            if held:
                node.release(device=held)

        memtrack.SERVER.add_spill_action(drop)
        try:
            config.set_var("tidb_tpu_server_mem_quota", 9 << 20)
            config.set_var("tidb_tpu_admission_timeout_ms", 2000)
            adm = sched.AdmissionController()
            t1 = adm.admit(4 << 20)     # min-progress head
            t2 = adm.admit(4 << 20)     # 8M resident + 4M + 4M > 9M: shed
            assert t2 is not None
            snap = adm.snapshot()
            assert snap["shed"] == 1 and snap["shed_bytes"] >= 8 << 20
            assert memtrack.SERVER.device == 0
            adm.finish(t1)
            adm.finish(t2)
        finally:
            memtrack.SERVER.remove_spill_action(drop)
            drop()
            node.detach()


class TestRunSpillActions:
    def test_target_and_recursion(self, fresh):
        root = memtrack.statement_root(memtrack.SERVER, label="spilltest")
        root.consume(host=1000)
        freed_calls: list = []

        def spill() -> None:
            freed_calls.append(1)
            with root._mu:
                held = root.host
            if held:
                root.release(host=held)

        root.add_spill_action(spill)
        try:
            # target above current total: nothing fires
            assert memtrack.SERVER.run_spill_actions(
                memtrack.SERVER.total() + 1, recurse=True) == 0
            assert not freed_calls
            # recurse reaches the statement root's action
            freed = memtrack.SERVER.run_spill_actions(0, recurse=True)
            assert freed >= 1000 and freed_calls
        finally:
            root.detach()

    def test_hbm_cache_shed_returns_ledger_to_zero(self, fresh):
        """The armed shed chain (ISSUE 10 satellite): one shed call —
        the /shed endpoint's body — returns the hbm-cache ledger to 0."""
        from tidb_tpu.chunk import Chunk, Column
        from tidb_tpu.sqltypes import FieldType, TypeCode
        from tidb_tpu.store.device_cache import DeviceCache, tracker

        ft = FieldType(TypeCode.LONGLONG)
        chunk = Chunk([Column(ft, np.arange(2048, dtype=np.int64),
                              np.ones(2048, dtype=bool))])
        cache = DeviceCache()
        block = cache.fill(("k",), 1, 10, chunk)
        assert block is not None
        assert cache.resident_bytes() > 0
        assert tracker().device > 0
        freed = sched.shed_server(0)
        assert freed >= block.nbytes
        assert cache.resident_bytes() == 0
        assert tracker().device == 0


class TestSessionAdmission:
    def test_statement_rejected_then_recovers(self, fresh):
        """An executable statement hits the retryable 9008 while the
        server is saturated; control statements (SET) still run; once
        the saturating ticket finishes the same statement succeeds."""
        from tidb_tpu.session import Session
        from tidb_tpu.store.storage import new_mock_storage

        storage = new_mock_storage()
        s = Session(storage)
        s.execute("CREATE DATABASE adm")
        s.execute("USE adm")
        s.execute("CREATE TABLE t (id BIGINT PRIMARY KEY, v BIGINT)")
        s.execute("INSERT INTO t VALUES (1, 10), (2, 20)")
        try:
            config.set_var("tidb_tpu_server_mem_quota", 100)
            config.set_var("tidb_tpu_admission_timeout_ms", 100)
            blocker = sched.admission().admit(1 << 20)  # saturate
            assert blocker is not None
            with pytest.raises(sched.AdmissionRejectedError):
                s.query("SELECT SUM(v) FROM t")
            # control statements bypass admission entirely
            s.execute("SET tidb_tpu_superchunk_rows = 262144")
            sched.admission().finish(blocker)
            # min-progress now admits it
            assert s.query("SELECT SUM(v) FROM t").rows == [(30,)]
            counts = sched.stats()["admission"]
            assert counts["rejected"] >= 1
        finally:
            config.set_var("tidb_tpu_server_mem_quota", 0)
            s.close()
            storage.close()
