"""Replicated out-of-process storage (store/remote.py primary/backup log
shipping; ref: the Raft-replicated TiKV store the reference's client stack
assumes — region_request.go retries onto new leaders after a node dies).

The acceptance bar (VERDICT r4 #5): kill -9 the primary mid-scan and
mid-commit; queries complete after failover with ZERO lost committed
writes."""

import os
import signal
import socket
import subprocess
import sys
import threading
import time

import pytest

from tidb_tpu.session import Session
from tidb_tpu.store.remote import connect


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _spawn(port, extra):
    proc = subprocess.Popen(
        [sys.executable, "-m", "tidb_tpu.store.remote",
         "--port", str(port)] + extra,
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        cwd="/root/repo", env={"PYTHONPATH": "/root/repo",
                               "PATH": "/usr/bin:/bin",
                               "JAX_PLATFORMS": "cpu",
                               "HOME": "/root"})
    line = proc.stdout.readline()
    assert "storage listening" in line, line
    return proc


@pytest.fixture
def pair():
    """primary + backup processes, primary ships synchronously."""
    p_port, b_port = _free_port(), _free_port()
    backup = _spawn(b_port, ["--role", "backup"])
    primary = _spawn(p_port, ["--backup", f"127.0.0.1:{b_port}"])
    yield p_port, b_port, primary, backup
    for proc in (primary, backup):
        if proc.poll() is None:
            proc.kill()
        proc.wait(timeout=20)


class TestReplication:
    def test_failover_zero_lost_committed_writes(self, pair):
        p_port, b_port, primary, _backup = pair
        st = connect("127.0.0.1", p_port, ("127.0.0.1", b_port))
        s = Session(st)
        s.execute("CREATE DATABASE d; USE d")
        s.execute("CREATE TABLE t (id BIGINT PRIMARY KEY, v BIGINT)")
        committed = []
        for i in range(50):
            s.execute(f"INSERT INTO t VALUES ({i}, {i * 7})")
            committed.append(i)

        primary.send_signal(signal.SIGKILL)      # kill -9, no snapshot
        primary.wait(timeout=20)

        # every committed row survives, served by the promoted backup
        r = s.query("SELECT COUNT(*), SUM(v) FROM t")
        assert r.rows == [(50, sum(i * 7 for i in range(50)))]

        # the promoted node accepts new writes and fresh sessions
        s.execute("INSERT INTO t VALUES (1000, 1)")
        assert s.query("SELECT COUNT(*) FROM t").rows == [(51,)]
        st2 = connect("127.0.0.1", p_port, ("127.0.0.1", b_port))
        s2 = Session(st2)
        s2.execute("USE d")
        assert s2.query("SELECT COUNT(*) FROM t").rows == [(51,)]
        s2.close(); st2.close()
        s.close(); st.close()

    def test_kill_mid_scan(self, pair):
        """Primary dies while a scan workload is running: reads keep
        completing (some after transparent failover), none wrong."""
        p_port, b_port, primary, _backup = pair
        st = connect("127.0.0.1", p_port, ("127.0.0.1", b_port))
        s = Session(st)
        s.execute("CREATE DATABASE d; USE d")
        s.execute("CREATE TABLE t (id BIGINT PRIMARY KEY, v BIGINT)")
        s.execute("INSERT INTO t VALUES " + ",".join(
            f"({i},{i})" for i in range(2000)))
        want = sum(range(2000))

        stop = threading.Event()

        def killer():
            time.sleep(0.3)
            primary.send_signal(signal.SIGKILL)
            stop.set()

        t = threading.Thread(target=killer)
        t.start()
        results = []
        deadline = time.monotonic() + 30
        while (not stop.is_set() or len(results) < 25) and \
                time.monotonic() < deadline:
            results.append(
                s.query("SELECT SUM(v), COUNT(*) FROM t").rows[0])
        t.join()
        assert len(results) >= 25
        assert all(r == (want, 2000) for r in results)
        s.close(); st.close()

    def test_kill_mid_commit_no_partial_visible(self, pair):
        """Primary dies while commits are in flight. Afterward every
        transaction is all-or-nothing: a txn's 3 rows are all visible or
        none are (Percolator atomicity across failover — undetermined
        commits get resolved by the lock resolver on read)."""
        p_port, b_port, primary, _backup = pair
        st = connect("127.0.0.1", p_port, ("127.0.0.1", b_port))
        s = Session(st)
        s.execute("CREATE DATABASE d; USE d")
        s.execute("CREATE TABLE t (id BIGINT PRIMARY KEY, g BIGINT)")

        acked = []
        failed = []

        def writer():
            st_w = connect("127.0.0.1", p_port, ("127.0.0.1", b_port))
            sw = Session(st_w)
            sw.execute("USE d")
            g = 0
            while not stop.is_set() and g < 200:
                base = g * 3
                try:
                    sw.execute(
                        f"INSERT INTO t VALUES ({base},{g}),"
                        f"({base + 1},{g}),({base + 2},{g})")
                    acked.append(g)
                except Exception:   # noqa: BLE001 — undetermined is legal
                    failed.append(g)
                g += 1
            sw.close(); st_w.close()

        stop = threading.Event()
        w = threading.Thread(target=writer)
        w.start()
        time.sleep(0.4)
        primary.send_signal(signal.SIGKILL)
        time.sleep(1.0)
        stop.set()
        w.join(timeout=60)

        rows = s.query("SELECT g, COUNT(*) FROM t GROUP BY g").rows
        by_group = dict(rows)
        # atomicity: any visible group has exactly its 3 rows
        assert all(c == 3 for c in by_group.values()), by_group
        # durability: every acked txn is fully visible
        for g in acked:
            assert by_group.get(g) == 3, f"acked txn {g} lost"
        s.close(); st.close()

    def test_backup_rejects_direct_writes(self, pair):
        p_port, b_port, _primary, _backup = pair
        from tidb_tpu import kv
        from tidb_tpu.store.remote import _Conn
        c = _Conn(("127.0.0.1", b_port))
        try:
            with pytest.raises(kv.NotLeaderError):
                c.call("tso", (), {})
        finally:
            c.close()

    def test_late_attaching_backup_syncs_snapshot(self):
        """A backup that starts AFTER data exists pulls a full state
        snapshot from the primary, then follows the log."""
        p_port, b_port = _free_port(), _free_port()
        primary = _spawn(p_port, ["--backup", f"127.0.0.1:{b_port}"])
        backup = None
        try:
            st = connect("127.0.0.1", p_port, ("127.0.0.1", b_port))
            s = Session(st)
            s.execute("CREATE DATABASE d; USE d")
            s.execute("CREATE TABLE t (id BIGINT PRIMARY KEY, v BIGINT)")
            s.execute("INSERT INTO t VALUES (1, 10), (2, 20)")

            backup = _spawn(b_port, ["--role", "backup",
                                     "--primary", f"127.0.0.1:{p_port}"])
            # primary degraded to solo while the backup was absent; the
            # next mutations trigger the automatic full-state re-sync
            # (repl_install), after which shipping resumes — so EVERY
            # acked row must survive the failover
            s.execute("INSERT INTO t VALUES (3, 30)")
            time.sleep(1.2)                  # resync retry interval
            s.execute("INSERT INTO t VALUES (4, 40)")

            primary.send_signal(signal.SIGKILL)
            primary.wait(timeout=20)
            r = s.query("SELECT COUNT(*), SUM(v) FROM t")
            assert r.rows == [(4, 100)]      # zero lost acked writes
            s.close(); st.close()
        finally:
            for proc in (primary, backup):
                if proc is not None:
                    if proc.poll() is None:
                        proc.kill()
                    proc.wait(timeout=20)
