"""Builtin function breadth (expression/builtins.py registry; ref:
expression/builtin_math.go, builtin_string.go, builtin_time.go,
builtin_encryption.go). Expected values follow MySQL semantics."""

import math

import pytest

from tidb_tpu.session import Session, SQLError
from tidb_tpu.store.storage import new_mock_storage


@pytest.fixture(scope="module")
def sess():
    s = Session(new_mock_storage())
    s.execute("CREATE DATABASE d")
    s.execute("USE d")
    s.execute("CREATE TABLE t (id BIGINT PRIMARY KEY, x DOUBLE, "
              "s VARCHAR(40), d DATETIME)")
    s.execute("INSERT INTO t VALUES "
              "(1, 2.0, 'hello world', '2024-03-15 10:30:45'),"
              "(2, -9.5, 'a,b,c', '2024-12-31 23:59:59'),"
              "(3, 0.25, NULL, NULL)")
    yield s
    s.close()


def one(sess, expr, where="id=1"):
    return sess.query(f"SELECT {expr} FROM t WHERE {where}").rows[0][0]


class TestMath:
    @pytest.mark.parametrize("expr,want", [
        ("SIN(x)", math.sin(2.0)), ("COS(x)", math.cos(2.0)),
        ("TAN(x)", math.tan(2.0)), ("COT(x)", 1 / math.tan(2.0)),
        ("ATAN(x)", math.atan(2.0)), ("ATAN(1, 1)", math.pi / 4),
        ("ATAN2(1, 1)", math.pi / 4), ("LOG(x)", math.log(2.0)),
        ("LOG(10, 100)", 2.0), ("LOG10(100)", 2.0),
        ("PI()", math.pi), ("DEGREES(PI())", 180.0),
        ("RADIANS(180)", math.pi), ("TRUNCATE(1.999, 1)", 1.9),
        ("TRUNCATE(-1.999, 1)", -1.9),
    ])
    def test_value(self, sess, expr, want):
        assert one(sess, expr) == pytest.approx(want, rel=1e-12)

    def test_asin_domain_error_is_null(self, sess):
        assert one(sess, "ASIN(x)") is None      # ASIN(2.0)

    def test_crc32_conv_bin_oct_hex(self, sess):
        assert one(sess, "CRC32('MySQL')") == 3259397556
        assert one(sess, "CONV('a', 16, 2)") == "1010"
        assert one(sess, "CONV(6, 10, 2)") == "110"
        assert one(sess, "BIN(12)") == "1100"
        assert one(sess, "OCT(12)") == "14"
        assert one(sess, "HEX(255)") == "FF"
        assert one(sess, "HEX('abc')") == "616263"
        # UNHEX yields VARBINARY (bytes), like MySQL's binary string
        assert one(sess, "UNHEX('4D7953514C')") == b"MySQL"

    def test_truncate_toward_zero_and_twos_complement(self, sess):
        assert one(sess, "TRUNCATE(-199, -1)") == -190
        assert one(sess, "TRUNCATE(199, -2)") == 100
        assert one(sess, "HEX(-1)") == "F" * 16
        assert one(sess, "BIN(-1)") == "1" * 64

    def test_rand(self, sess):
        v = one(sess, "RAND()")
        assert 0.0 <= v < 1.0
        assert one(sess, "RAND(5)") == one(sess, "RAND(5)")


class TestString:
    @pytest.mark.parametrize("expr,want", [
        ("CHAR_LENGTH(s)", 11), ("BIT_LENGTH('abc')", 24),
        ("LPAD('hi', 4, '?')", "??hi"), ("RPAD('hi', 4, '?')", "hi??"),
        ("LPAD('hello', 3, '?')", "hel"),
        ("REPEAT('ab', 3)", "ababab"), ("REVERSE('abc')", "cba"),
        ("SPACE(3)", "   "), ("STRCMP('b', 'a')", 1),
        ("STRCMP('a', 'b')", -1), ("STRCMP('a', 'a')", 0),
        ("LOCATE('world', s)", 7), ("LOCATE('xyz', s)", 0),
        ("LOCATE('o', s, 6)", 8),
        ("LTRIM('  x ')", "x "), ("RTRIM(' x  ')", " x"),
        ("QUOTE("
         "'don''t')", "'don\\'t'"),
        ("SUBSTRING_INDEX('www.mysql.com', '.', 2)", "www.mysql"),
        ("SUBSTRING_INDEX('www.mysql.com', '.', -2)", "mysql.com"),
        ("FIND_IN_SET('b', 'a,b,c')", 2),
        ("FIND_IN_SET('z', 'a,b,c')", 0),
        ("ELT(1, 'ej', 'heja')", "ej"),
        ("FIELD('ej', 'Hej', 'ej', 'Heja')", 2),
        ("MID(s, 1, 5)", "hello"),
    ])
    def test_value(self, sess, expr, want):
        assert one(sess, expr) == want

    def test_pad_negative_length_is_null(self, sess):
        assert one(sess, "LPAD('hi', -1, '?')") is None
        assert one(sess, "RPAD('hi', -1, '?')") is None

    def test_concat_ws_skips_nulls(self, sess):
        assert one(sess, "CONCAT_WS(',', 'a', NULL, 'b')") == "a,b"
        assert one(sess, "CONCAT_WS(NULL, 'a', 'b')") is None


class TestCompare:
    def test_greatest_least(self, sess):
        assert one(sess, "GREATEST(2, 0)") == 2
        assert one(sess, "GREATEST(34.0, 3.0, 5.0, 767.0)") == 767.0
        assert one(sess, "LEAST('B', 'A', 'C')") == "A"
        assert one(sess, "GREATEST(x, 0)", "id=2") == 0.0

    def test_isnull_nullif(self, sess):
        assert one(sess, "ISNULL(s)", "id=3") == 1
        assert one(sess, "ISNULL(s)") == 0
        assert one(sess, "NULLIF(1, 1)") is None
        assert one(sess, "NULLIF(1, 2)") == 1


class TestTime:
    # 2024-03-15 is a Friday, day 75, Q1, week 10 (mode 0)
    @pytest.mark.parametrize("expr,want", [
        ("DAYOFWEEK(d)", 6), ("WEEKDAY(d)", 4), ("DAYOFYEAR(d)", 75),
        ("QUARTER(d)", 1), ("WEEK(d)", 10), ("YEARWEEK(d)", 202410),
        ("MONTHNAME(d)", "March"), ("DAYNAME(d)", "Friday"),
        ("TO_DAYS(d)", 739325),
        ("UNIX_TIMESTAMP(d)", 1710498645),
        ("MICROSECOND(d)", 0),
        ("DATE_FORMAT(d, '%Y-%m-%d')", "2024-03-15"),
        ("DATE_FORMAT(d, '%W %M %Y')", "Friday March 2024"),
        ("DATE_FORMAT(d, '%H:%i:%s')", "10:30:45"),
    ])
    def test_value(self, sess, expr, want):
        assert one(sess, expr) == want

    def test_week_modes_and_yearweek_rollback(self, sess):
        assert one(sess, "WEEK('2024-01-01')") == 0
        assert one(sess, "WEEK('2024-01-01', 1)") == 1
        assert one(sess, "WEEK('2024-01-01', 3)") == 1
        assert one(sess, "WEEK('2019-12-30', 1)") == 53
        assert one(sess, "YEARWEEK('2024-01-01')") == 202353

    def test_week_null_mode_is_null(self, sess):
        assert one(sess, "WEEK('2024-01-01', NULL)") is None

    def test_string_datetime_literals(self, sess):
        assert one(sess, "DAYNAME('2024-03-15')") == "Friday"
        assert one(sess, "LAST_DAY('2024-02-10')") == \
            "2024-02-29 00:00:00"

    def test_last_day_from_unixtime(self, sess):
        assert one(sess, "LAST_DAY(d)") == "2024-03-31 00:00:00"
        assert one(sess, "FROM_UNIXTIME(1710498645)") == \
            "2024-03-15 10:30:45"

    def test_leap_quarter_edges(self, sess):
        assert one(sess, "DAYOFYEAR(d)", "id=2") == 366   # 2024 is leap
        assert one(sess, "QUARTER(d)", "id=2") == 4
        assert one(sess, "DAYNAME(d)", "id=2") == "Tuesday"


class TestCrypto:
    def test_digests(self, sess):
        assert one(sess, "MD5('abc')") == \
            "900150983cd24fb0d6963f7d28e17f72"
        assert one(sess, "SHA1('abc')") == \
            "a9993e364706816aba3e25717850c26c9cd0d89d"
        assert one(sess, "SHA2('abc', 256)") == (
            "ba7816bf8f01cfea414140de5dae2223"
            "b00361a396177a9cb410ff61f20015ad")
        assert one(sess, "SHA2('abc', 1)") is None   # bad bit width


class TestNullsAndErrors:
    @pytest.mark.parametrize("expr", [
        "SIN(d)", "REVERSE(s)", "DAYOFWEEK(d)", "MD5(s)",
        "DATE_FORMAT(d, '%Y')", "LPAD(s, 3, 'x')",
    ])
    def test_null_propagates(self, sess, expr):
        assert one(sess, expr, "id=3") is None

    def test_arity_errors(self, sess):
        with pytest.raises(SQLError):
            sess.query("SELECT SIN() FROM t")
        with pytest.raises(SQLError):
            sess.query("SELECT LPAD('a') FROM t")
        with pytest.raises(SQLError):
            sess.query("SELECT NO_SUCH_FN(1) FROM t")

    def test_generic_in_where_and_group(self, sess):
        # builtins compose with filters, grouping, and core ops
        r = sess.query("SELECT QUARTER(d), COUNT(*) FROM t "
                       "WHERE d IS NOT NULL AND DAYOFWEEK(d) > 0 "
                       "GROUP BY QUARTER(d) ORDER BY 1").rows
        assert r == [(1, 1), (4, 1)]


class TestRemotePushdown:
    def test_generic_filter_over_storage_rpc(self):
        """GENERIC specs pickle by name across the storage RPC (host
        filters ride inside the pushed cop plan)."""
        from tidb_tpu.store.remote import StorageServer, connect
        srv = StorageServer()
        srv.start()
        st = connect("127.0.0.1", srv.port)
        try:
            s = Session(st)
            s.execute("CREATE DATABASE r")
            s.execute("USE r")
            s.execute("CREATE TABLE t (id BIGINT PRIMARY KEY, "
                      "s VARCHAR(10))")
            s.execute("INSERT INTO t VALUES (1,'abc'), (2,'wxyz')")
            assert s.query("SELECT id FROM t WHERE CHAR_LENGTH(s) = 3"
                           ).rows == [(1,)]
            assert s.query("SELECT id FROM t WHERE SIN(id) < 0.9"
                           ).rows == [(1,)]
            s.close()
        finally:
            st.close()
            srv.close()


class TestGroupConcat:
    """GROUP_CONCAT through the partial/final protocol (host-only agg;
    ref: expression/aggregation concat)."""

    @pytest.fixture
    def gsess(self):
        s = Session(new_mock_storage())
        s.execute("CREATE DATABASE gc")
        s.execute("USE gc")
        s.execute("CREATE TABLE t (id BIGINT PRIMARY KEY, g BIGINT, "
                  "name VARCHAR(10), n BIGINT)")
        s.execute("INSERT INTO t VALUES (1,1,'a',10),(2,1,'b',20),"
                  "(3,2,'c',30),(4,2,NULL,40)")
        yield s
        s.close()

    def test_basic_and_separator(self, gsess):
        assert gsess.query("SELECT g, GROUP_CONCAT(name) FROM t "
                           "GROUP BY g ORDER BY g").rows == \
            [(1, "a,b"), (2, "c")]
        assert gsess.query("SELECT GROUP_CONCAT(name SEPARATOR '-') "
                           "FROM t WHERE g = 1").rows == [("a-b",)]

    def test_numeric_distinct_null(self, gsess):
        assert gsess.query("SELECT GROUP_CONCAT(n) FROM t").rows == \
            [("10,20,30,40",)]
        assert gsess.query("SELECT GROUP_CONCAT(DISTINCT g) FROM t"
                           ).rows == [("1,2",)]
        assert gsess.query("SELECT GROUP_CONCAT(name) FROM t "
                           "WHERE name IS NULL").rows == [(None,)]

    def test_display_formatting(self, gsess):
        gsess.execute("CREATE TABLE fmt (id BIGINT PRIMARY KEY, "
                      "amt DECIMAL(5,2), dt DATETIME, x DOUBLE)")
        gsess.execute("INSERT INTO fmt VALUES "
                      "(1, 12.34, '2024-01-02 03:04:05', 10), "
                      "(2, 5.60, '2024-06-07 08:09:10', 2.5)")
        r = gsess.query("SELECT GROUP_CONCAT(amt), GROUP_CONCAT(dt), "
                        "GROUP_CONCAT(x) FROM fmt").rows
        assert r == [("12.34,5.60",
                      "2024-01-02 03:04:05,2024-06-07 08:09:10",
                      "10,2.5")]

    def test_partials_merge_across_regions(self, gsess):
        gsess.query("SPLIT TABLE t REGIONS 3")
        assert gsess.query("SELECT g, GROUP_CONCAT(name) FROM t "
                           "GROUP BY g ORDER BY g").rows == \
            [(1, "a,b"), (2, "c")]


class TestBitOps:
    """Bitwise operators (ref: expression/builtin_op.go bitAndSig etc.).
    MySQL's domain is BIGINT UNSIGNED; ours is the same 64 bits viewed
    signed (docs/DEVIATIONS.md) — identical for &,|,^,<< and for >> of
    non-negative values."""

    def test_basic(self, sess):
        assert sess.query(
            "SELECT 5 & 3, 5 | 3, 5 ^ 3, 1 << 3, 16 >> 2").rows == \
            [(1, 7, 6, 8, 4)]

    def test_neg_and_precedence(self, sess):
        assert sess.query("SELECT ~5, ~~7").rows == [(-6, 7)]
        # ^ binds tighter than *; | tighter than = (MySQL ladder)
        assert sess.query("SELECT 3 ^ 1 * 2").rows == [(4,)]
        assert sess.query("SELECT 2 | 1 = 3").rows == [(1,)]

    def test_shift_out_of_range_and_logical_shr(self, sess):
        assert sess.query(
            "SELECT 1 << 64, 1 << 100, 8 >> 64, 5 << -1").rows == \
            [(0, 0, 0, 0)]
        # >> is a logical shift on the 64-bit word, not arithmetic
        assert sess.query("SELECT -8 >> 1").rows == \
            [(9223372036854775804,)]

    def test_rounds_fractional_operands(self, sess):
        assert sess.query("SELECT 1.6 & 3, 2.4 | 0").rows == [(2, 2)]

    def test_null_propagates(self, sess):
        assert sess.query("SELECT NULL & 1, 1 << NULL, ~NULL").rows == \
            [(None, None, None)]

    def test_on_columns_both_paths(self, sess):
        try:
            for dev in (1, 0):
                sess.execute(f"SET @@tidb_tpu_device = {dev}")
                assert sess.query(
                    "SELECT x FROM t WHERE CAST(x AS SIGNED) & 2 = 2 "
                    "ORDER BY id").rows == [(2.0,), (-9.5,)]
        finally:
            sess.execute("SET @@tidb_tpu_device = 1")

    def test_huge_string_operand_clamps(self, sess):
        # float('1e300') overflows int64: clamp, don't crash
        assert sess.query("SELECT '1e300' & 1").rows == [(1,)]
        assert sess.query("SELECT CAST('1e300' AS SIGNED)").rows == \
            [(9223372036854775807,)]

    def test_huge_double_saturates_not_wraps(self, sess):
        """float(2^63) cast straight to int64 wraps to INT64_MIN; the
        vectorized path must saturate like the string path does."""
        try:
            for dev in (1, 0):
                sess.execute(f"SET @@tidb_tpu_device = {dev}")
                assert sess.query(
                    "SELECT CAST(1e300 AS SIGNED), 1e300 & 1, "
                    "CAST(-1e300 AS SIGNED), CAST(9.3e18 AS SIGNED)"
                ).rows == [(9223372036854775807, 1,
                            -9223372036854775808, 9223372036854775807)]
        finally:
            sess.execute("SET @@tidb_tpu_device = 1")


class TestCastRounding:
    def test_cast_int_rounds_half_away(self, sess):
        assert sess.query(
            "SELECT CAST(3.7 AS SIGNED), CAST(-3.7 AS SIGNED), "
            "CAST(2.5 AS SIGNED), CAST(-2.5 AS SIGNED), "
            "CAST(3.4 AS SIGNED)").rows == [(4, -4, 3, -3, 3)]

    def test_cast_string_rounds(self, sess):
        assert sess.query("SELECT CAST('3.7' AS SIGNED)").rows == [(4,)]

    def test_no_double_round_at_boundary(self, sess):
        # 0.49999999999999994 + 0.5 is exactly 1.0 in IEEE double; a
        # floor(x+0.5) implementation would wrongly yield 1
        assert sess.query(
            "SELECT CAST(0.49999999999999994e0 AS SIGNED), "
            "CAST(-0.49999999999999994e0 AS SIGNED)").rows == [(0, 0)]


class TestTimeUnitsAndPatterns:
    """EXTRACT, sub-day INTERVAL units, calendar-exact month shifts,
    TIMESTAMPDIFF/ADD, LIKE ESCAPE, REGEXP/RLIKE, BINARY (ref:
    expression/builtin_time.go, builtin_like.go)."""

    def test_extract(self, sess):
        assert sess.query(
            "SELECT EXTRACT(YEAR FROM d), EXTRACT(MONTH FROM d), "
            "EXTRACT(MINUTE FROM d), EXTRACT(YEAR_MONTH FROM d) "
            "FROM t WHERE id = 1").rows == [(2024, 3, 30, 202403)]

    def test_subday_intervals(self, sess):
        assert sess.query(
            "SELECT DATE_ADD('2024-01-15 10:00:00', INTERVAL 5 HOUR), "
            "DATE_SUB('2024-01-15 00:00:00', INTERVAL 90 SECOND), "
            "DATE_ADD('2024-01-15 00:00:00', INTERVAL 30 MINUTE)"
        ).rows == [("2024-01-15 15:00:00", "2024-01-14 23:58:30",
                    "2024-01-15 00:30:00")]

    def test_month_shift_clamps_on_columns(self, sess):
        # non-constant base goes through the branch-free device op;
        # Jan 31 + 1 month clamps to Feb 29 (2024 is a leap year)
        assert sess.query(
            "SELECT DATE_ADD(d, INTERVAL 1 MONTH) FROM t WHERE id = 2"
        ).rows == [("2025-01-31 23:59:59",)]
        sess.execute("INSERT INTO t VALUES (90, 1.0, 'x', "
                     "'2024-01-31 08:00:00')")
        try:
            assert sess.query(
                "SELECT DATE_ADD(d, INTERVAL 1 MONTH), "
                "DATE_SUB(d, INTERVAL 11 MONTH) FROM t WHERE id = 90"
            ).rows == [("2024-02-29 08:00:00", "2023-02-28 08:00:00")]
        finally:
            sess.execute("DELETE FROM t WHERE id = 90")

    def test_timestampdiff(self, sess):
        assert sess.query(
            "SELECT TIMESTAMPDIFF(MONTH, '2024-01-15', '2024-03-16'), "
            "TIMESTAMPDIFF(MONTH, '2024-01-15', '2024-03-14'), "
            "TIMESTAMPDIFF(DAY, '2024-03-16', '2024-03-10'), "
            "TIMESTAMPDIFF(YEAR, '2022-06-01', '2024-05-31')"
        ).rows == [(2, 1, -6, 1)]

    def test_timestampadd(self, sess):
        assert sess.query(
            "SELECT TIMESTAMPADD(HOUR, 26, '2024-01-15 00:00:00')"
        ).rows == [("2024-01-16 02:00:00",)]

    def test_like_escape(self, sess):
        assert sess.query("SELECT 'a_b' LIKE 'a|_b' ESCAPE '|', "
                          "'axb' LIKE 'a|_b' ESCAPE '|', "
                          "'a%b' LIKE 'a|%b' ESCAPE '|'").rows == \
            [(1, 0, 1)]

    def test_regexp(self, sess):
        assert sess.query(
            "SELECT 'abc123' REGEXP '^abc[0-9]+$', "
            "'xyz' RLIKE 'a', 'xyz' NOT REGEXP 'a', "
            "'xabcx' REGEXP 'abc'").rows == [(1, 0, 1, 1)]
        assert sess.query(
            "SELECT s FROM t WHERE s REGEXP '^hello' AND id = 1"
        ).rows == [("hello world",)]

    def test_binary_operator_noop(self, sess):
        # collations are code-point everywhere; BINARY is the identity
        assert sess.query("SELECT BINARY 'A' = 'a', BINARY 'a' = 'a'"
                          ).rows == [(0, 1)]


class TestNegativeIntervalsAndErrors:
    def test_negative_amounts(self, sess):
        assert sess.query(
            "SELECT TIMESTAMPADD(HOUR, -2, '2024-03-31 01:00:00'), "
            "DATE_ADD('2024-03-31 01:00:00', INTERVAL -1 MONTH)"
        ).rows == [("2024-03-30 23:00:00", "2024-02-29 01:00:00")]

    def test_bad_regexp_is_sql_error(self, sess):
        with pytest.raises(SQLError, match="regexp"):
            sess.query("SELECT 'x' REGEXP '['")

    def test_bad_tsdiff_unit_is_sql_error(self, sess):
        with pytest.raises(SQLError, match="TIMESTAMPDIFF unit"):
            sess.query("SELECT TIMESTAMPDIFF(FORTNIGHT, d, d) FROM t")
