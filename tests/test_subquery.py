"""Subquery tests: EXISTS / IN / scalar, correlated and uncorrelated.

Ref model: executor tests for NestedLoopApplyExec + expression_rewriter
subquery cases (executor/executor_test.go TestSubquery-style SQL).
"""

import pytest

from tidb_tpu.session import Session, SQLError
from tidb_tpu.store import new_mock_storage


@pytest.fixture
def tk():
    storage = new_mock_storage()
    storage.async_commit_secondaries = False
    s = Session(storage)
    s.execute("CREATE DATABASE test; USE test")
    s.execute("CREATE TABLE t (a BIGINT PRIMARY KEY, b INT, c DOUBLE)")
    s.execute("INSERT INTO t VALUES (1, 10, 1.5), (2, 20, 2.5), "
              "(3, 30, 3.5), (4, NULL, 4.5)")
    s.execute("CREATE TABLE u (x BIGINT PRIMARY KEY, y INT)")
    s.execute("INSERT INTO u VALUES (1, 10), (2, 20), (5, NULL)")
    yield s
    s.close()
    storage.close()


def q(tk, sql):
    return tk.query(sql).rows


class TestUncorrelated:
    def test_in_subquery(self, tk):
        assert q(tk, "SELECT a FROM t WHERE b IN (SELECT y FROM u) "
                     "ORDER BY a") == [(1,), (2,)]

    def test_not_in_with_null_inner(self, tk):
        # u.y contains NULL: NOT IN is never TRUE (three-valued logic)
        assert q(tk, "SELECT a FROM t WHERE b NOT IN (SELECT y FROM u)") \
            == []

    def test_not_in_without_nulls(self, tk):
        assert q(tk, "SELECT a FROM t WHERE b NOT IN "
                     "(SELECT y FROM u WHERE y IS NOT NULL) ORDER BY a") \
            == [(3,)]

    def test_exists(self, tk):
        assert q(tk, "SELECT a FROM t WHERE EXISTS (SELECT 1 FROM u "
                     "WHERE x = 99) ORDER BY a") == []
        rows = q(tk, "SELECT a FROM t WHERE EXISTS "
                     "(SELECT 1 FROM u WHERE x = 1) ORDER BY a")
        assert rows == [(1,), (2,), (3,), (4,)]

    def test_scalar_compare(self, tk):
        assert q(tk, "SELECT a FROM t WHERE b > (SELECT AVG(y) FROM u) "
                     "ORDER BY a") == [(2,), (3,)]
        # subquery on the left flips the comparison
        assert q(tk, "SELECT a FROM t WHERE (SELECT MAX(y) FROM u) <= b "
                     "ORDER BY a") == [(2,), (3,)]

    def test_scalar_empty_is_null(self, tk):
        assert q(tk, "SELECT a FROM t WHERE b = "
                     "(SELECT y FROM u WHERE x = 99)") == []

    def test_scalar_multi_row_errors(self, tk):
        with pytest.raises(SQLError, match="more than 1 row"):
            q(tk, "SELECT a FROM t WHERE b = (SELECT y FROM u)")

    def test_not_in_empty_set_keeps_null_left(self, tk):
        # x NOT IN (empty set) is TRUE even when x is NULL (MySQL
        # keeps the row); a=4 has b NULL and must appear
        rows = q(tk, "SELECT a FROM t WHERE b NOT IN "
                     "(SELECT y FROM u WHERE x = 99) ORDER BY a")
        assert rows == [(1,), (2,), (3,), (4,)]

    def test_volatile_outer_survives_subquery_planning(self, tk):
        # a NOW() fold in the outer WHERE must mark the WHOLE plan
        # non-cacheable even when a subquery is planned afterwards
        # (nested Planner.plan resets the global volatile flag)
        from tidb_tpu.parser import parse

        stmt = parse("SELECT a FROM t WHERE c < NOW() AND "
                     "EXISTS (SELECT 1 FROM u)")[0]
        plan = tk._planner().plan(stmt)
        assert plan.cacheable is False
        # and the converse: no volatile fold -> still cacheable
        stmt = parse("SELECT a FROM t WHERE c < 2.0 AND "
                     "EXISTS (SELECT 1 FROM u)")[0]
        assert tk._planner().plan(stmt).cacheable is True


class TestCorrelated:
    def test_exists_correlated(self, tk):
        rows = q(tk, "SELECT a FROM t WHERE EXISTS "
                     "(SELECT 1 FROM u WHERE u.x = t.a) ORDER BY a")
        assert rows == [(1,), (2,)]

    def test_not_exists_correlated(self, tk):
        rows = q(tk, "SELECT a FROM t WHERE NOT EXISTS "
                     "(SELECT 1 FROM u WHERE u.x = t.a) ORDER BY a")
        assert rows == [(3,), (4,)]

    def test_in_correlated(self, tk):
        rows = q(tk, "SELECT a FROM t WHERE b IN "
                     "(SELECT y FROM u WHERE u.x = t.a) ORDER BY a")
        assert rows == [(1,), (2,)]

    def test_scalar_correlated(self, tk):
        # Q17 shape: compare to a per-row aggregate of another table
        rows = q(tk, "SELECT a FROM t WHERE c > "
                     "(SELECT AVG(y) FROM u WHERE u.x = t.a) ORDER BY a")
        # x=1: avg 10 -> 1.5 > 10 false; x=2: avg 20 -> 2.5 > 20 false
        assert rows == []
        rows = q(tk, "SELECT a FROM t WHERE b >= "
                     "(SELECT MAX(y) FROM u WHERE u.x = t.a) ORDER BY a")
        assert rows == [(1,), (2,)]

    def test_correlated_with_aggregate_outer(self, tk):
        # correlated filter under an aggregating outer query
        rows = q(tk, "SELECT COUNT(*) FROM t WHERE EXISTS "
                     "(SELECT 1 FROM u WHERE u.x = t.a)")
        assert rows == [(2,)]

    def test_q4_shape(self, tk):
        """TPC-H Q4: grouped count over EXISTS-correlated filter."""
        tk.execute("CREATE TABLE o (ok BIGINT PRIMARY KEY, pri VARCHAR(20))")
        tk.execute("CREATE TABLE l (lk BIGINT PRIMARY KEY, lok BIGINT, "
                   "cd INT, rd INT)")
        tk.execute("INSERT INTO o VALUES (1,'HIGH'), (2,'LOW'), "
                   "(3,'HIGH'), (4,'LOW')")
        # line items: late (cd < rd) only for orders 1 and 2
        tk.execute("INSERT INTO l VALUES (10, 1, 5, 9), (11, 2, 3, 4), "
                   "(12, 3, 9, 5), (13, 4, 7, 2)")
        rows = q(tk, "SELECT pri, COUNT(*) FROM o WHERE EXISTS ("
                     "SELECT 1 FROM l WHERE l.lok = o.ok AND l.cd < l.rd"
                     ") GROUP BY pri ORDER BY pri")
        assert rows == [("HIGH", 1), ("LOW", 1)]


class TestExplain:
    def test_apply_in_explain(self, tk):
        # correlated-equality EXISTS now decorrelates to a semi join
        plan = "\n".join(r[0] for r in q(
            tk, "EXPLAIN SELECT a FROM t WHERE EXISTS "
                "(SELECT 1 FROM u WHERE u.x = t.a)"))
        assert "semi" in plan and "Apply" not in plan
        # non-equality correlation still runs through the apply path
        plan_ne = "\n".join(r[0] for r in q(
            tk, "EXPLAIN SELECT a FROM t WHERE EXISTS "
                "(SELECT 1 FROM u WHERE u.x > t.a)"))
        assert "Apply" in plan_ne and "correlated" in plan_ne
        plan2 = "\n".join(r[0] for r in q(
            tk, "EXPLAIN SELECT a FROM t WHERE b IN (SELECT y FROM u)"))
        assert "Apply" in plan2 and "uncorrelated" in plan2


class TestQuantified:
    """expr <cmp> ANY/SOME/ALL (SELECT ...) with three-valued logic
    (ref: plan/expression_rewriter.go handleCompareSubquery)."""

    def test_ordering_any_all(self, tk):
        assert q(tk, "SELECT a FROM t WHERE b > ANY (SELECT y FROM u "
                     "WHERE y IS NOT NULL) ORDER BY a") == [(2,), (3,)]
        assert q(tk, "SELECT a FROM t WHERE b > ALL (SELECT y FROM u "
                     "WHERE y IS NOT NULL) ORDER BY a") == [(3,)]
        assert q(tk, "SELECT a FROM t WHERE b = SOME (SELECT y FROM u) "
                     "ORDER BY a") == [(1,), (2,)]

    def test_empty_set(self, tk):
        # ALL over the empty set is TRUE (even for NULL b); ANY FALSE
        assert q(tk, "SELECT COUNT(*) FROM t WHERE b > ALL "
                     "(SELECT y FROM u WHERE x > 90)") == [(4,)]
        assert q(tk, "SELECT COUNT(*) FROM t WHERE b > ANY "
                     "(SELECT y FROM u WHERE x > 90)") == [(0,)]

    def test_null_in_set_blocks_all(self, tk):
        # u.y holds a NULL: nothing is definitely > ALL of it
        assert q(tk, "SELECT COUNT(*) FROM t WHERE b > ALL "
                     "(SELECT y FROM u)") == [(0,)]
        # but definite violations (10 > 10, 20 > 20 both false) still
        # pass the negation; b=30 is NULL-blocked, NULL stays NULL
        assert q(tk, "SELECT a FROM t WHERE NOT (b > ALL "
                     "(SELECT y FROM u)) ORDER BY a") == [(1,), (2,)]

    def test_ne_quantifiers(self, tk):
        assert q(tk, "SELECT a FROM t WHERE b <> ALL (SELECT y FROM u "
                     "WHERE y IS NOT NULL) ORDER BY a") == [(3,)]
        assert q(tk, "SELECT a FROM t WHERE b <> ANY (SELECT y FROM u "
                     "WHERE y IS NOT NULL) ORDER BY a") == \
            [(1,), (2,), (3,)]


class TestScalarSubqueryExpr:
    """Scalar (SELECT ...) in expression position: select list, HAVING,
    ORDER BY, and general WHERE arithmetic — lifted to applied columns
    (ref: plan/expression_rewriter.go handleScalarSubquery)."""

    def test_select_list(self, tk):
        assert q(tk, "SELECT a, (SELECT MAX(y) FROM u) FROM t "
                     "WHERE a = 1") == [(1, 20)]

    def test_correlated_select_list(self, tk):
        assert q(tk, "SELECT a, (SELECT COUNT(*) FROM u WHERE u.x < t.a)"
                     " FROM t ORDER BY a") == \
            [(1, 0), (2, 1), (3, 2), (4, 2)]

    def test_where_arithmetic(self, tk):
        assert q(tk, "SELECT a FROM t WHERE b = "
                     "(SELECT MIN(y) FROM u) + 10") == [(2,)]

    def test_empty_scalar_is_null(self, tk):
        assert q(tk, "SELECT (SELECT y FROM u WHERE x > 90) IS NULL "
                     "FROM t WHERE a = 1") == [(1,)]

    def test_order_by_and_having(self, tk):
        assert q(tk, "SELECT a FROM t ORDER BY "
                     "b - (SELECT MIN(y) FROM u) DESC LIMIT 2") == \
            [(3,), (2,)]
        # groups: c<=2 sums to 10 (== MIN(y), excluded), c>2 to 50
        assert q(tk, "SELECT c > 2, SUM(b) FROM t GROUP BY c > 2 "
                     "HAVING SUM(b) > (SELECT MIN(y) FROM u) "
                     "ORDER BY 1") == [(1, 50)]

    def test_multirow_scalar_errors(self, tk):
        with pytest.raises(SQLError, match="more than 1 row"):
            q(tk, "SELECT (SELECT y FROM u) FROM t")


class TestLiftEdges:
    def test_star_not_polluted_by_lifted_column(self, tk):
        assert q(tk, "SELECT * FROM t WHERE b = "
                     "(SELECT MIN(y) FROM u) + 10") == [(2, 20, 2.5)]
        rows = q(tk, "SELECT * FROM t ORDER BY "
                     "b - (SELECT MIN(y) FROM u) LIMIT 1")
        assert rows == [(4, None, 4.5)]   # NULL key sorts first ASC

    def test_in_subquery_in_expression_position(self, tk):
        # IN's row set in expression position keeps IN's three-valued
        # semantics (u.y holds a NULL: non-matches go NULL, not 0)
        assert q(tk, "SELECT a FROM t WHERE (b IN (SELECT y FROM u)) "
                     "= 1 ORDER BY a") == [(1,), (2,)]
        assert q(tk, "SELECT a, b IN (SELECT y FROM u) FROM t "
                     "ORDER BY a") == \
            [(1, 1), (2, 1), (3, None), (4, None)]
        assert q(tk, "SELECT a, b NOT IN (SELECT y FROM u WHERE "
                     "y IS NOT NULL) FROM t ORDER BY a") == \
            [(1, 0), (2, 0), (3, 1), (4, None)]
        # empty set: 0 even for NULL left
        assert q(tk, "SELECT a, b IN (SELECT y FROM u WHERE x > 90) "
                     "FROM t ORDER BY a") == \
            [(1, 0), (2, 0), (3, 0), (4, 0)]

    def test_exists_in_expression_position(self, tk):
        assert q(tk, "SELECT a, EXISTS (SELECT 1 FROM u WHERE "
                     "u.x = t.a) FROM t ORDER BY a") == \
            [(1, 1), (2, 1), (3, 0), (4, 0)]
        assert q(tk, "SELECT CASE WHEN EXISTS (SELECT 1 FROM u WHERE "
                     "x = 99) THEN 'y' ELSE 'n' END") == [("n",)]
        assert q(tk, "SELECT (SELECT MAX(y) FROM u) + 1, "
                     "10 IN (SELECT y FROM u)") == [(21, 1)]

    def test_nulleq_quantifier_rejected(self, tk):
        from tidb_tpu.parser import ParseError
        with pytest.raises(ParseError, match="quantified"):
            q(tk, "SELECT a FROM t WHERE b <=> ANY (SELECT y FROM u)")


class TestExprPositionEdges:
    def test_aggregate_operand_clean_error(self, tk):
        with pytest.raises(SQLError, match="aggregate"):
            q(tk, "SELECT SUM(b) IN (SELECT y FROM u) FROM t")

    def test_star_in_subquery_expression_position(self, tk):
        with pytest.raises(SQLError, match="column named"):
            q(tk, "SELECT 1 IN (SELECT * FROM u)")
        # conjunct position keeps working with *
        assert q(tk, "SELECT a FROM t WHERE a IN (SELECT * FROM "
                     "(SELECT x FROM u) z) ORDER BY a") == [(1,), (2,)]

    def test_string_fractional_interval(self, tk):
        assert q(tk, "SELECT DATE_ADD('2024-01-01', "
                     "INTERVAL '1.5' DAY)") == \
            [("2024-01-03 00:00:00",)]
        with pytest.raises(SQLError, match="INTERVAL amount"):
            q(tk, "SELECT DATE_ADD('2024-01-01', INTERVAL 'abc' DAY)")

    def test_fractional_second_is_microseconds(self, tk):
        assert q(tk, "SELECT DATE_ADD('2024-01-01 00:00:00', "
                     "INTERVAL 1.5 SECOND), "
                     "DATE_SUB('2024-01-01 00:00:00', "
                     "INTERVAL 0.25 SECOND)") == \
            [("2024-01-01 00:00:01.500000",
              "2023-12-31 23:59:59.750000")]

    def test_nonfinite_interval_amounts_rejected(self, tk):
        for bad in ("'inf'", "'nan'", "'1e100'", "1e100"):
            with pytest.raises(SQLError, match="INTERVAL amount"):
                q(tk, f"SELECT DATE_ADD('2024-01-01', "
                      f"INTERVAL {bad} DAY)")

    def test_lifted_field_display_names(self, tk):
        res = tk.query("SELECT (SELECT MAX(y) FROM u), "
                       "10 IN (SELECT y FROM u), "
                       "EXISTS (SELECT 1 FROM u)")
        assert res.columns == ["(subquery)", "10 in (subquery)",
                               "exists(subquery)"]


class TestDatetimeFsp:
    def test_write_rounds_to_column_precision(self, tk):
        tk.execute("CREATE TABLE dtt (id BIGINT PRIMARY KEY, "
                   "dt DATETIME)")
        tk.execute("INSERT INTO dtt VALUES "
                   "(1, '2024-01-01 00:00:00.5'), "
                   "(2, '2024-01-01 00:00:00.4')")
        assert q(tk, "SELECT dt FROM dtt ORDER BY id") == \
            [("2024-01-01 00:00:01",), ("2024-01-01 00:00:00",)]
        # computed values keep their sub-second part in display
        assert q(tk, "SELECT DATE_ADD(dt, INTERVAL 0.5 SECOND) "
                     "FROM dtt WHERE id = 2") == \
            [("2024-01-01 00:00:00.500000",)]


class TestDMLSubqueryWhere:
    """Subqueries in UPDATE/DELETE WHERE ride the same apply/semi-join
    machinery as SELECT; reading the write target is refused like
    MySQL error 1093 (Halloween guard)."""

    def test_update_delete_with_subqueries(self, tk):
        tk.execute("UPDATE t SET b = 0 WHERE b > (SELECT AVG(y) FROM u)")
        assert q(tk, "SELECT a FROM t WHERE b = 0 ORDER BY a") == \
            [(2,), (3,)]
        tk.execute("UPDATE t SET b = 7 WHERE a IN (SELECT x FROM u)")
        assert q(tk, "SELECT b FROM t WHERE a = 1") == [(7,)]
        tk.execute("DELETE FROM t WHERE EXISTS "
                   "(SELECT 1 FROM u WHERE u.x = t.a)")
        assert q(tk, "SELECT COUNT(*) FROM t") == [(2,)]
        tk.execute("DELETE FROM t WHERE b >= ALL "
                   "(SELECT y FROM u WHERE y IS NOT NULL)")
        assert q(tk, "SELECT COUNT(*) FROM t") == [(2,)]

    def test_target_table_in_subquery_refused(self, tk):
        for sql in ["UPDATE t SET b = 1 WHERE a IN (SELECT a FROM t)",
                    "DELETE FROM t WHERE b > (SELECT AVG(b) FROM t)"]:
            with pytest.raises(SQLError, match="target table"):
                tk.execute(sql)

    def test_cross_db_same_name_allowed(self, tk):
        # the 1093 guard is db-qualified: test.t vs d2.t differ
        tk.execute("CREATE DATABASE d2")
        tk.execute("CREATE TABLE d2.t (a BIGINT PRIMARY KEY)")
        tk.execute("INSERT INTO d2.t VALUES (1)")
        tk.execute("UPDATE t SET b = -5 WHERE a IN (SELECT a FROM d2.t)")
        assert q(tk, "SELECT b FROM t WHERE a = 1") == [(-5,)]
        with pytest.raises(SQLError, match="target table"):
            tk.execute("UPDATE t SET b = 1 WHERE a IN "
                       "(SELECT a FROM test.t)")
