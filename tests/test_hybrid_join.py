"""Skew-aware, spill-capable hybrid hash join & partitioned agg
(ops/hybrid.py): partition-exact pair matching against the host
matcher, heavy-hitter routing (CMSketch-seeded and stream-promoted),
per-partition capacity/collision retry for aggregation, quota-pressure
partition spill (completes, never ER_MEM_EXCEED_QUOTA), and the
fallback observability surfaces."""

import numpy as np
import pytest

from tidb_tpu import config, memtrack, metrics
from tidb_tpu.chunk import Chunk, Column
from tidb_tpu.expression import AggDesc, AggFunc
from tidb_tpu.expression.core import ColumnRef
from tidb_tpu.ops import hybrid
from tidb_tpu.ops.hashagg import CapacityError, CollisionError, kernel_for
from tidb_tpu.ops.hostagg import host_hash_agg
from tidb_tpu.ops.join import JoinKernel, host_match_pairs
from tidb_tpu.session import Session
from tidb_tpu.sqltypes import FieldType, TypeCode
from tidb_tpu.store.storage import new_mock_storage

FT_I = FieldType(tp=TypeCode.LONGLONG)
FT_D = FieldType(tp=TypeCode.DOUBLE)


def _metric(prefix: str) -> float:
    return sum(v for k, v in metrics.snapshot().items()
               if k.startswith(prefix))


def _pairs_via_hybrid(hyb: hybrid.HybridJoinBuild, kernel, pk, n):
    """Drive route/ensure/dispatch/finalize by hand; -> set of global
    (probe, build) pairs."""
    hp, tasks = hyb.route(pk, n)
    out = set()
    for p, idx in tasks:
        dev = hyb.ensure(p)
        rows = hyb.build_rows(p)
        sub = [(d[idx], v[idx]) for d, v in pk]
        cap = hyb.hot_out_cap(hp[idx]) if p == hyb.parts else None
        tok = kernel.dispatch(None, sub, len(rows), len(idx),
                              out_cap=cap, build_dev=dev)
        li_l, ri_l = kernel.finalize(tok)
        out.update(zip(idx[li_l].tolist(), rows[ri_l].tolist()))
    return out


def _host_pairs(bk, pk, nb, n):
    li, ri = host_match_pairs(bk, pk, nb, n)
    return set(zip(li.tolist(), ri.tolist()))


class TestPartitionedPairs:
    """Device==host pair sets through the partitioned matcher on the
    capacity-sensitive shapes the ISSUE names."""

    @pytest.mark.parametrize("n", [1024, 2048, 4096])  # pow2 boundaries
    def test_pow2_boundary(self, n):
        rng = np.random.default_rng(7)
        nb = 4096
        bk = [(np.arange(nb, dtype=np.int64), np.ones(nb, bool))]
        pk = [(rng.integers(0, nb + 64, n).astype(np.int64),
               np.ones(n, bool))]
        kernel = JoinKernel(1)
        hyb = hybrid.HybridJoinBuild(kernel, bk, nb, parts=4,
                                     plan=object(), threshold=0)
        try:
            assert _pairs_via_hybrid(hyb, kernel, pk, n) == \
                _host_pairs(bk, pk, nb, n)
        finally:
            hyb.close()

    def test_all_one_key(self):
        """Every probe row carries THE one key: the worst skew there
        is — the single partition holding it must still match exactly
        (and with a threshold, the hot lane takes it wholesale)."""
        nb, n = 4096, 3000
        bk = [(np.arange(nb, dtype=np.int64), np.ones(nb, bool))]
        pk = [(np.full(n, 17, dtype=np.int64), np.ones(n, bool))]
        kernel = JoinKernel(1)
        want = _host_pairs(bk, pk, nb, n)
        for threshold in (0, 100):       # plain partition vs hot lane
            hyb = hybrid.HybridJoinBuild(kernel, bk, nb, parts=4,
                                         plan=object(),
                                         threshold=threshold)
            try:
                if threshold:
                    promo = hyb.observe(hybrid.probe_hashes(pk, n))
                    if promo is not None:
                        assert hyb.promote(promo)
                assert _pairs_via_hybrid(hyb, kernel, pk, n) == want
                if threshold:
                    assert hyb.hot_rows == n
            finally:
                hyb.close()

    def test_null_keys_match_nothing(self):
        rng = np.random.default_rng(8)
        nb, n = 4096, 5000
        bv = rng.random(nb) > 0.1        # some NULL build rows
        pv = rng.random(n) > 0.3         # many NULL probe rows
        bk = [(np.arange(nb, dtype=np.int64), bv)]
        pk = [(rng.integers(0, nb, n).astype(np.int64), pv)]
        kernel = JoinKernel(1)
        hyb = hybrid.HybridJoinBuild(kernel, bk, nb, parts=4,
                                     plan=object(), threshold=0)
        try:
            got = _pairs_via_hybrid(hyb, kernel, pk, n)
        finally:
            hyb.close()
        assert got == _host_pairs(bk, pk, nb, n)
        assert all(pv[li] and bv[ri] for li, ri in got)

    def test_cms_seeded_hot_routing(self):
        """A probe-side CMSketch with one heavy value seeds the hot set
        at detection time (the statistics.CMSketch leg), and probe rows
        of that key route through the broadcast lane."""
        from tidb_tpu.statistics import CMSketch, cm_key
        rng = np.random.default_rng(9)
        nb, n = 4096, 6000
        bk = [(np.arange(nb, dtype=np.int64), np.ones(nb, bool))]
        cid = rng.integers(0, nb, n)
        cid[rng.random(n) < 0.5] = 99
        pk = [(cid.astype(np.int64), np.ones(n, bool))]
        cms = CMSketch()
        for v, c in zip(*np.unique(cid, return_counts=True)):
            cms.insert(cm_key(int(v)), int(c))
        h = hybrid.build_hashes(bk, nb)
        hot = hybrid.detect_hot_hashes(h, threshold=1000,
                                       raw_key=bk[0], probe_cms=cms)
        assert hot.size >= 1
        kernel = JoinKernel(1)
        hyb = hybrid.HybridJoinBuild(kernel, bk, nb, parts=4,
                                     plan=object(), hot_hashes=hot,
                                     threshold=1000, h=h)
        try:
            got = _pairs_via_hybrid(hyb, kernel, pk, n)
            assert hyb.hot_rows >= int((cid == 99).sum())
        finally:
            hyb.close()
        assert got == _host_pairs(bk, pk, nb, n)

    def test_build_side_duplication_goes_hot(self):
        """Exact build-side dup counts alone (no sketch) classify a
        many-to-many hot key."""
        nb = 4096
        key = np.arange(nb, dtype=np.int64)
        key[:2000] = 5                     # 2000 duplicate build rows
        h = hybrid.build_hashes([(key, np.ones(nb, bool))], nb)
        hot = hybrid.detect_hot_hashes(h, threshold=1000)
        assert hot.size == 1


class TestPartitionedAgg:
    def _chunk(self, k, amt=None, valid=None):
        n = len(k)
        amt = amt if amt is not None else np.arange(n, dtype=np.float64)
        valid = valid if valid is not None else np.ones(n, bool)
        return Chunk([Column(FT_I, np.asarray(k, np.int64), valid),
                      Column(FT_D, amt, np.ones(n, bool))])

    def _exprs(self):
        g = ColumnRef(0, FT_I, name="k")
        aggs = [AggDesc(fn=AggFunc.COUNT, arg=None),
                AggDesc(fn=AggFunc.SUM, arg=ColumnRef(1, FT_D,
                                                      name="amt"))]
        return g, aggs

    @staticmethod
    def _norm(gr):
        return {key: (int(gr.partials[0][0][i]),
                      round(float(gr.partials[1][0][i]), 6))
                for i, key in enumerate(gr.keys)}

    @pytest.mark.parametrize("case", ["highcard", "onekey", "nulls",
                                      "pow2"])
    def test_matches_host(self, case):
        rng = np.random.default_rng(11)
        if case == "highcard":
            chunk = self._chunk(rng.integers(0, 9000, 50000))
        elif case == "onekey":
            chunk = self._chunk(np.full(4096, 3))
        elif case == "nulls":
            chunk = self._chunk(rng.integers(0, 500, 8192),
                                valid=rng.random(8192) > 0.25)
        else:
            chunk = self._chunk(rng.integers(0, 6000, 16384))
        g, aggs = self._exprs()
        gr = hybrid.partitioned_agg(chunk, None, [g], aggs, object(),
                                    parts=4)
        assert self._norm(gr) == \
            self._norm(host_hash_agg(chunk, None, [g], aggs))

    def test_agg_retry_from_real_capacity_error(self):
        rng = np.random.default_rng(12)
        chunk = self._chunk(rng.integers(0, 9000, 40000))
        g, aggs = self._exprs()
        k = kernel_for(None, [g], aggs, capacity=64)
        with pytest.raises(CapacityError) as ei:
            k(chunk)
        gr = hybrid.agg_retry(chunk, None, [g], aggs, object(),
                              ei.value)
        assert self._norm(gr) == \
            self._norm(host_hash_agg(chunk, None, [g], aggs))

    def test_collision_retries_per_partition(self, monkeypatch):
        """A CollisionError strands ONE partition on the host; the rest
        stay on device, the merged result is exact, and the fallback is
        counted with reason=collision."""
        rng = np.random.default_rng(13)
        chunk = self._chunk(rng.integers(0, 2000, 20000))
        g, aggs = self._exprs()
        real = hybrid.kernel_for
        state = {"failed": 0}

        def flaky(filter_expr, group_exprs, aggs_, capacity=4096):
            k = real(filter_expr, group_exprs, aggs_, capacity=capacity)
            if state["failed"] == 0:
                state["failed"] = 1

                class Once:
                    def dispatch_nbytes(self, c):
                        return k.dispatch_nbytes(c)

                    def __call__(self, c, dev_cols=None):
                        raise CollisionError("forced")
                return Once()
            return k

        monkeypatch.setattr(hybrid, "kernel_for", flaky)
        before = _metric(metrics.DEVICE_FALLBACKS)
        gr = hybrid.partitioned_agg(chunk, None, [g], aggs, object(),
                                    parts=4, reason="collision")
        assert self._norm(gr) == \
            self._norm(host_hash_agg(chunk, None, [g], aggs))
        assert state["failed"] == 1
        assert _metric(metrics.DEVICE_FALLBACKS) == before + 1
        snap = metrics.snapshot()
        assert any("reason=\"collision\"" in key.replace("'", "\"")
                   for key in snap if key.startswith(
                       metrics.DEVICE_FALLBACKS))


class TestQuotaSpill:
    def test_spill_action_sheds_cold_partitions(self):
        """Deterministic re-entrancy pin: an ensure() that crosses the
        statement quota fires the registered spill action, which evicts
        the OTHER resident partitions (never the active one), and the
        ensure completes instead of raising ER_MEM_EXCEED_QUOTA."""
        nb = 32768
        bk = [(np.arange(nb, dtype=np.int64), np.ones(nb, bool))]
        kernel = JoinKernel(1)
        root = memtrack.statement_root(None, quota=0)
        with memtrack.tracking(root):
            hyb = hybrid.HybridJoinBuild(kernel, bk, nb, parts=4,
                                         plan=object(), threshold=0)
            try:
                hyb.ensure(0)
                hyb.ensure(1)
                per_part = kernel.build_nbytes(hyb.part_rows(2))
                # quota admits the gathered copy + ~2.5 resident
                # partitions: the NEXT ensure must spill, not cancel
                root.quota = root.total() + per_part // 2
                before = _metric(metrics.JOIN_SPILL_PARTITIONS)
                spill_events = _metric(metrics.MEM_QUOTA_EXCEEDED +
                                       '{action="spill"}')
                hyb.ensure(2)          # crosses: spill action fires
                assert hyb.spilled >= 1
                assert _metric(metrics.JOIN_SPILL_PARTITIONS) > before
                assert _metric(metrics.MEM_QUOTA_EXCEEDED +
                               '{action="spill"}') > spill_events
                # spilled partitions now stage instead of re-uploading
                assert hyb.under_pressure()
                assert not hyb.want_immediate(0)
                assert hyb.want_immediate(2)   # the active one survived
            finally:
                hyb.close()
                root.detach()
        assert root.host == 0 and root.device == 0

    def test_sql_join_completes_with_spill_under_quota(self, skew_sess):
        """End-to-end acceptance: under a constrained
        tidb_tpu_mem_quota_query the hybrid join COMPLETES via
        partition spill — spill metric > 0, correct rows, no quota
        cancel."""
        s, host_rows, q = skew_sess
        s.execute("SET tidb_tpu_device = 1")
        s.execute("SET tidb_tpu_join_partitions = 8")
        s.execute("SET tidb_tpu_skew_threshold = 1500")
        s.execute("SET tidb_tpu_superchunk_rows = 4096")
        s.query(q)                       # unquota'd run: records peak
        mem = s._last_mem
        peak = mem.host_peak + mem.device_peak
        before = _metric(metrics.JOIN_SPILL_PARTITIONS)
        try:
            s.execute(f"SET tidb_tpu_mem_quota_query = {peak - 4096}")
            rows = s.query(q).rows
        finally:
            s.execute("SET tidb_tpu_mem_quota_query = 0")
        assert _metric(metrics.JOIN_SPILL_PARTITIONS) > before
        assert _approx(rows, host_rows)


def _approx(a, b):
    if len(a) != len(b):
        return False
    for ra, rb in zip(a, b):
        for x, y in zip(ra, rb):
            if isinstance(x, float) or isinstance(y, float):
                if abs(float(x) - float(y)) > max(1e-6,
                                                  abs(float(y)) * 1e-9):
                    return False
            elif x != y:
                return False
    return True


@pytest.fixture(scope="module")
def skew_sess():
    """Zipf-ish skewed join workload: dim table c (6000 rows), fact o
    (18000 rows, 35% on one hot cid), ANALYZE'd so the planner attaches
    the probe-side CMSketch. -> (session, host-truth rows, query)."""
    s = Session(new_mock_storage())
    s.execute("CREATE DATABASE hj")
    s.execute("USE hj")
    s.execute("CREATE TABLE c (id BIGINT PRIMARY KEY, seg BIGINT)")
    s.execute("CREATE TABLE o (id BIGINT PRIMARY KEY, cid BIGINT, "
              "amt DOUBLE)")
    rng = np.random.default_rng(21)
    nb, n = 6000, 18000
    s.execute("INSERT INTO c VALUES " +
              ",".join(f"({i}, {i % 7})" for i in range(nb)))
    cid = rng.integers(0, nb + 900, n)      # some dangle (outer joins)
    cid[rng.random(n) < 0.35] = 42          # the heavy hitter
    amt = rng.uniform(1, 100, n).round(2)
    for lo in range(0, n, 9000):
        s.execute("INSERT INTO o VALUES " + ",".join(
            f"({i}, {cid[i]}, {amt[i]})"
            for i in range(lo, min(lo + 9000, n))))
    s.execute("ANALYZE TABLE o")
    s.execute("ANALYZE TABLE c")
    q = ("SELECT c.seg, COUNT(*), SUM(o.amt) FROM o JOIN c "
         "ON o.cid = c.id GROUP BY c.seg ORDER BY c.seg")
    s.execute("SET tidb_tpu_device = 0")
    host_rows = s.query(q).rows
    s.execute("SET tidb_tpu_device = 1")
    s._truth = (cid, amt, nb, n)
    return s, host_rows, q


class TestSqlHybrid:
    def test_skew_join_on_device_no_fallback(self, skew_sess):
        """The ISSUE's acceptance shape: the skewed join runs entirely
        on device (fallback count 0), with the heavy hitter routed
        through the broadcast lane seeded from ANALYZE's CMSketch."""
        s, host_rows, q = skew_sess
        s.execute("SET tidb_tpu_join_partitions = 4")
        s.execute("SET tidb_tpu_skew_threshold = 1500")
        s.execute("SET tidb_tpu_superchunk_rows = 4096")
        hot0 = _metric(metrics.JOIN_HOT_ROWS)
        fb0 = _metric(metrics.DEVICE_FALLBACKS)
        rows = s.query(q).rows
        assert _approx(rows, host_rows)
        assert _metric(metrics.JOIN_HOT_ROWS) > hot0
        assert _metric(metrics.DEVICE_FALLBACKS) == fb0

    def test_left_join_null_extension_via_hybrid(self, skew_sess):
        s, _host_rows, _q = skew_sess
        cid, _amt, nb, _n = s._truth
        s.execute("SET tidb_tpu_join_partitions = 4")
        s.execute("SET tidb_tpu_skew_threshold = 1500")
        s.execute("SET tidb_tpu_superchunk_rows = 4096")
        rows = s.query(
            "SELECT COUNT(*) FROM o LEFT JOIN c ON o.cid = c.id "
            "WHERE c.id IS NULL").rows
        assert rows[0][0] == int(np.sum(cid >= nb))

    def test_high_card_cop_agg_stays_on_device(self, skew_sess):
        """Storage-side partial agg over > capacity distinct groups:
        before the hybrid retry this host-fell-back invisibly at
        store/copr.py's except net; now it escalates/partitions and the
        fallback counter stays flat."""
        s, _host_rows, _q = skew_sess
        q = "SELECT cid, COUNT(*) FROM o GROUP BY cid ORDER BY cid LIMIT 7"
        s.execute("SET tidb_tpu_device = 0")
        want = s.query(q).rows
        s.execute("SET tidb_tpu_device = 1")
        fb0 = _metric(metrics.DEVICE_FALLBACKS)
        got = s.query(q).rows
        assert got == want
        assert _metric(metrics.DEVICE_FALLBACKS) == fb0

    def test_explain_analyze_fallback_note(self, skew_sess):
        """A designed device rejection (string-computed group key) is
        counted and surfaces as a fallback note in the EXPLAIN ANALYZE
        pipeline column."""
        s, _host_rows, _q = skew_sess
        s.execute("CREATE TABLE sfb (id BIGINT PRIMARY KEY, "
                  "name VARCHAR(32), v BIGINT)")
        s.execute("INSERT INTO sfb VALUES " + ",".join(
            f"({i}, 'n{i % 50}', {i})" for i in range(4096)))
        fb0 = _metric(metrics.DEVICE_FALLBACKS)
        r = s.query("EXPLAIN ANALYZE SELECT CONCAT(name, 'x'), "
                    "COUNT(*) FROM sfb GROUP BY CONCAT(name, 'x')")
        assert _metric(metrics.DEVICE_FALLBACKS) > fb0
        pipeline_col = r.columns.index("pipeline")
        assert any("fallback=" in str(row[pipeline_col])
                   for row in r.rows)
        snap = metrics.snapshot()
        assert any(key.startswith(metrics.DEVICE_FALLBACKS) and
                   "unsupported" in key for key in snap)
