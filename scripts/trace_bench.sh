#!/usr/bin/env bash
# CI wrapper for the statement-tracing leg (`python bench.py trace`):
# a traced warm Q1 + point-lookup mix that FAILS if the
# latency_attribution block is unpopulated, any retained span tree is
# unbalanced (begin without end), the TRACE statement's tree is
# missing lifecycle/device-plane spans, or the Chrome trace-event
# export fails schema validation — bench.py asserts all of that
# itself and exits non-zero. Env overrides (BENCH_TRACE_SF / _ITERS /
# _LOOKUPS) pass straight through.
set -euo pipefail
cd "$(dirname "$0")/.."

export JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}"
export BENCH_TRACE_SF="${BENCH_TRACE_SF:-0.02}"
export BENCH_TRACE_ITERS="${BENCH_TRACE_ITERS:-3}"
export BENCH_TRACE_LOOKUPS="${BENCH_TRACE_LOOKUPS:-16}"

out="$(python bench.py trace)"
echo "$out"

TRACE_JSON="$out" python - <<'PY'
import json, os

rep = json.loads(os.environ["TRACE_JSON"])
d = rep["detail"]
assert d.get("passed"), f"trace bench did not pass: {d}"
assert rep["value"] > 0, "no traces retained"
attr = d["latency_attribution"]
assert attr.get("q1", {}).get("traces", 0) > 0, \
    f"attribution unpopulated: {attr}"
print(f"trace bench OK: {rep['value']} traces retained, "
      f"{d['chrome_events']} chrome events, "
      f"q1 p99={attr['q1']['statement']['p99_ms']}ms "
      f"(coverage {attr['q1'].get('p99_coverage')})")
PY
