#!/usr/bin/env bash
# CI wrapper for the kernel-profiling leg (`python bench.py profile`):
# warm Q1/Q3/Q5 under the continuous profiler that FAILS if
# information_schema.kernel_profile is unpopulated, any row that moved
# bytes is missing its roofline_fraction, compile counts grow across
# the warm iterations (a warm run that recompiles), or a
# statement_profile memo row is missing the mode that ran — bench.py
# asserts all of that itself and exits non-zero. Env overrides
# (BENCH_PROFILE_SF / _ITERS) pass straight through.
set -euo pipefail
cd "$(dirname "$0")/.."

export JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}"
export BENCH_PROFILE_SF="${BENCH_PROFILE_SF:-0.02}"
export BENCH_PROFILE_ITERS="${BENCH_PROFILE_ITERS:-3}"

out="$(python bench.py profile)"
echo "$out"

PROFILE_JSON="$out" python - <<'PY'
import json, os

rep = json.loads(os.environ["PROFILE_JSON"])
d = rep["detail"]
assert d.get("passed"), f"profile bench did not pass: {d['failures']}"
assert rep["value"] > 0, "no kernel profiles recorded"
assert d["statement_profile_rows"] > 0, "mode-history memo empty"
print(f"profile bench OK: {rep['value']} kernel profiles "
      f"({', '.join(d['kernel_profile_families'])}), "
      f"{d['statement_profile_rows']} memo rows "
      f"(modes {', '.join(d['statement_profile_modes'])}), "
      f"roofline peak {d['roofline']['peak_gbps']}GB/s "
      f"[{d['roofline']['source']}]")
PY
