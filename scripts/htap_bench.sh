#!/usr/bin/env bash
# CI wrapper for the HTAP write-pressure sweep (`python bench.py
# htap`): a TPC-C-style new-order/payment write mix under a warm
# analytic loop, swept across write rates, with sanity floors on the
# output — the heavy leg (wire connections, bigger scale) lives in
# tests/test_htap.py behind the `slow` marker. Env overrides
# (BENCH_HTAP_ROWS / _SECS / _RATES) pass straight through to bench.py.
set -euo pipefail
cd "$(dirname "$0")/.."

export JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}"
export BENCH_HTAP_ROWS="${BENCH_HTAP_ROWS:-40000}"
export BENCH_HTAP_SECS="${BENCH_HTAP_SECS:-4}"
export BENCH_HTAP_RATES="${BENCH_HTAP_RATES:-0,20,100}"
# acceptance: analytic rows/sec at the BEST nonzero write rate must be
# within 2x of the read-only warm number (the pre-delta-store behavior
# was cold-scan throughput at ANY nonzero rate)
HTAP_VS_FLOOR="${HTAP_VS_FLOOR:-0.5}"
# write-to-visible freshness must stay bounded (generous: CPU-XLA CI)
HTAP_FRESHNESS_CEIL_MS="${HTAP_FRESHNESS_CEIL_MS:-30000}"

out="$(python bench.py htap)"
echo "$out"

HTAP_JSON="$out" HTAP_VS_FLOOR="$HTAP_VS_FLOOR" \
HTAP_FRESHNESS_CEIL_MS="$HTAP_FRESHNESS_CEIL_MS" python - <<'PY'
import json, os

floor = float(os.environ["HTAP_VS_FLOOR"])
fresh_ceil = float(os.environ["HTAP_FRESHNESS_CEIL_MS"])
rep = json.loads(os.environ["HTAP_JSON"])
d = rep["detail"]
assert rep["value"] > 0, "analytic rows/sec must be positive"
nonzero = {int(k): v for k, v in d["rates"].items() if int(k) > 0}
assert nonzero, "sweep must include a nonzero write rate"
for rate, leg in sorted(d["rates"].items(), key=lambda kv: int(kv[0])):
    assert not leg["errors"], f"rate {rate}: errors {leg['errors']}"
    # the load-bearing pin: the HBM plane never re-colds under writes
    assert leg["delta"]["hbm_misses"] == 0, \
        f"rate {rate}: HBM cache re-colded ({leg['delta']})"
    if int(rate) > 0:
        assert leg["delta"]["served_with_delta"] > 0, \
            f"rate {rate}: no reads served as base+delta"
        assert leg["freshness_ms_max"] is None or \
            leg["freshness_ms_max"] <= fresh_ceil, \
            f"rate {rate}: freshness lag {leg['freshness_ms_max']}ms " \
            f"over the {fresh_ceil}ms ceiling"
ratios = [v["vs_read_only"] for v in nonzero.values()
          if v["vs_read_only"] is not None]
assert ratios, \
    "no read-only baseline ran — include rate 0 in BENCH_HTAP_RATES"
best = max(ratios)
assert best >= floor, \
    f"best nonzero-rate analytic throughput {best} of read-only " \
    f"(< {floor}: the write cliff is back)"
print(f"htap bench OK: {rep['value']} analytic rows/s at the top "
      f"write rate, best nonzero-rate ratio {best} vs read-only, "
      f"zero HBM re-colds")
PY
