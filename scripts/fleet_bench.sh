#!/usr/bin/env bash
# CI wrapper for the fleet scale-out harness (`python bench.py fleet`):
# one store-plane process + N stateless SQL-server processes with
# journal-coherent caches (ISSUE 16). A small fixed mixed workload
# replays against 1 -> 2 -> 4 SQL servers; the gate fails on an
# unpopulated block or sub-linear collapse (4-server aggregate below
# FLEET_SCALING_FLOOR x the single-server aggregate). Env overrides
# (BENCH_FLEET_SERVERS / _CLIENTS / _ROUNDS / _LOOKUPS / _SF) pass
# straight through to bench.py.
set -euo pipefail
cd "$(dirname "$0")/.."

export JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}"
export BENCH_FLEET_SERVERS="${BENCH_FLEET_SERVERS:-4}"
export BENCH_FLEET_CLIENTS="${BENCH_FLEET_CLIENTS:-4}"
export BENCH_FLEET_ROUNDS="${BENCH_FLEET_ROUNDS:-1}"
export BENCH_FLEET_LOOKUPS="${BENCH_FLEET_LOOKUPS:-4}"
export BENCH_FLEET_SF="${BENCH_FLEET_SF:-0.01}"
# the sub-linear-collapse gate: 4-server aggregate must reach this
# multiple of the single-server aggregate (ISSUE 16 satellite bar)
FLEET_SCALING_FLOOR="${FLEET_SCALING_FLOOR:-2.0}"
# p99 sanity ceiling per class, milliseconds (generous: CPU-XLA CI)
FLEET_P99_FLOOR_MS="${FLEET_P99_FLOOR_MS:-60000}"

out="$(python bench.py fleet)"
echo "$out"

FLEET_JSON="$out" FLEET_SCALING_FLOOR="$FLEET_SCALING_FLOOR" \
    FLEET_P99_FLOOR_MS="$FLEET_P99_FLOOR_MS" python - <<'PY'
import json, os

floor = float(os.environ["FLEET_SCALING_FLOOR"])
p99_floor = float(os.environ["FLEET_P99_FLOOR_MS"])
rep = json.loads(os.environ["FLEET_JSON"])
d = rep["detail"]
legs = d.get("legs")
assert legs, "fleet detail has no legs block"
assert rep["value"] > 0, "aggregate statements/sec must be positive"
for leg in legs:
    assert leg["stmts_per_sec"] > 0, f"leg x{leg['servers']} unpopulated"
    assert leg["latency"], f"leg x{leg['servers']} has no latency block"
    for cls, lat in leg["latency"].items():
        assert lat["p99_ms"] <= p99_floor, \
            f"x{leg['servers']} {cls}: p99 {lat['p99_ms']}ms over " \
            f"the {p99_floor}ms sanity floor"
    per = leg.get("per_server")
    assert per and len(per) == leg["servers"], \
        f"leg x{leg['servers']} per-server utilization unpopulated"
    served = sum(s["stmts"] for s in per.values())
    assert served > 0, f"leg x{leg['servers']}: no statements attributed"
cores = os.cpu_count() or 1
if legs[-1]["servers"] >= 4 and cores >= 4:
    scale = d["scaling_max_vs_1"]
    assert scale >= floor, \
        f"sub-linear collapse: x{legs[-1]['servers']} aggregate is " \
        f"only {scale}x the single-server aggregate (floor {floor}x)"
elif legs[-1]["servers"] >= 4:
    # N processes cannot scale past the physical core count; on a
    # starved CI box the gate keeps the populated/latency floors but
    # skips the scale-out multiple
    print(f"fleet bench: {cores} core(s) < 4 — scaling floor skipped "
          f"(observed {d['scaling_max_vs_1']}x)")
coh = d.get("coherence")
assert coh, "coherence counter block missing from the fleet detail"
assert sum(c["journal_pulls"] for c in coh.values()) > 0, \
    f"no journal-window pulls recorded: caches are not coherent ({coh})"
# cluster observability plane (ISSUE 17): the fleet_attribution block
# must be populated for every live member, and the traced statement's
# store-plane ring record must carry its origin_trace_id (bench.py
# raises — never a degraded-but-silent pass — if the cluster-table
# query errors instead of degrading, this block is simply absent)
fa = d.get("fleet_attribution")
assert fa, "fleet_attribution block missing from the fleet detail"
live = fa.get("live_members") or {}
util = fa.get("members") or {}
assert live and set(util) >= set(live), \
    f"per-member utilization unpopulated: live={sorted(live)} " \
    f"attributed={sorted(util)}"
assert any(m["statements"] > 0 for m in util.values()), \
    f"no member shows attributed statements: {util}"
assert fa.get("trace_id", 0) > 0xFFFFFF, \
    f"trace id {fa.get('trace_id')} is not fleet-unique (no nonce)"
assert fa.get("stitched_store"), \
    "store-plane ring record missing origin_trace_id for the traced " \
    "statement"
print(f"fleet bench OK: {rep['value']} stmts/s at "
      f"x{legs[-1]['servers']} ({d['scaling_max_vs_1']}x vs x1), "
      f"journal_pulls="
      f"{sum(c['journal_pulls'] for c in coh.values())}, "
      f"fleet trace {fa['trace_id']} stitched across "
      f"{len(fa['stitched_records'])} member(s)")
PY
