#!/usr/bin/env bash
# CI wrapper for the multi-client serving harness (`python bench.py
# serve`): a small fixed workload that fits the tier-1 time budget,
# with sanity floors on the output — the heavy leg (more clients,
# bigger scale) lives in tests/test_concurrent_serving.py behind the
# `slow` marker. Env overrides (BENCH_SERVE_CLIENTS / _ROUNDS /
# _LOOKUPS / _SF) pass straight through to bench.py.
set -euo pipefail
cd "$(dirname "$0")/.."

export JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}"
export BENCH_SERVE_CLIENTS="${BENCH_SERVE_CLIENTS:-4}"
export BENCH_SERVE_ROUNDS="${BENCH_SERVE_ROUNDS:-1}"
export BENCH_SERVE_LOOKUPS="${BENCH_SERVE_LOOKUPS:-4}"
export BENCH_SERVE_SF="${BENCH_SERVE_SF:-0.01}"
# p99 sanity ceiling per class, milliseconds (generous: CPU-XLA CI)
SERVE_P99_FLOOR_MS="${SERVE_P99_FLOOR_MS:-60000}"

out="$(python bench.py serve)"
echo "$out"

SERVE_JSON="$out" SERVE_P99_FLOOR_MS="$SERVE_P99_FLOOR_MS" python - <<'PY'
import json, os

floor_ms = float(os.environ["SERVE_P99_FLOOR_MS"])
rep = json.loads(os.environ["SERVE_JSON"])
d = rep["detail"]
conc = d["concurrent"]
assert rep["value"] > 0, "aggregate rows/sec must be positive"
for cls, lat in conc["latency"].items():
    assert lat["p99_ms"] <= floor_ms, \
        f"{cls}: p99 {lat['p99_ms']}ms over the {floor_ms}ms sanity floor"
pinched = d["pinched"]
assert pinched["completed"], f"pinched leg failed: {pinched['errors']}"
assert pinched["oom_cancels"] == 0, \
    f"pinched leg paid {pinched['oom_cancels']} mid-query OOM cancels"
util = d.get("utilization")
assert util, "utilization block missing from the serve detail"
for key in ("device_busy_fraction", "device_busy_secs",
            "attributed_device_secs", "attribution_coverage",
            "per_class_device_secs"):
    assert key in util, f"utilization block unpopulated: missing {key}"
assert util["device_busy_secs"] > 0, \
    f"utilization block unpopulated: zero device busy time ({util})"
assert 0.9 <= util["attribution_coverage"] <= 1.1, \
    f"attribution coverage {util['attribution_coverage']} outside " \
    f"[0.9, 1.1]: per-session metering is leaking ({util})"
print(f"serve bench OK: {rep['value']} rows/s concurrent "
      f"({conc['speedup_vs_serialized']}x vs serialized), "
      f"admission_shed={pinched['admission_shed']}, oom_cancels=0, "
      f"busy={util['device_busy_fraction']}, "
      f"coverage={util['attribution_coverage']}")
PY
