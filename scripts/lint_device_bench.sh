#!/usr/bin/env bash
# CI wrapper for the static-vs-runtime cross-check
# (`python bench.py lintcheck`): the device dataflow pass
# (tidb_tpu/lint/flow/device.py) predicts per-family compile behavior
# from source alone; the leg runs warm Q1/Q3 under kernel profiling
# and fails on drift in EITHER direction — a family the static model
# does not predict, a fingerprinted kernel_profile row compiling past
# the predicted per-row bound, any compile during warm iterations, or
# a non-clean `python -m tidb_tpu.lint --json` run — bench.py asserts
# all of that itself and exits non-zero. Env overrides
# (BENCH_LINTCHECK_SF / _ITERS) pass straight through.
set -euo pipefail
cd "$(dirname "$0")/.."

export JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}"
export BENCH_LINTCHECK_SF="${BENCH_LINTCHECK_SF:-0.02}"
export BENCH_LINTCHECK_ITERS="${BENCH_LINTCHECK_ITERS:-2}"

out="$(python bench.py lintcheck)"
echo "$out"

LINTCHECK_JSON="$out" python - <<'PY'
import json, os

rep = json.loads(os.environ["LINTCHECK_JSON"])
d = rep["detail"]
assert d.get("passed"), f"lintcheck did not pass: {d['failures']}"
assert rep["value"] > 0, "cross-check verified no kernel family"
assert d["lint_clean"], "lint --json reported findings"
assert not d["rows_over_bound"], d["rows_over_bound"]
slow = sorted(d["lint_rule_ms"].items(), key=lambda kv: -kv[1])[:3]
print(f"lintcheck OK: {rep['value']} families verified against the "
      f"static model ({', '.join(sorted(d['predictions']))}), "
      f"{d['traced_sites']} traced sites, {d['lint_rules']} lint rules "
      f"clean (slowest " +
      ", ".join(f"{n} {ms:.0f}ms" for n, ms in slow) + ")")
PY
