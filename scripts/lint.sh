#!/usr/bin/env bash
# CI / pre-commit lint gate: the exact rule set tests/test_lint.py runs
# in-process, invocable standalone (no pytest).
#
#   scripts/lint.sh             # human-readable findings + timing
#   scripts/lint.sh --json      # machine-readable (stable schema:
#                               #   file/line/rule/message findings,
#                               #   parse-count instrumentation)
#   scripts/lint.sh --rule lock-order   # any CLI flag passes through
#
# Exit codes (the CLI's contract, forwarded verbatim):
#   0  every rule ran clean
#   1  findings
#   2  usage error
#
# The report's timing block records wall time for the record, but the
# single-parse guarantee is asserted on parse COUNTS (timing.parse_calls
# == files: the engine parsed each package module exactly once, and the
# rule walks — the flow rules' call graph and lock registry included —
# added zero parses). Wall time under concurrent CI load is noise; the
# count is the invariant.

set -u

cd "$(dirname "$0")/.."

# the data-plane import is irrelevant to linting; keep it off any
# accelerator so the gate runs identically on CI runners and dev boxes
export JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}"

start_ms=$(python -c 'import time; print(int(time.time() * 1000))')
python -m tidb_tpu.lint "$@"
code=$?
end_ms=$(python -c 'import time; print(int(time.time() * 1000))')

echo "lint.sh: exit ${code} in $((end_ms - start_ms)) ms (interpreter + jax import included; the in-engine number above excludes it)" >&2
exit "${code}"
