#!/usr/bin/env bash
# CI / pre-commit lint gate: the exact rule set tests/test_lint.py runs
# in-process, invocable standalone (no pytest).
#
#   scripts/lint.sh             # findings + per-rule wall time table
#   scripts/lint.sh --json      # machine-readable (stable schema:
#                               #   file/line/rule/message findings,
#                               #   parse-count instrumentation)
#   scripts/lint.sh --rule lock-order --rule cache-key
#                               # any CLI flag passes through; --rule
#                               # scopes the run (repeatable)
#
# Exit codes (the CLI's contract, forwarded verbatim):
#   0  every rule ran clean
#   1  findings
#   2  usage error
#
# Human mode drives the CLI through --json and renders the timing
# block's per-rule wall times, so the cost of the flow passes (call
# graph, lock registry, device dataflow) is visible in CI logs. Wall
# time under concurrent CI load is noise for gating — the single-parse
# guarantee is asserted on parse COUNTS (timing.parse_calls == files);
# the table is for the record.

set -u

cd "$(dirname "$0")/.."

# the data-plane import is irrelevant to linting; keep it off any
# accelerator so the gate runs identically on CI runners and dev boxes
export JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}"

for arg in "$@"; do
    case "$arg" in
        --json|--list-rules|-h|--help)
            # raw CLI modes: forward verbatim, no reformatting
            exec python -m tidb_tpu.lint "$@"
            ;;
    esac
done

start_ms=$(python -c 'import time; print(int(time.time() * 1000))')
out="$(python -m tidb_tpu.lint --json "$@")"
code=$?
end_ms=$(python -c 'import time; print(int(time.time() * 1000))')

LINT_JSON="$out" python - <<'PY'
import json, os

rep = json.loads(os.environ["LINT_JSON"])
for f in rep["findings"]:
    print(f"{f['file']}:{f['line']}: [{f['rule']}] {f['message']}")
timing = rep["timing"]
rule_ms = sorted(timing.get("rule_ms", {}).items(), key=lambda kv: -kv[1])
width = max((len(n) for n, _ in rule_ms), default=0)
for name, ms in rule_ms:
    print(f"  {name:<{width}}  {ms:8.1f} ms")
print(f"{len(rep['rules'])} rule(s) over {rep['files']} files: "
      f"{len(rep['findings'])} finding(s) in {timing['total_ms']:.0f} ms "
      f"(parse {timing['parse_ms']:.0f} ms, "
      f"{timing['parse_calls']} parse calls)")
PY

echo "lint.sh: exit ${code} in $((end_ms - start_ms)) ms (interpreter + jax import included; the in-engine number above excludes it)" >&2
exit "${code}"
