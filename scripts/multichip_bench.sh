#!/usr/bin/env bash
# CI wrapper for the multichip scaling series (`python bench.py
# multichip`): per-chip rows/sec and serving aggregate at 1/2/4/8
# virtual devices, one subprocess per device count (the XLA
# host-platform device count is fixed at backend init). The bench
# itself exits non-zero on per-chip collapse (>25% drop 1→8), a
# serving aggregate that does not grow with the mesh, or any
# reason="mesh" fallback; this wrapper re-asserts those gates on the
# JSON so a silently-truncated report also fails. Env overrides
# (BENCH_MULTICHIP_SF / _ITERS / _SERVE_ROUNDS / _DEVS) pass straight
# through to bench.py.
set -euo pipefail
cd "$(dirname "$0")/.."

export JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}"

out="$(python bench.py multichip)"
echo "$out"

MULTICHIP_JSON="$out" python - <<'PY'
import json, os

rep = json.loads(os.environ["MULTICHIP_JSON"])
d = rep["detail"]
assert d["ok"], f"multichip checks failed: {d['checks']}"
assert d["checks"]["no_mesh_fallbacks"], \
    "reason=\"mesh\" fallback observed: the unified plane must not " \
    "have a mesh-specific fallback class"
ratios = d["per_chip_ratio_1_to_n"]
assert ratios and min(ratios.values()) >= 0.75, \
    f"per-chip rows/sec collapsed 1->N: {ratios}"
serve = {int(k): v for k, v in d["serve_aggregate_by_n"].items()}
ns = sorted(serve)
assert serve[ns[-1]] > serve[ns[0]] > 0, \
    f"serving aggregate did not grow with the mesh: {serve}"
print(f"multichip bench OK: per-chip ratio 1->{ns[-1]} = "
      f"{min(ratios.values())}, serve {serve[ns[0]]} -> "
      f"{serve[ns[-1]]} rows/s")
PY
