#!/usr/bin/env bash
# CI wrapper for the chaos serve harness (`python bench.py chaos`,
# docs/ROBUSTNESS.md): the PR-9 serve mix + PR-11 HTAP writes under a
# FIXED-SEED randomized fault schedule across the device plane, with
# hard assertions on the robustness contract — zero wrong results,
# zero non-retryable errors, zero stuck statements, zero mid-query OOM
# cancels, and every scheduler slot / memtrack ledger drained to zero.
# Env overrides (BENCH_CHAOS_SEED / _CLIENTS / _SECS / _SF /
# _WRITES_PER_SEC / _TIMEOUT_MS) pass straight through to bench.py.
set -euo pipefail
cd "$(dirname "$0")/.."

export JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}"
export BENCH_CHAOS_SEED="${BENCH_CHAOS_SEED:-20260804}"
export BENCH_CHAOS_CLIENTS="${BENCH_CHAOS_CLIENTS:-4}"
export BENCH_CHAOS_SECS="${BENCH_CHAOS_SECS:-12}"
export BENCH_CHAOS_SF="${BENCH_CHAOS_SF:-0.01}"

out="$(python bench.py chaos)"
echo "$out"

CHAOS_JSON="$out" python - <<'PY'
import json, os

rep = json.loads(os.environ["CHAOS_JSON"])
d = rep["detail"]
assert d["ops_completed"] > 0, "no client ops completed under chaos"
assert d["writes_completed"] > 0, "no HTAP writes completed under chaos"
assert d["failpoints_armed"] > 0 and d["failpoint_fires"], \
    "the fault schedule never fired — the run proved nothing"
assert d["wrong_results"] == [], \
    f"WRONG RESULTS under faults: {d['wrong_results']}"
assert d["non_retryable_errors"] == [], \
    f"non-retryable errors surfaced: {d['non_retryable_errors']}"
assert d["stuck_statements"] == [], \
    f"stuck statements: {d['stuck_statements']}"
assert d["oom_cancels"] == 0, \
    f"chaos paid {d['oom_cancels']} mid-query OOM cancels"
assert d["post_chaos_healthy"], "serving did not recover after disarm"
assert d["sched_inflight_end"] == 0 and d["sched_waiting_end"] == 0, \
    "scheduler slots leaked"
assert d["server_ledger_host_end"] == 0 and \
    d["server_ledger_device_end"] == 0, "SERVER memtrack ledgers leaked"
assert d["passed"], "chaos harness reported failure"
print(f"chaos bench OK: {d['ops_completed']} ops + "
      f"{d['writes_completed']} writes under "
      f"{d['failpoints_armed']} armed faults "
      f"(fires={sum(d['failpoint_fires'].values())}, "
      f"retries={d['retries']}, watchdog={d['watchdog_fires']}, "
      f"quarantines={d['quarantines']}, "
      f"worker_restarts={d['worker_restarts']}); "
      f"zero wrong results, zero non-retryable errors, ledgers drained")
PY
