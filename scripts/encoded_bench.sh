#!/usr/bin/env bash
# CI wrapper for the encoded-execution comparison (`python bench.py
# encoded`): warm Q1 (dict group keys, direct-indexed agg) and Q3
# (string-filtered join chain — encoded join key lanes + fragment
# fusion) with `tidb_tpu_encoded_exec` on vs off. Contract:
# identical results, ZERO device fallbacks with reason="encoding" on
# the stock TPC-H schema, and a populated bytes_touched block whose
# encoded bytes undercut the decoded equivalent. Env overrides
# (BENCH_ENCODED_SF / _ITERS) pass straight through to bench.py.
set -euo pipefail
cd "$(dirname "$0")/.."

export JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}"
export BENCH_ENCODED_SF="${BENCH_ENCODED_SF:-0.05}"
export BENCH_ENCODED_ITERS="${BENCH_ENCODED_ITERS:-3}"

out="$(python bench.py encoded)"
echo "$out"

ENCODED_JSON="$out" python - <<'PY'
import json, os

rep = json.loads(os.environ["ENCODED_JSON"])
qs = rep["detail"]["queries"]
assert qs, "no queries ran"
for name, q in qs.items():
    # the load-bearing pin: the encoded path never falls back on the
    # stock TPC-H schema — a fallback here means the vocabulary
    # regressed and warm scans silently re-decode
    assert q["encoding_fallbacks"] == 0, \
        f"{name}: {q['encoding_fallbacks']} encoding fallback(s)"
    bt = q["bytes_touched"]
    assert bt["decoded_equivalent_bytes"] > 0, \
        f"{name}: bytes_touched not populated ({bt})"
    assert bt["encoded_bytes"] > 0, \
        f"{name}: encoded bytes not counted ({bt})"
print("encoded bench OK: " +
      ", ".join(f"{n} speedup {q['speedup']}x ratio "
                f"{q['bytes_touched']['ratio']}"
                for n, q in sorted(qs.items())))
PY
