"""Round benchmark: TPC-H Q1-shaped filter + 8-agg group-by on one chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

Baseline: the reference's Go HashAggExec path (executor/aggregate.go:32 over
util/chunk) publishes no numbers (BASELINE.md), so vs_baseline is computed
against a fixed 10M rows/sec estimate for the single-threaded Go chunk
executor on Q1-shaped data — the north star in BASELINE.json is >=10x that.
"""

from __future__ import annotations

import json
import os
import time

import numpy as np

GO_BASELINE_ROWS_PER_SEC = 10e6

ROWS = int(os.environ.get("BENCH_ROWS", 1 << 21))
ITERS = int(os.environ.get("BENCH_ITERS", 8))


def main() -> None:
    import jax

    from __graft_entry__ import _lineitem_chunk, _q1_exprs
    from tidb_tpu.ops import runtime
    from tidb_tpu.ops.hashagg import HashAggKernel

    chunk = _lineitem_chunk(ROWS)
    flt, groups, aggs = _q1_exprs()
    kernel = HashAggKernel(flt, groups, aggs, capacity=64)

    cols, _dicts = runtime.device_put_chunk(chunk)
    n = chunk.num_rows

    # warmup: compile + one run
    out = kernel._jit(cols, n)
    jax.block_until_ready(out)

    t0 = time.perf_counter()
    for _ in range(ITERS):
        out = kernel._jit(cols, n)
    jax.block_until_ready(out)
    dt = time.perf_counter() - t0

    rows_per_sec = ROWS * ITERS / dt
    print(json.dumps({
        "metric": "tpch_q1_agg_rows_per_sec_per_chip",
        "value": round(rows_per_sec, 1),
        "unit": "rows/s",
        "vs_baseline": round(rows_per_sec / GO_BASELINE_ROWS_PER_SEC, 3),
    }))


if __name__ == "__main__":
    main()
